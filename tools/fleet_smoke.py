#!/usr/bin/env python
"""Golden 2-process CPU fleet run for CI (ci/tier1.sh): the ISSUE 20
acceptance properties, end to end, on the committed golden reads.

1. Split tests/golden/reads.fastq into two input files, run the
   `quorum` driver single-process (`--devices 1 --partitions 2` — the
   geometry a 2-process fleet plans), then run it as a REAL 2-process
   fleet (two subprocesses, `--coordinator 127.0.0.1:PORT` over
   `jax.distributed` + the coordination-service KV transport), and
   assert the database table payload and the corrected `.fa`/`.log`
   are BYTE-IDENTICAL — a fleet must never change the answer.
2. Hard-kill one host mid-stage-1 (`os._exit` fault plan on process 1
   only, per-pass partition cursor checkpoints), relaunch BOTH hosts
   with `--resume`, and assert the finished fleet output is still
   byte-identical to the single-process run.
3. Leave the fleet telemetry in --out-dir for the metrics_check gates
   that follow:
     fleet_metrics.hosts.json — the ONE aggregated fleet document
       (meta.host_process_count=2, per-host shards, min-reduced
       resource gauges; parallel/multihost.aggregate_metrics)

Exit 0 = all checks passed.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")

KILL_CODE = 43
BATCH_SIZE = 64  # 242 golden reads split 2 ways -> 2 batches per file
LAUNCH_TIMEOUT_S = 420


def _split_golden(out_dir: str) -> list[str]:
    """The golden reads as TWO fastq files (4-line records, split at a
    read boundary) — the fleet's per-host producer unit is the file."""
    with open(os.path.join(GOLDEN, "reads.fastq"), "rb") as f:
        lines = f.readlines()
    assert len(lines) % 4 == 0, "golden fastq is 4-line records"
    n_reads = len(lines) // 4
    cut = (n_reads // 2) * 4
    paths = []
    for i, chunk in enumerate((lines[:cut], lines[cut:])):
        p = os.path.join(out_dir, f"reads_part{i}.fastq")
        with open(p, "wb") as f:
            f.writelines(chunk)
        paths.append(p)
    return paths


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_fleet(argv_common: list[str], reads: list[str],
                  env_by_pid: dict | None = None) -> list:
    """Two driver subprocesses forming one fleet; returns the Popen
    pair (process-id order)."""
    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # a wedged fleet must die loudly inside the CI budget
        env.setdefault("QUORUM_FLEET_BARRIER_TIMEOUT_S", "120")
        if env_by_pid and pid in env_by_pid:
            env.update(env_by_pid[pid])
        cmd = ([sys.executable, "-m", "quorum_tpu.cli.quorum"]
               + argv_common
               + ["--coordinator", f"127.0.0.1:{port}",
                  "--num-processes", "2", "--process-id", str(pid)]
               + reads)
        procs.append(subprocess.Popen(cmd, cwd=REPO, env=env))
    return procs


def _wait_all(procs, timeout=LAUNCH_TIMEOUT_S) -> list[int]:
    rcs = []
    for p in procs:
        try:
            rcs.append(p.wait(timeout=timeout))
        except subprocess.TimeoutExpired:
            p.kill()
            rcs.append(p.wait())
    return rcs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Golden 2-process CPU fleet run: byte parity vs "
                    "single-process plus a kill-one-host fleet resume "
                    "(ci/tier1.sh gate)")
    p.add_argument("--out-dir", default=None,
                   help="Where the work files and metrics land "
                        "(default: a temp dir)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="fleet_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    reads = _split_golden(out_dir)
    base = ["-s", "64k", "-k", "13",
            "--batch-size", str(BATCH_SIZE), "--devices", "1"]

    # -- single-process reference at the fleet's planned geometry -----
    ref_prefix = os.path.join(out_dir, "ref")
    print("[fleet_smoke] reference: quorum --devices 1 --partitions 2")
    from quorum_tpu.cli import quorum as quorum_cli
    rc = quorum_cli.main(base + ["--partitions", "2",
                                 "-p", ref_prefix] + reads)
    if rc != 0:
        print(f"[fleet_smoke] FAIL: single-process reference rc {rc}",
              file=sys.stderr)
        return 1
    from quorum_tpu.io.db_format import db_payload_bytes
    ref_db = db_payload_bytes(ref_prefix + "_mer_database.jf")
    ref_fa = open(ref_prefix + ".fa", "rb").read()
    ref_log = open(ref_prefix + ".log", "rb").read()

    # -- the 2-process fleet: byte parity -----------------------------
    fleet_prefix = os.path.join(out_dir, "fleet")
    metrics = os.path.join(out_dir, "fleet_metrics.json")
    print("[fleet_smoke] fleet: 2 processes over jax.distributed")
    rcs = _wait_all(_launch_fleet(
        base + ["-p", fleet_prefix, "--metrics", metrics], reads))
    if rcs != [0, 0]:
        print(f"[fleet_smoke] FAIL: fleet driver rcs {rcs}",
              file=sys.stderr)
        return 1
    if db_payload_bytes(fleet_prefix + "_mer_database.jf") != ref_db:
        print("[fleet_smoke] FAIL: fleet database payload differs "
              "from single-process (must be byte-identical)",
              file=sys.stderr)
        return 1
    if (open(fleet_prefix + ".fa", "rb").read() != ref_fa
            or open(fleet_prefix + ".log", "rb").read() != ref_log):
        print("[fleet_smoke] FAIL: fleet .fa/.log differ from "
              "single-process (must be byte-identical)",
              file=sys.stderr)
        return 1
    print(f"[fleet_smoke] parity OK ({len(ref_fa)} fa bytes, "
          f"{len(ref_db)} db payload bytes)")

    # the ONE aggregated fleet document (process 0 wrote it at the
    # original --metrics base)
    hosts_doc_path = os.path.join(out_dir, "fleet_metrics.hosts.json")
    if not os.path.exists(hosts_doc_path):
        print("[fleet_smoke] FAIL: no aggregated fleet document at "
              f"{hosts_doc_path}", file=sys.stderr)
        return 1
    doc = json.load(open(hosts_doc_path))
    if (doc.get("meta", {}).get("host_process_count") != 2
            or len(doc.get("hosts", {})) != 2):
        print("[fleet_smoke] FAIL: aggregated document does not carry "
              "2 host shards with meta.host_process_count=2",
              file=sys.stderr)
        return 1

    # -- kill one host mid-stage-1, fleet --resume --------------------
    kill_prefix = os.path.join(out_dir, "killed")
    ckdir = os.path.join(out_dir, "ck")
    plan = json.dumps([{"site": "stage1.insert", "batch": 1,
                        "action": "exit", "code": KILL_CODE}])
    kill_args = base + ["-p", kill_prefix, "--checkpoint-dir", ckdir,
                        "--checkpoint-every", "1"]
    print(f"[fleet_smoke] killing host 1 mid-stage-1 ({plan})")
    procs = _launch_fleet(
        kill_args, reads,
        env_by_pid={1: {"QUORUM_FAULT_PLAN": plan,
                        # the survivor must time out fast once its
                        # peer is dead, not burn the CI budget
                        "QUORUM_FLEET_BARRIER_TIMEOUT_S": "120"}})
    try:
        rc1 = procs[1].wait(timeout=LAUNCH_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        procs[1].kill()
        rc1 = procs[1].wait()
    if rc1 != KILL_CODE:
        _wait_all(procs)
        print(f"[fleet_smoke] FAIL: killed host exited {rc1}, want "
              f"{KILL_CODE}", file=sys.stderr)
        return 1
    # the survivor is blocked on its dead peer: take it down
    procs[0].terminate()
    try:
        procs[0].wait(timeout=60)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        procs[0].wait()
    print("[fleet_smoke] host 1 killed at stage-1 batch 1; survivor "
          "reaped; relaunching fleet with --resume")
    rcs = _wait_all(_launch_fleet(kill_args + ["--resume"], reads))
    if rcs != [0, 0]:
        print(f"[fleet_smoke] FAIL: fleet resume rcs {rcs}",
              file=sys.stderr)
        return 1
    if db_payload_bytes(kill_prefix + "_mer_database.jf") != ref_db:
        print("[fleet_smoke] FAIL: resumed fleet database differs "
              "from single-process", file=sys.stderr)
        return 1
    if (open(kill_prefix + ".fa", "rb").read() != ref_fa
            or open(kill_prefix + ".log", "rb").read() != ref_log):
        print("[fleet_smoke] FAIL: resumed fleet .fa/.log differ "
              "from single-process", file=sys.stderr)
        return 1

    print("[fleet_smoke] OK: 2-process fleet parity and kill-one-host "
          f"resume byte-identical; metrics -> {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
