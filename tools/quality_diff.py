#!/usr/bin/env python
"""Correction-ACCURACY regression verdicts over quality scorecards
(ISSUE 17): the accuracy twin of tools/perf_diff.py. perf_diff fails
CI when the pipeline gets slow; quality_diff fails CI when it gets
WRONG — fewer corrections, a shifted substitution spectrum, a
contaminant surge, an anchor rate the DB's coverage model says is
too low.

The golden pipeline is DETERMINISTIC (fixed reads, seeded build), so
unlike the cliff-wide perf tolerances the committed baseline pins
every quality metric EXACTLY (min == max == value): any movement in
what the pipeline corrects is a contract violation, not noise.

Modes:

* **Golden gate** (what ci/tier1.sh runs)::

      python tools/quality_diff.py --golden \\
          --baseline QUALITY_BASELINE.json --out verdict.json

  Builds the golden DB (tests/golden), runs error-correct TWICE,
  asserts the two runs' `quality` sections are byte-identical
  (sort_keys JSON — the scorecard is a pure function of the counters,
  so any divergence is nondeterminism in the data plane itself), then
  judges run 1's scorecard against the committed baseline. Exit 1 on
  any regression or determinism break, 2 on a bad baseline/pipeline.

  `--seed-regression floor|contam` injects a known accuracy bug into
  the golden runs (a misapplied stage-2 presence floor, or the golden
  reads fed back as the contaminant screen) — ci/tier1.sh uses it as
  the negative test proving the gate actually fails when accuracy
  moves.

* **Artifact gate**::

      python tools/quality_diff.py --baseline QUALITY_BASELINE.json \\
          golden=/tmp/metrics.json

  Judges existing metrics documents (KEY=PATH, like perf_diff). A
  document without a `quality` section has one recomputed from its
  counters/histograms (telemetry/quality.section_from_doc) — the
  scorecard is derivable from any data-plane metrics document.

* **Baseline generation**: `--write-baseline QUALITY_BASELINE.json`
  (with --golden or KEY=PATH documents) regenerates the committed
  contract. Review the diff before committing — a baseline update is
  an accuracy-change ACKNOWLEDGEMENT, not a refresh.

Metric names are flat paths over the quality section::

    counts.reads  counts.corrected  counts.skipped
    counts.substitutions  counts.truncations_3p  counts.truncations_5p
    rates.<name>          skip_reasons.<slug>
    coverage.predicted_mean  coverage.predicted_anchor_rate
    spectrum.tail_frac    (substitution mass in the 3' half of the
                           occupied position spectrum — the Illumina
                           3'-decay signature as one number)

The verdict document (`quorum-tpu-quality-diff/1`) shares perf_diff's
verdict shape and is validated by tools/metrics_check.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from perf_diff import check_metric  # noqa: E402

BASELINE_SCHEMA = "quorum-tpu-quality-baseline/1"
GOLDEN_READS = os.path.join(_REPO, "tests", "golden", "reads.fastq")


def _verdict_schema() -> str:
    from quorum_tpu.telemetry.schema import QUALITY_DIFF_SCHEMA
    return QUALITY_DIFF_SCHEMA


def quality_section(doc: dict) -> dict:
    """The quality section of an artifact: embedded (a scorecard run),
    the document itself (a bare section), or recomputed from the
    counters/histograms — the scorecard is a pure function of the
    data-plane metrics, so any error-correct/serve document yields
    one."""
    from quorum_tpu.telemetry import quality
    if isinstance(doc.get("quality"), dict):
        return doc["quality"]
    if doc.get("schema") == quality.QUALITY_SCHEMA:
        return doc
    if isinstance(doc.get("counters"), dict):
        return quality.section_from_doc(doc)
    raise ValueError("no quality section and no counters to "
                     "recompute one from")


def profile_from_quality(q: dict) -> dict[str, float]:
    """Flat metric paths over one quality section."""
    prof: dict[str, float] = {}
    for k in ("reads", "corrected", "skipped", "substitutions",
              "truncations_3p", "truncations_5p"):
        prof[f"counts.{k}"] = float(q.get(k, 0))
    for k, v in q.get("rates", {}).items():
        prof[f"rates.{k}"] = float(v)
    for k, v in q.get("skip_reasons", {}).items():
        prof[f"skip_reasons.{k}"] = float(v)
    cov = q.get("coverage")
    if isinstance(cov, dict):
        for k in ("predicted_mean", "predicted_anchor_rate"):
            if isinstance(cov.get(k), (int, float)):
                prof[f"coverage.{k}"] = float(cov[k])
    spec = []
    for k, v in q.get("sub_pos_spectrum", {}).items():
        try:
            spec.append((int(k), int(v)))
        except (TypeError, ValueError):
            continue
    total = sum(n for _, n in spec)
    if total > 0:
        mx = max(b for b, _ in spec)
        tail = sum(n for b, n in spec if b > mx // 2)
        prof["spectrum.tail_frac"] = round(tail / total, 6)
    return prof


def extract_quality_profile(path: str) -> dict[str, float]:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError("not a JSON object")
    return profile_from_quality(quality_section(doc))


# -- golden pipeline --------------------------------------------------------

def _write_contam_fasta(path: str) -> None:
    """The golden reads themselves as a contaminant screen — the
    worst-case seeded regression: every read is a contaminant hit."""
    lines = []
    with open(GOLDEN_READS) as f:
        raw = f.read().splitlines()
    for i in range(0, len(raw) - 3, 4):
        lines.append(f">contam_{i // 4}")
        lines.append(raw[i + 1])
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def run_golden(workdir: str,
               seed_regression: str | None = None) -> list[str]:
    """Build the golden DB, run error-correct twice; returns the two
    metrics-document paths. `seed_regression` injects a known
    accuracy bug into BOTH runs (the gate must catch it; the
    determinism check alone must not)."""
    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import error_correct_reads as ec_cli
    db = os.path.join(workdir, "golden.db")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, GOLDEN_READS])
    if rc:
        raise RuntimeError(f"create_mer_database rc={rc}")
    extra: list[str] = []
    if seed_regression == "floor":
        # a misapplied stage-2 presence floor: every trusted mer
        # filtered, anchors vanish, corrections collapse
        extra = ["--presence-floor", "64"]
    elif seed_regression == "contam":
        contam = os.path.join(workdir, "contam.fa")
        _write_contam_fasta(contam)
        extra = ["--contaminant", contam]
    paths = []
    for i in (1, 2):
        out = os.path.join(workdir, f"corrected_{i}.fa")
        m = os.path.join(workdir, f"metrics_{i}.json")
        rc = ec_cli.main(["-p", "4", db, GOLDEN_READS, "-o", out,
                          "--metrics", m] + extra)
        if rc:
            raise RuntimeError(f"error_correct run {i} rc={rc}")
        paths.append(m)
    return paths


def check_determinism(path_a: str, path_b: str) -> str | None:
    """None when the two documents' quality sections serialize
    byte-identically (sort_keys JSON); else a one-line diagnosis."""
    with open(path_a) as f:
        qa = quality_section(json.load(f))
    with open(path_b) as f:
        qb = quality_section(json.load(f))
    sa = json.dumps(qa, sort_keys=True)
    sb = json.dumps(qb, sort_keys=True)
    if sa == sb:
        return None
    pa, pb = profile_from_quality(qa), profile_from_quality(qb)
    moved = sorted(k for k in pa.keys() | pb.keys()
                   if pa.get(k) != pb.get(k))
    return ("quality sections differ between identical runs "
            f"(nondeterministic data plane); moved: "
            f"{moved if moved else 'distribution keys'}")


# -- verdicts ---------------------------------------------------------------

def _emit(verdict: dict, out: str | None, quiet: bool) -> None:
    if not quiet:
        for key, dv in verdict["docs"].items():
            for name, entry in dv.get("metrics", {}).items():
                mark = "ok " if entry["ok"] else "REG"
                val = entry.get("value")
                base = entry.get("baseline")
                print(f"[quality_diff] {mark} {key}:{name} = "
                      f"{val if val is not None else '-'}"
                      + (f" (baseline {base})" if base is not None
                         else "")
                      + ("" if entry["ok"]
                         else f" -- {entry.get('status')}"))
    for msg in verdict["regressions"]:
        print(f"[quality_diff] REGRESSION {msg}", file=sys.stderr)
    print(f"[quality_diff] verdict: {verdict['verdict']} "
          f"({verdict['checked']} metric(s) checked, "
          f"{len(verdict['regressions'])} regression(s))")
    if out:
        from quorum_tpu.telemetry.registry import atomic_write
        atomic_write(out, json.dumps(verdict, indent=1) + "\n")


def run_baseline(baseline_path: str, docs: dict[str, str],
                 out: str | None, quiet: bool = False,
                 pre_regressions: list[str] | None = None) -> int:
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        print(f"quality_diff: {baseline_path}: {e}", file=sys.stderr)
        return 2
    if baseline.get("schema") != BASELINE_SCHEMA:
        print(f"quality_diff: {baseline_path} is not a "
              f"{BASELINE_SCHEMA} document", file=sys.stderr)
        return 2
    verdict = {
        "schema": _verdict_schema(),
        "baseline": os.path.basename(baseline_path),
        "verdict": "pass",
        "checked": 0,
        "regressions": list(pre_regressions or []),
        "docs": {},
    }
    for key, spec in baseline.get("docs", {}).items():
        path = docs.get(key)
        dv: dict = {"metrics": {}}
        verdict["docs"][key] = dv
        if path is None:
            if spec.get("optional"):
                dv["status"] = "not supplied (optional)"
                continue
            dv["status"] = "document not supplied"
            verdict["regressions"].append(f"{key}: document not "
                                          "supplied")
            continue
        try:
            prof = extract_quality_profile(path)
        except (OSError, ValueError) as e:
            dv["status"] = str(e)
            verdict["regressions"].append(f"{key}: {e}")
            continue
        dv["path"] = path
        for name, mspec in spec.get("metrics", {}).items():
            entry = check_metric(name, mspec, prof.get(name))
            dv["metrics"][name] = entry
            verdict["checked"] += 1
            if not entry["ok"]:
                verdict["regressions"].append(
                    f"{key}: {name}: {entry.get('status')}")
    if verdict["regressions"]:
        verdict["verdict"] = "regression"
    _emit(verdict, out, quiet)
    return 0 if verdict["verdict"] == "pass" else 1


def write_baseline(out: str, docs: dict[str, str]) -> int:
    baseline = {
        "schema": BASELINE_SCHEMA,
        "meta": {
            "note": "accuracy contract for the golden pipeline "
                    "(tools/quality_diff.py): the run is "
                    "deterministic, so every metric is pinned "
                    "EXACTLY — updating this file acknowledges an "
                    "accuracy change",
        },
        "docs": {},
    }
    for key, path in sorted(docs.items()):
        prof = extract_quality_profile(path)
        metrics = {}
        for name in sorted(prof):
            v = round(prof[name], 6)
            # exact pin: absolute min == max == value works for zero
            # baselines too, where ratio bounds are meaningless
            metrics[name] = {"value": v, "min": v, "max": v}
        baseline["docs"][key] = {"metrics": metrics}
    from quorum_tpu.telemetry.registry import atomic_write
    atomic_write(out, json.dumps(baseline, indent=1) + "\n")
    n = sum(len(d["metrics"]) for d in baseline["docs"].values())
    print(f"[quality_diff] wrote baseline {out} "
          f"({n} metric(s) over {len(docs)} document(s)) — review "
          "before committing")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Accuracy regression verdicts over quality "
                    "scorecards: golden-pipeline gate (--golden) or "
                    "existing-artifact gate (KEY=PATH pairs)")
    p.add_argument("docs", nargs="*", metavar="KEY=PATH",
                   help="Metrics documents to judge (ignored with "
                        "--golden, which produces its own)")
    p.add_argument("--golden", action="store_true",
                   help="Build the golden DB, run error-correct "
                        "twice, assert the quality sections are "
                        "byte-identical, judge run 1 as document key "
                        "'golden'")
    p.add_argument("--seed-regression", choices=("floor", "contam"),
                   default=None,
                   help="With --golden: inject a known accuracy bug "
                        "(misapplied presence floor / golden reads as "
                        "the contaminant screen) — the gate must "
                        "fail, proving it catches accuracy movement")
    p.add_argument("--baseline", metavar="path", default=None,
                   help="Baseline contract JSON "
                        f"({BASELINE_SCHEMA})")
    p.add_argument("--write-baseline", metavar="path", default=None,
                   help="Generate the baseline contract instead of "
                        "judging")
    p.add_argument("--out", metavar="path", default=None,
                   help="Write the verdict document "
                        "(quorum-tpu-quality-diff/1) here")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Only print regressions and the verdict")
    args = p.parse_args(argv)

    if args.baseline and args.write_baseline:
        p.error("--baseline and --write-baseline are exclusive")
    if not args.baseline and not args.write_baseline:
        p.error("one of --baseline / --write-baseline is required")

    docs: dict[str, str] = {}
    pre_regressions: list[str] = []
    workdir = None
    try:
        if args.golden:
            workdir = tempfile.mkdtemp(prefix="quality_diff.")
            try:
                m1, m2 = run_golden(workdir, args.seed_regression)
            except (RuntimeError, OSError) as e:
                print(f"quality_diff: golden pipeline failed: {e}",
                      file=sys.stderr)
                return 2
            diag = check_determinism(m1, m2)
            if diag is None:
                print("[quality_diff] determinism: quality sections "
                      "of both golden runs are byte-identical")
            else:
                pre_regressions.append(f"golden: {diag}")
            docs["golden"] = m1
        for item in args.docs:
            key, sep, path = item.partition("=")
            if not sep or not key or not path:
                p.error(f"expected KEY=PATH, got {item!r}")
            docs[key] = path
        if not docs:
            p.error("nothing to judge: supply KEY=PATH documents "
                    "or --golden")
        if args.write_baseline:
            if pre_regressions:
                print(f"quality_diff: refusing to write a baseline "
                      f"from a nondeterministic run: "
                      f"{pre_regressions}", file=sys.stderr)
                return 2
            return write_baseline(args.write_baseline, docs)
        return run_baseline(args.baseline, docs, args.out,
                            quiet=args.quiet,
                            pre_regressions=pre_regressions)
    finally:
        if workdir is not None:
            import shutil
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
