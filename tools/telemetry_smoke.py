#!/usr/bin/env python
"""Device-truth + push-transport smoke for CI (ISSUE 10, ci/tier1.sh).

Two gates in one tool:

1. **Profiled golden run**: build the mer database from the committed
   golden reads with `--profile` + `--metrics` + `--trace-spans` AND
   `--metrics-push-url` pointed at an in-process
   tools/push_receiver.py. Asserts the final metrics document carries
   the devtrace surface with real numbers (`device_kernel_us_total`
   > 0 from the profiler's own trace — CPU traces carry `hlo_op`
   kernel events too, which is the point of the gate), that
   `trace_summary --device` renders the host-dispatch /
   device-execute / device-idle attribution table, and that the
   receiver aggregated the run's terminal push into a fleet document
   (`meta.fleet`, written to --out-dir for metrics_check to gate).

2. **Receiver outage**: a MetricsPusher pointed at a dead port must
   fail its periodic pushes (counted, capped backoff) WITHOUT failing
   anything else, and once a receiver comes up on that port the
   terminal flush's bounded retry must still land the final document
   (`metrics_pushed` meta True, the host present in the receiver's
   fleet).

Artifacts land in --out-dir:
  telemetry_metrics.json — the profiled stage-1 document
                           (metrics_check gates the devtrace + push
                           names via meta.profile/metrics_push_url)
  telemetry_fleet.json   — the receiver's aggregated fleet document
                           (metrics_check gates meta.fleet)

Exit 0 = all checks passed.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import socket
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fail(msg: str) -> int:
    print(f"[telemetry_smoke] FAIL: {msg}", file=sys.stderr)
    return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Profiled golden run + push-transport smoke "
                    "(ci/tier1.sh gate, ISSUE 10)")
    p.add_argument("--out-dir", default=None,
                   help="Where telemetry_metrics.json / "
                        "telemetry_fleet.json land (default: temp)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="telemetry_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    from push_receiver import PushReceiver
    import trace_summary
    from quorum_tpu.cli import create_database as cdb_cli

    reads = os.path.join(GOLDEN, "reads.fastq")
    db = os.path.join(out_dir, "db.jf")
    metrics_path = os.path.join(out_dir, "telemetry_metrics.json")
    fleet_path = os.path.join(out_dir, "telemetry_fleet.json")
    profile_dir = os.path.join(out_dir, "profile")
    spans_path = os.path.join(out_dir, "spans.jsonl")

    # -- 1: profiled golden run, pushed to a live receiver ------------
    rx = PushReceiver(out_path=fleet_path, port=0)
    print(f"[telemetry_smoke] push receiver on 127.0.0.1:{rx.port}, "
          f"building golden database with --profile -> {profile_dir}")
    try:
        rc = cdb_cli.main(
            ["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
             "-o", db, "--metrics", metrics_path,
             "--profile", profile_dir, "--trace-spans", spans_path,
             "--metrics-push-url", f"http://127.0.0.1:{rx.port}/push",
             "--metrics-push-interval", "0.2", reads])
        if rc != 0:
            return _fail(f"profiled database build rc={rc}")
        hosts = rx.final_hosts
        fleet = rx.fleet
        periodic_pushes = rx.pushes
    finally:
        rx.close()

    with open(metrics_path) as f:
        doc = json.load(f)
    meta = doc.get("meta", {})
    if meta.get("devtrace_source") not in ("trace_json", "xplane"):
        return _fail(f"devtrace_source={meta.get('devtrace_source')!r} "
                     "(no profiler trace parsed)")
    kernel_us = doc.get("counters", {}).get("device_kernel_us_total")
    if not kernel_us or kernel_us <= 0:
        return _fail(f"device_kernel_us_total={kernel_us!r} — CPU "
                     "traces must carry kernel events too")
    steps = doc.get("gauges", {}).get("devtrace_steps", 0)
    if steps < 1:
        return _fail("no step windows joined (devtrace_steps=0): the "
                     "stage1_insert StepTraceAnnotations are missing "
                     "from the trace")
    stage_kernels = meta.get("devtrace_stage_kernel_us", {})
    if "stage1_insert" not in stage_kernels:
        return _fail(f"stage1_insert absent from per-stage kernel "
                     f"attribution {sorted(stage_kernels)}")
    print(f"[telemetry_smoke] devtrace: source="
          f"{meta['devtrace_source']} kernel_us={kernel_us} "
          f"steps={steps} stage1_insert="
          f"{stage_kernels['stage1_insert']}us")

    # the attribution table must render, with device truth > 0
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        ts_rc = trace_summary.main([spans_path, metrics_path,
                                    "--device", profile_dir])
    table = buf.getvalue()
    sys.stdout.write(table)
    if ts_rc != 0:
        return _fail(f"trace_summary --device rc={ts_rc}")
    if "device_execute_ms" not in table \
            or "stage1_insert" not in table:
        return _fail("trace_summary --device did not render the "
                     "attribution table")

    # the run's terminal push must have landed and aggregated
    if not hosts:
        return _fail("receiver saw no final push from the CLI")
    if not fleet or not fleet.get("meta", {}).get("fleet"):
        return _fail("receiver built no fleet document")
    if not os.path.exists(fleet_path):
        return _fail("fleet document was not written to --out")
    # presence, not >= 1: the final doc is snapshotted BEFORE the
    # terminal flush's own increment, so a run faster than the push
    # period legitimately carries 0 — the receiver's view proves the
    # periodic stream landed
    if "metrics_push_total" not in fleet.get("counters", {}):
        return _fail("fleet document lost the push counters")
    # >= 2: the terminal flush itself POSTs one exposition text, so a
    # single push proves only the flush — any beyond it had to come
    # from the periodic loop
    if periodic_pushes < 2:
        return _fail("receiver saw no periodic exposition push "
                     f"(pushes={periodic_pushes}; 1 is the terminal "
                     "flush's own)")
    print(f"[telemetry_smoke] push: fleet of {len(hosts)} host(s), "
          f"{periodic_pushes} periodic push(es) -> {fleet_path}")

    # -- 2: receiver outage: retry + terminal flush -------------------
    from quorum_tpu.telemetry.push import MetricsPusher
    from quorum_tpu.telemetry.registry import registry_for

    port = _free_port()
    reg = registry_for(None, force=True)
    reg.set_meta(stage="outage_probe")
    reg.counter("probe_events").inc(3)
    pusher = MetricsPusher(reg, f"http://127.0.0.1:{port}/push",
                           period_s=0.05)
    deadline = time.perf_counter() + 15
    while pusher.failures < 1:
        if time.perf_counter() > deadline:
            return _fail("no push failure recorded against the dead "
                         "receiver")
        time.sleep(0.02)
    print(f"[telemetry_smoke] outage: {pusher.failures} failed "
          f"push(es) against the dead port; bringing the receiver up")
    rx2 = PushReceiver(port=port)
    try:
        ok = pusher.close(final_doc=reg.as_dict())
        if not ok:
            return _fail("terminal flush did not land after the "
                         "receiver recovered")
        if reg.meta.get("metrics_pushed") is not True:
            return _fail("metrics_pushed meta not stamped True")
        if not rx2.final_hosts:
            return _fail("recovered receiver holds no final document")
    finally:
        rx2.close()
    print("[telemetry_smoke] OK: devtrace attribution rendered, fleet "
          "document aggregated, outage survived via retry + terminal "
          "flush")
    return 0


if __name__ == "__main__":
    sys.exit(main())
