#!/usr/bin/env python
"""Device-truth + push-transport + alerting-loop smoke for CI
(ISSUES 10 + 11, ci/tier1.sh).

Seven gates in one tool:

1. **Profiled golden run**: build the mer database from the committed
   golden reads with `--profile` + `--metrics` + `--trace-spans` AND
   `--metrics-push-url` pointed at an in-process
   tools/push_receiver.py. Asserts the final metrics document carries
   the devtrace surface with real numbers (`device_kernel_us_total`
   > 0 from the profiler's own trace — CPU traces carry `hlo_op`
   kernel events too, which is the point of the gate), that
   `trace_summary --device` renders the host-dispatch /
   device-execute / device-idle attribution table, and that the
   receiver aggregated the run's terminal push into a fleet document
   (`meta.fleet`, written to --out-dir for metrics_check to gate).

2. **Receiver outage**: a MetricsPusher pointed at a dead port must
   fail its periodic pushes (counted, capped backoff) WITHOUT failing
   anything else, and once a receiver comes up on that port the
   terminal flush's bounded retry must still land the final document
   (`metrics_pushed` meta True, the host present in the receiver's
   fleet).

3. **Stall -> absence alert -> heal** (ISSUE 11): a golden build with
   a `sleep` fault wedging one batch mid-run must fire the
   `pipeline_stalled` absence rule FROM THE TICKER (the stalled loop
   emits no heartbeats — that silence is the signal), land the
   structured `alert` events in the JSONL stream, then heal when the
   batch completes; the final document carries the alert surface
   (gauge back at 0, alerts_fired_total >= 1) and passes
   metrics_check.

4. **Serve SLO burn without flipping liveness** (ISSUE 11): a live
   quorum-serve under a fault plan failing every engine step after
   the first must burn the availability SLO — `/healthz` DETAIL
   (`slo`/`alerts`) reports the multi-window burn firing while the
   liveness verdict stays healthy (a burning SLO needs attention, not
   ejection) — and the drained final document passes metrics_check.

5. **Autotune round trip** (ISSUE 11): `quorum-autotune` writes a
   sealed profile whose probe lines pass `metrics_check
   --require-metric`; a subsequent stage run LOADS it
   (`meta.autotune_profile` stamped into its document) and an
   explicit lever env var still wins over the profile.

6. **Contaminant burst -> contam_spike -> sealed dump** (ISSUE 17):
   the golden reads fed back as the contaminant screen make the
   quality scorecard's windowed contam-rate gauge cross the default
   `contam_spike` rule end-to-end — the alert fires into the events
   stream, and the rule's `dump: true` leaves a SEALED flight dump
   whose trigger names the rule (the quality trajectory of a dying
   run, ISSUE 16's black box fed by ISSUE 17's scorecard).

7. **Serve quality-header parity** (ISSUE 17): every 200 response's
   `X-Quorum-Quality` per-request summary, summed over all requests,
   must reconcile EXACTLY with the drained serve document's
   scorecard — the header and the document are the same tallies
   through the same render path.

Artifacts land in --out-dir:
  telemetry_metrics.json  — the profiled stage-1 document
                            (metrics_check gates the devtrace + push
                            names via meta.profile/metrics_push_url)
  telemetry_fleet.json    — the receiver's aggregated fleet document
                            (metrics_check gates meta.fleet)
  telemetry_alerts_metrics.json(+.events.jsonl) — the stall run
  telemetry_serve_metrics.json — the burned serve document
  telemetry_autotune_metrics.json — the profile-applied stage run
  autotune_profile.json / autotune_lines.json — the derived profile
  telemetry_quality_metrics.json(+.events.jsonl, +.flight.json)
                          — the contaminant-burst run + its dump
  telemetry_serve_quality_metrics.json — the header-parity serve run

Exit 0 = all checks passed.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import socket
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fail(msg: str) -> int:
    print(f"[telemetry_smoke] FAIL: {msg}", file=sys.stderr)
    return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Profiled golden run + push-transport smoke "
                    "(ci/tier1.sh gate, ISSUE 10)")
    p.add_argument("--out-dir", default=None,
                   help="Where telemetry_metrics.json / "
                        "telemetry_fleet.json land (default: temp)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="telemetry_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    from push_receiver import PushReceiver
    import trace_summary
    from quorum_tpu.cli import create_database as cdb_cli

    reads = os.path.join(GOLDEN, "reads.fastq")
    db = os.path.join(out_dir, "db.jf")
    metrics_path = os.path.join(out_dir, "telemetry_metrics.json")
    fleet_path = os.path.join(out_dir, "telemetry_fleet.json")
    profile_dir = os.path.join(out_dir, "profile")
    spans_path = os.path.join(out_dir, "spans.jsonl")

    # -- 1: profiled golden run, pushed to a live receiver ------------
    rx = PushReceiver(out_path=fleet_path, port=0)
    print(f"[telemetry_smoke] push receiver on 127.0.0.1:{rx.port}, "
          f"building golden database with --profile -> {profile_dir}")
    try:
        rc = cdb_cli.main(
            ["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
             "-o", db, "--metrics", metrics_path,
             "--profile", profile_dir, "--trace-spans", spans_path,
             "--metrics-push-url", f"http://127.0.0.1:{rx.port}/push",
             "--metrics-push-interval", "0.2", reads])
        if rc != 0:
            return _fail(f"profiled database build rc={rc}")
        hosts = rx.final_hosts
        fleet = rx.fleet
        periodic_pushes = rx.pushes
    finally:
        rx.close()

    with open(metrics_path) as f:
        doc = json.load(f)
    meta = doc.get("meta", {})
    if meta.get("devtrace_source") not in ("trace_json", "xplane"):
        return _fail(f"devtrace_source={meta.get('devtrace_source')!r} "
                     "(no profiler trace parsed)")
    kernel_us = doc.get("counters", {}).get("device_kernel_us_total")
    if not kernel_us or kernel_us <= 0:
        return _fail(f"device_kernel_us_total={kernel_us!r} — CPU "
                     "traces must carry kernel events too")
    steps = doc.get("gauges", {}).get("devtrace_steps", 0)
    if steps < 1:
        return _fail("no step windows joined (devtrace_steps=0): the "
                     "stage1_insert StepTraceAnnotations are missing "
                     "from the trace")
    stage_kernels = meta.get("devtrace_stage_kernel_us", {})
    if "stage1_insert" not in stage_kernels:
        return _fail(f"stage1_insert absent from per-stage kernel "
                     f"attribution {sorted(stage_kernels)}")
    print(f"[telemetry_smoke] devtrace: source="
          f"{meta['devtrace_source']} kernel_us={kernel_us} "
          f"steps={steps} stage1_insert="
          f"{stage_kernels['stage1_insert']}us")

    # the attribution table must render, with device truth > 0
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        ts_rc = trace_summary.main([spans_path, metrics_path,
                                    "--device", profile_dir])
    table = buf.getvalue()
    sys.stdout.write(table)
    if ts_rc != 0:
        return _fail(f"trace_summary --device rc={ts_rc}")
    if "device_execute_ms" not in table \
            or "stage1_insert" not in table:
        return _fail("trace_summary --device did not render the "
                     "attribution table")

    # the run's terminal push must have landed and aggregated
    if not hosts:
        return _fail("receiver saw no final push from the CLI")
    if not fleet or not fleet.get("meta", {}).get("fleet"):
        return _fail("receiver built no fleet document")
    if not os.path.exists(fleet_path):
        return _fail("fleet document was not written to --out")
    # presence, not >= 1: the final doc is snapshotted BEFORE the
    # terminal flush's own increment, so a run faster than the push
    # period legitimately carries 0 — the receiver's view proves the
    # periodic stream landed
    if "metrics_push_total" not in fleet.get("counters", {}):
        return _fail("fleet document lost the push counters")
    # >= 2: the terminal flush itself POSTs one exposition text, so a
    # single push proves only the flush — any beyond it had to come
    # from the periodic loop
    if periodic_pushes < 2:
        return _fail("receiver saw no periodic exposition push "
                     f"(pushes={periodic_pushes}; 1 is the terminal "
                     "flush's own)")
    print(f"[telemetry_smoke] push: fleet of {len(hosts)} host(s), "
          f"{periodic_pushes} periodic push(es) -> {fleet_path}")

    # -- 2: receiver outage: retry + terminal flush -------------------
    from quorum_tpu.telemetry.push import MetricsPusher
    from quorum_tpu.telemetry.registry import registry_for

    port = _free_port()
    reg = registry_for(None, force=True)
    reg.set_meta(stage="outage_probe")
    reg.counter("probe_events").inc(3)
    pusher = MetricsPusher(reg, f"http://127.0.0.1:{port}/push",
                           period_s=0.05)
    deadline = time.perf_counter() + 15
    while pusher.failures < 1:
        if time.perf_counter() > deadline:
            return _fail("no push failure recorded against the dead "
                         "receiver")
        time.sleep(0.02)
    print(f"[telemetry_smoke] outage: {pusher.failures} failed "
          f"push(es) against the dead port; bringing the receiver up")
    rx2 = PushReceiver(port=port)
    try:
        ok = pusher.close(final_doc=reg.as_dict())
        if not ok:
            return _fail("terminal flush did not land after the "
                         "receiver recovered")
        if reg.meta.get("metrics_pushed") is not True:
            return _fail("metrics_pushed meta not stamped True")
        if not rx2.final_hosts:
            return _fail("recovered receiver holds no final document")
    finally:
        rx2.close()
    print("[telemetry_smoke] outage survived via retry + terminal "
          "flush")

    # -- 3: induced stall -> absence alert -> heal --------------------
    from quorum_tpu.utils import faults

    alerts_metrics = os.path.join(out_dir,
                                  "telemetry_alerts_metrics.json")
    alerts_events = os.path.join(
        out_dir, "telemetry_alerts_metrics.events.jsonl")
    stall_rules = os.path.join(out_dir, "stall_rules.json")
    with open(stall_rules, "w") as f:
        json.dump({"rules": [{"name": "pipeline_stalled",
                              "type": "absence", "for_s": 0.8}]}, f)
    stall_plan = json.dumps([{"site": "stage1.insert", "batch": 2,
                              "action": "sleep", "seconds": 2.5}])
    print("[telemetry_smoke] stall run: sleep fault at batch 2, "
          "absence rule for_s=0.8")
    try:
        rc = cdb_cli.main(
            ["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
             "-o", os.path.join(out_dir, "db_stall.jf"),
             "--batch-size", "64",
             "--metrics", alerts_metrics,
             "--metrics-interval", "0.1",
             "--alert-rules", stall_rules,
             "--fault-plan", stall_plan, reads])
    finally:
        faults.reset()
    if rc != 0:
        return _fail(f"stall run rc={rc}")
    with open(alerts_metrics) as f:
        adoc = json.load(f)
    states = []
    with open(alerts_events) as f:
        for line in f:
            obj = json.loads(line)
            if obj.get("event") == "alert" \
                    and obj.get("rule") == "pipeline_stalled":
                states.append(obj["state"])
    if "firing" not in states or "healed" not in states:
        return _fail(f"absence alert did not fire+heal (events: "
                     f"{states})")
    gauge = adoc.get("gauges", {}).get(
        'alerts_firing{rule="pipeline_stalled"}')
    if gauge != 0:
        return _fail(f"pipeline_stalled gauge should have healed to "
                     f"0, is {gauge!r}")
    if adoc.get("counters", {}).get("alerts_fired_total", 0) < 1:
        return _fail("alerts_fired_total did not count the firing")
    print(f"[telemetry_smoke] stall: alert fired+healed "
          f"({states.count('firing')} firing(s)), gauge back at 0")

    # -- 4: serve SLO burn visible in /healthz, liveness intact -------
    import threading

    from quorum_tpu.cli import serve as serve_cli
    from quorum_tpu.serve.client import ServeClient

    serve_metrics = os.path.join(out_dir,
                                 "telemetry_serve_metrics.json")
    serve_rules = os.path.join(out_dir, "serve_rules.json")
    with open(serve_rules, "w") as f:
        # tiny windows so a few seconds of bad traffic burns; the
        # objective/window shape is the production rule's, scaled
        json.dump({"rules": [
            {"name": "serve_slo_availability", "type": "burn_rate",
             "objective": 0.9,
             "bad": ["requests_failed", "requests_deadline_exceeded"],
             "total": ["requests_completed", "requests_failed",
                       "requests_deadline_exceeded"],
             "windows": [[2.0, 1.0], [0.5, 1.0]]}]}, f)
    # every engine step after the first fails: request 1 succeeds
    # (compiles + seeds the serve histograms), the rest 500 — pure
    # SLO burn with the process itself perfectly alive
    serve_plan = json.dumps([{"site": "serve.engine.step", "at": 2,
                              "count": -1, "action": "error"}])
    port = _free_port()
    rc_box: dict = {}

    def run_server():
        try:
            rc_box["rc"] = serve_cli.main(
                ["--port", str(port), "--max-batch", "64",
                 "--max-wait-ms", "2", "-p", "4",
                 "--max-consecutive-failures", "0",
                 "--metrics", serve_metrics,
                 "--metrics-interval", "0.2",
                 "--alert-rules", serve_rules,
                 "--fault-plan", serve_plan, db])
        finally:
            faults.reset()

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    client = ServeClient(port=port, timeout=300.0)
    deadline = time.perf_counter() + 60
    while True:
        try:
            client.healthz()
            break
        except OSError:
            if time.perf_counter() > deadline:
                return _fail("serve never came up")
            time.sleep(0.1)
    with open(reads) as f:
        body = "".join(f.readlines()[:8])  # 2 reads per request
    r1 = client.correct(body)
    if r1.status != 200:
        return _fail(f"first serve request status={r1.status} "
                     "(must succeed before the fault arms)")
    burned = None
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        r = client.correct(body)  # 500s: burning the error budget
        h = client.healthz()
        slo = h.get("slo", {}).get("serve_slo_availability", {})
        if slo.get("firing"):
            burned = h
            break
        time.sleep(0.1)
    if burned is None:
        return _fail("availability burn never surfaced in /healthz "
                     "slo detail")
    if burned.get("status") != "ok" or not burned.get("healthy"):
        return _fail(f"SLO burn flipped liveness: status="
                     f"{burned.get('status')!r} healthy="
                     f"{burned.get('healthy')!r} — burn is detail, "
                     "not ejection")
    if "serve_slo_availability" not in burned.get(
            "alerts", {}).get("firing", []):
        return _fail("alerts summary in /healthz does not list the "
                     "firing rule")
    print(f"[telemetry_smoke] serve burn: "
          f"{burned['slo']['serve_slo_availability']['burn']} "
          f"firing with status={burned['status']!r}")
    client.quiesce()
    t.join(timeout=90)
    if t.is_alive() or rc_box.get("rc") != 0:
        return _fail(f"serve drain failed (alive={t.is_alive()} "
                     f"rc={rc_box.get('rc')})")
    with open(serve_metrics) as f:
        sdoc = json.load(f)
    if sdoc.get("counters", {}).get("alerts_fired_total", 0) < 1:
        return _fail("serve document lost the alert firing")

    # -- 5: autotune profile derived, applied, env still wins ---------
    from quorum_tpu.cli import autotune as autotune_cli
    from quorum_tpu.ops import ctable, tuning

    profile_path = os.path.join(out_dir, "autotune_profile.json")
    lines_path = os.path.join(out_dir, "autotune_lines.json")
    autotune_metrics = os.path.join(
        out_dir, "telemetry_autotune_metrics.json")
    # same geometry as the bench A/B CI gate, so the compile cache is
    # already warm for these shapes
    rc = autotune_cli.main(["--reads", "256", "--len", "100",
                            "-k", "15", "--reps", "1",
                            "--out", profile_path,
                            "--metrics-lines", lines_path])
    if rc != 0:
        return _fail(f"quorum-autotune rc={rc}")
    import metrics_check
    if metrics_check.main(["--require-metric", "autotune_stage1",
                           "--require-metric", "autotune_stage2",
                           "--require-metric", "autotune_profile",
                           "-q", lines_path]) != 0:
        return _fail("autotune probe lines failed metrics_check "
                     "--require-metric")
    os.environ["QUORUM_AUTOTUNE_PROFILE"] = profile_path
    tuning.reset_cache()
    try:
        rc = cdb_cli.main(
            ["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
             "-o", os.path.join(out_dir, "db_tuned.jf"),
             "--metrics", autotune_metrics, reads])
        if rc != 0:
            return _fail(f"profile-applied build rc={rc}")
        with open(autotune_metrics) as f:
            tdoc = json.load(f)
        if tdoc.get("meta", {}).get("autotune_profile") \
                != profile_path:
            return _fail(f"meta.autotune_profile="
                         f"{tdoc.get('meta', {}).get('autotune_profile')!r}"
                         f" (expected {profile_path})")
        # an explicit env var must beat the profile's lever
        prof_lever = json.load(open(profile_path))[
            "levers"]["QUORUM_S1_AGGREGATE"]
        flipped = "0" if prof_lever != "0" else "1"
        os.environ["QUORUM_S1_AGGREGATE"] = flipped
        try:
            if ctable.s1_aggregate_default() != (flipped != "0"):
                return _fail("env QUORUM_S1_AGGREGATE did not win "
                             "over the profile lever")
        finally:
            os.environ.pop("QUORUM_S1_AGGREGATE", None)
    finally:
        os.environ.pop("QUORUM_AUTOTUNE_PROFILE", None)
        tuning.reset_cache()
    print(f"[telemetry_smoke] autotune: profile {profile_path} "
          f"applied (meta stamped), env override wins")

    # -- 6: contaminant burst -> contam_spike fires + flight dump -----
    # the standing accuracy alarm end-to-end (ISSUE 17): feed the
    # golden reads back as the contaminant screen, so the data plane
    # skips (nearly) everything as contaminant hits; the quality
    # scorecard's windowed contam-rate gauge crosses the default
    # `contam_spike` rule, whose dump:true leaves a sealed flight dump
    # naming the rule — the quality trajectory of a dying run
    from quorum_tpu.cli import error_correct_reads as ec_cli

    quality_metrics = os.path.join(
        out_dir, "telemetry_quality_metrics.json")
    quality_events = os.path.join(
        out_dir, "telemetry_quality_metrics.events.jsonl")
    quality_dump = os.path.join(
        out_dir, "telemetry_quality_metrics.flight.json")
    contam_fa = os.path.join(out_dir, "contam.fa")
    with open(reads) as f:
        raw = f.read().splitlines()
    with open(contam_fa, "w") as f:
        for i in range(0, len(raw) - 3, 4):
            f.write(f">c{i // 4}\n{raw[i + 1]}\n")
    print("[telemetry_smoke] contaminant burst: golden reads as the "
          "screen, window=64 reads")
    os.environ["QUORUM_QUALITY_WINDOW_READS"] = "64"
    try:
        rc = ec_cli.main(
            ["-p", "4", db, reads,
             "-o", os.path.join(out_dir, "contam_out.fa"),
             "--batch-size", "64", "--contaminant", contam_fa,
             "--metrics", quality_metrics,
             "--metrics-interval", "0.05"])
    finally:
        os.environ.pop("QUORUM_QUALITY_WINDOW_READS", None)
    if rc != 0:
        return _fail(f"contaminant-burst run rc={rc}")
    with open(quality_metrics) as f:
        qdoc = json.load(f)
    qsec = qdoc.get("quality", {})
    if qsec.get("rates", {}).get("contam_rate", 0) <= 0.2:
        return _fail(f"contam_rate="
                     f"{qsec.get('rates', {}).get('contam_rate')!r} "
                     "did not cross the contam_spike threshold")
    if qsec.get("skip_reasons", {}).get("contaminant", 0) < 1:
        return _fail("skip_reasons.contaminant empty despite the "
                     "seeded burst")
    qstates = []
    with open(quality_events) as f:
        for line in f:
            obj = json.loads(line)
            if obj.get("event") == "alert" \
                    and obj.get("rule") == "contam_spike":
                qstates.append(obj["state"])
    if "firing" not in qstates:
        return _fail(f"contam_spike never fired (events: {qstates})")
    if qdoc.get("counters", {}).get("alerts_fired_total", 0) < 1:
        return _fail("alerts_fired_total did not count the "
                     "contam_spike firing")
    if not os.path.exists(quality_dump):
        return _fail("contam_spike dump:true left no flight dump "
                     f"at {quality_dump}")
    with open(quality_dump) as f:
        fdoc = json.load(f)
    if fdoc.get("trigger", {}).get("site") != "contam_spike":
        return _fail(f"flight dump names site "
                     f"{fdoc.get('trigger', {}).get('site')!r}, "
                     "expected 'contam_spike'")
    if "crc32c" not in fdoc:
        return _fail("flight dump is not sealed (no crc32c)")
    if metrics_check.main(["-q", quality_metrics, quality_dump]) != 0:
        return _fail("contaminant-burst artifacts failed "
                     "metrics_check")
    print(f"[telemetry_smoke] contam burst: contam_rate="
          f"{qsec['rates']['contam_rate']} fired contam_spike, "
          f"sealed dump names the rule -> {quality_dump}")

    # -- 7: serve X-Quorum-Quality reconciles with the final doc ------
    # every 200 response carries a per-request quality summary; the
    # sums across all requests must equal the drained serve document's
    # scorecard exactly (same render path, same tallies — ISSUE 17)
    serve_q_metrics = os.path.join(
        out_dir, "telemetry_serve_quality_metrics.json")
    port = _free_port()
    rc_box2: dict = {}

    def run_quality_server():
        rc_box2["rc"] = serve_cli.main(
            ["--port", str(port), "--max-batch", "64",
             "--max-wait-ms", "2", "-p", "4",
             "--metrics", serve_q_metrics, db])

    t2 = threading.Thread(target=run_quality_server, daemon=True)
    t2.start()
    client2 = ServeClient(port=port, timeout=300.0)
    deadline = time.perf_counter() + 60
    while True:
        try:
            client2.healthz()
            break
        except OSError:
            if time.perf_counter() > deadline:
                return _fail("quality serve never came up")
            time.sleep(0.1)
    tot = {"reads": 0, "corrected": 0, "skipped": 0,
           "subs": 0, "t3": 0, "t5": 0}
    n_req = 0
    for start in range(0, len(raw) - 3, 4 * 96):
        body = "\n".join(raw[start:start + 4 * 96]) + "\n"
        r = client2.correct(body)
        if r.status != 200:
            return _fail(f"quality serve request status={r.status}")
        if not isinstance(r.quality, dict):
            return _fail("200 response carries no X-Quorum-Quality "
                         "header")
        n_req += 1
        for k in tot:
            tot[k] += int(r.quality.get(k, 0))
    client2.quiesce()
    t2.join(timeout=90)
    if t2.is_alive() or rc_box2.get("rc") != 0:
        return _fail(f"quality serve drain failed "
                     f"(alive={t2.is_alive()} rc={rc_box2.get('rc')})")
    with open(serve_q_metrics) as f:
        sqdoc = json.load(f)
    sq = sqdoc.get("quality", {})
    pairs = (("reads", "reads"), ("corrected", "corrected"),
             ("skipped", "skipped"), ("subs", "substitutions"),
             ("t3", "truncations_3p"), ("t5", "truncations_5p"))
    for hk, dk in pairs:
        if tot[hk] != sq.get(dk):
            return _fail(f"header sum {hk}={tot[hk]} != serve "
                         f"document quality.{dk}={sq.get(dk)!r}")
    if metrics_check.main(["-q", serve_q_metrics]) != 0:
        return _fail("quality serve document failed metrics_check")
    print(f"[telemetry_smoke] serve quality: {n_req} request(s), "
          f"header sums reconcile with the final document "
          f"({tot['reads']} reads, {tot['subs']} subs)")

    print("[telemetry_smoke] OK: devtrace attribution rendered, fleet "
          "document aggregated, outage survived, stall alert "
          "fired+healed, SLO burn surfaced without flipping "
          "liveness, autotune profile round-tripped, contaminant "
          "burst fired contam_spike with a sealed dump, serve "
          "quality headers reconciled")
    return 0


if __name__ == "__main__":
    sys.exit(main())
