#!/usr/bin/env python
"""Seeded chaos soak for the serve resilience tier (ci/tier1.sh gate,
ISSUE 7): drive a LIVE quorum-serve through every failure path the
fault-containment layer claims to survive, under a deterministic
fault plan, and assert the invariants that define the tier:

  * every request terminates (no future ever hangs to the wall),
  * every 200 body is byte-identical to the offline CLI's output for
    the same reads (per-read parity against tests/golden/expected.fa),
  * a `hang` fault in the engine step is contained by the watchdog:
    only that request fails, the engine generation bumps
    (`engine_restarts_total`), and the next request succeeds on the
    rebuilt engine — and the watchdog leaves exactly ONE sealed
    flight-recorder dump (telemetry/flight.py, ISSUE 16) naming the
    hung `serve.engine.step` with the abandoned step thread's stack;
    the clean drain at the end must NOT add another,
  * consecutive injected step failures flip /healthz to 503 and a
    clean request heals it back to 200,
  * an ambiguous batch failure is hedged: innocent batchmates of a
    poisoned request still answer 200 with byte parity
    (`hedges_total`),
  * POST /reload hot-swaps the engine (generation bump, parity on the
    new engine) and rolls back on a corrupt DB or an injected
    `serve.reload` fault (parity from the OLD engine),
  * per-client quotas shed a greedy client with 429 + Retry-After
    (`quota_rejections_total`) while anonymous traffic flows,
  * a seeded randomized fault storm (sleep/error at
    `serve.engine.step`) under retrying closed-loop load terminates
    with nothing but known statuses and byte-identical 200s,
  * the final metrics document passes tools/metrics_check.py
    (including the resilience feature counters) and the /metrics
    scrape lints clean with --prom.

Artifacts land in --out-dir:
  chaos_metrics.json — the final serve document (metrics_check gates
                       it, including SERVE_FEATURE_COUNTERS)
  chaos_scrape.prom  — a /metrics scrape taken mid-soak
                       (metrics_check --prom gates it)
  chaos_metrics.flight.json — the watchdog's black-box dump from the
                       hang phase (metrics_check gates it by schema)

Exit 0 = all invariants held. Deterministic for a fixed --seed: the
phase plans are fixed and the storm's fault plan derives from the
seed. Run by ci/tier1.sh after the tier-1 pytest pass.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import random
import re
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def _fail(msg: str) -> int:
    print(f"[chaos_soak] FAIL: {msg}", file=sys.stderr)
    return 1


def _parse_golden():
    """Per-read parity oracle from the committed golden artifacts:
    header -> (fastq_record_text, expected_fa, expected_log)."""
    with open(os.path.join(GOLDEN, "reads.fastq")) as f:
        fq_lines = f.read().splitlines(keepends=True)
    fq = {}
    for i in range(0, len(fq_lines), 4):
        hdr = fq_lines[i][1:].strip()
        fq[hdr] = "".join(fq_lines[i:i + 4])
    with open(os.path.join(GOLDEN, "expected.fa")) as f:
        fa_text = f.read()
    fa = {}
    for block in fa_text.split(">"):
        if not block:
            continue
        name = block.split(None, 1)[0].strip()
        fa[name] = ">" + block
    with open(os.path.join(GOLDEN, "expected.log")) as f:
        log_lines = f.read().splitlines(keepends=True)
    logs = {}
    for line in log_lines:
        m = re.match(r"Skipped (\S+):", line)
        if m:
            logs[m.group(1)] = line
    oracle = {}
    for hdr, rec in fq.items():
        oracle[hdr] = (rec, fa.get(hdr, ""), logs.get(hdr, ""))
    return oracle, fa_text


def _scrape_counter(text: str, name: str) -> float:
    """Sum a counter's samples out of a Prometheus scrape (the
    exposition suffixes counters with _total)."""
    total = 0.0
    for m in re.finditer(
            rf"^quorum_tpu_{re.escape(name)}_total(?:{{[^}}]*}})? "
            r"([0-9.eE+-]+)$", text, re.M):
        total += float(m.group(1))
    return total


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Seeded chaos soak: watchdog, health flip, "
                    "hedging, reload, quotas, and a randomized fault "
                    "storm against a live quorum-serve (ci/tier1.sh "
                    "gate)")
    p.add_argument("--out-dir", default=None,
                   help="Where chaos_metrics.json / chaos_scrape.prom "
                        "land (default: a temp dir)")
    p.add_argument("--seed", type=int, default=7,
                   help="Storm fault-plan seed (default 7; CI pins it)")
    p.add_argument("--rows", type=int, default=64,
                   help="Engine batch rows (default 64)")
    p.add_argument("--step-timeout-ms", type=float, default=20000,
                   help="Watchdog budget; must exceed the FIRST real "
                        "step's lazy compiles (the all-A warmup read "
                        "cannot reach the deeper extension-loop "
                        "levels, ~4s warm-cache on CPU), and the hang "
                        "phase costs this much wall time (default "
                        "20000)")
    p.add_argument("--storm-requests", type=int, default=24,
                   help="Requests in the randomized storm (default 24)")
    p.add_argument("--storm-workers", type=int, default=4,
                   help="Closed-loop storm workers (default 4)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="chaos_soak_")
    os.makedirs(out_dir, exist_ok=True)

    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import serve as serve_cli
    from quorum_tpu.serve.client import ServeClient
    from quorum_tpu.utils import faults

    oracle, expected_fa = _parse_golden()
    # reads whose parity we probe individually: the skipped read plus
    # a deterministic handful of corrected ones
    probe_headers = ["read0", "read1", "read7", "skip_no_anchor"]
    for h in probe_headers:
        assert h in oracle, f"golden fixture lost {h}"

    db = os.path.join(out_dir, "db.jf")
    metrics_path = os.path.join(out_dir, "chaos_metrics.json")
    scrape_path = os.path.join(out_dir, "chaos_scrape.prom")
    print(f"[chaos_soak] building golden database -> {db}")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, os.path.join(GOLDEN, "reads.fastq")])
    if rc != 0:
        return _fail("database build")

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rc_box: dict = {}

    def run_server():
        rc_box["rc"] = serve_cli.main(
            ["--port", str(port), "--max-batch", str(args.rows),
             "--max-wait-ms", "2", "-p", "4",
             "--warmup-lengths", "60",
             "--step-timeout-ms", str(args.step_timeout_ms),
             "--max-consecutive-failures", "3",
             "--max-hedges", "8",
             "--quota-rps", "2", "--quota-burst", "2",
             "--metrics", metrics_path, db])

    srv_thread = threading.Thread(target=run_server, daemon=True)
    srv_thread.start()
    client = ServeClient(port=port, timeout=900.0)
    deadline = time.perf_counter() + 120
    while True:
        try:
            client.healthz()
            break
        except OSError:
            if time.perf_counter() > deadline:
                return _fail("server never came up")
            time.sleep(0.1)

    def probe_parity(tag: str, hdr: str = "read0",
                     retry: bool = False) -> int:
        rec, want_fa, want_log = oracle[hdr]
        if retry:
            r = client.correct_with_retry(rec, want_log=True)
        else:
            r = client.correct(rec, want_log=True)
        if r.status != 200:
            return _fail(f"{tag}: probe {hdr} -> {r.status} {r.error}")
        if r.fa != want_fa or r.log != want_log:
            return _fail(f"{tag}: probe {hdr} parity DRIFT")
        return 0

    try:
        # -- phase 1: clean parity -----------------------------------------
        print("[chaos_soak] phase 1: clean parity (cold + warm)")
        with open(os.path.join(GOLDEN, "reads.fastq")) as f:
            full_body = f.read()
        r = client.correct(full_body)
        if r.status != 200 or r.fa != expected_fa:
            return _fail(f"phase 1: full-file status={r.status} "
                         f"parity="
                         f"{'ok' if r.fa == expected_fa else 'DRIFT'}")
        for hdr in probe_headers:
            if probe_parity("phase 1", hdr):
                return 1
        gen0 = client.healthz()["engine_generation"]

        # -- phase 2: hang contained by the watchdog -----------------------
        print("[chaos_soak] phase 2: hang -> watchdog engine restart "
              f"(~{args.step_timeout_ms / 1000:.0f}s)")
        faults.install(faults.FaultPlan.parse(
            {"site": "serve.engine.step", "action": "hang"}), "soak-hang")
        r = client.correct(oracle["read1"][0])
        if r.status != 500:
            return _fail(f"phase 2: hung request -> {r.status} "
                         "(want 500)")
        gen1 = client.healthz()["engine_generation"]
        if gen1 != gen0 + 1:
            return _fail(f"phase 2: generation {gen0} -> {gen1} "
                         "(want +1: watchdog engine restart)")
        # the very next request must succeed on the rebuilt engine
        if probe_parity("phase 2 (rebuilt engine)", "read1"):
            return 1
        faults.release_hangs()

        # the watchdog is a flight-recorder trigger (ISSUE 16): the
        # hang must leave exactly one sealed black-box dump next to
        # the metrics document, pinpointing the wedged engine step
        flight_path = metrics_path[:-len(".json")] + ".flight.json"
        fdeadline = time.perf_counter() + 10
        while not os.path.exists(flight_path):
            if time.perf_counter() > fdeadline:
                return _fail("phase 2: watchdog fired but no flight "
                             f"dump at {flight_path}")
            time.sleep(0.05)
        with open(flight_path) as f:
            fdoc = json.load(f)
        trig = fdoc.get("trigger", {})
        if trig.get("kind") != "watchdog":
            return _fail(f"phase 2: flight trigger kind "
                         f"{trig.get('kind')!r} (want 'watchdog')")
        if trig.get("site") != "serve.engine.step":
            return _fail(f"phase 2: flight trigger site "
                         f"{trig.get('site')!r} "
                         "(want 'serve.engine.step')")
        if "quorum-serve-step" not in trig.get("detail", ""):
            return _fail("phase 2: flight trigger does not name the "
                         f"hung step thread: {trig.get('detail')!r}")
        # the abandoned step thread was still alive at dump time, so
        # the all-thread stacks must show WHERE it wedged
        if not any(t.get("name", "").startswith("quorum-serve-step")
                   for t in fdoc.get("threads", [])):
            return _fail("phase 2: flight dump lacks the hung "
                         "quorum-serve-step thread's stack")
        print(f"[chaos_soak] phase 2: flight dump -> {flight_path}")

        # -- phase 3: health flips under consecutive failures, heals -------
        print("[chaos_soak] phase 3: consecutive failures flip "
              "/healthz, success heals")
        faults.install(faults.FaultPlan.parse(
            {"site": "serve.engine.step", "action": "error",
             "count": 3}), "soak-flip")
        for i in range(3):
            r = client.correct(oracle["read2" if "read2" in oracle
                                      else "read0"][0])
            if r.status != 500:
                return _fail(f"phase 3: injected failure {i} -> "
                             f"{r.status} (want 500)")
        code, h = client.healthz_full()
        if code != 503 or h["status"] != "unhealthy":
            return _fail(f"phase 3: healthz {code}/{h['status']} "
                         "(want 503/unhealthy)")
        if probe_parity("phase 3 (heal)"):
            return 1
        code, h = client.healthz_full()
        if code != 200 or h["status"] != "ok":
            return _fail(f"phase 3: healthz did not heal ({code})")

        # -- phase 4: hedging saves innocent batchmates --------------------
        print("[chaos_soak] phase 4: ambiguous batch failure -> "
              "solo hedges")
        hdrs = ["read3", "read4", "read5", "read6"]
        hedged = False
        for attempt in range(3):
            before = _scrape_counter(client.metrics_text(),
                                     "hedges_total")
            faults.install(faults.FaultPlan.parse([
                {"site": "serve.engine.step", "action": "sleep",
                 "seconds": 0.5},
                {"site": "serve.engine.step", "at": 2, "count": 2,
                 "action": "error"},
            ]), f"soak-hedge-{attempt}")
            occupier: dict = {}

            def occupy():
                occupier["r"] = client.correct(oracle["read0"][0])

            t0 = threading.Thread(target=occupy, daemon=True)
            t0.start()
            time.sleep(0.15)  # occupier's step is sleeping in-engine
            results: list = [None] * len(hdrs)
            ths = []
            for i, hdr in enumerate(hdrs):
                cl = ServeClient(port=port, timeout=900.0)

                def post(i=i, hdr=hdr, cl=cl):
                    results[i] = cl.correct(oracle[hdr][0],
                                            want_log=True)

                th = threading.Thread(target=post, daemon=True)
                th.start()
                ths.append(th)
            for th in ths + [t0]:
                th.join(timeout=60)
                if th.is_alive():
                    return _fail("phase 4: a request never terminated")
            faults.reset()
            delta = _scrape_counter(client.metrics_text(),
                                    "hedges_total") - before
            all_ok = all(r is not None and r.status == 200
                         for r in results)
            parity = all(
                r.fa == oracle[hdr][1] and r.log == oracle[hdr][2]
                for r, hdr in zip(results, hdrs)
                if r is not None and r.status == 200)
            if not parity:
                return _fail("phase 4: hedged responses lost parity")
            if all_ok and delta >= 2:
                hedged = True
                break
            print(f"[chaos_soak] phase 4: attempt {attempt} did not "
                  f"coalesce (delta={delta}); retrying")
        if not hedged:
            return _fail("phase 4: hedging never engaged in 3 attempts")

        # -- phase 5: hot reload + rollback --------------------------------
        print("[chaos_soak] phase 5: /reload swap, corrupt-DB "
              "rollback, injected-fault rollback")
        gen = client.healthz()["engine_generation"]
        code, doc = client.reload({})
        if code != 200 or doc.get("generation") != gen + 1:
            return _fail(f"phase 5: good reload -> {code} {doc}")
        if probe_parity("phase 5 (new generation)"):
            return 1
        corrupt = os.path.join(out_dir, "corrupt.jf")
        with open(corrupt, "wb") as f:
            f.write(b"\x00\x01 not a database \xff")
        code, doc = client.reload({"db": corrupt})
        if code != 400 or not doc.get("rolled_back"):
            return _fail(f"phase 5: corrupt reload -> {code} {doc}")
        if probe_parity("phase 5 (rollback)"):
            return 1
        faults.install(faults.FaultPlan.parse(
            {"site": "serve.reload", "action": "error"}), "soak-reload")
        code, doc = client.reload({})
        faults.reset()
        if code != 500 or not doc.get("rolled_back"):
            return _fail(f"phase 5: injected reload fault -> {code}")
        if probe_parity("phase 5 (fault rollback)"):
            return 1

        # -- phase 6: quotas + admission fault -----------------------------
        print("[chaos_soak] phase 6: greedy client quota, admission "
              "fault")
        # empty-body probes: the quota charges at ADMISSION (before
        # the engine), so a burst of 5 against burst=2 deterministically
        # splits 2x200 / 3x429 however slow the device is
        statuses = [client.correct("", client_id="greedy").status
                    for _ in range(5)]
        if statuses[:2] != [200, 200] or statuses.count(429) < 2:
            return _fail(f"phase 6: greedy statuses {statuses} "
                         "(want the burst admitted, then 429s)")
        if probe_parity("phase 6 (anonymous unaffected)"):
            return 1
        time.sleep(1.1)  # tokens refill at 2/s
        r = client.correct("", client_id="greedy")
        if r.status != 200:
            return _fail(f"phase 6: refilled greedy -> {r.status}")
        faults.install(faults.FaultPlan.parse(
            {"site": "serve.admit", "action": "error"}), "soak-admit")
        r = client.correct(oracle["read0"][0])
        faults.reset()
        if r.status != 503:
            return _fail(f"phase 6: admit fault -> {r.status} "
                         "(want 503)")
        if probe_parity("phase 6 (after admit fault)"):
            return 1

        # -- phase 7: seeded randomized fault storm ------------------------
        print(f"[chaos_soak] phase 7: randomized storm (seed "
              f"{args.seed}, {args.storm_requests} requests)")
        rng = random.Random(args.seed)
        specs = []
        for _ in range(6):
            if rng.random() < 0.5:
                specs.append({"site": "serve.engine.step",
                              "action": "sleep",
                              "at": rng.randint(1, args.storm_requests),
                              "seconds": round(rng.uniform(0.01, 0.2),
                                               3)})
            else:
                specs.append({"site": "serve.engine.step",
                              "action": "error",
                              "at": rng.randint(1, args.storm_requests),
                              "count": rng.randint(1, 2)})
        faults.install(faults.FaultPlan.parse(specs), "soak-storm")
        storm_hdrs = [h for h in oracle if h != "skip_no_anchor"]
        jobs = [rng.choice(storm_hdrs)
                for _ in range(args.storm_requests)]
        next_i = [0]
        lock = threading.Lock()
        outcomes: dict[int, int] = {}
        bad: list[str] = []

        def storm_worker():
            cl = ServeClient(port=port, timeout=900.0)
            while True:
                with lock:
                    i = next_i[0]
                    if i >= len(jobs):
                        return
                    next_i[0] += 1
                hdr = jobs[i]
                rec, want_fa, want_log = oracle[hdr]
                r = cl.correct_with_retry(rec, want_log=True,
                                          max_attempts=4,
                                          max_backoff_s=0.5)
                with lock:
                    outcomes[r.status] = outcomes.get(r.status, 0) + 1
                    if r.status == 200 and (r.fa != want_fa
                                            or r.log != want_log):
                        bad.append(f"{hdr}: parity drift")
                    elif r.status not in (200, 429, 500, 503, 504):
                        bad.append(f"{hdr}: status {r.status}")

        workers = [threading.Thread(target=storm_worker, daemon=True)
                   for _ in range(max(1, args.storm_workers))]
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=300)
            if w.is_alive():
                return _fail("phase 7: a storm request never "
                             "terminated")
        faults.reset()
        if bad:
            return _fail(f"phase 7: {bad[:5]}")
        if outcomes.get(200, 0) == 0:
            return _fail(f"phase 7: no successes at all ({outcomes})")
        print(f"[chaos_soak] phase 7 outcomes: {outcomes}")
        if probe_parity("phase 7 (after storm)", retry=True):
            return 1

        # -- drain + artifact gates ----------------------------------------
        with open(scrape_path, "w") as f:
            f.write(client.metrics_text())
        print(f"[chaos_soak] scraped /metrics -> {scrape_path}")
        print("[chaos_soak] draining via /quiesce")
        client.quiesce()
        srv_thread.join(timeout=120)
        if srv_thread.is_alive() or rc_box.get("rc") != 0:
            return _fail(f"drain (alive={srv_thread.is_alive()} "
                         f"rc={rc_box.get('rc')})")
    finally:
        faults.reset()  # releases any still-hung threads

    with open(metrics_path) as f:
        doc = json.load(f)
    counters = doc.get("counters", {})
    for name, floor in (("engine_restarts_total", 1),
                        ("hedges_total", 2), ("reload_total", 1),
                        ("reload_failures_total", 2),
                        ("quota_rejections_total", 1),
                        ("requests_rejected_admission", 1),
                        ("batch_bisections", 1),
                        ("engine_step_failures", 1)):
        if counters.get(name, 0) < floor:
            return _fail(f"final doc: counter {name}="
                         f"{counters.get(name)} < {floor}")
    if doc.get("meta", {}).get("drained") is not True:
        return _fail("final doc: meta.drained is not True")

    spec = importlib.util.spec_from_file_location(
        "metrics_check", os.path.join(REPO, "tools", "metrics_check.py"))
    mc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mc)
    if mc.main([metrics_path]) != 0:
        return _fail("metrics_check rejected the final document")
    if mc.main(["--prom", scrape_path]) != 0:
        return _fail("metrics_check --prom rejected the scrape")
    # the flight dump itself is a gated artifact: schema + seal via
    # the same metrics_check dispatch CI uses
    if mc.main([flight_path]) != 0:
        return _fail("metrics_check rejected the flight dump")
    # exactly ONE incident, and the clean drain added no dump: the
    # phase-2 watchdog dump is the only one (first-trigger-wins), and
    # phases 3-7's contained failures plus the quiesce drain must not
    # have produced another
    if counters.get("flight_dumps_total", 0) != 1:
        return _fail("final doc: flight_dumps_total="
                     f"{counters.get('flight_dumps_total')} (want "
                     "exactly 1: the phase-2 watchdog incident; a "
                     "clean drain must not dump)")
    stray = [n for n in os.listdir(out_dir)
             if n.endswith(".flight.json")
             and os.path.join(out_dir, n) != flight_path]
    if stray:
        return _fail(f"clean drain left stray flight dumps: {stray}")

    print(f"[chaos_soak] OK: all invariants held (seed {args.seed}); "
          f"final metrics -> {metrics_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
