#!/usr/bin/env python
"""Performance regression verdicts over quorum-tpu metrics artifacts
(ISSUE 11): compare what a run measured against what it is SUPPOSED
to measure, with per-metric tolerances, so a 30% throughput loss
fails CI the way a wrong byte does.

Two modes:

* **Baseline gate** (what ci/tier1.sh runs)::

      python tools/perf_diff.py --baseline PERF_BASELINE.json \\
          bench_ab=/tmp/bench_ab.json stage1=/tmp/metrics.json \\
          --out verdict.json

  `PERF_BASELINE.json` (committed at the repo root) names, per
  document key, the metrics to check with their baseline values and
  limits. Every named metric is extracted from the matching document
  (final metrics JSON or BENCH metric-line file), compared, and the
  verdict document (`quorum-tpu-perf-diff/1`, validated by
  tools/metrics_check.py) is written to `--out`. Exit 1 on any
  regression or required-metric absence.

* **Two-document compare** (by hand, between rounds)::

      python tools/perf_diff.py OLD.json NEW.json [--tolerance-pct 50]

  Extracts the perf-shaped metrics both documents share (wall
  seconds, dispatch/wait splits, devtrace kernel totals, serve phase
  histograms, bench speedups/throughput) and applies the direction
  heuristic: time-like metrics regress when they grow, speedup/
  throughput-like ones when they shrink.

Metric names are flat extraction paths over any artifact kind:

    gauges.<name>                   timers.<name>.total_seconds
    timers.<name>.stages.<s>.seconds
    counters.<name>                 histograms.<name>.count|sum|mean
    bench.<metric>.<field>          (BENCH metric-line documents)

Limits per baseline metric (any combination): `max_ratio` /
`min_ratio` (candidate vs baseline `value`), absolute `min` / `max`,
symmetric `tolerance_pct`, plus `optional` (absence is not a
regression) and `direction` ("higher_better" flips which ratio bound
the generator emits). Tolerances are wide by design on wall-clock
entries — shared CI boxes are noisy; the gate exists to catch the
4x cliff and the silently-vanished metric, while `min`-bounded
structural entries (device_kernel_us_total > 0, speedups, parity)
stay tight.

`--write-baseline` regenerates the baseline document from fresh
artifacts (curated default limits by name shape); review the diff
before committing it — the baseline is a CONTRACT, not a cache.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE_SCHEMA = "quorum-tpu-perf-baseline/1"
VERDICT_SCHEMA = "quorum-tpu-perf-diff/1"

# two-doc mode: only metrics matching these shapes are compared (a
# run manifest carries plenty of numbers that are not performance)
_PERF_SHAPES = (
    "timers.*.total_seconds", "timers.*.stages.*.seconds",
    "gauges.*_seconds", "gauges.*gb_per_h*",
    "counters.*_us_total",
    "counters.compile_events", "counters.compiles{site=*",
    "histograms.*_us.sum", "histograms.*_us.mean",
    "bench.*.speedup*", "bench.*.value", "bench.*_ms",
    "bench.*.base_ms", "bench.*.workers_ms",
    "bench.*.aggregated_ms", "bench.*.compact_sweep_ms",
    "bench.*.compact_drain_ms",
)

# direction heuristic: does a BIGGER candidate value mean regression?
_LOWER_BETTER_SUFFIXES = ("_seconds", ".seconds", "_ms", "_us",
                          ".sum", ".mean", "_us_total")
_HIGHER_BETTER_MARKS = ("speedup", "gb_per_h", "gb_h", "throughput",
                        ".value")


def direction_for(name: str) -> str:
    low = name.lower()
    for mark in _HIGHER_BETTER_MARKS:
        if mark in low:
            return "higher_better"
    for suf in _LOWER_BETTER_SUFFIXES:
        if low.endswith(suf):
            return "lower_better"
    return "both"


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def extract_profile(path: str) -> dict[str, float]:
    """The flat perf profile of one artifact: a final metrics JSON
    document (gauges/timers/counters/histograms) or a BENCH-style
    metric-line file (bench.<metric>.<field>)."""
    with open(path) as f:
        text = f.read()
    prof: dict[str, float] = {}
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and ("counters" in doc
                                  or "gauges" in doc):
        for k, v in doc.get("gauges", {}).items():
            if _is_num(v):
                prof[f"gauges.{k}"] = float(v)
        for k, v in doc.get("counters", {}).items():
            if _is_num(v):
                prof[f"counters.{k}"] = float(v)
        for k, t in doc.get("timers", {}).items():
            if _is_num(t.get("total_seconds")):
                prof[f"timers.{k}.total_seconds"] = float(
                    t["total_seconds"])
            for sk, sv in t.get("stages", {}).items():
                if isinstance(sv, dict) and _is_num(sv.get("seconds")):
                    prof[f"timers.{k}.stages.{sk}.seconds"] = float(
                        sv["seconds"])
        for k, h in doc.get("histograms", {}).items():
            if not isinstance(h, dict):
                continue
            n = h.get("count")
            s = h.get("sum")
            if _is_num(n):
                prof[f"histograms.{k}.count"] = float(n)
            if _is_num(s):
                prof[f"histograms.{k}.sum"] = float(s)
                if n:
                    prof[f"histograms.{k}.mean"] = float(s) / n
        return prof
    # line-oriented: BENCH metric lines (and anything else is skipped)
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if not isinstance(obj, dict) or not isinstance(
                obj.get("metric"), str):
            continue
        m = obj["metric"]
        for k, v in obj.items():
            if k != "metric" and _is_num(v):
                prof[f"bench.{m}.{k}"] = float(v)
    return prof


def check_metric(name: str, spec: dict, cand: float | None) -> dict:
    """One metric's verdict entry: ok flag + the limits applied."""
    entry: dict = {"ok": True}
    base = spec.get("value")
    if base is not None:
        entry["baseline"] = base
    if cand is None:
        if spec.get("optional"):
            entry["status"] = "absent (optional)"
        else:
            entry["ok"] = False
            entry["status"] = "missing from candidate"
        return entry
    entry["value"] = cand
    probs = []
    if _is_num(base) and base != 0:
        entry["ratio"] = round(cand / base, 4)
    if spec.get("min") is not None and cand < spec["min"]:
        probs.append(f"value {cand:g} < min {spec['min']:g}")
    if spec.get("max") is not None and cand > spec["max"]:
        probs.append(f"value {cand:g} > max {spec['max']:g}")
    if _is_num(base) and base != 0:
        # relative limits against a zero baseline are meaningless
        # (every positive candidate would "exceed 0 x ratio"); a
        # near-zero metric wants absolute min/max bounds instead —
        # the generator refuses to emit ratio entries for them
        if spec.get("max_ratio") is not None \
                and cand > base * spec["max_ratio"]:
            probs.append(f"value {cand:g} > baseline {base:g} x "
                         f"{spec['max_ratio']:g}")
        if spec.get("min_ratio") is not None \
                and cand < base * spec["min_ratio"]:
            probs.append(f"value {cand:g} < baseline {base:g} x "
                         f"{spec['min_ratio']:g}")
        tol = spec.get("tolerance_pct")
        if tol is not None and abs(cand - base) > abs(base) * tol / 100.0:
            probs.append(f"value {cand:g} outside +-{tol:g}% of "
                         f"baseline {base:g}")
    if probs:
        entry["ok"] = False
        entry["status"] = "; ".join(probs)
    return entry


def run_baseline(baseline_path: str, docs: dict[str, str],
                 out: str | None, quiet: bool = False) -> int:
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        print(f"perf_diff: {baseline_path}: {e}", file=sys.stderr)
        return 2
    if baseline.get("schema") != BASELINE_SCHEMA:
        print(f"perf_diff: {baseline_path} is not a "
              f"{BASELINE_SCHEMA} document", file=sys.stderr)
        return 2
    verdict = {
        "schema": VERDICT_SCHEMA,
        "baseline": os.path.basename(baseline_path),
        "verdict": "pass",
        "checked": 0,
        "regressions": [],
        "docs": {},
    }
    for key, spec in baseline.get("docs", {}).items():
        path = docs.get(key)
        dv: dict = {"metrics": {}}
        verdict["docs"][key] = dv
        if path is None:
            if spec.get("optional"):
                dv["status"] = "not supplied (optional)"
                continue
            dv["status"] = "document not supplied"
            verdict["regressions"].append(f"{key}: document not "
                                          "supplied")
            continue
        try:
            prof = extract_profile(path)
        except OSError as e:
            dv["status"] = str(e)
            verdict["regressions"].append(f"{key}: {e}")
            continue
        dv["path"] = path
        for name, mspec in spec.get("metrics", {}).items():
            entry = check_metric(name, mspec, prof.get(name))
            dv["metrics"][name] = entry
            verdict["checked"] += 1
            if not entry["ok"]:
                verdict["regressions"].append(
                    f"{key}: {name}: {entry.get('status')}")
    extra = docs.keys() - baseline.get("docs", {}).keys()
    if extra:
        print(f"perf_diff: warning: supplied documents not in the "
              f"baseline: {sorted(extra)}", file=sys.stderr)
    if verdict["regressions"]:
        verdict["verdict"] = "regression"
    _finish(verdict, out, quiet)
    return 0 if verdict["verdict"] == "pass" else 1


def run_two_doc(old_path: str, new_path: str, tolerance_pct: float,
                out: str | None, quiet: bool = False) -> int:
    try:
        old = extract_profile(old_path)
        new = extract_profile(new_path)
    except OSError as e:
        print(f"perf_diff: {e}", file=sys.stderr)
        return 2
    shared = sorted(
        n for n in old.keys() & new.keys()
        if any(fnmatch.fnmatch(n, pat) for pat in _PERF_SHAPES))
    verdict = {
        "schema": VERDICT_SCHEMA,
        "baseline": old_path,
        "verdict": "pass",
        "checked": 0,
        "regressions": [],
        "docs": {"candidate": {"path": new_path, "metrics": {}}},
    }
    mx = verdict["docs"]["candidate"]["metrics"]
    factor = 1.0 + tolerance_pct / 100.0
    for name in shared:
        d = direction_for(name)
        spec = {"value": old[name]}
        if d in ("lower_better", "both"):
            spec["max_ratio"] = factor
        if d in ("higher_better", "both"):
            spec["min_ratio"] = 1.0 / factor
        entry = check_metric(name, spec, new[name])
        entry["direction"] = d
        mx[name] = entry
        verdict["checked"] += 1
        if not entry["ok"]:
            verdict["regressions"].append(
                f"candidate: {name}: {entry.get('status')}")
    if verdict["regressions"]:
        verdict["verdict"] = "regression"
    _finish(verdict, out, quiet)
    return 0 if verdict["verdict"] == "pass" else 1


def _finish(verdict: dict, out: str | None, quiet: bool) -> None:
    if not quiet:
        for key, dv in verdict["docs"].items():
            for name, entry in dv.get("metrics", {}).items():
                mark = "ok " if entry["ok"] else "REG"
                val = entry.get("value")
                base = entry.get("baseline")
                print(f"[perf_diff] {mark} {key}:{name} = "
                      f"{val if val is not None else '-'}"
                      + (f" (baseline {base}"
                         + (f", ratio {entry['ratio']}"
                            if "ratio" in entry else "") + ")"
                         if base is not None else "")
                      + ("" if entry["ok"]
                         else f" -- {entry.get('status')}"))
    for msg in verdict["regressions"]:
        print(f"[perf_diff] REGRESSION {msg}", file=sys.stderr)
    print(f"[perf_diff] verdict: {verdict['verdict']} "
          f"({verdict['checked']} metric(s) checked, "
          f"{len(verdict['regressions'])} regression(s))")
    if out:
        from quorum_tpu.telemetry.registry import atomic_write
        atomic_write(out, json.dumps(verdict, indent=1) + "\n")


# -- baseline generation ----------------------------------------------------

# curated generator limits: what a committed baseline should bound,
# by extracted-name shape. Wall-clock entries get cliff-wide ratios
# (shared CI boxes are 2-4x noisy between runs); structural and
# ratio-like entries stay tight.
_GEN_RULES: list[tuple[str, dict]] = [
    # lever speedups: a probe that stops speeding up (or starts
    # losing parity runs) is exactly what the gate must catch
    ("bench.*.speedup*", {"min_ratio": 0.33}),
    # wall-clock probe times: generous cliff bounds
    ("bench.*_ms", {"max_ratio": 5.0}),
    ("timers.*.total_seconds", {"max_ratio": 5.0}),
    ("timers.*.stages.*.seconds", {"max_ratio": 8.0, "optional": True}),
    # devtrace totals: present and nonzero (the device did the work)
    ("counters.device_kernel_us_total", {"min": 1.0, "max_ratio": 8.0}),
    ("counters.device_step_us_total", {"min": 1.0, "max_ratio": 8.0}),
    # compile-sentinel ledger (ISSUE 15): compile counts are
    # DETERMINISTIC for a fixed workload, so the bounds stay tight —
    # a recompile regression is a wrong count, not noise; optional
    # because plain (sentinel-off) runs don't carry the export
    ("counters.compile_events",
     {"min": 1.0, "max_ratio": 1.5, "optional": True}),
    ("counters.compiles{site=*",
     {"min": 1.0, "max_ratio": 1.0, "optional": True}),
    # dispatch/wait split histograms: time-like
    ("histograms.*_us.mean", {"max_ratio": 8.0, "optional": True}),
]


def _gen_spec(name: str, value: float) -> dict | None:
    for pat, limits in _GEN_RULES:
        if fnmatch.fnmatch(name, pat):
            rounded = round(value, 6)
            if rounded == 0 and not any(
                    k in limits for k in ("min", "max")):
                # a ratio-bounded entry with a zero baseline would be
                # an always-failing (or never-failing) contract — a
                # metric this small has nothing to regress from
                return None
            return {"value": rounded, **limits}
    return None


def write_baseline(out: str, docs: dict[str, str]) -> int:
    baseline = {
        "schema": BASELINE_SCHEMA,
        "meta": {
            "note": "perf contract for ci/tier1.sh golden runs "
                    "(tools/perf_diff.py); tolerances are deliberately "
                    "cliff-wide on wall clock — shared CI boxes are "
                    "noisy — and tight on structure/speedups",
        },
        "docs": {},
    }
    for key, path in sorted(docs.items()):
        prof = extract_profile(path)
        metrics = {}
        for name in sorted(prof):
            spec = _gen_spec(name, prof[name])
            if spec is not None:
                metrics[name] = spec
        baseline["docs"][key] = {"metrics": metrics}
    from quorum_tpu.telemetry.registry import atomic_write
    atomic_write(out, json.dumps(baseline, indent=1) + "\n")
    n = sum(len(d["metrics"]) for d in baseline["docs"].values())
    print(f"[perf_diff] wrote baseline {out} "
          f"({n} metric(s) over {len(docs)} document(s)) — review "
          "before committing")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Regression verdicts over metrics/BENCH "
                    "documents: baseline gate (--baseline KEY=PATH "
                    "pairs) or two-document compare (OLD NEW)")
    p.add_argument("docs", nargs="+", metavar="KEY=PATH | FILE",
                   help="With --baseline/--write-baseline: KEY=PATH "
                        "pairs naming the baseline's documents. "
                        "Without: exactly two artifact paths "
                        "(OLD NEW)")
    p.add_argument("--baseline", metavar="path", default=None,
                   help="Baseline contract JSON "
                        "(quorum-tpu-perf-baseline/1)")
    p.add_argument("--write-baseline", metavar="path", default=None,
                   help="Generate a baseline contract from the "
                        "supplied documents instead of judging them")
    p.add_argument("--out", metavar="path", default=None,
                   help="Write the verdict document "
                        "(quorum-tpu-perf-diff/1) here")
    p.add_argument("--tolerance-pct", type=float, default=50.0,
                   help="Two-document mode: symmetric tolerance "
                        "(default 50)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Only print regressions and the verdict")
    args = p.parse_args(argv)

    if args.baseline and args.write_baseline:
        p.error("--baseline and --write-baseline are exclusive")
    if args.baseline or args.write_baseline:
        docs = {}
        for item in args.docs:
            key, sep, path = item.partition("=")
            if not sep or not key or not path:
                p.error(f"expected KEY=PATH, got {item!r}")
            docs[key] = path
        if args.write_baseline:
            return write_baseline(args.write_baseline, docs)
        return run_baseline(args.baseline, docs, args.out,
                            quiet=args.quiet)
    if len(args.docs) != 2:
        p.error("two-document mode takes exactly OLD NEW")
    return run_two_doc(args.docs[0], args.docs[1],
                       args.tolerance_pct, args.out, quiet=args.quiet)


if __name__ == "__main__":
    sys.exit(main())
