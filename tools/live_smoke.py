#!/usr/bin/env python
"""Golden LIVE-ingestion run for CI (ci/tier1.sh): start quorum-serve
with `--ingest` (no database — the service boots on an empty live
table), stream the committed golden reads through seq-stamped gzipped
`POST /ingest` chunks, and verify the acceptance properties of the
live tier (ISSUE 18):

  * epoch swaps happen ON the ingest path: `--epoch-reads 64` over 6
    chunks must seal and swap at least 2 epoch snapshots before the
    stream ends (plus the final forced `POST /epoch`),
  * end-state parity: once every read is ingested and the final epoch
    swapped, `POST /correct` answers byte-identical to
    tests/golden/expected.fa — the offline build+correct pipeline at
    the same cutoff (-p 4) and floor (1),
  * the warm (second) correction recompiles nothing,
  * a graceful drain commits the live-table checkpoint and writes the
    final metrics document with `meta.live_ingest`, so
    `metrics_check.py` requires the ingest/epoch counter surface.

Artifacts land in --out-dir (default: a temp dir):
  live_metrics.json — the final serve document (metrics_check gates
                      it; meta.live_ingest pulls in the ingest names)
  live_scrape.prom  — a /metrics scrape taken mid-run

Exit 0 = all checks passed. Run by ci/tier1.sh after serve_smoke;
usable by hand for a quick live-tier sanity check.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Golden live-ingestion run: streamed ingest, "
                    "epoch swaps, end-state parity, drain-with-"
                    "metrics (ci/tier1.sh gate)")
    p.add_argument("--out-dir", default=None,
                   help="Where live_metrics.json / live_scrape.prom "
                        "land (default: a temp dir)")
    p.add_argument("--rows", type=int, default=64,
                   help="Engine batch rows (default 64: fast CPU "
                        "compile; production uses 1024+)")
    p.add_argument("--chunk-reads", type=int, default=41,
                   help="Reads per /ingest chunk (default 41: 6 "
                        "chunks over the 242 golden reads)")
    p.add_argument("--epoch-reads", type=int, default=64,
                   help="Epoch boundary cadence (default 64: several "
                        "swaps happen DURING the stream)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="live_smoke_")
    os.makedirs(out_dir, exist_ok=True)
    live_dir = os.path.join(out_dir, "live")

    from quorum_tpu.cli import serve as serve_cli
    from quorum_tpu.io import fastq
    from quorum_tpu.serve.client import ServeClient

    reads = os.path.join(GOLDEN, "reads.fastq")
    expected_fa = os.path.join(GOLDEN, "expected.fa")
    metrics_path = os.path.join(out_dir, "live_metrics.json")
    scrape_path = os.path.join(out_dir, "live_scrape.prom")

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rc_box = {}

    def run_server():
        rc_box["rc"] = serve_cli.main(
            ["--port", str(port), "--max-batch", str(args.rows),
             "--max-wait-ms", "2", "-p", "4",
             "--ingest", "--live-dir", live_dir,
             "--ingest-mer-len", "13", "--ingest-bits", "7",
             "--ingest-size", "64k", "--ingest-qual-thresh", "38",
             "--epoch-reads", str(args.epoch_reads),
             "--metrics", metrics_path])

    print(f"[live_smoke] starting quorum-serve --ingest on :{port} "
          f"(epoch every {args.epoch_reads} reads)")
    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    client = ServeClient(port=port, timeout=900.0)
    deadline = time.perf_counter() + 60
    while True:
        try:
            client.healthz()
            break
        except OSError:
            if time.perf_counter() > deadline:
                print("[live_smoke] FAIL: server never came up",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)

    records = list(fastq.iter_records([reads]))
    n = max(1, args.chunk_reads)
    chunks = [records[i:i + n] for i in range(0, len(records), n)]
    print(f"[live_smoke] streaming {len(records)} reads as "
          f"{len(chunks)} gzipped chunks")
    for seq, chunk in enumerate(chunks):
        text = "".join(f"@{h}\n{s.decode()}\n+\n{q.decode()}\n"
                       for h, s, q in chunk)
        status, ack = client.ingest(text, seq=seq, gzip_body=True)
        if status != 200 or ack.get("cursor") != seq:
            print(f"[live_smoke] FAIL: ingest seq {seq} -> {status} "
                  f"{ack}", file=sys.stderr)
            return 1

    # seal the tail so the serving epoch holds EVERY ingested read
    status, doc = client.epoch()
    if status != 200 or not doc.get("ok"):
        print(f"[live_smoke] FAIL: forced epoch -> {status} {doc}",
              file=sys.stderr)
        return 1
    live = client.healthz().get("live", {})
    if live.get("reads") != len(records):
        print(f"[live_smoke] FAIL: ingested {live.get('reads')} reads,"
              f" want {len(records)}", file=sys.stderr)
        return 1
    # the forced epoch is one of these; at least 2 must have fired
    # from the --epoch-reads boundary DURING the stream
    if live.get("epoch", 0) < 3:
        print(f"[live_smoke] FAIL: only {live.get('epoch')} epoch "
              "swaps observed (want stream boundaries + the forced "
              "one)", file=sys.stderr)
        return 1
    print(f"[live_smoke] {live['epoch']} epoch swaps, cursor "
          f"{live['cursor']}, coverage {live['coverage']}")

    with open(reads) as f:
        body = f.read()
    with open(expected_fa) as f:
        want_fa = f.read()

    print("[live_smoke] cold correction against the final epoch")
    t0 = time.perf_counter()
    r1 = client.correct(body)
    cold_s = time.perf_counter() - t0
    if r1.status != 200 or r1.fa != want_fa:
        print(f"[live_smoke] FAIL: cold request status={r1.status} "
              f"parity={'ok' if r1.fa == want_fa else 'DRIFT'}",
              file=sys.stderr)
        return 1
    compiles1 = client.healthz()["engine_compiles"]

    print("[live_smoke] warm correction")
    t0 = time.perf_counter()
    r2 = client.correct(body)
    warm_s = time.perf_counter() - t0
    compiles2 = client.healthz()["engine_compiles"]
    if r2.status != 200 or r2.fa != want_fa:
        print("[live_smoke] FAIL: warm request parity",
              file=sys.stderr)
        return 1
    if compiles2 != compiles1:
        print(f"[live_smoke] FAIL: warm request recompiled "
              f"({compiles1} -> {compiles2})", file=sys.stderr)
        return 1

    with open(scrape_path, "w") as f:
        f.write(client.metrics_text())
    print(f"[live_smoke] scraped /metrics -> {scrape_path}")

    print("[live_smoke] draining via /quiesce")
    client.quiesce()
    t.join(timeout=120)
    if t.is_alive() or rc_box.get("rc") != 0:
        print(f"[live_smoke] FAIL: drain (alive={t.is_alive()} "
              f"rc={rc_box.get('rc')})", file=sys.stderr)
        return 1
    if not os.path.exists(metrics_path):
        print("[live_smoke] FAIL: no final metrics document",
              file=sys.stderr)
        return 1
    if not os.path.exists(os.path.join(live_dir, "live.ckpt")):
        print("[live_smoke] FAIL: drain committed no live-table "
              "checkpoint", file=sys.stderr)
        return 1
    print(f"[live_smoke] OK: {len(chunks)} chunks, {live['epoch']} "
          f"epochs, parity x2, cold {cold_s:.1f}s, warm {warm_s:.2f}s,"
          f" final metrics -> {metrics_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
