#!/usr/bin/env python
"""Golden data-integrity gate for CI (ci/tier1.sh, ISSUE 8):
quorum-fsck clean on real golden-pipeline artifacts, plus one
injected-corruption run proving detection end to end, plus the
journal --repair path.

1. Build the v5 mer database from the committed golden reads;
   `quorum-fsck` must report it clean (exit 0).
2. Run stage 1 again with checkpointing and a fault plan that
   hard-kills it mid-run — the surviving snapshot must fsck clean.
3. Run stage 2 with journaling and a hard-kill at batch 2 — the
   journal + partials must fsck clean EXCEPT the expected torn tail,
   which `--repair` truncates (after which fsck is clean), and the
   repaired run must still `--resume` to the byte-identical golden
   output.
4. Corruption: build a database under a seeded `corrupt` fault plan
   (site db.write) — `quorum-fsck` must exit non-zero naming the
   damaged section, and `quorum_error_correct_reads` must refuse the
   load with rc 3 while counting `integrity_errors_total`.
5. Sharded manifest (ISSUE 9): build with `--db-layout=sharded`,
   fsck clean; corrupt one SHARD file — fsck must pinpoint
   shard+section (`shard-K/...`), and the loader must refuse the
   manifest with rc 3 + `integrity_errors_total` >= 1.

Exit 0 = all checks passed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")

KILL_CODE = 41
BATCH_SIZE = 64  # 242 golden reads -> 4 batches


def fsck(args: list[str]) -> int:
    from quorum_tpu.cli.fsck import main as fsck_main
    return fsck_main(args)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Golden quorum-fsck gate: clean pipeline "
                    "artifacts, injected corruption detection, and "
                    "the journal --repair path (ci/tier1.sh)")
    p.add_argument("--out-dir", default=None)
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="fsck_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import error_correct_reads as ec_cli

    reads = os.path.join(GOLDEN, "reads.fastq")
    expected_fa = os.path.join(GOLDEN, "expected.fa")
    db = os.path.join(out_dir, "db.jf")
    ckpt = os.path.join(out_dir, "ckpt")
    prefix = os.path.join(out_dir, "corrected")
    metrics_path = os.path.join(out_dir, "fsck_metrics.json")

    # -- 1. clean database ----------------------------------------------
    print("[fsck_smoke] building golden v5 database")
    if cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                     "-o", db, reads]) != 0:
        print("[fsck_smoke] FAIL: database build", file=sys.stderr)
        return 1
    if fsck([db]) != 0:
        print("[fsck_smoke] FAIL: clean v5 database flagged",
              file=sys.stderr)
        return 1

    # -- 2. killed stage-1 run leaves an fsck-clean snapshot ------------
    plan = json.dumps([{"site": "stage1.insert", "batch": 3,
                        "action": "exit", "code": KILL_CODE}])
    env = dict(os.environ, QUORUM_FAULT_PLAN=plan)
    res = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.create_database",
         "-s", "64k", "-m", "13", "-b", "7", "-q", "38",
         "-o", os.path.join(out_dir, "db_killed.jf"),
         "--batch-size", str(BATCH_SIZE),
         "--checkpoint-dir", ckpt, "--checkpoint-every", "1", reads],
        cwd=REPO, env=env)
    if res.returncode != KILL_CODE:
        print(f"[fsck_smoke] FAIL: stage-1 kill exited "
              f"{res.returncode}, want {KILL_CODE}", file=sys.stderr)
        return 1
    if fsck([ckpt]) != 0:
        print("[fsck_smoke] FAIL: clean stage-1 checkpoint flagged",
              file=sys.stderr)
        return 1

    # -- 3. killed stage-2 run: journal clean, tail repaired ------------
    plan = json.dumps([{"site": "stage2.correct", "batch": 2,
                        "action": "exit", "code": KILL_CODE}])
    ec_args = ["-p", "4", "--batch-size", str(BATCH_SIZE),
               "--checkpoint-every", "1", "-o", prefix, db, reads]
    env = dict(os.environ, QUORUM_FAULT_PLAN=plan)
    res = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.error_correct_reads"]
        + ec_args, cwd=REPO, env=env)
    if res.returncode != KILL_CODE:
        print(f"[fsck_smoke] FAIL: stage-2 kill exited "
              f"{res.returncode}, want {KILL_CODE}", file=sys.stderr)
        return 1
    journal = prefix + ".resume.json"
    # append a torn tail past the commit point, as a crash mid-write
    # would leave — fsck must flag it, --repair must truncate it
    with open(prefix + ".fa.partial", "ab") as f:
        f.write(b">torn-tail-record\nNNNN")
    if fsck([journal]) == 0:
        print("[fsck_smoke] FAIL: torn tail not flagged",
              file=sys.stderr)
        return 1
    if fsck(["--repair", journal]) != 0:
        print("[fsck_smoke] FAIL: --repair did not clean the torn "
              "tail", file=sys.stderr)
        return 1
    if fsck([journal]) != 0:
        print("[fsck_smoke] FAIL: journal not clean after --repair",
              file=sys.stderr)
        return 1
    # the repaired journal must still resume to the golden bytes
    if ec_cli.main(ec_args + ["--resume", "--fault-plan", ""]) != 0:
        print("[fsck_smoke] FAIL: resume after repair", file=sys.stderr)
        return 1
    if open(prefix + ".fa", "rb").read() != open(expected_fa,
                                                 "rb").read():
        print("[fsck_smoke] FAIL: repaired resume output differs "
              "from golden", file=sys.stderr)
        return 1

    # -- 4. injected corruption: fsck + loader both detect --------------
    bad_db = os.path.join(out_dir, "db_corrupt.jf")
    # seeded corrupt fault at the committed database; offset 2000 is
    # deep in the entry payload for the golden geometry (header ~1 kB,
    # counts 512 B), so the damage lands in a digested section
    plan = json.dumps([{"site": "db.write", "action": "corrupt",
                        "offset": 2000, "bytes": 2, "seed": 7}])
    env = dict(os.environ, QUORUM_FAULT_PLAN=plan)
    res = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.create_database",
         "-s", "64k", "-m", "13", "-b", "7", "-q", "38",
         "-o", bad_db, reads], cwd=REPO, env=env)
    if res.returncode != 0:
        print("[fsck_smoke] FAIL: corrupt-plan build rc",
              res.returncode, file=sys.stderr)
        return 1
    if fsck([bad_db]) == 0:
        print("[fsck_smoke] FAIL: corrupted database passed fsck",
              file=sys.stderr)
        return 1
    print("[fsck_smoke] corrupted database flagged by fsck")
    rc = ec_cli.main(["-p", "4", "--batch-size", str(BATCH_SIZE),
                      "-o", os.path.join(out_dir, "bad_out"),
                      "--metrics", metrics_path, "--fault-plan", "",
                      bad_db, reads])
    if rc != 3:
        print(f"[fsck_smoke] FAIL: corrupted-db load rc {rc}, want 3",
              file=sys.stderr)
        return 1
    doc = json.load(open(metrics_path))
    errs = doc["counters"].get("integrity_errors_total", 0)
    if errs < 1:
        print(f"[fsck_smoke] FAIL: integrity_errors_total={errs}, "
              "want >= 1", file=sys.stderr)
        return 1
    # -- 5. sharded manifest: fsck pinpoints shard+section --------------
    import contextlib
    import io as _io

    from quorum_tpu.io import db_format

    sharded = os.path.join(out_dir, "db_sharded.jf")
    print("[fsck_smoke] building sharded-layout database")
    if cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                     "--db-layout", "sharded", "-o", sharded,
                     reads]) != 0:
        print("[fsck_smoke] FAIL: sharded build", file=sys.stderr)
        return 1
    if db_format.db_payload_bytes(sharded) != db_format.db_payload_bytes(db):
        print("[fsck_smoke] FAIL: sharded payload differs from the "
              "single-file layout", file=sys.stderr)
        return 1
    if fsck([sharded]) != 0:
        print("[fsck_smoke] FAIL: clean sharded manifest flagged",
              file=sys.stderr)
        return 1
    n_shards = int(db_format.read_header(sharded)["n_shards"])
    victim = db_format.shard_file_name(sharded, n_shards - 1, n_shards)
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    err_buf = _io.StringIO()
    with contextlib.redirect_stderr(err_buf):
        rc = fsck([sharded])
    if rc == 0:
        print("[fsck_smoke] FAIL: corrupted shard passed fsck",
              file=sys.stderr)
        return 1
    if f"shard-{n_shards - 1}" not in err_buf.getvalue():
        print("[fsck_smoke] FAIL: fsck did not pinpoint the damaged "
              f"shard:\n{err_buf.getvalue()}", file=sys.stderr)
        return 1
    print("[fsck_smoke] corrupted shard pinpointed by fsck "
          f"(shard-{n_shards - 1})")
    sh_metrics = os.path.join(out_dir, "fsck_sharded_metrics.json")
    rc = ec_cli.main(["-p", "4", "--batch-size", str(BATCH_SIZE),
                      "-o", os.path.join(out_dir, "bad_sharded_out"),
                      "--metrics", sh_metrics, "--fault-plan", "",
                      sharded, reads])
    if rc != 3:
        print(f"[fsck_smoke] FAIL: corrupted-shard load rc {rc}, "
              "want 3", file=sys.stderr)
        return 1
    sh_doc = json.load(open(sh_metrics))
    sh_errs = sh_doc["counters"].get("integrity_errors_total", 0)
    if sh_errs < 1:
        print(f"[fsck_smoke] FAIL: sharded integrity_errors_total="
              f"{sh_errs}, want >= 1", file=sys.stderr)
        return 1

    print(f"[fsck_smoke] OK: clean artifacts pass, corruption "
          f"refused (rc 3, integrity_errors_total={errs}), torn "
          f"tail repaired, sharded manifest corruption pinpointed + "
          f"refused (integrity_errors_total={sh_errs}); metrics -> "
          f"{metrics_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
