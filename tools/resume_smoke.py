#!/usr/bin/env python
"""Golden kill-resume run for CI (ci/tier1.sh): the ISSUE 4
acceptance property, end to end, with a REAL process kill.

1. Build the mer database from the committed golden reads.
2. Run `quorum_error_correct_reads` as a SUBPROCESS with a fault plan
   (via the QUORUM_FAULT_PLAN env var) that hard-exits the process
   (`os._exit`) at stage2.correct batch 2, journaling every batch —
   the run dies with batches 0-1 committed and partial outputs on
   disk.
3. Re-run in-process with `--resume`: the journal's batches are
   skipped, the torn tail truncated, and the output finalized
   atomically.
4. Assert the resumed `.fa` is BYTE-IDENTICAL to
   tests/golden/expected.fa (and `.log` to expected.log), the journal
   and partials are gone, and the resume metrics document carries the
   checkpoint/resume counters (`metrics_check.py` gates it after).

Artifacts land in --out-dir:
  resume_metrics.json — the resumed run's final metrics document
                        (gated by tools/metrics_check.py, which
                        requires the checkpoint/resume counter names)

Exit 0 = all checks passed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")

KILL_CODE = 41
BATCH_SIZE = 64  # 242 golden reads -> 4 batches; the kill lands at 2


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Golden kill-resume run: hard-kill stage 2 mid-run "
                    "via fault plan, resume, byte-diff (ci/tier1.sh "
                    "gate)")
    p.add_argument("--out-dir", default=None,
                   help="Where the work files and resume_metrics.json "
                        "land (default: a temp dir)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="resume_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import error_correct_reads as ec_cli

    reads = os.path.join(GOLDEN, "reads.fastq")
    expected_fa = os.path.join(GOLDEN, "expected.fa")
    expected_log = os.path.join(GOLDEN, "expected.log")
    db = os.path.join(out_dir, "db.jf")
    prefix = os.path.join(out_dir, "corrected")
    metrics_path = os.path.join(out_dir, "resume_metrics.json")

    print(f"[resume_smoke] building golden database -> {db}")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, reads])
    if rc != 0:
        print("[resume_smoke] FAIL: database build", file=sys.stderr)
        return 1

    # -- the kill: a subprocess dies by os._exit mid-stage-2 ----------
    plan = json.dumps([{"site": "stage2.correct", "batch": 2,
                        "action": "exit", "code": KILL_CODE}])
    ec_args = ["-p", "4", "--batch-size", str(BATCH_SIZE),
               "--checkpoint-every", "1", "-o", prefix, db, reads]
    env = dict(os.environ, QUORUM_FAULT_PLAN=plan)
    print(f"[resume_smoke] killed run (fault plan: {plan})")
    res = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.error_correct_reads"]
        + ec_args, cwd=REPO, env=env)
    if res.returncode != KILL_CODE:
        print(f"[resume_smoke] FAIL: killed run exited "
              f"{res.returncode}, want {KILL_CODE}", file=sys.stderr)
        return 1
    if os.path.exists(prefix + ".fa"):
        print("[resume_smoke] FAIL: final .fa exists after the kill "
              "(finalize must be atomic, not incremental)",
              file=sys.stderr)
        return 1
    if not (os.path.exists(prefix + ".fa.partial")
            and os.path.exists(prefix + ".resume.json")):
        print("[resume_smoke] FAIL: no partial/journal after the kill",
              file=sys.stderr)
        return 1
    journal = json.load(open(prefix + ".resume.json"))
    print(f"[resume_smoke] killed at batch 2; journal committed "
          f"{journal['batches']} batches / {journal['reads']} reads")
    if journal["batches"] != 2:
        print(f"[resume_smoke] FAIL: journal batches "
              f"{journal['batches']}, want 2", file=sys.stderr)
        return 1

    # -- the resume: skips journaled reads, finalizes atomically ------
    print("[resume_smoke] resuming with --resume")
    rc = ec_cli.main(ec_args + ["--resume", "--metrics", metrics_path,
                                "--fault-plan", ""])
    if rc != 0:
        print("[resume_smoke] FAIL: resume run rc", rc, file=sys.stderr)
        return 1

    # -- byte identity vs the committed golden output -----------------
    for got, want in ((prefix + ".fa", expected_fa),
                      (prefix + ".log", expected_log)):
        if open(got, "rb").read() != open(want, "rb").read():
            print(f"[resume_smoke] FAIL: {got} differs from {want} "
                  "(kill -> resume must be byte-identical)",
                  file=sys.stderr)
            return 1
    for leftover in (prefix + ".fa.partial", prefix + ".log.partial",
                     prefix + ".resume.json"):
        if os.path.exists(leftover):
            print(f"[resume_smoke] FAIL: {leftover} survived finalize",
                  file=sys.stderr)
            return 1

    doc = json.load(open(metrics_path))
    skipped = doc["counters"].get("resume_skipped_reads", 0)
    if not doc["meta"].get("resumed") or skipped != 2 * BATCH_SIZE:
        print(f"[resume_smoke] FAIL: resume telemetry (resumed="
              f"{doc['meta'].get('resumed')}, skipped={skipped})",
              file=sys.stderr)
        return 1
    print(f"[resume_smoke] OK: kill at batch 2 -> resume skipped "
          f"{skipped} reads -> byte-identical output; metrics -> "
          f"{metrics_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
