#!/usr/bin/env python
"""quorum-fsck from a checkout (no install needed): offline integrity
verifier for databases, checkpoint directories, and stage-2 resume
journals. The implementation lives in quorum_tpu/cli/fsck.py (the
`quorum-fsck` console script); this shim mirrors the other tools/
entry points for CI and scripted use.

Usage: python tools/fsck.py [--verify full|sample] [--repair] PATH...
Exit:  0 clean (or repaired), 1 damage, 2 unrecognized artifact.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from quorum_tpu.cli.fsck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
