#!/usr/bin/env python
"""Offline span/metrics join: where did the time go, without
TensorBoard (ISSUE 2 satellite).

Usage: python tools/trace_summary.py FILE [FILE ...]

Each FILE is dispatched on content — a `--trace-spans` JSONL stream
(telemetry/spans.py), a `--metrics` JSON document, or a multi-host
document carrying per-host shards under `hosts` (the quorum driver's
`.hosts.json` aggregate, or the fleet document
`tools/push_receiver.py` assembles from pushes) — and prints:

  * the per-span aggregate (calls, total, mean, share of wall time),
    with parent/child nesting preserved in the ordering;
  * each metrics document's StageTimer table (the same facts
    `-v` prints through vlog, recovered from the artifact);
  * a host / device-dispatch / device-wait attribution summary that
    joins the split timer stages and `*_dispatch_us`/`*_wait_us`
    histograms — the per-batch device-time breakdown the trace
    records, folded to one table per run;
  * for hosts/fleet documents: the PER-HOST attribution table
    (wall, host / device-dispatch / device-wait seconds per host,
    slowest host highlighted — the job runs at the slowest host's
    pace, ISSUE 11), then the aggregate's own tables;
  * for a multi-pass stage-1 build's events JSONL (ISSUE 14): the
    per-pass table from its `partition_pass` events (sketch pass +
    each partition pass: batches, distinct mers, seconds, share).

`--device PROFILE_DIR` (ISSUE 10) additionally parses the
jax.profiler trace the run wrote into that directory
(telemetry/devtrace.py: Chrome trace primary, xplane.pb fallback) and
prints the DEVICE-truth attribution table: per step-annotation stage,
host dispatch time (from the metrics documents' `*_dispatch_us`
histograms — host-observed), device-execute time (`device_kernel_us`
summed from the profiler's own kernel events — device truth), and
device idle inside the step windows (the device waiting on the
host), plus the top-K kernels by device time. This is the table that
says whether the sweep, the extension loop, or the exchange is on
the roofline — the host dispatch/wait split alone cannot.

`--flight` (ISSUE 16) renders flight-recorder crash dumps
(`quorum-tpu-flight/1`, telemetry/flight.py): the trigger line (what
fired, at which site, on which thread), the ring as a timeline —
optionally only the last `--last-s SECONDS` before the trigger — with the
triggering thread's rows marked and its Python stack printed in
full. Dumps are auto-detected by schema even without the flag.

`--quality` (ISSUE 17) renders each metrics document's correction-
quality scorecard instead of the timer tables: headline counts, the
data-plane rates, the skip-reason breakdown, and the bucketed
distributions (substitution-position spectrum per read cycle,
substitutions per read, truncation cycles) as ascii bars.

This is the quick look a BENCH run's time budget needs; for the
timeline view load the `.trace.json` twin in Perfetto or
`chrome://tracing`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_spans(path: str) -> list[dict]:
    spans = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if isinstance(obj, dict) and "span" in obj:
                spans.append(obj)
    return spans


def span_table(spans: list[dict]) -> tuple[list[tuple], float]:
    """Aggregate by (name, depth): [(name, depth, calls, total_s,
    mean_ms, pct_wall)], wall = latest end - earliest start."""
    if not spans:
        return [], 0.0
    by_id = {s["id"]: s for s in spans}

    def depth(s):
        d = 0
        seen = set()
        while s.get("parent") is not None and s["id"] not in seen:
            seen.add(s["id"])
            parent = by_id.get(s["parent"])
            if parent is None:
                break
            d += 1
            s = parent
        return d

    wall = (max(s["ts"] + s["dur"] for s in spans)
            - min(s["ts"] for s in spans))
    agg: dict[tuple, list] = {}
    order: list[tuple] = []
    for s in sorted(spans, key=lambda x: x["ts"]):
        key = (s["span"], depth(s))
        if key not in agg:
            agg[key] = [0, 0.0]
            order.append(key)
        agg[key][0] += 1
        agg[key][1] += s["dur"]
    rows = []
    for name, d in order:
        calls, total = agg[(name, d)]
        rows.append((name, d, calls, total,
                     total / calls * 1000.0,
                     100.0 * total / wall if wall > 0 else 0.0))
    return rows, wall


def _bucket(name: str) -> str:
    if name.endswith(("_dispatch", "_dispatch_ms", "_dispatch_us")):
        return "device dispatch"
    if name.endswith(("_wait", "_wait_ms", "_wait_us")):
        return "device wait"
    return "host"


def attribution(doc: dict) -> dict[str, float]:
    """host/device-dispatch/device-wait seconds from a metrics
    document's split timer stages."""
    out = {"host": 0.0, "device dispatch": 0.0, "device wait": 0.0}
    for t in doc.get("timers", {}).values():
        for name, st in t.get("stages", {}).items():
            out[_bucket(name)] += st.get("seconds", 0.0)
    return out


# step-annotation name (the StepTraceAnnotation the batch loops emit)
# -> the dispatch/wait histogram prefix the same loop records, so the
# --device table can put host-observed dispatch next to device truth
_STEP_DISPATCH_PREFIX = {
    "stage1_insert": "insert",
    "stage2_device": "device",
    "shard_build_step": "shard_step",
    "serve_device": "serve",
}


def _hist_sum_us(docs: list[dict], name: str) -> float:
    """Total µs recorded under histogram `name` across documents
    (`*_us` histograms observe integer microseconds)."""
    return sum(float(d.get("histograms", {}).get(name, {})
                     .get("sum", 0)) for d in docs)


def device_attribution(profile_dir: str, docs: list[dict]) -> int:
    """The host-dispatch / device-execute / device-idle table from
    the profiler's OWN trace (telemetry/devtrace.py), joined with the
    metrics documents' host-observed dispatch histograms. Returns 0,
    or 1 when the directory holds no readable trace."""
    from quorum_tpu.telemetry import devtrace

    s = devtrace.summarize_profile(profile_dir)
    print(f"\n== device attribution: {profile_dir} "
          f"(source {s.source}, {len(s.files)} file(s), "
          f"{len(s.steps)} step window(s)) ==")
    if s.source == "none":
        print("no readable profiler trace found", file=sys.stderr)
        return 1
    kern = s.stage_kernel_us()
    idle = s.stage_idle_us()
    windows: dict[str, int] = {}
    for w in s.steps:
        windows[w.name] = windows.get(w.name, 0) + 1
    print(f"{'stage':<18} {'steps':>6} {'host_dispatch_ms':>17} "
          f"{'device_execute_ms':>18} {'device_idle_ms':>15}")
    for name in sorted(windows):
        prefix = _STEP_DISPATCH_PREFIX.get(name)
        disp_us = (_hist_sum_us(docs, f"{prefix}_dispatch_us")
                   if prefix else 0.0)
        print(f"{name:<18} {windows[name]:>6} "
              f"{disp_us / 1e3:>17.3f} "
              f"{kern.get(name, 0.0) / 1e3:>18.3f} "
              f"{idle.get(name, 0.0) / 1e3:>15.3f}")
    print(f"device_kernel_us total: {s.total_kernel_us:.1f} "
          f"(unattributed {s.unattributed_kernel_us:.1f}); "
          f"step wall {s.total_step_us:.1f} us, "
          f"idle {s.total_idle_us:.1f} us")
    print("top kernels by device time:")
    for name, us in s.top_kernels():
        print(f"  {us / 1e3:>10.3f} ms  {name}")
    return 0


def _host_wall(doc: dict) -> float:
    """One host's wall proxy: the longest StageTimer total (the
    aggregate merge rule — job total = slowest host — uses the same
    quantity), falling back to summed attribution when a shard
    carries no timers."""
    totals = [t.get("total_seconds", 0.0)
              for t in doc.get("timers", {}).values()]
    return max(totals) if totals else sum(attribution(doc).values())


def fleet_table(path: str, doc: dict) -> None:
    """The per-host attribution table of a multi-host document (the
    driver's `.hosts.json` aggregate or a push-receiver fleet doc):
    who is slow, and where their time goes. The slowest host is
    highlighted because it IS the job's wall clock (counters sum,
    but the barrier waits for the straggler)."""
    hosts = doc.get("hosts", {})
    kind = "fleet" if doc.get("meta", {}).get("fleet") else "hosts"
    print(f"\n== {kind} document: {path} ({len(hosts)} host(s)) ==")
    if not hosts:
        return
    walls = {h: _host_wall(d) for h, d in hosts.items()}
    slowest = max(walls, key=walls.get) if walls else None
    print(f"{'host':<20} {'wall_s':>9} {'host_s':>9} "
          f"{'dispatch_s':>11} {'wait_s':>9} {'status':>8}")
    for h in sorted(hosts):
        d = hosts[h]
        att = attribution(d)
        status = str(d.get("meta", {}).get("status", "-"))
        mark = "  <-- slowest" if h == slowest and len(hosts) > 1 \
            else ""
        print(f"{h:<20} {walls[h]:>9.3f} {att['host']:>9.3f} "
              f"{att['device dispatch']:>11.3f} "
              f"{att['device wait']:>9.3f} {status:>8}{mark}")


def render_metrics_doc(mpath: str, doc: dict) -> None:
    for tname, t in doc.get("timers", {}).items():
        total = t.get("total_seconds", 0.0)
        print(f"\n== timers: {mpath} [{tname}] "
              f"(total {total:.3f} s) ==")
        print(f"{'stage':<20} {'calls':>6} {'seconds':>9} "
              f"{'%total':>7}  class")
        for sname, st in t.get("stages", {}).items():
            s = st.get("seconds", 0.0)
            pct = 100.0 * s / total if total > 0 else 0.0
            print(f"{sname:<20} {st.get('calls', 0):>6} "
                  f"{s:>9.3f} {pct:>7.1f}  {_bucket(sname)}")
    att = attribution(doc)
    total_att = sum(att.values())
    print(f"\n== attribution: {mpath} ==")
    for k in ("host", "device dispatch", "device wait"):
        pct = 100.0 * att[k] / total_att if total_att > 0 else 0.0
        print(f"{k:<18} {att[k]:>9.3f} s {pct:>6.1f}%")
    for hname, h in sorted(doc.get("histograms", {}).items()):
        if not hname.endswith(("_dispatch_ms", "_wait_ms",
                               "_dispatch_us", "_wait_us")):
            continue
        div = 1e3 if hname.endswith("_us") else 1.0
        n = h.get("count", 0)
        mean = h.get("sum", 0) / div / n if n else 0.0
        print(f"  {hname}: n={n} mean={mean:.2f} ms "
              f"sum={h.get('sum', 0) / div / 1000.0:.3f} s")


def load_events(path: str) -> list[dict]:
    """Event lines of a `--metrics-interval` JSONL stream ({"event":
    kind, "t": elapsed_s, ...})."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "event" in obj:
                events.append(obj)
    return events


def partition_table(path: str, events: list[dict]) -> None:
    """Per-pass time attribution of a multi-pass stage-1 build
    (ISSUE 14): one row per `partition_pass` event (the sketch pass
    and each partition pass), with the share of the total pass time —
    the table that says whether a partitioned build's wall clock is
    input-bound (flat passes) or skew-bound (one hot partition)."""
    passes = [e for e in events if e.get("event") == "partition_pass"]
    total = sum(float(e.get("seconds", 0.0)) for e in passes)
    print(f"\n== partition passes: {path} ({len(passes)} pass(es), "
          f"{total:.3f} s) ==")
    print(f"{'pass':<10} {'batches':>8} {'distinct':>10} "
          f"{'seconds':>9} {'%passes':>8}")
    for e in passes:
        part = str(e.get("partition", "?"))
        secs = float(e.get("seconds", 0.0))
        pct = 100.0 * secs / total if total > 0 else 0.0
        dist = e.get("distinct")
        print(f"{part:<10} {e.get('batches', 0):>8} "
              f"{dist if dist is not None else '-':>10} "
              f"{secs:>9.3f} {pct:>8.1f}")


def _qbar(n: int, peak: int, width: int = 40) -> str:
    if peak <= 0 or n <= 0:
        return ""
    return "#" * max(1, int(round(width * n / peak)))


def _quality_count_map(q: dict, key: str) -> list[tuple[int, int]]:
    """A quality count map as (numeric key, count) rows, ascending;
    the 'overflow' spillover key (Histogram.MAX_KEYS) sorts last."""
    rows = []
    for k, v in q.get(key, {}).items():
        try:
            rows.append((int(k), int(v)))
        except (TypeError, ValueError):
            rows.append((1 << 30, int(v)))
    rows.sort()
    return rows


def render_quality(mpath: str, doc: dict) -> int:
    """The correction-quality scorecard of one metrics document
    (ISSUE 17): headline counts, the data-plane rates, the skip-reason
    breakdown, and the bucketed distributions (substitution-position
    spectrum per read cycle, substitutions per read, truncation
    cycles) as ascii bars. Returns 1 when the document carries no
    `quality` section."""
    q = doc.get("quality")
    if not isinstance(q, dict):
        print(f"{mpath}: no quality section (produced by --metrics "
              "runs of the error-correct/serve data plane; "
              "tools/quality_diff.py can recompute one)",
              file=sys.stderr)
        return 1
    print(f"\n== quality: {mpath} (schema {q.get('schema')}) ==")
    print(f"reads {q.get('reads', 0)}  "
          f"corrected {q.get('corrected', 0)}  "
          f"skipped {q.get('skipped', 0)}  "
          f"subs {q.get('substitutions', 0)}  "
          f"3'trunc {q.get('truncations_3p', 0)}  "
          f"5'trunc {q.get('truncations_5p', 0)}")
    rates = q.get("rates", {})
    if rates:
        print("rates:")
        for k in sorted(rates):
            print(f"  {k:<22} {float(rates[k]):>10.6f}")
    cov = q.get("coverage")
    if isinstance(cov, dict):
        print(f"coverage model: predicted_mean "
              f"{cov.get('predicted_mean')}  predicted_anchor_rate "
              f"{cov.get('predicted_anchor_rate')}")
    reasons = q.get("skip_reasons", {})
    if reasons:
        total = sum(int(v) for v in reasons.values())
        print("skip reasons:")
        for k in sorted(reasons):
            n = int(reasons[k])
            pct = 100.0 * n / total if total > 0 else 0.0
            print(f"  {k:<16} {n:>8} {pct:>6.1f}%")
    per_bucket = int(q.get("spectrum_cycles_per_bucket", 1) or 1)
    for key, label, scale in (
            ("sub_pos_spectrum", "cycle", per_bucket),
            ("trunc_cycle_3p", "cycle", per_bucket),
            ("trunc_cycle_5p", "cycle", per_bucket),
            ("substitutions_per_read", "subs/read", 1)):
        rows = _quality_count_map(q, key)
        if not rows:
            continue
        peak = max(n for _, n in rows)
        print(f"{key} ({label} per row"
              + (f", {scale} cycles/bucket" if scale > 1 else "")
              + "):")
        for b, n in rows:
            head = "overflow" if b >= (1 << 30) else str(b * scale)
            print(f"  {head:>9} {n:>8} {_qbar(n, peak)}")
    return 0


FLIGHT_SCHEMA = "quorum-tpu-flight/1"


def render_flight_dump(path: str, doc: dict,
                       last_s: float | None = None) -> None:
    """The postmortem view of a flight-recorder dump (ISSUE 16): the
    trigger line first (what fired, where, on which thread), then the
    ring as a timeline — optionally only the last `last_s` seconds
    before the trigger — with the triggering thread's rows marked, and
    finally that thread's Python stack (plus one line per other
    thread). This is the `quorum-tpu-flight/1` twin of the span
    tables: what the process was doing when it died or wedged."""
    trig = doc.get("trigger", {})
    ring = [e for e in doc.get("ring", []) if isinstance(e, dict)]
    trig_tid = trig.get("tid")
    t_end = max([float(e.get("t", 0.0)) for e in ring]
                + [float(trig.get("t", 0.0))] or [0.0])
    shown = ring
    if last_s is not None and last_s > 0:
        shown = [e for e in ring
                 if float(e.get("t", 0.0)) >= t_end - last_s]
    print(f"== flight dump: {path} ({len(ring)} ring entries, "
          f"{doc.get('dropped', 0)} dropped, "
          f"{len(doc.get('threads', []))} thread(s)) ==")
    site = f" site={trig.get('site')}" if trig.get("site") else ""
    print(f"trigger: {trig.get('kind', '?')}{site} on thread "
          f"{trig.get('thread', '?')!r} (tid {trig_tid}) "
          f"at t={float(trig.get('t', 0.0)):.3f}s")
    if trig.get("detail"):
        print(f"  detail: {trig['detail']}")
    if trig.get("exception"):
        print(f"  exception: {trig['exception']}")
    window = (f"last {last_s:g} s"
              if last_s is not None and last_s > 0 else "full ring")
    print(f"\ntimeline ({window}, {len(shown)} entries; "
          "* = triggering thread):")
    print(f"{'t':>10} {'':1} {'tid':>8} {'kind':<10} {'name':<26} "
          "fields")
    for e in shown:
        mark = "*" if trig_tid is not None \
            and e.get("tid") == trig_tid else " "
        extras = {k: v for k, v in e.items()
                  if k not in ("t", "kind", "name", "tid")}
        fields = " ".join(f"{k}={v}" for k, v in extras.items())
        print(f"{float(e.get('t', 0.0)):>10.3f} {mark} "
              f"{e.get('tid', '?'):>8} {str(e.get('kind', '?')):<10} "
              f"{str(e.get('name', '?')):<26} {fields}")
    threads = [t for t in doc.get("threads", [])
               if isinstance(t, dict)]
    culprit = next((t for t in threads if t.get("tid") == trig_tid),
                   None)
    if culprit is not None:
        print(f"\ntriggering thread {culprit.get('name', '?')!r} "
              f"(tid {trig_tid}) stack:")
        for frame in culprit.get("stack", []):
            for ln in frame.splitlines():
                print(f"  {ln}")
    others = [t for t in threads if t is not culprit]
    if others:
        print(f"\nother threads ({len(others)}):")
        for t in others:
            print(f"  {t.get('name', '?')!r} (tid {t.get('tid')}, "
                  f"{len(t.get('stack', []))} frame(s))")


def render_spans_file(path: str) -> None:
    spans = load_spans(path)
    rows, wall = span_table(spans)
    print(f"== spans: {path} ({len(spans)} spans, "
          f"wall {wall:.3f} s) ==")
    print(f"{'span':<28} {'calls':>6} {'total_s':>9} {'mean_ms':>9} "
          f"{'%wall':>6}")
    for name, d, calls, total, mean_ms, pct in rows:
        label = "  " * d + name
        print(f"{label:<28} {calls:>6} {total:>9.3f} {mean_ms:>9.2f} "
              f"{pct:>6.1f}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Summarize span JSONL / metrics JSON / multi-host "
                    "fleet documents into per-stage (and per-host) "
                    "host/device/wait tables")
    p.add_argument("files", nargs="+", metavar="FILE",
                   help="Span JSONL (--trace-spans), metrics JSON "
                        "(--metrics), or hosts/fleet documents "
                        "(.hosts.json, push_receiver --out) — "
                        "dispatched on content")
    p.add_argument("--flight", action="store_true",
                   help="Render FILEs as flight-recorder dumps "
                        "(quorum-tpu-flight/1): the trigger, the ring "
                        "timeline, the triggering thread highlighted "
                        "with its stack. Dumps are also auto-detected "
                        "by schema without this flag; the flag "
                        "additionally REQUIRES each FILE to be a dump")
    p.add_argument("--last-s", type=float, default=None,
                   metavar="SECONDS",
                   help="With --flight: only the last SECONDS of the "
                        "ring timeline before the trigger (default: "
                        "the full ring)")
    p.add_argument("--quality", action="store_true",
                   help="Render each metrics document's correction-"
                        "quality scorecard (counts, rates, skip "
                        "reasons, position spectrum) instead of the "
                        "timer tables; a metrics FILE without a "
                        "quality section is an error")
    p.add_argument("--device", metavar="PROFILE_DIR", default=None,
                   help="Parse the jax.profiler trace in this "
                        "--profile directory and print the device-"
                        "truth kernel attribution table "
                        "(host dispatch / device execute / device "
                        "idle per stage, top kernels)")
    args = p.parse_args(argv)

    docs: list[dict] = []
    for path in args.files:
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            print(f"{path}: {e}", file=sys.stderr)
            return 1
        try:
            doc = json.loads(text)
        except ValueError:
            doc = None
        if isinstance(doc, dict) and doc.get("schema") == FLIGHT_SCHEMA:
            render_flight_dump(path, doc, args.last_s)
        elif args.flight:
            print(f"{path}: not a flight dump "
                  f"(schema {doc.get('schema') if isinstance(doc, dict) else None!r}, "
                  f"expected {FLIGHT_SCHEMA!r})", file=sys.stderr)
            return 1
        elif isinstance(doc, dict) and isinstance(doc.get("hosts"),
                                                  dict):
            # a multi-host aggregate (driver .hosts.json or a
            # push-receiver fleet document): per-host table first,
            # then the aggregate's own tables
            fleet_table(path, doc)
            docs.append(doc)
            if args.quality:
                if render_quality(path, doc):
                    return 1
            else:
                render_metrics_doc(path, doc)
        elif isinstance(doc, dict) and ("counters" in doc
                                        or "timers" in doc):
            docs.append(doc)
            if args.quality:
                if render_quality(path, doc):
                    return 1
            else:
                render_metrics_doc(path, doc)
        else:
            try:
                events = load_events(path)
                if any(e.get("event") == "partition_pass"
                       for e in events):
                    # a multi-pass build's events stream: the per-pass
                    # attribution table (ISSUE 14)
                    partition_table(path, events)
                else:
                    render_spans_file(path)
            except (ValueError, KeyError) as e:
                print(f"{path}: not a span/metrics/fleet artifact "
                      f"({e})", file=sys.stderr)
                return 1
    if args.device:
        return device_attribution(args.device, docs)
    return 0


if __name__ == "__main__":
    sys.exit(main())
