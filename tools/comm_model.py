"""Multi-chip communication model for the routed stage-2 path.

VERDICT r4 item 9: back the 8-chip throughput projection with a
MEASURED communication term. No multi-chip hardware exists in this
environment, so the two factual inputs are measured on what does
exist and the chip-to-chip link is an explicit parameter:

* **Iteration counts** (measured here): the routed extension loop's
  lockstep trip count — every iteration is a global pmax barrier plus
  one owner-bucketed all_to_all per in-loop lookup — counted EXACTLY
  by running the corrector eagerly (jax.disable_jit) on the 8-virtual-
  device CPU mesh with a counting lax.while_loop. Iterations depend on
  data (events/lane), not on device speed, so CPU-mesh counts carry
  over to real chips at the same coverage/error regime.

* **Per-iteration all_to_all bytes** (analytic, from the shapes in
  parallel/tile_sharded.routed_lookup_local): each routed lookup
  exchanges 3 outbound u32 planes (khi, klo, act) of S*cap words plus
  1 return plane, cap = lookup lanes. On a ring, each chip puts
  (S-1)/S of its buffer on the wire.

* **ICI bandwidth** (parameter): v5e publishes 1600 Gbit/s aggregate
  ICI per chip (2 links x 100 GB/s each direction); the model prints
  the comm seconds/batch for that figure and for a 10x-derated one.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     JAX_PLATFORMS=cpu python tools/comm_model.py
(the repo's tests/conftest.py environment; ~2-4 min, eager mode)
"""

from __future__ import annotations

import json
import os
import sys

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

jax.config.update("jax_default_device", jax.devices("cpu")[0])

from quorum_tpu.ops import ctable  # noqa: E402
from quorum_tpu.models import corrector  # noqa: E402
from quorum_tpu.models.ec_config import ECConfig  # noqa: E402
from quorum_tpu.parallel import tile_sharded as ts  # noqa: E402

S = 8          # shards
K = 15         # eager mode is slow; events/lane, not k, set iterations
RLEN = 100
B_PER_SHARD = 64
ERR = 0.01
COV = 40

# single-chip v5e measurements this model composes with (PERF_NOTES.md
# round 4/5, 16k x 150 bp, event-driven): device compute per batch and
# the measured in-loop per-iteration cost breakdown.
V5E_DEVICE_S_PER_16K_BATCH = 0.9   # measured steady state (CLI, warm)
V5E_BASES_PER_BATCH = 16384 * 150

ICI_GBYTES_S = 200.0   # v5e: 2 ICI links x ~100 GB/s per direction
ICI_DERATED = 20.0     # pessimistic 10x derate (protocol + small msgs)


def counting_while(counts):
    orig = jax.lax.while_loop

    def f(cond, body, carry):
        n = 0
        while bool(cond(carry)):
            carry = body(carry)
            n += 1
        counts.append(n)
        return carry
    return orig, f


def main():
    rng = np.random.default_rng(3)
    genome = rng.integers(0, 4, size=4000, dtype=np.int8)
    n_reads = S * B_PER_SHARD
    starts = rng.integers(0, len(genome) - RLEN, size=n_reads)
    codes = genome[starts[:, None] + np.arange(RLEN)[None, :]].astype(np.int8)
    errs = rng.random(codes.shape) < ERR
    codes = np.where(errs, (codes + rng.integers(1, 4, size=codes.shape)) % 4,
                     codes).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    lengths = np.full((n_reads,), RLEN, np.int32)

    cpus = jax.devices("cpu")[:S]
    mesh = ts.make_mesh(S, cpus)
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=10, n_shards=S)
    state, meta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, meta, 53)

    cfg = ECConfig(k=K, cutoff=2, poisson_dtype="float32")

    # Iteration counting: the routed loop's lockstep trip count is
    # pmax over shards of the local count, and every shard sees the
    # same stop condition as a single chip correcting the full batch
    # (the cond is any-lane-alive, pmax'ed; parallel/tile_sharded
    # cond at _extend_loop). So counting the SINGLE-CHIP eager run of
    # the same global batch gives exactly the lockstep count —
    # shard_map can't run eagerly, but it adds no iterations.
    gstate, gmeta = ts.gather_table(state, meta)
    counts: list[int] = []
    orig, counting = counting_while(counts)
    jax.lax.while_loop = counting
    try:
        with jax.disable_jit():
            res = corrector.correct_batch(
                gstate, gmeta, jnp.asarray(codes), jnp.asarray(quals),
                jnp.asarray(lengths), cfg)
    finally:
        jax.lax.while_loop = orig

    ok = int(np.sum(np.asarray(res.status) == corrector.OK))
    # under disable_jit+shard_map the body traces once (not per shard);
    # counts holds every while_loop trip count in the corrector —
    # the extend loop dominates (anchors are closed-form)
    iters = max(counts) if counts else 0
    b_lookup_lanes = 2 * n_reads          # merged fwd+bwd loop: 2B lanes
    ambig_cap = max(256, (2 * n_reads) // 8)

    # per-iteration a2a bytes PER CHIP (ring): 4 u32 planes x cap words
    # x (S-1)/S for the gba lookup (4 variants fused into ONE routed
    # lookup of 4B lanes) + the compacted ambig probe (16 x cap lanes)
    def a2a_bytes(lanes):
        return 4 * 4 * lanes * (S - 1) // S

    per_iter = a2a_bytes(4 * b_lookup_lanes) + a2a_bytes(16 * ambig_cap)
    # scale lanes to the production batch (16k reads/chip), and use
    # the CONSERVATIVE production iteration count: round-4's traced
    # worst case at 65k lanes was 51 lockstep iterations (cap-stall
    # cascades; PERF_NOTES.md) — far above the small-shape measurement
    # here, so the model can't understate comm
    iters_prod = max(iters, 51)
    scale = 16384 / n_reads
    per_iter_prod = int(per_iter * scale)
    total_comm = per_iter_prod * iters_prod

    out = {
        "measured": {
            "extend_iterations_lockstep": iters,
            "iterations_assumed_production": iters_prod,
            "all_while_loop_counts": sorted(set(counts), reverse=True)[:6],
            "reads": n_reads,
            "reads_ok": ok,
            "coverage": COV,
        },
        "analytic_per_production_batch_16k_reads_per_chip": {
            "a2a_bytes_per_iteration_per_chip": per_iter_prod,
            "a2a_bytes_total_per_chip": total_comm,
            "comm_seconds_at_full_ici": round(
                total_comm / (ICI_GBYTES_S * 1e9), 4),
            "comm_seconds_at_derated_ici": round(
                total_comm / (ICI_DERATED * 1e9), 4),
        },
        "model_8_chips": {},
    }
    # DP throughput model: each chip corrects its own 16k-read batch;
    # replicated-table stage 2 has NO per-iteration comm (the default
    # layout); routed stage 2 adds the comm term per iteration.
    dev = V5E_DEVICE_S_PER_16K_BATCH
    for tag, comm in (("replicated", 0.0),
                      ("routed_full_ici", total_comm / (ICI_GBYTES_S * 1e9)),
                      ("routed_derated_ici",
                       total_comm / (ICI_DERATED * 1e9))):
        t = dev + comm
        gbh = S * V5E_BASES_PER_BATCH / t * 3600 / 1e9
        out["model_8_chips"][tag] = {
            "s_per_batch_per_chip": round(t, 3),
            "gbases_per_hour_8chips": round(gbh, 1),
        }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
