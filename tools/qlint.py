#!/usr/bin/env python
"""Shim for `quorum-lint` (quorum_tpu/analysis/cli.py) so CI and
developers can run the static-analysis suite without installing the
package: `python tools/qlint.py --strict`. See the README "Static
analysis" section for the rule list and suppression syntax."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from quorum_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
