#!/usr/bin/env python
"""Validate quorum-tpu metrics artifacts against the telemetry schema
(quorum_tpu/telemetry/schema.py, version quorum-tpu-metrics/1).

Usage: python tools/metrics_check.py FILE [FILE ...]
       python tools/metrics_check.py --prom TEXTFILE [...]

Default mode accepts any of the artifact kinds the pipeline produces
and dispatches on content, not extension:

  * final metrics JSON documents (`--metrics PATH` on the CLIs,
    MetricsRegistry.write), including multi-host aggregated documents
    with a `hosts` section (parallel/multihost.aggregate_metrics)
  * JSONL event streams (`--metrics-interval` heartbeats, hash-grow
    and stage-done events)
  * span JSONL streams (`--trace-spans`, telemetry/spans.py) and
    their Chrome trace_event twins (`*.trace.json`)
  * bench-style metric-line files (one {"metric": ...} object per
    line, as bench.py and quorum-serve-bench emit — so CI can gate
    BENCH_*.json output)

A final document whose `meta.stage` is "serve" (quorum-serve's
`--metrics` output) is additionally required to carry the serve
request/batch metric names (SERVE_REQUIRED_*), so a golden serve run
in CI fails loudly if the serving telemetry regresses — and, when its
meta declares a resilience feature enabled (watchdog, hedging,
reload, quotas), the feature's counter too (SERVE_FEATURE_COUNTERS).
A document whose meta declares a checksummed database
(`db_version >= 5`) or a verification mode (`verify_db`) must carry
the integrity counters (INTEGRITY_COUNTERS, ISSUE 8). A document
whose meta declares a `--profile` directory must carry the
device-truth devtrace metrics (DEVTRACE_*, ISSUE 10); one declaring
`metrics_push_url` must carry the push-transport counters (PUSH_*);
and a push-receiver fleet aggregate (meta.fleet) must carry per-host
shards matching meta.fleet_hosts. A multi-host fleet run's aggregated
document (meta.host_process_count > 1, ISSUE 20) must carry exactly
one host shard per process under `hosts`, the min-reduced resource
gauges, and each sentinel host's per-site compile counters. A document declaring alert rules
active (meta.alert_rules, ISSUE 11) must carry the alert engine's
counters/gauges with `alerts_firing{rule=}` values in {0, 1} naming
declared rules; `meta.autotune_profile`, when present, must be a
non-empty path. A document declaring meta.flight (the flight
recorder was installed and enabled, ISSUE 16) must carry the
dump/drop counters (FLIGHT_COUNTERS); one declaring
meta.resource_guard (utils/resources armed a disk monitor, ISSUE 19)
must carry the guard counters and monitor gauges (RESOURCE_*);
flight dump documents
(quorum-tpu-flight/1) and debug-bundle manifests
(quorum-tpu-debug-bundle/1) validate through their own schema
validators, seal recomputed. perf_diff verdict documents
(quorum-tpu-perf-diff/1) validate for internal coherence (verdict
vs regression list vs per-metric ok flags). `request` and `alert`
lifecycle events in events JSONL are held to their richer contracts
(request_id/status/lane/phases; rule/state) by the shared schema
validator.

`--prom` switches to linting Prometheus text exposition output
(`--metrics-textfile` files or a saved `/metrics` scrape) through the
shared linter in telemetry/export.py.

Prints one line per problem and exits 1 if any file fails, 0 if all
are valid. Used by tests/test_telemetry.py and tests/test_golden.py
on golden-pipeline dumps.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from quorum_tpu.telemetry import check_file  # noqa: E402
from quorum_tpu.telemetry.export import lint_prometheus_text  # noqa: E402

# The required-name catalogs are single-sourced in
# quorum_tpu/telemetry/contract.py (ISSUE 12): this checker, the
# quorum-lint counter-pre-creation rule, and the telemetry layers all
# import the SAME lists, so the CI gate and the code that fulfils it
# cannot drift. Re-exported here because tests and callers address
# them as metrics_check.* attributes.
from quorum_tpu.telemetry.contract import (  # noqa: E402,F401
    ALERT_COUNTERS,
    ALERT_GAUGES,
    COMPILE_COUNTERS,
    COMPILE_META,
    DEVTRACE_COUNTERS,
    DEVTRACE_GAUGES,
    DEVTRACE_HISTOGRAMS,
    DEVTRACE_META,
    FAULT_COUNTERS,
    FLEET_COMPILE_PREFIX,
    FLEET_GAUGES,
    FLEET_META,
    FLIGHT_COUNTERS,
    INTEGRITY_COUNTERS,
    LIVE_INGEST_COUNTERS,
    LIVE_INGEST_GAUGES,
    PARTITION_COUNTERS,
    PARTITION_GAUGE_PREFIX,
    PREFILTER_COUNTERS,
    PUSH_COUNTERS,
    PUSH_META,
    QUALITY_COUNTERS,
    QUALITY_GAUGES,
    QUALITY_HISTOGRAMS,
    RESOURCE_COUNTERS,
    RESOURCE_GAUGE_PREFIX,
    RESOURCE_GAUGES,
    SERVE_FEATURE_COUNTERS,
    SERVE_REQUIRED_COUNTERS,
    SERVE_REQUIRED_HISTOGRAMS,
    SHARD_REQUIRED_COUNTERS,
    SHARD_REQUIRED_GAUGES,
    SHARD_REQUIRED_META_LISTS,
)


def _check_shard_names(doc: dict) -> list[str]:
    """Sharded-build requirements: dispatch on gauges.n_shards > 1 in
    a stage-1 document; also verify the per-shard meta lists have
    exactly n_shards entries (a truncated list means a shard's
    telemetry was dropped)."""
    errs = []
    gauges = doc.get("gauges", {})
    try:
        n_shards = int(gauges.get("n_shards", 1))
    except (TypeError, ValueError):
        return ["gauges.n_shards is not an integer"]
    if doc.get("meta", {}).get("stage") != "create_database" \
            or n_shards <= 1:
        return []
    for name in SHARD_REQUIRED_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"sharded build document missing counter "
                        f"{name!r}")
    for name in SHARD_REQUIRED_GAUGES:
        if name not in gauges:
            errs.append(f"sharded build document missing gauge "
                        f"{name!r}")
    for name in SHARD_REQUIRED_META_LISTS:
        val = doc.get("meta", {}).get(name)
        if not isinstance(val, list) or len(val) != n_shards:
            errs.append(
                f"sharded build document meta.{name} must be a list "
                f"of {n_shards} per-shard values, got {val!r}")
    return errs


def _check_memfrugal_names(doc: dict) -> list[str]:
    """Memory-frugal counting requirements (ISSUE 14): dispatch on
    meta.prefilter (a non-off mode must carry the prefilter counters)
    and meta.partitions (> 1 must carry the pass counter and exactly
    one partition_distinct gauge per partition)."""
    errs = []
    meta = doc.get("meta", {})
    counters = doc.get("counters", {})
    mode = meta.get("prefilter")
    if mode and mode != "off":
        for name in PREFILTER_COUNTERS:
            if name not in counters:
                errs.append(f"document with meta.prefilter={mode!r} "
                            f"missing counter {name!r}")
    try:
        parts = int(meta.get("partitions") or 1)
    except (TypeError, ValueError):
        return errs + ["meta.partitions is not an integer"]
    if parts > 1:
        for name in PARTITION_COUNTERS:
            if name not in counters:
                errs.append(f"document with meta.partitions={parts} "
                            f"missing counter {name!r}")
        gauges = doc.get("gauges", {})
        # a PER-HOST fleet shard (ISSUE 20) runs only the passes it
        # owns (p % host_process_count == host_process_index), so
        # only those gauges can exist in it; the aggregated document
        # merges the full set and is held to every partition
        try:
            pc = int(meta.get("host_process_count") or 1)
            pi = int(meta.get("host_process_index") or 0)
        except (TypeError, ValueError):
            pc, pi = 1, 0
        fleet_shard = pc > 1 and "hosts" not in doc \
            and "aggregated_hosts" not in meta
        for p in range(parts):
            if fleet_shard and p % pc != pi:
                continue
            gname = f'{PARTITION_GAUGE_PREFIX}"{p}"}}'
            if gname not in gauges:
                errs.append(
                    f"document with meta.partitions={parts} missing "
                    f"gauge {gname!r} (a partition pass's telemetry "
                    "was dropped)")
    return errs


def _check_hosts_doc(doc: dict) -> list[str]:
    """Aggregated-document requirements (parallel/multihost.
    aggregate_metrics, written by the quorum driver every run): the
    shard count recorded in meta must match the shards present."""
    if "hosts" not in doc:
        return []
    errs = []
    hosts = doc["hosts"]
    n = doc.get("meta", {}).get("aggregated_hosts")
    if isinstance(hosts, dict) and n != len(hosts):
        errs.append(
            f"aggregated document meta.aggregated_hosts={n!r} but "
            f"{len(hosts)} host shard(s) present")
    return errs


def _check_fault_names(doc: dict) -> list[str]:
    errs = []
    meta = doc.get("meta", {})
    counters = doc.get("counters", {})

    def want(cond, name, why):
        if cond and name not in counters:
            errs.append(f"document with {why} missing counter {name!r}")

    try:
        every = float(meta.get("checkpoint_every") or 0)
    except (TypeError, ValueError):
        every = 0
    want(every > 0, "checkpoint_writes_total",
         f"meta.checkpoint_every={meta.get('checkpoint_every')!r}")
    want(bool(meta.get("resumed")), "resume_skipped_reads",
         "meta.resumed set")
    want(meta.get("on_bad_read") in ("skip", "quarantine"),
         "bad_reads_total",
         f"meta.on_bad_read={meta.get('on_bad_read')!r}")
    want(meta.get("driver") == "quorum", "stage_retries_total",
         "meta.driver=quorum")
    return errs


def _check_integrity_names(doc: dict) -> list[str]:
    """Integrity-surface requirements (ISSUE 8): dispatch on
    meta.db_version >= 5 or meta.verify_db."""
    errs = []
    meta = doc.get("meta", {})
    counters = doc.get("counters", {})
    try:
        db_version = int(meta.get("db_version") or 0)
    except (TypeError, ValueError):
        return ["meta.db_version is not an integer"]
    declared = db_version >= 5 or bool(meta.get("verify_db"))
    if not declared:
        return []
    why = (f"meta.db_version={meta.get('db_version')!r}"
           if db_version >= 5
           else f"meta.verify_db={meta.get('verify_db')!r}")
    for name in INTEGRITY_COUNTERS:
        if name not in counters:
            errs.append(f"document with {why} missing counter "
                        f"{name!r}")
    return errs


def _check_devtrace_names(doc: dict) -> list[str]:
    """Devtrace-surface requirements (ISSUE 10): dispatch on
    meta.profile — every `--profile` run records the device-kernel
    attribution post-run, zeros included."""
    meta = doc.get("meta", {})
    if not meta.get("profile"):
        return []
    errs = []
    why = f"meta.profile={meta.get('profile')!r}"
    for name in DEVTRACE_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"document with {why} missing counter {name!r}")
    for name in DEVTRACE_GAUGES:
        if name not in doc.get("gauges", {}):
            errs.append(f"document with {why} missing gauge {name!r}")
    for name in DEVTRACE_HISTOGRAMS:
        if name not in doc.get("histograms", {}):
            errs.append(f"document with {why} missing histogram "
                        f"{name!r}")
    for name in DEVTRACE_META:
        if name not in meta:
            errs.append(f"document with {why} missing meta.{name}")
    return errs


def _check_push_names(doc: dict) -> list[str]:
    """Push-transport requirements (ISSUE 10): dispatch on
    meta.metrics_push_url (the MetricsPusher stamps it at start)."""
    meta = doc.get("meta", {})
    if not meta.get("metrics_push_url"):
        return []
    errs = []
    why = f"meta.metrics_push_url={meta.get('metrics_push_url')!r}"
    for name in PUSH_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"document with {why} missing counter {name!r}")
    for name in PUSH_META:
        if name not in meta:
            errs.append(f"document with {why} missing meta.{name}")
    return errs


def _check_compile_names(doc: dict) -> list[str]:
    """Compile-sentinel requirements (ISSUE 15): dispatch on
    meta.compile_sentinel — a run under QUORUM_COMPILE_SENTINEL=1
    exports its jit-compile ledger at final write, so a missing
    counter means the export regressed and the perf_diff compile
    gate went quietly vacuous."""
    meta = doc.get("meta", {})
    if not meta.get("compile_sentinel"):
        return []
    errs = []
    why = f"meta.compile_sentinel={meta.get('compile_sentinel')!r}"
    for name in COMPILE_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"document with {why} missing counter {name!r}")
    for name in COMPILE_META:
        if not isinstance(meta.get(name), dict):
            errs.append(f"document with {why} missing (or non-map) "
                        f"meta.{name}")
    return errs


def _check_flight_names(doc: dict) -> list[str]:
    """Flight-recorder requirements (ISSUE 16): dispatch on
    meta.flight — observability() stamps it when the recorder is
    installed and enabled, and FlightRecorder pre-creates both
    counters at construction, so a missing name means the black box
    silently disarmed (a clean zero-dump run still carries them
    at 0)."""
    meta = doc.get("meta", {})
    if not meta.get("flight"):
        return []
    errs = []
    why = f"meta.flight={meta.get('flight')!r}"
    for name in FLIGHT_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"document with {why} missing counter {name!r}")
    return errs


def _check_fleet_doc(doc: dict) -> list[str]:
    """Fleet-document requirements (tools/push_receiver.py): a
    document stamped meta.fleet must carry the per-host shards under
    `hosts`, keyed exactly by meta.fleet_hosts — a mismatch means a
    host's final push was dropped from the aggregate."""
    meta = doc.get("meta", {})
    if not meta.get("fleet"):
        return []
    errs = []
    hosts = doc.get("hosts")
    if not isinstance(hosts, dict) or not hosts:
        return ["fleet document missing its per-host 'hosts' section"]
    names = meta.get("fleet_hosts")
    if not isinstance(names, list) or sorted(hosts) != sorted(
            str(n) for n in names):
        errs.append(
            f"fleet document meta.fleet_hosts={names!r} does not "
            f"match hosts keys {sorted(hosts)}")
    return errs


def _check_multihost_fleet(doc: dict) -> list[str]:
    """Multi-host fleet requirements (ISSUE 20): dispatch on
    meta.host_process_count > 1 — the ONE aggregated document
    multihost.aggregate_metrics writes on process 0 of a fleet run.
    It must carry exactly one host shard per process under `hosts`,
    the fleet-reduced resource gauges (free space min-reduced across
    hosts, so the document reports the tightest disk anywhere in the
    fleet), and — for every host shard declaring compile_sentinel —
    that host's per-site compiles{site=...} counters (a sentinel host
    with no ledger is a host whose compile telemetry was dropped)."""
    meta = doc.get("meta", {})
    try:
        pc = int(meta.get("host_process_count") or 1)
    except (TypeError, ValueError):
        return ["meta.host_process_count is not an integer"]
    if pc <= 1:
        return []
    if "hosts" not in doc and "aggregated_hosts" not in meta:
        # a PER-HOST shard document (the host-scoped --metrics files
        # each fleet process writes) also carries host_process_count;
        # the aggregate contract applies to the one merged document,
        # which CI gates by name (fleet_metrics.hosts.json)
        return []
    errs = []
    why = f"meta.host_process_count={pc}"
    for name in FLEET_META:
        if name not in meta:
            errs.append(f"fleet document ({why}) missing meta.{name}")
    hosts = doc.get("hosts")
    if not isinstance(hosts, dict) or len(hosts) != pc:
        errs.append(
            f"fleet document ({why}) must carry exactly {pc} host "
            f"shard(s) under 'hosts', got "
            f"{sorted(hosts) if isinstance(hosts, dict) else hosts!r}")
        hosts = {}
    for name in FLEET_GAUGES:
        if name not in doc.get("gauges", {}):
            errs.append(f"fleet document ({why}) missing fleet-"
                        f"reduced gauge {name!r}")
    for hname in sorted(hosts):
        hdoc = hosts[hname]
        if not isinstance(hdoc, dict):
            errs.append(f"fleet host shard {hname!r} is not a "
                        "document")
            continue
        if not hdoc.get("meta", {}).get("compile_sentinel"):
            continue
        hcounters = hdoc.get("counters", {})
        if not any(c.startswith(FLEET_COMPILE_PREFIX)
                   for c in hcounters):
            errs.append(
                f"fleet host shard {hname!r} declares "
                "compile_sentinel but carries no "
                f"{FLEET_COMPILE_PREFIX}...}} counter (its compile "
                "ledger was dropped)")
    return errs


def _check_alert_names(doc: dict) -> list[str]:
    """Alerting-surface requirements (ISSUE 11): dispatch on
    meta.alert_rules — the engine stamps the active rule names at
    setup and pre-creates the counters, so a missing name means the
    alerting telemetry regressed."""
    meta = doc.get("meta", {})
    rules = meta.get("alert_rules")
    if not rules:
        return []
    errs = []
    if not isinstance(rules, list) or not all(
            isinstance(r, str) for r in rules):
        return ["meta.alert_rules must be a list of rule names"]
    why = f"meta.alert_rules ({len(rules)} rule(s))"
    for name in ALERT_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"document with {why} missing counter {name!r}")
    for name in ALERT_GAUGES:
        if name not in doc.get("gauges", {}):
            errs.append(f"document with {why} missing gauge {name!r}")
    declared = set(rules)
    for gname, val in doc.get("gauges", {}).items():
        if not gname.startswith("alerts_firing{"):
            continue
        if val not in (0, 1):
            errs.append(f"gauge {gname!r} must be 0 or 1, got {val!r}")
        rule = gname[len("alerts_firing{"):-1]
        rule = rule.partition("=")[2].strip('"')
        if rule and rule not in declared:
            errs.append(f"gauge {gname!r} names a rule not in "
                        f"meta.alert_rules")
    return errs


def _check_autotune_meta(doc: dict) -> list[str]:
    """Autotune-surface requirement (ISSUE 11): meta.autotune_profile
    — stamped by observability() when a profile steers the run's
    levers — must be a non-empty path string."""
    meta = doc.get("meta", {})
    if "autotune_profile" not in meta:
        return []
    val = meta.get("autotune_profile")
    if not isinstance(val, str) or not val:
        return [f"meta.autotune_profile must be a non-empty path "
                f"string, got {val!r}"]
    return []


def _check_quality_names(doc: dict) -> list[str]:
    """Correction-quality requirements (ISSUE 17), two dispatches:

    * meta.quality (a QualityScorecard was installed by
      observability()) -> the windowed quality_* gauges must be
      present (pre-created at quiet values) and the document must
      carry a schema-valid top-level `quality` section (the schema
      validator already checked its shape if present — here we
      require its presence).
    * meta.stage in (error_correct, serve) — a stage-2 data plane —
      -> the full outcome surface: every skipped_<slug> counter (the
      PR-7 zero-count lesson) and the quality histograms, all
      pre-created by models/error_correct.precreate_outcome_counters.
    """
    errs = []
    meta = doc.get("meta", {})
    if meta.get("quality"):
        why = f"meta.quality={meta.get('quality')!r}"
        for name in QUALITY_GAUGES:
            if name not in doc.get("gauges", {}):
                errs.append(f"document with {why} missing gauge "
                            f"{name!r}")
        if not isinstance(doc.get("quality"), dict):
            errs.append(f"document with {why} missing its top-level "
                        "'quality' section")
    if meta.get("stage") in ("error_correct", "serve"):
        why = f"meta.stage={meta.get('stage')!r}"
        for name in QUALITY_COUNTERS:
            if name not in doc.get("counters", {}):
                errs.append(f"document with {why} missing counter "
                            f"{name!r}")
        for name in QUALITY_HISTOGRAMS:
            if name not in doc.get("histograms", {}):
                errs.append(f"document with {why} missing histogram "
                            f"{name!r}")
    return errs


def _check_live_ingest_names(doc: dict) -> list[str]:
    """A serve document declaring `meta.live_ingest` ran the live
    ingestion tier (ISSUE 18): the ingest counters and the
    cursor/floor gauges must exist, or the epoch-swap machinery was
    silently bypassed."""
    errs = []
    meta = doc.get("meta", {})
    if not meta.get("live_ingest"):
        return errs
    why = f"meta.live_ingest={meta.get('live_ingest')!r}"
    for name in LIVE_INGEST_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"document with {why} missing counter "
                        f"{name!r}")
    for name in LIVE_INGEST_GAUGES:
        if name not in doc.get("gauges", {}):
            errs.append(f"document with {why} missing gauge {name!r}")
    return errs


def _check_resource_names(doc: dict) -> list[str]:
    """Resource-guard requirements (ISSUE 19): dispatch on
    meta.resource_guard — utils/resources.install stamps it when a
    disk monitor is armed over the run's artifact filesystems, and
    pre-creates the guard counters, so a missing name means the
    guard telemetry regressed. The monitor publishes its gauges at a
    synchronous first tick, so they must exist even in a run that
    finished inside one poll interval; at least one per-path
    `disk_free_bytes{path="..."}` labeled gauge must ride along (the
    path SET is run-shaped, so no individual path is required)."""
    errs = []
    meta = doc.get("meta", {})
    if not meta.get("resource_guard"):
        return errs
    why = f"meta.resource_guard={meta.get('resource_guard')!r}"
    for name in RESOURCE_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"document with {why} missing counter "
                        f"{name!r}")
    gauges = doc.get("gauges", {})
    for name in RESOURCE_GAUGES:
        if name not in gauges:
            errs.append(f"document with {why} missing gauge {name!r}")
    if not any(g.startswith(RESOURCE_GAUGE_PREFIX) for g in gauges):
        errs.append(f"document with {why} carries no "
                    f"{RESOURCE_GAUGE_PREFIX}...}} labeled gauge "
                    "(the disk monitor never ticked)")
    return errs


def _check_serve_names(doc: dict) -> list[str]:
    errs = []
    for name in SERVE_REQUIRED_COUNTERS:
        if name not in doc.get("counters", {}):
            errs.append(f"serve document missing counter {name!r}")
    for name in SERVE_REQUIRED_HISTOGRAMS:
        if name not in doc.get("histograms", {}):
            errs.append(f"serve document missing histogram {name!r}")
    meta = doc.get("meta", {})
    counters = doc.get("counters", {})
    for key, name in SERVE_FEATURE_COUNTERS:
        val = meta.get(key)
        if isinstance(val, (int, float)):
            declared = val > 0
        else:
            declared = bool(val)
        if declared and name not in counters:
            errs.append(f"serve document declaring meta.{key}="
                        f"{val!r} missing counter {name!r}")
    return errs


def _check_with_serve_names(path: str) -> list[str]:
    """check_file, plus the serve-name requirements when the artifact
    is a serve final document and the fault-tolerance names whenever
    the document's meta declares the feature (dispatch on meta, like
    the rest of the content dispatch)."""
    problems = check_file(path)
    try:
        import json
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return problems
    if not isinstance(doc, dict):
        return problems
    from quorum_tpu.telemetry.schema import SCHEMA_VERSION
    if (isinstance(doc.get("schema"), str)
            and doc["schema"] != SCHEMA_VERSION):
        # a flight dump / debug-bundle manifest / perf-diff verdict:
        # its own schema validator ran in check_file, and its meta
        # (pid/argv/stage of the dying run) must not pull the final-
        # document counter contracts onto a forensics artifact
        return problems
    if doc.get("meta", {}).get("stage") == "serve":
        problems = problems + _check_serve_names(doc)
    if "meta" in doc:
        problems = problems + _check_fault_names(doc)
        problems = problems + _check_integrity_names(doc)
        problems = problems + _check_shard_names(doc)
        problems = problems + _check_memfrugal_names(doc)
        problems = problems + _check_hosts_doc(doc)
        problems = problems + _check_multihost_fleet(doc)
        problems = problems + _check_devtrace_names(doc)
        problems = problems + _check_push_names(doc)
        problems = problems + _check_fleet_doc(doc)
        problems = problems + _check_alert_names(doc)
        problems = problems + _check_autotune_meta(doc)
        problems = problems + _check_compile_names(doc)
        problems = problems + _check_flight_names(doc)
        problems = problems + _check_quality_names(doc)
        problems = problems + _check_live_ingest_names(doc)
        problems = problems + _check_resource_names(doc)
    return problems


def _check_bench_required(path: str, required: list[str]) -> list[str]:
    """BENCH-style gating (--require-metric): the file must be a valid
    line-oriented artifact AND carry at least one bench metric line
    for every required name — so CI fails loudly when a freshly
    produced bench document silently lost its headline (a truncated
    run emits valid-but-incomplete output)."""
    import json
    errs = []
    seen = set()
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # check_file already reports bad lines
                if isinstance(obj, dict) and isinstance(
                        obj.get("metric"), str):
                    seen.add(obj["metric"])
    except OSError as e:
        return [str(e)]
    for name in required:
        if name not in seen:
            errs.append(f"bench document missing required metric "
                        f"{name!r} (has {sorted(seen)})")
    return errs


def _check_prom(path: str) -> list[str]:
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [str(e)]
    return lint_prometheus_text(text)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Validate metrics JSON / events JSONL / span JSONL "
                    "/ Chrome trace / bench metric-line files against "
                    "quorum-tpu-metrics/1, or Prometheus textfiles "
                    "with --prom")
    p.add_argument("files", nargs="+", metavar="FILE")
    p.add_argument("--prom", action="store_true",
                   help="Lint FILEs as Prometheus text exposition "
                        "format (--metrics-textfile output)")
    p.add_argument("--require-metric", action="append", default=[],
                   metavar="NAME",
                   help="Additionally require every FILE (a BENCH-"
                        "style metric-line document) to carry at "
                        "least one line with this metric name; "
                        "repeatable — ci/tier1.sh gates the fresh "
                        "bench A/B document this way")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Suppress per-file OK lines")
    args = p.parse_args(argv)

    if args.prom and args.require_metric:
        # --require-metric names bench metric lines, which a
        # Prometheus textfile cannot carry — combining them would
        # silently drop the requirement
        p.error("--require-metric cannot be combined with --prom")
    if args.prom:
        check = _check_prom
    elif args.require_metric:
        def check(path, _req=args.require_metric):
            return (_check_with_serve_names(path)
                    + _check_bench_required(path, _req))
    else:
        check = _check_with_serve_names
    bad = 0
    for path in args.files:
        problems = check(path)
        if problems:
            bad += 1
            for msg in problems:
                print(f"{path}: {msg}", file=sys.stderr)
        elif not args.quiet:
            print(f"{path}: OK")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
