#!/usr/bin/env python
"""Validate quorum-tpu metrics artifacts against the telemetry schema
(quorum_tpu/telemetry/schema.py, version quorum-tpu-metrics/1).

Usage: python tools/metrics_check.py FILE [FILE ...]

Accepts any of the three artifact kinds the pipeline produces and
dispatches on content, not extension:

  * final metrics JSON documents (`--metrics PATH` on the CLIs,
    MetricsRegistry.write)
  * JSONL event streams (`--metrics-interval` heartbeats, hash-grow
    and stage-done events)
  * bench-style metric-line files (one {"metric": ...} object per
    line, as bench.py emits — so CI can gate BENCH_*.json output)

Prints one line per problem and exits 1 if any file fails, 0 if all
are valid. Used by tests/test_telemetry.py on a golden-pipeline dump.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from quorum_tpu.telemetry import check_file  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Validate metrics JSON / events JSONL / bench "
                    "metric-line files against quorum-tpu-metrics/1")
    p.add_argument("files", nargs="+", metavar="FILE")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Suppress per-file OK lines")
    args = p.parse_args(argv)

    bad = 0
    for path in args.files:
        problems = check_file(path)
        if problems:
            bad += 1
            for msg in problems:
                print(f"{path}: {msg}", file=sys.stderr)
        elif not args.quiet:
            print(f"{path}: OK")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
