#!/usr/bin/env python
"""Degradation-ladder smoke for CI (ISSUE 19, ci/tier1.sh): out of
space must mean *less telemetry*, never *less output* — and a wedged
step must die retryably, not hang a CI lane forever.

Three gates in one tool:

1. **Optional writer degrades, run completes**: the golden database
   build with per-batch checkpoints whose checkpoint filesystem
   "fills" (fault action ``diskfull`` at ``checkpoint.commit``) must
   exit 0 with a table identical to an unfaulted build,
   ``writer_degraded_total`` counted, ``meta.resource_guard``
   declared, and a final document tools/metrics_check.py accepts
   (the resource-guard contract gates it).

2. **Required writer fails fast**: the same build with the DB export
   itself out of space (``diskfull`` at ``db.write``) must exit with
   the non-retryable ``DISK_FULL_RC`` and seal exactly one flight
   dump whose trigger is kind ``disk_full`` naming writer
   ``db.payload`` — the postmortem pinpoints WHICH writer hit the
   wall.

3. **Stall watchdog, then resume**: a subprocess stage-2 run wedged
   by a ``sleep`` fault at ``stage2.correct`` under
   ``--stall-timeout-s`` must exit ``STALL_RC`` (the hard abort — a
   thread sleeping in native code never sees the soft async raise,
   which is exactly the wedge the two-stage design exists for) and
   leave a ``stall``-kind flight dump plus an intact journal; the
   ``--resume`` rerun must converge on output byte-identical to an
   unfaulted run.

Artifacts land in --out-dir:
  degrade_metrics.json         — gate 1's final document
  diskfull_metrics.json        — gate 2's error document
  diskfull_metrics.flight.json — gate 2's sealed disk_full dump
  stall_metrics.flight.json    — gate 3's sealed stall dump

Exit 0 = all gates held.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def _fail(msg: str) -> int:
    print(f"[degrade_smoke] FAIL: {msg}", file=sys.stderr)
    return 1


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _db_entries(path):
    from quorum_tpu.io import db_format
    state, meta, _ = db_format.read_db(path, to_device=False)
    khi, klo, vals = db_format.db_iterate(state, meta)
    return sorted(zip(khi.tolist(), klo.tolist(), vals.tolist()))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Degradation-ladder smoke: an out-of-space "
                    "optional writer degrades while the run "
                    "completes byte-identically, a required writer "
                    "fails fast with DISK_FULL_RC + a sealed dump, "
                    "and a wedged stage-2 step exits STALL_RC then "
                    "resumes (ci/tier1.sh gate)")
    p.add_argument("--out-dir", default=None,
                   help="Artifact directory (default: a temp dir)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="degrade_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import error_correct_reads as ec_cli
    from quorum_tpu.telemetry import schema as schema_mod
    from quorum_tpu.utils import faults, resources

    mc = _load_tool("metrics_check")
    reads = os.path.join(GOLDEN, "reads.fastq")
    cdb_args = ["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                "--batch-size", "64"]

    # the unfaulted reference build: gates 1 and 2 compare against it
    db0 = os.path.join(out_dir, "db0.jf")
    if cdb_cli.main(cdb_args + ["-o", db0, reads]) != 0:
        return _fail("reference golden build failed")

    # -- gate 1: optional writer degrades, the run completes ----------------
    print("[degrade_smoke] gate 1: diskfull at checkpoint.commit "
          "(optional writer)")
    db1 = os.path.join(out_dir, "db1.jf")
    ckdir = os.path.join(out_dir, "ck")
    metrics1 = os.path.join(out_dir, "degrade_metrics.json")
    faults.install(faults.FaultPlan.parse(
        {"site": "checkpoint.commit", "action": "diskfull",
         "count": -1}), "degrade-smoke")
    try:
        rc = cdb_cli.main(cdb_args + [
            "-o", db1, "--checkpoint-dir", ckdir,
            "--checkpoint-every", "1", "--metrics", metrics1, reads])
    finally:
        faults.reset()
    if rc != 0:
        return _fail(f"gate 1: rc={rc} (an optional writer's ENOSPC "
                     "must not fail the run)")
    if _db_entries(db1) != _db_entries(db0):
        return _fail("gate 1: degraded-checkpoint table differs from "
                     "the unfaulted build")
    with open(metrics1) as f:
        doc = json.load(f)
    if doc.get("counters", {}).get("writer_degraded_total", 0) < 1:
        return _fail("gate 1: writer_degraded_total not counted")
    if doc.get("meta", {}).get("resource_guard") is not True:
        return _fail("gate 1: final document does not declare "
                     "meta.resource_guard")
    if mc.main([metrics1, "-q"]) != 0:
        return _fail("gate 1: metrics_check rejected the document")
    print("[degrade_smoke] gate 1: degraded, completed, identical "
          "table")

    # -- gate 2: required writer fails fast with a sealed dump --------------
    print("[degrade_smoke] gate 2: diskfull at db.write (required "
          "writer)")
    db2 = os.path.join(out_dir, "db2.jf")
    metrics2 = os.path.join(out_dir, "diskfull_metrics.json")
    faults.install(faults.FaultPlan.parse(
        {"site": "db.write", "action": "diskfull", "count": -1}),
        "degrade-smoke")
    try:
        rc = cdb_cli.main(cdb_args + ["-o", db2, "--metrics", metrics2,
                                      reads])
    finally:
        faults.reset()
    if rc != resources.DISK_FULL_RC:
        return _fail(f"gate 2: rc={rc} (want the non-retryable "
                     f"DISK_FULL_RC={resources.DISK_FULL_RC})")
    dump2 = metrics2[:-len(".json")] + ".flight.json"
    if not os.path.exists(dump2):
        return _fail(f"gate 2: no flight dump at {dump2}")
    with open(dump2) as f:
        fdoc = json.load(f)
    errs = schema_mod.validate_flight_dump(fdoc)
    if errs:
        return _fail(f"gate 2: dump invalid: {errs[:3]}")
    trig = fdoc.get("trigger", {})
    if trig.get("kind") != "disk_full":
        return _fail(f"gate 2: trigger kind {trig.get('kind')!r} "
                     "(want 'disk_full')")
    if trig.get("site") != "db.payload":
        return _fail(f"gate 2: trigger site {trig.get('site')!r} "
                     "(want the writer name 'db.payload')")
    if mc.main([dump2, "-q"]) != 0 or mc.main([metrics2, "-q"]) != 0:
        return _fail("gate 2: metrics_check rejected the dump or the "
                     "error document")
    print("[degrade_smoke] gate 2: DISK_FULL_RC with a dump naming "
          "db.payload")

    # -- gate 3: stall watchdog aborts retryably, resume converges ----------
    # Subprocess on purpose: the wedge is a thread blocked in native
    # sleep, so the watchdog escalates to the hard abort
    # (os._exit(STALL_RC)) — which must kill the CHILD, not this tool.
    print("[degrade_smoke] gate 3: seeded stall at stage2.correct "
          "(subprocess)")
    ec_args = ["--batch-size", "16", "--checkpoint-every", "1"]
    prefix0 = os.path.join(out_dir, "out0")
    if ec_cli.main(ec_args + ["-o", prefix0, db0, reads]) != 0:
        return _fail("gate 3: reference stage-2 run failed")
    prefix = os.path.join(out_dir, "out1")
    metrics3 = os.path.join(out_dir, "stall_metrics.json")
    plan = json.dumps({"site": "stage2.correct", "batch": 2,
                       "action": "sleep", "seconds": 30})
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("QUORUM_FAULT_PLAN", None)
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.error_correct_reads"]
        + ec_args + ["-o", prefix, "--stall-timeout-s", "1",
                     "--fault-plan", plan, "--metrics", metrics3,
                     db0, reads],
        cwd=REPO, env=env, timeout=600,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    if proc.returncode != resources.STALL_RC:
        return _fail(f"gate 3: rc={proc.returncode} (want the "
                     f"retryable STALL_RC={resources.STALL_RC}); "
                     f"stderr tail: {proc.stderr[-500:]}")
    dump3 = metrics3[:-len(".json")] + ".flight.json"
    if not os.path.exists(dump3):
        return _fail(f"gate 3: no stall dump at {dump3}")
    with open(dump3) as f:
        sdoc = json.load(f)
    if sdoc.get("trigger", {}).get("kind") != "stall":
        return _fail(f"gate 3: trigger kind "
                     f"{sdoc.get('trigger', {}).get('kind')!r} "
                     "(want 'stall')")
    if mc.main([dump3, "-q"]) != 0:
        return _fail("gate 3: metrics_check rejected the stall dump")
    # the journal survived the hard abort: resume and converge
    rc = ec_cli.main(ec_args + ["-o", prefix, "--resume", db0, reads])
    if rc != 0:
        return _fail(f"gate 3: --resume rerun rc={rc}")
    with open(prefix0 + ".fa", "rb") as f:
        want = f.read()
    with open(prefix + ".fa", "rb") as f:
        got = f.read()
    if got != want:
        return _fail("gate 3: resumed output differs from the "
                     "unfaulted run")
    print("[degrade_smoke] gate 3: STALL_RC, stall dump, resumed "
          "byte-identical")

    print(f"[degrade_smoke] OK: less telemetry, never less output; "
          f"artifacts -> {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
