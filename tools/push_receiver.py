#!/usr/bin/env python
"""Tiny stdlib push-gateway for quorum-tpu metrics (ISSUE 10): the
receiving end of `--metrics-push-url` (quorum_tpu/telemetry/push.py).

Each pushing host POSTs its Prometheus exposition text to `/push` and
its final metrics JSON document to `/push/final`, both stamped with an
`X-Quorum-Host` identity header. The receiver:

* keeps the LATEST exposition text per host and re-serves the whole
  fleet's at `GET /metrics` (duplicate `# TYPE` headers deduplicated),
  so one scraper covers a fleet that cannot itself be scraped;
* aggregates the per-host FINAL documents into one fleet document via
  `parallel/multihost.merge_host_docs` — the exact merge rules
  `aggregate_metrics` applies collectively (counters sum, gauges max,
  histograms merge, job total = slowest host) — re-written atomically
  to `--out` after every final push, with `meta.fleet` / per-host ids
  stamped so `tools/metrics_check.py` can gate it;
* serves the current fleet document at `GET /fleet` and liveness at
  `GET /healthz` — which carries a per-host `doc_age_s` staleness map
  (seconds since each host's last push), the fleet-level signal a
  silent host can't suppress; the same staleness rides `GET /metrics`
  as `quorum_tpu_push_doc_age_seconds{host=...}` gauges so an
  absence-style alert rule can watch it (ISSUE 11);
* with `--stale-after-s S`, evaluates that absence rule ITSELF
  (telemetry/alerts.py semantics: arm on first push, fire once silent
  past S, heal on return): each armed host gets a 0/1
  `fleet_host_stale{host=...}` gauge at `GET /metrics`, firing hosts
  are listed under `stale_hosts` in `/healthz`, and every transition
  appends an `alert` event to the fleet document's `events` section —
  the one record the silent host cannot write itself (ISSUE 16).

Usage: python tools/push_receiver.py --port 9200 --out fleet.json

The class is importable (`PushReceiver`) for tests and smoke tools;
`--port 0` binds an ephemeral port (printed on stdout).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from quorum_tpu.telemetry.registry import atomic_write  # noqa: E402


def merge_fleet(docs_by_host: dict) -> dict:
    """The fleet document: merge_host_docs over the per-host finals in
    sorted host-id order (deterministic shard keys), stamped as a
    pushed fleet aggregate."""
    from quorum_tpu.parallel.multihost import merge_host_docs
    hosts = sorted(docs_by_host)
    merged = merge_host_docs([docs_by_host[h] for h in hosts])
    # re-key the shards by the pushed identity (merge_host_docs keys
    # by list position, which is meaningless here)
    merged["hosts"] = {h: docs_by_host[h] for h in hosts}
    merged["meta"]["fleet"] = True
    merged["meta"]["fleet_hosts"] = hosts
    return merged


def _dedupe_type_lines(texts: list[str]) -> str:
    """Concatenate per-host expositions keeping each `# TYPE` header
    once (scrapers reject duplicates)."""
    seen: set[str] = set()
    out: list[str] = []
    for text in texts:
        for line in text.splitlines():
            if line.startswith("# TYPE"):
                if line in seen:
                    continue
                seen.add(line)
            out.append(line)
    return "\n".join(out) + ("\n" if out else "")


class PushReceiver:
    """The aggregating HTTP listener. Thread-safe; daemon threads."""

    def __init__(self, out_path: str | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = True,
                 stale_after_s: float | None = None):
        import http.server

        self.out_path = out_path
        self._lock = threading.Lock()
        self._texts: dict[str, str] = {}      # host -> latest prom text
        self._finals: dict[str, dict] = {}    # host -> final document
        self._last_seen: dict[str, float] = {}  # host -> last push t
        self._fleet: dict | None = None
        self.pushes = 0
        self.final_pushes = 0
        self._t0 = time.perf_counter()
        # fleet staleness alerting (ISSUE 16): absence-rule semantics
        # from telemetry/alerts.py — a host ARMS on its first push
        # (only hosts in _last_seen are watched), FIRES once silent
        # past the threshold, HEALS when it pushes again; each
        # transition appends one alert-shaped event that rides the
        # fleet document (the silent host cannot write it itself)
        self.stale_after_s = (float(stale_after_s)
                              if stale_after_s else None)
        self._stale: dict[str, bool] = {}     # host -> firing
        self._alert_events: list[dict] = []
        self._stop = threading.Event()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _body(self) -> bytes | None:
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    n = -1
                if n < 0 or n > 64 * 1024 * 1024:
                    self.close_connection = True
                    self._reply(400, b'{"error": "bad Content-Length"}\n')
                    return None
                return self.rfile.read(n)

            def do_POST(self):  # noqa: N802 - http.server API
                # a bare-root --metrics-push-url maps '' -> /push and
                # its terminal flush '/final' -> /push/final: accepting
                # one but 404ing the other would drop every FINAL doc
                # of a misconfigured-but-working pusher
                route = self.path.split("?")[0].rstrip("/") or "/push"
                if route == "/final":
                    route = "/push/final"
                body = self._body()
                if body is None:
                    return
                hid = self.headers.get("X-Quorum-Host", "unknown")
                if route == "/push":
                    outer._on_text(hid, body)
                    self._reply(200, b'{"status": "ok"}\n')
                elif route == "/push/final":
                    try:
                        doc = json.loads(body.decode() or "{}")
                        if not isinstance(doc, dict):
                            raise ValueError("final doc must be an object")
                    except (ValueError, UnicodeDecodeError) as e:
                        self._reply(400, (json.dumps(
                            {"error": str(e)}) + "\n").encode())
                        return
                    outer._on_final(hid, doc)
                    self._reply(200, b'{"status": "ok"}\n')
                else:
                    self._reply(404, b'{"error": "not found"}\n')

            def do_GET(self):  # noqa: N802 - http.server API
                route = self.path.split("?")[0]
                if route == "/metrics":
                    with outer._lock:
                        texts = [outer._texts[h]
                                 for h in sorted(outer._texts)]
                    body = (_dedupe_type_lines(texts)
                            + outer._own_metrics_text())
                    self._reply(200, body.encode(),
                                "text/plain; version=0.0.4; "
                                "charset=utf-8")
                elif route == "/fleet":
                    with outer._lock:
                        fleet = outer._fleet
                    if fleet is None:
                        self._reply(404,
                                    b'{"error": "no final pushes yet"}\n')
                    else:
                        self._reply(200, (json.dumps(fleet, indent=1)
                                          + "\n").encode())
                elif route == "/healthz":
                    body = json.dumps(outer.health()) + "\n"
                    self._reply(200, body.encode())
                else:
                    self._reply(404, b'{"error": "not found"}\n')

            def log_message(self, fmt, *args):
                if not quiet:
                    sys.stderr.write("push_receiver: "
                                     + (fmt % args) + "\n")

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="quorum-push-receiver", daemon=True)
        self._thread.start()
        self._ticker = None
        if self.stale_after_s is not None:
            self._ticker = threading.Thread(
                target=self._stale_loop,
                name="quorum-push-staleness", daemon=True)
            self._ticker.start()

    # -- push handling ----------------------------------------------------
    def _on_text(self, host_id: str, body: bytes) -> None:
        with self._lock:
            self._texts[host_id] = body.decode(errors="replace")
            self._last_seen[host_id] = time.perf_counter()
            self.pushes += 1

    def _on_final(self, host_id: str, doc: dict) -> None:
        with self._lock:
            self._finals[host_id] = doc
            self._last_seen[host_id] = time.perf_counter()
            self.final_pushes += 1
            self._rebuild_fleet_locked()

    def _rebuild_fleet_locked(self) -> None:
        """Re-merge and re-write the fleet document (caller holds the
        lock): the alert-event ledger rides every snapshot, so a host
        that went stale AFTER its final push still shows in the
        on-disk document."""
        if not self._finals:
            return
        fleet = merge_fleet(self._finals)
        if self._alert_events:
            fleet["events"] = [dict(e) for e in self._alert_events]
        self._fleet = fleet
        # write INSIDE the lock: ThreadingHTTPServer handles
        # concurrent finals, and a stale snapshot written last
        # would silently drop the other host from the on-disk doc
        if self.out_path:
            atomic_write(self.out_path,
                         json.dumps(fleet, indent=1) + "\n")

    # -- staleness alerting (ISSUE 16) ------------------------------------
    def _check_stale_locked(self, now: float) -> bool:
        """One absence-rule evaluation over every armed host (caller
        holds the lock). Returns True when any host transitioned
        (fired or healed) — the signal to re-write the fleet doc."""
        if self.stale_after_s is None:
            return False
        changed = False
        for h, last in self._last_seen.items():
            age = now - last
            firing = age > self.stale_after_s
            if firing == self._stale.get(h, False):
                continue
            changed = True
            self._stale[h] = firing
            state = "firing" if firing else "healed"
            detail = (f"no push for {age:.1f}s "
                      f"(> {self.stale_after_s:g}s)" if firing
                      else "pushing again")
            self._alert_events.append({
                "event": "alert", "t": round(now - self._t0, 3),
                "rule": "fleet_host_stale", "state": state,
                "host": h, "value": round(age, 3),
                "detail": detail, "severity": "warn"})
        return changed

    def _stale_loop(self) -> None:
        """The staleness ticker: absence rules need a clock, not a
        push — the whole point is noticing the push that DIDN'T
        come."""
        interval = max(0.05, min(1.0, self.stale_after_s / 4.0))
        while not self._stop.wait(interval):
            with self._lock:
                if self._check_stale_locked(time.perf_counter()):
                    self._rebuild_fleet_locked()

    # -- introspection ----------------------------------------------------
    def doc_ages(self) -> dict[str, float]:
        """Per-host seconds since the last push of ANY kind — the
        fleet-level staleness signal (ISSUE 11): a host that stopped
        pushing is invisible in its own (absent) document, so the
        RECEIVER is where its silence shows. Pairs with an absence
        alert rule watching the receiver's exposition."""
        now = time.perf_counter()
        with self._lock:
            return {h: round(now - t, 3)
                    for h, t in sorted(self._last_seen.items())}

    def health(self) -> dict:
        ages = self.doc_ages()
        with self._lock:
            h = {
                "status": "ok",
                "uptime_s": round(time.perf_counter() - self._t0, 3),
                "hosts": len(self._texts),
                "final_hosts": len(self._finals),
                "pushes": self.pushes,
                # a silent host is visible here long before any
                # scraper notices its series went stale
                "doc_age_s": ages,
            }
            if self.stale_after_s is not None:
                # evaluate NOW so the answer is current, and re-write
                # the fleet doc on a transition — whichever observer
                # (ticker, scrape, healthz) sees it first must not
                # strand the alert event off-disk
                if self._check_stale_locked(time.perf_counter()):
                    self._rebuild_fleet_locked()
                h["stale_after_s"] = self.stale_after_s
                h["stale_hosts"] = sorted(
                    host for host, firing in self._stale.items()
                    if firing)
            return h

    def _own_metrics_text(self) -> str:
        """The receiver's OWN gauges, appended to the fleet
        exposition: per-host staleness + host counts, so one scrape
        of the receiver answers 'which host went quiet' without the
        fleet document."""
        lines = ["# TYPE quorum_tpu_push_doc_age_seconds gauge"]
        for h, age in self.doc_ages().items():
            hv = h.replace("\\", "\\\\").replace('"', '\\"')
            lines.append(
                f'quorum_tpu_push_doc_age_seconds{{host="{hv}"}} {age}')
        with self._lock:
            if self.stale_after_s is not None:
                # the 0/1 verdict next to the raw age: a threshold
                # rule can watch the gauge directly instead of
                # re-deriving the absence semantics from doc_age
                if self._check_stale_locked(time.perf_counter()):
                    self._rebuild_fleet_locked()
                lines.append("# TYPE fleet_host_stale gauge")
                for h in sorted(self._stale):
                    hv = h.replace("\\", "\\\\").replace('"', '\\"')
                    lines.append(
                        f'fleet_host_stale{{host="{hv}"}} '
                        f'{1 if self._stale[h] else 0}')
            lines.append("# TYPE quorum_tpu_push_hosts gauge")
            lines.append(f"quorum_tpu_push_hosts {len(self._texts)}")
            lines.append("# TYPE quorum_tpu_push_final_hosts gauge")
            lines.append(
                f"quorum_tpu_push_final_hosts {len(self._finals)}")
        return "\n".join(lines) + "\n"

    @property
    def fleet(self) -> dict | None:
        with self._lock:
            return self._fleet

    @property
    def final_hosts(self) -> list[str]:
        with self._lock:
            return sorted(self._finals)

    @property
    def alert_events(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._alert_events]

    def close(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Aggregate quorum-tpu metric pushes "
                    "(--metrics-push-url) into one fleet document")
    p.add_argument("--host", default="127.0.0.1",
                   help="Bind address (default loopback)")
    p.add_argument("--port", type=int, default=9200,
                   help="Listen port (default 9200; 0 = ephemeral)")
    p.add_argument("--out", metavar="path", default=None,
                   help="Re-write the aggregated fleet document here "
                        "after every final push (atomic replace)")
    p.add_argument("--stale-after-s", type=float, default=None,
                   metavar="S",
                   help="Fire a per-host fleet_host_stale{host=} "
                        "gauge (and an alert event in the fleet "
                        "document) when a host that has pushed "
                        "before goes silent for more than S seconds "
                        "(absence-rule semantics: arm on first push, "
                        "fire past the threshold, heal on return)")
    args = p.parse_args(argv)

    rx = PushReceiver(out_path=args.out, host=args.host,
                      port=args.port, quiet=not args.verbose,
                      stale_after_s=args.stale_after_s)
    print(f"push_receiver: listening on {rx.host}:{rx.port}"
          + (f", fleet -> {args.out}" if args.out else ""), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        rx.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
