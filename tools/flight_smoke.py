#!/usr/bin/env python
"""Flight-recorder smoke for CI (ISSUE 16, ci/tier1.sh): the black
box must dump when a run dies, stay silent when it doesn't, and cost
~nothing while it waits.

Four gates in one tool:

1. **Steady state**: a clean golden database build with the recorder
   on (its default) must produce ZERO flight dumps — no
   ``*.flight.json`` sibling, ``flight_dumps_total`` 0 in the final
   document (which declares ``meta.flight``, so metrics_check
   requires the contract counters to be present at all).

2. **Overhead A/B**: the same build timed recorder-on vs
   ``QUORUM_FLIGHT=0``, emitted as a BENCH metric line
   (``flight_overhead``: ``base_ms`` / ``flight_ms`` /
   ``overhead_ratio``) into ``flight_ab.json`` for the perf-diff gate
   — PERF_BASELINE.json bounds the ratio ABSOLUTELY (machine-
   independent), so a recorder that starts costing real time fails CI
   like a throughput cliff.

3. **Seeded crash**: the golden build killed by a fault-plan
   ``error`` at ``stage1.insert`` must exit nonzero AND leave exactly
   one sealed dump (``<metrics>.flight.json``) that passes the
   schema/seal validation via tools/metrics_check.py, whose trigger
   records the dying run (kind ``error``) and whose ring holds the
   ``fault`` breadcrumb naming ``stage1.insert`` — the black box
   pinpoints the site that killed the run. ``trace_summary --flight``
   must render it (timeline + triggering thread).

4. **Postmortem bundle**: ``quorum-debug-bundle`` over the crash
   dump + error document + the steady run's database must produce a
   tarball whose sealed manifest validates, classifies the artifacts
   (flight/metrics), and carries a quorum-fsck verdict + config.

Artifacts land in --out-dir:
  steady_metrics.json        — the clean run (metrics_check gates it)
  crash_metrics.json         — the killed run's error document
  crash_metrics.flight.json  — the black-box dump (metrics_check
                               gates it by schema)
  flight_ab.json             — the overhead metric line (perf_diff
                               judges it against PERF_BASELINE.json)

Exit 0 = all gates held.
"""

from __future__ import annotations

import argparse
import contextlib
import importlib.util
import io
import json
import os
import sys
import tarfile
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def _fail(msg: str) -> int:
    print(f"[flight_smoke] FAIL: {msg}", file=sys.stderr)
    return 1


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Flight-recorder smoke: zero dumps on a clean "
                    "golden run, a sealed pinpointing dump on a "
                    "seeded stage1.insert crash, bounded ring "
                    "overhead (A/B), and a debug-bundle round trip "
                    "(ci/tier1.sh gate)")
    p.add_argument("--out-dir", default=None,
                   help="Artifact directory (default: a temp dir)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="flight_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import debug_bundle
    from quorum_tpu.telemetry import schema as schema_mod
    from quorum_tpu.utils import faults

    mc = _load_tool("metrics_check")
    ts = _load_tool("trace_summary")
    reads = os.path.join(GOLDEN, "reads.fastq")
    cdb_args = ["-s", "64k", "-m", "13", "-b", "7", "-q", "38"]

    def build(db: str, metrics: str | None) -> int:
        a = list(cdb_args) + ["-o", db]
        if metrics:
            a += ["--metrics", metrics]
        return cdb_cli.main(a + [reads])

    # -- gate 1: steady state — a clean run must not dump -------------------
    print("[flight_smoke] gate 1: clean golden build (recorder on)")
    db = os.path.join(out_dir, "db.jf")
    steady_metrics = os.path.join(out_dir, "steady_metrics.json")
    if build(db, steady_metrics) != 0:
        return _fail("gate 1: clean build failed")
    steady_dump = steady_metrics[:-len(".json")] + ".flight.json"
    if os.path.exists(steady_dump):
        return _fail(f"gate 1: clean run dumped: {steady_dump}")
    with open(steady_metrics) as f:
        doc = json.load(f)
    if doc.get("meta", {}).get("flight") is not True:
        return _fail("gate 1: final document does not declare "
                     "meta.flight")
    if doc.get("counters", {}).get("flight_dumps_total") != 0:
        return _fail("gate 1: flight_dumps_total="
                     f"{doc.get('counters', {}).get('flight_dumps_total')}"
                     " (want 0 on a clean run)")
    if mc.main([steady_metrics]) != 0:
        return _fail("gate 1: metrics_check rejected the steady doc")

    # -- gate 2: overhead A/B — the ring must be ~free ----------------------
    # gate 1 was the warmup: it paid the JIT compile, so both timed
    # builds below hit a warm cache and measure the recorder alone.
    # Absolute ratio bounds live in PERF_BASELINE.json: wall clock is
    # machine-dependent, the RATIO is not.
    print("[flight_smoke] gate 2: overhead A/B (QUORUM_FLIGHT=0 base)")
    t0 = time.perf_counter()
    rc = build(os.path.join(out_dir, "db_flight.jf"), None)
    flight_ms = (time.perf_counter() - t0) * 1e3
    if rc != 0:
        return _fail("gate 2: recorder-on build failed")
    prev = os.environ.get("QUORUM_FLIGHT")
    os.environ["QUORUM_FLIGHT"] = "0"
    try:
        t0 = time.perf_counter()
        rc = build(os.path.join(out_dir, "db_base.jf"), None)
        base_ms = (time.perf_counter() - t0) * 1e3
    finally:
        if prev is None:
            os.environ.pop("QUORUM_FLIGHT", None)
        else:
            os.environ["QUORUM_FLIGHT"] = prev
    if rc != 0:
        return _fail("gate 2: QUORUM_FLIGHT=0 build failed")
    ab_path = os.path.join(out_dir, "flight_ab.json")
    line = {"metric": "flight_overhead",
            "base_ms": round(base_ms, 3),
            "flight_ms": round(flight_ms, 3),
            "overhead_ratio": round(flight_ms / base_ms, 4)}
    with open(ab_path, "w") as f:
        f.write(json.dumps(line) + "\n")
    print(f"[flight_smoke] gate 2: base={base_ms:.0f}ms "
          f"flight={flight_ms:.0f}ms "
          f"ratio={line['overhead_ratio']:.3f}")
    if mc.main(["--require-metric", "flight_overhead", ab_path]) != 0:
        return _fail("gate 2: metrics_check rejected flight_ab.json")

    # -- gate 3: seeded crash — the black box must pinpoint it --------------
    print("[flight_smoke] gate 3: fault-plan error at stage1.insert")
    crash_metrics = os.path.join(out_dir, "crash_metrics.json")
    faults.install(faults.FaultPlan.parse(
        {"site": "stage1.insert", "action": "error"}), "flight-smoke")
    try:
        rc = build(os.path.join(out_dir, "db_crash.jf"), crash_metrics)
    finally:
        faults.reset()
    if rc == 0:
        return _fail("gate 3: the seeded crash run succeeded")
    dump_path = crash_metrics[:-len(".json")] + ".flight.json"
    if not os.path.exists(dump_path):
        return _fail(f"gate 3: no flight dump at {dump_path}")
    with open(dump_path) as f:
        fdoc = json.load(f)
    errs = schema_mod.validate_flight_dump(fdoc)
    if errs:
        return _fail(f"gate 3: dump invalid: {errs[:3]}")
    trig = fdoc.get("trigger", {})
    if trig.get("kind") != "error":
        return _fail(f"gate 3: trigger kind {trig.get('kind')!r} "
                     "(want 'error': a run that exited "
                     "status=error)")
    # the ring's fault breadcrumb is the pinpoint: the site that
    # killed the run, recorded by the injection itself
    hits = [e for e in fdoc.get("ring", [])
            if e.get("kind") == "fault"
            and e.get("name") == "stage1.insert"]
    if not hits:
        return _fail("gate 3: ring carries no fault breadcrumb for "
                     "stage1.insert")
    if not any(t.get("tid") == hits[-1].get("tid")
               for t in fdoc.get("threads", [])):
        return _fail("gate 3: dump lacks the faulting thread's stack")
    if mc.main([dump_path]) != 0:
        return _fail("gate 3: metrics_check rejected the dump")
    with open(crash_metrics) as f:
        cdoc = json.load(f)
    if cdoc.get("counters", {}).get("flight_dumps_total") != 1:
        return _fail("gate 3: error doc flight_dumps_total="
                     f"{cdoc.get('counters', {}).get('flight_dumps_total')}"
                     " (want exactly 1)")
    if mc.main([crash_metrics]) != 0:
        return _fail("gate 3: metrics_check rejected the error doc")
    # the operator view must render: timeline + triggering thread
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = ts.main(["--flight", dump_path])
    text = buf.getvalue()
    if rc != 0:
        return _fail(f"gate 3: trace_summary --flight rc={rc}")
    if "stage1.insert" not in text or "trigger" not in text.lower():
        return _fail("gate 3: trace_summary --flight render lacks "
                     "the trigger/fault site")
    print("[flight_smoke] gate 3: dump pinpoints stage1.insert "
          f"({len(fdoc.get('ring', []))} ring entries)")

    # -- gate 4: postmortem bundle round trip -------------------------------
    print("[flight_smoke] gate 4: quorum-debug-bundle round trip")
    bundle = os.path.join(out_dir, "postmortem.tar.gz")
    rc = debug_bundle.main([dump_path, crash_metrics,
                            "--db", db, "--out", bundle, "-q"])
    if rc != 0:
        return _fail(f"gate 4: quorum-debug-bundle rc={rc}")
    with tarfile.open(bundle) as tar:
        names = tar.getnames()
        mf = tar.extractfile("MANIFEST.json")
        manifest = json.load(mf)
    errs = schema_mod.validate_debug_bundle_manifest(manifest)
    if errs:
        return _fail(f"gate 4: manifest invalid: {errs[:3]}")
    kinds = {e["kind"] for e in manifest["files"]}
    if not {"flight", "metrics", "fsck", "config"} <= kinds:
        return _fail(f"gate 4: manifest kinds {sorted(kinds)} "
                     "(want flight/metrics/fsck/config)")
    by_kind = {e["kind"]: e for e in manifest["files"]}
    if by_kind["flight"]["problems"] != 0:
        return _fail("gate 4: the collected dump was flagged "
                     f"({by_kind['flight']['problems']} problems)")
    if by_kind["fsck"]["exit_status"] != 0:
        return _fail("gate 4: fsck verdict nonzero on the clean db")
    missing = [e["name"] for e in manifest["files"]
               if e["name"] not in names]
    if missing:
        return _fail(f"gate 4: manifest names absent files: {missing}")

    print(f"[flight_smoke] OK: silent when clean, pinpointing when "
          f"killed; artifacts -> {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
