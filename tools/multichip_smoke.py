#!/usr/bin/env python
"""Golden 2-device CPU-mesh run for CI (ci/tier1.sh): the ISSUE 5
acceptance properties, end to end, on the committed golden reads.

1. Run the `quorum` driver at `--devices 1` and `--devices 2` over
   tests/golden/reads.fastq (2-device mesh via
   XLA_FLAGS=--xla_force_host_platform_device_count, which the CI
   wrapper sets) and assert the corrected `.fa`/`.log` outputs are
   BYTE-IDENTICAL — scale-out must never change the answer.
2. Hard-kill (`os._exit` fault plan, real subprocess) a sharded
   stage-1 build mid-run with per-batch checkpoints, resume it with
   `--resume`, and assert the finished database's table payload is
   byte-identical to an uninterrupted sharded build — every shard
   restored at the same cursor.
3. Leave the sharded run's telemetry in --out-dir for the
   metrics_check gates that follow:
     multichip_metrics.stage1.json — sharded stage-1 document (the
       per-shard insert/occupancy counter requirements)
     multichip_metrics.hosts.json  — the driver's aggregated document
       (parallel/multihost.aggregate_metrics)

Exit 0 = all checks passed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")

KILL_CODE = 43
BATCH_SIZE = 64  # 242 golden reads -> 4 batches


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Golden 2-device mesh run: --devices 2 byte parity "
                    "vs --devices 1 plus a sharded stage-1 kill/resume "
                    "(ci/tier1.sh gate)")
    p.add_argument("--out-dir", default=None,
                   help="Where the work files and metrics land "
                        "(default: a temp dir)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="multichip_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    import jax
    if len(jax.devices()) < 2:
        print("[multichip_smoke] FAIL: need >= 2 devices (set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=2+ "
              "before importing jax)", file=sys.stderr)
        return 1

    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import quorum as quorum_cli

    reads = os.path.join(GOLDEN, "reads.fastq")
    metrics_path = os.path.join(out_dir, "multichip_metrics.json")

    # -- driver parity: --devices 2 output == --devices 1 output ------
    outputs = {}
    for dev in ("1", "2"):
        prefix = os.path.join(out_dir, f"corrected_d{dev}")
        argv_d = ["-s", "64k", "-k", "13", "-p", prefix,
                  "--batch-size", str(BATCH_SIZE), "--devices", dev]
        if dev == "2":
            argv_d += ["--metrics", metrics_path]
        print(f"[multichip_smoke] quorum --devices {dev}")
        rc = quorum_cli.main(argv_d + [reads])
        if rc != 0:
            print(f"[multichip_smoke] FAIL: driver rc {rc} at "
                  f"--devices {dev}", file=sys.stderr)
            return 1
        outputs[dev] = (open(prefix + ".fa", "rb").read(),
                        open(prefix + ".log", "rb").read())
    if outputs["1"] != outputs["2"]:
        print("[multichip_smoke] FAIL: --devices 2 output differs "
              "from --devices 1 (must be byte-identical)",
              file=sys.stderr)
        return 1
    print(f"[multichip_smoke] parity OK "
          f"({len(outputs['1'][0])} fa bytes)")

    # -- sharded stage-1 kill -> resume -> identical database ---------
    ckdir = os.path.join(out_dir, "ck")
    ref_db = os.path.join(out_dir, "ref_db.jf")
    db = os.path.join(out_dir, "resumed_db.jf")
    cdb_args = ["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                "--batch-size", str(BATCH_SIZE), "--devices", "2"]
    rc = cdb_cli.main(cdb_args + ["-o", ref_db, reads])
    if rc != 0:
        print("[multichip_smoke] FAIL: reference sharded build",
              file=sys.stderr)
        return 1
    plan = json.dumps([{"site": "stage1.insert", "batch": 2,
                        "action": "exit", "code": KILL_CODE}])
    env = dict(os.environ, QUORUM_FAULT_PLAN=plan)
    print(f"[multichip_smoke] killed sharded build (fault plan: {plan})")
    res = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.create_database"]
        + cdb_args + ["-o", db, "--checkpoint-dir", ckdir,
                      "--checkpoint-every", "1", reads],
        cwd=REPO, env=env)
    if res.returncode != KILL_CODE:
        print(f"[multichip_smoke] FAIL: killed run exited "
              f"{res.returncode}, want {KILL_CODE}", file=sys.stderr)
        return 1
    manifest = os.path.join(ckdir, "stage1.sharded.json")
    if not os.path.exists(manifest):
        print("[multichip_smoke] FAIL: no sharded manifest after the "
              "kill", file=sys.stderr)
        return 1
    cursor = json.load(open(manifest))["cursor"]
    print(f"[multichip_smoke] killed at batch 2; manifest committed "
          f"cursor {cursor}")
    rc = cdb_cli.main(cdb_args + ["-o", db, "--checkpoint-dir", ckdir,
                                  "--checkpoint-every", "1", "--resume",
                                  "--fault-plan", "", reads])
    if rc != 0:
        print("[multichip_smoke] FAIL: sharded resume rc", rc,
              file=sys.stderr)
        return 1
    # headers carry a timestamp (and the v5 trailer digests them);
    # the table payload proper is the invariant
    from quorum_tpu.io.db_format import db_payload_bytes
    ref = db_payload_bytes(ref_db)
    got = db_payload_bytes(db)
    if ref != got:
        print("[multichip_smoke] FAIL: resumed sharded database "
              "differs from uninterrupted build", file=sys.stderr)
        return 1
    if os.path.exists(manifest):
        print("[multichip_smoke] FAIL: manifest survived the finished "
              "build", file=sys.stderr)
        return 1

    s1 = json.load(open(os.path.join(
        out_dir, "multichip_metrics.stage1.json")))
    if int(s1.get("gauges", {}).get("n_shards", 0)) != 2:
        print("[multichip_smoke] FAIL: stage-1 document does not "
              "report n_shards=2", file=sys.stderr)
        return 1

    # -- sharded on-disk layout (ISSUE 9): no gather, same payload ----
    sharded_db = os.path.join(out_dir, "sharded_layout_db.jf")
    rc = cdb_cli.main(cdb_args + ["-o", sharded_db,
                                  "--db-layout", "sharded", reads])
    if rc != 0:
        print("[multichip_smoke] FAIL: sharded-layout build rc", rc,
              file=sys.stderr)
        return 1
    from quorum_tpu.io.db_format import (MANIFEST_FORMAT, read_header,
                                         shard_file_name)
    if read_header(sharded_db).get("format") != MANIFEST_FORMAT:
        print("[multichip_smoke] FAIL: sharded layout did not write "
              "a manifest", file=sys.stderr)
        return 1
    for s in range(2):
        if not os.path.exists(shard_file_name(sharded_db, s, 2)):
            print(f"[multichip_smoke] FAIL: shard file {s} missing",
                  file=sys.stderr)
            return 1
    if db_payload_bytes(sharded_db) != ref:
        print("[multichip_smoke] FAIL: --db-layout=sharded payload "
              "differs from the single-file layout (must reassemble "
              "byte-identical)", file=sys.stderr)
        return 1
    print("[multichip_smoke] sharded layout OK: manifest + 2 shards, "
          "payload byte-identical to single-file")

    print("[multichip_smoke] OK: 2-device parity, sharded kill/resume "
          f"byte-identical; metrics -> {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
