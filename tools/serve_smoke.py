#!/usr/bin/env python
"""Golden serve run for CI (ci/tier1.sh): build the mer database from
the committed golden reads, start quorum-serve in-process, POST the
golden reads twice, and verify the acceptance properties of ISSUE 3:

  * the response is byte-identical to tests/golden/expected.fa (the
    offline CLI's output at -p 4),
  * the second (warm) request recompiles nothing
    (`engine_compiles` stays flat),
  * a graceful drain (POST /quiesce) writes the final metrics
    document and a Prometheus scrape of the serving port's /metrics.

Artifacts land in --out-dir (default: a temp dir):
  serve_metrics.json  — the final serve document
                        (`metrics_check.py` gates it, including the
                        serve metric names)
  serve_scrape.prom   — a /metrics scrape taken mid-run
                        (`metrics_check.py --prom` gates it)

Exit 0 = all checks passed. Run by ci/tier1.sh after the tier-1
pytest pass; usable by hand for a quick serving sanity check.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Golden serve run: parity, warm no-recompile, "
                    "drain-with-metrics (ci/tier1.sh gate)")
    p.add_argument("--out-dir", default=None,
                   help="Where serve_metrics.json / serve_scrape.prom "
                        "land (default: a temp dir)")
    p.add_argument("--rows", type=int, default=64,
                   help="Engine batch rows (default 64: fast CPU "
                        "compile; production uses 1024+)")
    args = p.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="serve_smoke_")
    os.makedirs(out_dir, exist_ok=True)

    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.cli import serve as serve_cli
    from quorum_tpu.serve.client import ServeClient

    reads = os.path.join(GOLDEN, "reads.fastq")
    expected_fa = os.path.join(GOLDEN, "expected.fa")
    db = os.path.join(out_dir, "db.jf")
    metrics_path = os.path.join(out_dir, "serve_metrics.json")
    scrape_path = os.path.join(out_dir, "serve_scrape.prom")

    print(f"[serve_smoke] building golden database -> {db}")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, reads])
    if rc != 0:
        print("[serve_smoke] FAIL: database build", file=sys.stderr)
        return 1

    # run the real quorum-serve CLI on an ephemeral-ish port in a
    # thread; drain over HTTP when done so its final metrics land
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rc_box = {}

    def run_server():
        rc_box["rc"] = serve_cli.main(
            ["--port", str(port), "--max-batch", str(args.rows),
             "--max-wait-ms", "2", "-p", "4",
             "--metrics", metrics_path, db])

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    client = ServeClient(port=port, timeout=900.0)
    deadline = time.perf_counter() + 30
    while True:
        try:
            client.healthz()
            break
        except OSError:
            if time.perf_counter() > deadline:
                print("[serve_smoke] FAIL: server never came up",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)

    with open(reads) as f:
        body = f.read()
    with open(expected_fa) as f:
        want_fa = f.read()

    print("[serve_smoke] cold request (compiles the length bucket)")
    t0 = time.perf_counter()
    r1 = client.correct(body)
    cold_s = time.perf_counter() - t0
    if r1.status != 200 or r1.fa != want_fa:
        print(f"[serve_smoke] FAIL: cold request status={r1.status} "
              f"parity={'ok' if r1.fa == want_fa else 'DRIFT'}",
              file=sys.stderr)
        return 1
    compiles1 = client.healthz()["engine_compiles"]

    print("[serve_smoke] warm request")
    t0 = time.perf_counter()
    r2 = client.correct(body)
    warm_s = time.perf_counter() - t0
    compiles2 = client.healthz()["engine_compiles"]
    if r2.status != 200 or r2.fa != want_fa:
        print("[serve_smoke] FAIL: warm request parity",
              file=sys.stderr)
        return 1
    if compiles2 != compiles1:
        print(f"[serve_smoke] FAIL: warm request recompiled "
              f"({compiles1} -> {compiles2})", file=sys.stderr)
        return 1

    with open(scrape_path, "w") as f:
        f.write(client.metrics_text())
    print(f"[serve_smoke] scraped /metrics -> {scrape_path}")

    print("[serve_smoke] draining via /quiesce")
    client.quiesce()
    t.join(timeout=60)
    if t.is_alive() or rc_box.get("rc") != 0:
        print(f"[serve_smoke] FAIL: drain (alive={t.is_alive()} "
              f"rc={rc_box.get('rc')})", file=sys.stderr)
        return 1
    if not os.path.exists(metrics_path):
        print("[serve_smoke] FAIL: no final metrics document",
              file=sys.stderr)
        return 1
    print(f"[serve_smoke] OK: parity x2, cold {cold_s:.1f}s, warm "
          f"{warm_s:.2f}s, compiles flat at {compiles2}, final "
          f"metrics -> {metrics_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
