"""Memory-frugal counting (ISSUE 14): the singleton prefilter
(ops/sketch) and the minimizer-partitioned multi-pass build.

Covers the two load-bearing guarantees:

* the two-pass prefiltered table is EXACTLY the full table minus true
  singletons (plus counted false passes), and stage 2 over it is
  byte-identical to the unfiltered run at the same presence floor;
* a --partitions P build's reassembled payload is byte-identical to
  the single-pass build — including under --devices 2 and across a
  hard kill -> resume that re-runs only the torn partition.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from quorum_tpu.io import db_format, packing
from quorum_tpu.models.create_database import extract_observations
from quorum_tpu.ops import ctable, mer
from quorum_tpu.ops import sketch as sketch_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

K = 15
READ_LEN = 80
N_READS = 512
BATCH = 256
QT = 38


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# shared input + built databases (module scope: the CLI builds compile
# once and every test reads them)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def reads():
    rng = np.random.default_rng(17)
    genome = rng.integers(0, 4, size=5000, dtype=np.int8)
    starts = rng.integers(0, len(genome) - READ_LEN, size=N_READS)
    idx = starts[:, None] + np.arange(READ_LEN)[None, :]
    truth = genome[idx]
    errs = rng.random(truth.shape) < 0.01
    codes = np.where(errs, (truth + rng.integers(
        1, 4, size=truth.shape)) % 4, truth).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    return codes, quals


@pytest.fixture(scope="module")
def fastq_file(reads, tmp_path_factory):
    from bench import write_fastq
    d = tmp_path_factory.mktemp("memfrugal")
    fq = str(d / "reads.fastq")
    write_fastq(fq, reads[0], reads[1])
    return fq


def _cdb(args):
    from quorum_tpu.cli import create_database as cdb_cli
    return cdb_cli.main(args)


_COMMON = ["-s", "100k", "-m", str(K), "-b", "7", "-q", str(QT),
           "--batch-size", str(BATCH)]


@pytest.fixture(scope="module")
def plain_db(fastq_file, tmp_path_factory):
    d = tmp_path_factory.mktemp("plain")
    out = str(d / "plain.qdb")
    assert _cdb(_COMMON + ["-o", out, fastq_file]) == 0
    return out


@pytest.fixture(scope="module")
def prefiltered_db(fastq_file, tmp_path_factory):
    d = tmp_path_factory.mktemp("pf")
    out = str(d / "pf.qdb")
    metrics = str(d / "pf_metrics.json")
    assert _cdb(_COMMON + ["-o", out, "--prefilter", "two-pass",
                           "--metrics", metrics,
                           "--metrics-interval", "0.001",
                           fastq_file]) == 0
    return out, metrics


@pytest.fixture(scope="module")
def partitioned_db(fastq_file, tmp_path_factory):
    d = tmp_path_factory.mktemp("part")
    out = str(d / "part.qdb")
    metrics = str(d / "part_metrics.json")
    assert _cdb(_COMMON + ["-o", out, "--partitions", "4",
                           "--metrics", metrics,
                           "--metrics-interval", "0.001",
                           fastq_file]) == 0
    return out, metrics


@pytest.fixture(scope="module")
def obs(reads):
    """Host truth: every valid canonical observation + totals."""
    codes, quals = reads
    chi, clo, q, valid = (np.asarray(a) for a in extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), K, QT))
    keys = (chi.astype(np.uint64) << 32) | clo.astype(np.uint64)
    vm = valid.astype(bool)
    return keys[vm], q[vm]


# ---------------------------------------------------------------------------
# sketch unit tests
# ---------------------------------------------------------------------------


def test_sketch_never_undercounts(obs):
    keys, q = obs
    smeta = sketch_mod.SketchMeta(cells_log2=16)
    sk = sketch_mod.make_sketch(smeta)
    # three uneven batch splits exercise cross-batch accumulation
    cuts = [0, len(keys) // 3, len(keys) // 2, len(keys)]
    for a, b in zip(cuts, cuts[1:]):
        hq = jnp.asarray((q[a:b] == 1).astype(np.uint32))
        lq = jnp.asarray((q[a:b] == 0).astype(np.uint32))
        chi = jnp.asarray((keys[a:b] >> 32).astype(np.uint32))
        clo = jnp.asarray((keys[a:b] & 0xFFFFFFFF).astype(np.uint32))
        u = sketch_mod._distinct_lanes(chi, clo, hq, lq,
                                       jnp.ones((b - a,), bool))
        sk = sketch_mod._sketch_update_lanes(sk, smeta, u[0], u[1],
                                             u[2] + u[3], u[4])
    uk, cnt = np.unique(keys, return_counts=True)
    vals = np.asarray(sketch_mod.sketch_min(
        sk, smeta, jnp.asarray((uk >> 32).astype(np.uint32)),
        jnp.asarray((uk & 0xFFFFFFFF).astype(np.uint32))))
    # the count-min invariant: never below min(2, true count)
    assert int((vals < np.minimum(cnt, 2)).sum()) == 0
    # and a meaningfully small false-pass rate at this density
    singles = cnt == 1
    assert (vals[singles] >= 2).mean() < 0.25


def test_sketch_geometry_lever(monkeypatch):
    monkeypatch.setenv("QUORUM_SKETCH_BITS", "18")
    assert sketch_mod.cells_log2_for(10 ** 9) == 18
    monkeypatch.delenv("QUORUM_SKETCH_BITS")
    auto = sketch_mod.cells_log2_for(1 << 20)
    assert auto == 23  # 8 cells per expected distinct mer
    assert sketch_mod.cells_log2_for(10 ** 12) == 30  # clamped


def test_two_pass_gate_is_exact(reads, obs):
    """The gated insert drops EXACTLY the observations whose mer the
    sketch scored < 2 — and every kept mer keeps exact counts."""
    codes, quals = reads
    keys, q = obs
    lengths = np.full((N_READS,), READ_LEN, np.int32)
    pk = packing.pack_reads(codes, quals, lengths, thresholds=(QT,))
    smeta = sketch_mod.SketchMeta(cells_log2=18)
    sk = sketch_mod.make_sketch(smeta)
    sk, n_obs = sketch_mod.sketch_update_packed(sk, smeta, K, pk, QT)
    assert int(n_obs) == len(keys)
    meta = ctable.TileMeta(k=K, bits=7,
                           rb_log2=ctable.tile_rb_for(8192, K, 7))
    bs = ctable.make_tile_build(meta)
    bs, sk, full, _o, d_hq, d_lq = \
        sketch_mod.tile_insert_reads_packed_gated(
            bs, meta, sk, smeta, pk, QT, "two-pass")
    assert not full
    st = ctable.tile_finalize(bs, meta)
    # reference: insert observations whose mer scored >= 2
    uk = np.unique(keys)
    vals = np.asarray(sketch_mod.sketch_min(
        sk, smeta, jnp.asarray((uk >> 32).astype(np.uint32)),
        jnp.asarray((uk & 0xFFFFFFFF).astype(np.uint32))))
    gate = vals[np.searchsorted(uk, keys)] >= 2
    assert d_hq + d_lq == int((~gate).sum())
    bs2 = ctable.make_tile_build(meta)
    bs2, f2, _p = ctable.tile_insert_observations(
        bs2, meta, jnp.asarray((keys >> 32).astype(np.uint32)),
        jnp.asarray((keys & 0xFFFFFFFF).astype(np.uint32)),
        jnp.asarray(q.astype(np.uint32)), jnp.asarray(gate))
    assert not f2
    st_ref = ctable.tile_finalize(bs2, meta)

    def ent(s):
        return sorted(zip(*(a.tolist()
                            for a in ctable.tile_iterate(s, meta))))
    assert ent(st) == ent(st_ref)


def test_inline_matches_two_pass_when_roomy(reads):
    """With a collision-free sketch and quality-homogeneous input,
    inline's retro-credit makes it EXACTLY the two-pass table."""
    codes, quals = reads
    lengths = np.full((N_READS,), READ_LEN, np.int32)
    smeta = sketch_mod.SketchMeta(cells_log2=22)  # roomy: no collisions
    meta = ctable.TileMeta(k=K, bits=7,
                           rb_log2=ctable.tile_rb_for(8192, K, 7))
    tables = {}
    for mode in ("two-pass", "inline"):
        sk = sketch_mod.make_sketch(smeta)
        if mode == "two-pass":
            for i in range(0, N_READS, BATCH):
                pk = packing.pack_reads(codes[i:i + BATCH],
                                        quals[i:i + BATCH],
                                        lengths[:BATCH],
                                        thresholds=(QT,))
                sk, _n = sketch_mod.sketch_update_packed(
                    sk, smeta, K, pk, QT)
        bs = ctable.make_tile_build(meta)
        for i in range(0, N_READS, BATCH):
            pk = packing.pack_reads(codes[i:i + BATCH],
                                    quals[i:i + BATCH],
                                    lengths[:BATCH], thresholds=(QT,))
            bs, sk, full, _o, _dh, _dl = \
                sketch_mod.tile_insert_reads_packed_gated(
                    bs, meta, sk, smeta, pk, QT, mode)
            assert not full
        st = ctable.tile_finalize(bs, meta)
        tables[mode] = sorted(zip(*(
            a.tolist() for a in ctable.tile_iterate(st, meta))))
    assert tables["inline"] == tables["two-pass"]


# ---------------------------------------------------------------------------
# minimizers
# ---------------------------------------------------------------------------


def test_minimizer_host_device_parity(reads):
    codes = reads[0][:3]
    mv, kvalid = mer.minimizer_kmers(jnp.asarray(codes), K, 7)
    mv, kvalid = np.asarray(mv), np.asarray(kvalid)
    for r in range(codes.shape[0]):
        for p in range(K - 1, READ_LEN, 11):
            assert kvalid[r, p]
            seq = "".join("ACGT"[c] for c in codes[r, p - K + 1:p + 1])
            assert mer.minimizer_py(seq, 7) == int(mv[r, p])


def test_minimizer_invalid_windows():
    codes = np.full((1, 30), 2, np.int8)
    codes[0, 10] = -1  # N base
    mv, kvalid = mer.minimizer_kmers(jnp.asarray(codes), K, 7)
    mv, kvalid = np.asarray(mv), np.asarray(kvalid)
    assert not kvalid[0, :K - 1].any()       # window not filled
    assert not kvalid[0, 10:10 + K].any()    # windows holding the N
    assert kvalid[0, 10 + K]
    assert (mv[0, ~kvalid[0]] == 0xFFFFFFFF).all()
    with pytest.raises(ValueError):
        mer.minimizer_kmers(jnp.asarray(codes), K, 16)


# ---------------------------------------------------------------------------
# partitioning primitives
# ---------------------------------------------------------------------------


def test_partition_mask_disjoint_exhaustive(obs):
    keys, _q = obs
    meta = ctable.TileMeta(k=K, bits=7, rb_log2=8)
    chi = jnp.asarray((keys >> 32).astype(np.uint32))
    clo = jnp.asarray((keys & 0xFFFFFFFF).astype(np.uint32))
    owners = np.zeros(len(keys), np.int32)
    hits = np.zeros(len(keys), np.int32)
    for p in range(4):
        m = np.asarray(ctable.partition_mask(chi, clo, meta, p, 4))
        owners[m] = p
        hits += m.astype(np.int32)
    assert (hits == 1).all()  # exactly one partition owns each mer
    # ownership is a pure key function: same key -> same owner
    uk, inv = np.unique(keys, return_inverse=True)
    first = np.zeros(len(uk), np.int32)
    np.maximum.at(first, inv, owners)
    assert (owners == first[inv]).all()


def test_departition_floor_and_reassembly(reads):
    """Partition passes + departition rebase == the single global
    build, bit-for-bit after canonical row ordering; tile_floor of
    the reassembled plane equals tile_floor of the global plane."""
    codes, quals = reads
    lengths = np.full((N_READS,), READ_LEN, np.int32)
    pk = packing.pack_reads(codes, quals, lengths, thresholds=(QT,))
    P, g = 4, 2
    lmeta = ctable.TileMeta(k=K, bits=7, rb_log2=7)
    parts = []
    for p in range(P):
        bs = ctable.make_tile_build(lmeta)
        bs, full, _o = ctable.tile_insert_reads_packed(
            bs, lmeta, pk, QT, part=p, n_parts=P)
        assert not full
        st = ctable.tile_finalize(bs, lmeta)
        dp, bad = ctable.tile_departition_rows(st, lmeta, g, p)
        assert not bool(bad)
        parts.append(np.asarray(dp.rows))
    gmeta = ctable.TileMeta(k=K, bits=7, rb_log2=7 + g)
    reassembled = ctable.TileState(
        jnp.asarray(np.concatenate(parts, axis=0)))
    bsg = ctable.make_tile_build(gmeta)
    bsg, full, _o = ctable.tile_insert_reads_packed(bsg, gmeta, pk, QT)
    assert not full
    stg = ctable.tile_finalize(bsg, gmeta)
    c1 = np.asarray(ctable._canonical_rows(reassembled, gmeta).rows)
    c2 = np.asarray(ctable._canonical_rows(stg, gmeta).rows)
    assert np.array_equal(c1, c2)
    f1 = np.asarray(ctable.tile_floor(
        ctable.TileState(jnp.asarray(c1)), gmeta, 2).rows)
    f2 = np.asarray(ctable.tile_floor(
        ctable.TileState(jnp.asarray(c2)), gmeta, 2).rows)
    assert np.array_equal(f1, f2)
    # floor 1 is the identity (no copy, no change)
    assert ctable.tile_floor(stg, gmeta, 1) is stg
    # host (numpy) floor matches the device floor
    fh = ctable.tile_floor(ctable.TileState(c1.copy()), gmeta, 2)
    assert np.array_equal(np.asarray(fh.rows), f1)


# ---------------------------------------------------------------------------
# CLI pipelines
# ---------------------------------------------------------------------------


def test_partitioned_payload_parity(plain_db, partitioned_db):
    out, metrics = partitioned_db
    assert (db_format.db_payload_bytes(out)
            == db_format.db_payload_bytes(plain_db))
    h = db_format.read_header(out)
    assert h["format"] == db_format.MANIFEST_FORMAT
    assert h["n_shards"] == 4
    doc = json.load(open(metrics))
    assert doc["meta"]["partitions"] == 4
    assert doc["counters"]["partition_passes_total"] == 4
    for p in range(4):
        assert f'partition_distinct{{partition="{p}"}}' in doc["gauges"]
    # the pass-boundary events + per-pass heartbeat partitions
    events = [json.loads(ln) for ln in
              open(metrics.replace(".json", ".events.jsonl"))]
    passes = [e for e in events if e["event"] == "partition_pass"]
    assert [e["partition"] for e in passes] == [0, 1, 2, 3]
    assert all("seconds" in e and "batches" in e for e in passes)
    beats = [e for e in events if e["event"] == "heartbeat"]
    assert {e.get("partition") for e in beats} <= {0, 1, 2, 3, None}


def test_partitioned_devices2_parity(fastq_file, plain_db, tmp_path):
    out = str(tmp_path / "part_d2.qdb")
    assert _cdb(_COMMON + ["-o", out, "--partitions", "2",
                           "--devices", "2", fastq_file]) == 0
    assert (db_format.db_payload_bytes(out)
            == db_format.db_payload_bytes(plain_db))


def test_prefilter_table_and_header(plain_db, prefiltered_db, obs):
    out, metrics = prefiltered_db
    keys, q = obs
    uk, cnt = np.unique(keys, return_counts=True)
    h = db_format.read_header(out)
    hp = db_format.read_header(plain_db)
    pf = h["prefilter"]
    assert pf["mode"] == "two-pass" and pf["min_obs"] == 2
    # dropped + kept = all distinct; false passes are kept singletons
    n_singles = int((cnt == 1).sum())
    assert pf["dropped"] == n_singles - pf["false_pass"]
    assert h["n_entries"] == len(uk) - pf["dropped"]
    assert h["n_entries"] < hp["n_entries"]
    # the header's Poisson stats equal the FULL table's (all-hq input:
    # every distinct mer is an hq mer here)
    st, meta, _ = db_format.read_db(plain_db, to_device=False)
    _occ, d_hq, t_hq = (int(x) for x in db_format.db_stats(st, meta))
    assert h["poisson_stats"]["distinct_hq"] == d_hq
    assert h["poisson_stats"]["total_hq"] == t_hq
    doc = json.load(open(metrics))
    assert doc["meta"]["prefilter"] == "two-pass"
    assert doc["counters"]["prefilter_dropped_total"] == pf["dropped"]
    assert (doc["counters"]["prefilter_false_pass_total"]
            == pf["false_pass"])


def test_prefilter_stage2_parity_at_floor(plain_db, prefiltered_db,
                                          fastq_file, tmp_path):
    """THE guarantee: prefiltered DB == unfiltered DB at the same
    presence floor, .fa and .log byte-identical (auto floor from the
    DB's own declaration on one side, explicit flag on the other)."""
    from quorum_tpu.cli import error_correct_reads as ec_cli
    a = str(tmp_path / "floor")
    b = str(tmp_path / "pf")
    args = ["--batch-size", str(BATCH)]
    assert ec_cli.main(args + ["--presence-floor", "2", "-o", a,
                               plain_db, fastq_file]) == 0
    assert ec_cli.main(args + ["-o", b, prefiltered_db[0],
                               fastq_file]) == 0
    assert (open(a + ".fa", "rb").read()
            == open(b + ".fa", "rb").read())
    assert (open(a + ".log", "rb").read()
            == open(b + ".log", "rb").read())


def test_partition_kill_resume_byte_identical(fastq_file, plain_db,
                                              tmp_path):
    """Hard os._exit after the second partition commit; --resume
    re-runs ONLY the torn partitions and the final payload is
    byte-identical to the single-pass build."""
    out = str(tmp_path / "kr.qdb")
    ck = str(tmp_path / "ckpt")
    metrics = str(tmp_path / "kr_metrics.json")
    code = (
        "import sys\n"
        "from quorum_tpu.cli import create_database as cdb\n"
        f"sys.exit(cdb.main({_COMMON!r} + ['-o', {out!r}, "
        f"'--partitions', '4', '--checkpoint-dir', {ck!r}, "
        f"'--metrics', {metrics!r}] + sys.argv[1:] + "
        f"[{fastq_file!r}]))\n")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               JAX_COMPILATION_CACHE_DIR="/tmp/quorum_tpu_test_jaxcache",
               QUORUM_FAULT_PLAN=json.dumps([{
                   "site": "partition.commit", "at": 2,
                   "action": "exit", "code": 41}]))
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=600)
    assert res.returncode == 41, res.stderr[-2000:]
    assert not os.path.exists(out)  # no manifest yet
    cur = json.load(open(os.path.join(ck, "stage1.partitions.json")))
    assert [r["shard"] for r in cur["completed"]] == [0, 1]
    env.pop("QUORUM_FAULT_PLAN")
    res = subprocess.run([sys.executable, "-c", code, "--resume"],
                         cwd=REPO, env=env, capture_output=True,
                         text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-2000:]
    assert (db_format.db_payload_bytes(out)
            == db_format.db_payload_bytes(plain_db))
    doc = json.load(open(metrics))
    # only the torn partitions (2, 3) ran in the resumed process
    assert doc["counters"]["partition_passes_total"] == 2
    # ...but every partition's gauge is present (restored from cursor)
    for p in range(4):
        assert f'partition_distinct{{partition="{p}"}}' in doc["gauges"]
    assert not os.path.exists(
        os.path.join(ck, "stage1.partitions.json"))


def test_partitioned_fsck_pinpoints_and_loader_refuses(
        partitioned_db, fastq_file, tmp_path, capsys):
    """A corrupted partition shard is pinpointed by quorum-fsck under
    its shard-K section and refused by the loader with rc 3 — the
    partitioned manifest IS the PR 9 sharded format."""
    from quorum_tpu.cli import error_correct_reads as ec_cli
    from quorum_tpu.cli import fsck as fsck_cli
    src = partitioned_db[0]
    d = str(tmp_path / "corrupt")
    os.makedirs(d)
    for f in os.listdir(os.path.dirname(src)):
        if f.startswith(os.path.basename(src)):
            shutil.copy(os.path.join(os.path.dirname(src), f),
                        os.path.join(d, f))
    man = os.path.join(d, os.path.basename(src))
    shard2 = man + ".shard-2-of-4.qdb"
    data = bytearray(open(shard2, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(shard2, "wb") as f:  # qlint: disable=raw-artifact-write
        f.write(bytes(data))
    rc = fsck_cli.main([man])
    err = capsys.readouterr().err
    assert rc == 1
    assert "shard-2" in err
    rc = ec_cli.main(["--batch-size", str(BATCH), "-o",
                      str(tmp_path / "out"), man, fastq_file])
    assert rc == 3


def test_prefilter_refusals(fastq_file, tmp_path, capsys):
    out = str(tmp_path / "x.qdb")
    assert _cdb(_COMMON + ["-o", out, "--partitions", "3",
                           fastq_file]) == 1
    assert "power of two" in capsys.readouterr().err
    assert _cdb(_COMMON + ["-o", out, "--prefilter", "two-pass",
                           "--devices", "2", fastq_file]) == 1
    assert "--devices 1" in capsys.readouterr().err
    assert _cdb(_COMMON + ["-o", out, "--prefilter", "inline",
                           "--partitions", "2", fastq_file]) == 1
    assert "two-pass" in capsys.readouterr().err
    assert _cdb(_COMMON + ["-o", out, "--prefilter", "inline",
                           "--checkpoint-dir", str(tmp_path / "ck"),
                           fastq_file]) == 1
    assert "two-pass" in capsys.readouterr().err
    assert _cdb(_COMMON + ["-o", out, "--ref-format",
                           "--partitions", "2", fastq_file]) == 1
    capsys.readouterr()


def test_inline_cli_build_loads(fastq_file, obs, tmp_path):
    """Inline mode through the CLI: a loadable DB declaring the mode,
    honoring inline's HARD guarantees — every recurring mer is kept
    (the sketch never undercounts) with its count within the
    documented +-1 collision margin, and every absent mer is a true
    singleton. (Exact equality with two-pass needs a collision-free
    sketch — test_inline_matches_two_pass_when_roomy.)"""
    out = str(tmp_path / "inl.qdb")
    assert _cdb(_COMMON + ["-o", out, "--prefilter", "inline",
                           fastq_file]) == 0
    h = db_format.read_header(out)
    assert h["prefilter"]["mode"] == "inline"
    keys, _q = obs
    uk, cnt = np.unique(keys, return_counts=True)
    st, meta, _hdr = db_format.read_db(out, to_device=False)
    khi, klo, vals = db_format.db_iterate(st, meta)
    stored = {(int(h_) << 32) | int(l_): int(v) >> 1
              for h_, l_, v in zip(khi, klo, vals)}
    for key, c in zip(uk, cnt):
        if c >= 2:
            assert int(key) in stored
            assert abs(stored[int(key)] - int(c)) <= 1
    for key in stored:
        assert cnt[np.searchsorted(uk, np.uint64(key))] >= 1
    absent = set(int(k) for k in uk) - set(stored)
    assert all(cnt[np.searchsorted(uk, np.uint64(k))] == 1
               for k in absent)


# ---------------------------------------------------------------------------
# checkpoint / contract units
# ---------------------------------------------------------------------------


def test_partition_cursor_identity_and_digest(tmp_path):
    from quorum_tpu.io import checkpoint as ckpt_mod
    d = str(tmp_path)
    shard = os.path.join(d, "x.shard-0-of-2.qdb")
    with open(shard, "wb") as f:  # qlint: disable=raw-artifact-write
        f.write(b"payload-bytes")
    cur = ckpt_mod.Stage1PartitionCursor(d)
    rec = {"path": os.path.basename(shard), "shard": 0,
           "n_entries": 3, "value_bytes": 13, "file_crc32c": 1}
    ident = {"k": 15, "partitions": 2}
    cur.save(ident, [rec], d)
    got = cur.load(ident, d)
    assert [r["shard"] for r in got] == [0]
    assert cur.cursor() == 1
    # identity mismatch = a different run's cursor = fresh build
    assert cur.load({"k": 16, "partitions": 2}, d) is None
    # damaged completed shard = loud refusal
    with open(shard, "ab") as f:  # qlint: disable=raw-artifact-write
        f.write(b"!")
    with pytest.raises(ckpt_mod.CheckpointError):
        cur.load(ident, d)
    cur.clear()
    assert cur.cursor() is None
    # sketch checkpoint round-trips and refuses corruption
    sk = ckpt_mod.SketchCheckpoint(d)
    cells = np.arange(64, dtype=np.uint8)
    sk.save(cells, ident)
    assert np.array_equal(sk.load(ident), cells)
    assert sk.load({"k": 9}) is None
    raw = bytearray(open(sk.path, "rb").read())
    raw[-1] ^= 0xFF
    with open(sk.path, "wb") as f:  # qlint: disable=raw-artifact-write
        f.write(bytes(raw))
    with pytest.raises(ckpt_mod.CheckpointError):
        sk.load(ident)


def test_metrics_check_memfrugal_names():
    mc = _load_tool("metrics_check")
    ok = {"meta": {"prefilter": "two-pass", "partitions": 2},
          "counters": {"prefilter_dropped_total": 5,
                       "prefilter_false_pass_total": 0,
                       "partition_passes_total": 2},
          "gauges": {'partition_distinct{partition="0"}': 10,
                     'partition_distinct{partition="1"}': 12}}
    assert mc._check_memfrugal_names(ok) == []
    missing = {"meta": ok["meta"], "counters": {},
               "gauges": {'partition_distinct{partition="0"}': 10}}
    errs = mc._check_memfrugal_names(missing)
    assert len(errs) == 4  # 2 prefilter + 1 partition counter + gauge 1
    off = {"meta": {"prefilter": "off", "partitions": 1},
           "counters": {}, "gauges": {}}
    assert mc._check_memfrugal_names(off) == []


def test_trace_summary_partition_table(tmp_path, capsys):
    ts = _load_tool("trace_summary")
    ev = str(tmp_path / "events.jsonl")
    with open(ev, "w") as f:  # qlint: disable=raw-artifact-write
        for line in (
                {"event": "partition_pass", "t": 1.0,
                 "partition": "sketch", "n_partitions": 2,
                 "batches": 3, "seconds": 0.5},
                {"event": "partition_pass", "t": 2.0, "partition": 0,
                 "n_partitions": 2, "batches": 3, "distinct": 100,
                 "seconds": 0.8},
                {"event": "heartbeat", "t": 2.5, "partition": 1},
                {"event": "partition_pass", "t": 3.0, "partition": 1,
                 "n_partitions": 2, "batches": 3, "distinct": 90,
                 "seconds": 0.7}):
            f.write(json.dumps(line) + "\n")
    assert ts.main([ev]) == 0
    out = capsys.readouterr().out
    assert "partition passes" in out
    assert "sketch" in out and "3 pass(es)" in out


def test_levers_and_tuning_registration():
    from quorum_tpu.ops import tuning
    from quorum_tpu.utils import levers
    assert "QUORUM_PREFILTER" in levers.CATALOG
    assert "QUORUM_SKETCH_BITS" in levers.CATALOG
    assert "QUORUM_PREFILTER" in tuning.LEVER_ENVS
    assert "QUORUM_SKETCH_BITS" in tuning.CAP_ENVS
    from quorum_tpu.telemetry import contract
    pre = contract.precreated_counter_names()
    for name in ("prefilter_dropped_total", "prefilter_false_pass_total",
                 "partition_passes_total"):
        assert name in pre
    from quorum_tpu.utils import faults
    assert "partition.commit" in faults.SITES


def test_driver_never_replays_truncated_cache(fastq_file, tmp_path,
                                              monkeypatch):
    """A multi-pass stage 1 that abandons the driver's caching
    producer mid-stream (a partition-geometry restart) must not leave
    a truncated RAM replay cache that stage 2 silently consumes as
    the whole input (ISSUE 14 review finding)."""
    from quorum_tpu.cli import quorum as quorum_cli

    def half_consuming_cdb(argv, handoff=None, batches=None,
                           batches_factory=None):
        it = batches_factory()
        next(it)  # consume ONE batch, then abandon the iterator
        return 0

    seen = {}

    def fake_ec(argv, db=None, prepacked=None):
        seen["prepacked"] = prepacked
        return 0

    monkeypatch.setattr(quorum_cli.cdb_cli, "main", half_consuming_cdb)
    monkeypatch.setattr(quorum_cli.ec_cli, "main", fake_ec)
    # batch-size 32 -> 16 batches: the driver's prefetch thread
    # (depth 4) cannot drain the abandoned source into its queue, so
    # "complete" deterministically stays False. At 4 total batches
    # the producer CAN legitimately finish the whole input after the
    # consumer abandons it — a complete cache, and a racy assertion
    # (observed under the ISSUE-15 compile sentinel's timing shift).
    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-q", "33",
                          "-p", str(tmp_path / "q"),
                          "--batch-size", "32", fastq_file])
    assert rc == 0
    # the truncated cache must NOT reach stage 2 — None forces the
    # disk re-parse, which sees every read
    assert seen["prepacked"] is None


def test_partitioned_composition_validated_in_model():
    """The partitioned builder enforces its own composition rules —
    a library caller can't get an unfiltered table whose header
    claims a prefilter ran."""
    from quorum_tpu.models.create_database import (
        BuildConfig, _build_database_partitioned)
    from quorum_tpu.telemetry import NULL, NULL_TRACER
    with pytest.raises(ValueError, match="inline"):
        _build_database_partitioned(
            ["x.fastq"], BuildConfig(k=K, partitions=2,
                                     prefilter="inline"),
            "out.qdb", None, None, NULL, NULL_TRACER)
    with pytest.raises(ValueError, match="devices 1"):
        _build_database_partitioned(
            ["x.fastq"], BuildConfig(k=K, partitions=2, devices=2,
                                     prefilter="two-pass"),
            "out.qdb", None, None, NULL, NULL_TRACER)


def test_prefilter_mode_resolution(monkeypatch):
    monkeypatch.setenv("QUORUM_PREFILTER", "two-pass")
    assert sketch_mod.prefilter_default() == "two-pass"
    monkeypatch.setenv("QUORUM_PREFILTER", "bogus")
    assert sketch_mod.prefilter_default() == "off"
    monkeypatch.delenv("QUORUM_PREFILTER")
    assert sketch_mod.prefilter_default() == "off"
