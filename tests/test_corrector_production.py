"""Production-shape corrector run (k=24, 150 bp, 4k-read batch):
sampled oracle parity plus efficacy and a lockstep-divergence metric
(VERDICT r2 item 9). Complements the k=9 adversarial parity tests in
test_corrector.py with the real geometry."""

import numpy as np
import jax.numpy as jnp
import pytest

from quorum_tpu.ops import ctable, mer
from quorum_tpu.models import corrector
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.models.oracle import DictDB, OracleCorrector
from quorum_tpu.models.create_database import extract_observations

K, RLEN, B = 24, 150, 4096
BASES = "ACGT"


@pytest.fixture(scope="module")
def production_batch():
    rng = np.random.default_rng(42)
    genome = rng.integers(0, 4, size=120_000, dtype=np.int8)
    starts = rng.integers(0, len(genome) - RLEN, size=B)
    codes = genome[starts[:, None] + np.arange(RLEN)[None, :]].astype(np.int8)
    errs = rng.random(codes.shape) < 0.01
    codes = np.where(errs, (codes + rng.integers(1, 4, size=codes.shape)) % 4,
                     codes).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    quals[errs] = 68
    # build the tile DB from the reads themselves (~5x coverage)
    meta = ctable.TileMeta(k=K, bits=7, rb_log2=ctable.tile_rb_for(
        600_000, K, 7))
    bstate = ctable.make_tile_build(meta)
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), K, 38)
    bstate, full, _ = ctable.tile_insert_observations(
        bstate, meta, chi, clo, q, valid)
    assert not full
    state = ctable.tile_finalize(bstate, meta)
    return genome, codes, quals, errs, state, meta


def test_production_shape_parity_and_efficacy(production_batch):
    genome, codes, quals, errs, state, meta = production_batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    lengths = jnp.full((B,), RLEN, jnp.int32)
    res = corrector.correct_batch(state, meta, jnp.asarray(codes),
                                  jnp.asarray(quals), lengths, cfg)
    dev = corrector.finish_batch(res, B, cfg)

    # EXHAUSTIVE bit-exact oracle parity: every read in the batch
    # (VERDICT r4 weak #7 — k=24/150 bp is where packing/layout bugs
    # would live, and a sampled check could miss them)
    ikhi, iklo, ivals = ctable.tile_iterate(state, meta)
    d = {(int(h) << 32) | int(l): (int(v) >> 1, int(v) & 1)
         for h, l, v in zip(ikhi, iklo, ivals)}
    oc = OracleCorrector(DictDB(d, K), cfg)
    seqs = np.frombuffer(b"ACGT", np.uint8)[np.clip(codes, 0, 3)]
    for i in range(B):
        read = seqs[i].tobytes().decode()
        qual = "".join(chr(int(q)) for q in quals[i])
        o = oc.correct(read, qual)
        dv = dev[i]
        assert (o.ok, o.error, o.seq, o.fwd_log, o.bwd_log, o.start,
                o.end) == (dv.ok, dv.error, dv.seq, dv.fwd_log,
                           dv.bwd_log, dv.start, dv.end), f"read {i}"

    # efficacy: nearly every read corrects, and at injected-error
    # positions inside the kept window the base must have CHANGED
    # (count-of-corrected proxy; full truth comparison lives in the
    # golden CLI tests)
    n_ok = sum(1 for r in dev if r.ok)
    assert n_ok > 0.95 * B
    corrected = total = 0
    for i in range(B):
        r = dev[i]
        if not r.ok or r.end - r.start < 50:
            continue
        out = mer.seq_to_codes(r.seq)
        inj = np.nonzero(errs[i][r.start:r.end])[0]
        if len(inj) == 0:
            continue
        total += len(inj)
        corrected += int(np.sum(out[inj] != codes[i, r.start:r.end][inj]))
    assert total > 100
    assert corrected / total > 0.85, \
        f"only {corrected}/{total} errors corrected"


def test_divergence_metric_reported(production_batch):
    """Measure lockstep divergence: fraction of lanes already finished
    when the forward extension loop ends (informative for batch
    sizing; SURVEY hard part (a))."""
    genome, codes, quals, errs, state, meta = production_batch
    cfg = ECConfig(k=K, cutoff=4)
    lengths = jnp.full((B,), RLEN, jnp.int32)
    res = corrector.correct_batch(state, meta, jnp.asarray(codes),
                                  jnp.asarray(quals), lengths, cfg)
    status = np.asarray(res.status)
    ok = status == 0
    spans = np.asarray(res.end) - np.asarray(res.start)
    waste = 1.0 - spans[ok].mean() / RLEN
    print(f"\nlockstep divergence: ok={ok.mean():.3f} "
          f"mean kept span={spans[ok].mean():.1f}/{RLEN} "
          f"(waste fraction {waste:.3f})")
    assert spans[ok].mean() > 100
