"""Sanity tests for the oracle corrector: known scenarios with
hand-derivable outcomes (clean read untouched, single error corrected
and logged, unsupported tail truncated, anchor failure, homopolymer
trim, window budget)."""

import numpy as np
import pytest

from quorum_tpu.models.ec_config import ECConfig, ERROR_NO_STARTING_MER
from quorum_tpu.models.oracle import DictDB, Kmer, OracleCorrector

K = 15


def make_db(genome, k=K, cov=30):
    """Perfect high-quality coverage of every k-mer in the genome."""
    d = {}
    for i in range(len(genome) - k + 1):
        m = Kmer(k)
        for c in genome[i : i + k]:
            m.shift_left("ACGT".index(c))
        d[m.canonical()] = (cov, 1)
    return DictDB(d, k)


@pytest.fixture
def genome():
    rng = np.random.default_rng(5)
    return "".join(rng.choice(list("ACGT"), size=600))


def cfg(**kw):
    kw.setdefault("cutoff", 4)
    return ECConfig(k=K, **kw)


def test_clean_read_untouched(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg())
    read = genome[50:150]
    res = oc.correct(read, "I" * len(read))
    assert res.ok
    assert res.seq == read
    assert res.fwd_log == "" and res.bwd_log == ""
    assert res.start == 0 and res.end == 100


def test_single_error_corrected(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg())
    read = list(genome[50:150])
    orig = read[60]
    sub = {"A": "C", "C": "G", "G": "T", "T": "A"}[orig]
    read[60] = sub
    res = oc.correct("".join(read), "I" * len(read))
    assert res.ok
    assert res.seq == genome[50:150]
    assert f"60:sub:{sub}-{orig}" in res.fwd_log
    assert res.bwd_log == ""


def test_error_before_anchor_corrected_backward(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg())
    read = list(genome[50:150])
    orig = read[5]
    sub = {"A": "C", "C": "G", "G": "T", "T": "A"}[orig]
    read[5] = sub
    res = oc.correct("".join(read), "I" * len(read))
    assert res.ok
    assert res.seq == genome[50:150]
    assert f"5:sub:{sub}-{orig}" in res.bwd_log
    assert res.fwd_log == ""


def test_garbage_tail_truncated(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg())
    # genome prefix + random tail that matches nothing
    rng = np.random.default_rng(9)
    comp = {"A": "T", "C": "G", "G": "C", "T": "A"}
    tail = "".join(comp[c] for c in genome[300:340][::-1])  # revcomp of a
    # distant region reversed = unrelated sequence
    read = genome[50:120] + tail[:30]
    res = oc.correct(read, "I" * len(read))
    assert res.ok
    assert res.start == 0
    # forward log must contain a 3' truncation event
    assert "3_trunc" in res.fwd_log
    # kept prefix must be a prefix of the genome region
    assert genome[50:120].startswith(res.seq[:70][:5])
    assert res.seq == genome[50 : 50 + len(res.seq)]


def test_no_anchor(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg())
    rng = np.random.default_rng(13)
    junk = "".join(rng.choice(list("ACGT"), size=60))
    res = oc.correct(junk, "I" * 60)
    assert not res.ok
    assert res.error == ERROR_NO_STARTING_MER


def test_short_read_no_anchor(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg())
    res = oc.correct(genome[50 : 50 + K], "I" * K)  # too short: skip=1
    assert not res.ok


def test_n_base_corrected(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg())
    read = list(genome[50:150])
    orig = read[60]
    read[60] = "N"
    res = oc.correct("".join(read), "I" * len(read))
    assert res.ok
    assert res.seq == genome[50:150]
    assert f"60:sub:N-{orig}" in res.fwd_log


def test_homo_trim(genome):
    db = make_db(genome)
    oc = OracleCorrector(db, cfg(homo_trim=10))
    read = genome[50:120] + "A" * 30
    res = oc.correct(read, "I" * len(read))
    assert res.ok
    # polyA tail trimmed; kept part is genome prefix
    assert len(res.seq) <= 75
    assert res.seq == genome[50 : 50 + len(res.seq)]


def test_window_budget_truncates(genome):
    """More than `error` corrections within `window` bases must rewind
    and truncate (err_log.hpp:87-106)."""
    db = make_db(genome)
    oc = OracleCorrector(db, cfg(window=10, error=2))
    read = list(genome[50:150])
    # three errors clustered within a 6-base window
    positions = [70, 72, 74]
    origs = {}
    for p in positions:
        origs[p] = read[p]
        read[p] = {"A": "C", "C": "G", "G": "T", "T": "A"}[read[p]]
    res = oc.correct("".join(read), "I" * len(read))
    assert res.ok
    # the read must be truncated before position 74
    assert res.end <= 74
    assert "3_trunc" in res.fwd_log


def test_paired_quality_semantics(genome):
    """Low-quality-only k-mers don't anchor (get_val returns 0)."""
    d = {}
    for i in range(len(genome) - K + 1):
        m = Kmer(K)
        for c in genome[i : i + K]:
            m.shift_left("ACGT".index(c))
        d[m.canonical()] = (30, 0)  # high count but low quality
    db = DictDB(d, K)
    oc = OracleCorrector(db, cfg())
    res = oc.correct(genome[50:150], "I" * 100)
    assert not res.ok
    assert res.error == ERROR_NO_STARTING_MER
