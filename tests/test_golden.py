"""Committed golden fixture: a fixed synthetic dataset with its
expected corrected FASTA, regenerated through the full CLI path and
byte-diffed. Unlike the oracle-parity tests (where the oracle and the
device share one reading of the spec), this pins today's verified
output against any future JOINT drift of both implementations
(VERDICT r2 weak #6)."""

import filecmp
import os

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli

HERE = os.path.dirname(__file__)
GOLDEN = os.path.join(HERE, "golden")


def test_golden_end_to_end(tmp_path):
    reads = os.path.join(GOLDEN, "reads.fastq")
    db = str(tmp_path / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, reads])
    assert rc == 0
    out = str(tmp_path / "corr")
    rc = ec_cli.main(["-p", "4", db, reads, "-o", out])
    assert rc == 0
    assert filecmp.cmp(out + ".fa", os.path.join(GOLDEN, "expected.fa"),
                       shallow=False), "corrected FASTA drifted from golden"
    assert filecmp.cmp(out + ".log", os.path.join(GOLDEN, "expected.log"),
                       shallow=False)
    # and the default path: cutoff auto-computed from the DB
    # (compute_poisson_cutoff), which fixed -p would mask
    out2 = str(tmp_path / "auto")
    rc = ec_cli.main([db, reads, "-o", out2])
    assert rc == 0
    assert filecmp.cmp(out2 + ".fa",
                       os.path.join(GOLDEN, "expected_auto.fa"),
                       shallow=False), "auto-cutoff output drifted"
    assert filecmp.cmp(out2 + ".log",
                       os.path.join(GOLDEN, "expected_auto.log"),
                       shallow=False)


def test_golden_metrics_end_to_end(tmp_path):
    """Acceptance (ISSUE 1): the golden pipeline run with --metrics
    produces schema-valid metrics whose outcome counters exactly match
    the counts recoverable from expected.fa/expected.log, while the
    .fa/.log outputs stay byte-identical."""
    import json
    import subprocess
    import sys

    from quorum_tpu.models.error_correct import REASON_SLUGS
    from quorum_tpu.telemetry import validate_metrics

    reads = os.path.join(GOLDEN, "reads.fastq")
    db = str(tmp_path / "db.jf")
    m1 = str(tmp_path / "stage1.json")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, "--metrics", m1, reads])
    assert rc == 0
    out = str(tmp_path / "corr")
    m2 = str(tmp_path / "stage2.json")
    rc = ec_cli.main(["-p", "4", db, reads, "-o", out, "--metrics", m2])
    assert rc == 0

    # byte parity unchanged with telemetry enabled
    assert filecmp.cmp(out + ".fa", os.path.join(GOLDEN, "expected.fa"),
                       shallow=False)
    assert filecmp.cmp(out + ".log", os.path.join(GOLDEN, "expected.log"),
                       shallow=False)

    # schema-valid, through the actual validator tool
    check = os.path.join(os.path.dirname(HERE), "tools",
                         "metrics_check.py")
    res = subprocess.run([sys.executable, check, m1, m2],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr

    # ground truth recovered from the committed expected outputs
    fastq_lines = open(reads).read().splitlines()
    n_reads = len(fastq_lines) // 4
    n_bases = sum(len(s) for s in fastq_lines[1::4])
    fa = open(os.path.join(GOLDEN, "expected.fa")).read()
    log = open(os.path.join(GOLDEN, "expected.log")).read()
    corrected = fa.count(">")
    skip_reasons = [ln.split(": ", 1)[1]
                    for ln in log.splitlines()
                    if ln.startswith("Skipped ")]

    doc1 = json.load(open(m1))
    assert validate_metrics(doc1) == []
    assert doc1["meta"]["stage"] == "create_database"
    assert doc1["counters"]["reads"] == n_reads
    assert doc1["counters"]["bases"] == n_bases
    assert doc1["counters"]["distinct_mers"] > 0
    assert 0 < doc1["gauges"]["hash_fill"] < 1
    assert "stage1" in doc1["timers"]

    doc2 = json.load(open(m2))
    assert validate_metrics(doc2) == []
    assert doc2["meta"]["stage"] == "error_correct"
    c = doc2["counters"]
    assert c["reads_in"] == n_reads
    assert c["reads_corrected"] == corrected
    assert c["reads_skipped"] == len(skip_reasons)
    assert corrected + len(skip_reasons) == n_reads
    assert c["bases_in"] == n_bases
    assert c["substitutions"] == fa.count(":sub:")
    assert c.get("truncations_3p", 0) == fa.count(":3_trunc")
    assert c.get("truncations_5p", 0) == fa.count(":5_trunc")
    want_skips: dict = {}
    for r in skip_reasons:
        slug = REASON_SLUGS.get(r, "other")
        want_skips[slug] = want_skips.get(slug, 0) + 1
    for slug, n in want_skips.items():
        assert c[f"skipped_{slug}"] == n, slug
    h = doc2["histograms"]["substitutions_per_read"]
    assert h["count"] == corrected
    assert h["sum"] == c["substitutions"]
    assert "stage2" in doc2["timers"]
