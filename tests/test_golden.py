"""Committed golden fixture: a fixed synthetic dataset with its
expected corrected FASTA, regenerated through the full CLI path and
byte-diffed. Unlike the oracle-parity tests (where the oracle and the
device share one reading of the spec), this pins today's verified
output against any future JOINT drift of both implementations
(VERDICT r2 weak #6)."""

import filecmp
import os

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli

HERE = os.path.dirname(__file__)
GOLDEN = os.path.join(HERE, "golden")


def test_golden_end_to_end(tmp_path):
    reads = os.path.join(GOLDEN, "reads.fastq")
    db = str(tmp_path / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, reads])
    assert rc == 0
    out = str(tmp_path / "corr")
    rc = ec_cli.main(["-p", "4", db, reads, "-o", out])
    assert rc == 0
    assert filecmp.cmp(out + ".fa", os.path.join(GOLDEN, "expected.fa"),
                       shallow=False), "corrected FASTA drifted from golden"
    assert filecmp.cmp(out + ".log", os.path.join(GOLDEN, "expected.log"),
                       shallow=False)
    # and the default path: cutoff auto-computed from the DB
    # (compute_poisson_cutoff), which fixed -p would mask
    out2 = str(tmp_path / "auto")
    rc = ec_cli.main([db, reads, "-o", out2])
    assert rc == 0
    assert filecmp.cmp(out2 + ".fa",
                       os.path.join(GOLDEN, "expected_auto.fa"),
                       shallow=False), "auto-cutoff output drifted"
    assert filecmp.cmp(out2 + ".log",
                       os.path.join(GOLDEN, "expected_auto.log"),
                       shallow=False)


def test_golden_metrics_end_to_end(tmp_path):
    """Acceptance (ISSUE 1): the golden pipeline run with --metrics
    produces schema-valid metrics whose outcome counters exactly match
    the counts recoverable from expected.fa/expected.log, while the
    .fa/.log outputs stay byte-identical."""
    import json
    import subprocess
    import sys

    from quorum_tpu.models.error_correct import REASON_SLUGS
    from quorum_tpu.telemetry import validate_metrics

    reads = os.path.join(GOLDEN, "reads.fastq")
    db = str(tmp_path / "db.jf")
    m1 = str(tmp_path / "stage1.json")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, "--metrics", m1, reads])
    assert rc == 0
    out = str(tmp_path / "corr")
    m2 = str(tmp_path / "stage2.json")
    rc = ec_cli.main(["-p", "4", db, reads, "-o", out, "--metrics", m2])
    assert rc == 0

    # byte parity unchanged with telemetry enabled
    assert filecmp.cmp(out + ".fa", os.path.join(GOLDEN, "expected.fa"),
                       shallow=False)
    assert filecmp.cmp(out + ".log", os.path.join(GOLDEN, "expected.log"),
                       shallow=False)

    # schema-valid, through the actual validator tool
    check = os.path.join(os.path.dirname(HERE), "tools",
                         "metrics_check.py")
    res = subprocess.run([sys.executable, check, m1, m2],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr

    # ground truth recovered from the committed expected outputs
    fastq_lines = open(reads).read().splitlines()
    n_reads = len(fastq_lines) // 4
    n_bases = sum(len(s) for s in fastq_lines[1::4])
    fa = open(os.path.join(GOLDEN, "expected.fa")).read()
    log = open(os.path.join(GOLDEN, "expected.log")).read()
    corrected = fa.count(">")
    skip_reasons = [ln.split(": ", 1)[1]
                    for ln in log.splitlines()
                    if ln.startswith("Skipped ")]

    doc1 = json.load(open(m1))
    assert validate_metrics(doc1) == []
    assert doc1["meta"]["stage"] == "create_database"
    assert doc1["counters"]["reads"] == n_reads
    assert doc1["counters"]["bases"] == n_bases
    assert doc1["counters"]["distinct_mers"] > 0
    assert 0 < doc1["gauges"]["hash_fill"] < 1
    assert "stage1" in doc1["timers"]

    doc2 = json.load(open(m2))
    assert validate_metrics(doc2) == []
    assert doc2["meta"]["stage"] == "error_correct"
    c = doc2["counters"]
    assert c["reads_in"] == n_reads
    assert c["reads_corrected"] == corrected
    assert c["reads_skipped"] == len(skip_reasons)
    assert corrected + len(skip_reasons) == n_reads
    assert c["bases_in"] == n_bases
    assert c["substitutions"] == fa.count(":sub:")
    assert c.get("truncations_3p", 0) == fa.count(":3_trunc")
    assert c.get("truncations_5p", 0) == fa.count(":5_trunc")
    want_skips: dict = {}
    for r in skip_reasons:
        slug = REASON_SLUGS.get(r, "other")
        want_skips[slug] = want_skips.get(slug, 0) + 1
    for slug, n in want_skips.items():
        assert c[f"skipped_{slug}"] == n, slug
    h = doc2["histograms"]["substitutions_per_read"]
    assert h["count"] == corrected
    assert h["sum"] == c["substitutions"]
    assert "stage2" in doc2["timers"]


def test_golden_observability_gate(tmp_path):
    """CI gate (ISSUE 2 satellite): the golden pipeline run with
    --metrics + --metrics-textfile + --trace-spans must produce
    artifacts that metrics_check passes — the JSON/JSONL/trace kinds
    in default mode and the Prometheus textfile under --prom — while
    the corrected outputs stay byte-identical."""
    import json
    import subprocess
    import sys

    reads = os.path.join(GOLDEN, "reads.fastq")
    db = str(tmp_path / "db.jf")
    m1 = str(tmp_path / "stage1.json")
    tf = str(tmp_path / "live.prom")
    sp1 = str(tmp_path / "spans1.jsonl")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, "--metrics", m1,
                       "--metrics-interval", "0.001",
                       "--metrics-textfile", tf,
                       "--trace-spans", sp1, reads])
    assert rc == 0
    out = str(tmp_path / "corr")
    m2 = str(tmp_path / "stage2.json")
    sp2 = str(tmp_path / "spans2.jsonl")
    rc = ec_cli.main(["-p", "4", db, reads, "-o", out,
                      "--metrics", m2, "--metrics-textfile", tf,
                      "--trace-spans", sp2])
    assert rc == 0

    # byte parity unchanged with the full observability surface on
    assert filecmp.cmp(out + ".fa", os.path.join(GOLDEN, "expected.fa"),
                       shallow=False)
    assert filecmp.cmp(out + ".log", os.path.join(GOLDEN, "expected.log"),
                       shallow=False)

    check = os.path.join(os.path.dirname(HERE), "tools",
                         "metrics_check.py")
    artifacts = [m1, m2, sp1, sp2,
                 str(tmp_path / "stage1.events.jsonl"),
                 str(tmp_path / "spans1.trace.json"),
                 str(tmp_path / "spans2.trace.json")]
    for a in artifacts:
        assert os.path.exists(a), a
    res = subprocess.run([sys.executable, check] + artifacts,
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr
    res = subprocess.run([sys.executable, check, "--prom", tf],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr

    # the split timers finally separate dispatch from device wait
    doc2 = json.load(open(m2))
    st = doc2["timers"]["stage2"]["stages"]
    assert "device_dispatch" in st and "device_wait" in st
    assert doc2["histograms"]["device_dispatch_us"]["count"] \
        == doc2["histograms"]["device_wait_us"]["count"] > 0
    doc1 = json.load(open(m1))
    s1 = doc1["timers"]["stage1"]["stages"]
    assert "insert_dispatch" in s1 and "insert_wait" in s1

    # trace_summary runs over the artifacts and prints the
    # host/device/wait attribution table
    summ = os.path.join(os.path.dirname(HERE), "tools",
                        "trace_summary.py")
    res = subprocess.run([sys.executable, summ, sp2, m2],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert "device wait" in res.stdout
    assert "stage2_batch" in res.stdout
