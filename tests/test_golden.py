"""Committed golden fixture: a fixed synthetic dataset with its
expected corrected FASTA, regenerated through the full CLI path and
byte-diffed. Unlike the oracle-parity tests (where the oracle and the
device share one reading of the spec), this pins today's verified
output against any future JOINT drift of both implementations
(VERDICT r2 weak #6)."""

import filecmp
import os

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli

HERE = os.path.dirname(__file__)
GOLDEN = os.path.join(HERE, "golden")


def test_golden_end_to_end(tmp_path):
    reads = os.path.join(GOLDEN, "reads.fastq")
    db = str(tmp_path / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, reads])
    assert rc == 0
    out = str(tmp_path / "corr")
    rc = ec_cli.main(["-p", "4", db, reads, "-o", out])
    assert rc == 0
    assert filecmp.cmp(out + ".fa", os.path.join(GOLDEN, "expected.fa"),
                       shallow=False), "corrected FASTA drifted from golden"
    assert filecmp.cmp(out + ".log", os.path.join(GOLDEN, "expected.log"),
                       shallow=False)
    # and the default path: cutoff auto-computed from the DB
    # (compute_poisson_cutoff), which fixed -p would mask
    out2 = str(tmp_path / "auto")
    rc = ec_cli.main([db, reads, "-o", out2])
    assert rc == 0
    assert filecmp.cmp(out2 + ".fa",
                       os.path.join(GOLDEN, "expected_auto.fa"),
                       shallow=False), "auto-cutoff output drifted"
    assert filecmp.cmp(out2 + ".log",
                       os.path.join(GOLDEN, "expected_auto.log"),
                       shallow=False)
