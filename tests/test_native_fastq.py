"""Native FASTQ parser (quorum_tpu/native) vs the pure-Python parser:
identical batches on strict 4-line FASTQ; graceful fallback on FASTA."""

import numpy as np
import pytest

from quorum_tpu.io import fastq
from quorum_tpu.native import binding


pytestmark = pytest.mark.skipif(not binding.available(),
                                reason="no g++ / native lib")

BASES = "ACGTN"


def write_fastq(path, rng, n, minlen=40, maxlen=120, crlf=False,
                trailing_newline=True):
    recs = []
    with open(path, "w", newline="") as f:
        eol = "\r\n" if crlf else "\n"
        for i in range(n):
            m = int(rng.integers(minlen, maxlen))
            seq = "".join(BASES[c] for c in rng.integers(0, 5, m))
            qual = "".join(chr(int(c)) for c in rng.integers(33, 74, m))
            recs.append((f"r{i} extra", seq, qual))
            tail = eol if (trailing_newline or i < n - 1) else ""
            f.write(f"@r{i} extra{eol}{seq}{eol}+{eol}{qual}{tail}")
    return recs


@pytest.mark.parametrize("crlf,trailing", [(False, True), (True, True),
                                           (False, False)])
def test_native_matches_python(tmp_path, crlf, trailing):
    rng = np.random.default_rng(1)
    path = str(tmp_path / "r.fastq")
    write_fastq(path, rng, 1000, crlf=crlf, trailing_newline=trailing)
    nat = list(binding.read_batches([path], batch_size=256))
    py = list(fastq.batch_records(fastq.iter_records([path]), 256))
    assert sum(b.n for b in nat) == sum(b.n for b in py) == 1000
    ni = ((b, i) for b in nat for i in range(b.n))
    pi = ((b, i) for b in py for i in range(b.n))
    for (nb, j), (pb, k) in zip(ni, pi):
        assert nb.headers[j] == pb.headers[k]
        L = nb.lengths[j]
        assert L == pb.lengths[k]
        assert np.array_equal(nb.codes[j, :L], pb.codes[k, :L])
        assert np.array_equal(nb.quals[j, :L], pb.quals[k, :L])
        assert np.all(nb.codes[j, L:] == -2)


def test_fasta_falls_back(tmp_path):
    path = str(tmp_path / "r.fa")
    with open(path, "w") as f:
        f.write(">a\nACGTACGTACGT\nACGT\n>b\nTTTT\n")
    batches = list(binding.read_batches([path], batch_size=8))
    assert sum(b.n for b in batches) == 2
    assert batches[0].headers[0] == "a"
    assert batches[0].lengths[0] == 16  # multi-line joined


def test_gzip_input(tmp_path):
    import gzip
    rng = np.random.default_rng(2)
    plain = str(tmp_path / "r.fastq")
    recs = write_fastq(plain, rng, 100)
    gz = str(tmp_path / "r.fastq.gz")
    with open(plain, "rb") as f, gzip.open(gz, "wb") as g:
        g.write(f.read())
    nat = list(binding.read_batches([gz], batch_size=64))
    assert sum(b.n for b in nat) == 100
    assert nat[0].headers[0] == recs[0][0]


def test_oversized_read_grows_stride(tmp_path):
    rng = np.random.default_rng(3)
    path = str(tmp_path / "r.fastq")
    with open(path, "w") as f:
        f.write("@short\nACGT\n+\nIIII\n")
        seq = "".join("ACGT"[c] for c in rng.integers(0, 4, 6000))
        f.write(f"@long\n{seq}\n+\n{'I' * 6000}\n")
    batches = list(binding.read_batches([path], batch_size=4))
    total = sum(b.n for b in batches)
    assert total == 2
    lens = sorted(int(l) for b in batches for l in b.lengths[:b.n])
    assert lens == [4, 6000]
