"""quorum-lint suite tests (ISSUE 12): per-rule golden fixtures —
one seeded-violation snippet and one clean snippet per rule — plus
baseline/suppression semantics, the --emit-docs round trip, the
repo-must-be-clean acceptance gate, and the runtime lock-order
sanitizer (deliberate A->B / B->A inversion caught, clean nested
acquisition passing)."""

import json
import os
import threading

import pytest

from quorum_tpu.analysis import run_lint, tsan
from quorum_tpu.analysis.cli import main as qlint_main
from quorum_tpu.analysis.core import (Project, SourceFile,
                                      apply_baseline, load_baseline,
                                      run_rules)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_repo(tmp_path, files: dict) -> str:
    """A throwaway repo root holding the given rel-path -> source
    snippets."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return str(tmp_path)


def lint(root, rule_id):
    return run_rules(Project(root), [rule_id])


# -- rule fixtures: seeded violation + clean, one pair per rule -----------

def test_raw_artifact_write_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'def export(path, data):\n'
            '    with open(path, "wb") as f:\n'
            '        f.write(data)\n',
        "quorum_tpu/good.py":
            'import os\n'
            'def export(path, data):\n'
            '    sibling = path + ".new"\n'
            '    with open(sibling, "wb") as f:\n'
            '        f.write(data)\n'
            '    os.replace(sibling, path)\n',
        "quorum_tpu/stream.py":
            'def quarantine(path):\n'
            '    return open(path + ".quarantine.fastq", "ab")\n',
    })
    found = lint(root, "raw-artifact-write")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]
    assert found[0].line == 2
    assert "atomic" in found[0].message


def test_raw_artifact_write_inline_suppression(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/s.py":
            'def stream(path):\n'
            '    return open(path, "w")  '
            '# qlint: disable=raw-artifact-write\n',
    })
    assert lint(root, "raw-artifact-write") == []


def test_append_truncation_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'class Sink:\n'
            '    def start(self):\n'
            '        self._f = open(self.events_path, "wb")\n'
            '    def restart(self):\n'
            '        self._f = open(self.events_path, "wb")\n',
        "quorum_tpu/good.py":
            'class Sink:\n'
            '    def start(self):\n'
            '        if self._f is None:\n'
            '            self._f = open(self.events_path, "wb")\n',
    })
    found = lint(root, "append-truncation")
    assert {f.path for f in found} == {"quorum_tpu/bad.py"}
    assert sorted(f.line for f in found) == [3, 5]


def test_lever_raw_env_read_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'import os\n'
            'v = os.environ.get("QUORUM_TPU_VERBOSE")\n',
        "quorum_tpu/good.py":
            'from .utils import levers\n'
            'v = levers.raw("QUORUM_TPU_VERBOSE")\n',
        "quorum_tpu/other_env.py":
            'import os\n'
            'v = os.environ.get("JAX_PLATFORMS")\n',  # not a lever
    })
    found = lint(root, "lever-raw-env-read")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]


def test_lever_undeclared_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'import os\n'
            'v = os.environ.get("QUORUM_NOT_A_REAL_LEVER")\n',
        "quorum_tpu/good.py":
            'from .utils import levers\n'
            'v = levers.raw("QUORUM_TPU_VERBOSE")\n',
    })
    found = lint(root, "lever-undeclared")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]
    assert "QUORUM_NOT_A_REAL_LEVER" in found[0].message


def test_lever_unused_via_catalog_monkeypatch(monkeypatch):
    from quorum_tpu.utils import levers
    # concatenated so this test file's own text doesn't count as a
    # usage of the orphan (the scanner reads tests too — by design)
    name = "QUORUM_QLINT_" + "ORPHAN_LEVER"
    fake = dict(levers.CATALOG)
    fake[name] = levers.Lever(name, "bool", "0", "test orphan")
    monkeypatch.setattr(levers, "CATALOG", fake)
    found = run_lint(REPO, ["lever-unused"])
    assert [name in f.message for f in found] == [True]


def test_fault_site_undeclared_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'from .utils import faults\n'
            'faults.inject("totally.made.up")\n',
        "quorum_tpu/good.py":
            'from .utils import faults\n'
            'faults.inject("stage1.insert", batch=3)\n',
    })
    found = lint(root, "fault-site-undeclared")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]


def test_fault_site_unused_via_catalog_monkeypatch(monkeypatch):
    from quorum_tpu.utils import faults
    fake = dict(faults.SITES)
    fake["qlint.test.orphan"] = "a site nothing fires"
    monkeypatch.setattr(faults, "SITES", fake)
    found = run_lint(REPO, ["fault-site-unused"])
    assert ["qlint.test.orphan" in f.message for f in found] == [True]


def test_counter_not_precreated_via_contract_monkeypatch(monkeypatch):
    from quorum_tpu.telemetry import contract
    real = contract.precreated_counter_names()
    monkeypatch.setattr(
        contract, "precreated_counter_names",
        lambda: real + ("qlint_test_ghost_counter_total",))
    found = run_lint(REPO, ["counter-not-precreated"])
    assert ["qlint_test_ghost_counter_total" in f.message
            for f in found] == [True]


HOT_BAD = '''\
import time
import numpy as np

def device_loop(batches, tracer, reg):
    for i, batch in enumerate(batches):
        t0 = time.perf_counter()
        with tracer.step("insert", i):
            state, flag, stats = run_step(batch)
            t1 = time.perf_counter()
            flag = bool(flag)
            t2 = time.perf_counter()
        observe_dispatch_wait(reg, "insert", t0, t1, t2)
        totals = np.asarray(stats)
        untimed = other_sync()

def other_sync():
    import jax
    return 1
'''

HOT_WORSE = '''\
import numpy as np

def device_loop(batches, tracer, reg):
    for i, batch in enumerate(batches):
        with tracer.step("insert", i):
            state, flag = run_step(batch)
        flag = bool(flag)
        observe_dispatch_wait(reg, "insert", 0, 0, 0)
'''


def test_hot_path_sync_seeded_and_clean(tmp_path):
    # the rule's scope is the four device-loop modules by path, so
    # the fixture impersonates one of them. HOT_WORSE: bool(flag) on
    # a step output with NO timer window at all -> finding. HOT_BAD's
    # np.asarray(stats) is a ready-data copy AFTER the timed
    # bool(flag) -> exempt, proving the exemption is narrow.
    root = make_repo(tmp_path, {
        "quorum_tpu/models/create_database.py": HOT_WORSE,
        "quorum_tpu/models/error_correct.py": HOT_BAD,
    })
    found = lint(root, "hot-path-sync")
    assert [f.path for f in found] == [
        "quorum_tpu/models/create_database.py"]
    assert "bool(flag)" in found[0].message


def test_thread_swallowed_exception_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'import threading\n'
            'def start():\n'
            '    def loop():\n'
            '        while True:\n'
            '            try:\n'
            '                work()\n'
            '            except Exception:\n'
            '                pass\n'
            '    threading.Thread(target=loop, daemon=True).start()\n',
        "quorum_tpu/good.py":
            'import threading\n'
            'def start(reg):\n'
            '    def loop():\n'
            '        while True:\n'
            '            try:\n'
            '                work()\n'
            '            except Exception:\n'
            '                reg.counter("loop_errors").inc()\n'
            '    threading.Thread(target=loop, daemon=True).start()\n',
        "quorum_tpu/relay.py":
            'import threading\n'
            'def start(box):\n'
            '    def run():\n'
            '        try:\n'
            '            box["res"] = work()\n'
            '        except BaseException as e:\n'
            '            box["err"] = e\n'
            '    threading.Thread(target=run).start()\n',
    })
    found = lint(root, "thread-swallowed-exception")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]
    assert found[0].line == 7


LOCKY_BAD = '''\
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.depth = 0
    def submit(self):
        with self._lock:
            self.depth += 1
    def reset_unsafe(self):
        self.depth = 0
'''

LOCKY_GOOD = LOCKY_BAD.replace("def reset_unsafe(self):",
                               "def reset_locked(self):")


def test_lock_unguarded_write_seeded_and_clean(tmp_path):
    # scope is by module path: impersonate serve/batcher.py
    bad = make_repo(tmp_path / "bad",
                    {"quorum_tpu/serve/batcher.py": LOCKY_BAD})
    found = lint(bad, "lock-unguarded-write")
    assert [f.line for f in found] == [11]
    assert "depth" in found[0].message
    good = make_repo(tmp_path / "good",
                     {"quorum_tpu/serve/batcher.py": LOCKY_GOOD})
    assert lint(good, "lock-unguarded-write") == []


ORDER_SERVER = '''\
import threading

class CorrectionHTTPServer:
    def __init__(self):
        self._req_lock = threading.Lock()
    def swap_generation(self):
        with self._req_lock:
            return 1
'''

ORDER_BATCHER_BAD = '''\
import threading

class Batcher:
    def __init__(self, srv):
        self._lock = threading.Lock()
        self.srv = srv
    def drain(self):
        with self._lock:
            self.srv.swap_generation()
'''


def test_lock_order_inversion_seeded_and_clean(tmp_path):
    # declared order ranks server._req_lock OUTER of batcher._lock;
    # calling into a _req_lock-taking method while holding the
    # batcher lock is the inversion
    bad = make_repo(tmp_path / "bad", {
        "quorum_tpu/serve/server.py": ORDER_SERVER,
        "quorum_tpu/serve/batcher.py": ORDER_BATCHER_BAD,
    })
    found = lint(bad, "lock-order-inversion")
    assert [f.path for f in found] == ["quorum_tpu/serve/batcher.py"]
    assert "swap_generation" in found[0].message
    # the designed direction (server holds its lock, then calls a
    # distinctively-named batcher method) is clean
    good = make_repo(tmp_path / "good", {
        "quorum_tpu/serve/server.py": '''\
import threading

class CorrectionHTTPServer:
    def __init__(self, b):
        self._req_lock = threading.Lock()
        self.b = b
    def handle(self):
        with self._req_lock:
            self.b.enqueue_corrections()
''',
        "quorum_tpu/serve/batcher.py": '''\
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
    def enqueue_corrections(self):
        with self._lock:
            return 1
''',
    })
    assert lint(good, "lock-order-inversion") == []


JITTY_LEVER_BAD = '''\
import functools
import jax
from .utils import levers

@functools.partial(jax.jit, static_argnums=(1,))
def kernel(x, cap):
    if levers.get_bool("QUORUM_TPU_VERBOSE"):
        return x
    return x + cap
'''

JITTY_LEVER_GOOD = '''\
import functools
import jax
from .utils import levers

def kernel(x):
    verbose = levers.get_bool("QUORUM_TPU_VERBOSE")
    return _kernel_jit(x, verbose)

@functools.partial(jax.jit, static_argnums=(1,))
def _kernel_jit(x, verbose):
    return x + (1 if verbose else 0)
'''


def test_trace_lever_read_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py": JITTY_LEVER_BAD,
        "quorum_tpu/good.py": JITTY_LEVER_GOOD,
    })
    found = lint(root, "trace-lever-read")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]
    assert "TRACE time" in found[0].message


def test_trace_lever_read_env_and_global(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'import jax\n'
            'import os\n'
            '_MODE = "fast"\n'
            '@jax.jit\n'
            'def kernel(x):\n'
            '    global _MODE\n'
            '    if os.environ.get("QUORUM_TPU_VERBOSE"):\n'
            '        return x\n'
            '    return x\n',
    })
    found = lint(root, "trace-lever-read")
    assert len(found) == 2  # the env read and the global statement
    assert all(f.path == "quorum_tpu/bad.py" for f in found)


BRANCHY_BAD = '''\
import jax

@jax.jit
def kernel(x):
    total = x.sum()
    if total > 0:
        return x
    return -x
'''

BRANCHY_GOOD = '''\
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnums=(1,))
def kernel(x, mode, contam=None):
    if mode == "fast":          # static arg: fine
        return x
    if contam is None:          # structural: fine
        return x * 2
    if x.shape[0] > 8:          # shape is static at trace time
        return x * 3
    if len(x) > 4:              # len() is static too
        return x * 4
    return jnp.where(x.sum() > 0, x, -x)
'''


def test_trace_python_branch_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py": BRANCHY_BAD,
        "quorum_tpu/good.py": BRANCHY_GOOD,
    })
    found = lint(root, "trace-python-branch")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]
    assert "'total'" in found[0].message
    assert "lax.cond" in found[0].hint


def test_trace_python_branch_while_and_nested(tmp_path):
    # taint flows through assignments and into nested closures; a
    # nested def's own parameters shadow the traced names
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'import jax\n'
            '@jax.jit\n'
            'def kernel(x):\n'
            '    n = x[0]\n'
            '    while n > 0:\n'
            '        n = n - 1\n'
            '    return n\n',
        "quorum_tpu/good.py":
            'import jax\n'
            '@jax.jit\n'
            'def kernel(x):\n'
            '    def body(n):\n'
            '        return n - 1   # n is the lax-body param\n'
            '    return jax.lax.while_loop(lambda n: n > 0, body,\n'
            '                              x[0])\n',
    })
    found = lint(root, "trace-python-branch")
    assert [f.path for f in found] == ["quorum_tpu/bad.py"]
    assert "while" in found[0].message


def test_jit_unbudgeted_seeded(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'import jax\n'
            '@jax.jit\n'
            'def mystery_kernel(x):\n'
            '    return x\n',
    })
    found = lint(root, "jit-unbudgeted")
    mine = [f for f in found if f.path == "quorum_tpu/bad.py"]
    assert len(mine) == 1
    assert "mystery_kernel" in mine[0].message
    assert "COMPILE_BUDGET" in mine[0].message


def test_jit_unbudgeted_stale_entry_via_monkeypatch(monkeypatch):
    from quorum_tpu.analysis import compile_budget
    fake = dict(compile_budget.COMPILE_BUDGET)
    ghost = "quorum_tpu/ops/ctable.py:qlint_test_ghost_kernel"
    fake[ghost] = compile_budget.Budget(
        ghost, "nothing", "nothing", 1)
    monkeypatch.setattr(compile_budget, "COMPILE_BUDGET", fake)
    found = run_lint(REPO, ["jit-unbudgeted"])
    assert [ghost in f.message for f in found] == [True]
    assert found[0].path == "quorum_tpu/analysis/compile_budget.py"


STATIC_BAD = '''\
import functools
import jax

@functools.partial(jax.jit, static_argnums=(1, 2, 9))
def kernel(x, threshold: float, opts: list, y=None):
    return x
'''

STATIC_GOOD = '''\
import functools
import jax

@functools.partial(jax.jit, static_argnums=(1, 2))
def kernel(x, rounds: int, caps: tuple):
    return x
'''


def test_static_argnum_hazard_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py": STATIC_BAD,
        "quorum_tpu/good.py": STATIC_GOOD,
    })
    found = lint(root, "static-argnum-hazard")
    assert {f.path for f in found} == {"quorum_tpu/bad.py"}
    msgs = " | ".join(f.message for f in found)
    assert "float static argument 'threshold'" in msgs
    assert "unhashable static argument 'opts'" in msgs
    assert "index 9 is out of range" in msgs


def test_budget_catalog_matches_repo_sites():
    """The acceptance shape of the tentpole: the catalog and the live
    jit sites agree in both directions on the tree that ships."""
    assert run_lint(REPO, ["jit-unbudgeted"]) == []


def test_unused_definition_seeded_and_clean(tmp_path):
    root = make_repo(tmp_path, {
        "quorum_tpu/mod.py":
            'import json\n'
            'def orphan_helper():\n'
            '    return 1\n'
            'def used_helper():\n'
            '    return json.dumps({})\n',
        "quorum_tpu/caller.py":
            'from .mod import used_helper\n'
            'print(used_helper())\n',
    })
    found = lint(root, "unused-definition")
    assert [f.message.split()[1] for f in found] == ["orphan_helper"]


def test_unused_definition_tools_is_info_only(tmp_path):
    root = make_repo(tmp_path, {
        "tools/helper.py": 'def never_called():\n    return 1\n',
    })
    found = lint(root, "unused-definition")
    assert [f.severity for f in found] == ["info"]


# -- suppression / baseline semantics -------------------------------------

def test_suppression_parsing():
    src = SourceFile("x.py", "a = 1  # qlint: disable=rule-a,rule-b\n")
    assert src.is_suppressed("rule-a", 1)
    assert src.is_suppressed("rule-b", 1)
    assert not src.is_suppressed("rule-c", 1)
    assert not src.is_suppressed("rule-a", 2)


def test_baseline_matching(tmp_path):
    from quorum_tpu.analysis.core import Finding
    f1 = Finding("r", "a.py", 10, "m")
    f2 = Finding("r", "a.py", 20, "m")
    f3 = Finding("q", "a.py", 10, "m")
    # line-pinned entry absorbs only its line; file-wide absorbs all
    live, used = apply_baseline(
        [f1, f2, f3], [{"rule": "r", "file": "a.py", "line": 10}])
    assert live == [f2, f3] and len(used) == 1
    live, used = apply_baseline(
        [f1, f2, f3], [{"rule": "r", "file": "a.py"}])
    assert live == [f3]
    bad = tmp_path / "b.json"
    bad.write_text('{"findings": [{"rule": "r"}]}')
    with pytest.raises(ValueError):
        load_baseline(str(bad))


def test_cli_baseline_and_strict(tmp_path, capsys):
    root = make_repo(tmp_path, {
        "quorum_tpu/bad.py":
            'def export(path, data):\n'
            '    with open(path, "wb") as f:\n'
            '        f.write(data)\n',
        "README.md": "x\n<!-- qlint:levers -->\n<!-- /qlint:levers -->\n",
    })
    args = ["--root", root, "--rules", "raw-artifact-write", "-q"]
    assert qlint_main(args) == 1
    base = tmp_path / "qlint_baseline.json"
    base.write_text(json.dumps({"findings": [
        {"rule": "raw-artifact-write", "file": "quorum_tpu/bad.py"}]}))
    capsys.readouterr()
    assert qlint_main(args) == 0            # baselined
    assert qlint_main(args + ["--strict"]) == 1  # strict: no parking
    err = capsys.readouterr().err
    assert "baseline" in err


# -- --emit-docs round trip ------------------------------------------------

ALL_REGIONS_README = (
    "# t\n\n<!-- qlint:levers -->\nstale\n<!-- /qlint:levers -->\n"
    "mid\n<!-- qlint:faults -->\nstale2\n<!-- /qlint:faults -->\n"
    "mid2\n<!-- qlint:budget -->\nstale3\n<!-- /qlint:budget -->\n"
    "tail\n")


def test_emit_docs_round_trip(tmp_path, capsys):
    root = make_repo(tmp_path, {
        "quorum_tpu/clean.py": "x = 1\n",
        "README.md": ALL_REGIONS_README,
    })
    assert qlint_main(["--root", root, "--check-docs"]) == 1
    assert qlint_main(["--root", root, "--emit-docs"]) == 0
    text = (tmp_path / "README.md").read_text()
    # all three catalogs rendered, all stale payloads replaced
    assert "QUORUM_TPU_VERBOSE" in text      # levers table
    assert "serve.engine.step" in text       # fault-site table
    assert "_correct_device_packed" in text  # compile-budget table
    assert "stale" not in text
    assert text.endswith("tail\n")
    assert qlint_main(["--root", root, "--check-docs"]) == 0
    # idempotent: emitting again changes nothing
    assert qlint_main(["--root", root, "--emit-docs"]) == 0
    assert (tmp_path / "README.md").read_text() == text


def test_emit_docs_missing_region_is_loud(tmp_path, capsys):
    # a README carrying only the levers markers cannot silently pass:
    # every generated table must have a home (rc 2 names the tag)
    root = make_repo(tmp_path, {
        "quorum_tpu/clean.py": "x = 1\n",
        "README.md": "x\n<!-- qlint:levers -->\n"
                     "<!-- /qlint:levers -->\n",
    })
    assert qlint_main(["--root", root, "--emit-docs"]) == 2
    assert "qlint:faults" in capsys.readouterr().err


# -- the acceptance gate: the REPO ITSELF is clean ------------------------

def test_repo_is_clean_strict():
    findings = run_lint(REPO)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.render() for f in errors)
    entries = load_baseline(os.path.join(REPO, "qlint_baseline.json"))
    assert entries == [], "qlint_baseline.json must stay empty"


def test_repo_docs_in_sync():
    assert qlint_main(["--root", REPO, "--check-docs"]) == 0


def test_metrics_check_imports_contract():
    """The checker's required-name lists must BE the contract objects
    (imported, not copied) — satellite 5's one-source-of-truth."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_check as mc
    finally:
        sys.path.pop(0)
    from quorum_tpu.telemetry import contract
    assert mc.SERVE_FEATURE_COUNTERS is contract.SERVE_FEATURE_COUNTERS
    assert mc.FAULT_COUNTERS is contract.FAULT_COUNTERS
    assert mc.DEVTRACE_COUNTERS is contract.DEVTRACE_COUNTERS


# -- runtime sanitizer ----------------------------------------------------

@pytest.fixture
def sanitizer():
    """Install (if not already via QUORUM_TSAN=1), snapshot the
    violation count, and always reset observed edges afterwards so a
    deliberate test inversion never leaks into the conftest gate."""
    was_installed = tsan.installed()
    tsan.install()
    try:
        yield tsan
    finally:
        tsan.reset()
        if not was_installed:
            tsan.uninstall()


def test_tsan_catches_inversion(sanitizer):
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def ab():
        with lock_a:
            with lock_b:
                pass

    def ba():
        with lock_b:
            with lock_a:
                pass

    before = len(tsan.violations())
    ab()
    t = threading.Thread(target=ba)
    t.start()
    t.join()
    fresh = tsan.violations()[before:]
    assert len(fresh) == 1
    v = fresh[0]
    assert v["held"] != v["acquiring"]
    report = tsan.format_violation(v)
    assert "inversion" in report and "reverse" in report


def test_tsan_clean_nested_and_reentrant(sanitizer):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    rl = threading.RLock()
    before = len(tsan.violations())

    def consistent():
        with lock_a:
            with lock_b:
                with rl:
                    with rl:  # reentrant: no edge, no violation
                        pass

    for _ in range(3):
        consistent()
    t = threading.Thread(target=consistent)
    t.start()
    t.join()
    assert tsan.violations()[before:] == []


def test_tsan_condition_compat(sanitizer):
    # Condition over a wrapped Lock: wait/notify round trip works and
    # records no spurious inversion
    before = len(tsan.violations())
    cond = threading.Condition(threading.Lock())
    got = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            got.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.1)
    with cond:
        cond.notify_all()
    t.join(5)
    assert got == [1]
    assert tsan.violations()[before:] == []
