"""Tests for 2-bit k-mer arithmetic (quorum_tpu.ops.mer)."""

import numpy as np
import jax.numpy as jnp
import pytest

from quorum_tpu.ops import mer


def ref_revcomp(s):
    comp = {"A": "T", "C": "G", "G": "C", "T": "A"}
    return "".join(comp[c] for c in reversed(s))


@pytest.mark.parametrize("k", [5, 16, 17, 24, 31])
def test_pack_unpack_roundtrip(k):
    rng = np.random.default_rng(42 + k)
    for _ in range(20):
        s = "".join(rng.choice(list("ACGT"), size=k))
        hi, lo = mer.pack_kmer(s)
        assert mer.unpack_kmer(hi, lo, k) == s


@pytest.mark.parametrize("k", [5, 16, 24, 31])
def test_revcomp_and_canonical(k):
    rng = np.random.default_rng(7 + k)
    for _ in range(20):
        s = "".join(rng.choice(list("ACGT"), size=k))
        hi, lo = mer.pack_kmer(s)
        rhi, rlo = mer.revcomp_py(hi, lo, k)
        assert mer.unpack_kmer(rhi, rlo, k) == ref_revcomp(s)
        chi, clo = mer.canonical_py(hi, lo, k)
        expect = min(s, ref_revcomp(s))
        assert mer.unpack_kmer(chi, clo, k) == expect


@pytest.mark.parametrize("k", [5, 16, 17, 24, 31])
def test_rolling_kmers_match_host(k):
    rng = np.random.default_rng(3 + k)
    L = 60
    B = 4
    seqs = []
    for _ in range(B):
        s = "".join(rng.choice(list("ACGTN"), size=L, p=[0.24, 0.24, 0.24, 0.24, 0.04]))
        seqs.append(s)
    codes = np.stack([mer.seq_to_codes(s) for s in seqs]).astype(np.int32)
    fhi, flo, rhi, rlo, valid = mer.rolling_kmers(jnp.asarray(codes), k)
    fhi, flo, rhi, rlo, valid = map(np.asarray, (fhi, flo, rhi, rlo, valid))
    for b, s in enumerate(seqs):
        for p in range(L):
            window = s[p - k + 1 : p + 1] if p >= k - 1 else ""
            ok = len(window) == k and all(c in "ACGT" for c in window)
            assert bool(valid[b, p]) == ok, (b, p, window)
            if ok:
                assert mer.unpack_kmer(fhi[b, p], flo[b, p], k) == window
                assert (
                    mer.unpack_kmer(rhi[b, p], rlo[b, p], k) == ref_revcomp(window)
                )


def test_shift_and_base_ops():
    k = 24
    s = "ACGTACGTACGTACGTACGTACGT"
    hi, lo = mer.pack_kmer(s)
    hi_j, lo_j = jnp.uint32(hi), jnp.uint32(lo)
    # shift_left appends at base 0
    nhi, nlo = mer.shift_left(hi_j, lo_j, jnp.uint32(2), k)
    assert mer.unpack_kmer(int(nhi), int(nlo), k) == s[1:] + "G"
    # shift_right inserts at base k-1
    nhi, nlo = mer.shift_right(hi_j, lo_j, jnp.uint32(1), k)
    assert mer.unpack_kmer(int(nhi), int(nlo), k) == "C" + s[:-1]
    # get/set base 0 and k-1
    assert int(mer.get_base(hi_j, lo_j, 0, k)) == 3  # T
    assert int(mer.get_base(hi_j, lo_j, k - 1, k)) == 0  # A
    shi, slo = mer.set_base(hi_j, lo_j, 0, jnp.uint32(1), k)
    assert mer.unpack_kmer(int(shi), int(slo), k) == s[:-1] + "C"
    shi, slo = mer.set_base(hi_j, lo_j, k - 1, jnp.uint32(3), k)
    assert mer.unpack_kmer(int(shi), int(slo), k) == "T" + s[1:]
