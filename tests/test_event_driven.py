"""Event-driven stepping (round 4): bit-parity of the teleporting loop
against the plain lockstep loop on data shaped to exercise every new
mechanism — long clean runs (teleports), isolated and clustered errors
(tail probes, incl. tail stops), ambiguous sites after teleported runs
(lazy-prev backscan), N bases, and tiny compaction capacities (stall
paths). The plain loop (event_driven=False) is itself pinned to the
oracle by tests/test_corrector.py, so parity here closes the chain."""

import numpy as np
import jax.numpy as jnp
import pytest

from quorum_tpu.ops import ctable
from quorum_tpu.models import corrector
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.models.create_database import extract_observations

K, RLEN, B = 9, 50, 1024
BASES = "ACGT"


def _build(rng, codes, quals):
    meta = ctable.TileMeta(k=K, bits=7, rb_log2=ctable.tile_rb_for(
        200_000, K, 7))
    bstate = ctable.make_tile_build(meta)
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), K, 38)
    bstate, full, _ = ctable.tile_insert_observations(
        bstate, meta, chi, clo, q, valid)
    assert not full
    return ctable.tile_finalize(bstate, meta), meta


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(7)
    genome = rng.integers(0, 4, size=2000, dtype=np.int8)
    starts = rng.integers(0, len(genome) - RLEN, size=B)
    codes = genome[starts[:, None] + np.arange(RLEN)[None, :]].astype(np.int8)
    errs = rng.random(codes.shape) < 0.02
    # clustered errors (within k) on a slice of reads: tail-stop paths
    errs[:64, 20] = True
    errs[:64, 24] = True
    codes = np.where(errs, (codes + rng.integers(1, 4, size=codes.shape)) % 4,
                     codes).astype(np.int8)
    # N bases on another slice
    codes[64:96, 30] = -1
    quals = np.full(codes.shape, 70, np.uint8)
    quals[errs] = 68
    state, meta = _build(rng, codes, quals)
    return codes, quals, state, meta


def _run(batch, event_driven, ambig_cap=None):
    codes, quals, state, meta = batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    lengths = jnp.full((B,), RLEN, jnp.int32)
    return corrector.correct_batch(state, meta, jnp.asarray(codes),
                                   jnp.asarray(quals), lengths, cfg,
                                   ambig_cap=ambig_cap,
                                   event_driven=event_driven)


def _assert_same(a, b):
    np.testing.assert_array_equal(np.asarray(a.out), np.asarray(b.out))
    np.testing.assert_array_equal(np.asarray(a.start), np.asarray(b.start))
    np.testing.assert_array_equal(np.asarray(a.end), np.asarray(b.end))
    np.testing.assert_array_equal(np.asarray(a.status), np.asarray(b.status))
    for name in corrector.LogState._fields:
        if name == "lwin":  # internal scratch; n/pos/meta are the output
            continue
        for la, lb in ((a.fwd_log, b.fwd_log), (a.bwd_log, b.bwd_log)):
            av, bv = np.asarray(getattr(la, name)), np.asarray(
                getattr(lb, name))
            if name in ("pos", "meta"):
                # compare only the live entries
                n = np.asarray(a.fwd_log.n if la is a.fwd_log else
                               a.bwd_log.n)
                w = min(av.shape[1], bv.shape[1])
                msk = np.arange(w)[None, :] < n[:, None]
                np.testing.assert_array_equal(
                    np.where(msk, av[:, :w], 0), np.where(msk, bv[:, :w], 0))
            else:
                np.testing.assert_array_equal(av, bv)


def test_planes_actually_teleport(batch):
    """The fixture must genuinely exercise the fast path: most
    positions provably clean, and the ambig pre-pass must cover a
    nonempty set of positions."""
    codes, quals, state, meta = batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    codes32 = jnp.asarray(codes, jnp.int32)
    sweep = corrector._position_sweep(
        state, meta, codes32, cfg, *corrector._dummy_contam(K), False)
    lengths = jnp.full((B,), RLEN, jnp.int32)
    start_off = jnp.full((B,), K + 1, jnp.int32)
    planes = corrector._event_planes(
        state, meta, sweep, codes32, jnp.asarray(quals, jnp.int32),
        lengths, start_off, cfg, RLEN, max(256, (B * RLEN) // 16))
    clean = np.asarray(planes.clean)[:B, K - 1:]
    assert clean.mean() > 0.5, f"fixture too dirty ({clean.mean():.2f})"
    pre = (np.asarray(planes.aux) >> corrector._AX_PRE) & 1
    assert pre.sum() > 0, "ambig pre-pass covered nothing"


def test_event_parity(batch):
    _assert_same(_run(batch, True), _run(batch, False))


def test_event_parity_tiny_ambig_cap(batch):
    """ambig-cap stalls interleaved with backscan stalls."""
    _assert_same(_run(batch, True, ambig_cap=1), _run(batch, False))


@pytest.mark.parametrize("homo", [None, 2])
def test_finish_lean_parity(batch, homo):
    """The lean finish path (no seq plane, compacted entries) must
    produce identical ReadResults to the packed-plane path, including
    under homo-trim entry edits — and the FUSED pack (the buffer
    produced inside the correction executable, the production CLI
    path) must match too, including with a cap that forces the
    overflow re-pack."""
    codes, quals, state, meta = batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32", homo_trim=homo)
    lengths = jnp.full((B,), RLEN, jnp.int32)
    res = corrector.correct_batch(state, meta, jnp.asarray(codes),
                                  jnp.asarray(quals), lengths, cfg,
                                  event_driven=True)
    wide = corrector.finish_batch(res, B, cfg)
    lean = corrector.finish_batch(res, B, cfg, codes=codes)
    assert wide == lean
    res2, packed = corrector.correct_batch(
        state, meta, jnp.asarray(codes), jnp.asarray(quals), lengths,
        cfg, event_driven=True, pack_cap=4 * B)
    fused = corrector.finish_batch(res2, B, cfg, codes=codes,
                                   packed=packed)
    assert wide == fused
    # a too-small fused cap must trigger the exact-size re-pack
    res3, packed3 = corrector.correct_batch(
        state, meta, jnp.asarray(codes), jnp.asarray(quals), lengths,
        cfg, event_driven=True, pack_cap=8)
    small = corrector.finish_batch(res3, B, cfg, codes=codes,
                                   packed=packed3)
    assert wide == small


def test_event_parity_variable_lengths(batch):
    """Non-uniform lengths take the gather-path planes remap."""
    codes, quals, state, meta = batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    rng = np.random.default_rng(3)
    lengths = rng.integers(K + 5, RLEN + 1, size=B).astype(np.int32)
    c = codes.copy()
    for i, ln in enumerate(lengths):
        c[i, ln:] = -2
    a = corrector.correct_batch(state, meta, jnp.asarray(c),
                                jnp.asarray(quals), jnp.asarray(lengths),
                                cfg, event_driven=True)
    b = corrector.correct_batch(state, meta, jnp.asarray(c),
                                jnp.asarray(quals), jnp.asarray(lengths),
                                cfg, event_driven=False)
    _assert_same(a, b)
