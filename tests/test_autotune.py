"""Autotune profiles (ops/tuning.py + quorum-autotune, ISSUE 11):
sealed-profile round trip, the env > profile > default resolution
order at every lever, tamper/backend refusal, the winner-decision
hysteresis, and the meta.autotune_profile stamp."""

import json
import os

import pytest

from quorum_tpu.cli import autotune
from quorum_tpu.models import corrector
from quorum_tpu.ops import ctable, tuning


@pytest.fixture(autouse=True)
def clean_tuning(monkeypatch, tmp_path):
    """Isolate every test from ambient profiles: point the profile
    dir at an empty tmp dir and clear the parse cache around each
    test."""
    monkeypatch.delenv("QUORUM_AUTOTUNE_PROFILE", raising=False)
    monkeypatch.setenv("QUORUM_AUTOTUNE_DIR", str(tmp_path / "prof"))
    for env in tuning.LEVER_ENVS + tuning.CAP_ENVS:
        monkeypatch.delenv(env, raising=False)
    tuning.reset_cache()
    yield
    tuning.reset_cache()


def write(tmp_path, levers, backend=None, caps=None, name="p.json"):
    path = str(tmp_path / name)
    tuning.write_profile(path, backend or tuning.backend_name(),
                         {"reads": 64, "read_len": 32, "k": 13},
                         levers, caps=caps)
    return path


def test_profile_round_trip_and_resolution_order(tmp_path,
                                                 monkeypatch):
    path = write(tmp_path, {"QUORUM_S1_AGGREGATE": "0",
                            "QUORUM_COMPACT_SWEEP": "1",
                            "QUORUM_DRAIN_LEVELS": "1"},
                 caps={"QUORUM_AMBIG_CAP": 512,
                       "QUORUM_S1_AGG_CAP_FRAC": 0.25})
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", path)
    tuning.reset_cache()
    assert tuning.active_profile_path() == path
    # profile steers every lever...
    assert ctable.s1_aggregate_default() is False
    assert corrector.compact_sweep_default() is True
    assert corrector.drain_levels_default() == 1
    assert tuning.cap("QUORUM_AMBIG_CAP", 99) == 512.0
    # ...but an explicit env var ALWAYS wins
    monkeypatch.setenv("QUORUM_S1_AGGREGATE", "1")
    monkeypatch.setenv("QUORUM_COMPACT_SWEEP", "0")
    monkeypatch.setenv("QUORUM_DRAIN_LEVELS", "2")
    monkeypatch.setenv("QUORUM_AMBIG_CAP", "64")
    assert ctable.s1_aggregate_default() is True
    assert corrector.compact_sweep_default() is False
    assert corrector.drain_levels_default() == 2
    assert tuning.cap("QUORUM_AMBIG_CAP", 99) == 64.0


def test_no_profile_keeps_backend_keyed_defaults():
    assert tuning.active_profile_path() is None
    assert ctable.s1_aggregate_default() is True
    # CPU test environment: stage-2 levers default off
    assert corrector.compact_sweep_default() is \
        ctable.accel_backend()


def test_agg_cap_fraction_steers_capacity(monkeypatch, tmp_path):
    assert ctable.agg_cap_for(65536) == 32768  # default half
    path = write(tmp_path, {"QUORUM_S1_AGGREGATE": "1"},
                 caps={"QUORUM_S1_AGG_CAP_FRAC": 0.25})
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", path)
    tuning.reset_cache()
    assert ctable.agg_cap_for(65536) == 16384
    monkeypatch.setenv("QUORUM_S1_AGG_CAP_FRAC", "1.0")
    assert ctable.agg_cap_for(65536) == 65536
    monkeypatch.setenv("QUORUM_S1_AGG_CAP_FRAC", "7.0")  # nonsense
    assert ctable.agg_cap_for(65536) == 32768  # clamped to default


def test_tampered_profile_is_refused(tmp_path, monkeypatch,
                                     capsys):
    path = write(tmp_path, {"QUORUM_S1_AGGREGATE": "0"})
    doc = json.load(open(path))
    doc["levers"]["QUORUM_S1_AGGREGATE"] = "1"  # hand edit
    json.dump(doc, open(path, "w"))
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", path)
    tuning.reset_cache()
    assert tuning.load_profile() is None
    assert tuning.active_profile_path() is None
    assert ctable.s1_aggregate_default() is True  # built-in default
    assert "failed its header self-digest" in capsys.readouterr().err


def test_unsealed_profile_is_refused(tmp_path, monkeypatch):
    path = str(tmp_path / "unsealed.json")
    json.dump({"schema": tuning.PROFILE_SCHEMA,
               "backend": tuning.backend_name(),
               "levers": {"QUORUM_S1_AGGREGATE": "0"}},
              open(path, "w"))
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", path)
    tuning.reset_cache()
    assert tuning.load_profile() is None


def test_foreign_backend_profile_never_applies(tmp_path,
                                               monkeypatch):
    path = write(tmp_path, {"QUORUM_S1_AGGREGATE": "0"},
                 backend="tpu-imaginary")
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", path)
    tuning.reset_cache()
    assert tuning.load_profile() is None
    assert ctable.s1_aggregate_default() is True


def test_empty_env_disables_profiles(tmp_path, monkeypatch):
    # a valid default-dir profile exists...
    d = tmp_path / "prof"
    d.mkdir()
    tuning.write_profile(str(d / f"{tuning.backend_name()}.json"),
                         tuning.backend_name(), {},
                         {"QUORUM_S1_AGGREGATE": "0"})
    tuning.reset_cache()
    assert ctable.s1_aggregate_default() is False
    # ...until QUORUM_AUTOTUNE_PROFILE= (empty) opts out entirely
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", "")
    tuning.reset_cache()
    assert tuning.active_profile_path() is None
    assert ctable.s1_aggregate_default() is True


def test_decide_hysteresis():
    m = {"s1_base_s": 1.0, "s1_agg_s": 0.8,
         "s2_base_s": 1.0, "s2_sweep_s": 0.7,
         "s2_sweep_drain_s": 0.6}
    lev = autotune.decide(m)
    assert lev == {"QUORUM_S1_AGGREGATE": "1",
                   "QUORUM_COMPACT_SWEEP": "1",
                   "QUORUM_DRAIN_LEVELS": "2"}
    # a within-noise "win" keeps the incumbent
    m = {"s1_base_s": 1.0, "s1_agg_s": 0.995,
         "s2_base_s": 1.0, "s2_sweep_s": 0.99,
         "s2_sweep_drain_s": 0.995}
    lev = autotune.decide(m)
    assert lev == {"QUORUM_S1_AGGREGATE": "0",
                   "QUORUM_COMPACT_SWEEP": "0",
                   "QUORUM_DRAIN_LEVELS": "0"}
    # sweep alone wins, drain loses
    m = {"s1_base_s": 1.0, "s1_agg_s": 2.0,
         "s2_base_s": 1.0, "s2_sweep_s": 0.5,
         "s2_sweep_drain_s": 1.5}
    lev = autotune.decide(m)
    assert lev == {"QUORUM_S1_AGGREGATE": "0",
                   "QUORUM_COMPACT_SWEEP": "1",
                   "QUORUM_DRAIN_LEVELS": "0"}


def test_observability_stamps_autotune_profile(tmp_path,
                                               monkeypatch):
    from quorum_tpu.cli.observability import observability
    path = write(tmp_path, {"QUORUM_S1_AGGREGATE": "1"})
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", path)
    tuning.reset_cache()
    mp = tmp_path / "m.json"
    with observability(str(mp), stage="test"):
        pass
    doc = json.load(open(mp))
    assert doc["meta"]["autotune_profile"] == path
    # and without a profile the stamp is absent
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", "")
    tuning.reset_cache()
    mp2 = tmp_path / "m2.json"
    with observability(str(mp2), stage="test"):
        pass
    assert "autotune_profile" not in json.load(open(mp2))["meta"]


def test_profile_cache_tracks_mtime(tmp_path, monkeypatch):
    path = write(tmp_path, {"QUORUM_S1_AGGREGATE": "0"})
    monkeypatch.setenv("QUORUM_AUTOTUNE_PROFILE", path)
    tuning.reset_cache()
    assert ctable.s1_aggregate_default() is False
    # a re-tune replaces the file: resolution follows WITHOUT a
    # process restart (write_profile also clears the cache, but an
    # external writer only moves mtime/size)
    tuning.write_profile(path, tuning.backend_name(), {},
                         {"QUORUM_S1_AGGREGATE": "1"})
    assert ctable.s1_aggregate_default() is True
