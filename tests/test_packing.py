"""Bit-packed read transport (io/packing.py): the wire format must be
a pure re-encoding — device-side widening reproduces the exact code
array, and both stage entry points produce bit-identical results
through the packed path. (The packed path is what the CLIs ship over
the tunnel; these tests close the parity chain back to the oracle via
tests/test_corrector.py and tests/test_ctable.py.)"""

import numpy as np
import jax.numpy as jnp
import pytest

from quorum_tpu.io import packing
from quorum_tpu.ops import ctable, mer
from quorum_tpu.models import corrector
from quorum_tpu.models.create_database import extract_observations
from quorum_tpu.models.ec_config import ECConfig

K, RLEN, B = 9, 50, 512


def _random_reads(rng, b=B, lmax=RLEN, uniform=False):
    genome = rng.integers(0, 4, size=2000, dtype=np.int8)
    starts = rng.integers(0, len(genome) - lmax, size=b)
    codes = genome[starts[:, None] + np.arange(lmax)[None, :]].astype(np.int8)
    errs = rng.random(codes.shape) < 0.02
    codes = np.where(errs, (codes + rng.integers(1, 4, size=codes.shape)) % 4,
                     codes).astype(np.int8)
    codes[5:9, 30] = -1  # N bases
    quals = np.full(codes.shape, 70, np.uint8)
    quals[errs] = 68
    quals[rng.random(codes.shape) < 0.1] = 30
    if uniform:
        lengths = np.full(b, lmax, np.int32)
    else:
        lengths = rng.integers(K + 2, lmax + 1, size=b).astype(np.int32)
        pos = np.arange(lmax)[None, :]
        codes = np.where(pos >= lengths[:, None], -2, codes).astype(np.int8)
        quals = np.where(pos >= lengths[:, None], 0, quals).astype(np.uint8)
    return codes, quals, lengths


@pytest.mark.parametrize("lmax", [RLEN, 47])  # 47: L % 4 != 0, L % 8 != 0
def test_roundtrip(lmax):
    rng = np.random.default_rng(3)
    codes, quals, lengths = _random_reads(rng, lmax=lmax)
    p = packing.pack_reads(codes, quals, lengths, thresholds=(38, 65))
    got = np.asarray(mer.unpack_codes_device(
        jnp.asarray(p.pcodes), jnp.asarray(p.nmask),
        jnp.asarray(lengths), lmax))
    np.testing.assert_array_equal(got, codes.astype(np.int32))
    for t in (38, 65):
        syn = np.asarray(mer.synth_quals_device(jnp.asarray(p.hq[t]),
                                                lmax, t))
        np.testing.assert_array_equal(syn >= t, quals >= t)
    # the whole point: the wire is 4x smaller than int8+uint8
    assert p.nbytes < (codes.nbytes + quals.nbytes) / 2.5


def _build_db(codes, quals):
    meta = ctable.TileMeta(k=K, bits=7,
                           rb_log2=ctable.tile_rb_for(200_000, K, 7))
    bstate = ctable.make_tile_build(meta)
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), K, 38)
    bstate, full, _ = ctable.tile_insert_observations(
        bstate, meta, chi, clo, q, valid)
    assert not full
    return ctable.tile_finalize(bstate, meta), meta


@pytest.mark.parametrize("uniform", [True, False])
def test_corrector_parity(uniform):
    rng = np.random.default_rng(11)
    codes, quals, lengths = _random_reads(rng, uniform=uniform)
    state, meta = _build_db(codes, quals)
    cfg = ECConfig(k=K, cutoff=4, qual_cutoff=65, poisson_dtype="float32")
    ref = corrector.correct_batch(state, meta, jnp.asarray(codes),
                                  jnp.asarray(quals),
                                  jnp.asarray(lengths, jnp.int32), cfg)
    p = packing.pack_reads(codes, quals, lengths,
                           thresholds=(cfg.qual_cutoff,))
    got = corrector.correct_batch_packed(state, meta, p, cfg)
    np.testing.assert_array_equal(np.asarray(ref.out), np.asarray(got.out))
    np.testing.assert_array_equal(np.asarray(ref.start),
                                  np.asarray(got.start))
    np.testing.assert_array_equal(np.asarray(ref.end), np.asarray(got.end))
    np.testing.assert_array_equal(np.asarray(ref.status),
                                  np.asarray(got.status))
    for la, lb in ((ref.fwd_log, got.fwd_log), (ref.bwd_log, got.bwd_log)):
        np.testing.assert_array_equal(np.asarray(la.n), np.asarray(lb.n))
        n = np.asarray(la.n)
        msk = np.arange(la.pos.shape[1])[None, :] < n[:, None]
        for name in ("pos", "meta"):
            av = np.asarray(getattr(la, name))
            bv = np.asarray(getattr(lb, name))
            np.testing.assert_array_equal(np.where(msk, av, 0),
                                          np.where(msk, bv, 0))


def test_insert_parity():
    rng = np.random.default_rng(5)
    codes, quals, lengths = _random_reads(rng)
    meta = ctable.TileMeta(k=K, bits=7,
                           rb_log2=ctable.tile_rb_for(200_000, K, 7))

    b1 = ctable.make_tile_build(meta)
    b1, full1, _ = ctable.tile_insert_reads(
        b1, meta, jnp.asarray(codes), jnp.asarray(quals), 38)
    assert not full1
    s1 = ctable.tile_finalize(b1, meta)

    p = packing.pack_reads(codes, quals, lengths, thresholds=(38,))
    b2 = ctable.make_tile_build(meta)
    b2, full2, _ = ctable.tile_insert_reads_packed(b2, meta, p, 38)
    assert not full2
    s2 = ctable.tile_finalize(b2, meta)

    # same finalized table, entry for entry (iterate is order-stable:
    # it walks buckets/slots)
    for a, b in zip(ctable.tile_iterate(s1, meta),
                    ctable.tile_iterate(s2, meta)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compact_and_wire_surface():
    """compact() keeps only the wire + geometry; the packed entry
    points' guards behave on both forms."""
    rng = np.random.default_rng(21)
    codes, quals, lengths = _random_reads(rng, b=32)
    p = packing.pack_reads(codes, quals, lengths, thresholds=(38,))
    w = p.to_wire()
    c = p.compact()
    assert c.pcodes is None and c.n_reads == 32
    assert np.array_equal(c.to_wire(), w)
    c.require_plane(38)
    with pytest.raises(KeyError, match="lacks the qual>=99"):
        c.require_plane(99)
    # a compacted batch that somehow lost its wire must fail loudly
    c2 = packing.PackedReads(pcodes=None, nmask=None, hq={38: None},
                             lengths=p.lengths, length=p.length, _b=32)
    with pytest.raises(ValueError, match="lost its planes"):
        c2.to_wire()
    # nbytes counts only live arrays (lengths ride inside the wire)
    assert c.nbytes == w.nbytes


def test_multihost_refusal(monkeypatch):
    """The single-chip CLIs refuse multi-process runs (their state is
    host-local; parallel/multihost + tile_sharded is the path)."""
    import jax
    from quorum_tpu.models.create_database import BuildConfig, \
        build_database
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(RuntimeError, match="multi-host build"):
        build_database(["/nonexistent.fastq"], BuildConfig(k=9))
