"""Round 7 (ISSUE 6): the compacted sibling sweep, the lane-draining
extension loop, stage-1 batch-local insert pre-aggregation, and the
satellite surfaces (journaled heartbeat JSONL, native-parser fault
site, driver replay-cache resume, bench A/B gating, span export into
the profile dir).

The corrector parity chain: the plain lockstep loop is pinned to the
oracle (tests/test_corrector.py), the event-driven loop to the plain
loop (tests/test_event_driven.py); here each round-7 lever is pinned
bit-exact against the path it replaces, closing the chain for the
production default (compact sweep + drained loop)."""

import conftest  # noqa: F401  (pins CPU devices)

import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quorum_tpu.io import checkpoint as ckpt_mod
from quorum_tpu.io import db_format, packing
from quorum_tpu.models import corrector
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.ops import ctable
from quorum_tpu.utils import faults

from test_event_driven import _assert_same

K, RLEN, B = 9, 48, 512


def _build(codes, quals):
    meta = ctable.TileMeta(k=K, bits=7,
                           rb_log2=ctable.tile_rb_for(100_000, K, 7))
    bstate = ctable.make_tile_build(meta)
    chi, clo, q, valid = ctable.extract_observations_impl(
        jnp.asarray(codes), jnp.asarray(quals), K, 38)
    bstate, full, _ = ctable.tile_insert_observations(
        bstate, meta, chi, clo, q, valid)
    assert not full
    return ctable.tile_finalize(bstate, meta), meta


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(11)
    genome = rng.integers(0, 4, size=1500, dtype=np.int8)
    starts = rng.integers(0, len(genome) - RLEN, size=B)
    codes = genome[starts[:, None] + np.arange(RLEN)[None, :]].astype(
        np.int8)
    errs = rng.random(codes.shape) < 0.02
    errs[:32, 18] = True
    errs[:32, 22] = True  # clustered: tail-stop paths
    codes = np.where(errs,
                     (codes + rng.integers(1, 4, size=codes.shape)) % 4,
                     codes).astype(np.int8)
    codes[32:48, 25] = -1  # N bases
    quals = np.full(codes.shape, 70, np.uint8)
    quals[errs] = 68
    # a low-quality stripe so some own-mers are LQ (candidate class)
    quals[48:64, 10:20] = 33
    state, meta = _build(codes, quals)
    return codes, quals, state, meta


def _run(batch, compact, drain, event=True):
    codes, quals, state, meta = batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    lengths = jnp.full((B,), RLEN, jnp.int32)
    return corrector.correct_batch(state, meta, jnp.asarray(codes),
                                   jnp.asarray(quals), lengths, cfg,
                                   event_driven=event,
                                   compact_sweep=compact,
                                   drain_levels=drain)


# ---------------------------------------------------------------------------
# Tentpole 1: compacted sibling sweep
# ---------------------------------------------------------------------------

def test_compact_sweep_parity(batch):
    """Compacted sibling sweep vs the full-width sweep, drain
    isolated off: byte-identical correction."""
    _assert_same(_run(batch, True, 0), _run(batch, False, 0))


def test_compact_sweep_planes_consumed_parity(batch):
    """The CONSUMED plane surfaces are bit-exact: clean and nd
    everywhere, cnt/aux at every non-clean (event) position, and the
    c1keep/prev chain (lastc1/prevval) at every consumption point —
    the exactness argument behind the count==1 circularity fix."""
    codes, quals, state, meta = batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    codes32 = jnp.asarray(codes, jnp.int32)
    quals32 = jnp.asarray(quals, jnp.int32)
    lengths = jnp.full((B,), RLEN, jnp.int32)
    start_off = jnp.full((B,), K + 1, jnp.int32)
    sweep = corrector._position_sweep(
        state, meta, codes32, cfg, *corrector._dummy_contam(K), False)
    cap = max(256, (B * RLEN) // 16)
    full = corrector._event_planes(state, meta, sweep, codes32, quals32,
                                   lengths, start_off, cfg, RLEN, cap,
                                   compact_sweep=False)
    comp = corrector._event_planes(state, meta, sweep, codes32, quals32,
                                   lengths, start_off, cfg, RLEN, cap,
                                   compact_sweep=True)
    clean_f = np.asarray(full.clean)
    np.testing.assert_array_equal(clean_f, np.asarray(comp.clean))
    np.testing.assert_array_equal(np.asarray(full.nd),
                                  np.asarray(comp.nd))
    ev = ~clean_f
    np.testing.assert_array_equal(
        np.where(ev, np.asarray(full.cnt), 0),
        np.where(ev, np.asarray(comp.cnt), 0))
    # aux at events: every consumed bit field (level/count/ucode/pre/
    # succ/cwn) — mask off the chain's C1K bit, which the compact path
    # resolves separately
    m = np.uint32(~(1 << corrector._AX_C1K) & 0xFFFFFFFF)
    np.testing.assert_array_equal(
        np.where(ev, np.asarray(full.aux) & m, 0),
        np.where(ev, np.asarray(comp.aux) & m, 0))
    # chain at consumption points t: same last prev-definer, or both
    # below the lowest position the chain can be consumed FROM — the
    # teleport guard is `lc >= pos` with pos inside t's clean run AND
    # at/after the frame's extension start (fwd: start_off; rc:
    # lengths - start_off + k), so smaller values are never read
    l = clean_f.shape[1]
    p = np.arange(l)[None, :]
    ln = np.asarray(lengths)
    so = np.asarray(start_off)
    lengths2 = np.concatenate([ln, ln])[:, None]
    min_pos = np.concatenate([so, ln - so + K])[:, None]
    nxt_nonclean = np.concatenate(
        [~clean_f[:, 1:], np.ones((clean_f.shape[0], 1), bool)], axis=1)
    cp = clean_f & (p < lengths2) & (nxt_nonclean | (p == lengths2 - 1))
    run_start = np.maximum.accumulate(
        np.where(~clean_f, p, -1), axis=1) + 1
    floor = np.maximum(run_start, min_pos)
    lc_f = np.asarray(full.lastc1)
    lc_c = np.asarray(comp.lastc1)
    same = lc_f == lc_c
    both_dead = (lc_f < floor) & (lc_c < floor)
    assert np.all(~cp | same | both_dead)
    pv_f = np.asarray(full.prevval)
    pv_c = np.asarray(comp.prevval)
    live = cp & (lc_f >= floor)
    assert live.any()
    np.testing.assert_array_equal(np.where(live, pv_f, 0),
                                  np.where(live, pv_c, 0))


# ---------------------------------------------------------------------------
# Tentpole 2: lane-draining extension loop
# ---------------------------------------------------------------------------

def test_drain_parity(batch):
    """Two-level lane draining vs the single-level loop: byte-
    identical correction. Both sides run the compacted sweep, so the
    only varying lever is the drain — and both executables are reused
    from the neighbouring parity tests (compile-budget discipline:
    tier-1 runs the whole suite under one timeout)."""
    _assert_same(_run(batch, True, 2), _run(batch, True, 0))


def test_production_default_parity_vs_plain(batch):
    """The full round-7 production default (compact sweep + drained
    loop) against the oracle-pinned plain lockstep loop."""
    _assert_same(_run(batch, True, 2), _run(batch, False, 0, event=False))


def test_routed_compact_drain_parity(batch, monkeypatch):
    """The levers under the ROUTED sharded corrector: every lookup is
    a collective, so the compact sweep's chunk loop, the c1k walk, and
    the drain levels must all stay in lockstep across shards (their
    conds pmax). Shard 0 gets clean reads and shard 1 error-heavy ones
    so per-shard candidate counts, walk depths, and live-lane counts
    genuinely diverge — a lost pmax here deadlocks or corrupts."""
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from quorum_tpu.parallel import tile_sharded as ts
    if len(_jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    monkeypatch.setenv("QUORUM_COMPACT_SWEEP", "1")
    monkeypatch.setenv("QUORUM_DRAIN_LEVELS", "2")
    codes, quals, state, meta = batch
    nb = 16
    c = codes[:nb].copy()
    q = quals[:nb].copy()
    c[:nb // 2] = codes[256:256 + nb // 2]  # clean-ish half
    rng = np.random.default_rng(5)
    errs = rng.random(c[nb // 2:].shape) < 0.06
    c[nb // 2:] = np.where(
        errs, (c[nb // 2:] + rng.integers(1, 4, size=errs.shape)) % 4,
        c[nb // 2:]).astype(np.int8)
    lengths = np.full((nb,), RLEN, np.int32)
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    mesh = ts.make_mesh(2)
    smeta = ts.TileShardedMeta(k=K, bits=7, rb_log2=meta.rb_log2,
                               n_shards=2)
    rows = _jax.device_put(state.rows,
                           NamedSharding(mesh, P(ts.AXIS)))
    step = ts.correct_step_routed(mesh, smeta, cfg)
    res = step(ctable.TileState(rows), jnp.asarray(c), jnp.asarray(q),
               jnp.asarray(lengths))
    # single-chip reference rides the FULL-batch executable the other
    # parity tests already compiled (batch composition is unobservable
    # per lane — caps/stalls are pure delay): embed the 16 reads in a
    # B-row batch and compare the first 16 rows
    c512 = codes.copy()
    q512 = quals.copy()
    c512[:nb] = c
    q512[:nb] = q
    single = corrector.correct_batch(
        state, meta, jnp.asarray(c512), jnp.asarray(q512),
        jnp.full((B,), RLEN, jnp.int32), cfg,
        compact_sweep=True, drain_levels=2)
    for name in ("out", "start", "end", "status"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res, name)),
            np.asarray(getattr(single, name))[:nb])


def test_variable_lengths_compact_drain(batch):
    """Non-uniform lengths through the gather-path remap with both
    levers on."""
    codes, quals, state, meta = batch
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")
    rng = np.random.default_rng(3)
    lengths = rng.integers(K + 5, RLEN + 1, size=B).astype(np.int32)
    c = codes.copy()
    for i, ln in enumerate(lengths):
        c[i, ln:] = -2
    a = corrector.correct_batch(state, meta, jnp.asarray(c),
                                jnp.asarray(quals), jnp.asarray(lengths),
                                cfg, compact_sweep=True, drain_levels=2)
    b = corrector.correct_batch(state, meta, jnp.asarray(c),
                                jnp.asarray(quals), jnp.asarray(lengths),
                                cfg, compact_sweep=False, drain_levels=0)
    _assert_same(a, b)


# ---------------------------------------------------------------------------
# Tentpole 3: stage-1 batch-local pre-aggregation
# ---------------------------------------------------------------------------

def test_aggregate_obs_unit():
    """Sort/segment/compact semantics: sums per distinct key, stable
    mapping, invalid lanes and past-cap keys excluded."""
    chi = jnp.asarray([5, 3, 5, 3, 5, 9, 7], jnp.uint32)
    clo = jnp.asarray([1, 2, 1, 2, 1, 4, 6], jnp.uint32)
    hq = jnp.asarray([1, 0, 1, 1, 0, 1, 1], jnp.uint32)
    lq = jnp.asarray([0, 1, 0, 0, 1, 0, 0], jnp.uint32)
    valid = jnp.asarray([1, 1, 1, 1, 1, 1, 0], bool)
    cap = 4
    u_chi, u_clo, u_hq, u_lq, u_valid, seg_of = jax.tree_util.tree_map(
        np.asarray,
        ctable._aggregate_obs_impl(chi, clo, hq, lq, valid, cap))
    got = {}
    for i in range(cap):
        if u_valid[i]:
            got[(int(u_chi[i]), int(u_clo[i]))] = (int(u_hq[i]),
                                                   int(u_lq[i]))
    assert got == {(3, 2): (1, 1), (5, 1): (2, 1), (9, 4): (1, 0)}
    # every valid obs maps to the unique lane holding its key; the
    # invalid lane maps to cap
    for i, (c_, l_) in enumerate(zip([5, 3, 5, 3, 5, 9, 7],
                                     [1, 2, 1, 2, 1, 4, 6])):
        if not bool(valid[i]):
            assert seg_of[i] == cap
        else:
            j = int(seg_of[i])
            assert j < cap
            assert (int(u_chi[j]), int(u_clo[j])) == (c_, l_)


def test_insert_aggregation_parity(monkeypatch):
    """Aggregated vs per-observation inserts: identical table CONTENT
    (counts, quality bits) and — thanks to the canonical v4 export —
    identical database bytes."""
    rng = np.random.default_rng(4)
    codes = rng.integers(0, 4, size=(96, RLEN)).astype(np.int8)
    codes[:48] = codes[48:]  # heavy intra-batch duplication
    quals = rng.integers(34, 71, size=codes.shape).astype(np.uint8)

    def build(agg):
        monkeypatch.setenv("QUORUM_S1_AGGREGATE", "1" if agg else "0")
        meta = ctable.TileMeta(k=K, bits=7, rb_log2=6)
        bstate = ctable.make_tile_build(meta)
        bstate, full, _obs = ctable.tile_insert_reads(
            bstate, meta, jnp.asarray(codes), jnp.asarray(quals), 38)
        assert not full
        return ctable.tile_finalize(bstate, meta), meta

    sa, ma = build(True)
    sb, mb = build(False)
    ents = lambda s, m: sorted(zip(*(a.tolist()
                                     for a in ctable.tile_iterate(s, m))))
    assert ents(sa, ma) == ents(sb, mb)
    assert len(ents(sa, ma)) > 0


def test_agg_cap_overflow_exact(monkeypatch):
    """Distinct mers past the aggregation cap resolve through the
    per-observation drain — same table, just slower."""
    rng = np.random.default_rng(6)
    codes = rng.integers(0, 4, size=(64, RLEN)).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    chi, clo, q, valid = ctable.extract_observations_impl(
        jnp.asarray(codes), jnp.asarray(quals), K, 38)

    def insert(cap):
        meta = ctable.TileMeta(k=K, bits=7, rb_log2=6)
        bstate = ctable.make_tile_build(meta)
        if cap is None:
            monkeypatch.setenv("QUORUM_S1_AGGREGATE", "0")
        else:
            monkeypatch.setenv("QUORUM_S1_AGGREGATE", "1")
            monkeypatch.setattr(ctable, "agg_cap_for", lambda n: cap)
        bstate, full, _ = ctable.tile_insert_observations(
            bstate, meta, chi, clo, q, valid)
        assert not full
        return ctable.tile_finalize(bstate, meta), meta

    tiny, mt = insert(32)  # far fewer than the distinct-mer count
    monkeypatch.undo()
    base, mbs = insert(None)
    ents = lambda s, m: sorted(zip(*(a.tolist()
                                     for a in ctable.tile_iterate(s, m))))
    assert ents(tiny, mt) == ents(base, mbs)


def test_sharded_aggregated_build_parity(monkeypatch):
    """The sharded step wire with pre-aggregation on: identical table
    content to the single-chip aggregated build (the per-shard
    aggregate runs BEFORE the owner exchange)."""
    import jax as _jax
    from quorum_tpu.parallel import tile_sharded as ts
    if len(_jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    monkeypatch.setenv("QUORUM_S1_AGGREGATE", "1")
    rng = np.random.default_rng(8)
    codes = rng.integers(0, 4, size=(64, RLEN)).astype(np.int8)
    codes[:32] = codes[32:]  # duplication across the shard split
    quals = rng.integers(34, 71, size=codes.shape).astype(np.uint8)
    mesh = ts.make_mesh(2)
    smeta = ts.TileShardedMeta(k=K, bits=7, rb_log2=7, n_shards=2)
    sstate, smeta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, smeta, 38)
    gstate, gmeta = ts.gather_table(sstate, smeta)
    meta1 = ctable.TileMeta(k=K, bits=7, rb_log2=7)
    b1 = ctable.make_tile_build(meta1)
    b1, full, _ = ctable.tile_insert_reads(
        b1, meta1, jnp.asarray(codes), jnp.asarray(quals), 38)
    assert not full
    s1 = ctable.tile_finalize(b1, meta1)
    ents = lambda s, m: sorted(zip(*(a.tolist()
                                     for a in ctable.tile_iterate(s, m))))
    assert ents(gstate, gmeta) == ents(s1, meta1)


def test_v4_export_canonical_order(tmp_path):
    """Two tables with identical content but different slot placement
    (reversed insertion order) write byte-identical v4 databases."""
    rng = np.random.default_rng(9)
    n = 300
    khi = jnp.zeros((n,), jnp.uint32)
    klo = jnp.asarray(rng.choice(4 ** K, size=n, replace=False)
                      .astype(np.uint32))

    def build(order):
        meta = ctable.TileMeta(k=K, bits=7, rb_log2=4)  # crowded rows
        bstate = ctable.make_tile_build(meta)
        q = jnp.ones((n,), jnp.int32)
        valid = jnp.ones((n,), bool)
        bstate, full, _ = ctable.tile_insert_observations(
            bstate, meta, khi[order], klo[order], q[order], valid[order])
        assert not full
        return ctable.tile_finalize(bstate, meta), meta

    fwd = jnp.arange(n)
    sa, ma = build(fwd)
    sb, mb = build(fwd[::-1])
    pa = tmp_path / "a.jf"
    pb = tmp_path / "b.jf"
    db_format.write_db(str(pa), sa, ma, n_entries=n)
    db_format.write_db(str(pb), sb, mb, n_entries=n)
    payload = lambda p: p.read_bytes().split(b"\n", 1)[1]
    assert payload(pa) == payload(pb)
    # and the canonical file round-trips to the same content
    st, mt, _ = db_format.read_db(str(pa), to_device=False)
    got = sorted(zip(*(a.tolist() for a in ctable.tile_iterate(st, mt))))
    want = sorted(zip(np.asarray(khi).tolist(),
                      np.asarray(klo).tolist()))
    assert [g[:2] for g in got] == want


# ---------------------------------------------------------------------------
# Satellite: journaled --metrics JSONL heartbeats
# ---------------------------------------------------------------------------

def test_events_jsonl_survives_hard_kill(tmp_path):
    """A hard os._exit mid-run (the utils/faults.py hard-exit site)
    must leave the heartbeat JSONL with COMPLETE lines only — the
    line-journal write discipline in MetricsRegistry.event."""
    from test_error_correct_cli import make_dataset
    reads_path, _r, _q = make_dataset(tmp_path, n_reads=240)
    mpath = str(tmp_path / "m.json")
    plan = json.dumps([{"site": "stage1.insert", "batch": 2,
                        "action": "exit", "code": 47}])
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               QUORUM_FAULT_PLAN=plan,
               JAX_COMPILATION_CACHE_DIR="/tmp/quorum_tpu_test_jaxcache")
    res = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.create_database",
         "-s", "64k", "-m", "13", "-b", "7", "-q", "38",
         "--batch-size", "64", "-o", str(tmp_path / "db.jf"),
         "--metrics", mpath, "--metrics-interval", "0.000001",
         reads_path],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 47, res.stderr
    events = tmp_path / "m.events.jsonl"
    assert events.exists()
    raw = events.read_bytes()
    assert raw, "no events landed before the kill"
    assert raw.endswith(b"\n"), "torn last line after hard kill"
    lines = raw.decode().splitlines()
    assert any(json.loads(ln).get("event") == "heartbeat"
               for ln in lines)
    for ln in lines:
        json.loads(ln)  # every line complete


# ---------------------------------------------------------------------------
# Satellite: native-parser fastq.read fault site
# ---------------------------------------------------------------------------

def test_native_parser_carries_fault_site(tmp_path, monkeypatch):
    """An active fault plan no longer bypasses the C++ parser: the
    fastq.read site fires per record on the native path too."""
    from quorum_tpu.io import fastq
    from quorum_tpu.native import binding
    if not binding.available():
        pytest.skip("native parser not built")
    p = tmp_path / "r.fastq"
    with open(p, "w") as f:
        for i in range(10):
            f.write(f"@r{i}\nACGTACGTAC\n+\nIIIIIIIIII\n")

    def no_python_parse(*a, **kw):  # pragma: no cover - guard
        raise AssertionError("pure-Python parser used despite native")

    monkeypatch.setattr(fastq, "iter_records", no_python_parse)
    faults.install(faults.FaultPlan.parse(
        [{"site": "fastq.read", "at": 3, "action": "io_error"}]))
    try:
        with pytest.raises(OSError):
            list(fastq.read_batches([str(p)], batch_size=4))
    finally:
        faults.reset()


# ---------------------------------------------------------------------------
# Satellite: driver replay-cache checkpoint across --resume
# ---------------------------------------------------------------------------

def test_replay_cache_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    from quorum_tpu.io import fastq
    codes = rng.integers(0, 4, size=(8, 20)).astype(np.int8)
    quals = rng.integers(40, 70, size=codes.shape).astype(np.uint8)
    lengths = np.full((8,), 20, np.int32)
    pk = packing.pack_reads(codes, quals, lengths,
                            thresholds=(64,)).compact()
    batch = fastq.ReadBatch(codes=codes, quals=None, lengths=lengths,
                            headers=[f"r{i}" for i in range(8)], n=8)
    ident = {"inputs": ["x.fastq"], "batch_size": 8,
             "qual_cutoff": 64, "on_bad_read": "abort"}
    store = ckpt_mod.ReplayCache(str(tmp_path / "ck"))
    w = store.start(ident, 1 << 30)
    w.add(batch, pk)
    assert store.load(ident) is None  # no manifest yet = no commit
    assert w.finish()
    rd = store.load(ident)
    assert rd is not None and rd.n_batches == 1
    (b2, pk2), = list(rd.batches())
    np.testing.assert_array_equal(b2.codes, codes)
    assert b2.headers == batch.headers and b2.n == 8
    np.testing.assert_array_equal(pk2.to_wire(), pk.to_wire())
    assert pk2.n_reads == 8 and 64 in pk2.hq
    # identity mismatch refuses (falls back to the disk parse)
    assert store.load(dict(ident, batch_size=16)) is None
    # over-budget capture aborts and removes itself
    w = store.start(ident, 1)
    w.add(batch, pk)
    assert not w.finish()
    assert store.load(ident) is None


def test_driver_resume_replays_without_reparse(tmp_path, monkeypatch):
    """Kill stage 2, resume the driver: stage 1's database is reused
    AND the reads replay from the on-disk capture — no FASTQ re-parse
    (before round 7 only the stage outputs resumed)."""
    from test_error_correct_cli import make_dataset
    from quorum_tpu.cli import quorum as quorum_cli
    monkeypatch.chdir(tmp_path)
    reads_path, _r, _q = make_dataset(tmp_path)
    ckdir = str(tmp_path / "ck")

    ref_prefix = str(tmp_path / "ref")
    rc = quorum_cli.main(["-s", "64k", "-k", "13", "-p", ref_prefix,
                          "--batch-size", "64", reads_path])
    assert rc == 0

    prefix = str(tmp_path / "qc")
    plan = json.dumps([{"site": "stage2.correct", "batch": 0,
                        "action": "error"}])
    args = ["-s", "64k", "-k", "13", "-p", prefix, "--batch-size", "64",
            "--checkpoint-dir", ckdir]
    rc = quorum_cli.main(args + ["--fault-plan", plan, reads_path])
    assert rc == 1
    # the capture committed when stage 1 drained the shared producer
    store = ckpt_mod.ReplayCache(ckdir)
    assert store.manifest() is not None

    # resume: any re-parse attempt explodes
    import quorum_tpu.models.create_database as cdb_mod
    import quorum_tpu.models.error_correct as ec_mod

    def no_reparse(*a, **kw):  # pragma: no cover - guard
        raise AssertionError("resumed driver re-parsed the FASTQ")

    monkeypatch.setattr(quorum_cli.fastq, "read_batches", no_reparse)
    monkeypatch.setattr(cdb_mod.fastq, "read_batches", no_reparse)
    monkeypatch.setattr(ec_mod.fastq, "read_batches", no_reparse)
    rc = quorum_cli.main(args + ["--resume", "--fault-plan", "",
                                 reads_path])
    assert rc == 0
    assert open(prefix + ".fa").read() == open(ref_prefix + ".fa").read()
    assert open(prefix + ".log").read() == open(ref_prefix + ".log").read()
    # success clears the (sizeable) capture
    assert store.manifest() is None


# ---------------------------------------------------------------------------
# Satellite: BENCH-style gating + span export into the profile dir
# ---------------------------------------------------------------------------

def test_metrics_check_require_metric(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import metrics_check
    p = tmp_path / "bench.json"
    p.write_text('{"metric": "ab_stage1_insert", "speedup": 1.5}\n')
    assert metrics_check.main(
        ["--require-metric", "ab_stage1_insert", str(p), "-q"]) == 0
    assert metrics_check.main(
        ["--require-metric", "ab_stage2_device", str(p), "-q"]) == 1


def test_span_twin_lands_in_profile_dir(tmp_path):
    from quorum_tpu.cli.observability import observability
    prof = tmp_path / "prof"
    spans = str(tmp_path / "spans.jsonl")
    with observability(trace_spans=spans, profile=str(prof)) as obs:
        with obs.tracer.span("work", reads=1):
            pass
    twin = prof / "spans.trace.json"
    assert twin.exists()
    doc = json.loads(twin.read_text())
    assert any(ev["name"] == "work" for ev in doc["traceEvents"])
