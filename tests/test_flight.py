"""Flight recorder + crash forensics (ISSUE 16): the ring buffer,
dump-on-trigger semantics, the dump/bundle schema validators, the
metrics_check gate, trace_summary --flight rendering, the
quorum-debug-bundle round trip, and the push-receiver staleness
alerting that rides the same PR."""

import importlib.util
import json
import os
import tarfile
import threading
import time
import urllib.request

import pytest

from quorum_tpu.telemetry import MetricsRegistry, flight
from quorum_tpu.telemetry.schema import (FLIGHT_SCHEMA,
                                         validate_debug_bundle_manifest,
                                         validate_flight_dump,
                                         validate_metrics)
from quorum_tpu.telemetry.spans import SpanTracer
from quorum_tpu.utils import faults

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _recorder(tmp_path, **kw):
    reg = MetricsRegistry()
    out = str(tmp_path / "dump.flight.json")
    rec = flight.FlightRecorder(reg, out_path=out, **kw)
    return reg, rec, out


# ---------------------------------------------------------------------------
# the ring
# ---------------------------------------------------------------------------

def test_ring_evicts_and_counts_drops(tmp_path):
    reg, rec, _ = _recorder(tmp_path, capacity=16)
    for i in range(20):
        rec.record("event", f"e{i}", i=i)
    snap = rec.snapshot()
    assert len(snap["ring"]) == 16
    assert snap["dropped"] == 4
    # the oldest entries are the evicted ones
    assert snap["ring"][0]["name"] == "e4"
    rec.flush_drop_counter()
    assert reg.as_dict()["counters"][
        "flight_events_dropped_total"] == 4
    # flushing again without new evictions adds nothing
    rec.flush_drop_counter()
    assert reg.as_dict()["counters"][
        "flight_events_dropped_total"] == 4


def test_capacity_floor_and_lever(tmp_path, monkeypatch):
    monkeypatch.setenv("QUORUM_FLIGHT_RING", "64")
    reg, rec, _ = _recorder(tmp_path)
    assert rec.capacity == 64
    # explicit capacity wins over the lever, floored at 16
    _, rec2, _ = _recorder(tmp_path, capacity=2)
    assert rec2.capacity == 16


def test_disabled_recorder_is_inert(tmp_path, monkeypatch):
    monkeypatch.setenv("QUORUM_FLIGHT", "0")
    reg, rec, out = _recorder(tmp_path)
    assert not rec.enabled
    rec.record("event", "e")
    assert rec.dump("exception", detail="boom") is None
    assert not os.path.exists(out)
    assert reg.as_dict()["counters"]["flight_dumps_total"] == 0


def test_record_is_reentrancy_safe(tmp_path):
    # a tap firing while a record is already in flight on the same
    # thread (the TSAN hook observing the ring lock itself) must be
    # dropped, not deadlock
    reg, rec, _ = _recorder(tmp_path)
    orig_append = rec._ring.append

    def reentrant_append(obj):
        rec.record("lock", "flight.FlightRecorder._lock")
        orig_append(obj)

    rec._ring = type("R", (), {"append": staticmethod(reentrant_append),
                               "__len__": lambda self: 0,
                               "__iter__": lambda self: iter(())})()
    rec.record("event", "outer")  # returns, no deadlock/recursion


def test_cold_surfaces_are_reentrancy_safe(tmp_path):
    # the TSAN hook fires on EVERY ring-lock acquisition, including
    # the recorder's own cold surfaces (flush_drop_counter at
    # teardown, snapshot/dump at trigger time) — each re-enters
    # record() on the same thread and must bail out, not block on
    # the lock it is reporting (the tier-1 QUORUM_TSAN=1 deadlock)
    import threading

    reg, rec, out = _recorder(tmp_path)
    real_lock = rec._lock

    class HookedLock:
        def acquire(self, *a, **kw):
            rec.record("lock", "flight.FlightRecorder._lock")
            return real_lock.acquire(*a, **kw)

        def release(self):
            real_lock.release()

        def __enter__(self):
            self.acquire()
            return self

        def __exit__(self, *exc):
            self.release()
            return False

    rec.record("event", "before")
    rec._lock = HookedLock()
    rec.flush_drop_counter()            # would deadlock unguarded
    snap = rec.snapshot()
    assert any(e["name"] == "before" for e in snap["ring"])
    assert rec.dump("exception", detail="boom") == out
    # the hooked lock was never re-entered: the guard dropped the
    # hook's record instead of blocking, and the dump completed
    assert not real_lock.locked()
    assert threading.current_thread() is threading.main_thread()


# ---------------------------------------------------------------------------
# taps: the existing sinks feed the ring with no new call sites
# ---------------------------------------------------------------------------

def test_registry_event_tap(tmp_path):
    reg, rec, _ = _recorder(tmp_path)
    reg.flight = rec
    reg.event("heartbeat", bases=100)
    ring = rec.snapshot()["ring"]
    assert ring[-1]["kind"] == "event"
    assert ring[-1]["name"] == "heartbeat"
    assert ring[-1]["bases"] == 100


def test_span_tracer_tap(tmp_path):
    reg, rec, _ = _recorder(tmp_path)
    tracer = SpanTracer(None)
    tracer.flight = rec
    with tracer.step("stage1_insert", 3, reads=7):
        pass
    kinds = [(e["kind"], e["name"]) for e in rec.snapshot()["ring"]]
    assert ("span_open", "stage1_insert") in kinds
    assert ("span", "stage1_insert") in kinds


def test_fault_firing_leaves_breadcrumb(tmp_path):
    reg, rec, _ = _recorder(tmp_path)
    token = flight.install(rec)
    try:
        faults.install(faults.FaultPlan.parse(
            {"site": "stage1.insert", "action": "error"}), "t-crumb")
        with pytest.raises(faults.FaultError):
            faults.inject("stage1.insert", batch=5)
    finally:
        faults.reset()
        flight.uninstall(token)
    ring = rec.snapshot()["ring"]
    crumb = [e for e in ring if e["kind"] == "fault"]
    assert crumb and crumb[-1]["name"] == "stage1.insert"
    assert crumb[-1]["action"] == "error"
    assert crumb[-1]["batch"] == 5


# ---------------------------------------------------------------------------
# dumps
# ---------------------------------------------------------------------------

def test_dump_is_sealed_valid_and_once_per_incident(tmp_path):
    reg, rec, out = _recorder(tmp_path)
    reg.flight = rec
    reg.event("checkpoint", cursor=42)
    path = rec.dump("watchdog", detail="step wedged",
                    site="serve.engine.step")
    assert path == out
    with open(out) as f:
        doc = json.load(f)
    assert validate_flight_dump(doc) == []
    assert doc["schema"] == FLIGHT_SCHEMA
    trig = doc["trigger"]
    assert trig["kind"] == "watchdog"
    assert trig["site"] == "serve.engine.step"
    assert trig["thread"] == threading.current_thread().name
    assert any(e["name"] == "checkpoint" for e in doc["ring"])
    assert any(t["tid"] == trig["tid"] for t in doc["threads"])
    assert "QUORUM_FLIGHT" in doc["levers"]
    assert validate_metrics(doc["registry"]) == []
    # first trigger wins: a second dump is a no-op returning the path
    assert rec.dump("exception", detail="later") == out
    assert reg.as_dict()["counters"]["flight_dumps_total"] == 1
    with open(out) as f:
        assert json.load(f)["trigger"]["kind"] == "watchdog"
    # ... unless forced (the operator's SIGUSR1)
    assert rec.dump("sigusr1", force=True) == out
    assert reg.as_dict()["counters"]["flight_dumps_total"] == 2


def test_dump_without_path_stays_in_ring(tmp_path):
    reg = MetricsRegistry()
    rec = flight.FlightRecorder(reg, out_path=None)
    assert rec.dump("watchdog", site="serve.engine.step") is None
    ring = rec.snapshot()["ring"]
    assert ring[-1]["kind"] == "trigger"
    assert ring[-1]["site"] == "serve.engine.step"
    assert reg.as_dict()["counters"]["flight_dumps_total"] == 0


def test_dump_captures_exception_context(tmp_path):
    reg, rec, out = _recorder(tmp_path)
    try:
        raise ValueError("kaboom")
    except ValueError:
        rec.dump("exception", detail="umbrella")
    with open(out) as f:
        trig = json.load(f)["trigger"]
    assert "kaboom" in trig["exception"]
    assert any("kaboom" in ln for ln in trig["exc_stack"])


def test_default_out_path(monkeypatch, tmp_path):
    monkeypatch.delenv("QUORUM_FLIGHT_DIR", raising=False)
    assert flight.default_out_path("run/metrics.json") == \
        "run/metrics.flight.json"
    assert flight.default_out_path(None) is None
    monkeypatch.setenv("QUORUM_FLIGHT_DIR", str(tmp_path))
    p = flight.default_out_path("run/metrics.json")
    assert p == str(tmp_path / f"flight-{os.getpid()}.json")


def test_install_nesting_and_try_dump(tmp_path):
    assert flight.current() is None
    assert flight.try_dump("watchdog") is None  # no recorder: no-op
    reg1, rec1, _ = _recorder(tmp_path)
    reg2, rec2, out2 = _recorder(tmp_path / "inner")
    os.makedirs(tmp_path / "inner", exist_ok=True)
    t1 = flight.install(rec1)
    t2 = flight.install(rec2)
    try:
        assert flight.current() is rec2
        assert flight.try_dump("exception", detail="x") == out2
    finally:
        flight.uninstall(t2)
        assert flight.current() is rec1
        flight.uninstall(t1)
    assert flight.current() is None


def test_try_dump_reraises_the_fault_site(tmp_path):
    reg, rec, out = _recorder(tmp_path)
    token = flight.install(rec)
    try:
        faults.install(faults.FaultPlan.parse(
            {"site": "flight.dump", "action": "error"}), "t-site")
        with pytest.raises(faults.FaultError):
            flight.try_dump("watchdog", site="serve.engine.step")
    finally:
        faults.reset()
        flight.uninstall(token)
    # the dump itself landed before the injected post-write failure
    assert os.path.exists(out)


def test_sigusr1_handler_forces_a_dump(tmp_path):
    reg, rec, out = _recorder(tmp_path)
    token = flight.install(rec)
    try:
        rec.dump("watchdog")
        flight._sigusr1(None, None)  # the handler body, sans signal
    finally:
        flight.uninstall(token)
    assert reg.as_dict()["counters"]["flight_dumps_total"] == 2
    with open(out) as f:
        assert json.load(f)["trigger"]["kind"] == "sigusr1"


# ---------------------------------------------------------------------------
# schema validators + the metrics_check gate
# ---------------------------------------------------------------------------

def test_validate_flight_dump_requires_the_seal(tmp_path):
    reg, rec, out = _recorder(tmp_path)
    rec.dump("error")
    with open(out) as f:
        doc = json.load(f)
    assert validate_flight_dump(doc) == []
    # tampering after the write must be detected
    doc["dropped"] += 1
    assert any("seal mismatch" in e for e in validate_flight_dump(doc))
    # an unsealed dump is invalid even if otherwise well-formed
    doc["dropped"] -= 1
    del doc["crc32c"]
    assert any("seal" in e for e in validate_flight_dump(doc))


def test_validate_flight_dump_shape_errors():
    assert validate_flight_dump([]) != []
    errs = validate_flight_dump({"schema": "nope"})
    assert any("schema" in e for e in errs)
    assert any("trigger" in e for e in errs)
    assert any("ring" in e for e in errs)


def test_validate_debug_bundle_manifest():
    from quorum_tpu.io import integrity
    good = integrity.seal({
        "schema": "quorum-tpu-debug-bundle/1",
        "meta": {"tool": "quorum-debug-bundle", "pid": 1,
                 "argv": ["x"], "created_unix_s": 0, "missing": 0},
        "files": [{"name": "dump.flight.json", "kind": "flight",
                   "bytes": 10, "crc32c": 7, "problems": 0}],
    })
    assert validate_debug_bundle_manifest(good) == []
    bad_kind = dict(good)
    bad_kind["files"] = [dict(good["files"][0], kind="selfie")]
    assert any("kind" in e
               for e in validate_debug_bundle_manifest(bad_kind))
    empty = dict(good, files=[])
    assert any("empty" in e
               for e in validate_debug_bundle_manifest(empty))


def test_check_file_dispatches_flight_and_bundle(tmp_path):
    from quorum_tpu.telemetry import check_file
    reg, rec, out = _recorder(tmp_path)
    rec.dump("error")
    assert check_file(out) == []
    # a tampered dump fails through the same dispatch
    with open(out) as f:
        doc = json.load(f)
    doc["dropped"] += 1
    bad = tmp_path / "bad.flight.json"
    bad.write_text(json.dumps(doc))
    assert check_file(str(bad)) != []


def test_metrics_check_serve_stage_dump_not_held_to_serve_names(
        tmp_path):
    # a serve run's flight dump carries meta.stage == "serve" (the
    # dying run's stage), but it is a forensics artifact, NOT a final
    # serve document: metrics_check must validate it by its own
    # schema and not demand the serve counter contract of it (the
    # chaos_soak watchdog-dump regression)
    mc = _tool("metrics_check")
    reg, rec, out = _recorder(tmp_path)
    rec.record("event", "heartbeat")
    rec.dump("watchdog", detail="engine step wedged",
             site="serve.engine.step")
    with open(out) as f:
        doc = json.load(f)
    doc["meta"]["stage"] = "serve"
    from quorum_tpu.io import integrity
    doc.pop("crc32c", None)
    sealed = integrity.seal(doc)
    out2 = tmp_path / "serve_run.flight.json"
    out2.write_text(json.dumps(sealed))
    assert mc._check_with_serve_names(str(out2)) == []


def test_metrics_check_requires_flight_counters_when_declared():
    mc = _tool("metrics_check")
    doc = {"schema": "quorum-tpu-metrics/1",
           "meta": {"flight": True},
           "counters": {}, "gauges": {}, "histograms": {},
           "timers": {}}
    probs = mc._check_flight_names(doc)
    assert any("flight_dumps_total" in p for p in probs)
    doc["counters"] = {"flight_dumps_total": 0,
                       "flight_events_dropped_total": 0}
    assert mc._check_flight_names(doc) == []
    # undeclared documents are not held to it
    assert mc._check_flight_names(
        {"meta": {}, "counters": {}}) == []


def test_validate_metrics_events_section():
    base = {"schema": "quorum-tpu-metrics/1", "meta": {},
            "counters": {}, "gauges": {}, "histograms": {},
            "timers": {}}
    ev = {"event": "alert", "t": 1.5, "rule": "fleet_host_stale",
          "state": "firing", "host": "h:1", "value": 2.0,
          "detail": "no push for 2.0s", "severity": "warn"}
    assert validate_metrics(dict(base, events=[ev])) == []
    # a malformed alert event is flagged in place
    bad = dict(ev, state="panicking")
    errs = validate_metrics(dict(base, events=[bad]))
    assert any("events[0]" in e for e in errs)
    # nested host shards may NOT carry events
    nested = dict(base, hosts={"h:1": dict(base, events=[ev])})
    assert any("unknown top-level keys" in e
               for e in validate_metrics(nested))


# ---------------------------------------------------------------------------
# trace_summary --flight
# ---------------------------------------------------------------------------

def test_trace_summary_renders_flight_dump(tmp_path, capsys):
    reg, rec, out = _recorder(tmp_path)
    reg.flight = rec
    reg.event("heartbeat", bases=9)
    rec.record("dispatch", "stage1", dispatch_us=10, wait_us=2)
    rec.dump("watchdog", detail="engine step exceeded 100 ms",
             site="serve.engine.step")
    ts = _tool("trace_summary")
    assert ts.main(["--flight", out]) == 0
    text = capsys.readouterr().out
    assert "trigger: watchdog site=serve.engine.step" in text
    assert "heartbeat" in text
    assert "dispatch_us=10" in text
    assert "triggering thread" in text


def test_trace_summary_flight_window_filters(tmp_path, capsys):
    reg, rec, out = _recorder(tmp_path)
    rec.record("event", "ancient")
    rec._ring[0]["t"] = 0.0
    rec.record("event", "recent")
    rec._ring[1]["t"] = 100.0
    rec.dump("error")
    ts = _tool("trace_summary")
    assert ts.main(["--flight", "--last-s", "5", out]) == 0
    text = capsys.readouterr().out
    assert "recent" in text
    assert "ancient" not in text


def test_trace_summary_flight_rejects_non_dump(tmp_path, capsys):
    p = tmp_path / "metrics.json"
    p.write_text(json.dumps(
        {"schema": "quorum-tpu-metrics/1", "meta": {}, "counters": {},
         "gauges": {}, "histograms": {}, "timers": {}}))
    ts = _tool("trace_summary")
    assert ts.main(["--flight", str(p)]) == 1
    assert "not a flight dump" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# quorum-debug-bundle
# ---------------------------------------------------------------------------

def test_debug_bundle_round_trip(tmp_path):
    from quorum_tpu.cli import debug_bundle
    reg, rec, dump = _recorder(tmp_path)
    rec.dump("error", detail="died")
    metrics = tmp_path / "metrics.json"
    reg.write(str(metrics))
    gone = tmp_path / "vanished.json"
    bundle = tmp_path / "postmortem.tar.gz"
    rc = debug_bundle.main([dump, str(metrics), str(gone),
                            "--out", str(bundle), "-q"])
    assert rc == 0
    with tarfile.open(bundle) as tar:
        names = tar.getnames()
        manifest = json.load(tar.extractfile("MANIFEST.json"))
        for entry in manifest["files"]:
            data = tar.extractfile(entry["name"]).read()
            assert len(data) == entry["bytes"]
    assert validate_debug_bundle_manifest(manifest) == []
    assert manifest["meta"]["missing"] == 1
    kinds = {e["kind"] for e in manifest["files"]}
    assert {"flight", "metrics", "config"} <= kinds
    flight_entry = next(e for e in manifest["files"]
                        if e["kind"] == "flight")
    assert flight_entry["problems"] == 0
    cfg = next(e for e in manifest["files"] if e["kind"] == "config")
    assert cfg["name"] in names


def test_debug_bundle_needs_something_to_collect():
    from quorum_tpu.cli import debug_bundle
    with pytest.raises(SystemExit):
        debug_bundle.main([])


# ---------------------------------------------------------------------------
# push-receiver staleness alerting (satellite a)
# ---------------------------------------------------------------------------

def _host_doc():
    return {"schema": "quorum-tpu-metrics/1", "meta": {},
            "counters": {"reads": 1}, "gauges": {}, "histograms": {},
            "timers": {}}


def test_push_receiver_staleness_fires_and_heals(tmp_path):
    pr = _tool("push_receiver")
    out = tmp_path / "fleet.json"
    rx = pr.PushReceiver(out_path=str(out), port=0, quiet=True,
                         stale_after_s=0.25)
    try:
        body = json.dumps(_host_doc()).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{rx.port}/push/final", data=body,
            headers={"X-Quorum-Host": "h:1"})
        urllib.request.urlopen(req, timeout=10).read()
        # armed but fresh: not stale
        h = rx.health()
        assert h["stale_after_s"] == 0.25
        assert h["stale_hosts"] == []
        # go silent past the threshold: the ticker fires the rule
        deadline = time.monotonic() + 10
        while rx.health()["stale_hosts"] != ["h:1"]:
            assert time.monotonic() < deadline, "never fired"
            time.sleep(0.05)
        text = rx._own_metrics_text()
        assert 'fleet_host_stale{host="h:1"} 1' in text
        events = rx.alert_events
        assert events[-1]["rule"] == "fleet_host_stale"
        assert events[-1]["state"] == "firing"
        # the alert event rides the on-disk fleet document
        deadline = time.monotonic() + 10
        while True:
            fleet = json.loads(out.read_text())
            if fleet.get("events"):
                break
            assert time.monotonic() < deadline, "event never landed"
            time.sleep(0.05)
        assert validate_metrics(fleet) == []
        assert fleet["events"][-1]["state"] == "firing"
        # the host returns: the rule heals
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{rx.port}/push/final", data=body,
                headers={"X-Quorum-Host": "h:1"}),
            timeout=10).read()
        assert rx.health()["stale_hosts"] == []
        assert 'fleet_host_stale{host="h:1"} 0' \
            in rx._own_metrics_text()
        states = [e["state"] for e in rx.alert_events]
        assert states.count("firing") == 1
        assert states.count("healed") == 1
    finally:
        rx.close()


def test_push_receiver_without_threshold_is_unchanged(tmp_path):
    pr = _tool("push_receiver")
    rx = pr.PushReceiver(port=0, quiet=True)
    try:
        h = rx.health()
        assert "stale_hosts" not in h
        assert "fleet_host_stale" not in rx._own_metrics_text()
        assert rx._ticker is None
    finally:
        rx.close()
