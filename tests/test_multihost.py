"""Multi-host input sharding (parallel/multihost): the global plan is
deterministic, partitioning (no file lost, none duplicated), and
size-balanced; single-process behavior is the identity."""

import json
import os

import numpy as np
import pytest

from quorum_tpu.parallel import multihost


def _mk_files(tmp_path, sizes):
    paths = []
    for i, s in enumerate(sizes):
        p = tmp_path / f"r{i}.fastq"
        p.write_bytes(b"@r\n" + b"A" * s + b"\n+\n" + b"I" * s + b"\n")
        paths.append(str(p))
    return paths


def test_partition_no_loss_no_dup(tmp_path):
    paths = _mk_files(tmp_path, [10, 2000, 50, 50, 800, 300, 7, 4000])
    pc = 3
    shards = [multihost.host_shard_paths(paths, pi, pc)
              for pi in range(pc)]
    got = [p for s in shards for p in s]
    assert sorted(got) == sorted(paths)
    assert len(got) == len(set(got))


def test_balanced_by_size(tmp_path):
    rng = np.random.default_rng(0)
    sizes = rng.integers(100, 10_000, size=24).tolist()
    paths = _mk_files(tmp_path, sizes)
    sz = dict(zip(paths, sizes))
    pc = 4
    loads = [sum(sz[p] for p in multihost.host_shard_paths(paths, pi, pc))
             for pi in range(pc)]
    assert max(loads) < 1.5 * (sum(sizes) / pc)


def test_single_process_identity(tmp_path):
    paths = _mk_files(tmp_path, [5, 5])
    assert multihost.host_shard_paths(paths, 0, 1) == paths
    batches = list(multihost.read_batches_multihost(paths, 4))
    assert sum(b.n for b in batches) == 2


def test_deterministic_across_hosts(tmp_path):
    """Every host must compute the same global plan independently."""
    paths = _mk_files(tmp_path, [10, 2000, 50, 800])
    pc = 2
    a = [multihost.host_shard_paths(paths, pi, pc) for pi in range(pc)]
    b = [multihost.host_shard_paths(paths, pi, pc) for pi in range(pc)]
    assert a == b


def test_read_batches_metrics(tmp_path):
    """Telemetry wiring: this host's input share and batch/read
    counters."""
    from quorum_tpu.telemetry import MetricsRegistry

    paths = _mk_files(tmp_path, [5, 5])
    reg = MetricsRegistry()
    batches = list(multihost.read_batches_multihost(paths, 4,
                                                    metrics=reg))
    doc = reg.as_dict()
    assert doc["gauges"]["host_input_files"] == 2
    assert doc["gauges"]["host_input_bytes"] > 0
    assert doc["counters"]["host_reads"] == sum(b.n for b in batches) == 2
    assert doc["counters"]["host_batches"] == len(batches)
    assert doc["meta"]["host_input_paths"] == paths


# ---------------------------------------------------------------------------
# ISSUE 2: multi-host metrics aggregation (one document per job)
# ---------------------------------------------------------------------------

def _host_reg(reads, stall, subs_counts, stage_s, pi):
    from quorum_tpu.telemetry import MetricsRegistry
    from quorum_tpu.utils.profiling import StageTimer

    reg = MetricsRegistry()
    reg.set_meta(stage="create_database", host_process_index=pi,
                 host_input_paths=[f"h{pi}.fastq"])
    reg.counter("host_reads").inc(reads)
    reg.counter("host_batches").inc(1)
    reg.gauge("prefetch_queue_depth_max").set_max(stall)
    for v, n in subs_counts.items():
        reg.histogram("insert_wait_ms").observe(v, n)
    t = StageTimer()
    t.add_time("insert_wait", stage_s)
    reg.set_timer("stage1", t.as_dict())
    return reg


def test_merge_host_docs_counters_sum():
    from quorum_tpu.parallel.multihost import merge_host_docs
    from quorum_tpu.telemetry import validate_metrics

    d0 = _host_reg(100, 3, {0: 5, 2: 1}, 1.0, 0).as_dict()
    d1 = _host_reg(40, 4, {0: 2, 7: 2}, 2.5, 1).as_dict()
    merged = merge_host_docs([d0, d1])
    assert validate_metrics(merged) == []
    # the acceptance invariant: top-level counters == sum of shards
    assert merged["counters"]["host_reads"] == 140
    assert merged["counters"]["host_batches"] == 2
    assert merged["hosts"]["0"]["counters"]["host_reads"] == 100
    assert merged["hosts"]["1"]["counters"]["host_reads"] == 40
    # gauges keep the per-host high-water mark
    assert merged["gauges"]["prefetch_queue_depth_max"] == 4
    # histograms merge exactly
    h = merged["histograms"]["insert_wait_ms"]
    assert h["count"] == 10
    assert h["counts"] == {"0": 7, "2": 1, "7": 2}
    assert h["sum"] == d0["histograms"]["insert_wait_ms"]["sum"] \
        + d1["histograms"]["insert_wait_ms"]["sum"]
    # timers: stages sum, job total = slowest host
    st = merged["timers"]["stage1"]
    assert st["stages"]["insert_wait"]["seconds"] == 3.5
    assert st["total_seconds"] == max(
        d["timers"]["stage1"]["total_seconds"] for d in (d0, d1))
    # per-host meta stays in the shards, not the merged top level
    assert "host_process_index" not in merged["meta"]
    assert merged["meta"]["aggregated_hosts"] == 2
    assert merged["hosts"]["1"]["meta"]["host_process_index"] == 1


def test_aggregate_metrics_two_hosts_one_document(tmp_path, monkeypatch):
    """Acceptance (ISSUE 2): a 2-host run produces exactly ONE
    aggregated document, written by process 0, whose counters equal
    the sum of the per-host shards."""
    import json

    from quorum_tpu.telemetry import validate_metrics

    regs = [_host_reg(100, 3, {0: 5}, 1.0, 0),
            _host_reg(40, 4, {1: 2}, 2.0, 1)]
    # simulate the collective: every host contributes its own payload
    payloads = [json.dumps(r.as_dict()).encode() for r in regs]
    monkeypatch.setattr(multihost, "_allgather_bytes",
                        lambda payload: list(payloads))

    outs = []
    for pi, reg in enumerate(regs):
        path = str(tmp_path / f"agg_pi{pi}" / "metrics.json")
        (tmp_path / f"agg_pi{pi}").mkdir()
        outs.append(multihost.aggregate_metrics(reg, path,
                                                process_index=pi))
    # every host gets the same merged document back...
    assert outs[0] == outs[1]
    # ...but exactly one file lands (process 0's)
    assert (tmp_path / "agg_pi0" / "metrics.json").exists()
    assert not (tmp_path / "agg_pi1" / "metrics.json").exists()
    doc = json.load(open(tmp_path / "agg_pi0" / "metrics.json"))
    assert validate_metrics(doc) == []
    assert doc["counters"]["host_reads"] == sum(
        doc["hosts"][h]["counters"]["host_reads"] for h in doc["hosts"])
    assert doc["counters"]["host_reads"] == 140
    assert doc["meta"]["aggregated_hosts"] == 2


def test_aggregate_metrics_single_process_identity(tmp_path):
    """Under one process the collective is the identity and the
    document still writes (the degenerate 1-host job)."""
    import json

    from quorum_tpu.telemetry import validate_metrics

    reg = _host_reg(7, 1, {0: 1}, 0.5, 0)
    path = str(tmp_path / "agg.json")
    merged = multihost.aggregate_metrics(reg, path)
    assert (tmp_path / "agg.json").exists()
    assert json.load(open(path)) == merged
    assert validate_metrics(merged) == []
    assert merged["counters"]["host_reads"] == 7
    assert merged["meta"]["aggregated_hosts"] == 1
    assert merged["hosts"]["0"]["counters"]["host_reads"] == 7


# ---------------------------------------------------------------------------
# ISSUE 20: fleet host-plan edge cases, plan agreement, gauge reduce
# ---------------------------------------------------------------------------

def test_host_plan_more_hosts_than_files(tmp_path):
    """A fleet larger than the input file set: every file still has
    exactly one owner, surplus hosts get an EMPTY producer share (they
    must still hit every barrier — fleet orchestration, not the plan,
    guarantees that), and nothing is double-assigned."""
    paths = _mk_files(tmp_path, [100, 5000])
    pc = 5
    owner, sizes = multihost.host_plan(paths, pc)
    assert len(owner) == len(paths) and len(sizes) == len(paths)
    assert all(0 <= h < pc for h in owner)
    assert len(set(owner)) == len(paths)  # distinct hosts while they last
    shares = [multihost.host_shard_paths(paths, pi, pc)
              for pi in range(pc)]
    assert sorted(p for s in shares for p in s) == sorted(paths)
    assert sum(1 for s in shares if not s) == pc - len(paths)
    # an empty share drains immediately as an empty batch stream
    for pi in range(pc):
        if not shares[pi]:
            assert list(multihost.read_batches_multihost(
                shares[pi], 4)) == []


def test_host_plan_uneven_sizes_balance(tmp_path):
    """One huge file plus many small ones: the greedy plan puts the
    huge file alone on one host and spreads the rest."""
    paths = _mk_files(tmp_path, [100_000, 10, 10, 10, 10, 10])
    owner, sizes = multihost.host_plan(paths, 2)
    big_host = owner[0]
    assert all(h != big_host for h in owner[1:])


def test_verify_plan_hash_divergence_is_loud(tmp_path):
    """The defense-in-depth plan agreement: a host whose stat results
    produced a different plan than process 0's must refuse to shard,
    never silently double-parse or drop files."""
    paths = [str(p) for p in _mk_files(tmp_path, [10, 20])]
    owner, sizes = multihost.host_plan(paths, 2)
    # agreement: process 0 broadcasts the same digest we computed
    multihost._verify_plan_hash(paths, sizes, owner,
                                _broadcast=lambda d: d)
    with pytest.raises(RuntimeError, match="disagrees with process 0"):
        multihost._verify_plan_hash(paths, sizes, owner,
                                    _broadcast=lambda d: "0" * 64)


def _doc_with_gauges(pi, gauges):
    reg = _host_reg(10, 1, {0: 1}, 1.0, pi)
    for k, v in gauges.items():
        reg.gauge(k).set(v)
    return reg.as_dict()


def test_merge_host_docs_free_space_gauges_reduce_by_min():
    """Resource gauges in the fleet aggregate (ISSUE 19 -> 20): free
    space reduces by MIN (the fleet-level number an operator acts on
    is the tightest host's headroom), per-path labeled gauges
    included; RSS keeps the default high-water MAX."""
    from quorum_tpu.parallel.multihost import merge_host_docs
    d0 = _doc_with_gauges(0, {
        "disk_free_bytes_min": 500, "host_rss_bytes": 1000,
        'disk_free_bytes{path="/ck"}': 800})
    d1 = _doc_with_gauges(1, {
        "disk_free_bytes_min": 200, "host_rss_bytes": 3000,
        'disk_free_bytes{path="/ck"}': 900})
    g = merge_host_docs([d0, d1])["gauges"]
    assert g["disk_free_bytes_min"] == 200
    assert g['disk_free_bytes{path="/ck"}'] == 800
    assert g["host_rss_bytes"] == 3000


def test_push_receiver_fleet_merge_inherits_min_rule():
    """The push-receiver fleet aggregate rides merge_host_docs, so its
    free-space gauges min-reduce too."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "push_receiver", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "push_receiver.py"))
    pr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pr)
    d0 = _doc_with_gauges(0, {"disk_free_bytes_min": 50})
    d1 = _doc_with_gauges(1, {"disk_free_bytes_min": 900})
    merged = pr.merge_fleet({"hostA": d0, "hostB": d1})
    assert merged["gauges"]["disk_free_bytes_min"] == 50
    assert merged["meta"]["fleet_hosts"] == ["hostA", "hostB"]


def test_fleet_aggregated_document_schema_contract():
    """The ISSUE 20 fleet-document contract: meta.host_process_count
    > 1 requires one host shard per process with distinct in-range
    host_process_index values (telemetry/schema), and the name-level
    gate (tools/metrics_check) requires the fleet-reduced resource
    gauges plus each sentinel host's compile ledger."""
    from quorum_tpu.parallel.multihost import merge_host_docs
    from quorum_tpu.telemetry import validate_metrics

    def shard(pi):
        reg = _host_reg(10, 1, {0: 1}, 1.0, pi)
        reg.set_meta(host_process_count=2, host_process_index=pi,
                     compile_sentinel=True)
        reg.gauge("disk_free_bytes_min").set(100 + pi)
        reg.gauge("host_rss_bytes").set(1000)
        reg.gauge('disk_free_bytes{path="/o"}').set(50)
        reg.counter('compiles{site="stage1.insert"}').inc()
        return reg.as_dict()

    merged = merge_host_docs([shard(0), shard(1)])
    assert validate_metrics(merged) == []

    # a dropped host shard fails the schema shape check
    broken = json.loads(json.dumps(merged))
    del broken["hosts"]["1"]
    broken["meta"]["aggregated_hosts"] = 1
    assert any("host shard" in e for e in validate_metrics(broken))

    # duplicate process indices (one host overwrote another) fail
    dup = json.loads(json.dumps(merged))
    dup["hosts"]["1"]["meta"]["host_process_index"] = 0
    assert any("duplicate" in e for e in validate_metrics(dup))

    # name-level gate: the checker requires the reduced gauges and
    # each sentinel host's compiles{site=} ledger
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "metrics_check", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "metrics_check.py"))
    mc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mc)
    assert mc._check_multihost_fleet(merged) == []
    nogauge = json.loads(json.dumps(merged))
    del nogauge["gauges"]["disk_free_bytes_min"]
    assert any("disk_free_bytes_min" in e
               for e in mc._check_multihost_fleet(nogauge))
    noledger = json.loads(json.dumps(merged))
    del noledger["hosts"]["0"]["counters"]['compiles{site="stage1.insert"}']
    assert any("compile ledger was dropped" in e
               for e in mc._check_multihost_fleet(noledger))
