"""Multi-host input sharding (parallel/multihost): the global plan is
deterministic, partitioning (no file lost, none duplicated), and
size-balanced; single-process behavior is the identity."""

import numpy as np

from quorum_tpu.parallel import multihost


def _mk_files(tmp_path, sizes):
    paths = []
    for i, s in enumerate(sizes):
        p = tmp_path / f"r{i}.fastq"
        p.write_bytes(b"@r\n" + b"A" * s + b"\n+\n" + b"I" * s + b"\n")
        paths.append(str(p))
    return paths


def test_partition_no_loss_no_dup(tmp_path):
    paths = _mk_files(tmp_path, [10, 2000, 50, 50, 800, 300, 7, 4000])
    pc = 3
    shards = [multihost.host_shard_paths(paths, pi, pc)
              for pi in range(pc)]
    got = [p for s in shards for p in s]
    assert sorted(got) == sorted(paths)
    assert len(got) == len(set(got))


def test_balanced_by_size(tmp_path):
    rng = np.random.default_rng(0)
    sizes = rng.integers(100, 10_000, size=24).tolist()
    paths = _mk_files(tmp_path, sizes)
    sz = dict(zip(paths, sizes))
    pc = 4
    loads = [sum(sz[p] for p in multihost.host_shard_paths(paths, pi, pc))
             for pi in range(pc)]
    assert max(loads) < 1.5 * (sum(sizes) / pc)


def test_single_process_identity(tmp_path):
    paths = _mk_files(tmp_path, [5, 5])
    assert multihost.host_shard_paths(paths, 0, 1) == paths
    batches = list(multihost.read_batches_multihost(paths, 4))
    assert sum(b.n for b in batches) == 2


def test_deterministic_across_hosts(tmp_path):
    """Every host must compute the same global plan independently."""
    paths = _mk_files(tmp_path, [10, 2000, 50, 800])
    pc = 2
    a = [multihost.host_shard_paths(paths, pi, pc) for pi in range(pc)]
    b = [multihost.host_shard_paths(paths, pi, pc) for pi in range(pc)]
    assert a == b


def test_read_batches_metrics(tmp_path):
    """Telemetry wiring: this host's input share and batch/read
    counters."""
    from quorum_tpu.telemetry import MetricsRegistry

    paths = _mk_files(tmp_path, [5, 5])
    reg = MetricsRegistry()
    batches = list(multihost.read_batches_multihost(paths, 4,
                                                    metrics=reg))
    doc = reg.as_dict()
    assert doc["gauges"]["host_input_files"] == 2
    assert doc["gauges"]["host_input_bytes"] > 0
    assert doc["counters"]["host_reads"] == sum(b.n for b in batches) == 2
    assert doc["counters"]["host_batches"] == len(batches)
    assert doc["meta"]["host_input_paths"] == paths
