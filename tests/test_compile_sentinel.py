"""Compile-sentinel tests (ISSUE 15): the runtime half of the
trace-contract tier.

Unit level: the jit wrapper counts cache MISSES only (hits and
re-entrant calls are free), the budget checks fire with the
acquisition stack attached (overrun, duplicate-signature,
unbudgeted), `jax.clear_caches` starts a fresh epoch, and jits
created OUTSIDE the package come back unwrapped. System level: a
warm CorrectionEngine answers a second request with zero ledgered
compiles, and the seeded regression — dropping the bucket from
warmup — demonstrably shows up as a request-phase compile.

Deliberate violations are made against a monkeypatched budget and
always reset, so they never leak into the conftest autouse gate.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quorum_tpu.analysis import compile_budget, compile_sentinel as cs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
READS = os.path.join(REPO, "tests", "golden", "reads.fastq")

# a real budgeted key to ledger synthetic events against
SITE = "quorum_tpu/ops/ctable.py:lookup"


@pytest.fixture
def sentinel(monkeypatch):
    """Install (if not already via QUORUM_COMPILE_SENTINEL=1) and
    always reset afterwards so deliberate violations never reach the
    conftest gate. Budget edits go through monkeypatch on a copied
    catalog."""
    was_installed = cs.installed()
    cs.install()
    fake = {k: compile_budget.Budget(v.site, v.entry, v.per, v.allow,
                                     v.recreated)
            for k, v in compile_budget.COMPILE_BUDGET.items()}
    monkeypatch.setattr(compile_budget, "COMPILE_BUDGET", fake)
    try:
        yield fake
    finally:
        cs.reset()
        if not was_installed:
            cs.uninstall()


def _wrapped(fun, site=SITE, **jit_kw):
    """A _SentinelJit around a real jitted function, pinned to a
    budgeted site — the factory's attribution path is exercised by
    the whole suite running under the sentinel; these tests pin the
    site so the budget semantics are deterministic."""
    return cs._SentinelJit(jax.jit(fun, **jit_kw), site)


# -- miss/hit counting ----------------------------------------------------

def test_cache_miss_counted_hit_free(sentinel):
    f = _wrapped(lambda x: x + 1)
    before = len(cs.events())
    f(jnp.ones(3))
    assert len(cs.events()) == before + 1
    f(jnp.ones(3))                       # cached: no event
    f(jnp.ones(3))
    assert len(cs.events()) == before + 1
    f(jnp.ones(4))                       # new shape: one event
    events = cs.events()
    assert len(events) == before + 2
    assert events[-1]["site"] == SITE
    assert any("float32[4]" in leaf for leaf in events[-1]["signature"])


def test_reentrant_nested_trace_not_double_counted(sentinel):
    inner = _wrapped(lambda x: x * 2)
    outer = _wrapped(lambda x: inner(x) + 1,
                     site="quorum_tpu/ops/ctable.py:tile_lookup")
    before = len(cs.events())
    outer(jnp.ones(5))
    # the inner jit traced under the outer is INLINED into the outer
    # executable — one ledger event, which is one real executable
    assert len(cs.events()) == before + 1
    assert cs.events()[-1]["site"].endswith("tile_lookup")
    outer(jnp.ones(5))
    assert len(cs.events()) == before + 1
    # a later CONCRETE call of the inner compiles its own standalone
    # executable: second event, at the inner site
    inner(jnp.ones(5))
    assert len(cs.events()) == before + 2
    assert cs.events()[-1]["site"] == SITE


def test_clear_caches_starts_new_epoch_no_duplicate(sentinel):
    f = _wrapped(lambda x: x - 1)
    f(jnp.ones(2))
    jax.clear_caches()
    f(jnp.ones(2))  # legitimate re-pay: new epoch, not a duplicate
    assert [v for v in cs.violations() if v["kind"] == "duplicate"] \
        == []


def test_reset_resyncs_warm_wrappers(sentinel):
    # a ledger reset() forgets history but the jit caches stay warm:
    # a post-reset cache HIT must not replay the wrapper's prior
    # cache size as phantom compile events (it did, before the floors
    # were re-anchored on reset — every later test's warm calls
    # inflated compile_events)
    f = _wrapped(lambda x: x + 1)
    for n in (1, 2, 3):
        f(jnp.ones(n))
    cs.reset()
    assert cs.events() == []
    f(jnp.ones(2))                        # warm hit: nothing to report
    assert cs.events() == []
    f(jnp.ones(9))                        # genuinely new: one event
    assert [e["count"] for e in cs.events()] == [1]


def test_external_jit_left_unwrapped(sentinel):
    # a jit created from test code (outside quorum_tpu/) must come
    # back raw: the budget is about the package's own sites
    f = jax.jit(lambda x: x + 1)
    assert not isinstance(f, cs._SentinelJit)
    before = len(cs.events())
    f(jnp.ones(3))
    assert len(cs.events()) == before


def test_wrapper_delegates_attributes(sentinel):
    def plus(x):
        return x + 1
    f = _wrapped(plus)
    assert f.__wrapped__ is plus  # jax.jit exposes the target
    f(jnp.ones(2))
    assert f._cache_size() >= 1


# -- budget checks --------------------------------------------------------

def test_budget_overrun_fails_with_stack(sentinel):
    sentinel[SITE].allow = 2
    f = _wrapped(lambda x: x + 1)
    before = len(cs.violations())
    for n in (1, 2, 3):
        f(jnp.ones(n))
    fresh = [v for v in cs.violations()[before:]
             if v["kind"] == "overrun"]
    assert len(fresh) == 1
    v = fresh[0]
    assert v["site"] == SITE
    assert "allowance of 2" in v["detail"]
    report = cs.format_violation(v)
    assert "test_compile_sentinel" in v["stack"]
    assert "overrun" in report and SITE in report


def test_duplicate_compile_detected_unless_recreated(sentinel):
    before = len(cs.violations())
    # two instances of the same non-recreated site compiling the same
    # signature: the re-jit-per-call bug class
    _wrapped(lambda x: x + 1)(jnp.ones(3))
    _wrapped(lambda x: x + 1)(jnp.ones(3))
    dups = [v for v in cs.violations()[before:]
            if v["kind"] == "duplicate"]
    assert len(dups) == 1 and dups[0]["site"] == SITE
    # the same shape at a `recreated` site is the documented pattern
    sentinel[SITE].recreated = True
    before = len(cs.violations())
    _wrapped(lambda x: x + 2)(jnp.ones(3))
    _wrapped(lambda x: x + 2)(jnp.ones(3))
    assert [v for v in cs.violations()[before:]
            if v["kind"] == "duplicate"] == []


def test_unbudgeted_site_is_violation(sentinel):
    ghost = "quorum_tpu/ops/ctable.py:ghost_kernel"
    before = len(cs.violations())
    _wrapped(lambda x: x * 3, site=ghost)(jnp.ones(2))
    fresh = [v for v in cs.violations()[before:]
             if v["kind"] == "unbudgeted"]
    assert len(fresh) == 1 and fresh[0]["site"] == ghost


# -- ledger export --------------------------------------------------------

def test_export_stamps_registry(sentinel, tmp_path):
    import json

    from quorum_tpu.telemetry.registry import MetricsRegistry
    _wrapped(lambda x: x + 7)(jnp.ones(9))
    path = str(tmp_path / "m.json")
    reg = MetricsRegistry(path)
    reg.write()
    doc = json.load(open(path))
    assert doc["counters"]["compile_events"] >= 1
    assert doc["meta"]["compile_sentinel"] == 1
    assert SITE in doc["meta"]["compile_sites"]
    labeled = [k for k in doc["counters"] if k.startswith("compiles{")]
    assert any(SITE in k for k in labeled)
    # idempotent: a second final write must not double the counters
    total = doc["counters"]["compile_events"]
    reg.write()
    doc2 = json.load(open(path))
    assert doc2["counters"]["compile_events"] == total


# -- the engine contract: warm serve compiles zero ------------------------

@pytest.fixture(scope="module")
def warm_engine(tmp_path_factory):
    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.serve.engine import CorrectionEngine
    db = str(tmp_path_factory.mktemp("cs_db") / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, READS])
    assert rc == 0
    return CorrectionEngine(db, cutoff=4, rows=16)


def _request(engine, length=100):
    seq = b"ACGT" * (length // 4)
    return engine.step([("r", seq, b"I" * len(seq))])


def test_warm_serve_second_request_zero_compiles(warm_engine):
    """The engine docstring's promise, enforced: after warmup pays
    the length bucket, a request and a SECOND request ledger zero
    compiles (and grow zero engine shapes). Under
    QUORUM_COMPILE_SENTINEL=1 the ledger assertion is exact; in a
    plain run the shape-set half still gates."""
    warm_engine.warmup([100])
    _request(warm_engine)                    # first real request
    ledger = len(cs.events()) if cs.installed() else None
    shapes = warm_engine.compiles
    _request(warm_engine)                    # THE warm request
    assert warm_engine.compiles == shapes
    if ledger is not None:
        fresh = cs.events()[ledger:]
        assert fresh == [], (
            "warm serve request compiled: "
            + ", ".join(e["site"] for e in fresh))


def test_dropped_warmup_bucket_shows_as_request_compile(warm_engine):
    """The seeded regression of the acceptance criteria: a length
    bucket the warmup never paid compiles during the REQUEST instead
    — visible to the sentinel ledger (and the shape set), which is
    exactly what the conftest gate would flag on a budget breach."""
    shapes = warm_engine.compiles
    ledger = len(cs.events()) if cs.installed() else None
    # 256 maps to a bucket warmup([100]) never touched
    _request(warm_engine, length=256)
    assert warm_engine.compiles == shapes + 1
    if ledger is not None:
        assert len(cs.events()) > ledger, (
            "sentinel missed the unwarmed-bucket compile")


def test_lever_declared():
    from quorum_tpu.utils import levers
    assert "QUORUM_COMPILE_SENTINEL" in levers.CATALOG
    assert cs.enabled_by_env() == (
        os.environ.get("QUORUM_COMPILE_SENTINEL", "")
        .strip().lower() not in ("", "0", "false", "no"))
