"""Multi-host fleet tier unit tests (ISSUE 20): partition planning,
pass ownership, the order-preserving stage-2 segment merge, host
scoping of shared paths, bring-up idempotence, the host-run sanction,
and the sharded-checkpoint fleet agreement check. The live 2-process
fleet (real coordination service, byte-identity, kill-one-host
resume) is exercised end-to-end by tools/fleet_smoke.py in tier 1;
these tests pin the pure planning/merge logic every host computes
independently."""

import os

import numpy as np
import pytest

import conftest
from quorum_tpu.io import checkpoint as ckpt_mod
from quorum_tpu.models.create_database import BuildConfig, BuildStats
from quorum_tpu.parallel import fleet
from quorum_tpu.parallel import tile_sharded as ts


# ---------------------------------------------------------------------------
# partition planning and pass ownership
# ---------------------------------------------------------------------------

def test_plan_partitions_power_of_two_floor():
    # next power of two >= max(requested, processes, 1)
    assert fleet.plan_partitions(1, 1) == 1
    assert fleet.plan_partitions(0, 1) == 1
    assert fleet.plan_partitions(1, 2) == 2
    assert fleet.plan_partitions(2, 2) == 2
    assert fleet.plan_partitions(4, 2) == 4
    assert fleet.plan_partitions(3, 5) == 8
    assert fleet.plan_partitions(8, 3) == 8
    assert fleet.plan_partitions(9, 2) == 16


def test_owns_pass_partitions_cover_disjoint():
    """Every pass has exactly one owner; every host owns >= 1 pass
    whenever P >= num_processes (which plan_partitions guarantees)."""
    for pc in (1, 2, 3, 4):
        P = fleet.plan_partitions(4, pc)
        ctxs = [fleet.FleetContext(pc, h) for h in range(pc)]
        for p in range(P):
            owners = [h for h, c in enumerate(ctxs) if c.owns_pass(p)]
            assert owners == [p % pc]
        for c in ctxs:
            assert any(c.owns_pass(p) for p in range(P))


def test_grow_vote_single_process_identity():
    assert fleet.FleetContext(1, 0).grow_vote(7) == 7


def test_grow_vote_adopts_fleet_max(monkeypatch):
    monkeypatch.setattr(fleet, "exchange_json",
                        lambda tag, obj: [obj, 9, 6])
    assert fleet.FleetContext(3, 0).grow_vote(7) == 9


# ---------------------------------------------------------------------------
# host scoping of shared paths
# ---------------------------------------------------------------------------

def test_host_scoped_path_and_idempotence():
    assert fleet.host_scoped_path("m.json", 1) == "m.host0001.json"
    assert fleet.host_scoped_path("/a/b/m.jsonl", 0) == \
        "/a/b/m.host0000.jsonl"
    # the driver scopes its base, then forwards DERIVED per-stage
    # paths to the in-process stage CLIs, which scope again
    once = fleet.host_scoped_path("m.json", 2)
    assert fleet.host_scoped_path(once, 2) == once
    derived = "m.host0002.stage1.json"
    assert fleet.host_scoped_path(derived, 2) == derived
    # a DIFFERENT host's marker does not suppress scoping
    assert fleet.host_scoped_path("m.host0001.json", 2) == \
        "m.host0001.host0002.json"


def test_host_scoped_dir():
    c = fleet.FleetContext(2, 1)
    assert c.host_scoped_dir("/ck") == "/ck/host0001"


# ---------------------------------------------------------------------------
# the order-preserving stage-2 segment merge
# ---------------------------------------------------------------------------

def _write_segments(tmp_path, n, suffixes=(".fa", ".log")):
    prefix = str(tmp_path / "out")
    for gi in range(n):
        for s in suffixes:
            with open(fleet.segment_prefix(prefix, gi) + s, "wb") as f:
                f.write(f"seg{gi}{s};".encode())
    return prefix


def test_fleet_merge_preserves_global_file_order(tmp_path):
    prefix = _write_segments(tmp_path, 3)
    fleet.fleet_merge(prefix, 3)
    assert open(prefix + ".fa", "rb").read() == \
        b"seg0.fa;seg1.fa;seg2.fa;"
    assert open(prefix + ".log", "rb").read() == \
        b"seg0.log;seg1.log;seg2.log;"
    # segments are consumed by default
    assert not [p for p in os.listdir(str(tmp_path)) if ".fleet" in p]


def test_fleet_merge_keep_segments(tmp_path):
    prefix = _write_segments(tmp_path, 2)
    fleet.fleet_merge(prefix, 2, keep_segments=True)
    assert os.path.exists(fleet.segment_prefix(prefix, 0) + ".fa")
    assert open(prefix + ".fa", "rb").read() == b"seg0.fa;seg1.fa;"


def test_fleet_merge_missing_segment_is_hard_error(tmp_path):
    prefix = _write_segments(tmp_path, 3)
    os.remove(fleet.segment_prefix(prefix, 1) + ".fa")
    with pytest.raises(RuntimeError, match="missing output segment"):
        fleet.fleet_merge(prefix, 3)
    # no partial merged output left behind (tmp cleaned up)
    assert not os.path.exists(prefix + ".fa")
    assert not [p for p in os.listdir(str(tmp_path))
                if p.endswith(".tmp")]


# ---------------------------------------------------------------------------
# bring-up and the host-run sanction
# ---------------------------------------------------------------------------

def test_ensure_initialized_single_process_noop(monkeypatch):
    for var in ("QUORUM_FLEET_COORDINATOR",
                "QUORUM_FLEET_NUM_PROCESSES",
                "QUORUM_FLEET_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    fleet._reset_for_tests()
    assert fleet.ensure_initialized() is None
    assert fleet.active() is None


def test_ensure_initialized_rejects_bad_process_id(monkeypatch):
    fleet._reset_for_tests()
    # resolving flags must fail loudly BEFORE jax.distributed runs
    class A:
        coordinator = "127.0.0.1:1"
        num_processes = 2
        process_id = 2
    with pytest.raises(ValueError, match=r"process-id must be in"):
        fleet.ensure_initialized(A())
    fleet._reset_for_tests()


def test_exchange_bytes_single_process_identity():
    assert fleet.exchange_bytes("t", b"x", process_index=0,
                                process_count=1) == [b"x"]


def test_global_mesh_spans_local_devices_single_process():
    # single-process: jax.devices() IS the local set, so the fleet's
    # global mesh is a 1-D mesh over it under the named axis
    import jax

    mesh = fleet.global_mesh("hosts")
    assert mesh.axis_names == ("hosts",)
    assert mesh.devices.size == len(jax.devices())


def test_host_run_nesting():
    assert not fleet.in_host_run()
    with fleet.host_run():
        assert fleet.in_host_run()
        with fleet.host_run():
            assert fleet.in_host_run()
        assert fleet.in_host_run()
    assert not fleet.in_host_run()


# ---------------------------------------------------------------------------
# sharded-checkpoint fleet generalization (io/checkpoint)
# ---------------------------------------------------------------------------

K, BATCH = 16, 64


def _saved_sharded_ckpt(tmp_path):
    mesh = ts.make_mesh(2, conftest.cpu_devices(2))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=6, n_shards=2)
    bstate = ts.make_build_state(meta, mesh)
    cfg = BuildConfig(k=K, bits=7, qual_thresh=53, batch_size=BATCH,
                      devices=2)
    stats = BuildStats(reads=10, bases=480, batches=3)
    ck = ckpt_mod.Stage1ShardedCheckpoint(str(tmp_path))
    ck.save(bstate, meta, cfg, 3, stats, ["a.fastq"])
    return ck, meta, bstate


def test_sharded_load_shard_subset(tmp_path):
    """A fleet host restores only the shards its devices hold; the
    subset planes equal the matching rows of the full restore."""
    ck, meta, bstate = _saved_sharded_ckpt(tmp_path)
    full = ck.load()
    rows_local = meta.rows // 2
    for s in (0, 1):
        part = ck.load(shards=[s])
        np.testing.assert_array_equal(
            part.tag, full.tag[s * rows_local:(s + 1) * rows_local])
        assert part.cursor == full.cursor
    # empty subset still restores the manifest (cursor agreement)
    empty = ck.load(shards=[])
    assert empty.cursor == full.cursor and empty.tag.shape[0] == 0
    with pytest.raises(ckpt_mod.CheckpointError, match="shard 5"):
        ck.load(shards=[5])


def test_sharded_fleet_agreement(tmp_path):
    """Hosts agreeing on the committed manifest proceed; any digest
    divergence (or one host seeing no manifest) refuses LOUDLY."""
    ck, _, _ = _saved_sharded_ckpt(tmp_path)
    agreed = ck.fleet_agreement(
        exchange=lambda tag, digest: [digest, digest])
    assert agreed is not None and int(agreed["cursor"]) == 3
    with pytest.raises(ckpt_mod.CheckpointError, match="disagree"):
        ck.fleet_agreement(
            exchange=lambda tag, digest: [digest, "deadbeef"])
    # a host with NO manifest while a peer has one must refuse too
    other = ckpt_mod.Stage1ShardedCheckpoint(str(tmp_path / "empty"))
    with pytest.raises(ckpt_mod.CheckpointError, match="disagree"):
        other.fleet_agreement(
            exchange=lambda tag, digest: [digest, "somedigest"])
    # no manifest ANYWHERE is a clean cold start, not an error
    assert other.fleet_agreement(
        exchange=lambda tag, digest: [digest, digest]) is None


def test_sharded_fleet_agreement_single_process(tmp_path):
    """Without an active fleet the check is a local manifest read."""
    fleet._reset_for_tests()
    ck, _, _ = _saved_sharded_ckpt(tmp_path)
    assert int(ck.fleet_agreement()["cursor"]) == 3
