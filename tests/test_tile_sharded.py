"""Multi-chip on the PRODUCTION tile table (round 4): owner-bucketed
all_to_all build parity (incl. undersized-to-force-grow, the SURVEY §4
trick), routed queries, DP correction on replicated tile state, and
the routed-corrector capacity path — all against the single-chip tile
implementations on a virtual CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import conftest
from quorum_tpu.models import corrector
from quorum_tpu.models.create_database import extract_observations
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.ops import ctable
from quorum_tpu.parallel import tile_sharded as ts

K = 9
RLEN = 40


def _reads(rng, n_reads, genome_size=600, err=0.03):
    genome = rng.integers(0, 4, size=genome_size, dtype=np.int8)
    starts = rng.integers(0, genome_size - RLEN, size=n_reads)
    codes = genome[starts[:, None] + np.arange(RLEN)[None, :]].astype(np.int8)
    e = rng.random(codes.shape) < err
    codes = np.where(e, (codes + rng.integers(1, 4, size=codes.shape)) % 4,
                     codes).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    quals[rng.random(codes.shape) < 0.05] = 34  # some low-quality bases
    return codes, quals


def _single_chip_build(codes, quals, rb_log2):
    meta = ctable.TileMeta(k=K, bits=7, rb_log2=rb_log2)
    bstate = ctable.make_tile_build(meta)
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), K, 53)
    pending = valid
    for _ in range(8):
        bstate, full, placed = ctable.tile_insert_observations(
            bstate, meta, chi, clo, q, pending)
        if not full:
            break
        pending = jnp.logical_and(pending, jnp.logical_not(placed))
        bstate, meta = ctable.tile_grow_build(bstate, meta)
    else:
        raise AssertionError("single-chip build could not grow enough")
    return ctable.tile_finalize(bstate, meta), meta


def _entry_map(state, meta):
    khi, klo, vals = ctable.tile_iterate(state, meta)
    return {(int(h), int(lo)): int(v)
            for h, lo, v in zip(khi, klo, vals)}


@pytest.mark.parametrize("n_shards", [1, 2, 8])
def test_build_parity(n_shards):
    rng = np.random.default_rng(n_shards)
    codes, quals = _reads(rng, 8 * n_shards * 4)
    mesh = ts.make_mesh(n_shards, conftest.cpu_devices(n_shards))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=max(8, 3 + (
        n_shards - 1).bit_length()), n_shards=n_shards)
    state, meta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, meta, 53)
    gstate, gmeta = ts.gather_table(state, meta)
    sstate, smeta = _single_chip_build(codes, quals, meta.rb_log2)
    assert _entry_map(gstate, gmeta) == _entry_map(sstate, smeta)


def test_build_grow_parity():
    """Undersized initial geometry forces the cross-shard re-routing
    resize; final content must still match the single-chip build."""
    n_shards = 4
    rng = np.random.default_rng(77)
    codes, quals = _reads(rng, 64, genome_size=3000)
    mesh = ts.make_mesh(n_shards, conftest.cpu_devices(n_shards))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=4, n_shards=n_shards)
    state, meta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, meta, 53)
    assert meta.rb_log2 > 4, "growth did not trigger"
    gstate, gmeta = ts.gather_table(state, meta)
    sstate, smeta = _single_chip_build(codes, quals, meta.rb_log2)
    assert _entry_map(gstate, gmeta) == _entry_map(sstate, smeta)


def test_routed_query():
    n_shards = 4
    rng = np.random.default_rng(5)
    codes, quals = _reads(rng, 64)
    mesh = ts.make_mesh(n_shards, conftest.cpu_devices(n_shards))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=8, n_shards=n_shards)
    state, meta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, meta, 53)
    gstate, gmeta = ts.gather_table(state, meta)
    khi, klo, vals = ctable.tile_iterate(gstate, gmeta)
    n = (len(khi) // n_shards) * n_shards
    khi, klo, vals = khi[:n], klo[:n], vals[:n]
    q = ts.query_step(mesh, meta)
    got = np.asarray(q(state, jnp.asarray(khi), jnp.asarray(klo)))
    assert np.array_equal(got, vals)
    # absent keys return 0 (flip IN-DOMAIN bits only: bits above 2k
    # are masked off by the Feistel, so flipping them aliases present
    # keys)
    mlo = klo ^ np.uint32(0xA5)
    miss = np.asarray(q(state, jnp.asarray(khi), jnp.asarray(mlo)))
    present = {(int(h), int(lo)) for h, lo in zip(khi, klo)}
    for i, (h, lo) in enumerate(zip(khi, mlo)):
        if (int(h), int(lo)) not in present:
            assert int(miss[i]) == 0


def _batch_result_equal(a, b):
    for name in ("out", "start", "end", "status"):
        np.testing.assert_array_equal(np.asarray(getattr(a, name)),
                                      np.asarray(getattr(b, name)))
    for la, lb in ((a.fwd_log, b.fwd_log), (a.bwd_log, b.bwd_log)):
        np.testing.assert_array_equal(np.asarray(la.n), np.asarray(lb.n))
        n = np.asarray(la.n)
        w = min(la.pos.shape[1], lb.pos.shape[1])
        msk = np.arange(w)[None, :] < n[:, None]
        for f in ("pos", "meta"):
            np.testing.assert_array_equal(
                np.where(msk, np.asarray(getattr(la, f))[:, :w], 0),
                np.where(msk, np.asarray(getattr(lb, f))[:, :w], 0))


@pytest.mark.parametrize("n_shards", [2, 8])
def test_dp_correct_tile(n_shards):
    """Reads data-parallel over the mesh, tile table replicated:
    bit-exact vs the single-chip corrector."""
    rng = np.random.default_rng(n_shards + 10)
    codes, quals = _reads(rng, 8 * n_shards)
    mesh = ts.make_mesh(n_shards, conftest.cpu_devices(n_shards))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=8, n_shards=n_shards)
    state, meta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, meta, 53)
    gstate, gmeta = ts.gather_table(state, meta)
    cfg = ECConfig(k=K, cutoff=2, poisson_dtype="float32")
    lengths = np.full((codes.shape[0],), RLEN, np.int32)
    step = ts.correct_step(mesh, gmeta, cfg)
    res = step(ts.replicate_table(gstate, mesh), codes, quals, lengths)
    single = corrector.correct_batch(gstate, gmeta, codes, quals,
                                     jnp.asarray(lengths), cfg)
    _batch_result_equal(res, single)
    assert int(np.sum(np.asarray(res.status) == corrector.OK)) > 0


def test_routed_correct_tile():
    """The capacity path: table stays sharded, every lookup routes
    over the mesh — still bit-exact vs single-chip. This is the layout
    that lifts the rb_log2<=24 per-chip ceiling."""
    n_shards = 4
    rng = np.random.default_rng(42)
    codes, quals = _reads(rng, 8 * n_shards)
    mesh = ts.make_mesh(n_shards, conftest.cpu_devices(n_shards))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=8, n_shards=n_shards)
    state, meta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, meta, 53)
    cfg = ECConfig(k=K, cutoff=2, poisson_dtype="float32")
    lengths = np.full((codes.shape[0],), RLEN, np.int32)
    step = ts.correct_step_routed(mesh, meta, cfg)
    res = step(state, codes, quals, lengths)
    gstate, gmeta = ts.gather_table(state, meta)
    single = corrector.correct_batch(gstate, gmeta, codes, quals,
                                     jnp.asarray(lengths), cfg)
    _batch_result_equal(res, single)
    assert int(np.sum(np.asarray(res.status) == corrector.OK)) > 0


def test_build_metrics_counters():
    """Telemetry wiring of the sharded build: batch/read/grow counters
    and the final per-shard occupancy matching the table content."""
    from quorum_tpu.telemetry import MetricsRegistry, validate_metrics

    n_shards = 2
    rng = np.random.default_rng(3)
    codes, quals = _reads(rng, 32, genome_size=1500)
    mesh = ts.make_mesh(n_shards, conftest.cpu_devices(n_shards))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=4, n_shards=n_shards)
    reg = MetricsRegistry()
    state, meta = ts.build_database_tile_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, meta, 53,
        metrics=reg)
    doc = reg.as_dict()
    assert validate_metrics(doc) == []
    c, g = doc["counters"], doc["gauges"]
    assert c["shard_batches"] == 1
    assert c["shard_reads"] == 32
    assert c["shard_grows"] >= 1  # rb_log2=4 is undersized on purpose
    gstate, gmeta = ts.gather_table(state, meta)
    n_distinct = len(_entry_map(gstate, gmeta))
    assert c["distinct_mers"] == n_distinct
    per = doc["meta"]["shard_distinct_mers"]
    assert len(per) == n_shards and sum(per) == n_distinct
    assert g["n_shards"] == n_shards
    assert g["shard_distinct_min"] <= g["shard_distinct_max"]
    assert per == ts.shard_occupancy(state, meta)
