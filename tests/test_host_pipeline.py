"""ISSUE 9: the sharded host pipeline — N finish/render workers behind
the sequence-numbered reorder stage (utils/pipeline.ReorderingPool),
byte parity for any worker count (including under kill -> resume
journal replay), worker-error propagation without deadlocking the
writer, and the span-parallel single-file FASTQ parse."""

import json
import os
import threading
import time

import numpy as np
import pytest

from quorum_tpu.io import fastq
from quorum_tpu.utils.pipeline import ReorderingPool

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
READS = os.path.join(GOLDEN, "reads.fastq")
BATCH = 64  # 242 golden reads -> 4 batches


# ---------------------------------------------------------------------------
# ReorderingPool: the reorder stage in isolation
# ---------------------------------------------------------------------------


def test_reorder_out_of_order_completion():
    """Workers finishing out of order must still drain in submission
    order — the property the `.fa`/`.log` byte-parity guarantee rests
    on."""
    release = [threading.Event() for _ in range(6)]
    done: list = []

    def work(i):
        release[i].wait(timeout=10)
        return i

    pool = ReorderingPool(3, done.append, max_pending=6)
    for i in range(6):
        pool.submit(work, i)
    # finish them backwards: 5 first, 0 last
    for i in reversed(range(6)):
        release[i].set()
        time.sleep(0.005)
    pool.flush()
    pool.shutdown()
    assert done == [0, 1, 2, 3, 4, 5]
    assert pool.take_reorder_wait() >= 0.0


def test_reorder_worker_error_propagates():
    """A worker raising mid-batch re-raises at the drain point, in
    order — never a silent skip, never a deadlock."""
    done: list = []

    def work(i):
        if i == 2:
            raise ValueError("injected render failure")
        return i

    pool = ReorderingPool(2, done.append, max_pending=4)
    try:
        with pytest.raises(ValueError, match="injected render"):
            for i in range(8):
                pool.submit(work, i)
            pool.flush()
    finally:
        pool.shutdown()
    # items before the failing one drained in order; nothing after it
    assert done == [0, 1]


def test_reorder_backpressure_bounds_pending():
    """submit() drains the head once max_pending items are in flight —
    bounded RAM (each pending item holds a fetched D2H buffer)."""
    gate = threading.Event()
    done: list = []

    def work(i):
        gate.wait(timeout=10)
        return i

    pool = ReorderingPool(2, done.append, max_pending=3)
    for i in range(3):
        pool.submit(work, i)
    assert pool.depth == 3
    gate.set()
    pool.submit(work, 3)  # must first drain the head
    assert pool.depth <= 3
    pool.flush()
    pool.shutdown()
    assert done == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# Render workers through the real stage-2 pipeline
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def golden_db(tmp_path_factory):
    from quorum_tpu.cli import create_database as cdb_cli
    db = str(tmp_path_factory.mktemp("hostpipe") / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, READS])
    assert rc == 0
    return db


def _correct(db, prefix, extra=()):
    from quorum_tpu.cli import error_correct_reads as ec_cli
    rc = ec_cli.main(["-o", prefix, "-p", "4",
                      "--batch-size", str(BATCH), *extra, db, READS])
    assert rc == 0
    return prefix


def test_render_workers_byte_parity(golden_db, tmp_path):
    """`.fa`/`.log` bytes identical across --render-workers {1, 3}
    (the acceptance property), and the host-tail attribution
    histograms land in the metrics document."""
    p1 = _correct(golden_db, str(tmp_path / "w1"),
                  ("--render-workers", "1"))
    mpath = str(tmp_path / "metrics.json")
    p3 = _correct(golden_db, str(tmp_path / "w3"),
                  ("--render-workers", "3", "--metrics", mpath))
    for suffix in (".fa", ".log"):
        a = open(p1 + suffix, "rb").read()
        b = open(p3 + suffix, "rb").read()
        assert a == b, f"--render-workers 3 {suffix} differs from 1"
    assert open(p1 + ".fa", "rb").read()  # non-trivial output
    doc = json.load(open(mpath))
    assert doc["meta"]["render_workers"] == 3
    assert "render_ms" in doc["histograms"]
    assert "reorder_wait_ms" in doc["histograms"]
    assert doc["histograms"]["render_ms"]["count"] >= 1


def test_render_worker_failure_fails_run(golden_db, tmp_path,
                                         monkeypatch):
    """A render worker raising mid-run propagates out of the pipeline
    (the writer closes via the normal error path; the run must not
    hang waiting on a result that will never come)."""
    from quorum_tpu.models import error_correct as ec_mod

    real = ec_mod.finish_batch_host
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected finish failure")
        return real(*a, **kw)

    monkeypatch.setattr(ec_mod, "finish_batch_host", flaky)
    opts = ec_mod.ECOptions(output=str(tmp_path / "boom"), cutoff=4,
                            batch_size=BATCH, render_workers=3)
    with pytest.raises(RuntimeError, match="injected finish"):
        ec_mod.run_error_correct(golden_db, [READS], None, opts)
    assert calls["n"] >= 2


def test_render_workers_kill_resume_parity(golden_db, tmp_path):
    """Journal replay under N render workers: a run failed at batch 2
    and resumed with --render-workers 3 is byte-identical to an
    uninterrupted single-worker run — the reorder stage preserves the
    journal's batch commit order."""
    from quorum_tpu.cli import error_correct_reads as ec_cli
    ref = _correct(golden_db, str(tmp_path / "ref"),
                   ("--render-workers", "1"))
    prefix = str(tmp_path / "resumed")
    plan = json.dumps([{"site": "stage2.correct", "batch": 2,
                        "action": "error", "message": "injected"}])
    rc = ec_cli.main(["-o", prefix, "-p", "4",
                      "--batch-size", str(BATCH),
                      "--checkpoint-every", "1",
                      "--render-workers", "3",
                      "--fault-plan", plan, golden_db, READS])
    assert rc != 0
    assert os.path.exists(prefix + ".resume.json")
    _correct(golden_db, prefix,
             ("--checkpoint-every", "1", "--resume",
              "--render-workers", "3", "--fault-plan", ""))
    for suffix in (".fa", ".log"):
        assert (open(prefix + suffix, "rb").read()
                == open(ref + suffix, "rb").read()), suffix
    assert not os.path.exists(prefix + ".resume.json")


def test_resolve_render_workers():
    from quorum_tpu.models.error_correct import resolve_render_workers
    assert resolve_render_workers(3) == 3
    assert resolve_render_workers(1) == 1
    auto = resolve_render_workers(0)
    assert 1 <= auto <= 4


# ---------------------------------------------------------------------------
# Span-parallel single-file FASTQ parse
# ---------------------------------------------------------------------------


@pytest.fixture()
def span_fastq(tmp_path, monkeypatch):
    """A FASTQ big enough to split, with '@'-leading quality bytes (the
    classic mis-sync trap) and varied read lengths; the size threshold
    is lowered so the span path engages on a test-sized file."""
    monkeypatch.setattr(fastq, "PARALLEL_SPAN_MIN_BYTES", 1024)
    rng = np.random.default_rng(11)
    bases = b"ACGT"
    path = tmp_path / "big.fastq"
    with open(path, "wb") as f:
        for i in range(400):
            m = int(rng.integers(30, 120))
            seq = bytes(bases[c] for c in rng.integers(0, 4, m))
            qual = bytes(int(q) for q in rng.integers(33, 75, m))
            f.write(b"@r%d desc\n" % i + seq + b"\n+\n" + qual + b"\n")
    return str(path)


def _batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.headers == y.headers
        assert x.n == y.n
        np.testing.assert_array_equal(x.codes, y.codes)
        np.testing.assert_array_equal(x.quals, y.quals)
        np.testing.assert_array_equal(x.lengths, y.lengths)


def test_span_parallel_parity(span_fastq):
    """threads=4 on ONE file must produce the exact batch stream the
    serial parse does — headers, codes, quals, lengths, batching."""
    spans = fastq._single_file_spans(span_fastq, 4)
    assert spans and len(spans) > 1
    serial = list(fastq.read_batches([span_fastq], 48, threads=1))
    par = list(fastq.read_batches([span_fastq], 48, threads=4))
    _batches_equal(serial, par)


def test_span_non_abort_policy_forces_serial(span_fastq, monkeypatch):
    """skip/quarantine policies opt OUT of span parallelism: on a
    damaged file, WHICH records a resync swallows depends on parser
    state carried across the damage — a span cut truncates that, so
    the survivor stream could diverge from the serial parse. Triage
    modes stay serial; the counts and batches therefore match the
    serial parse exactly (they ARE the serial parse)."""
    data = open(span_fastq, "rb").read()
    lines = data.split(b"\n")
    # truncate one quality line mid-file: a classic torn record
    for i in range(len(lines) // 2, len(lines)):
        if lines[i] == b"+":
            lines[i + 1] = lines[i + 1][:-3]
            break
    bad_path = span_fastq + ".bad"
    open(bad_path, "wb").write(b"\n".join(lines))

    def boom(*a, **kw):
        raise AssertionError("span path used under a non-abort policy")

    monkeypatch.setattr(fastq, "_iter_records_spans", boom)
    pol_s = fastq.BadReadPolicy("skip")
    serial = list(fastq.read_batches([bad_path], 48, threads=1,
                                     policy=pol_s))
    pol_p = fastq.BadReadPolicy("skip")
    par = list(fastq.read_batches([bad_path], 48, threads=4,
                                  policy=pol_p))
    assert pol_s.bad == pol_p.bad >= 1
    _batches_equal(serial, par)


def test_span_probe_rejects_unsplittable(tmp_path, monkeypatch):
    """FASTA, gzip, and tiny files fall back to the serial parse
    (spans = None), never a mis-split."""
    monkeypatch.setattr(fastq, "PARALLEL_SPAN_MIN_BYTES", 16)
    fa = tmp_path / "a.fasta"
    fa.write_bytes(b">r1\nACGTACGTACGTACGT\n>r2\nTTTTACGTACGTAAAA\n" * 50)
    assert fastq._single_file_spans(str(fa), 4) is None
    import gzip as gz
    fq = tmp_path / "a.fastq.gz"
    with gz.open(fq, "wb") as f:
        f.write(b"@r1\nACGT\n+\nIIII\n" * 200)
    assert fastq._single_file_spans(str(fq), 4) is None
    # WRAPPED (multi-line) FASTQ: _iter_one parses it, but there are
    # no record-aligned byte cuts — must stay serial, never mis-split
    wrapped = tmp_path / "wrapped.fastq"
    with open(wrapped, "wb") as f:
        for i in range(200):
            f.write(b"@w%d\nACGTACGT\nACGTACGT\n+\n!!!!!!!!\n!!!!!!!!\n"
                    % i)
    assert fastq._single_file_spans(str(wrapped), 4) is None
    got_w = list(fastq.read_batches([str(wrapped)], 16, threads=4))
    assert sum(b.n for b in got_w) == 200
    assert got_w[0].lengths[0] == 16  # both chunks, one record
    tiny = tmp_path / "tiny.fastq"
    monkeypatch.setattr(fastq, "PARALLEL_SPAN_MIN_BYTES", 1 << 20)
    tiny.write_bytes(b"@r1\nACGT\n+\nIIII\n" * 10)
    assert fastq._single_file_spans(str(tiny), 4) is None
    # unsplittable input still parses fine through read_batches
    got = list(fastq.read_batches([str(fa)], 16, threads=4))
    assert sum(b.n for b in got) == 100


def test_span_fault_plan_forces_serial(span_fastq, monkeypatch):
    """An active fault plan opts OUT of span parallelism (the
    `fastq.read` `at=`/`count=` hit indices must be reproducible, not
    scheduler-dependent) — the fault still fires, on the serial
    parser."""
    from quorum_tpu.utils import faults

    def boom(*a, **kw):
        raise AssertionError("span path used under an active plan")

    monkeypatch.setattr(fastq, "_iter_records_spans", boom)
    plan = [{"site": "fastq.read", "action": "error",
             "message": "injected parse fault", "count": 1}]
    faults.install(faults.FaultPlan.parse(plan))
    try:
        with pytest.raises(RuntimeError, match="injected parse fault"):
            list(fastq.read_batches([span_fastq], 48, threads=4))
    finally:
        faults.reset()


def test_span_quarantine_forces_serial(span_fastq, monkeypatch):
    """A quarantine policy opts out of span parallelism too: the
    .quarantine.fastq must hold bad records in FILE ORDER, which only
    the serial parse guarantees."""
    def boom(*a, **kw):
        raise AssertionError("span path used under quarantine policy")

    monkeypatch.setattr(fastq, "_iter_records_spans", boom)
    qpath = span_fastq + ".quarantine"
    pol = fastq.BadReadPolicy("quarantine", qpath)
    got = list(fastq.read_batches([span_fastq], 48, threads=4,
                                  policy=pol))
    assert sum(b.n for b in got) == 400
