"""ECConfig defaults validated against the reference CLI spec
(src/error_correct_reads_cmdline.yaggo) — VERDICT r2 item 10."""

import os
import re

import pytest

from quorum_tpu.models.ec_config import ECConfig

YAGGO = "/root/reference/src/error_correct_reads_cmdline.yaggo"


def yaggo_defaults():
    text = open(YAGGO).read()
    out = {}
    for m in re.finditer(
            r'option\("([^"]+)"[^)]*\)\s*\{[^}]*?default\s+"?([0-9.e-]+)"?',
            text, re.S):
        out[m.group(1).replace("-", "_")] = m.group(2)
    return out


@pytest.mark.skipif(not os.path.exists(YAGGO), reason="reference not mounted")
def test_defaults_match_yaggo():
    d = yaggo_defaults()
    cfg = ECConfig(k=24, cutoff=4)
    assert cfg.skip == int(d["skip"])
    assert cfg.good == int(d["good"])
    assert cfg.anchor_count == int(d["anchor_count"])
    assert cfg.min_count == int(d["min_count"])
    assert cfg.window == int(d["window"])
    assert cfg.error == int(d["error"])
    assert cfg.poisson_threshold == float(d["poisson_threshold"])
    assert cfg.collision_prob == float(d["apriori_error_rate"]) / 3.0
    # cutoff intentionally has NO usable default (computed per DB)
    with pytest.raises(TypeError):
        ECConfig(k=24)


def test_window_error_fallbacks():
    cfg = ECConfig(k=20, cutoff=4, window=0, error=0)
    assert cfg.effective_window == 20
    assert cfg.effective_error == 10
