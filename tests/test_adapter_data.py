"""Built-in adapter contaminant set (quorum_tpu/data): the
error-tolerant expansion rule (canonical Illumina adapters + all
1-substitution variants, reference data/adapter.fa) and its use as a
--contaminant input."""

import numpy as np

from quorum_tpu.data import ADAPTERS, adapter_fasta, adapter_records
from quorum_tpu.io.contaminant import load_contaminant
from quorum_tpu.io import db_format
from quorum_tpu.ops import mer


def test_expansion_rule():
    recs = list(adapter_records())
    seqs = [s for _, s in recs]
    assert len(seqs) == len(set(seqs))  # dedup'd
    # originals first
    assert seqs[:len(set(ADAPTERS))] == list(dict.fromkeys(ADAPTERS))
    # every record is hamming<=1 from a canonical adapter
    for s in seqs:
        ok = any(len(s) == len(b)
                 and sum(a != c for a, c in zip(s, b)) <= 1
                 for b in ADAPTERS)
        assert ok, s
    # and the expansion is complete: 7 canonical + 3*len 1-sub variants
    # minus cross-set duplicates = the reference's 871-sequence set
    want = set()
    for b in ADAPTERS:
        want.add(b)
        for j, c in enumerate(b):
            for x in "ACGT":
                if x != c:
                    want.add(b[:j] + x + b[j + 1:])
    assert set(seqs) == want
    assert len(want) == 871


def test_adapter_fasta_loads_as_contaminant(tmp_path):
    path = adapter_fasta(str(tmp_path / "adapters.fa"))
    k = 24
    state, meta = load_contaminant(path, k)
    # a k-mer from inside an adapter is a member
    s = ADAPTERS[2][:k]
    codes = mer.seq_to_codes(s)
    fhi, flo, rhi, rlo, valid = mer.rolling_kmers(
        np.asarray(codes, np.int8)[None, :], k)
    chi, clo = mer.canonical(fhi, flo, rhi, rlo)
    assert db_format.db_lookup_np(state, meta, int(chi[0, k - 1]),
                                  int(clo[0, k - 1])) != 0
    # a random non-adapter k-mer is not
    assert db_format.db_lookup_np(state, meta, 0x12345678, 0x9abcdef0) == 0
