"""Alert rule engine (telemetry/alerts.py, ISSUE 11): rule-kind
semantics under a mocked clock, firing/healed transitions, the
heartbeat-thread survival guarantee, the serve /healthz detail, and
the metrics_check/schema surface for alert artifacts."""

import json
import os
import subprocess
import sys
import threading

import pytest

from quorum_tpu.telemetry import alerts, registry_for
from quorum_tpu.telemetry.alerts import AlertEngine
from quorum_tpu.telemetry.schema import (check_file,
                                         validate_events_line)

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
METRICS_CHECK = os.path.join(REPO, "tools", "metrics_check.py")


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_engine(rules, tmp_path=None, events=False):
    ev = str(tmp_path / "ev.jsonl") if events else None
    reg = registry_for(None, events_path=ev, force=True)
    clk = Clock()
    return reg, clk, AlertEngine(reg, rules, now=clk)


# ---------------------------------------------------------------------------
# rule kinds
# ---------------------------------------------------------------------------

def test_threshold_fire_and_heal_transitions(tmp_path):
    reg, clk, eng = make_engine(
        [{"name": "deep", "type": "threshold",
          "metric": "gauges.depth", "op": ">", "value": 3}],
        tmp_path, events=True)
    assert eng.evaluate() == []  # metric absent: quiet, no error
    reg.gauge("depth").set(10)
    assert eng.evaluate() == ["deep"]
    assert eng.evaluate() == ["deep"]  # still firing: ONE event only
    reg.gauge("depth").set(1)
    assert eng.evaluate() == []
    assert reg.counter("alerts_fired_total").value == 1
    states = [json.loads(line) for line in
              open(tmp_path / "ev.jsonl")]
    alert_ev = [e for e in states if e["event"] == "alert"]
    assert [e["state"] for e in alert_ev] == ["firing", "healed"]
    assert all(e["rule"] == "deep" for e in alert_ev)
    assert all(validate_events_line(e) == [] for e in alert_ev)
    # the labeled gauge tracked the transitions
    assert reg.gauge('alerts_firing{rule="deep"}').value == 0


def test_absence_rule_on_dead_heartbeat(tmp_path):
    reg, clk, eng = make_engine(
        [{"name": "stalled", "type": "absence", "for_s": 5.0}])
    # UNARMED: a registry that never heartbeats (the quorum driver's
    # manifest registry idles while its stages heartbeat their own)
    # must never false-page, however long it runs
    assert eng.evaluate() == []
    clk.advance(1000.0)
    assert eng.evaluate() == []
    eng.beat()  # first real activity arms the rule
    clk.advance(4.0)
    eng.beat()
    assert eng.evaluate() == []  # beat within the window
    clk.advance(5.5)  # silence past for_s
    assert eng.evaluate() == ["stalled"]
    eng.beat()  # the batch finally lands
    assert eng.evaluate() == []
    assert reg.gauge('alerts_firing{rule="stalled"}').value == 0


def test_absence_rule_on_unchanging_metric():
    reg, clk, eng = make_engine(
        [{"name": "quiet", "type": "absence",
          "metric": "counters.batches", "for_s": 3.0}])
    reg.counter("batches").inc()
    eng.beat()
    assert eng.evaluate() == []
    clk.advance(2.0)
    reg.counter("batches").inc()  # progress
    eng.beat()
    assert eng.evaluate() == []
    clk.advance(4.0)  # no progress, even though heartbeats continue
    eng.beat()
    assert eng.evaluate() == ["quiet"]
    reg.counter("batches").inc()
    assert eng.evaluate() == []


def test_rate_rule_over_window():
    reg, clk, eng = make_engine(
        [{"name": "failing", "type": "rate",
          "metric": "counters.fails", "window_s": 10.0,
          "op": ">", "value": 1.0}])
    reg.counter("fails")
    assert eng.evaluate() == []
    for _ in range(10):  # 0.5/s: under the threshold
        clk.advance(2.0)
        reg.counter("fails").inc(1)
        assert eng.evaluate() == []
    for _ in range(5):  # 5/s: over it
        clk.advance(1.0)
        reg.counter("fails").inc(5)
    assert eng.evaluate() == ["failing"]
    for _ in range(15):  # flat again: the window rolls over and heals
        clk.advance(1.0)
        eng.evaluate()
    assert eng.evaluate() == []


def test_burn_rate_multi_window_and_rollover():
    reg, clk, eng = make_engine(
        [{"name": "slo", "type": "burn_rate", "objective": 0.9,
          "bad": ["bad"], "total": ["good", "bad"],
          "windows": [[60.0, 1.0], [10.0, 1.0]]}])
    good, bad = reg.counter("good"), reg.counter("bad")
    good.inc(100)
    for _ in range(30):  # healthy traffic
        clk.advance(1.0)
        good.inc(10)
        assert eng.evaluate() == []
    for _ in range(12):  # 100% failures: both windows burn
        clk.advance(1.0)
        bad.inc(10)
    assert eng.evaluate() == ["slo"]
    status = eng.slo_status()["slo"]
    assert status["firing"] is True
    assert status["burn"]["10s"] >= 1.0
    assert status["burn"]["60s"] >= 1.0
    # recovery: the SHORT window heals first (rollover), which is
    # enough to stop firing under the all-windows rule
    for _ in range(12):
        clk.advance(1.0)
        good.inc(10)
        eng.evaluate()
    assert eng.evaluate() == []
    assert eng.slo_status()["slo"]["burn"]["10s"] < 1.0


def test_burn_rate_from_latency_histogram():
    reg, clk, eng = make_engine(
        [{"name": "lat", "type": "burn_rate", "objective": 0.5,
          "hist": "request_us", "above_us": 1000,
          "windows": [[10.0, 1.0]]}])
    h = reg.histogram("request_us")
    for _ in range(10):
        h.observe(100)
    clk.advance(1.0)
    assert eng.evaluate() == []
    for _ in range(20):  # every request blows the budget
        h.observe(50_000)
    clk.advance(1.0)
    assert eng.evaluate() == ["lat"]


def test_latency_bucket_quantization_bounds_cardinality():
    """The serve latency-SLO feed: quantized buckets must stay well
    under Histogram.MAX_KEYS across the full latency range (raw
    request_us overflows within a few hundred requests, blinding any
    rule that reads it), round DOWN, and stay monotonic."""
    from quorum_tpu.telemetry.registry import Histogram
    keys = set()
    prev = -1
    for us in range(0, 60_000_000, 997):  # 0..60s, awkward stride
        b = alerts.latency_bucket_us(us)
        assert b <= us or us <= 4
        assert b >= prev  # monotonic in the observed value
        prev = b
        keys.add(b)
    assert len(keys) < Histogram.MAX_KEYS // 2
    # floor error bounded by one quarter-octave (~25% worst case)
    for us in (1000, 5000, 123_456, 2_000_001, 59_999_999):
        b = alerts.latency_bucket_us(us)
        assert b <= us < b * 1.26


def test_no_traffic_is_not_a_burn():
    reg, clk, eng = make_engine(
        [{"name": "slo", "type": "burn_rate", "objective": 0.99,
          "bad": ["bad"], "total": ["good", "bad"],
          "windows": [[10.0, 1.0]]}])
    for _ in range(30):
        clk.advance(1.0)
        assert eng.evaluate() == []  # zero traffic: burn 0, not NaN


# ---------------------------------------------------------------------------
# robustness: bad rules must not take down the evaluation thread
# ---------------------------------------------------------------------------

def test_missing_metric_never_crashes_and_bad_address_counts_once(
        tmp_path):
    reg, clk, eng = make_engine(
        [{"name": "ok_rule", "type": "threshold",
          "metric": "counters.never_appears", "op": ">", "value": 0},
         {"name": "bad_addr", "type": "threshold",
          "metric": "nodots", "op": ">", "value": 0}],
        tmp_path, events=True)
    for _ in range(5):
        clk.advance(1.0)
        assert eng.evaluate() == []  # never raises
    # the malformed address errored ONCE; the absent metric is fine
    assert reg.counter("alert_rule_errors_total").value == 1
    errs = [json.loads(line) for line in open(tmp_path / "ev.jsonl")
            if json.loads(line)["event"] == "alert_rule_error"]
    assert len(errs) == 1 and errs[0]["rule"] == "bad_addr"


def test_malformed_rule_spec_counted_at_construction():
    reg = registry_for(None, force=True)
    eng = AlertEngine(reg, [
        {"name": "good", "type": "threshold",
         "metric": "gauges.x", "op": ">", "value": 1},
        {"name": "nope", "type": "wibble"},
        {"name": "noop", "type": "threshold"},  # missing metric/value
    ], now=Clock())
    assert len(eng.rules) == 1
    assert reg.counter("alert_rule_errors_total").value == 2
    assert reg.meta["alert_rules"] == ["good"]


def test_evaluate_from_heartbeat_thread_survives_everything(tmp_path):
    """The exporter hook runs inside registry.heartbeat() on pipeline
    threads: an evaluation raising there would kill the run. Drive it
    through the REAL hook with a hostile rule set."""
    ev = str(tmp_path / "ev.jsonl")
    reg = registry_for(None, events_path=ev, force=True)
    eng = AlertEngine(reg, [
        {"name": "bad", "type": "threshold", "metric": "x",
         "op": ">", "value": 0},
        {"name": "burn", "type": "burn_rate", "objective": 0.9,
         "bad": ["b"], "total": ["t"], "windows": [[5.0, 1.0]]},
    ])
    eng.attach(period_s=0.001)  # evaluate on ~every notification
    errors = []

    def beat_many():
        try:
            for _ in range(50):
                reg.heartbeat(reads=1)
        except Exception as e:  # noqa: BLE001 - the failure under test
            errors.append(e)

    threads = [threading.Thread(target=beat_many) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.close()
    assert errors == []


def test_ticker_fires_while_registry_is_silent(tmp_path):
    """The stalled-pipeline case end to end, real time: after ONE
    heartbeat (arming), the run goes silent — the ticker thread
    alone must fire the absence rule (the stalled loop will never
    notify the engine itself)."""
    import time as _time
    ev = str(tmp_path / "ev.jsonl")
    reg = registry_for(None, events_path=ev, force=True)
    eng = AlertEngine(reg, [{"name": "stalled", "type": "absence",
                             "for_s": 0.15}])
    eng.attach(period_s=0.05)
    reg.heartbeat(reads=1)  # batch 1 lands, then the pipeline wedges
    deadline = _time.monotonic() + 5.0
    try:
        while _time.monotonic() < deadline:
            if reg.gauge('alerts_firing{rule="stalled"}').value == 1:
                break
            _time.sleep(0.02)
        assert reg.gauge('alerts_firing{rule="stalled"}').value == 1
    finally:
        eng.close()
    # close() counts as life: the final state healed
    assert reg.gauge('alerts_firing{rule="stalled"}').value == 0


def test_closed_engine_is_inert(tmp_path):
    reg, clk, eng = make_engine(
        [{"name": "deep", "type": "threshold",
          "metric": "gauges.depth", "op": ">", "value": 0}],
        tmp_path, events=True)
    eng.close()
    reg.gauge("depth").set(5)
    assert eng.evaluate() == []  # no state change after close
    reg.heartbeat()  # exporter no-op
    assert reg.counter("alerts_fired_total").value == 0


# ---------------------------------------------------------------------------
# rule loading / merging
# ---------------------------------------------------------------------------

def test_load_and_merge_rules(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps({"rules": [
        {"name": "pipeline_stalled", "type": "absence", "for_s": 1.0},
        {"name": "push_failing", "disable": True},
        {"name": "mine", "type": "threshold", "metric": "gauges.g",
         "op": ">", "value": 1},
    ]}))
    merged = alerts.merge_rules(alerts.DEFAULT_RULES,
                                alerts.load_rules(str(p)))
    by_name = {r["name"]: r for r in merged}
    assert by_name["pipeline_stalled"]["for_s"] == 1.0  # overridden
    assert "push_failing" not in by_name                # disabled
    assert "mine" in by_name                            # added
    assert "integrity_errors" in by_name                # default kept


def test_load_rules_rejects_malformed(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{\"rules\": 3}")
    with pytest.raises(ValueError):
        alerts.load_rules(str(p))
    p.write_text(json.dumps([{"type": "absence"}]))  # no name
    with pytest.raises(ValueError):
        alerts.load_rules(str(p))


def test_observability_survives_bad_rules_file(tmp_path):
    """A typo'd --alert-rules file must cost a warning + a counted
    rule error, never the run: the built-in defaults keep watching."""
    from quorum_tpu.cli.observability import observability
    bad = tmp_path / "bad.json"
    bad.write_text("not json at all")
    mp = tmp_path / "m.json"
    with observability(str(mp), alert_rules=str(bad),
                       stage="test") as obs:
        assert obs.alerts is not None
        assert len(obs.alerts.rules) == (len(alerts.DEFAULT_RULES)
                                         + len(alerts.DEFAULT_QUALITY_RULES))
    doc = json.load(open(mp))
    assert doc["counters"]["alert_rule_errors_total"] >= 1
    assert doc["meta"]["alert_rules"]  # defaults active
    assert "alert_rules_file" not in doc["meta"]


# ---------------------------------------------------------------------------
# serve /healthz detail
# ---------------------------------------------------------------------------

def test_serve_health_carries_alert_detail_without_liveness():
    from quorum_tpu.serve.server import CorrectionServer

    class FakeEngine:
        compiles = 0

    class FakeBatcher:
        healthy = True
        depth = 0
        consecutive_failures = 0
        generation = 0
        engine = FakeEngine()

        def drain(self, timeout=None):
            return True

    reg = registry_for(None, force=True)
    clk = Clock()
    eng = AlertEngine(reg, [
        {"name": "serve_slo_availability", "type": "burn_rate",
         "objective": 0.9, "bad": ["requests_failed"],
         "total": ["requests_completed", "requests_failed"],
         "windows": [[10.0, 1.0]]}], now=clk)
    srv = CorrectionServer(FakeBatcher(), port=0, registry=reg,
                           alerts=eng)
    try:
        reg.counter("requests_completed").inc(1)
        clk.advance(1.0)
        eng.evaluate()
        h = srv.health()
        assert h["status"] == "ok" and h["healthy"]
        assert h["alerts"]["firing"] == []
        assert h["slo"]["serve_slo_availability"]["firing"] is False
        # burn the budget: every request fails
        for _ in range(5):
            clk.advance(1.0)
            reg.counter("requests_failed").inc(10)
            eng.evaluate()
        h = srv.health()
        assert h["slo"]["serve_slo_availability"]["firing"] is True
        assert "serve_slo_availability" in h["alerts"]["firing"]
        # the whole point: liveness is untouched
        assert h["status"] == "ok" and h["healthy"]
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# metrics_check / schema surface (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def _doc(**over):
    doc = {"schema": "quorum-tpu-metrics/1",
           "meta": {"alert_rules": ["a", "b"]},
           "counters": {"alerts_fired_total": 1,
                        "alert_rule_errors_total": 0},
           "gauges": {"alert_rules_active": 2,
                      'alerts_firing{rule="a"}': 1,
                      'alerts_firing{rule="b"}': 0},
           "histograms": {}, "timers": {}}
    doc.update(over)
    return doc


def test_metrics_check_requires_alert_surface(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_check
    finally:
        sys.path.pop(0)
    assert metrics_check._check_alert_names(_doc()) == []
    # no alert_rules declared -> nothing required
    assert metrics_check._check_alert_names(
        _doc(meta={})) == []
    # declared but counters dropped -> loud
    bad = _doc(counters={})
    errs = metrics_check._check_alert_names(bad)
    assert any("alerts_fired_total" in e for e in errs)
    # firing gauge out of range / naming an undeclared rule
    errs = metrics_check._check_alert_names(
        _doc(gauges={"alert_rules_active": 2,
                     'alerts_firing{rule="a"}': 7}))
    assert any("0 or 1" in e for e in errs)
    errs = metrics_check._check_alert_names(
        _doc(gauges={"alert_rules_active": 2,
                     'alerts_firing{rule="zz"}': 0}))
    assert any("not in meta.alert_rules" in e for e in errs)


def test_metrics_check_autotune_meta(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_check
    finally:
        sys.path.pop(0)
    ok = _doc(meta={"autotune_profile": "/x/cpu.json"})
    assert metrics_check._check_autotune_meta(ok) == []
    assert metrics_check._check_autotune_meta(_doc(meta={})) == []
    bad = _doc(meta={"autotune_profile": ""})
    assert metrics_check._check_autotune_meta(bad) != []


def test_alert_event_schema():
    good = {"event": "alert", "t": 1.0, "rule": "r",
            "state": "firing", "value": 1.5, "detail": "x"}
    assert validate_events_line(good) == []
    assert validate_events_line(
        {**good, "state": "wat"}) != []
    assert validate_events_line(
        {"event": "alert", "t": 1.0, "state": "firing"}) != []


def test_metrics_check_cli_accepts_alerting_run_artifacts(tmp_path):
    """End to end through the tool: a document + events stream from a
    real engine run validate clean."""
    ev = str(tmp_path / "run.events.jsonl")
    mp = str(tmp_path / "run.json")
    reg = registry_for(mp, events_path=ev, force=True)
    clk = Clock()
    eng = AlertEngine(reg, [{"name": "g", "type": "threshold",
                             "metric": "gauges.v", "op": ">",
                             "value": 1}], now=clk)
    reg.gauge("v").set(5)
    eng.evaluate()
    reg.gauge("v").set(0)
    eng.evaluate()
    eng.close()
    reg.set_meta(status="ok")
    reg.write()
    res = subprocess.run(
        [sys.executable, METRICS_CHECK, mp, ev],
        capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert check_file(mp) == [] and check_file(ev) == []
