"""Reference on-disk format codecs (round 4, VERDICT missing #1/#2):
binary/quorum_db round-trip through the offsets-packed layout
(io/quorum_db) and Jellyfish binary_dumper record files (io/jf_binary),
wired into read_db, the inspection CLIs, and --contaminant."""

import subprocess
import sys

import numpy as np
import pytest

from quorum_tpu.io import db_format, jf_binary, quorum_db
from quorum_tpu.ops import ctable, mer


def _rand_entries(rng, n, k):
    """n distinct canonical keys with nonzero value words."""
    seen = {}
    while len(seen) < n:
        codes = rng.integers(0, 4, size=k)
        hi, lo = mer.pack_kmer("".join("ACGT"[c] for c in codes), k)
        chi, clo = mer.canonical_py(hi, lo, k)
        seen[(chi, clo)] = rng.integers(1, 1 << 8)
    keys = list(seen)
    khi = np.array([h for h, _ in keys], np.uint32)
    klo = np.array([lo_ for _, lo_ in keys], np.uint32)
    vals = np.array([seen[kk] for kk in keys], np.uint32)
    return khi, klo, vals


@pytest.mark.parametrize("k,n", [(24, 500), (15, 64), (31, 200)])
def test_quorum_db_roundtrip(tmp_path, k, n):
    rng = np.random.default_rng(k * 1000 + n)
    khi, klo, vals = _rand_entries(rng, n, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7)
    rhi, rlo, rvals, rk, rbits = quorum_db.read_ref_db(path)
    assert (rk, rbits) == (k, 7)
    got = {(int(h), int(lo)): int(v) for h, lo, v in zip(rhi, rlo, rvals)}
    want = {(int(h), int(lo)): int(v) & 0xFF
            for h, lo, v in zip(khi, klo, vals)}
    assert got == want


def test_quorum_db_collision_pressure(tmp_path):
    """A small table under heavy load exercises deep reprobe chains
    and the grow-on-placement-failure path."""
    k = 24
    rng = np.random.default_rng(7)
    khi, klo, vals = _rand_entries(rng, 3000, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7, min_fill=0.99)
    rhi, rlo, rvals, _, _ = quorum_db.read_ref_db(path)
    assert len(rhi) == 3000
    got = {(int(h), int(lo)): int(v) for h, lo, v in zip(rhi, rlo, rvals)}
    want = {(int(h), int(lo)): int(v) & 0xFF
            for h, lo, v in zip(khi, klo, vals)}
    assert got == want


def test_quorum_db_header_contract(tmp_path):
    """Header carries every field database_query consumes
    (mer_database.hpp:270-278) and the byte counts match the payload."""
    import os

    from quorum_tpu.io.ref_db import parse_jf_header

    k = 24
    rng = np.random.default_rng(1)
    khi, klo, vals = _rand_entries(rng, 100, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7,
                           cmdline=["quorum_create_database", "x"])
    with open(path, "rb") as f:
        data = f.read()
    header, off = parse_jf_header(data)
    for field in ("format", "size", "key_len", "val_len", "max_reprobe",
                  "matrix", "bits", "key_bytes", "value_bytes"):
        assert field in header, field
    assert header["format"] == "binary/quorum_db"
    assert header["key_len"] == 2 * k
    assert os.path.getsize(path) == (off + header["key_bytes"]
                                     + header["value_bytes"])


def test_read_db_accepts_ref_format(tmp_path):
    """read_db transparently decodes reference-format files into the
    tile layout; lookups agree."""
    k = 24
    rng = np.random.default_rng(3)
    khi, klo, vals = _rand_entries(rng, 300, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7)
    state, meta, header = db_format.read_db(path, to_device=False)
    assert isinstance(meta, ctable.TileMeta)
    for h, lo, v in zip(khi[:50], klo[:50], vals[:50]):
        assert db_format.db_lookup_np(state, meta, int(h), int(lo)) \
            == int(v) & 0xFF


def test_tools_read_ref_format(tmp_path):
    """query_mer_database and histo_mer_database accept reference
    files and agree with the native-format outputs."""
    k = 24
    rng = np.random.default_rng(5)
    khi, klo, vals = _rand_entries(rng, 200, k)
    ref = str(tmp_path / "ref.jf")
    quorum_db.write_ref_db(ref, khi, klo, vals, k, bits=7)
    mers = [mer.unpack_kmer(int(h), int(lo), k)
            for h, lo in zip(khi[:5], klo[:5])]
    out = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.query_mer_database",
         ref, *mers], capture_output=True, text=True, check=True).stdout
    for m, h, lo, v in zip(mers, khi, klo, vals):
        assert f"val:{int(v) >> 1} qual:{int(v) & 1}" in out
        assert m in out
    histo = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.histo_mer_database", ref],
        capture_output=True, text=True, check=True).stdout
    assert histo.strip(), "histo produced nothing"


def test_jf_binary_roundtrip(tmp_path):
    k = 24
    rng = np.random.default_rng(11)
    khi, klo, vals = _rand_entries(rng, 150, k)
    path = str(tmp_path / "adapter.jf")
    jf_binary.write_jf_binary(path, khi, klo, vals, k)
    assert jf_binary.is_jf_binary(path)
    rhi, rlo, counts, rk = jf_binary.read_jf_binary(path)
    assert rk == k
    assert set(zip(rhi.tolist(), rlo.tolist())) \
        == set(zip(khi.tolist(), klo.tolist()))


def test_contaminant_accepts_jf_binary(tmp_path):
    """--contaminant with a binary_dumper adapter DB: member k-mers
    hit, others miss, and a k mismatch dies with the reference
    message."""
    from quorum_tpu.io.contaminant import load_contaminant

    k = 9
    rng = np.random.default_rng(13)
    khi, klo, vals = _rand_entries(rng, 40, k)
    path = str(tmp_path / "adapter.jf")
    jf_binary.write_jf_binary(path, khi, klo, vals, k)
    state, meta = load_contaminant(path, k)
    for h, lo in zip(khi[:10], klo[:10]):
        assert db_format.db_lookup_np(state, meta, int(h), int(lo)) != 0
    miss_hi, miss_lo, _ = _rand_entries(rng, 5, k)
    member = set(zip(khi.tolist(), klo.tolist()))
    for h, lo in zip(miss_hi, miss_lo):
        if (int(h), int(lo)) in member:
            continue
        assert db_format.db_lookup_np(state, meta, int(h), int(lo)) == 0
    with pytest.raises(ValueError, match="Contaminant mer length"):
        load_contaminant(path, k + 1)


def test_create_database_ref_format(tmp_path):
    """--ref-format end to end: build a DB from FASTQ, write the
    reference format, read it back and compare with the native file."""
    rng = np.random.default_rng(17)
    fq = tmp_path / "reads.fastq"
    with open(fq, "w") as f:
        for i in range(60):
            seq = "".join("ACGT"[c] for c in rng.integers(0, 4, size=60))
            f.write(f"@r{i}\n{seq}\n+\n{'F' * 60}\n")
    from quorum_tpu.cli import create_database as cdb

    nat = str(tmp_path / "nat.qdb")
    ref = str(tmp_path / "ref.jf")
    args = ["-s", "100k", "-m", "15", "-b", "7", "-q", "38"]
    assert cdb.main([*args, "-o", nat, str(fq)]) == 0
    assert cdb.main([*args, "-o", ref, "--ref-format", str(fq)]) == 0
    ns, nm, _ = db_format.read_db(nat, to_device=False)
    nkhi, nklo, nvals = db_format.db_iterate(ns, nm)
    rhi, rlo, rvals, rk, rbits = quorum_db.read_ref_db(ref)
    assert (rk, rbits) == (15, 7)
    nat_d = {(int(h), int(lo)): int(v)
             for h, lo, v in zip(nkhi, nklo, nvals)}
    ref_d = {(int(h), int(lo)): int(v)
             for h, lo, v in zip(rhi, rlo, rvals)}
    assert nat_d == ref_d
