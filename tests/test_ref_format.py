"""Reference on-disk format codecs (round 4, VERDICT missing #1/#2):
binary/quorum_db round-trip through the offsets-packed layout
(io/quorum_db) and Jellyfish binary_dumper record files (io/jf_binary),
wired into read_db, the inspection CLIs, and --contaminant."""

import subprocess
import sys

import numpy as np
import pytest

from quorum_tpu.io import db_format, jf_binary, quorum_db
from quorum_tpu.ops import ctable, mer


def _rand_entries(rng, n, k):
    """n distinct canonical keys with nonzero value words."""
    seen = {}
    while len(seen) < n:
        codes = rng.integers(0, 4, size=k)
        hi, lo = mer.pack_kmer("".join("ACGT"[c] for c in codes), k)
        chi, clo = mer.canonical_py(hi, lo, k)
        seen[(chi, clo)] = rng.integers(1, 1 << 8)
    keys = list(seen)
    khi = np.array([h for h, _ in keys], np.uint32)
    klo = np.array([lo_ for _, lo_ in keys], np.uint32)
    vals = np.array([seen[kk] for kk in keys], np.uint32)
    return khi, klo, vals


@pytest.mark.parametrize("k,n", [(24, 500), (15, 64), (31, 200)])
def test_quorum_db_roundtrip(tmp_path, k, n):
    rng = np.random.default_rng(k * 1000 + n)
    khi, klo, vals = _rand_entries(rng, n, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7)
    rhi, rlo, rvals, rk, rbits = quorum_db.read_ref_db(path)
    assert (rk, rbits) == (k, 7)
    got = {(int(h), int(lo)): int(v) for h, lo, v in zip(rhi, rlo, rvals)}
    want = {(int(h), int(lo)): int(v) & 0xFF
            for h, lo, v in zip(khi, klo, vals)}
    assert got == want


def test_quorum_db_collision_pressure(tmp_path):
    """A small table under heavy load exercises deep reprobe chains
    and the grow-on-placement-failure path."""
    k = 24
    rng = np.random.default_rng(7)
    khi, klo, vals = _rand_entries(rng, 3000, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7, min_fill=0.99)
    rhi, rlo, rvals, _, _ = quorum_db.read_ref_db(path)
    assert len(rhi) == 3000
    got = {(int(h), int(lo)): int(v) for h, lo, v in zip(rhi, rlo, rvals)}
    want = {(int(h), int(lo)): int(v) & 0xFF
            for h, lo, v in zip(khi, klo, vals)}
    assert got == want


def test_quorum_db_header_contract(tmp_path):
    """Header carries every field database_query consumes
    (mer_database.hpp:270-278) and the byte counts match the payload."""
    import os

    from quorum_tpu.io.ref_db import parse_jf_header

    k = 24
    rng = np.random.default_rng(1)
    khi, klo, vals = _rand_entries(rng, 100, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7,
                           cmdline=["quorum_create_database", "x"])
    with open(path, "rb") as f:
        data = f.read()
    header, off = parse_jf_header(data)
    for field in ("format", "size", "key_len", "val_len", "max_reprobe",
                  "matrix", "bits", "key_bytes", "value_bytes"):
        assert field in header, field
    assert header["format"] == "binary/quorum_db"
    assert header["key_len"] == 2 * k
    assert os.path.getsize(path) == (off + header["key_bytes"]
                                     + header["value_bytes"])


def test_read_db_accepts_ref_format(tmp_path):
    """read_db transparently decodes reference-format files into the
    tile layout; lookups agree."""
    k = 24
    rng = np.random.default_rng(3)
    khi, klo, vals = _rand_entries(rng, 300, k)
    path = str(tmp_path / "db.jf")
    quorum_db.write_ref_db(path, khi, klo, vals, k, bits=7)
    state, meta, header = db_format.read_db(path, to_device=False)
    assert isinstance(meta, ctable.TileMeta)
    for h, lo, v in zip(khi[:50], klo[:50], vals[:50]):
        assert db_format.db_lookup_np(state, meta, int(h), int(lo)) \
            == int(v) & 0xFF


def test_tools_read_ref_format(tmp_path):
    """query_mer_database and histo_mer_database accept reference
    files and agree with the native-format outputs."""
    k = 24
    rng = np.random.default_rng(5)
    khi, klo, vals = _rand_entries(rng, 200, k)
    ref = str(tmp_path / "ref.jf")
    quorum_db.write_ref_db(ref, khi, klo, vals, k, bits=7)
    mers = [mer.unpack_kmer(int(h), int(lo), k)
            for h, lo in zip(khi[:5], klo[:5])]
    out = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.query_mer_database",
         ref, *mers], capture_output=True, text=True, check=True).stdout
    for m, h, lo, v in zip(mers, khi, klo, vals):
        assert f"val:{int(v) >> 1} qual:{int(v) & 1}" in out
        assert m in out
    histo = subprocess.run(
        [sys.executable, "-m", "quorum_tpu.cli.histo_mer_database", ref],
        capture_output=True, text=True, check=True).stdout
    assert histo.strip(), "histo produced nothing"


def test_jf_binary_roundtrip(tmp_path):
    k = 24
    rng = np.random.default_rng(11)
    khi, klo, vals = _rand_entries(rng, 150, k)
    path = str(tmp_path / "adapter.jf")
    jf_binary.write_jf_binary(path, khi, klo, vals, k)
    assert jf_binary.is_jf_binary(path)
    rhi, rlo, counts, rk = jf_binary.read_jf_binary(path)
    assert rk == k
    assert set(zip(rhi.tolist(), rlo.tolist())) \
        == set(zip(khi.tolist(), klo.tolist()))


def test_contaminant_accepts_jf_binary(tmp_path):
    """--contaminant with a binary_dumper adapter DB: member k-mers
    hit, others miss, and a k mismatch dies with the reference
    message."""
    from quorum_tpu.io.contaminant import load_contaminant

    k = 9
    rng = np.random.default_rng(13)
    khi, klo, vals = _rand_entries(rng, 40, k)
    path = str(tmp_path / "adapter.jf")
    jf_binary.write_jf_binary(path, khi, klo, vals, k)
    state, meta = load_contaminant(path, k)
    for h, lo in zip(khi[:10], klo[:10]):
        assert db_format.db_lookup_np(state, meta, int(h), int(lo)) != 0
    miss_hi, miss_lo, _ = _rand_entries(rng, 5, k)
    member = set(zip(khi.tolist(), klo.tolist()))
    for h, lo in zip(miss_hi, miss_lo):
        if (int(h), int(lo)) in member:
            continue
        assert db_format.db_lookup_np(state, meta, int(h), int(lo)) == 0
    with pytest.raises(ValueError, match="Contaminant mer length"):
        load_contaminant(path, k + 1)


def test_create_database_ref_format(tmp_path):
    """--ref-format end to end: build a DB from FASTQ, write the
    reference format, read it back and compare with the native file."""
    rng = np.random.default_rng(17)
    fq = tmp_path / "reads.fastq"
    with open(fq, "w") as f:
        for i in range(60):
            seq = "".join("ACGT"[c] for c in rng.integers(0, 4, size=60))
            f.write(f"@r{i}\n{seq}\n+\n{'F' * 60}\n")
    from quorum_tpu.cli import create_database as cdb

    nat = str(tmp_path / "nat.qdb")
    ref = str(tmp_path / "ref.jf")
    args = ["-s", "100k", "-m", "15", "-b", "7", "-q", "38"]
    assert cdb.main([*args, "-o", nat, str(fq)]) == 0
    assert cdb.main([*args, "-o", ref, "--ref-format", str(fq)]) == 0
    ns, nm, _ = db_format.read_db(nat, to_device=False)
    nkhi, nklo, nvals = db_format.db_iterate(ns, nm)
    rhi, rlo, rvals, rk, rbits = quorum_db.read_ref_db(ref)
    assert (rk, rbits) == (15, 7)
    nat_d = {(int(h), int(lo)): int(v)
             for h, lo, v in zip(nkhi, nklo, nvals)}
    ref_d = {(int(h), int(lo)): int(v)
             for h, lo, v in zip(rhi, rlo, rvals)}
    assert nat_d == ref_d


def test_jf_binary_rejects_bad_counter_len(tmp_path):
    """ADVICE r4: counter_len outside 1..8 must be a clean parse error,
    not undefined uint64 shifts / degenerate record sizes."""
    k = 9
    rng = np.random.default_rng(17)
    khi, klo, vals = _rand_entries(rng, 10, k)
    path = str(tmp_path / "bad.jf")
    jf_binary.write_jf_binary(path, khi, klo, vals, k)
    raw = open(path, "rb").read()
    for bad in (0, 9, -1):
        mangled = raw.replace(b'"counter_len": 4', f'"counter_len": {bad}'
                              .encode(), 1)
        assert mangled != raw
        p = str(tmp_path / f"bad{bad}.jf")
        open(p, "wb").write(mangled)
        with pytest.raises(ValueError, match="counter_len"):
            jf_binary.read_jf_binary(p)


def test_v3_db_rejects_corrupt_addr(tmp_path):
    """ADVICE r4: out-of-range v3 bucket addresses must raise, not be
    silently clamped into a wrong table by the device scatter."""
    import json as _json
    import quorum_tpu.ops.ctable as _ct

    k = 9
    rng = np.random.default_rng(19)
    khi, klo, vals = _rand_entries(rng, 30, k)
    state, meta = _ct.tile_from_entries(khi, klo, vals, k, 7)
    # hand-write the v3 layout (write_db emits v4 since round 5)
    a4, l4, h4, _ = (np.asarray(x) for x in _ct.tile_compact_device(
        state, meta, 64))
    n = int(_ct.tile_stats(state, meta)[0])
    hdr = {"format": db_format.FORMAT, "version": 3,
           "key_len": 2 * k, "bits": 7, "rb_log2": meta.rb_log2,
           "rows": meta.rows, "n_entries": n}
    path = str(tmp_path / "db.qdb")
    with open(path, "wb") as f:
        f.write((_json.dumps(hdr) + "\n").encode())
        f.write(a4[:n].astype(np.int32).tobytes())
        f.write(l4[:n].tobytes())
        f.write(h4[:n].tobytes())

    raw = open(path, "rb").read()
    nl = raw.index(b"\n") + 1
    addr = np.frombuffer(raw[nl:nl + 4 * n], np.int32).copy()

    def rewrite(new_addr, name):
        p = str(tmp_path / name)
        open(p, "wb").write(raw[:nl] + new_addr.tobytes()
                            + raw[nl + 4 * n:])
        return p

    bad = addr.copy()
    bad[0] = meta.rows + 3
    with pytest.raises(ValueError, match="bucket address"):
        db_format.read_db(rewrite(bad, "hi.qdb"), to_device=True)
    bad = addr.copy()
    bad[0] = -2
    with pytest.raises(ValueError, match="bucket address"):
        db_format.read_db(rewrite(bad, "neg.qdb"), to_device=False)
    # >64 entries claiming one bucket: rewrite the file with 65 copies
    # of entry 0 (all sharing one bucket address)
    lo = np.frombuffer(raw[nl + 4 * n:nl + 8 * n], np.uint32)
    hi = np.frombuffer(raw[nl + 8 * n:nl + 12 * n], np.uint32)
    hdr2 = dict(hdr, n_entries=65)
    p = str(tmp_path / "crowd.qdb")
    open(p, "wb").write(
        (_json.dumps(hdr2) + "\n").encode()
        + np.tile(addr[:1], 65).tobytes()
        + np.tile(lo[:1], 65).tobytes() + np.tile(hi[:1], 65).tobytes())
    with pytest.raises(ValueError, match="entries"):
        db_format.read_db(p, to_device=False)


def test_v3_still_readable(tmp_path):
    """v3 files (round 4) written by hand must load identically to the
    v4 the same entries produce."""
    import json as _json
    import quorum_tpu.ops.ctable as _ct

    k = 9
    rng = np.random.default_rng(23)
    khi, klo, vals = _rand_entries(rng, 50, k)
    state, meta = _ct.tile_from_entries(khi, klo, vals, k, 7)
    p4 = str(tmp_path / "v4.qdb")
    db_format.write_db(p4, state, meta, db_version=4)
    s4, m4, h4 = db_format.read_db(p4, to_device=False)
    assert h4["version"] == 4

    # hand-write the same entries as v3
    addr, lo, hi, _ = (np.asarray(x) for x in _ct.tile_compact_device(
        state, meta, 64))
    n = int(_ct.tile_stats(state, meta)[0])
    hdr = {"format": db_format.FORMAT, "version": 3,
           "key_len": 2 * k, "bits": 7, "rb_log2": meta.rb_log2,
           "rows": meta.rows, "n_entries": n}
    p3 = str(tmp_path / "v3.qdb")
    with open(p3, "wb") as f:
        f.write((_json.dumps(hdr) + "\n").encode())
        f.write(addr[:n].astype(np.int32).tobytes())
        f.write(lo[:n].tobytes())
        f.write(hi[:n].tobytes())
    s3, m3, _h3 = db_format.read_db(p3, to_device=False)
    # compare CONTENT, not slot layout: slot order within a bucket is
    # free (lookups compare all 64 slots), and round 7's v4 export
    # canonicalizes it while a hand-written v3 keeps device slot order
    def ents(s, m):
        return sorted(zip(*(a.tolist() for a in _ct.tile_iterate(s, m))))

    assert ents(s3, m3) == ents(s4, m4)
    assert len(ents(s3, m3)) == n


def test_v4_rejects_corrupt_counts(tmp_path):
    import json as _json
    import quorum_tpu.ops.ctable as _ct

    k = 9
    rng = np.random.default_rng(29)
    khi, klo, vals = _rand_entries(rng, 30, k)
    state, meta = _ct.tile_from_entries(khi, klo, vals, k, 7)
    p = str(tmp_path / "v4.qdb")
    # v4 explicitly: this test pins the STRUCTURAL check (v5 digests
    # would catch the same mutation earlier; tests/test_integrity.py
    # covers that path)
    db_format.write_db(p, state, meta, db_version=4)
    raw = open(p, "rb").read()
    nl = raw.index(b"\n") + 1
    hdr = _json.loads(raw[:nl])
    rows_n = hdr["rows"]
    counts = bytearray(raw[nl:nl + rows_n])
    # inflate one row count: sum mismatch must raise
    i = next(i for i, c in enumerate(counts) if c > 0)
    counts[i] += 1
    open(str(tmp_path / "bad.qdb"), "wb").write(
        raw[:nl] + bytes(counts) + raw[nl + rows_n:])
    with pytest.raises(ValueError, match="row counts sum"):
        db_format.read_db(str(tmp_path / "bad.qdb"), to_device=False)
    # >capacity count
    counts[i] = 80
    open(str(tmp_path / "bad2.qdb"), "wb").write(
        raw[:nl] + bytes(counts) + raw[nl + rows_n:])
    with pytest.raises(ValueError, match="entries"):
        db_format.read_db(str(tmp_path / "bad2.qdb"), to_device=False)
