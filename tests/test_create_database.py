"""End-to-end tests for stage 1 (create_database): synthetic FASTQ ->
DB file -> reload -> exact (count, quality) parity with a host
brute-force replay of the reference counting rule
(create_database.cc:64-91)."""

import numpy as np
import pytest

from quorum_tpu.io import fastq, db_format
from quorum_tpu.ops import mer
from quorum_tpu.cli import create_database as cdb_cli


def brute_counts(reads, k, qual_thresh, bits):
    """Replay quality_mer_counter::start per read, sequentially."""
    max_val = (1 << bits) - 1
    db = {}
    for seq, qual in reads:
        m = 0
        low_len = 0
        high_len = 0
        for i, ch in enumerate(seq):
            code = {"A": 0, "C": 1, "G": 2, "T": 3}.get(ch.upper(), -1)
            if code < 0:
                high_len = low_len = 0
                continue
            m = ((m << 2) | code) & ((1 << (2 * k)) - 1)
            low_len += 1
            if ord(qual[i]) >= qual_thresh:
                high_len += 1
            else:
                high_len = 0
            if low_len >= k:
                hi, lo = (m >> 32) & 0xFFFFFFFF, m & 0xFFFFFFFF
                chi, clo = mer.canonical_py(hi, lo, k)
                key = (int(chi) << 32) | int(clo)
                q = 1 if high_len >= k else 0
                cnt, cq = db.get(key, (0, 0))
                if cq < q:
                    db[key] = (1, 1)
                elif cnt == max_val or cq > q:
                    pass
                else:
                    db[key] = (cnt + 1, cq)
    return db


def write_fastq(path, reads, headers=None):
    with open(path, "w") as f:
        for i, (seq, qual) in enumerate(reads):
            h = headers[i] if headers else f"read{i}"
            f.write(f"@{h}\n{seq}\n+\n{qual}\n")


@pytest.fixture
def synthetic_reads():
    rng = np.random.default_rng(11)
    genome = "".join(rng.choice(list("ACGT"), size=3000))
    reads = []
    for _ in range(300):
        p = int(rng.integers(0, len(genome) - 80))
        seq = list(genome[p : p + 80])
        # sprinkle errors and Ns
        if rng.random() < 0.3:
            seq[int(rng.integers(0, 80))] = "N"
        qual = [chr(int(rng.integers(33, 74))) for _ in range(80)]
        reads.append(("".join(seq), "".join(qual)))
    return reads


def test_fastq_reader_roundtrip(tmp_path, synthetic_reads):
    path = str(tmp_path / "r.fastq")
    write_fastq(path, synthetic_reads)
    got = list(fastq.iter_records([path]))
    assert len(got) == len(synthetic_reads)
    for (h, s, q), (seq, qual) in zip(got, synthetic_reads):
        assert s.decode() == seq and q.decode() == qual

    batches = list(fastq.read_batches([path], batch_size=128))
    assert sum(b.n for b in batches) == len(synthetic_reads)
    b0 = batches[0]
    assert b0.codes.shape[1] == 128  # bucket for len 80
    back = mer.codes_to_seq(np.where(b0.codes[0, :80] < 0, 0, b0.codes[0, :80]))
    expect = synthetic_reads[0][0].replace("N", "A")
    assert back == expect


@pytest.mark.parametrize("k", [15, 24])
def test_cdb_cli_end_to_end(tmp_path, synthetic_reads, k):
    path = str(tmp_path / "r.fastq")
    out = str(tmp_path / "db.qdb")
    write_fastq(path, synthetic_reads)
    qual_thresh = 38
    rc = cdb_cli.main([
        "-s", "16k", "-m", str(k), "-b", "7", "-q", str(qual_thresh),
        "-o", out, "--batch-size", "64", path,
    ])
    assert rc == 0

    state, meta, header = db_format.read_db(out, to_device=False)
    assert header["key_len"] == 2 * k
    assert header["version"] == 5  # checksummed entry-compact default
    expect = brute_counts(synthetic_reads, k, qual_thresh, bits=7)
    # every brute-force key present with exact value
    for key, (cnt, q) in expect.items():
        v = db_format.db_lookup_np(state, meta,
                                   (key >> 32) & 0xFFFFFFFF,
                                   key & 0xFFFFFFFF)
        assert (v >> 1, v & 1) == (cnt, q), f"key {key:x}"
    # and no extra keys
    _, _, vals = db_format.db_iterate(state, meta)
    assert len(vals) == len(expect)


def test_cdb_growth_from_tiny(tmp_path, synthetic_reads):
    """Start with a comically small size: the pipeline must auto-grow
    (reference behavior: cooperative doubling) and still be exact."""
    path = str(tmp_path / "r.fastq")
    out = str(tmp_path / "db.qdb")
    write_fastq(path, synthetic_reads)
    rc = cdb_cli.main([
        "-s", "16", "-m", "17", "-b", "3", "-q", "38", "-o", out, path,
    ])
    assert rc == 0
    state, meta, _ = db_format.read_db(out, to_device=False)
    expect = brute_counts(synthetic_reads, 17, 38, bits=3)
    _, _, _vals = db_format.db_iterate(state, meta)
    assert len(_vals) == len(expect)
    items = list(expect.items())
    for key, (cnt, q) in items[:200]:
        v = db_format.db_lookup_np(state, meta,
                                   (key >> 32) & 0xFFFFFFFF,
                                   key & 0xFFFFFFFF)
        assert (v >> 1, v & 1) == (cnt, q)
