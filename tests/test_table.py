"""Tests for the HBM hash table (quorum_tpu.ops.table).

Mirrors the reference's only real unit test
(unit_tests/test_mer_database.cc TEST_P(MerDatabase, WriteRead)): random
sequences inserted under different quality patterns, then exact (count,
quality) asserted per k-mer, parameterized over undersized tables to
force the growth path."""

import numpy as np
import jax.numpy as jnp
import pytest

from quorum_tpu.ops import mer, table


def brute_force_counts(obs, bits):
    """obs: list of (key_int, qual). Returns {key: (count, qual)} by
    replaying the reference add() rule sequentially."""
    max_val = (1 << bits) - 1
    d = {}
    for key, q in obs:
        cnt, cq = d.get(key, (0, 0))
        if cq < q:
            d[key] = (1, 1)
        elif cnt == max_val or cq > q:
            pass
        else:
            d[key] = (cnt + 1, cq)
    return d


def make_obs(rng, n_keys, n_obs, k):
    keys = rng.integers(0, 1 << (2 * k), size=n_keys, dtype=np.uint64)
    idx = rng.integers(0, n_keys, size=n_obs)
    quals = rng.integers(0, 2, size=n_obs)
    return keys[idx], quals


@pytest.mark.parametrize("bits", [3, 7])
@pytest.mark.parametrize("size_log2", [6, 10])
def test_merge_matches_sequential_reference_rule(bits, size_log2):
    k = 24
    rng = np.random.default_rng(size_log2 * 100 + bits)
    keys, quals = make_obs(rng, n_keys=40, n_obs=600, k=k)
    meta = table.TableMeta(k=k, bits=bits, size_log2=size_log2)
    state = table.make_table(meta)

    # Insert in several batches with interleaved quality order — the rule
    # is order independent (pinned by the reference unit test).
    for start in range(0, len(keys), 97):
        kk = keys[start : start + 97]
        qq = quals[start : start + 97]
        khi = jnp.asarray((kk >> np.uint64(32)).astype(np.uint32))
        klo = jnp.asarray((kk & np.uint64(0xFFFFFFFF)).astype(np.uint32))
        state, full = table.add_kmer_batch(
            state, meta, khi, klo, jnp.asarray(qq.astype(np.int32)),
            jnp.ones(len(kk), dtype=bool),
        )
        assert not bool(full)

    expect = brute_force_counts(
        [(int(kx), int(q)) for kx, q in zip(keys, quals)], bits
    )
    ukeys = sorted(set(int(kx) for kx in keys))
    khi = jnp.asarray(np.array([kx >> 32 for kx in ukeys], dtype=np.uint32))
    klo = jnp.asarray(np.array([kx & 0xFFFFFFFF for kx in ukeys], dtype=np.uint32))
    vals = np.asarray(table.lookup(state, meta, khi, klo))
    for kx, v in zip(ukeys, vals):
        cnt, q = int(v) >> 1, int(v) & 1
        assert (cnt, q) == expect[kx], hex(kx)

    # absent keys return 0
    absent = jnp.asarray(np.array([1, 2, 3], dtype=np.uint32))
    absent_hi = jnp.asarray(np.array([0x3FFF0000, 0x3FFF0001, 0x3FFF0002], dtype=np.uint32))
    v = np.asarray(table.lookup(state, meta, absent_hi, absent))
    assert (v == 0).all()


def test_growth_path():
    """Undersized table (the reference's sizes 1-20x trick) must report
    full; grow() then preserves every entry exactly."""
    k = 20
    rng = np.random.default_rng(0)
    meta = table.TableMeta(k=k, bits=7, size_log2=4)  # 16 slots
    state = table.make_table(meta)
    keys = rng.integers(0, 1 << (2 * k), size=500, dtype=np.uint64)
    quals = rng.integers(0, 2, size=500)

    khi = jnp.asarray((keys >> np.uint64(32)).astype(np.uint32))
    klo = jnp.asarray((keys & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    qq = jnp.asarray(quals.astype(np.int32))
    valid = jnp.ones(len(keys), dtype=bool)

    state, full = table.add_kmer_batch(state, meta, khi, klo, qq, valid)
    # 500 obs of ~500 distinct keys into 16 slots must overflow
    assert bool(full)

    # host driver loop: grow until the batch fits (replays the whole batch;
    # idempotence is guaranteed because a failed merge_batch leaves some
    # keys unplaced — so the driver must re-merge from a clean snapshot.
    # Here we simply restart from scratch at each size like the CDB
    # pipeline does per batch-with-retry.)
    while True:
        meta = table.TableMeta(k=k, bits=7, size_log2=meta.size_log2 + 1)
        state = table.make_table(meta)
        state, full = table.add_kmer_batch(state, meta, khi, klo, qq, valid)
        if not bool(full):
            break
    assert meta.size >= 500

    expect = brute_force_counts(
        [(int(kx), int(q)) for kx, q in zip(keys, quals)], 7
    )
    # grow twice more and re-check values survive re-scatter
    for _ in range(2):
        state, meta = table.grow(state, meta, chunk=64)
    ukeys = sorted(set(int(kx) for kx in keys))
    uhi = jnp.asarray(np.array([kx >> 32 for kx in ukeys], dtype=np.uint32))
    ulo = jnp.asarray(np.array([kx & 0xFFFFFFFF for kx in ukeys], dtype=np.uint32))
    vals = np.asarray(table.lookup(state, meta, uhi, ulo))
    for kx, v in zip(ukeys, vals):
        assert (int(v) >> 1, int(v) & 1) == expect[kx]

    # full-table stats agree with brute force
    occ, distinct, total = table.table_stats(state, meta)
    assert int(occ) == len(ukeys)
    exp_distinct = sum(1 for c, q in expect.values() if q == 1 and c >= 1)
    exp_total = sum(c for c, q in expect.values() if q == 1 and c >= 1)
    assert int(distinct) == exp_distinct
    assert int(total) == exp_total


def test_saturation():
    k = 24
    meta = table.TableMeta(k=k, bits=3, size_log2=6)  # max_val = 7
    state = table.make_table(meta)
    khi = jnp.zeros(20, dtype=jnp.uint32)
    klo = jnp.full(20, 5, dtype=jnp.uint32)
    state, full = table.add_kmer_batch(
        state, meta, khi, klo,
        jnp.ones(20, dtype=jnp.int32), jnp.ones(20, dtype=bool),
    )
    assert not bool(full)
    v = int(np.asarray(table.lookup(state, meta, khi[:1], klo[:1]))[0])
    assert v >> 1 == 7 and v & 1 == 1


def test_quality_reset_across_batches():
    """LQ batch then HQ batch == HQ alone (reference :117-118); HQ then
    LQ ignores LQ."""
    k = 24
    meta = table.TableMeta(k=k, bits=7, size_log2=6)
    st = table.make_table(meta)
    khi = jnp.zeros(3, dtype=jnp.uint32)
    klo = jnp.asarray(np.array([1, 1, 1], dtype=np.uint32))
    ones = jnp.ones(3, dtype=bool)
    lq = jnp.zeros(3, dtype=jnp.int32)
    hq = jnp.ones(3, dtype=jnp.int32)
    st, _ = table.add_kmer_batch(st, meta, khi, klo, lq, ones)  # 3 LQ
    st, _ = table.add_kmer_batch(st, meta, khi[:2], klo[:2], hq[:2], ones[:2])  # 2 HQ
    st, _ = table.add_kmer_batch(st, meta, khi, klo, lq, ones)  # 3 LQ again
    v = int(np.asarray(table.lookup(st, meta, khi[:1], klo[:1]))[0])
    assert (v >> 1, v & 1) == (2, 1)
