"""tools/perf_diff.py (ISSUE 11): profile extraction from both
artifact kinds, limit semantics, the seeded-regression negative case
the CI gate depends on, baseline generation, and verdict-document
validation through metrics_check."""

import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))
import perf_diff  # noqa: E402

from quorum_tpu.telemetry.schema import (check_file,  # noqa: E402
                                         validate_perf_diff)

METRICS_CHECK = os.path.join(REPO, "tools", "metrics_check.py")


def metrics_doc(stage1_s=2.5, kernel_us=5000, disp_mean=200):
    return {
        "schema": "quorum-tpu-metrics/1", "meta": {"stage": "x"},
        "counters": {"device_kernel_us_total": kernel_us,
                     "reads": 100},
        "gauges": {"stage1_seconds": stage1_s},
        "histograms": {"insert_dispatch_us": {
            "count": 4, "sum": disp_mean * 4,
            "counts": {str(disp_mean): 4}}},
        "timers": {"stage1": {
            "total_seconds": stage1_s,
            "stages": {"insert_wait": {"seconds": stage1_s / 2,
                                       "calls": 4, "units": 0}}}},
    }


def bench_lines(speedup=1.2, base_ms=100.0):
    return (json.dumps({"metric": "ab_stage1_insert",
                        "speedup": speedup, "base_ms": base_ms,
                        "parity": "content-identical"}) + "\n"
            + json.dumps({"metric": "ab_env", "reps": 2}) + "\n")


@pytest.fixture
def artifacts(tmp_path):
    mp = tmp_path / "m.json"
    mp.write_text(json.dumps(metrics_doc()))
    bp = tmp_path / "bench.json"
    bp.write_text(bench_lines())
    return str(mp), str(bp)


def test_extract_profile_both_kinds(artifacts):
    mp, bp = artifacts
    prof = perf_diff.extract_profile(mp)
    assert prof["timers.stage1.total_seconds"] == 2.5
    assert prof["timers.stage1.stages.insert_wait.seconds"] == 1.25
    assert prof["counters.device_kernel_us_total"] == 5000.0
    assert prof["histograms.insert_dispatch_us.mean"] == 200.0
    assert prof["gauges.stage1_seconds"] == 2.5
    bprof = perf_diff.extract_profile(bp)
    assert bprof["bench.ab_stage1_insert.speedup"] == 1.2
    assert bprof["bench.ab_stage1_insert.base_ms"] == 100.0
    assert "bench.ab_stage1_insert.parity" not in bprof  # non-numeric


def test_direction_heuristic():
    assert perf_diff.direction_for(
        "timers.stage1.total_seconds") == "lower_better"
    assert perf_diff.direction_for(
        "bench.ab.speedup") == "higher_better"
    assert perf_diff.direction_for(
        "gauges.foo_gb_per_h") == "higher_better"
    assert perf_diff.direction_for(
        "histograms.x_us.mean") == "lower_better"
    assert perf_diff.direction_for("counters.reads") == "both"


def test_check_metric_limit_semantics():
    cm = perf_diff.check_metric
    assert cm("m", {"value": 10, "max_ratio": 2.0}, 19)["ok"]
    assert not cm("m", {"value": 10, "max_ratio": 2.0}, 21)["ok"]
    assert cm("m", {"value": 10, "min_ratio": 0.5}, 6)["ok"]
    assert not cm("m", {"value": 10, "min_ratio": 0.5}, 4)["ok"]
    assert cm("m", {"min": 1}, 2)["ok"]
    assert not cm("m", {"min": 1}, 0)["ok"]
    assert not cm("m", {"value": 10, "tolerance_pct": 10}, 12)["ok"]
    assert cm("m", {"value": 10, "tolerance_pct": 30}, 12)["ok"]
    # absence: regression unless optional
    assert not cm("m", {"value": 1}, None)["ok"]
    assert cm("m", {"value": 1, "optional": True}, None)["ok"]


def write_baseline(tmp_path, artifacts):
    mp, bp = artifacts
    out = str(tmp_path / "PERF_BASELINE.json")
    rc = perf_diff.main(["--write-baseline", out,
                         f"stage1={mp}", f"bench_ab={bp}"])
    assert rc == 0
    return out, mp, bp


def test_baseline_gate_pass_and_verdict_doc(tmp_path, artifacts):
    base, mp, bp = write_baseline(tmp_path, artifacts)
    verdict = str(tmp_path / "v.json")
    rc = perf_diff.main(["--baseline", base, f"stage1={mp}",
                         f"bench_ab={bp}", "--out", verdict, "-q"])
    assert rc == 0
    doc = json.load(open(verdict))
    assert doc["verdict"] == "pass" and doc["checked"] > 0
    assert validate_perf_diff(doc) == []
    assert check_file(verdict) == []
    res = subprocess.run([sys.executable, METRICS_CHECK, verdict],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr


def test_seeded_regression_fails_the_gate(tmp_path, artifacts):
    """The negative case ci/tier1.sh depends on: a doctored candidate
    (8x slower wall clock, collapsed speedup) must exit 1 with a
    valid 'regression' verdict document."""
    base, mp, bp = write_baseline(tmp_path, artifacts)
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(metrics_doc(stage1_s=20.0)))
    nospeed = tmp_path / "nospeed.json"
    nospeed.write_text(bench_lines(speedup=0.2))
    verdict = str(tmp_path / "v.json")
    rc = perf_diff.main(["--baseline", base, f"stage1={slow}",
                         f"bench_ab={nospeed}", "--out", verdict,
                         "-q"])
    assert rc == 1
    doc = json.load(open(verdict))
    assert doc["verdict"] == "regression"
    assert any("total_seconds" in r for r in doc["regressions"])
    assert any("speedup" in r for r in doc["regressions"])
    assert validate_perf_diff(doc) == []
    res = subprocess.run([sys.executable, METRICS_CHECK, verdict],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr  # a valid doc, bad verdict


def test_missing_metric_is_a_regression(tmp_path, artifacts):
    base, mp, bp = write_baseline(tmp_path, artifacts)
    gutted = tmp_path / "gutted.json"
    doc = metrics_doc()
    del doc["timers"]
    gutted.write_text(json.dumps(doc))
    rc = perf_diff.main(["--baseline", base, f"stage1={gutted}",
                         f"bench_ab={bp}", "-q"])
    assert rc == 1


def test_missing_document_is_a_regression(tmp_path, artifacts):
    base, mp, bp = write_baseline(tmp_path, artifacts)
    rc = perf_diff.main(["--baseline", base, f"bench_ab={bp}", "-q"])
    assert rc == 1


def test_two_doc_mode_directions(tmp_path, artifacts):
    mp, _bp = artifacts
    same = perf_diff.main([mp, mp, "-q"])
    assert same == 0
    worse = tmp_path / "worse.json"
    worse.write_text(json.dumps(metrics_doc(stage1_s=20.0)))
    assert perf_diff.main([mp, str(worse), "-q"]) == 1
    # the same delta in the GOOD direction passes
    assert perf_diff.main([str(worse), mp, "-q"]) == 0


def test_validate_perf_diff_rejects_incoherent():
    base = {"schema": "quorum-tpu-perf-diff/1", "verdict": "pass",
            "checked": 1, "regressions": [],
            "docs": {"a": {"metrics": {"m": {"ok": True}}}}}
    assert validate_perf_diff(base) == []
    assert validate_perf_diff(
        {**base, "verdict": "wat"}) != []
    assert validate_perf_diff(
        {**base, "regressions": ["x"]}) != []  # pass + regressions
    assert validate_perf_diff(
        {**base, "verdict": "regression"}) != []  # regression + none
    tampered = json.loads(json.dumps(base))
    tampered["docs"]["a"]["metrics"]["m"]["ok"] = False
    assert validate_perf_diff(tampered) != []  # pass + ok=false entry


def test_committed_baseline_is_valid():
    """The repo's committed contract must parse and name only
    extractable limits — CI trips over it otherwise."""
    path = os.path.join(REPO, "PERF_BASELINE.json")
    assert os.path.exists(path), "PERF_BASELINE.json missing"
    doc = json.load(open(path))
    assert doc["schema"] == perf_diff.BASELINE_SCHEMA
    assert doc["docs"], "baseline names no documents"
    for key, spec in doc["docs"].items():
        assert spec["metrics"], f"doc {key} has no metrics"
        for name, mspec in spec["metrics"].items():
            assert isinstance(mspec, dict)
            assert any(k in mspec for k in
                       ("min", "max", "max_ratio", "min_ratio",
                        "tolerance_pct")), \
                f"{key}:{name} bounds nothing"
