"""ISSUE 9: the no-gather sharded v5 database export — per-shard
`PREFIX.shard-K-of-S.qdb` files under a sealed manifest, byte parity
with the single-file layout via db_payload_bytes, loaders and
quorum-fsck consuming the manifest, and corruption refusing loudly at
every surface."""

import json
import os

import numpy as np
import pytest

from quorum_tpu.io import db_format
from quorum_tpu.io.integrity import IntegrityError
from quorum_tpu.ops import ctable

K = 13
RLEN = 48
BATCH = 32
N_READS = 64


@pytest.fixture(scope="module")
def reads_fastq(tmp_path_factory):
    rng = np.random.default_rng(21)
    genome = rng.integers(0, 4, size=1200, dtype=np.int8)
    starts = rng.integers(0, 1200 - RLEN, size=N_READS)
    codes = genome[starts[:, None] + np.arange(RLEN)[None, :]]
    codes = codes.astype(np.int8)
    err = rng.random(codes.shape) < 0.03
    codes = np.where(err, (codes + rng.integers(1, 4, size=codes.shape))
                     % 4, codes).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    quals[err] = 34
    bases = np.frombuffer(b"ACGT", np.uint8)
    path = tmp_path_factory.mktemp("shdb") / "reads.fastq"
    with open(path, "wb") as f:
        for i in range(N_READS):
            f.write(b"@r%d\n" % i + bases[codes[i]].tobytes()
                    + b"\n+\n" + quals[i].tobytes() + b"\n")
    return str(path)


def _build(reads, out, devices, extra=()):
    from quorum_tpu.cli import create_database as cdb_cli
    rc = cdb_cli.main(["-s", "32k", "-m", str(K), "-b", "7", "-q", "53",
                       "-o", out, "--batch-size", str(BATCH),
                       "--devices", str(devices), *extra, reads])
    assert rc == 0
    return out


@pytest.fixture(scope="module")
def built_dbs(reads_fastq, tmp_path_factory):
    """One single-file build and one 2-device sharded-layout build,
    shared across the read-side tests."""
    d = tmp_path_factory.mktemp("shdb_out")
    single = _build(reads_fastq, str(d / "single.jf"), 1)
    sharded = _build(reads_fastq, str(d / "sharded.jf"), 2,
                     extra=("--db-layout", "sharded"))
    return single, sharded


def test_sharded_layout_payload_parity(built_dbs):
    """THE acceptance property: db_payload_bytes over the manifest
    reassembles exactly the single-file payload — the two layouts are
    interchangeable representations of the same bytes."""
    single, sharded = built_dbs
    assert (db_format.db_payload_bytes(single)
            == db_format.db_payload_bytes(sharded))
    # and the shard files exist under the documented names
    for s in range(2):
        assert os.path.exists(db_format.shard_file_name(sharded, s, 2))


def test_sharded_export_never_gathers(reads_fastq, tmp_path,
                                      monkeypatch):
    """--db-layout=sharded must not call gather_table (the gather is
    the ~13 min cliff the format exists to remove)."""
    from quorum_tpu.parallel import tile_sharded as ts

    def boom(*a, **kw):
        raise AssertionError("gather_table called on the sharded "
                             "export path")

    monkeypatch.setattr(ts, "gather_table", boom)
    out = _build(reads_fastq, str(tmp_path / "nogather.jf"), 2,
                 extra=("--db-layout", "sharded"))
    assert os.path.exists(out)


def test_manifest_load_matches_single(built_dbs):
    """read_db over the manifest reconstructs the identical table."""
    single, sharded = built_dbs
    s1, m1, h1 = db_format.read_db(single, to_device=False)
    s2, m2, h2 = db_format.read_db(sharded, to_device=False)
    assert (m1.k, m1.bits, m1.rb_log2) == (m2.k, m2.bits, m2.rb_log2)
    np.testing.assert_array_equal(np.asarray(s1.rows),
                                  np.asarray(s2.rows))
    assert h2["format"] == db_format.MANIFEST_FORMAT


def test_sharded_correct_byte_parity(built_dbs, reads_fastq, tmp_path):
    """Stage 2 fed the manifest produces byte-identical output to the
    single-file database."""
    from quorum_tpu.cli import error_correct_reads as ec_cli
    single, sharded = built_dbs
    outs = {}
    for tag, db in (("s", single), ("m", sharded)):
        prefix = str(tmp_path / f"out_{tag}")
        rc = ec_cli.main(["-o", prefix, "-p", "2",
                          "--batch-size", str(BATCH), db, reads_fastq])
        assert rc == 0
        outs[tag] = (open(prefix + ".fa", "rb").read(),
                     open(prefix + ".log", "rb").read())
    assert outs["s"] == outs["m"]
    assert outs["s"][0]  # non-trivial


def test_single_shard_roundtrip(tmp_path):
    """write_db_sharded over a plain single-chip table (S=1) round-
    trips through the manifest with payload parity vs write_db — the
    format works without a mesh."""
    rng = np.random.default_rng(3)
    n = 500
    khi = rng.integers(0, 1 << 6, size=n).astype(np.uint32)
    klo = rng.integers(0, 1 << 32, size=n, dtype=np.uint64).astype(
        np.uint32)
    vals = ((rng.integers(1, 100, size=n) << 1) | 1).astype(np.uint32)
    state, meta = ctable.tile_from_entries(khi, klo, vals, K, 7)
    single = str(tmp_path / "single.qdb")
    sharded = str(tmp_path / "sharded.qdb")
    occ, _d, _t = ctable.tile_stats(state, meta)
    db_format.write_db(single, state, meta, n_entries=int(occ))
    db_format.write_db_sharded(sharded, state, meta)
    assert (db_format.db_payload_bytes(single)
            == db_format.db_payload_bytes(sharded))
    s2, m2, _h = db_format.read_db(sharded, to_device=False)
    a = sorted(zip(*(x.tolist()
                     for x in ctable.tile_iterate(state, meta))))
    b = sorted(zip(*(x.tolist() for x in ctable.tile_iterate(s2, m2))))
    assert a == b


def test_corrupt_shard_refuses(built_dbs, tmp_path):
    """A flipped byte inside one shard refuses at read_db
    (IntegrityError -> rc 3 at the CLIs) and is pinpointed by
    verify_db_file with a shard-qualified section."""
    import shutil
    _single, sharded = built_dbs
    d = tmp_path / "corrupt"
    d.mkdir()
    man = str(d / os.path.basename(sharded))
    shutil.copy(sharded, man)
    for s in range(2):
        shutil.copy(db_format.shard_file_name(sharded, s, 2),
                    db_format.shard_file_name(man, s, 2))
    victim = db_format.shard_file_name(man, 1, 2)
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(IntegrityError):
        db_format.read_db(man, to_device=False)
    header, problems = db_format.verify_db_file(man)
    assert problems
    assert any(sec.startswith("shard-1") for sec, _o, _m in problems)
    # and quorum-fsck exits damaged, naming the shard
    from quorum_tpu.cli.fsck import main as fsck_main
    assert fsck_main([man]) == 1
    # verify=off loads structurally (digest checks skipped)
    st, meta, _h = db_format.read_db(man, to_device=False,
                                     verify="off")
    assert meta.k == K


def test_manifest_tamper_refuses(built_dbs, tmp_path):
    """Editing the manifest (cursor a shard to a different file, bump
    a count) breaks its seal — refused even though the JSON still
    parses."""
    import shutil
    _single, sharded = built_dbs
    d = tmp_path / "tamper"
    d.mkdir()
    man = str(d / "m.jf")
    shutil.copy(sharded, man)
    for s in range(2):
        shutil.copy(db_format.shard_file_name(sharded, s, 2),
                    db_format.shard_file_name(man, s, 2))
    doc = json.loads(open(man).read())
    doc["n_entries"] = int(doc["n_entries"]) + 1
    open(man, "w").write(json.dumps(doc) + "\n")
    with pytest.raises(IntegrityError, match="self-digest"):
        db_format.read_db(man, to_device=False)


def test_missing_shard_refuses(built_dbs, tmp_path):
    import shutil
    _single, sharded = built_dbs
    d = tmp_path / "missing"
    d.mkdir()
    man = str(d / "m.jf")
    shutil.copy(sharded, man)
    shutil.copy(db_format.shard_file_name(sharded, 0, 2),
                db_format.shard_file_name(man, 0, 2))
    with pytest.raises(IntegrityError, match="missing shard"):
        db_format.read_db(man, to_device=False)
    _header, problems = db_format.verify_db_file(man)
    assert any("missing" in m for _s, _o, m in problems)


def test_shard_file_direct_load_refused(built_dbs):
    """Loading a bare shard file points the operator at the
    manifest."""
    _single, sharded = built_dbs
    shard0 = db_format.shard_file_name(sharded, 0, 2)
    with pytest.raises(ValueError, match="manifest"):
        db_format.read_db(shard0, to_device=False)


def test_v4_sharded_layout_digests(reads_fastq, tmp_path):
    """db_version=4 shard files carry no per-section checksums, but
    the sealed manifest's whole-file digests still catch corruption at
    load."""
    man = _build(reads_fastq, str(tmp_path / "v4.jf"), 2,
                 extra=("--db-layout", "sharded", "--db-version", "4"))
    st, meta, header = db_format.read_db(man, to_device=False)
    assert header["version"] == 4
    victim = db_format.shard_file_name(man, 0, 2)
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size - 8)
        byte = f.read(1)
        f.seek(size - 8)
        f.write(bytes([byte[0] ^ 0x55]))
    with pytest.raises(IntegrityError):
        db_format.read_db(man, to_device=False)


def test_fsck_clean_v4_shard_file(reads_fastq, tmp_path):
    """quorum-fsck on a standalone UNDAMAGED v4 shard file reports
    clean (the structural decode runs over the shard's local row
    range; read_db's load-through-the-manifest refusal must not be
    mistaken for damage)."""
    from quorum_tpu.cli import fsck as fsck_cli
    man = _build(reads_fastq, str(tmp_path / "v4f.jf"), 2,
                 extra=("--db-layout", "sharded", "--db-version", "4"))
    shard = db_format.shard_file_name(man, 0, 2)
    header, problems = db_format.verify_db_file(shard, "full")
    assert header["layout"] == "shard"
    assert problems == []
    assert fsck_cli.main([shard]) == 0


def test_rb25_manifest_single_chip_refusal_names_devices(tmp_path):
    """A manifest past the single-chip geometry cap refuses a
    to_device load pointing at --devices N, but the HOST-side
    reassembly (what a routed multi-device run consumes) gets past
    the gate — proven by it failing later, on the missing shard
    files, not on the cap."""
    from quorum_tpu.io import integrity
    from quorum_tpu.parallel.tile_sharded import TileShardedMeta
    meta = TileShardedMeta(k=31, bits=7, rb_log2=25, n_shards=2)
    hb = (max(0, meta.rem_bits - meta.rlo_bits) + 7) // 8
    man = str(tmp_path / "big.jf")
    doc = integrity.seal({
        "format": db_format.MANIFEST_FORMAT, "version": 5,
        "layout": "sharded", "key_len": 62, "bits": 7, "rb_log2": 25,
        "rows": 1 << 25, "n_shards": 2, "n_entries": 8,
        "hi_bytes": hb,
        "shards": [{"path": f"missing-{s}.qdb", "shard": s,
                    "n_entries": 4, "value_bytes": 0,
                    "file_crc32c": 0} for s in range(2)]})
    with open(man, "wb") as f:
        f.write(json.dumps(doc).encode() + b"\n")
    with pytest.raises(ValueError, match="--devices N"):
        db_format.read_db(man, to_device=True)
    with pytest.raises(IntegrityError, match="missing shard"):
        db_format.read_db(man, to_device=False)


def test_driver_resume_reuses_sharded_db(reads_fastq, tmp_path):
    """The quorum driver's --resume reuse bar accepts (and verifies)
    a finished sharded-layout database, so a resumed run skips the
    rebuild whichever layout stage 1 wrote."""
    from quorum_tpu.cli import quorum as quorum_cli
    prefix = str(tmp_path / "drv")
    argv = ["-s", "32k", "-k", str(K), "-q", "33", "-p", prefix,
            "--batch-size", str(BATCH), "--devices", "2",
            "--db-layout", "sharded", reads_fastq]
    assert quorum_cli.main(argv) == 0
    db_file = prefix + "_mer_database.jf"
    header = db_format.read_header(db_file)
    assert header["format"] == db_format.MANIFEST_FORMAT
    fa1 = open(prefix + ".fa", "rb").read()
    # second run with --resume: stage 1 must be skipped, output equal
    mpath = str(tmp_path / "m.json")
    assert quorum_cli.main(argv + ["--resume", "--metrics",
                                   mpath]) == 0
    doc = json.load(open(mpath))
    assert doc["meta"].get("stage1_resumed_db") == db_file
    assert open(prefix + ".fa", "rb").read() == fa1
