"""The persistent correction service (ISSUE 3): serve parity with the
offline CLI, warm-path no-recompile, admission control, deadlines, and
graceful drain.

The parity tests run a REAL engine over the committed golden fixture:
the server's `POST /correct` response must be byte-identical to what
`quorum_error_correct_reads` writes for the same reads (both go
through models/error_correct.render_result, so a drift here means the
serving path broke batching/demux, not rendering). The
backpressure/deadline/drain tests use a gated fake engine so they are
fast and deterministic.
"""

import conftest  # noqa: F401  (pins CPU devices)

import json
import os
import socket
import threading
import time

import pytest

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli
from quorum_tpu.cli import serve as serve_cli
from quorum_tpu.serve import (CorrectionEngine, CorrectionServer,
                              DeadlineExceeded, Draining,
                              DynamicBatcher, EngineStepTimeout,
                              QueueFull, TokenBucketQuota)
from quorum_tpu.serve.client import ServeClient, ServeResult, bench_main
from quorum_tpu.telemetry import registry_for, validate_metrics
from quorum_tpu.utils import faults

HERE = os.path.dirname(__file__)
GOLDEN = os.path.join(HERE, "golden")
READS = os.path.join(GOLDEN, "reads.fastq")


# ---------------------------------------------------------------------------
# real-engine stack over the golden fixture (module-scoped: one compile)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def golden_db(tmp_path_factory):
    db = str(tmp_path_factory.mktemp("serve_db") / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, READS])
    assert rc == 0
    return db


@pytest.fixture(scope="module")
def offline(golden_db, tmp_path_factory):
    """The offline CLI's output at -p 4 (matches tests/golden)."""
    out = str(tmp_path_factory.mktemp("serve_off") / "off")
    rc = ec_cli.main(["-p", "4", golden_db, READS, "-o", out])
    assert rc == 0
    with open(out + ".fa") as f:
        fa = f.read()
    with open(out + ".log") as f:
        log = f.read()
    return fa, log


@pytest.fixture(scope="module")
def warm_stack(golden_db):
    reg = registry_for(None, force=True)
    reg.set_meta(stage="serve")
    engine = CorrectionEngine(golden_db, cutoff=4, rows=64, registry=reg)
    batcher = DynamicBatcher(engine, max_batch=64, max_wait_ms=2,
                             queue_requests=8, registry=reg)

    def builder(params):
        # the same validate-then-build shape cli/serve.py wires up
        from quorum_tpu.io import db_format
        cur = batcher.current_engine()
        db = params.get("db") or cur.db_path
        header = db_format.read_header(db)
        if (header.get("key_len") != 2 * cur.cfg.k
                or header.get("bits") != cur.meta.bits):
            raise ValueError(f"reload refused: k/bits mismatch in {db}")
        eng = CorrectionEngine(db, cutoff=4, rows=64, registry=reg)
        eng.warmup(cur.warm_lengths)
        return eng

    server = CorrectionServer(batcher, port=0, registry=reg,
                              engine_builder=builder)
    yield reg, engine, server
    server.close()


def test_serve_parity_and_warm_no_recompile(warm_stack, offline):
    """Acceptance: a warm server answers a second POST /correct
    without recompilation and byte-identical to the offline CLI."""
    reg, engine, server = warm_stack
    off_fa, off_log = offline
    client = ServeClient(port=server.port)
    body = open(READS).read()

    r1 = client.correct(body, want_log=True)
    assert r1.status == 200
    assert r1.fa == off_fa          # byte parity, .fa channel
    assert r1.log == off_log        # byte parity, .log channel
    assert r1.reads == 242 and r1.skipped >= 1
    compiles_after_first = reg.counter("engine_compiles").value
    assert compiles_after_first >= 1

    t0 = time.perf_counter()
    r2 = client.correct(body, want_log=True)
    warm_s = time.perf_counter() - t0
    assert r2.status == 200
    assert r2.fa == off_fa and r2.log == off_log
    # THE acceptance signal: no new executable for the warm request
    assert reg.counter("engine_compiles").value == compiles_after_first
    assert warm_s < 30  # cold path is minutes on CPU; warm is sub-second

    health = client.healthz()
    assert health["status"] == "ok"
    assert health["engine_compiles"] == compiles_after_first

    # /metrics on the serving port carries the serve series
    text = client.metrics_text()
    for name in ("quorum_tpu_requests_accepted_total",
                 "quorum_tpu_reads_corrected_total",
                 "quorum_tpu_batch_reads", "quorum_tpu_engine_compiles"):
        assert name in text, f"{name} missing from /metrics"


def test_serve_multi_request_demux(warm_stack, offline):
    """Several small requests concatenate to the offline output —
    the batcher coalesces them but each Future gets exactly its own
    slice back."""
    _reg, _engine, server = warm_stack
    off_fa, off_log = offline
    client = ServeClient(port=server.port)
    with open(READS) as f:
        lines = f.read().splitlines(keepends=True)
    recs = ["".join(lines[i:i + 4]) for i in range(0, len(lines), 4)]
    # 242 reads in 5 uneven requests (the last is tiny)
    chunks = [recs[0:50], recs[50:120], recs[120:190], recs[190:240],
              recs[240:]]
    fa_parts, log_parts = [], []
    for chunk in chunks:
        r = client.correct("".join(chunk), want_log=True)
        assert r.status == 200
        fa_parts.append(r.fa)
        log_parts.append(r.log)
    assert "".join(fa_parts) == off_fa
    assert "".join(log_parts) == off_log


def test_serve_empty_and_bad_input(warm_stack):
    _reg, _engine, server = warm_stack
    client = ServeClient(port=server.port)
    r = client.correct("")
    assert r.status == 200 and r.fa == "" and r.reads == 0
    r = client.correct("@h\nACGT\n+\nzzz\n")  # qual/seq length mismatch
    assert r.status == 400


@pytest.fixture(scope="module")
def offline_quality_doc(golden_db, tmp_path_factory):
    """The offline CLI's final metrics document — with its `quality`
    section — over the same golden input the serve tests POST."""
    d = tmp_path_factory.mktemp("serve_q")
    out = str(d / "off")
    m = str(d / "m.json")
    rc = ec_cli.main(["-p", "4", golden_db, READS, "-o", out,
                      "--metrics", m])
    assert rc == 0
    with open(m) as f:
        return json.load(f)


def test_serve_quality_header_matches_offline_doc(warm_stack,
                                                  offline_quality_doc):
    """ISSUE 17 parity: the per-request X-Quorum-Quality tally for
    the full golden input equals the offline run's final `quality`
    section. The header is decoded from the same rendered text the
    client receives (quality.summarize_results), so serve and
    offline cannot disagree about correction quality."""
    _reg, _engine, server = warm_stack
    client = ServeClient(port=server.port)
    r = client.correct(open(READS).read(), want_log=True)
    assert r.status == 200
    q = offline_quality_doc["quality"]
    assert r.quality == {
        "reads": q["reads"], "corrected": q["corrected"],
        "skipped": q["skipped"], "subs": q["substitutions"],
        "t3": q["truncations_3p"], "t5": q["truncations_5p"]}
    assert r.quality["reads"] == 242 and r.quality["subs"] == 227


def test_reload_rollback_and_swap_real_engine(warm_stack, offline,
                                              tmp_path):
    """Acceptance (ISSUE 7): POST /reload with a corrupt DB leaves the
    server answering byte-identical responses from the old engine
    (rollback); a good reload swaps generations and parity still
    holds on the rebuilt engine."""
    reg, _engine, server = warm_stack
    off_fa, off_log = offline
    client = ServeClient(port=server.port)
    body = open(READS).read()
    gen0 = client.healthz()["engine_generation"]

    corrupt = tmp_path / "corrupt.jf"
    corrupt.write_bytes(b"\x00\x01 not a database \xff\xfe")
    code, doc = client.reload({"db": str(corrupt)})
    assert code == 400 and doc.get("rolled_back") is True
    assert reg.counter("reload_failures_total").value >= 1
    r = client.correct(body, want_log=True)
    assert r.status == 200
    assert r.fa == off_fa and r.log == off_log   # old engine, byte-same

    code, doc = client.reload({})   # same DB: validate, rebuild, swap
    assert code == 200 and doc["generation"] == gen0 + 1
    assert client.healthz()["engine_generation"] == gen0 + 1
    r = client.correct(body, want_log=True)
    assert r.status == 200
    assert r.fa == off_fa and r.log == off_log   # new engine, byte-same
    assert reg.counter("reload_total").value >= 1


# ---------------------------------------------------------------------------
# backpressure / deadline / drain (gated fake engine: fast + exact)
# ---------------------------------------------------------------------------

class FakeEngine:
    """Engine-shaped stub: echoes each read as a one-line .fa record,
    optionally blocking on an Event so tests control dispatch."""

    def __init__(self, gate=None, rows=1024, **_kw):
        self.gate = gate
        self.rows = rows
        self.stepped = 0
        self.entered = threading.Event()  # a step actually began

    @property
    def compiles(self):
        return 0

    def step(self, records):
        self.entered.set()
        if self.gate is not None:
            assert self.gate.wait(timeout=30), "test gate never opened"
        self.stepped += 1
        return [(f">{h}\n{s.decode()}\n", "") for h, s, _q in records]


def _drain_to_depth(batcher, depth=0, timeout=5.0):
    t0 = time.perf_counter()
    while batcher.depth > depth:
        if time.perf_counter() - t0 > timeout:
            raise AssertionError(f"queue stuck at {batcher.depth}")
        time.sleep(0.005)


def test_batcher_429_on_full_queue():
    gate = threading.Event()
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(gate), max_batch=4, max_wait_ms=0,
                         queue_requests=1, registry=reg)
    recs = [("r", b"ACGT", b"IIII")]
    fa = bat.submit(recs)          # popped by the dispatcher, blocks
    _drain_to_depth(bat, 0)        # ensure A left the queue
    fb = bat.submit(recs)          # fills the queue
    with pytest.raises(QueueFull) as ei:
        bat.submit(recs)           # bounced at the door
    assert ei.value.retry_after > 0
    assert reg.counter("requests_rejected_queue_full").value == 1
    gate.set()
    assert fa.result(timeout=10)[0][0].startswith(">r")
    assert fb.result(timeout=10)[0][0].startswith(">r")
    assert bat.drain(timeout=5)


def test_batcher_deadline_exceeded():
    gate = threading.Event()
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(gate), max_batch=4, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    recs = [("r", b"ACGT", b"IIII")]
    fa = bat.submit(recs)                      # blocks in the engine
    _drain_to_depth(bat, 0)
    fb = bat.submit(recs, deadline_s=0.01)     # will expire while queued
    time.sleep(0.05)
    gate.set()
    assert fa.result(timeout=10)
    with pytest.raises(DeadlineExceeded):
        fb.result(timeout=10)
    assert reg.counter("requests_deadline_exceeded").value == 1
    assert bat.drain(timeout=5)


def test_server_http_429_504_and_drain(tmp_path):
    """The HTTP mappings: 429 + Retry-After on a full queue, 504 past
    the deadline, 503 while draining — and the final metrics document
    lands through the observability teardown on drain."""
    from quorum_tpu.cli.observability import observability

    gate = threading.Event()
    metrics_path = str(tmp_path / "serve.json")
    with observability(metrics_path, stage="serve") as obs:
        reg = obs.registry
        eng = FakeEngine(gate)
        bat = DynamicBatcher(eng, max_batch=4,
                             max_wait_ms=0, queue_requests=1,
                             registry=reg)
        srv = CorrectionServer(bat, port=0, registry=reg,
                               drain_grace_s=5.0)
        client = ServeClient(port=srv.port)
        body = "@r\nACGT\n+\nIIII\n"

        # occupy the engine: t1's request dispatches and blocks.
        # `entered` (not queue depth) is the occupancy signal — depth
        # 0 is also the state BEFORE t1's request arrives over HTTP.
        t1 = threading.Thread(
            target=lambda: client.correct(body), daemon=True)
        t1.start()
        assert eng.entered.wait(5), "t1's request never dispatched"
        _drain_to_depth(bat, 0)

        # deadline: the engine is gated, so this queued request's
        # 10 ms deadline expires (the handler's wall-timeout backstop
        # answers 504; its queue slot frees when the gate opens)
        r = ServeClient(port=srv.port).correct(body, deadline_ms=10)
        assert r.status == 504

        # the expired request still occupies the 1-slot queue until
        # the dispatcher gets to it -> the next request bounces
        r = ServeClient(port=srv.port).correct(body)
        assert r.status == 429
        assert r.retry_after_s >= 1

        gate.set()
        t1.join(timeout=10)
        _drain_to_depth(bat, 0)

        # drain via /quiesce: stops admission, flushes, unblocks
        # serve_until_drained
        assert client.quiesce()["status"] == "draining"
        deadline = time.perf_counter() + 5
        while True:  # admission shuts asynchronously after /quiesce
            r = ServeClient(port=srv.port).correct(body)
            if r.status == 503:
                break
            assert time.perf_counter() < deadline, r.status
            time.sleep(0.02)
        srv.serve_until_drained()
        srv.close()

    with open(metrics_path) as f:
        doc = json.load(f)
    assert validate_metrics(doc) == []
    assert doc["meta"]["status"] == "ok"
    assert doc["meta"]["drained"] is True
    assert doc["counters"]["requests_accepted"] >= 2
    assert doc["counters"]["requests_rejected_queue_full"] >= 1
    assert doc["counters"]["requests_deadline_exceeded"] >= 1


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_serve_cli_end_to_end_with_fake_engine(tmp_path, monkeypatch):
    """The quorum-serve CLI surface: flag plumbing, in-thread serving,
    HTTP quiesce, rc 0, and a schema-valid final metrics document with
    the serve metric names (the same set ci/tier1.sh gates on)."""
    import quorum_tpu.serve as serve_pkg

    monkeypatch.setattr(serve_pkg, "CorrectionEngine",
                        lambda db, **kw: FakeEngine(
                            rows=kw.get("rows", 1024)))
    port = _free_port()
    metrics_path = str(tmp_path / "serve.json")
    rc_box = {}

    def run():
        rc_box["rc"] = serve_cli.main(
            ["--port", str(port), "--max-wait-ms", "0",
             "--max-batch", "8", "--metrics", metrics_path,
             "ignored.jf"])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    client = ServeClient(port=port)
    deadline = time.perf_counter() + 10
    while True:
        try:
            assert client.healthz()["status"] == "ok"
            break
        except OSError:
            if time.perf_counter() > deadline:
                raise AssertionError("server never came up")
            time.sleep(0.05)
    r = client.correct("@a\nAC\n+\nII\n@b\nGT\n+\nII\n")
    assert r.status == 200 and r.reads == 2
    client.quiesce()
    t.join(timeout=15)
    assert not t.is_alive()
    assert rc_box["rc"] == 0
    with open(metrics_path) as f:
        doc = json.load(f)
    assert validate_metrics(doc) == []
    assert doc["meta"]["stage"] == "serve"
    assert doc["meta"]["status"] == "ok"
    for c in ("requests_accepted", "requests_completed"):
        assert doc["counters"].get(c, 0) >= 1, c
    for h in ("queue_wait_us", "request_us", "request_reads"):
        assert h in doc["histograms"], h


def test_serve_sigterm_drains_and_writes_metrics(tmp_path):
    """Acceptance: a REAL SIGTERM (subprocess, signal handler on the
    main thread) drains cleanly — exit 0 and a final metrics document
    with status=ok. The engine is stubbed in the child so the test
    exercises the signal/drain path, not compilation."""
    import signal
    import subprocess
    import sys as _sys

    port = _free_port()
    metrics_path = str(tmp_path / "serve.json")
    child_src = f"""
import sys
sys.path.insert(0, {repr(os.path.dirname(HERE))!s})
import quorum_tpu.serve as serve_pkg

class FE:
    def __init__(self, rows=1024):
        self.rows = rows
    compiles = 0
    def step(self, records):
        return [(">%s\\n%s\\n" % (h, s.decode()), "")
                for h, s, _q in records]

serve_pkg.CorrectionEngine = lambda db, **kw: FE(kw.get("rows", 1024))
from quorum_tpu.cli import serve as serve_cli
sys.exit(serve_cli.main(["--port", "{port}", "--max-wait-ms", "0",
                         "--metrics", {repr(metrics_path)!s},
                         "ignored.jf"]))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([_sys.executable, "-c", child_src], env=env,
                            stderr=subprocess.PIPE)
    try:
        client = ServeClient(port=port)
        deadline = time.perf_counter() + 60
        while True:
            try:
                client.healthz()
                break
            except OSError:
                if proc.poll() is not None:
                    raise AssertionError(
                        "child died: "
                        + proc.stderr.read().decode(errors="replace"))
                assert time.perf_counter() < deadline, "never came up"
                time.sleep(0.1)
        r = client.correct("@a\nACGT\n+\nIIII\n")
        assert r.status == 200 and r.reads == 1
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == 0, proc.stderr.read().decode(errors="replace")
    finally:
        if proc.poll() is None:
            proc.kill()
    with open(metrics_path) as f:
        doc = json.load(f)
    assert validate_metrics(doc) == []
    assert doc["meta"]["status"] == "ok"
    assert doc["meta"]["drained"] is True
    assert doc["counters"]["requests_completed"] >= 1


def test_serve_bench_closed_loop(capsys):
    """quorum-serve-bench against a fake-engine server: closed loop
    completes, prints one schema-valid bench metric line."""
    from quorum_tpu.telemetry import validate_bench_line

    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=32, max_wait_ms=1,
                         queue_requests=16, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        rc = bench_main(["--port", str(srv.port), "-c", "3", "-n", "9",
                         "-r", "4", READS])
    finally:
        srv.close()
    assert rc == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    by_metric = {o["metric"]: o for o in lines}
    obj = by_metric["serve_bench"]
    assert validate_bench_line(obj) == []
    assert obj["ok"] == 9 and obj["reads"] == 36
    assert obj["latency_p50_ms"] > 0
    # the server-side phase breakdown rides a second metric line
    # (ISSUE 10): every 200 carried X-Quorum-Phases
    ph = by_metric["serve_bench_phases"]
    assert validate_bench_line(ph) == []
    assert ph["requests"] == 9
    assert ph["total_mean_ms"] > 0
    assert ph["device_mean_ms"] >= 0
    assert 0.0 <= ph.get("device_share", 0.0) <= 1.0


# ---------------------------------------------------------------------------
# serve fault isolation (ISSUE 4): poisoned batches, healthz flip,
# dispatcher-death future failing
# ---------------------------------------------------------------------------

class PoisonEngine:
    """Engine-shaped stub that raises whenever a batch contains a
    record whose header is 'poison' — a deterministic device-step
    failure localized to one request."""

    def __init__(self, rows=1024):
        self.rows = rows
        self.steps = 0

    compiles = 0

    def step(self, records):
        self.steps += 1
        if any(h == "poison" for h, _s, _q in records):
            raise RuntimeError("poisoned batch")
        return [(f">{h}\n{s.decode()}\n", "") for h, s, _q in records]


def test_poisoned_batch_bisection_isolates_request():
    """Acceptance: a device-step exception fails only its own batch —
    and with bisection, only the poisoned REQUEST: its batchmate still
    gets its answer, the dispatcher survives, later requests succeed."""
    reg = registry_for(None, force=True)
    eng = PoisonEngine()
    bat = DynamicBatcher(eng, max_batch=8, max_wait_ms=100,
                         queue_requests=8,
                         max_consecutive_failures=3, registry=reg)
    try:
        good = bat.submit([("good", b"ACGT", b"IIII")])
        poison = bat.submit([("poison", b"ACGT", b"IIII")])
        # the coalesced batch fails; the bisect retry isolates halves
        assert good.result(timeout=10) == [(">good\nACGT\n", "")]
        with pytest.raises(RuntimeError, match="poisoned"):
            poison.result(timeout=10)
        assert reg.counter("batch_bisections").value == 1
        assert reg.counter("requests_failed").value == 1
        assert reg.counter("engine_step_failures").value >= 1
        # the dispatcher is alive and healthy: a half succeeded, so
        # the consecutive-failure streak reset
        later = bat.submit([("later", b"GG", b"II")])
        assert later.result(timeout=10) == [(">later\nGG\n", "")]
        assert bat.healthy
    finally:
        bat.drain(timeout=5)


def test_consecutive_failures_flip_healthz_and_recover():
    """After --max-consecutive-failures device-step failures in a row
    /healthz answers 503 (load balancers eject the replica); a
    successful step flips it back."""
    reg = registry_for(None, force=True)
    eng = PoisonEngine()
    bat = DynamicBatcher(eng, max_batch=8, max_wait_ms=0,
                         queue_requests=8,
                         max_consecutive_failures=2, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        client = ServeClient(port=srv.port)
        code, h = client.healthz_full()
        assert code == 200 and h["status"] == "ok" and h["healthy"]
        # two single-request poisoned batches: no bisection (nothing
        # to isolate), two consecutive engine failures
        for _ in range(2):
            f = bat.submit([("poison", b"ACGT", b"IIII")])
            with pytest.raises(RuntimeError):
                f.result(timeout=10)
        code, h = client.healthz_full()
        assert code == 503
        assert h["status"] == "unhealthy" and not h["healthy"]
        assert h["consecutive_failures"] == 2
        # the HTTP surface still isolates the failure per request:
        # a good request succeeds AND heals the streak
        r = client.correct("@ok\nACGT\n+\nIIII\n")
        assert r.status == 200 and r.fa == ">ok\nACGT\n"
        code, h = client.healthz_full()
        assert code == 200 and h["status"] == "ok"
        # a poisoned HTTP request maps to 500, later requests fine
        r = client.correct("@poison\nACGT\n+\nIIII\n")
        assert r.status == 500 and "poisoned" in r.error
        r = client.correct("@ok2\nAC\n+\nII\n")
        assert r.status == 200
    finally:
        srv.close()


def test_dispatcher_death_fails_queued_futures(monkeypatch):
    """Satellite fix: ANY dispatcher exit path must fail queued
    futures immediately — before this, a dead dispatcher stranded
    clients until their deadline."""
    reg = registry_for(None, force=True)
    gate = threading.Event()
    eng = FakeEngine(gate)
    bat = DynamicBatcher(eng, max_batch=4, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    recs = [("r", b"ACGT", b"IIII")]
    f1 = bat.submit(recs)              # dispatched, blocked in engine
    assert eng.entered.wait(5)
    _drain_to_depth(bat, 0)
    f2 = bat.submit(recs)              # queued behind the blocked step

    # kill the dispatch loop itself (outside the per-batch watchdog)
    def boom():
        raise AssertionError("dispatch loop bug")

    monkeypatch.setattr(bat, "_take_locked", boom)
    gate.set()
    assert f1.result(timeout=10)       # in-flight work still resolves
    with pytest.raises(RuntimeError, match="dispatcher exited"):
        f2.result(timeout=10)          # queued future fails FAST
    bat._thread.join(timeout=5)
    assert not bat._thread.is_alive()
    assert not bat.healthy
    assert reg.counter("dispatcher_crashes").value == 1
    with pytest.raises(Draining):
        bat.submit(recs)               # admission refused, not hung


def test_drained_batcher_refuses_politely():
    """A cleanly-drained replica is not "unhealthy": /healthz keeps
    answering 200 with status=draining (it finished what it admitted;
    it needs patience, not ejection), and admission raises Draining."""
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=4, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        srv.initiate_drain()
        assert srv._drained.wait(timeout=5)
        assert not bat.healthy  # the batcher itself reports done
        code, h = ServeClient(port=srv.port).healthz_full()
        assert code == 200 and h["status"] == "draining"
        with pytest.raises(Draining):
            bat.submit([("r", b"A", b"I")])
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# the observability() context manager (ISSUE 3 satellite)
# ---------------------------------------------------------------------------

def test_observability_error_stamp(tmp_path):
    from quorum_tpu.cli.observability import observability

    path = str(tmp_path / "m.json")
    with pytest.raises(RuntimeError):
        with observability(path, stage="boom"):
            raise RuntimeError("kaboom")
    with open(path) as f:
        doc = json.load(f)
    assert doc["meta"]["status"] == "error"
    assert doc["meta"]["stage"] == "boom"


def test_observability_rc_status_and_at_exit(tmp_path):
    from quorum_tpu.cli.observability import observability

    path = str(tmp_path / "m.json")
    with observability(path) as obs:
        obs.registry.counter("things").inc(3)
        obs.at_exit(lambda reg: reg.gauge("derived").set(7))
        obs.status = "error"   # rc-style failure without an exception
    with open(path) as f:
        doc = json.load(f)
    assert doc["meta"]["status"] == "error"
    assert doc["counters"]["things"] == 3
    assert doc["gauges"]["derived"] == 7


def test_observability_respects_body_write(tmp_path):
    """A body that already stamped status=ok and wrote (the
    run_error_correct success path) is left alone — no second
    write clobbers post-write mutations."""
    from quorum_tpu.cli.observability import observability

    path = str(tmp_path / "m.json")
    with observability(path) as obs:
        obs.registry.counter("n").inc()
        obs.registry.set_meta(status="ok")
        obs.registry.write()
        obs.registry.counter("n").inc()  # after the write: must NOT land
    with open(path) as f:
        doc = json.load(f)
    assert doc["counters"]["n"] == 1


def test_observability_null_when_disabled():
    from quorum_tpu.cli.observability import observability

    with observability() as obs:
        assert not obs.registry.enabled
        assert not getattr(obs.tracer, "enabled", False)
        assert obs.server is None


# ---------------------------------------------------------------------------
# serve resilience (ISSUE 7): watchdog, hedging, priority lanes,
# quotas, hot reload, and the races between them
# ---------------------------------------------------------------------------

class HangEngine(FakeEngine):
    """Engine-shaped stub whose step wedges forever (until `release`)
    when any record's header is 'hang' — the watchdog acceptance
    case."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.release = threading.Event()

    def step(self, records):
        self.entered.set()
        if any(h == "hang" for h, _s, _q in records):
            self.release.wait(timeout=60)
            raise RuntimeError("hung step released by test teardown")
        self.stepped += 1
        return [(f">{h}\n{s.decode()}\n", "") for h, s, _q in records]


def test_watchdog_contains_hung_step_and_restarts_engine():
    """Acceptance: a hung engine step fails only its batch
    (EngineStepTimeout), engine_restarts_total increments, and the
    next request succeeds on the rebuilt engine."""
    reg = registry_for(None, force=True)
    hung = HangEngine()
    fresh = FakeEngine()
    bat = DynamicBatcher(hung, max_batch=8, max_wait_ms=0,
                         queue_requests=8, step_timeout_ms=150,
                         engine_factory=lambda old: fresh, registry=reg)
    try:
        f = bat.submit([("hang", b"ACGT", b"IIII")])
        with pytest.raises(EngineStepTimeout):
            f.result(timeout=10)
        assert reg.counter("engine_step_timeouts").value == 1
        assert reg.counter("engine_restarts_total").value == 1
        assert bat.current_engine() is fresh
        assert bat.generation == 1
        ok = bat.submit([("ok", b"AC", b"II")])
        assert ok.result(timeout=10) == [(">ok\nAC\n", "")]
        assert bat.healthy
    finally:
        hung.release.set()
        bat.drain(timeout=5)


def test_watchdog_survives_wedged_rebuild():
    """Review hardening: if even the engine REBUILD hangs (the
    device/compiler is truly wedged), the dispatcher abandons it too
    instead of re-wedging on the cure — the old engine stays, later
    steps keep timing out, and the failure streak flips /healthz."""
    reg = registry_for(None, force=True)
    hung = HangEngine()
    release_build = threading.Event()

    def wedged_factory(_old):
        release_build.wait(timeout=60)
        return FakeEngine()

    bat = DynamicBatcher(hung, max_batch=8, max_wait_ms=0,
                         queue_requests=8, step_timeout_ms=100,
                         max_consecutive_failures=2,
                         engine_factory=wedged_factory, registry=reg)
    bat.rebuild_timeout_s = 0.2
    try:
        for _ in range(2):
            f = bat.submit([("hang", b"ACGT", b"IIII")])
            with pytest.raises(EngineStepTimeout):
                f.result(timeout=10)
        assert reg.counter("engine_rebuild_failures").value == 2
        assert reg.counter("engine_restarts_total").value == 0
        assert bat.current_engine() is hung   # old engine kept
        assert not bat.healthy                # streak flipped healthz
    finally:
        release_build.set()
        hung.release.set()
        bat.drain(timeout=5)


def test_watchdog_fires_during_bisection_retry():
    """Race satellite: the batch step hangs (watchdog restart #1),
    the bisect solo retry of the hung request hangs AGAIN on the
    rebuilt engine (watchdog restart #2), and the innocent batchmate
    still gets its answer from the latest engine."""
    reg = registry_for(None, force=True)
    first = HangEngine()
    spawned: list[HangEngine] = []

    def factory(_old):
        e = HangEngine()
        spawned.append(e)
        return e

    bat = DynamicBatcher(first, max_batch=8, max_wait_ms=200,
                         queue_requests=8, step_timeout_ms=150,
                         engine_factory=factory, registry=reg)
    try:
        bad = bat.submit([("hang", b"ACGT", b"IIII")])
        good = bat.submit([("good", b"AC", b"II")])
        # coalesced batch hangs -> restart; bisect: [hang] hangs ->
        # restart again; [good] succeeds on the newest engine
        assert good.result(timeout=20) == [(">good\nAC\n", "")]
        with pytest.raises(EngineStepTimeout):
            bad.result(timeout=20)
        assert reg.counter("engine_restarts_total").value == 2
        assert reg.counter("batch_bisections").value == 1
        assert bat.generation == 2
    finally:
        first.release.set()
        for e in spawned:
            e.release.set()
        bat.drain(timeout=5)


def test_hedging_isolates_innocent_batchmates():
    """Acceptance: when a failed batch bisects ambiguously (a failing
    half with >1 request), the survivors are re-run solo — the
    innocent batchmate of a poisoned request never eats a 500."""
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(PoisonEngine(), max_batch=8, max_wait_ms=200,
                         queue_requests=8, max_hedges=8, registry=reg)
    try:
        a = bat.submit([("a", b"AC", b"II")])
        b = bat.submit([("b", b"AC", b"II")])
        c = bat.submit([("poison", b"AC", b"II")])
        d = bat.submit([("d", b"AC", b"II")])
        # one coalesced batch [a,b,poison,d]: fails; half [a,b] ok;
        # half [poison,d] fails again -> hedge solo: poison fails,
        # d succeeds
        assert a.result(timeout=10) == [(">a\nAC\n", "")]
        assert b.result(timeout=10) == [(">b\nAC\n", "")]
        with pytest.raises(RuntimeError, match="poisoned"):
            c.result(timeout=10)
        assert d.result(timeout=10) == [(">d\nAC\n", "")]
        assert reg.counter("batch_bisections").value == 1
        assert reg.counter("hedges_total").value == 2
        assert reg.counter("requests_failed").value == 1
    finally:
        bat.drain(timeout=5)


def test_hedge_budget_exhausted_fails_remainder():
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(PoisonEngine(), max_batch=8, max_wait_ms=200,
                         queue_requests=8, max_hedges=1, registry=reg)
    try:
        a = bat.submit([("a", b"AC", b"II")])
        b = bat.submit([("b", b"AC", b"II")])
        c = bat.submit([("poison", b"AC", b"II")])
        d = bat.submit([("d", b"AC", b"II")])
        assert a.result(timeout=10) and b.result(timeout=10)
        with pytest.raises(RuntimeError, match="poisoned"):
            c.result(timeout=10)
        # d was innocent but the single hedge went to the poisoned
        # request: d fails with the half's original error
        with pytest.raises(RuntimeError, match="poisoned"):
            d.result(timeout=10)
        assert reg.counter("hedges_total").value == 1
        assert reg.counter("requests_failed").value == 2
    finally:
        bat.drain(timeout=5)


class OrderEngine(FakeEngine):
    """FakeEngine that records the header order of stepped reads."""

    def __init__(self, gate=None, **kw):
        super().__init__(gate=gate, **kw)
        self.order: list[str] = []

    def step(self, records):
        res = super().step(records)
        self.order.extend(h for h, _s, _q in records)
        return res


def test_priority_lanes_weighted_pop_under_full_queue():
    """Race satellite: with both lanes full, interactive requests pop
    ahead of a bulk backlog at `interactive_weight` per bulk pop —
    bulk drains at a guaranteed floor, interactive never starves."""
    reg = registry_for(None, force=True)
    gate = threading.Event()
    eng = OrderEngine(gate)
    bat = DynamicBatcher(eng, max_batch=1, max_wait_ms=0,
                         queue_requests=32, interactive_weight=2,
                         registry=reg)
    try:
        r0 = bat.submit([("r0", b"A", b"I")])   # occupies the engine
        assert eng.entered.wait(5)
        _drain_to_depth(bat, 0)
        bulk = [bat.submit([(f"b{i}", b"A", b"I")], priority="bulk")
                for i in range(4)]
        inter = [bat.submit([(f"i{i}", b"A", b"I")]) for i in range(4)]
        gate.set()
        for f in [r0] + bulk + inter:
            assert f.result(timeout=10)
        # pops 1..8 with weight 2 (pop 0 was r0):
        # i0, b0, i1, i2, b1, i3, b2, b3
        assert eng.order == ["r0", "i0", "b0", "i1", "i2", "b1",
                             "i3", "b2", "b3"]
        with pytest.raises(ValueError, match="unknown priority"):
            bat.submit([("x", b"A", b"I")], priority="urgent")
    finally:
        bat.drain(timeout=5)


def test_swap_engine_conditional_on_generation():
    """Review hardening: a watchdog rebuild that raced a /reload must
    not clobber the reload's fresher engine — the conditional swap
    drops the stale replacement."""
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=8, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    try:
        e1, e2 = FakeEngine(), FakeEngine()
        gen0 = bat.generation
        assert bat.swap_engine(e1) == gen0 + 1    # the /reload lands
        # the watchdog rebuild captured gen0 before the reload: stale
        assert bat.swap_engine(e2, expected_generation=gen0) == -1
        assert bat.current_engine() is e1
        assert bat.generation == gen0 + 1
    finally:
        bat.drain(timeout=5)


def test_no_hedging_after_watchdog_timeout():
    """Review hardening: a half that fails with EngineStepTimeout is
    NOT hedged — each solo hedge of a deterministically-hanging
    request would cost a full step-timeout + rebuild with the
    dispatcher blocked. The half fails fast instead."""
    reg = registry_for(None, force=True)
    first = HangEngine()
    spawned: list[HangEngine] = []

    def factory(_old):
        e = HangEngine()
        spawned.append(e)
        return e

    bat = DynamicBatcher(first, max_batch=8, max_wait_ms=200,
                         queue_requests=8, step_timeout_ms=150,
                         engine_factory=factory, max_hedges=8,
                         registry=reg)
    try:
        a = bat.submit([("a", b"AC", b"II")])
        b = bat.submit([("b", b"AC", b"II")])
        c = bat.submit([("hang", b"AC", b"II")])
        d = bat.submit([("d", b"AC", b"II")])
        # batch [a,b,hang,d] times out; half [a,b] succeeds; half
        # [hang,d] times out AGAIN -> fails fast, NO solo hedging
        assert a.result(timeout=20) and b.result(timeout=20)
        with pytest.raises(EngineStepTimeout):
            c.result(timeout=20)
        with pytest.raises(EngineStepTimeout):
            d.result(timeout=20)
        assert reg.counter("hedges_total").value == 0
        assert reg.counter("engine_restarts_total").value == 2
    finally:
        first.release.set()
        for e in spawned:
            e.release.set()
        bat.drain(timeout=5)


def test_token_bucket_quota_lru_eviction():
    clock = [0.0]
    q = TokenBucketQuota(1.0, burst=2, max_clients=3,
                         clock=lambda: clock[0])
    for name in ("a", "b", "c"):
        assert q.admit(name)[0]
    assert q.admit("a")[0]        # refreshes a's LRU position
    assert q.admit("d")[0]        # evicts the oldest (b), not a
    assert q.clients == 3
    assert not q.admit("a")[0]    # a kept its drained bucket (0 left)
    assert q.admit("b")[0]        # b re-enters with a FRESH bucket
    with pytest.raises(ValueError):
        TokenBucketQuota(1.0, burst=0.5)


def test_token_bucket_quota_semantics():
    clock = [0.0]
    q = TokenBucketQuota(2.0, burst=2, clock=lambda: clock[0])
    assert q.admit("a") == (True, 0.0)
    assert q.admit("a") == (True, 0.0)
    ok, retry = q.admit("a")
    assert not ok and retry == pytest.approx(0.5)  # 1 token at 2/s
    assert q.admit("b")[0]          # other clients unaffected
    clock[0] += 0.6
    assert q.admit("a")[0]          # refilled
    with pytest.raises(ValueError):
        TokenBucketQuota(0)


def test_quota_rejects_greedy_client_and_refills():
    clock = [0.0]
    quota = TokenBucketQuota(1.0, burst=2, clock=lambda: clock[0])
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=8, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg, quota=quota)
    try:
        client = ServeClient(port=srv.port)
        body = "@r\nACGT\n+\nIIII\n"
        assert client.correct(body, client_id="alice").status == 200
        assert client.correct(body, client_id="alice").status == 200
        r = client.correct(body, client_id="alice")
        assert r.status == 429
        assert r.retry_after_s >= 1          # Retry-After header
        assert "quota" in r.error
        assert reg.counter("quota_rejections_total").value == 1
        # a different client and an anonymous request are unaffected
        assert client.correct(body, client_id="bob").status == 200
        assert client.correct(body).status == 200
        clock[0] += 1.5                      # tokens refill
        assert client.correct(body, client_id="alice").status == 200
    finally:
        srv.close()


def test_reload_swaps_engine_and_rolls_back_stub():
    """The /reload orchestration with a stub builder: a good reload
    swaps generations; ValueError -> 400, any other failure -> 500,
    and both leave the old engine answering."""
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=8, max_wait_ms=0,
                         queue_requests=8, registry=reg)

    class Tagged(FakeEngine):
        def __init__(self, tag):
            super().__init__()
            self.tag = tag

        def step(self, records):
            self.stepped += 1
            return [(f">{self.tag}:{h}\n", "") for h, _s, _q in records]

    def builder(params):
        if params.get("boom"):
            raise ValueError("bad db header")
        if params.get("crash"):
            raise RuntimeError("build exploded")
        return Tagged(params.get("tag", "new"))

    srv = CorrectionServer(bat, port=0, registry=reg,
                           engine_builder=builder)
    try:
        client = ServeClient(port=srv.port)
        body = "@r\nACGT\n+\nIIII\n"
        assert client.correct(body).fa == ">r\nACGT\n"   # boot engine
        code, doc = client.reload({"tag": "g1"})
        assert code == 200 and doc["generation"] == 1
        assert client.correct(body).fa == ">g1:r\n"      # new engine
        code, doc = client.reload({"boom": 1})
        assert code == 400 and doc["rolled_back"] is True
        assert doc["generation"] == 1
        code, doc = client.reload({"crash": 1})
        assert code == 500 and doc["rolled_back"] is True
        assert client.correct(body).fa == ">g1:r\n"      # still g1
        assert reg.counter("reload_total").value == 1
        assert reg.counter("reload_failures_total").value == 2
        # an injected serve.reload fault rolls back the same way
        faults.install(faults.FaultPlan.parse(
            {"site": "serve.reload", "action": "error"}), "t-reload")
        try:
            code, doc = client.reload({"tag": "g2"})
        finally:
            faults.reset()
        assert code == 500 and doc["rolled_back"] is True
        assert client.correct(body).fa == ">g1:r\n"
    finally:
        faults.reset()
        srv.close()


def test_reload_unconfigured_answers_501():
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=8, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        code, doc = ServeClient(port=srv.port).reload({})
        assert code == 501 and "not configured" in doc["error"]
    finally:
        srv.close()


def test_reload_races_sigterm_drain():
    """Race satellite: /reload mid-build while a SIGTERM drain starts.
    Both complete without deadlock; the reload answers 200 (swap won
    the race) or 503 (drain won), and the server drains cleanly
    either way."""
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=8, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    building = threading.Event()

    def slow_builder(_params):
        building.set()
        time.sleep(0.3)
        return FakeEngine()

    srv = CorrectionServer(bat, port=0, registry=reg,
                           drain_grace_s=5.0,
                           engine_builder=slow_builder)
    try:
        client = ServeClient(port=srv.port)
        box = {}

        def do_reload():
            box["code"], box["doc"] = client.reload({})

        t = threading.Thread(target=do_reload, daemon=True)
        t.start()
        assert building.wait(5)          # reload is mid-build
        srv.initiate_drain()             # the SIGTERM path
        t.join(timeout=10)
        assert not t.is_alive()
        assert box["code"] in (200, 503)
        assert srv._drained.wait(5)
        # post-drain: both endpoints refuse politely
        code, _doc = client.reload({})
        assert code == 503
        assert client.correct("@r\nAC\n+\nII\n").status == 503
    finally:
        srv.close()


def test_admit_fault_site_maps_to_retryable_503():
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=8, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        faults.install(faults.FaultPlan.parse(
            {"site": "serve.admit", "action": "error"}), "t-admit")
        r = ServeClient(port=srv.port).correct("@r\nAC\n+\nII\n")
        assert r.status == 503 and r.retry_after_s >= 1
        assert reg.counter("requests_rejected_admission").value == 1
        faults.reset()
        r = ServeClient(port=srv.port).correct("@r\nAC\n+\nII\n")
        assert r.status == 200
    finally:
        faults.reset()
        srv.close()


def test_correct_with_retry_honors_retry_after(monkeypatch):
    client = ServeClient(port=1)
    replies = [ServeResult(status=429, retry_after_s=2.0),
               ServeResult(status=503, retry_after_s=0.0),
               ServeResult(status=200, fa="ok")]
    calls = []

    def fake_correct(_body, deadline_ms=None, want_log=False,
                     priority=None, client_id=None, gzip_body=False):
        calls.append(1)
        return replies[len(calls) - 1]

    monkeypatch.setattr(client, "correct", fake_correct)
    sleeps: list[float] = []
    res = client.correct_with_retry("x", base_backoff_s=0.1,
                                    sleep=sleeps.append)
    assert res.status == 200 and res.fa == "ok"
    assert sleeps[0] == 2.0   # the server's Retry-After hint wins
    assert sleeps[1] == pytest.approx(0.2)  # no hint -> exponential


def test_correct_with_retry_caps_and_gives_up(monkeypatch):
    client = ServeClient(port=1)
    monkeypatch.setattr(
        client, "correct",
        lambda *_a, **_k: ServeResult(status=429, retry_after_s=0.0))
    sleeps: list[float] = []
    res = client.correct_with_retry("x", max_attempts=3,
                                    base_backoff_s=0.5,
                                    max_backoff_s=0.6,
                                    sleep=sleeps.append)
    assert res.status == 429
    assert sleeps == [0.5, 0.6]   # capped exponential: 0.5 then 0.6


def test_serve_bench_retry_flag(capsys):
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=32, max_wait_ms=1,
                         queue_requests=16, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        rc = bench_main(["--port", str(srv.port), "-c", "2", "-n", "6",
                         "-r", "3", "--retry", "--priority", "bulk",
                         "--client-id", "bench", READS])
    finally:
        srv.close()
    assert rc == 0
    by_metric = {o["metric"]: o for o in
                 (json.loads(ln) for ln in
                  capsys.readouterr().out.strip().splitlines())}
    obj = by_metric["serve_bench"]
    assert obj["ok"] == 6 and obj["reads"] == 18


def test_serve_cli_resilience_flags_and_meta(tmp_path, monkeypatch):
    """The quorum-serve resilience flags land in the final metrics
    document's meta (what metrics_check dispatches on) with the
    feature counters present at 0 from setup."""
    import quorum_tpu.serve as serve_pkg

    monkeypatch.setattr(serve_pkg, "CorrectionEngine",
                        lambda db, **kw: FakeEngine(
                            rows=kw.get("rows", 1024)))
    port = _free_port()
    metrics_path = str(tmp_path / "serve.json")
    rc_box = {}

    def run():
        rc_box["rc"] = serve_cli.main(
            ["--port", str(port), "--max-wait-ms", "0",
             "--max-batch", "8", "--step-timeout-ms", "5000",
             "--quota-rps", "100", "--metrics", metrics_path,
             "ignored.jf"])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    client = ServeClient(port=port)
    deadline = time.perf_counter() + 10
    while True:
        try:
            client.healthz()
            break
        except OSError:
            assert time.perf_counter() < deadline, "never came up"
            time.sleep(0.05)
    r = client.correct("@a\nAC\n+\nII\n", priority="bulk",
                       client_id="c1")
    assert r.status == 200 and r.reads == 1
    assert client.correct("@a\nAC\n+\nII\n",
                          priority="urgent").status == 400
    client.quiesce()
    t.join(timeout=15)
    assert rc_box["rc"] == 0
    with open(metrics_path) as f:
        doc = json.load(f)
    assert validate_metrics(doc) == []
    assert doc["meta"]["step_timeout_ms"] == 5000
    assert doc["meta"]["max_hedges"] == 8
    assert doc["meta"]["quota_rps"] == 100
    assert doc["meta"]["reload"] is True
    for c in ("engine_restarts_total", "hedges_total", "reload_total",
              "quota_rejections_total"):
        assert doc["counters"].get(c) == 0, c


def test_metrics_check_serve_feature_names():
    import importlib.util
    repo = os.path.dirname(HERE)
    spec = importlib.util.spec_from_file_location(
        "metrics_check", os.path.join(repo, "tools", "metrics_check.py"))
    mc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mc)

    counters = {c: 0 for c in mc.SERVE_REQUIRED_COUNTERS}
    hists = {h: {"count": 0, "sum": 0, "counts": {}}
             for h in mc.SERVE_REQUIRED_HISTOGRAMS}
    doc = {"meta": {"stage": "serve", "step_timeout_ms": 500,
                    "max_hedges": 8, "reload": True, "quota_rps": 10},
           "counters": dict(counters), "histograms": hists}
    errs = mc._check_serve_names(doc)
    assert len(errs) == 4
    for name in ("engine_restarts_total", "hedges_total",
                 "reload_total", "quota_rejections_total"):
        assert any(name in e for e in errs), name
    doc["counters"].update({"engine_restarts_total": 0,
                            "hedges_total": 2, "reload_total": 1,
                            "quota_rejections_total": 0})
    assert mc._check_serve_names(doc) == []
    # undeclared or zero-valued features require nothing
    off = {"meta": {"stage": "serve", "max_hedges": 0},
           "counters": dict(counters), "histograms": hists}
    assert mc._check_serve_names(off) == []


# ---------------------------------------------------------------------------
# request-scoped tracing (ISSUE 10): ids, phases, lifecycle events,
# per-lane series
# ---------------------------------------------------------------------------

def test_request_id_echo_unique_and_phase_sums(tmp_path):
    """Every 200 echoes X-Quorum-Request-Id (unique when the client
    sent none, verbatim when it did) and carries X-Quorum-Phases whose
    disjoint phase durations sum to <= the end-to-end latency; each
    terminal status emits one schema-valid `request` lifecycle
    event."""
    from quorum_tpu.telemetry import validate_events_line

    evts = str(tmp_path / "events.jsonl")
    reg = registry_for(None, events_path=evts)
    bat = DynamicBatcher(FakeEngine(), max_batch=8, max_wait_ms=1,
                         queue_requests=8, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        client = ServeClient(port=srv.port)
        t0 = time.perf_counter()
        r1 = client.correct("@a\nACGT\n+\nIIII\n")
        e2e_us = (time.perf_counter() - t0) * 1e6
        r2 = client.correct("@b\nAC\n+\nII\n")
        r3 = client.correct("@c\nAC\n+\nII\n", request_id="my-trace-7")
        assert r1.status == r2.status == r3.status == 200
        assert r1.request_id and r2.request_id
        assert r1.request_id != r2.request_id  # unique when absent
        assert r3.request_id == "my-trace-7"   # verbatim when given
        ph = r1.phases
        assert ph is not None and ph["lane"] == "interactive"
        parts = (ph["admission_us"] + ph["queue_us"] + ph["device_us"]
                 + ph["hedge_us"] + ph["render_us"])
        assert 0 <= parts <= ph["total_us"] <= e2e_us
        assert not ph["bisected"] and not ph["hedged"]
    finally:
        srv.close()
    with open(evts) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    reqs = [o for o in lines if o.get("event") == "request"]
    assert len(reqs) == 3
    for o in reqs:
        assert validate_events_line(o) == []
        assert o["status"] == 200
        assert (o["admission_us"] + o["queue_us"] + o["device_us"]
                + o["hedge_us"] + o["render_us"]) <= o["total_us"]
    assert ({o["request_id"] for o in reqs}
            == {r1.request_id, r2.request_id, "my-trace-7"})


def test_request_id_echoed_on_429_504_500(tmp_path):
    """Rejections carry the trace id too: 429 (queue full), 504
    (deadline), 500 (engine failure) all echo X-Quorum-Request-Id and
    land lifecycle events with the terminal status."""
    evts = str(tmp_path / "events.jsonl")
    reg = registry_for(None, events_path=evts)
    gate = threading.Event()
    eng = FakeEngine(gate)
    bat = DynamicBatcher(eng, max_batch=4, max_wait_ms=0,
                         queue_requests=1, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg,
                           drain_grace_s=5.0)
    body = "@r\nACGT\n+\nIIII\n"
    try:
        client = ServeClient(port=srv.port)
        # occupy the engine so later requests queue behind it
        t1 = threading.Thread(
            target=lambda: ServeClient(port=srv.port).correct(
                body, request_id="rid-held"), daemon=True)
        t1.start()
        assert eng.entered.wait(5)
        _drain_to_depth(bat, 0)
        # B fills the one-slot queue and expires -> 504, id echoed
        box = {}

        def post_b():
            # the deadline is the race window for the 429 probe below:
            # B must still occupy the slot when the probe's POST lands,
            # so keep it well above a loaded-machine HTTP round trip
            box["b"] = ServeClient(port=srv.port).correct(
                body, deadline_ms=2000, request_id="rid-504")

        t2 = threading.Thread(target=post_b, daemon=True)
        t2.start()
        # wait for B to OCCUPY the slot (depth >= 1), not merely for
        # depth <= 1 — before B's POST lands the depth is 0 and the
        # 429 probe below would steal the slot instead of bouncing
        t0 = time.perf_counter()
        while bat.depth < 1:
            assert time.perf_counter() - t0 < 5, "B never queued"
            time.sleep(0.005)
        r429 = client.correct(body, request_id="rid-429")
        assert r429.status == 429 and r429.request_id == "rid-429"
        t2.join(timeout=10)
        assert not t2.is_alive()
        assert box["b"].status == 504
        assert box["b"].request_id == "rid-504"
        gate.set()
        t1.join(timeout=10)
    finally:
        gate.set()
        srv.close()
    with open(evts) as f:
        by_rid = {o["request_id"]: o for ln in f if ln.strip()
                  for o in [json.loads(ln)] if o.get("event") == "request"}
    assert by_rid["rid-429"]["status"] == 429
    assert by_rid["rid-504"]["status"] == 504
    assert by_rid["rid-held"]["status"] == 200


def test_bisect_hedge_events_carry_victim_request_ids(tmp_path):
    """A bisected batch's event lists every rider's request id and
    each solo hedge's event names its victim; the survivors' phase
    ledgers mark bisected/hedged with the hedge time separated from
    the device time."""
    from quorum_tpu.telemetry import validate_events_line

    evts = str(tmp_path / "events.jsonl")
    reg = registry_for(None, events_path=evts)
    bat = DynamicBatcher(PoisonEngine(), max_batch=8, max_wait_ms=150,
                         queue_requests=8, max_hedges=8, registry=reg)
    try:
        # one coalesced batch of four: the first bisect half
        # [poison, a] fails again ambiguously -> both hedged solo;
        # the second half [b, c] succeeds in one pass
        fp = bat.submit([("poison", b"ACGT", b"IIII")],
                        request_id="rid-p")
        fa = bat.submit([("a", b"AC", b"II")], request_id="rid-a")
        fb = bat.submit([("b", b"AC", b"II")], request_id="rid-b")
        fc = bat.submit([("c", b"AC", b"II")], request_id="rid-c")
        with pytest.raises(RuntimeError, match="poisoned"):
            fp.result(timeout=15)
        assert fa.result(timeout=15) == [(">a\nAC\n", "")]
        assert fb.result(timeout=15) == [(">b\nAC\n", "")]
        assert fc.result(timeout=15) == [(">c\nAC\n", "")]
        assert reg.counter("batch_bisections").value == 1
        assert reg.counter("hedges_total").value == 2
        # the survivor's ledger: hedged, with hedge time ledgered
        # apart from the (failed) batch/half device attempts
        req_a = fa.request
        assert req_a.bisected and req_a.hedged
        assert req_a.hedge_us >= 0 and req_a.device_us >= 0
        req_b = fb.request
        assert req_b.bisected and not req_b.hedged
    finally:
        bat.drain(timeout=5)
    with open(evts) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    for o in lines:
        assert validate_events_line(o) == []
    bisects = [o for o in lines if o["event"] == "batch_bisect"]
    assert len(bisects) == 1
    ids = bisects[0]["request_ids"].split(",")
    assert set(ids) == {"rid-p", "rid-a", "rid-b", "rid-c"}
    hedges = [o for o in lines if o["event"] == "hedge"]
    assert {o["request_id"] for o in hedges} == {"rid-p", "rid-a"}


def test_per_lane_depth_and_wait_series():
    """Satellite: queue_depth and lane_wait_us split per lane (the
    summed queue_depth series stays for dashboards), rendered as REAL
    Prometheus labels by the exposition layer, lint-clean."""
    from quorum_tpu.telemetry import export as export_mod
    from quorum_tpu.telemetry import labeled

    gate = threading.Event()
    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(gate), max_batch=4, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    try:
        f1 = bat.submit([("i", b"AC", b"II")], priority="interactive")
        _drain_to_depth(bat, 0)  # i popped; engine now blocked on it
        f2 = bat.submit([("b", b"AC", b"II")], priority="bulk")
        f3 = bat.submit([("i2", b"AC", b"II")], priority="interactive")
        gate.set()
        for f in (f1, f2, f3):
            assert f.result(timeout=10)
    finally:
        bat.drain(timeout=5)
    doc = reg.as_dict()
    # per-lane series exist from setup; bulk saw depth 1
    assert doc["gauges"][labeled("queue_depth", lane="bulk")] >= 1
    assert labeled("queue_depth", lane="interactive") in doc["gauges"]
    assert "queue_depth" in doc["gauges"]  # the summed series stays
    hi = doc["histograms"][labeled("lane_wait_us", lane="interactive")]
    hb = doc["histograms"][labeled("lane_wait_us", lane="bulk")]
    assert hi["count"] == 2 and hb["count"] == 1
    # the embedded label set renders as a real Prometheus label
    text = export_mod.prometheus_text({"serve": doc})
    assert 'lane="bulk"' in text and 'lane="interactive"' in text
    assert export_mod.lint_prometheus_text(text) == []


# ---------------------------------------------------------------------------
# gzip transport (request + response bodies, ISSUE 18)
# ---------------------------------------------------------------------------

def _raw_post(port, path, body, headers):
    """One POST over a fresh connection, no client-side codec help —
    the raw wire view the ServeClient conveniences would hide."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("POST", path, body=body, headers=dict(headers))
        resp = conn.getresponse()
        return resp.status, dict(resp.headers), resp.read()
    finally:
        conn.close()


def test_serve_gzip_request_and_response_round_trip():
    """gzip request bodies decode to the identity answer; responses
    compress only when the client advertises gzip AND the payload
    clears GZIP_MIN_BYTES; ServeClient does both ends transparently."""
    import gzip

    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=64, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        body = "".join(f"@r{i}\nACGTACGT\n+\nIIIIIIII\n"
                       for i in range(64)).encode()
        # gzip request, identity response (no Accept-Encoding sent)
        status, hdrs, want = _raw_post(
            srv.port, "/correct", gzip.compress(body),
            {"Content-Encoding": "gzip"})
        assert status == 200
        assert "Content-Encoding" not in hdrs
        assert want.startswith(b">r0\n")
        # identity request, gzip response (payload > GZIP_MIN_BYTES)
        status, hdrs, data = _raw_post(
            srv.port, "/correct", body, {"Accept-Encoding": "gzip"})
        assert status == 200
        assert hdrs.get("Content-Encoding") == "gzip"
        assert gzip.decompress(data) == want
        # ServeClient compresses the request and inflates the response
        r = ServeClient(port=srv.port).correct(body, gzip_body=True)
        assert r.status == 200
        assert r.fa.encode() == want
        # a tiny response stays identity even when gzip is accepted
        status, hdrs, data = _raw_post(
            srv.port, "/correct", b"@a\nAC\n+\nII\n",
            {"Accept-Encoding": "gzip"})
        assert status == 200
        assert "Content-Encoding" not in hdrs
        assert data == b">a\nAC\n"
    finally:
        srv.close()
        bat.drain(timeout=5)


def test_serve_gzip_rejections(monkeypatch):
    """Bad codings fail closed: garbage/truncated gzip answer 400, an
    unknown Content-Encoding 415, and the body cap applies to the
    DECOMPRESSED size — a small bomb answers 413, not an engine step.
    /ingest and /epoch answer 501 when --ingest was never configured."""
    import gzip

    from quorum_tpu.serve import server as server_mod

    reg = registry_for(None, force=True)
    bat = DynamicBatcher(FakeEngine(), max_batch=64, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    srv = CorrectionServer(bat, port=0, registry=reg)
    try:
        status, _, _ = _raw_post(srv.port, "/correct", b"not gzip",
                                 {"Content-Encoding": "gzip"})
        assert status == 400
        whole = gzip.compress(b"@a\nAC\n+\nII\n" * 64)
        status, _, _ = _raw_post(srv.port, "/correct", whole[:-8],
                                 {"Content-Encoding": "gzip"})
        assert status == 400
        status, _, _ = _raw_post(srv.port, "/correct", b"x",
                                 {"Content-Encoding": "br"})
        assert status == 415
        monkeypatch.setattr(server_mod, "MAX_BODY_BYTES", 4096)
        bomb = gzip.compress(b"@a\nAC\n+\nII\n" * 10000)
        assert len(bomb) < 4096  # small on the wire, huge inflated
        status, _, _ = _raw_post(srv.port, "/correct", bomb,
                                 {"Content-Encoding": "gzip"})
        assert status == 413
        monkeypatch.setattr(server_mod, "MAX_BODY_BYTES",
                            256 * 1024 * 1024)
        for path in ("/ingest", "/epoch"):
            status, _, _ = _raw_post(srv.port, path, b"", {})
            assert status == 501, path
        # the engine never ran for any of the rejected bodies
        assert bat.engine.stepped == 0
    finally:
        srv.close()
        bat.drain(timeout=5)
