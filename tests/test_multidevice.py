"""ISSUE 5: the tile-sharded multi-device path as a PRODUCT surface —
`--devices N` through the real CLIs, byte-identical output vs the
single-chip path, per-shard checkpoint/resume semantics, and the
satellite fixes (PackedReads.nbytes, replay-plane fallback,
host_shard_paths hardening, resolve_devices)."""

import json
import os

import numpy as np
import pytest

import conftest
from quorum_tpu.io import checkpoint as ckpt_mod
from quorum_tpu.io import packing
from quorum_tpu.models.create_database import BuildConfig, BuildStats
from quorum_tpu.parallel import tile_sharded as ts

K = 13
RLEN = 48
BATCH = 32
N_READS = 64


@pytest.fixture(scope="module")
def reads_fastq(tmp_path_factory):
    rng = np.random.default_rng(9)
    genome = rng.integers(0, 4, size=1200, dtype=np.int8)
    starts = rng.integers(0, 1200 - RLEN, size=N_READS)
    codes = genome[starts[:, None] + np.arange(RLEN)[None, :]]
    codes = codes.astype(np.int8)
    err = rng.random(codes.shape) < 0.03
    codes = np.where(err, (codes + rng.integers(1, 4, size=codes.shape))
                     % 4, codes).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    quals[err] = 34
    bases = np.frombuffer(b"ACGT", np.uint8)
    path = tmp_path_factory.mktemp("mdev") / "reads.fastq"
    with open(path, "wb") as f:
        for i in range(N_READS):
            f.write(b"@r%d\n" % i + bases[codes[i]].tobytes()
                    + b"\n+\n" + quals[i].tobytes() + b"\n")
    return str(path)


def _build(reads, out, devices, extra=()):
    from quorum_tpu.cli import create_database as cdb_cli
    rc = cdb_cli.main(["-s", "32k", "-m", str(K), "-b", "7", "-q", "53",
                       "-o", out, "--batch-size", str(BATCH),
                       "--devices", str(devices), *extra, reads])
    assert rc == 0
    return out


def _correct(reads, db, prefix, devices, extra=()):
    from quorum_tpu.cli import error_correct_reads as ec_cli
    rc = ec_cli.main(["-o", prefix, "--batch-size", str(BATCH),
                      "-p", "2", "--devices", str(devices), *extra,
                      db, reads])
    assert rc == 0
    return prefix


def _payload(path):
    """The table payload proper (the header timestamps vary per run,
    and the v5 trailer digests that header)."""
    from quorum_tpu.io.db_format import db_payload_bytes
    return db_payload_bytes(path)


def test_cli_parity_multidevice(reads_fastq, tmp_path):
    """The acceptance property: --devices 2 end-to-end (build then
    correct, through the real CLI mains) produces a byte-identical
    database payload and byte-identical corrected FASTQ/log output
    vs --devices 1."""
    db1 = _build(reads_fastq, str(tmp_path / "db1.jf"), 1)
    db2 = _build(reads_fastq, str(tmp_path / "db2.jf"), 2)
    assert _payload(db1) == _payload(db2)
    p1 = _correct(reads_fastq, db1, str(tmp_path / "out1"), 1)
    p2 = _correct(reads_fastq, db2, str(tmp_path / "out2"), 2)
    for suffix in (".fa", ".log"):
        a = open(p1 + suffix, "rb").read()
        b = open(p2 + suffix, "rb").read()
        assert a == b, f"--devices 2 {suffix} differs from --devices 1"
    assert open(p1 + ".fa", "rb").read()  # non-trivial output


def test_routed_layout_parity(reads_fastq, tmp_path, monkeypatch):
    """Forcing the replicate threshold to 1 byte keeps the table
    row-sharded with routed lookups — output must still match."""
    monkeypatch.setenv("QUORUM_REPLICATE_TABLE_BYTES", "1")
    db = _build(reads_fastq, str(tmp_path / "db.jf"), 2)
    pr = _correct(reads_fastq, db, str(tmp_path / "outR"), 2)
    monkeypatch.delenv("QUORUM_REPLICATE_TABLE_BYTES")
    p1 = _correct(reads_fastq, db, str(tmp_path / "out1"), 1)
    assert open(pr + ".fa", "rb").read() == open(p1 + ".fa",
                                                 "rb").read()
    assert open(pr + ".log", "rb").read() == open(p1 + ".log",
                                                  "rb").read()


def test_sharded_build_kill_resume(reads_fastq, tmp_path):
    """A killed sharded stage-1 build resumed with --resume converges
    on the byte-identical database, and the checkpoint clears once
    the database lands."""
    ref = _build(reads_fastq, str(tmp_path / "ref.jf"), 2)
    ckdir = str(tmp_path / "ck")
    plan = json.dumps([{"site": "stage1.insert", "batch": 1,
                        "action": "error", "message": "injected"}])
    from quorum_tpu.cli import create_database as cdb_cli
    rc = cdb_cli.main(["-s", "32k", "-m", str(K), "-b", "7", "-q", "53",
                       "-o", str(tmp_path / "k.jf"),
                       "--batch-size", str(BATCH), "--devices", "2",
                       "--checkpoint-dir", ckdir,
                       "--checkpoint-every", "1",
                       "--fault-plan", plan, reads_fastq])
    assert rc != 0
    ck = ckpt_mod.Stage1ShardedCheckpoint(ckdir)
    assert ck.cursor() == 1  # one batch committed before the fault
    _build(reads_fastq, str(tmp_path / "k.jf"), 2,
           extra=("--checkpoint-dir", ckdir, "--checkpoint-every", "1",
                  "--resume", "--fault-plan", ""))
    assert _payload(str(tmp_path / "k.jf")) == _payload(ref)
    assert ck.cursor() is None  # cleared with the durable database


def test_sharded_resume_batch_index_is_global(reads_fastq, tmp_path):
    """A resumed sharded build numbers batches from the checkpoint
    cursor, not from zero: a fault plan pinned to `batch=1` must fire
    on the batch WITH global index 1 — the one the resume is about to
    process — exactly as on the single-device loop."""
    ckdir = str(tmp_path / "ck")
    # count=-1: the same in-process plan spec keeps its spent hit
    # counters across the two main() calls, so a count=1 fault would
    # stay spent on the resume no matter what batch index it sees
    plan = json.dumps([{"site": "stage1.insert", "batch": 1,
                        "count": -1, "action": "error",
                        "message": "injected"}])
    from quorum_tpu.cli import create_database as cdb_cli
    args = ["-s", "32k", "-m", str(K), "-b", "7", "-q", "53",
            "-o", str(tmp_path / "g.jf"), "--batch-size", str(BATCH),
            "--devices", "2", "--checkpoint-dir", ckdir,
            "--checkpoint-every", "1"]
    from quorum_tpu.utils import faults
    try:
        assert cdb_cli.main(args + ["--fault-plan", plan,
                                    reads_fastq]) != 0
        ck = ckpt_mod.Stage1ShardedCheckpoint(ckdir)
        assert ck.cursor() == 1  # batch 0 committed, batch 1 faulted
        # resume with the SAME plan: the next processed batch IS
        # global batch 1, so it must fault again immediately (a
        # zero-based restart would never reach batch=1 — only one
        # batch remains — and would wrongly finish the build)
        assert cdb_cli.main(args + ["--resume", "--fault-plan", plan,
                                    reads_fastq]) != 0
        assert ck.cursor() == 1  # nothing new committed
    finally:
        faults.reset()  # the count=-1 plan must not outlive the test


def test_sharded_checkpoint_consistency(tmp_path):
    """Per-shard snapshots under one manifest: load round-trips the
    planes; a truncated shard, a missing shard, or a config mismatch
    refuses loudly (CheckpointError), never a silent partial
    restore."""
    mesh = ts.make_mesh(2, conftest.cpu_devices(2))
    meta = ts.TileShardedMeta(k=K, bits=7, rb_log2=6, n_shards=2)
    bstate = ts.make_build_state(meta, mesh)
    cfg = BuildConfig(k=K, bits=7, qual_thresh=53, batch_size=BATCH,
                      devices=2)
    stats = BuildStats(reads=10, bases=480, batches=3)
    ck = ckpt_mod.Stage1ShardedCheckpoint(str(tmp_path))
    ck.save(bstate, meta, cfg, 3, stats, ["a.fastq"])
    snap = ck.load()
    assert snap.cursor == 3 and snap.n_shards == 2
    assert snap.tag.shape == (meta.rows, np.asarray(bstate.tag).shape[1])
    np.testing.assert_array_equal(snap.tag, np.asarray(bstate.tag))
    snap.check_config(K, 7, 53, BATCH, ["a.fastq"], 2)
    with pytest.raises(ckpt_mod.CheckpointError, match="n_shards"):
        snap.check_config(K, 7, 53, BATCH, ["a.fastq"], 4)
    with pytest.raises(ckpt_mod.CheckpointError, match="inputs"):
        snap.check_config(K, 7, 53, BATCH, ["b.fastq"], 2)
    # a second save bumps the generation; the old payloads are gone
    ck.save(bstate, meta, cfg, 4, stats, ["a.fastq"])
    assert ck.load().cursor == 4
    shard_files = sorted(p for p in os.listdir(str(tmp_path))
                         if p.startswith("stage1.shard")
                         and p.endswith(".ckpt"))
    assert len(shard_files) == 2  # exactly one generation retained
    # truncate one shard payload -> loud refusal
    victim = os.path.join(str(tmp_path), shard_files[0])
    data = open(victim, "rb").read()
    open(victim, "wb").write(data[:-4])
    with pytest.raises(ckpt_mod.CheckpointError, match="corrupt"):
        ck.load()
    # remove it entirely -> loud refusal
    os.remove(victim)
    with pytest.raises(ckpt_mod.CheckpointError, match="missing"):
        ck.load()
    # a .tmp orphan from a save killed pre-rename is reaped by clear
    with open(os.path.join(str(tmp_path),
                           "stage1.shard0000.g9.ckpt.tmp"), "wb") as f:
        f.write(b"x")
    ck.clear()
    assert ck.load() is None
    assert [p for p in os.listdir(str(tmp_path))
            if p.startswith("stage1.")] == []


def test_packed_nbytes_no_double_count():
    """ADVICE r5: once the wire is warmed, nbytes is the wire's size —
    not wire + the standalone planes it already contains."""
    rng = np.random.default_rng(0)
    codes = rng.integers(0, 4, size=(8, 16)).astype(np.int8)
    quals = np.full((8, 16), 70, np.uint8)
    lengths = np.full((8,), 16, np.int32)
    pk = packing.pack_reads(codes, quals, lengths, thresholds=(53,))
    before = pk.nbytes
    plane_bytes = (pk.pcodes.nbytes + pk.nmask.nbytes
                   + pk.hq[53].nbytes + pk.lengths.nbytes)
    assert before == plane_bytes
    wire = pk.to_wire()
    assert pk.nbytes == wire.nbytes  # warmed: counted exactly once
    assert pk.compact().nbytes == wire.nbytes


def test_host_shard_paths_stats_once(tmp_path, monkeypatch):
    """ADVICE r5: every path is stat'ed exactly once per plan (an
    attribute cache returning different sizes between the sort and
    the load update could silently desynchronize the plan)."""
    from quorum_tpu.parallel import multihost
    paths = []
    for i, size in enumerate((300, 100, 200, 50)):
        p = tmp_path / f"f{i}.fastq"
        p.write_bytes(b"x" * size)
        paths.append(str(p))
    calls = {}
    real = os.path.getsize

    def counting(p):
        calls[p] = calls.get(p, 0) + 1
        return real(p)

    monkeypatch.setattr(os.path, "getsize", counting)
    mine = [multihost.host_shard_paths(paths, process_index=i,
                                       process_count=2)
            for i in range(2)]
    # each of the two plan computations stats each path exactly once
    assert all(n == 2 for n in calls.values()), calls
    assert sorted(mine[0] + mine[1]) == sorted(paths)
    assert mine[0] and mine[1]  # both hosts got work


def test_resolve_devices_validation(monkeypatch):
    import jax
    avail = len(jax.devices())
    assert ts.resolve_devices("1") == 1
    assert ts.resolve_devices(2) == 2
    assert ts.resolve_devices("all") == avail
    with pytest.raises(ValueError, match="power of two"):
        ts.resolve_devices(3)
    with pytest.raises(ValueError, match="local device"):
        ts.resolve_devices(str(2 * avail))
    with pytest.raises(ValueError, match=">= 1"):
        ts.resolve_devices(0)
    with pytest.raises(ValueError, match="integer"):
        ts.resolve_devices("banana")
    # auto on the CPU backend is the single-chip path
    assert ts.resolve_devices("auto") == 1


def test_replay_plane_fallback(reads_fastq, tmp_path):
    """A replay cache packed for a different qual cutoff falls back to
    the disk re-read (same output), instead of a KeyError mid-run."""
    from quorum_tpu.models.error_correct import (ECOptions,
                                                 _replay_plane_missing,
                                                 run_error_correct)
    db = _build(reads_fastq, str(tmp_path / "db.jf"), 1)
    # a cache whose only plane is qual>=53 cannot serve cutoff 127
    rng = np.random.default_rng(1)
    codes = rng.integers(0, 4, size=(BATCH, RLEN)).astype(np.int8)
    quals = np.full((BATCH, RLEN), 70, np.uint8)
    lengths = np.full((BATCH,), RLEN, np.int32)
    pk = packing.pack_reads(codes, quals, lengths, thresholds=(53,))
    assert _replay_plane_missing([(None, pk)], 127)
    assert not _replay_plane_missing([(None, pk)], 53)
    assert not _replay_plane_missing([], 127)
    opts = ECOptions(output=str(tmp_path / "fb"), batch_size=BATCH,
                     cutoff=2)
    stats = run_error_correct(db, [reads_fastq], None, opts,
                              prepacked=[(None, pk)])
    assert stats.reads == N_READS  # re-read ALL reads from disk
    ref = _correct(reads_fastq, db, str(tmp_path / "ref"), 1)
    assert (open(str(tmp_path / "fb") + ".fa", "rb").read()
            == open(ref + ".fa", "rb").read())
    # no inputs to fall back to -> a clear error, not a KeyError
    with pytest.raises(RuntimeError, match="replay cache"):
        run_error_correct(db, [], None, opts, prepacked=[(None, pk)])


def test_metrics_check_sharded_requirements():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "metrics_check", os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "metrics_check.py"))
    mc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mc)
    good = {
        "meta": {"stage": "create_database",
                 "shard_distinct_mers": [3, 4],
                 "shard_inserts": [10, 12]},
        "counters": {"shard_batches": 1, "shard_reads": 2,
                     "shard_inserts_total": 22, "distinct_mers": 7},
        "gauges": {"n_shards": 2, "shard_distinct_min": 3,
                   "shard_distinct_max": 4, "shard_inserts_min": 10,
                   "shard_inserts_max": 12},
    }
    assert mc._check_shard_names(good) == []
    # single-chip documents are exempt
    assert mc._check_shard_names(
        {"meta": {"stage": "create_database"}, "gauges": {}}) == []
    bad = {k: (dict(v) if isinstance(v, dict) else v)
           for k, v in good.items()}
    bad["counters"] = {}
    bad["meta"] = dict(good["meta"], shard_inserts=[10])
    errs = mc._check_shard_names(bad)
    assert any("shard_inserts_total" in e for e in errs)
    assert any("meta.shard_inserts" in e for e in errs)
    assert mc._check_hosts_doc(
        {"meta": {"aggregated_hosts": 1}, "hosts": {"0": {}}}) == []
    errs = mc._check_hosts_doc(
        {"meta": {"aggregated_hosts": 2}, "hosts": {"0": {}}})
    assert errs and "aggregated_hosts" in errs[0]
