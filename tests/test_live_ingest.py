"""The live ingestion tier (ISSUE 18): streaming online correction
with epoch-swapped tables.

Four contracts under test:

* **Build parity** — a LiveTable fed the golden reads in arbitrary
  chunk sizes seals to the SAME table payload bytes the offline
  `quorum_create_database` writes (counts are commutative, the insert
  wire is the same fused packed insert, and the grow ladder lands on
  the same final geometry).
* **Epoch swap semantics** — in-flight /correct batches finish on the
  OLD epoch while a swap lands; a failed swap (injected `serve.epoch`
  fault) rolls back completely: generation unchanged, orphan snapshot
  removed, failure counted, and the next boundary retries cleanly.
* **Durability** — the live-table checkpoint round-trips planes +
  cursor + stats, refuses corruption and config drift, and a KILLED
  service (subprocess, `serve.ingest` exit fault) resumes at the
  committed cursor: re-sent chunks ack as duplicates, nothing is
  double-counted, and the end-state epoch snapshot is byte-identical
  to a fresh table fed the same chunks.
* **End-state parity** — corrections served from a live-built epoch
  are byte-identical to the offline build+correct pipeline at the
  same floor and cutoff.
"""

import conftest  # noqa: F401  (pins CPU devices)

import json
import math
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli
from quorum_tpu.io import db_format, fastq
from quorum_tpu.io.checkpoint import CheckpointError
from quorum_tpu.serve import (CorrectionEngine, CorrectionServer,
                              DynamicBatcher)
from quorum_tpu.serve.client import ServeClient
from quorum_tpu.serve.ingest import IngestDispatcher
from quorum_tpu.serve.live_table import (LiveTable, LiveTableCheckpoint,
                                         epoch_floor, load_or_create)
from quorum_tpu.telemetry import registry_for
from quorum_tpu.utils import faults

HERE = os.path.dirname(__file__)
GOLDEN = os.path.join(HERE, "golden")
READS = os.path.join(GOLDEN, "reads.fastq")

# the golden fixture's stage-1 geometry (tests/golden/README): every
# test shares it so the fused insert/seal executables compile once
K, BITS, SIZE, QT = 13, 7, 64 * 1024, 38


def _records():
    return list(fastq.iter_records([READS]))


# ---------------------------------------------------------------------------
# epoch_floor: the time-varying presence floor
# ---------------------------------------------------------------------------

def test_epoch_floor_ramp():
    # thin coverage -> full initial floor; past the ramp -> final
    assert epoch_floor(4, 1, 20.0, 0.0) == 4
    assert epoch_floor(4, 1, 20.0, 20.0) == 1
    assert epoch_floor(4, 1, 20.0, 50.0) == 1
    # halfway down the ramp: final + ceil((initial-final) * 1/2)
    assert epoch_floor(4, 1, 20.0, 10.0) == 1 + math.ceil(3 * 0.5)
    # degenerate policies pin at final
    assert epoch_floor(1, 1, 20.0, 0.0) == 1
    assert epoch_floor(4, 1, 0.0, 0.0) == 1
    assert epoch_floor(2, 5, 20.0, 0.0) == 5
    # monotone non-increasing in coverage
    floors = [epoch_floor(6, 2, 30.0, c) for c in
              [0.0, 5.0, 10.0, 15.0, 20.0, 25.0, 30.0, 40.0]]
    assert floors == sorted(floors, reverse=True)
    assert floors[0] == 6 and floors[-1] == 2


# ---------------------------------------------------------------------------
# build parity: live insert wire == offline stage 1
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def golden_db(tmp_path_factory):
    db = str(tmp_path_factory.mktemp("live_golden") / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, READS])
    assert rc == 0
    return db


def test_live_table_build_matches_offline(golden_db, tmp_path):
    """Feeding the live table the golden reads in deliberately odd
    chunk sizes seals to the byte-identical table payload the offline
    build writes: the streaming wire changes WHEN counting happens,
    never WHAT is counted."""
    recs = _records()
    table = LiveTable(K, BITS, SIZE, QT)
    for i in range(0, len(recs), 37):  # 37 never divides anything
        table.ingest_records(recs[i:i + 37])
    assert table.stats.reads == len(recs) == 242
    state, occ, distinct, total = table.seal()
    assert occ > 0 and distinct > 0 and total >= distinct
    live_db = str(tmp_path / "live.qdb")
    db_format.write_db(live_db, state, table.meta, n_entries=occ)
    assert (db_format.db_payload_bytes(live_db)
            == db_format.db_payload_bytes(golden_db))


def test_live_table_grows_like_offline(tmp_path):
    """An undersized live table grows through the same geometry
    ladder as the offline build and lands on the same payload."""
    recs = _records()[:100]
    sub = tmp_path / "sub.fastq"
    with open(sub, "w") as f:
        for h, s, q in recs:
            f.write(f"@{h}\n{s.decode()}\n+\n{q.decode()}\n")
    off_db = str(tmp_path / "off.jf")
    rc = cdb_cli.main(["-s", "256", "-m", "13", "-b", "7", "-q", "38",
                       "-o", off_db, str(sub)])
    assert rc == 0
    table = LiveTable(K, BITS, 256, QT)
    table.ingest_records(recs)
    assert table.stats.grows >= 1  # 256 entries cannot hold 100 reads
    state, occ, *_ = table.seal()
    live_db = str(tmp_path / "live.qdb")
    db_format.write_db(live_db, state, table.meta, n_entries=occ)
    assert (db_format.db_payload_bytes(live_db)
            == db_format.db_payload_bytes(off_db))


# ---------------------------------------------------------------------------
# durability: the live-table checkpoint
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_and_refusals(tmp_path):
    recs = _records()[:64]
    table = LiveTable(K, BITS, SIZE, QT)
    table.ingest_records(recs)
    ckpt = LiveTableCheckpoint(str(tmp_path))
    ckpt.save(table, cursor=7)
    assert ckpt.cursor() == 7

    resumed, cur = load_or_create(ckpt, K, BITS, SIZE, QT)
    assert cur == 7
    assert resumed.stats.reads == table.stats.reads
    assert resumed.stats.bases == table.stats.bases
    assert resumed.meta.rb_log2 == table.meta.rb_log2
    for attr in ("tag", "hq", "lq"):
        assert np.array_equal(
            np.asarray(getattr(resumed.bstate, attr)),
            np.asarray(getattr(table.bstate, attr))), attr

    # the resumed table keeps ingesting and seals identically to a
    # never-killed table fed the same stream
    more = _records()[64:128]
    resumed.ingest_records(more)
    table.ingest_records(more)
    s1, occ1, *_ = resumed.seal()
    s2, occ2, *_ = table.seal()
    assert occ1 == occ2
    p1 = str(tmp_path / "a.qdb")
    p2 = str(tmp_path / "b.qdb")
    db_format.write_db(p1, s1, resumed.meta, n_entries=occ1)
    db_format.write_db(p2, s2, table.meta, n_entries=occ2)
    assert (db_format.db_payload_bytes(p1)
            == db_format.db_payload_bytes(p2))

    # config drift: resuming under different stage-1 parameters must
    # refuse, not silently mix incompatible counts
    with pytest.raises(CheckpointError):
        load_or_create(ckpt, K, BITS, SIZE, QT + 1)

    # payload corruption: a flipped byte fails the digest loudly
    with open(ckpt.path, "r+b") as f:
        f.seek(-4, os.SEEK_END)
        b = f.read(1)
        f.seek(-4, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(CheckpointError):
        ckpt.load()

    # truncation is refused too (resume-from-garbage must not look
    # like a fresh start)
    ckpt.save(table, cursor=9)
    size = os.path.getsize(ckpt.path)
    with open(ckpt.path, "r+b") as f:
        f.truncate(size - 128)
    with pytest.raises(CheckpointError):
        ckpt.load()


# ---------------------------------------------------------------------------
# dispatcher semantics (real LiveTable, engine-shaped stubs)
# ---------------------------------------------------------------------------

class MarkEngine:
    """Engine-shaped stub whose corrections are tagged with `mark`, so
    a response proves WHICH epoch served it."""

    def __init__(self, mark, gate=None, rows=1024):
        self.mark = mark
        self.gate = gate
        self.rows = rows
        self.warm_lengths = ()
        self.entered = threading.Event()

    @property
    def compiles(self):
        return 0

    def warmup(self, lengths):
        pass

    def step(self, records):
        self.entered.set()
        if self.gate is not None:
            assert self.gate.wait(timeout=30), "test gate never opened"
        return [(f">{h}:{self.mark}\n{s.decode()}\n", "")
                for h, s, _q in records]


def _mark_stack(tmp_path, gate=None):
    """A dispatcher over a real LiveTable whose epoch engines are
    MarkEngine stubs (epoch N serves mark 'epoch-N')."""
    reg = registry_for(None, force=True)
    table = LiveTable(K, BITS, SIZE, QT)
    ckpt = LiveTableCheckpoint(str(tmp_path))
    built = []

    def builder(path, poisson):
        assert os.path.exists(path)
        header = db_format.read_header(path)
        eng = MarkEngine(f"epoch-{header['live_epoch']['epoch']}")
        built.append((eng, header, poisson))
        return eng

    disp = IngestDispatcher(table, ckpt, builder,
                            live_dir=str(tmp_path), registry=reg)
    boot = MarkEngine("boot", gate=gate)
    bat = DynamicBatcher(boot, max_batch=8, max_wait_ms=0,
                         queue_requests=8, registry=reg)
    disp.start(bat)
    return reg, disp, bat, boot, built


def test_ingest_dedupe_and_cursor(tmp_path):
    recs = _records()
    _reg, disp, bat, _boot, _built = _mark_stack(tmp_path)
    try:
        ack = disp.submit_chunk(recs[:8], seq=3)
        assert ack == {"accepted": True, "duplicate": False, "seq": 3,
                       "reads": 8, "cursor": 3}
        # a retransmit of an applied seq acks duplicate, counts nothing
        ack2 = disp.submit_chunk(recs[:8], seq=3)
        assert ack2["duplicate"] is True
        assert disp.stats()["reads"] == 8
        # an unstamped chunk gets the next seq past the horizon
        ack3 = disp.submit_chunk(recs[8:16])
        assert ack3["seq"] == 4 and ack3["duplicate"] is False
        assert disp.cursor == 4
        assert disp.stats()["reads"] == 16
    finally:
        disp.drain(timeout=10)
        bat.drain(timeout=5)


def test_inflight_correct_finishes_on_old_epoch(tmp_path):
    """THE swap semantic: a /correct batch dispatched before the epoch
    swap completes on the OLD engine; the next batch sees the new
    one."""
    gate = threading.Event()
    _reg, disp, bat, boot, _built = _mark_stack(tmp_path, gate=gate)
    try:
        disp.submit_chunk(_records()[:32], seq=0)
        gen0 = bat.generation
        fut = bat.submit([("r", b"ACGTACGTACGT", b"IIIIIIIIIIII")])
        assert boot.entered.wait(5), "in-flight step never dispatched"
        res = disp.force_epoch(timeout=60)
        assert res["ok"] is True, res
        assert res["epoch"] == 1 and bat.generation == gen0 + 1
        # the in-flight step is STILL blocked on the boot engine; the
        # swap must not have torn it away
        gate.set()
        out = fut.result(timeout=10)
        assert ":boot" in out[0][0]
        out2 = bat.submit(
            [("r2", b"ACGTACGTACGT", b"IIIIIIIIIIII")]).result(timeout=10)
        assert ":epoch-1" in out2[0][0]
    finally:
        gate.set()
        disp.drain(timeout=10)
        bat.drain(timeout=5)


def test_epoch_swap_failure_rolls_back(tmp_path):
    """An injected `serve.epoch` fault between snapshot export and the
    swap leaves the old epoch serving: generation unchanged, orphan
    snapshot removed, failure counted — and the NEXT boundary
    succeeds cleanly."""
    reg, disp, bat, _boot, _built = _mark_stack(tmp_path)
    try:
        disp.submit_chunk(_records()[:32], seq=0)
        gen0 = bat.generation
        faults.setup('[{"site": "serve.epoch", "action": "error", '
                     '"message": "injected swap failure", "count": 1}]')
        try:
            res = disp.force_epoch(timeout=60)
        finally:
            faults.setup("")  # clear the plan whatever happened
        assert res["ok"] is False
        assert "injected swap failure" in res["error"]
        assert bat.generation == gen0
        assert reg.counter("epoch_swap_failures_total").value == 1
        assert disp.stats()["last_epoch_error"] is not None
        # the failed attempt's snapshot file must not linger
        files = sorted(os.listdir(tmp_path))
        assert "epoch-000001.qdb" not in files
        # correction path still answers from the old engine
        out = bat.submit(
            [("r", b"ACGTACGTACGT", b"IIIIIIIIIIII")]).result(timeout=10)
        assert ":boot" in out[0][0]
        # retry: the same boundary now succeeds and swaps
        res = disp.force_epoch(timeout=60)
        assert res["ok"] is True and res["epoch"] == 1
        assert bat.generation == gen0 + 1
        assert reg.counter("epoch_swaps_total").value == 1
        assert disp.stats()["last_epoch_error"] is None
    finally:
        disp.drain(timeout=10)
        bat.drain(timeout=5)


def test_epoch_boundary_reads_and_pruning(tmp_path):
    """--epoch-reads boundaries fire from the ingest path itself, and
    old snapshots are pruned down to keep_epochs."""
    reg = registry_for(None, force=True)
    table = LiveTable(K, BITS, SIZE, QT)
    ckpt = LiveTableCheckpoint(str(tmp_path))
    builder = lambda path, poisson: MarkEngine("x")  # noqa: E731
    disp = IngestDispatcher(table, ckpt, builder,
                            live_dir=str(tmp_path), epoch_reads=32,
                            registry=reg)
    bat = DynamicBatcher(MarkEngine("boot"), max_batch=8,
                         max_wait_ms=0, queue_requests=8, registry=reg)
    disp.start(bat)
    try:
        recs = _records()
        for i in range(4):  # 4 x 40 reads, boundary every 32
            disp.submit_chunk(recs[i * 40:(i + 1) * 40], seq=i)
        deadline = time.perf_counter() + 30
        while reg.counter("epoch_swaps_total").value < 2:
            assert time.perf_counter() < deadline, "no epoch swaps"
            time.sleep(0.05)
        st = disp.stats()
        assert st["epoch"] >= 2
        epochs = sorted(f for f in os.listdir(tmp_path)
                        if f.startswith("epoch-"))
        assert len(epochs) <= 2  # keep_epochs=2 pruning
    finally:
        disp.drain(timeout=10)
        bat.drain(timeout=5)


# ---------------------------------------------------------------------------
# kill -> resume (subprocess: the fault exits the PROCESS mid-stream)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_CHILD_SRC = """
import sys
sys.path.insert(0, {root!r})
import quorum_tpu.serve as serve_pkg

class FE:
    def __init__(self, rows=1024):
        self.rows = rows
        self.warm_lengths = ()
    compiles = 0
    def warmup(self, lengths):
        pass
    def step(self, records):
        return [(">%s\\n%s\\n" % (h, s.decode()), "")
                for h, s, _q in records]

serve_pkg.CorrectionEngine = lambda db, **kw: FE(kw.get("rows", 1024))
from quorum_tpu.cli import serve as serve_cli
sys.exit(serve_cli.main({args!r}))
"""


def _spawn_live_server(port, live_dir, metrics=None, fault_plan=None):
    args = ["--port", str(port), "--max-wait-ms", "0",
            "--ingest", "--live-dir", live_dir,
            "--ingest-mer-len", str(K), "--ingest-bits", str(BITS),
            "--ingest-size", "64k", "--ingest-qual-thresh", str(QT),
            "--live-checkpoint-every", "1"]
    if metrics:
        args += ["--metrics", metrics]
    src = _CHILD_SRC.format(root=os.path.dirname(HERE), args=args)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("QUORUM_FAULT_PLAN", None)
    if fault_plan is not None:
        env["QUORUM_FAULT_PLAN"] = fault_plan
    return subprocess.Popen([sys.executable, "-c", src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_healthz(client, proc, timeout=180):
    deadline = time.perf_counter() + timeout
    while True:
        try:
            return client.healthz()
        except (OSError, RuntimeError):
            assert proc.poll() is None, \
                f"server died rc={proc.returncode}"
            assert time.perf_counter() < deadline, "server never up"
            time.sleep(0.2)


def test_ingest_kill_resume_subprocess(tmp_path):
    """A service killed MID-STREAM (os._exit via the serve.ingest
    fault site) resumes from its live-table checkpoint: the cursor is
    restored, re-sent chunks ack as duplicates, nothing double-counts,
    and the final epoch snapshot is byte-identical to a fresh table
    fed the same chunks once each."""
    recs = _records()
    chunks = [recs[i:i + 41] for i in range(0, len(recs), 41)]
    assert len(chunks) == 6 and sum(len(c) for c in chunks) == 242
    texts = ["".join(f"@{h}\n{s.decode()}\n+\n{q.decode()}\n"
                     for h, s, q in c) for c in chunks]
    live_dir = str(tmp_path / "live")
    os.makedirs(live_dir)

    # phase 1: die while ingesting chunk seq 3 (after 3 committed)
    port = _free_port()
    plan = json.dumps([{"site": "serve.ingest", "batch": 3,
                        "action": "exit", "code": 41}])
    proc = _spawn_live_server(port, live_dir, fault_plan=plan)
    try:
        client = ServeClient(port=port)
        _wait_healthz(client, proc)
        for seq in range(3):
            status, ack = client.ingest(texts[seq], seq=seq)
            assert status == 200 and ack["cursor"] == seq, ack
        with pytest.raises(OSError):
            client.ingest(texts[3], seq=3)
        assert proc.wait(timeout=30) == 41
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # the checkpoint committed after chunk 2 survived the kill
    assert LiveTableCheckpoint(live_dir).cursor() == 2

    # phase 2: restart; replay ALL chunks (at-least-once client)
    port = _free_port()
    metrics = str(tmp_path / "serve.json")
    proc = _spawn_live_server(port, live_dir, metrics=metrics)
    try:
        client = ServeClient(port=port)
        h = _wait_healthz(client, proc)
        assert h["live"]["cursor"] == 2, h["live"]
        assert h["live"]["reads"] == sum(len(c) for c in chunks[:3])
        for seq in range(6):
            status, ack = client.ingest(texts[seq], seq=seq,
                                        gzip_body=True)
            assert status == 200, ack
            assert ack["duplicate"] is (seq <= 2), (seq, ack)
        h = client.healthz()
        assert h["live"]["cursor"] == 5
        assert h["live"]["reads"] == 242  # no loss, no double-count
        status, doc = client.epoch()
        assert status == 200 and doc["ok"] is True, doc
        client.quiesce()
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # end-state parity: the sealed epoch == a fresh table fed the
    # same chunks exactly once
    epoch_db = os.path.join(live_dir, "epoch-000001.qdb")
    assert os.path.exists(epoch_db)
    ref = LiveTable(K, BITS, SIZE, QT)
    for c in chunks:
        ref.ingest_records(c)
    state, occ, *_ = ref.seal()
    ref_db = str(tmp_path / "ref.qdb")
    db_format.write_db(ref_db, state, ref.meta, n_entries=occ)
    assert (db_format.db_payload_bytes(epoch_db)
            == db_format.db_payload_bytes(ref_db))

    # the final metrics document carries the live-ingest surface the
    # telemetry contract requires under meta.live_ingest
    with open(metrics) as f:
        doc = json.load(f)
    assert doc["meta"]["live_ingest"] is True
    for c in ("ingest_requests_total", "ingest_reads_total",
              "epoch_swaps_total", "epoch_swap_failures_total"):
        assert c in doc["counters"], c
    for g in ("ingest_cursor", "live_floor"):
        assert g in doc["gauges"], g
    assert doc["counters"]["ingest_reads_total"] == sum(
        len(c) for c in chunks[3:])  # duplicates counted nothing
    assert doc["counters"]["epoch_swaps_total"] >= 1
    assert doc["gauges"]["ingest_cursor"] == 5


# ---------------------------------------------------------------------------
# end-state parity: live epoch serves byte-identical corrections
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def offline(golden_db, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("live_off") / "off")
    rc = ec_cli.main(["-p", "4", golden_db, READS, "-o", out])
    assert rc == 0
    with open(out + ".fa") as f:
        fa = f.read()
    with open(out + ".log") as f:
        log = f.read()
    return fa, log


def test_live_end_state_parity_with_offline(offline, tmp_path):
    """Acceptance: once every read is ingested, /correct answers from
    the live-built epoch byte-identically to the offline
    build+correct pipeline at the same floor (1) and cutoff (4)."""
    reg = registry_for(None, force=True)
    reg.set_meta(stage="serve")
    table = LiveTable(K, BITS, SIZE, QT)
    table.ingest_records(_records())
    ckpt = LiveTableCheckpoint(str(tmp_path))

    def builder(path, poisson):
        return CorrectionEngine(path, cutoff=4, rows=64, registry=reg)

    disp = IngestDispatcher(table, ckpt, builder,
                            live_dir=str(tmp_path), registry=reg)
    engine = disp.boot_epoch()  # epoch 0 = the fully-ingested table
    bat = DynamicBatcher(engine, max_batch=64, max_wait_ms=2,
                         queue_requests=8, registry=reg)
    disp.start(bat)
    server = CorrectionServer(bat, port=0, registry=reg, ingest=disp)
    try:
        client = ServeClient(port=server.port)
        assert client.healthz()["live"]["epoch"] == 0
        r = client.correct(open(READS).read(), want_log=True)
        assert r.status == 200
        off_fa, off_log = offline
        assert r.fa == off_fa      # byte parity, .fa channel
        assert r.log == off_log    # byte parity, .log channel
    finally:
        server.close()
        disp.drain(timeout=10)
        bat.drain(timeout=5)
