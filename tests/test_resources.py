"""Resource-exhaustion robustness tests (ISSUE 19): the writer
degradation ladder (a parameterized ENOSPC sweep over the whole
WRITERS catalog), disk preflight in all three modes, the monitor
ticker's gauge surface, the offline stall watchdog's soft abort, the
metrics_check resource-guard gate, and the end-to-end truths — an
out-of-space OPTIONAL writer degrades while the run completes
byte-identically, a kill after the degradation still resumes to the
same table, and an out-of-space REQUIRED writer fails fast with the
non-retryable DISK_FULL_RC and a sealed flight dump naming it.

The unit tests drive utils/resources directly under a throwaway
frame; the end-to-end tests run the real stage-1 CLI over the small
synthetic dataset the other chaos suites use (shared jit shapes) with
the `diskfull` fault action standing in for the full filesystem.
"""

import conftest  # noqa: F401  (pins CPU devices)

import errno
import json
import os
import shutil
import sys
import threading
import time

import pytest

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.io import checkpoint as ckpt_mod
from quorum_tpu.io import db_format
from quorum_tpu.telemetry import flight as flight_mod
from quorum_tpu.telemetry import registry_for
from quorum_tpu.telemetry.registry import labeled
from quorum_tpu.utils import faults, resources

from test_error_correct_cli import K, make_dataset


@pytest.fixture(autouse=True)
def _clean_state():
    """Every test starts and ends without a fault plan or a leaked
    resource-guard frame."""
    faults.reset()
    yield
    faults.reset()
    resources._FRAME = resources._Frame(None, None)


def _enospc():
    return OSError(errno.ENOSPC, "no space left on device")


# ---------------------------------------------------------------------------
# the catalog and the errno family
# ---------------------------------------------------------------------------

def test_writer_catalog_classification():
    # the required set is the run's reason to exist — growing it is a
    # semantic change (the driver stops retrying those failures)
    required = {w for w, c in resources.WRITERS.items()
                if c == resources.REQUIRED}
    assert required == {"db.payload", "output.stream", "stage2.journal"}
    assert all(c in (resources.REQUIRED, resources.OPTIONAL)
               for c in resources.WRITERS.values())
    # the rc family stays disjoint from the existing non-retryable rc
    assert resources.DISK_FULL_RC == 4
    assert resources.STALL_RC == 75
    assert ckpt_mod.NON_RETRYABLE_RC not in (resources.DISK_FULL_RC,
                                             resources.STALL_RC)


def test_is_enospc_family():
    assert resources.is_enospc(_enospc())
    assert resources.is_enospc(OSError(errno.EDQUOT, "quota"))
    assert resources.is_enospc(resources.ResourceExhausted("x", "d"))
    assert not resources.is_enospc(OSError(errno.ENOENT, "missing"))
    assert not resources.is_enospc(ValueError("nope"))


# ---------------------------------------------------------------------------
# the degradation ladder: ENOSPC sweep over every declared writer
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("writer", sorted(resources.WRITERS))
def test_guard_ladders_every_writer(writer):
    reg = registry_for(None, force=True)
    tok = resources.install(reg)
    try:
        if resources.WRITERS[writer] == resources.REQUIRED:
            with pytest.raises(resources.ResourceExhausted) as ei:
                with resources.guard(writer, path="/x/y"):
                    raise _enospc()
            assert ei.value.writer == writer
            assert resources.is_enospc(ei.value)
            # required writers fail fast, they never degrade
            assert not resources.degraded(writer)
            assert reg.counter("writer_degraded_total").value == 0
        else:
            with resources.guard(writer, path="/x/y"):
                raise _enospc()  # swallowed: the writer degrades
            assert resources.degraded(writer)
            assert reg.counter("writer_degraded_total").value == 1
            assert reg.counter(labeled("writer_degraded_total",
                                       writer=writer)).value == 1
            # EDQUOT ladders identically; the counter keeps counting
            # but the first failure detail is retained
            with resources.guard(writer, path="/x/z"):
                raise OSError(errno.EDQUOT, "quota exceeded")
            assert reg.counter("writer_degraded_total").value == 2
            assert "/x/y" in resources.degraded_writers()[writer]
    finally:
        resources.uninstall(tok)


def test_guard_passthrough_and_validation():
    reg = registry_for(None, force=True)
    tok = resources.install(reg)
    try:
        # non-ENOSPC errors pass through untouched, optional or not
        with pytest.raises(OSError, match="missing"):
            with resources.guard("trace.spans"):
                raise OSError(errno.ENOENT, "missing")
        with pytest.raises(ValueError, match="bad"):
            with resources.guard("stage2.journal"):
                raise ValueError("bad")
        assert not resources.degraded("trace.spans")
        # a nested guard's ResourceExhausted is not laddered twice
        with pytest.raises(resources.ResourceExhausted) as ei:
            with resources.guard("stage1.checkpoint"):
                raise resources.ResourceExhausted("db.payload", "inner")
        assert ei.value.writer == "db.payload"
        assert not resources.degraded("stage1.checkpoint")
        # undeclared writers are a programming error, loudly
        with pytest.raises(ValueError, match="undeclared writer"):
            with resources.guard("not.a.writer"):
                pass
    finally:
        resources.uninstall(tok)


def test_frames_nest_and_isolate():
    reg = registry_for(None, force=True)
    outer = resources.install(reg)
    resources.degrade("trace.spans", _enospc())
    inner = resources.install(reg)
    # a nested (in-process stage) frame starts with a clean slate
    assert not resources.degraded("trace.spans")
    resources.uninstall(inner)
    assert resources.degraded("trace.spans")
    resources.uninstall(outer)
    assert not resources.degraded("trace.spans")


# ---------------------------------------------------------------------------
# preflight
# ---------------------------------------------------------------------------

def test_preflight_modes(tmp_path, capsys):
    reg = registry_for(None, force=True)
    tok = resources.install(reg)
    target = str(tmp_path / "out.db")
    huge = shutil.disk_usage(str(tmp_path)).free + (1 << 30)
    try:
        with pytest.raises(ValueError, match="--preflight"):
            resources.preflight("loud", {target: 1})
        resources.preflight("off", {target: huge})  # silent no-op
        resources.preflight("strict", {})           # nothing to check
        resources.preflight("strict", {target: 1024})  # fits
        resources.preflight("warn", {target: huge})
        assert "preflight warning" in capsys.readouterr().err
        assert reg.counter("preflight_refusals_total").value == 0
        with pytest.raises(resources.ResourceExhausted,
                           match="preflight refused"):
            resources.preflight("strict", {target: huge})
        assert reg.counter("preflight_refusals_total").value == 1
        # a vanished estimate target is the writer's problem later,
        # not a preflight crash
        resources.preflight("strict",
                            {str(tmp_path / "no" / "dir" / "f"): huge})
    finally:
        resources.uninstall(tok)


def test_preflight_estimates(tmp_path):
    small = resources.estimate_table_bytes(1 << 10, 13, 7)
    big = resources.estimate_table_bytes(1 << 20, 13, 7)
    assert 0 < small < big

    out = str(tmp_path / "db.jf")
    needs = resources.estimate_stage1_needs(out, 1 << 16, 13, 7)
    assert set(needs) == {out}
    ck = str(tmp_path / "ck")
    needs = resources.estimate_stage1_needs(out, 1 << 16, 13, 7,
                                            checkpoint_dir=ck)
    # ~2 retained snapshots in the checkpoint dir
    assert needs[ck] == 2 * needs[out]

    fq = tmp_path / "r.fastq"
    fq.write_bytes(b"x" * 1000)
    gz = tmp_path / "r2.fastq.gz"
    gz.write_bytes(b"x" * 1000)
    out2 = str(tmp_path / "out.fa")
    needs = resources.estimate_stage2_needs(out2, [str(fq), str(gz)])
    # 1000 plain + 1000 * 4 (gz expansion), times the 1.2x factor
    assert needs == {out2: int(5000 * 1.2)}
    assert resources.estimate_stage2_needs(
        out2, [str(tmp_path / "missing.fastq")]) == {}


# ---------------------------------------------------------------------------
# the monitor ticker and install() meta discipline
# ---------------------------------------------------------------------------

def test_install_arms_monitor_and_meta(tmp_path):
    reg = registry_for(None, force=True)
    tok = resources.install(reg, watch_paths=(str(tmp_path / "o.db"),
                                              str(tmp_path / "o.db"),
                                              "", None))
    try:
        assert reg.meta.get("resource_guard") is True
        # the synchronous first tick published the full gauge surface
        assert reg.gauge("disk_free_bytes_min").value > 0
        assert reg.gauge(labeled("disk_free_bytes",
                                 path=str(tmp_path))).value > 0
        assert reg.gauge("host_rss_bytes").value > 0
        # the contract counters exist at zero (PR-7 zero-count lesson)
        for name in ("writer_degraded_total", "preflight_refusals_total",
                     "stall_aborts_total"):
            assert reg.counter(name).value == 0
    finally:
        resources.uninstall(tok)


def test_install_without_paths_declares_nothing():
    reg = registry_for(None, force=True)
    tok = resources.install(reg)
    try:
        # no watched paths -> no monitor, so no resource_guard claim
        # (metrics_check would require gauges that cannot exist)
        assert "resource_guard" not in reg.meta
        assert tok.monitor is None and tok.watchdog is None
    finally:
        resources.uninstall(tok)


# ---------------------------------------------------------------------------
# the offline stall watchdog
# ---------------------------------------------------------------------------

def test_watchdog_beat_is_noop_without_frame():
    resources.watchdog_beat("anywhere", 0)  # no frame: must not raise


def test_watchdog_soft_aborts_stalled_thread():
    reg = registry_for(None, force=True)
    tok = resources.install(reg, stall_timeout_s=0.3)
    caught = threading.Event()

    def worker():
        resources.watchdog_beat("stage2.correct", 0)
        try:
            for _ in range(600):  # a wedged step, interruptible
                time.sleep(0.01)
        except resources.StallError:
            # disarm the hard abort before unwinding, as the stage
            # error paths do by tearing the frame down
            resources.watchdog_beat("stage2.correct", 1)
            caught.set()

    try:
        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=10.0)
        assert caught.is_set(), "watchdog never delivered StallError"
        assert reg.counter("stall_aborts_total").value >= 1
    finally:
        resources.uninstall(tok)


# ---------------------------------------------------------------------------
# the metrics_check resource-guard gate (schema unit test)
# ---------------------------------------------------------------------------

def _mc():
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import metrics_check
    return metrics_check


def _doc(meta=None, counters=None, gauges=None):
    return {"schema": "quorum-tpu-metrics/1", "meta": meta or {},
            "counters": counters or {}, "gauges": gauges or {},
            "histograms": {}, "timers": {}}


def test_metrics_check_requires_resource_surface(tmp_path):
    mc = _mc()
    counters = {"writer_degraded_total": 0,
                "preflight_refusals_total": 0,
                "stall_aborts_total": 0}
    gauges = {"disk_free_bytes_min": 1e9, "host_rss_bytes": 1e8,
              'disk_free_bytes{path="/data"}': 1e9}
    ok = _doc(meta={"resource_guard": True}, counters=counters,
              gauges=gauges)
    assert mc._check_resource_names(ok) == []
    # undeclared documents are not held to the surface
    assert mc._check_resource_names(_doc()) == []
    # 3 missing counters + 2 missing gauges + no labeled gauge
    errs = mc._check_resource_names(_doc(meta={"resource_guard": True}))
    assert len(errs) == 6
    # the labeled per-path gauge is required even with the scalars
    bare = _doc(meta={"resource_guard": True}, counters=counters,
                gauges={"disk_free_bytes_min": 1e9,
                        "host_rss_bytes": 1e8})
    errs = mc._check_resource_names(bare)
    assert len(errs) == 1 and "labeled gauge" in errs[0]
    # end to end through the file checker
    p = str(tmp_path / "d.json")
    json.dump(ok, open(p, "w"))
    assert mc.main([p, "-q"]) == 0
    json.dump(_doc(meta={"resource_guard": True}), open(p, "w"))
    assert mc.main([p, "-q"]) == 1


# ---------------------------------------------------------------------------
# end to end: the ladder through the real stage-1 pipeline
# ---------------------------------------------------------------------------

def _db_entries(path):
    state, meta, _ = db_format.read_db(path, to_device=False)
    khi, klo, vals = db_format.db_iterate(state, meta)
    return sorted(zip(khi.tolist(), klo.tolist(), vals.tolist()))


BASE_ARGS = ["-s", "64k", "-m", str(K), "-b", "7", "-q", "38",
             "--batch-size", "64"]


def test_stage1_checkpoint_enospc_degrades_run_completes(tmp_path):
    """An out-of-space checkpoint writer (optional) degrades: the run
    completes, the table is byte-identical to an unfaulted build, and
    the degradation is counted in a document metrics_check accepts."""
    reads_path, _r, _q = make_dataset(tmp_path)
    db0 = str(tmp_path / "db0.jf")
    assert cdb_cli.main(BASE_ARGS + ["-o", db0, reads_path]) == 0

    db1 = str(tmp_path / "db1.jf")
    ckdir = str(tmp_path / "ck")
    mpath = str(tmp_path / "m.json")
    plan = json.dumps([{"site": "checkpoint.commit",
                        "action": "diskfull", "count": -1}])
    rc = cdb_cli.main(BASE_ARGS + [
        "-o", db1, "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--fault-plan", plan, "--metrics", mpath, reads_path])
    assert rc == 0
    assert _db_entries(db1) == _db_entries(db0)
    doc = json.load(open(mpath))
    assert doc["counters"]["writer_degraded_total"] >= 1
    assert doc["meta"]["resource_guard"] is True
    assert _mc().main([mpath, "-q"]) == 0


def test_stage1_kill_resume_after_degraded_checkpoints(tmp_path):
    """Checkpoints that DEGRADE mid-run (disk filled at the third
    commit) then a kill: the resume — a fresh process, so the writer
    re-enables — continues from the last GOOD checkpoint and
    converges on the unfaulted table."""
    reads_path, _r, _q = make_dataset(tmp_path)
    db0 = str(tmp_path / "db0.jf")
    assert cdb_cli.main(BASE_ARGS + ["-o", db0, reads_path]) == 0

    db1 = str(tmp_path / "db1.jf")
    ckdir = str(tmp_path / "ck")
    plan = json.dumps([
        {"site": "checkpoint.commit", "action": "diskfull",
         "at": 3, "count": -1},
        {"site": "stage1.insert", "batch": 3, "action": "error"},
    ])
    rc = cdb_cli.main(BASE_ARGS + [
        "-o", db1, "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--fault-plan", plan, reads_path])
    assert rc == 1
    assert not os.path.exists(db1)
    # the checkpoint.commit site fires AFTER the atomic replace, so
    # the third snapshot itself landed before the injected ENOSPC
    # degraded the writer: three commits are durable
    assert ckpt_mod.Stage1Checkpoint(ckdir).cursor() == 3

    rc = cdb_cli.main(BASE_ARGS + [
        "-o", db1, "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--resume", "--fault-plan", "", reads_path])
    assert rc == 0
    assert _db_entries(db1) == _db_entries(db0)


def test_stage1_db_export_enospc_fails_fast_with_dump(tmp_path):
    """An out-of-space DB export (required) is the non-retryable
    DISK_FULL_RC with a sealed flight dump naming the writer."""
    reads_path, _r, _q = make_dataset(tmp_path)
    db1 = str(tmp_path / "db1.jf")
    mpath = str(tmp_path / "m.json")
    plan = json.dumps([{"site": "db.write", "action": "diskfull",
                        "count": -1}])
    rc = cdb_cli.main(BASE_ARGS + [
        "-o", db1, "--fault-plan", plan, "--metrics", mpath,
        reads_path])
    assert rc == resources.DISK_FULL_RC
    dump_path = flight_mod.default_out_path(mpath)
    assert os.path.exists(dump_path)
    dump = json.load(open(dump_path))
    assert dump["trigger"]["kind"] == "disk_full"
    assert dump["trigger"]["site"] == "db.payload"
