"""Test harness: pin tests to an 8-virtual-device CPU platform.

The container force-registers the experimental `axon` TPU backend via
sitecustomize (ignoring JAX_PLATFORMS), so we can't exclude it by env
var alone; instead we request 8 host CPU devices and set the default
device to CPU. Multi-chip sharding tests build their mesh from
jax.devices('cpu') explicitly. Benchmarks (bench.py) run on the real
chip outside pytest."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_default_device", jax.devices("cpu")[0])

# Tests must NOT share the persistent compile cache with TPU-tunnel
# processes: the tunnel's AOT helper caches CPU executables compiled
# with ITS machine features, and loading them here warns "Machine type
# ... doesn't match ... could lead to execution errors such as SIGILL"
# — observed as Fatal aborts late in full-suite runs (round 4). The
# CLI entry points call jaxcache.enable_cache(), which respects an
# already-configured dir, so pin a test-local one first.
jax.config.update("jax_compilation_cache_dir",
                  "/tmp/quorum_tpu_test_jaxcache")

# Hermetic lever resolution (ISSUE 11): an ambient autotune profile
# in ~/.cache/quorum_tpu/autotune would silently steer the round-7
# lever defaults (and stamp meta.autotune_profile into golden
# documents) machine-by-machine. Empty = profiles disabled
# (ops/tuning); tests that exercise profiles set their own paths.
os.environ.setdefault("QUORUM_AUTOTUNE_PROFILE", "")

import pytest  # noqa: E402

_last_module = [None]


@pytest.fixture(autouse=True)
def _clear_jax_caches_between_modules(request):
    """The suite compiles hundreds of CPU executables; letting them
    accumulate for the whole session has produced allocator aborts
    near the end of full runs (round 4). Dropping jax's caches at each
    module boundary bounds live executables at the cost of
    recompiling shared helpers per module."""
    mod = request.node.nodeid.split("::", 1)[0]
    if _last_module[0] is not None and _last_module[0] != mod:
        jax.clear_caches()
    _last_module[0] = mod
    yield


def cpu_devices(n=None):
    devs = jax.devices("cpu")
    return devs if n is None else devs[:n]
