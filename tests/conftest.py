"""Test harness: pin tests to an 8-virtual-device CPU platform.

The container force-registers the experimental `axon` TPU backend via
sitecustomize (ignoring JAX_PLATFORMS), so we can't exclude it by env
var alone; instead we request 8 host CPU devices and set the default
device to CPU. Multi-chip sharding tests build their mesh from
jax.devices('cpu') explicitly. Benchmarks (bench.py) run on the real
chip outside pytest."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_default_device", jax.devices("cpu")[0])

# Tests must NOT share the persistent compile cache with TPU-tunnel
# processes: the tunnel's AOT helper caches CPU executables compiled
# with ITS machine features, and loading them here warns "Machine type
# ... doesn't match ... could lead to execution errors such as SIGILL"
# — observed as Fatal aborts late in full-suite runs (round 4). The
# CLI entry points call jaxcache.enable_cache(), which respects an
# already-configured dir, so pin a test-local one first.
jax.config.update("jax_compilation_cache_dir",
                  "/tmp/quorum_tpu_test_jaxcache")

# Hermetic lever resolution (ISSUE 11): an ambient autotune profile
# in ~/.cache/quorum_tpu/autotune would silently steer the round-7
# lever defaults (and stamp meta.autotune_profile into golden
# documents) machine-by-machine. Empty = profiles disabled
# (ops/tuning); tests that exercise profiles set their own paths.
os.environ.setdefault("QUORUM_AUTOTUNE_PROFILE", "")

import pytest  # noqa: E402

# Concurrency sanitizer opt-in (ISSUE 12): QUORUM_TSAN=1 — on in
# ci/tier1.sh — wraps threading.Lock/RLock so every lock constructed
# from here on records per-thread acquisition order, keyed by
# construction site. An observed A->B / B->A inversion (two threads
# interleaving those paths deadlock) FAILS the test that observed it,
# with both acquisition stacks. Installed before test modules import
# the serve/telemetry stack so their locks are all wrapped.
#
# The compile sentinel (ISSUE 15, QUORUM_COMPILE_SENTINEL=1 — also on
# in ci/tier1.sh) rides the same import point: importing quorum_tpu
# here, BEFORE any test module imports the jit-bearing submodules,
# lets the package __init__ wrap jax.jit so every module-level
# `functools.partial(jax.jit, ...)` decorator binds the recording
# factory. Every jit-cache miss is ledgered against the
# COMPILE_BUDGET catalog (analysis/compile_budget.py); the autouse
# gate below fails the test that observed an overrun, a duplicate
# compile, or an unbudgeted site.
from quorum_tpu.analysis import compile_sentinel as _csent  # noqa: E402
from quorum_tpu.analysis import tsan as _tsan  # noqa: E402

if _tsan.enabled_by_env():
    _tsan.install()


@pytest.fixture(autouse=True)
def _tsan_inversion_gate():
    """Fail the test during which a lock-order inversion was first
    observed (QUORUM_TSAN=1 runs only). Background threads may
    surface an inversion a beat late; the stacks in the report point
    at the acquiring code either way."""
    if not _tsan.installed():
        yield
        return
    before = len(_tsan.violations())
    yield
    fresh = _tsan.violations()[before:]
    if fresh:
        pytest.fail("QUORUM_TSAN observed lock-order inversion(s):\n"
                    + "\n".join(_tsan.format_violation(v)
                                for v in fresh))


@pytest.fixture(autouse=True)
def _compile_budget_gate():
    """Fail the test during which the compile sentinel first observed
    a budget violation (QUORUM_COMPILE_SENTINEL=1 runs only): a site
    exceeding its declared executable allowance, an identical
    signature compiled twice in one cache epoch, or an unbudgeted
    jit compiling. The acquisition stack in the report points at the
    dispatching code."""
    if not _csent.installed():
        yield
        return
    before = len(_csent.violations())
    yield
    fresh = _csent.violations()[before:]
    if fresh:
        pytest.fail(
            "QUORUM_COMPILE_SENTINEL observed budget violation(s):\n"
            + "\n".join(_csent.format_violation(v) for v in fresh))


_last_module = [None]


@pytest.fixture(autouse=True)
def _clear_jax_caches_between_modules(request):
    """The suite compiles hundreds of CPU executables; letting them
    accumulate for the whole session has produced allocator aborts
    near the end of full runs (round 4). Dropping jax's caches at each
    module boundary bounds live executables at the cost of
    recompiling shared helpers per module."""
    mod = request.node.nodeid.split("::", 1)[0]
    if _last_module[0] is not None and _last_module[0] != mod:
        jax.clear_caches()
    _last_module[0] = mod
    yield


def cpu_devices(n=None):
    devs = jax.devices("cpu")
    return devs if n is None else devs[:n]
