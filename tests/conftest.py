"""Test harness: pin tests to an 8-virtual-device CPU platform.

The container force-registers the experimental `axon` TPU backend via
sitecustomize (ignoring JAX_PLATFORMS), so we can't exclude it by env
var alone; instead we request 8 host CPU devices and set the default
device to CPU. Multi-chip sharding tests build their mesh from
jax.devices('cpu') explicitly. Benchmarks (bench.py) run on the real
chip outside pytest."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_default_device", jax.devices("cpu")[0])


def cpu_devices(n=None):
    devs = jax.devices("cpu")
    return devs if n is None else devs[:n]
