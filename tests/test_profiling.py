"""Profiling surfaces: StageTimer accounting and the trace context."""

import os

from quorum_tpu.utils import vlog as vlog_mod
from quorum_tpu.utils.profiling import StageTimer, trace


def test_stage_timer_accumulates_and_reports(capsys):
    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("a"):
        pass
    with t.stage("b"):
        pass
    t.add_units("a", 1000)
    assert t.calls["a"] == 2
    assert t.calls["b"] == 1
    assert t.seconds["a"] >= 0.0
    old = vlog_mod.verbose
    vlog_mod.verbose = True
    try:
        t.report(total_units=2000)
    finally:
        vlog_mod.verbose = old
    err = capsys.readouterr().err
    assert "stage a" in err
    assert "stage b" in err
    assert "Gbases/hour" in err


def test_stage_timer_exception_still_counts():
    t = StageTimer()
    try:
        with t.stage("x"):
            raise ValueError("boom")
    except ValueError:
        pass
    assert t.calls["x"] == 1


def test_trace_noop_without_dir():
    with trace(None):
        pass
    with trace(""):
        pass


def test_trace_writes_profile(tmp_path):
    d = str(tmp_path / "prof")
    with trace(d):
        import jax.numpy as jnp

        _ = (jnp.zeros((8,)) + 1).sum()
    # jax.profiler.trace writes plugins/profile/<ts>/ under the dir
    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert found, "profiler trace directory is empty"


def test_trace_logs_dir_even_when_body_raises(tmp_path, capsys):
    """An interrupted profiled run is exactly when the pointer to the
    trace dir matters: the vlog must fire from the finally."""
    d = str(tmp_path / "prof")
    old = vlog_mod.verbose
    vlog_mod.verbose = True
    try:
        try:
            with trace(d):
                import jax.numpy as jnp

                _ = (jnp.zeros((4,)) + 1).sum()
                raise RuntimeError("interrupted")
        except RuntimeError:
            pass
    finally:
        vlog_mod.verbose = old
    assert "Wrote profiler trace" in capsys.readouterr().err


def test_stage_timer_zero_total_reports_zero_percent(capsys, monkeypatch):
    """Satellite (ISSUE 2): a no-work run prints explicit 0.0% rows,
    not sentinel-divided garbage percentages."""
    from quorum_tpu.utils import profiling as prof_mod

    now = [10.0]
    monkeypatch.setattr(prof_mod.time, "perf_counter", lambda: now[0])
    t = StageTimer()  # _t0 = 10.0; the clock never advances
    with t.stage("a"):
        pass
    old = vlog_mod.verbose
    vlog_mod.verbose = True
    try:
        t.report(total_units=0)
    finally:
        vlog_mod.verbose = old
    err = capsys.readouterr().err
    assert "stage a" in err
    assert "(  0.0%)" in err
    assert "nan" not in err and "inf" not in err
    # and the dict form stays schema-clean with a zero total
    d = t.as_dict()
    assert d["total_seconds"] == 0.0
    assert "units_per_hour" not in d


def test_stage_timer_add_time_accumulates():
    """add_time attributes externally-measured durations (the
    dispatch/wait split) without extra clock reads."""
    t = StageTimer()
    t.add_time("device_dispatch", 0.25)
    t.add_time("device_dispatch", 0.5, calls=2)
    t.add_time("device_wait", 1.0)
    assert t.seconds["device_dispatch"] == 0.75
    assert t.calls["device_dispatch"] == 3
    d = t.as_dict()
    assert d["stages"]["device_wait"]["seconds"] == 1.0
