"""Data-integrity tier tests (ISSUE 8): the CRC32C primitives, the v5
checksummed database format (round trip, v4 parity, per-section
corruption refusal), digest-bearing checkpoint/journal/replay
artifacts, the `corrupt` fault action, quorum-fsck, the integrity
metrics gate, and the representative serve warmup read.

The corruption sweep flips real bytes in real artifacts and asserts
the three-part contract everywhere: the load REFUSES (IntegrityError/
CheckpointError → rc 3 at the CLIs), the detection is COUNTED
(integrity_errors_total) and EVENTED (file/section/offset), and a v4
database — no digests — still loads unchanged.
"""

import conftest  # noqa: F401  (pins CPU devices)

import json
import os

import numpy as np
import pytest

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli
from quorum_tpu.cli import fsck as fsck_cli
from quorum_tpu.io import checkpoint as ckpt_mod
from quorum_tpu.io import db_format, integrity, packing
from quorum_tpu.ops import ctable
from quorum_tpu.telemetry.registry import MetricsRegistry
from quorum_tpu.utils import faults

from test_error_correct_cli import K, QUAL_THRESH, make_dataset


@pytest.fixture(autouse=True)
def _no_leaked_state():
    faults.reset()
    prev = integrity.install_registry(None)
    yield
    faults.reset()
    integrity.install_registry(prev)


@pytest.fixture()
def tracking_registry(tmp_path):
    """A real registry (with an events stream) installed as the
    ambient integrity sink, so tests can assert counters + events."""
    reg = MetricsRegistry(str(tmp_path / "m.json"),
                          events_path=str(tmp_path / "m.events.jsonl"))
    integrity.install_registry(reg)
    return reg


def _events(reg):
    reg.write()
    path = reg.events_path
    if not os.path.exists(path):
        return []
    return [json.loads(l) for l in open(path) if l.strip()]


# ---------------------------------------------------------------------------
# CRC32C primitives
# ---------------------------------------------------------------------------

def test_crc32c_known_vector_and_chaining():
    assert integrity.crc32c(b"123456789") == 0xE3069283  # iSCSI vector
    assert integrity.crc32c(b"") == 0
    data = np.random.default_rng(3).bytes(50_000)
    whole = integrity.crc32c(data)
    # chaining == one pass; vectorized path == scalar path
    assert integrity.crc32c(data[17:], integrity.crc32c(data[:17])) \
        == whole
    small = integrity._crc_scalar(
        np.frombuffer(data, np.uint8), 0xFFFFFFFF) ^ 0xFFFFFFFF
    assert small == whole
    # combine derives the concatenation's CRC from the parts'
    a, b = data[:20_000], data[20_000:]
    assert integrity.crc32c_combine(
        integrity.crc32c(a), integrity.crc32c(b), len(b)) == whole
    assert integrity.crc32c_combine(whole, 0, 0) == whole


def test_crc32c_accepts_ndarrays():
    arr = np.arange(1000, dtype=np.uint32)
    assert integrity.crc32c(arr) == integrity.crc32c(arr.tobytes())


def test_seal_check_seal_tamper():
    doc = integrity.seal({"cursor": 7, "bytes": 123})
    integrity.check_seal(doc, "test doc", "p")  # clean passes
    integrity.check_seal({"no": "seal"}, "test doc", "p")  # unsealed ok
    with pytest.raises(integrity.IntegrityError, match="self-digest"):
        integrity.check_seal(dict(doc, cursor=8), "test doc", "p")


# ---------------------------------------------------------------------------
# v5 database format
# ---------------------------------------------------------------------------

def _tiny_table(n=64, k=11):
    rng = np.random.default_rng(5)
    khi = rng.integers(0, 1 << 22, n).astype(np.uint32)
    klo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    vals = ((rng.integers(1, 100, n) << 1) | 1).astype(np.uint32)
    state, meta = ctable.tile_from_entries(khi, klo, vals, k, 7)
    return ctable.TileState(np.asarray(state.rows)), meta


def _entries(state, meta):
    khi, klo, vals = ctable.tile_iterate(state, meta)
    return sorted(zip(khi.tolist(), klo.tolist(), vals.tolist()))


def test_v5_roundtrip_v4_parity(tmp_path):
    state, meta = _tiny_table()
    p5, p4 = str(tmp_path / "a5.qdb"), str(tmp_path / "a4.qdb")
    db_format.write_db(p5, state, meta)
    db_format.write_db(p4, state, meta, db_version=4)
    s5, m5, h5 = db_format.read_db(p5, to_device=False)
    s4, m4, h4 = db_format.read_db(p4, to_device=False, verify="full")
    assert (h5["version"], h4["version"]) == (5, 4)
    assert _entries(s5, m5) == _entries(s4, m4)  # v4 loads unchanged

    # the v5 PAYLOAD is the v4 payload byte-for-byte — checksums ride
    # in the header and trailer only
    def payload(p):
        with open(p, "rb") as f:
            h = json.loads(f.readline())
            return f.read(h["value_bytes"])
    assert payload(p5) == payload(p4)
    # header carries the section digests; trailer the file digest
    assert h5["checksum"]["algo"] == "crc32c"
    assert set(h5["checksum"]["sections"]) == {"bucket_index",
                                               "entries"}
    _, problems = db_format.verify_db_file(p5)
    assert problems == []


def _flip(path, off, n=1):
    with open(path, "r+b") as f:
        f.seek(off)
        cur = f.read(n)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in cur))


def _layout(path):
    with open(path, "rb") as f:
        line = f.readline()
        h = json.loads(line)
    return len(line), h


def test_v5_corruption_refused_per_section(tmp_path, tracking_registry):
    state, meta = _tiny_table()
    src = str(tmp_path / "ok.qdb")
    db_format.write_db(src, state, meta)
    hlen, h = _layout(src)
    rows, vb = h["rows"], h["value_bytes"]
    spots = {
        "bucket_index": hlen + rows // 2,
        "entries": hlen + rows + 7,
        "trailer": hlen + vb + 20,
    }
    import shutil
    for want_section, off in spots.items():
        p = str(tmp_path / f"bad_{want_section}.qdb")
        shutil.copy(src, p)
        _flip(p, off)
        with pytest.raises(integrity.IntegrityError) as ei:
            db_format.read_db(p, to_device=False)
        assert ei.value.section == want_section
        # fsck pinpoints the same section
        _, problems = db_format.verify_db_file(p)
        assert any(sec == want_section for sec, _o, _m in problems)
    reg = tracking_registry
    assert reg.counter("integrity_errors_total").value >= len(spots)
    evs = [e for e in _events(reg) if e["event"] == "integrity_error"]
    assert evs and all(e.get("file") and e.get("section") for e in evs)


def test_v5_verify_modes(tmp_path, tracking_registry):
    state, meta = _tiny_table()
    p = str(tmp_path / "v.qdb")
    db_format.write_db(p, state, meta)
    hlen, h = _layout(p)
    db_format.read_db(p, to_device=False, verify="sample")
    # corrupt the trailer: full catches it, off skips checksums
    _flip(p, hlen + h["value_bytes"] + 20)
    with pytest.raises(integrity.IntegrityError):
        db_format.read_db(p, to_device=False, verify="full")
    s, m, _ = db_format.read_db(p, to_device=False, verify="off")
    assert _entries(s, m) == _entries(state, meta)
    with pytest.raises(ValueError, match="verify must be"):
        db_format.read_db(p, to_device=False, verify="paranoid")
    # verification telemetry: bytes counted, meta declared
    reg = tracking_registry
    assert reg.counter("integrity_bytes_verified_total").value > 0
    assert reg.meta.get("db_version") == 5
    assert reg.meta.get("verify_db") == "off"  # last load's mode


# ---------------------------------------------------------------------------
# the `corrupt` fault action
# ---------------------------------------------------------------------------

def test_corrupt_action_explicit_offset_and_modes(tmp_path):
    p = str(tmp_path / "f.bin")
    open(p, "wb").write(bytes(range(64)))
    faults.setup(json.dumps([{"site": "db.write", "action": "corrupt",
                              "offset": 10, "bytes": 3}]))
    faults.inject("db.write", path=p)
    data = open(p, "rb").read()
    assert data[10:13] == bytes(b ^ 0xFF for b in range(10, 13))
    assert data[:10] == bytes(range(10))
    faults.setup(json.dumps([{"site": "db.write", "action": "corrupt",
                              "offset": 5, "bytes": 2,
                              "mode": "zero"}]))
    faults.inject("db.write", path=p)
    assert open(p, "rb").read()[5:7] == b"\0\0"


def test_corrupt_action_seeded_deterministic(tmp_path):
    offs = []
    for name in ("a.bin", "b.bin"):
        p = str(tmp_path / name)
        open(p, "wb").write(b"\0" * 256)
        faults.setup(json.dumps([{"site": "db.write",
                                  "action": "corrupt", "seed": 9}]))
        faults.inject("db.write", path=p)
        data = open(p, "rb").read()
        hit = [i for i, b in enumerate(data) if b != 0]
        assert len(hit) == 1  # one flipped byte
        offs.append(hit[0])
        faults.reset()
    assert offs[0] == offs[1]  # same (seed, site, firing) -> same spot


def test_corrupt_action_requires_path():
    faults.setup(json.dumps([{"site": "stage1.insert",
                              "action": "corrupt"}]))
    with pytest.raises(faults.FaultError, match="no file path"):
        faults.inject("stage1.insert")


def test_corrupt_mode_validation():
    with pytest.raises(ValueError, match="corrupt mode"):
        faults.FaultPlan.parse([{"site": "x", "action": "corrupt",
                                 "mode": "scramble"}])
    with pytest.raises(ValueError, match="bytes"):
        faults.FaultPlan.parse([{"site": "x", "action": "corrupt",
                                 "bytes": 0}])


# ---------------------------------------------------------------------------
# checkpoint artifacts: digests refuse silent corruption
# ---------------------------------------------------------------------------

class _Stats:
    reads = bases = batches = grows = 0


class _Cfg:
    qual_thresh = 38
    batch_size = 64


def _save_snapshot(tmp_path):
    meta = ctable.TileMeta(k=11, bits=7, rb_log2=4)
    tag = np.arange(meta.rows * ctable.TILE,
                    dtype=np.uint32).reshape(meta.rows, ctable.TILE)
    acc = meta.rows * ctable.TSLOTS
    bstate = ctable.TBuildState(tag, np.ones(acc, np.uint32),
                                np.zeros(acc, np.uint32))
    ck = ckpt_mod.Stage1Checkpoint(str(tmp_path))
    ck.save(bstate, meta, _Cfg(), 5, _Stats(), ["r.fastq"])
    return ck


def test_stage1_snapshot_payload_digest(tmp_path, tracking_registry):
    ck = _save_snapshot(tmp_path)
    snap = ck.load()  # clean load passes + counts verified bytes
    assert snap.cursor == 5
    assert tracking_registry.counter(
        "integrity_bytes_verified_total").value > 0
    # flip one payload byte (past the header line)
    with open(ck.path, "rb") as f:
        hlen = len(f.readline())
    _flip(ck.path, hlen + 1000)
    with pytest.raises(ckpt_mod.CheckpointError, match="payload digest"):
        ck.load()
    assert tracking_registry.counter("integrity_errors_total").value >= 1


def test_stage1_snapshot_header_seal(tmp_path):
    ck = _save_snapshot(tmp_path)
    # tamper the header's cursor, keeping valid JSON and length: the
    # payload length check still passes, only the seal catches it
    with open(ck.path, "rb") as f:
        line = f.readline()
        payload = f.read()
    h = json.loads(line)
    h["cursor"] = 6  # splice a different resume point
    with open(ck.path, "wb") as f:
        f.write(json.dumps(h).encode() + b"\n")
        f.write(payload)
    with pytest.raises(ckpt_mod.CheckpointError, match="self-digest"):
        ck.load()


def test_journal_committed_range_digest(tmp_path, tracking_registry):
    prefix = str(tmp_path / "out")
    j = ckpt_mod.Stage2Journal(prefix)
    out, log = j.open_outputs(None)
    out.write("the committed record\n")
    out.flush()
    log.flush()
    j.commit(1, _ec_stats(), out.tell(), log.tell(), 64, {"db": "a"})
    out.write("torn tail past the commit")
    out.close()
    log.close()
    st = j.load()
    assert st["fa_crc32c"] == integrity.crc32c(b"the committed record\n")
    # torn tail alone resumes fine (truncated away)...
    out2, log2 = j.open_outputs(st)
    out2.close()
    log2.close()
    assert open(j.fa_partial).read() == "the committed record\n"
    # ...but corruption INSIDE the committed range refuses
    _flip(j.fa_partial, 4)
    with pytest.raises(ckpt_mod.CheckpointError, match="committed"):
        j.open_outputs(st)
    assert tracking_registry.counter("integrity_errors_total").value >= 1


def test_journal_resume_from_pre_digest_journal(tmp_path):
    """A journal written BEFORE the digest upgrade (no fa_crc32c)
    resumes, commits, and resumes AGAIN cleanly: the first resume
    must seed the CRC streams from the file content, not 0 — else
    the second resume's digest covers only post-resume bytes and
    refuses an undamaged file."""
    prefix = str(tmp_path / "out")
    j = ckpt_mod.Stage2Journal(prefix)
    out, log = j.open_outputs(None)
    out.write("first half\n")
    j.commit(1, _ec_stats(), out.tell(), log.tell(), 64)
    out.close()
    log.close()
    # strip the digests + seal, as a pre-ISSUE-8 release wrote it
    doc = json.load(open(j.path))
    for key in ("fa_crc32c", "log_crc32c", "crc32c"):
        doc.pop(key, None)
    with open(j.path, "w") as f:
        json.dump(doc, f)
    # resume 1: append + commit (now journals full-range digests)
    j2 = ckpt_mod.Stage2Journal(prefix)
    st = j2.load()
    out, log = j2.open_outputs(st)
    out.write("second half\n")
    j2.commit(2, _ec_stats(), out.tell(), log.tell(), 64)
    out.close()
    log.close()
    # resume 2: the full committed range must verify clean
    j3 = ckpt_mod.Stage2Journal(prefix)
    st = j3.load()
    assert st["fa_crc32c"] == integrity.crc32c(
        b"first half\nsecond half\n")
    out, log = j3.open_outputs(st)  # must NOT refuse
    out.close()
    log.close()
    assert open(j3.fa_partial).read() == "first half\nsecond half\n"


def test_journal_document_seal(tmp_path):
    prefix = str(tmp_path / "out")
    j = ckpt_mod.Stage2Journal(prefix)
    out, log = j.open_outputs(None)
    out.write("x\n")
    j.commit(1, _ec_stats(), out.tell(), log.tell(), 64)
    out.close()
    log.close()
    doc = json.load(open(j.path))
    doc["fa_bytes"] = 999  # a flipped count that still parses
    with open(j.path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ckpt_mod.CheckpointError, match="self-digest"):
        j.load()


def _ec_stats():
    class S:
        reads = corrected = skipped = bases_in = bases_out = 0
    return S()


def test_replay_cache_batch_digest(tmp_path, tracking_registry):
    from quorum_tpu.io import fastq
    cache = ckpt_mod.ReplayCache(str(tmp_path))
    ident = {"inputs": ["r.fastq"], "batch_size": 4}
    w = cache.start(ident, cap_bytes=1 << 30)
    codes = np.zeros((4, 20), np.int8)
    quals = np.full((4, 20), 60, np.uint8)
    lengths = np.full(4, 20, np.int32)
    pk = packing.pack_reads(codes, quals, lengths, thresholds=(38,))
    batch = fastq.ReadBatch(codes=codes, quals=quals, lengths=lengths,
                            headers=["a", "b", "c", "d"], n=4)
    w.add(batch, pk.compact())
    assert w.finish()
    # clean replay round-trips
    rd = cache.load(ident)
    assert rd is not None
    got = list(rd.batches())
    assert len(got) == 1 and got[0][0].n == 4
    # corrupt the batch payload: iteration refuses
    _flip(cache._batch_path(0), 100)
    with pytest.raises(ckpt_mod.CheckpointError, match="digest"):
        list(cache.load(ident).batches())
    assert tracking_registry.counter("integrity_errors_total").value >= 1
    # tamper the manifest: load refuses loudly (not a silent re-parse)
    doc = json.load(open(cache.manifest_path))
    doc["n_batches"] = 2
    with open(cache.manifest_path, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ckpt_mod.CheckpointError, match="self-digest"):
        cache.load(ident)


# ---------------------------------------------------------------------------
# end to end: corrupt DB -> stage-2 rc 3 + counters; fsck pinpoints
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pipeline(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("integ")
    reads_path, reads, quals = make_dataset(tmp)
    db_path = str(tmp / "db.jf")
    assert cdb_cli.main(["-s", "64k", "-m", str(K), "-b", "7",
                         "-q", str(QUAL_THRESH), "-o", db_path,
                         reads_path]) == 0
    return str(tmp), reads_path, db_path


def test_ec_cli_refuses_corrupt_db_rc3(pipeline, tmp_path):
    tmp, reads_path, db_path = pipeline
    import shutil
    bad = str(tmp_path / "bad.jf")
    shutil.copy(db_path, bad)
    hlen, h = _layout(bad)
    _flip(bad, hlen + h["rows"] + 11)  # inside the entry payload
    mpath = str(tmp_path / "m.json")
    rc = ec_cli.main(["-p", "4", "--batch-size", "64", "-o",
                      str(tmp_path / "o"), "--metrics", mpath,
                      bad, reads_path])
    assert rc == ckpt_mod.NON_RETRYABLE_RC  # 3: deterministic refusal
    doc = json.load(open(mpath))
    assert doc["counters"]["integrity_errors_total"] >= 1
    assert doc["meta"]["status"] == "error"


def test_ec_cli_verify_off_flag(pipeline, tmp_path):
    # --verify-db=off on a CLEAN db still corrects (declares the mode)
    tmp, reads_path, db_path = pipeline
    mpath = str(tmp_path / "m.json")
    rc = ec_cli.main(["-p", "4", "--batch-size", "64", "--verify-db",
                      "off", "-o", str(tmp_path / "o"),
                      "--metrics", mpath, db_path, reads_path])
    assert rc == 0
    doc = json.load(open(mpath))
    assert doc["meta"]["verify_db"] == "off"
    assert doc["meta"]["db_version"] == 5
    assert "integrity_errors_total" in doc["counters"]  # at 0
    assert doc["counters"]["integrity_errors_total"] == 0


def test_fsck_cli(pipeline, tmp_path, capsys):
    tmp, reads_path, db_path = pipeline
    assert fsck_cli.main([db_path]) == 0
    import shutil
    bad = str(tmp_path / "bad.jf")
    shutil.copy(db_path, bad)
    hlen, h = _layout(bad)
    _flip(bad, hlen + 3)
    assert fsck_cli.main([bad]) == 1
    err = capsys.readouterr().err
    assert "bucket_index" in err and "BAD" in err
    assert fsck_cli.main([str(tmp_path / "nothing.here")]) == 2


def test_fsck_repairs_torn_journal(tmp_path, capsys):
    prefix = str(tmp_path / "out")
    j = ckpt_mod.Stage2Journal(prefix)
    out, log = j.open_outputs(None)
    out.write("committed\n")
    j.commit(1, _ec_stats(), out.tell(), log.tell(), 64)
    out.write("torn")
    out.close()
    log.close()
    assert fsck_cli.main([j.path]) == 1  # torn tail flagged
    assert fsck_cli.main(["--repair", j.path]) == 0
    assert open(j.fa_partial).read() == "committed\n"
    assert fsck_cli.main([j.path]) == 0


# ---------------------------------------------------------------------------
# metrics_check integrity gate (satellite: schema unit test)
# ---------------------------------------------------------------------------

def _doc(meta=None, counters=None):
    return {"schema": "quorum-tpu-metrics/1", "meta": meta or {},
            "counters": counters or {}, "gauges": {},
            "histograms": {}, "timers": {}}


def test_metrics_check_requires_integrity_counters(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import metrics_check

    both = {"integrity_errors_total": 0,
            "integrity_bytes_verified_total": 123}
    # declared via db_version >= 5
    errs = metrics_check._check_integrity_names(
        _doc(meta={"db_version": 5}))
    assert len(errs) == 2
    assert not metrics_check._check_integrity_names(
        _doc(meta={"db_version": 5}, counters=both))
    # declared via verify_db
    errs = metrics_check._check_integrity_names(
        _doc(meta={"verify_db": "sample"}))
    assert len(errs) == 2
    # v4 documents are not held to it
    assert not metrics_check._check_integrity_names(
        _doc(meta={"db_version": 4}))
    assert not metrics_check._check_integrity_names(_doc())
    # end to end through the file checker
    p = str(tmp_path / "d.json")
    json.dump(_doc(meta={"db_version": 5}, counters=both),
              open(p, "w"))
    assert metrics_check.main([p, "-q"]) == 0
    json.dump(_doc(meta={"db_version": 5}), open(p, "w"))
    assert metrics_check.main([p, "-q"]) == 1


# ---------------------------------------------------------------------------
# representative warmup read (satellite)
# ---------------------------------------------------------------------------

def test_representative_read_walks_db_kmers(pipeline):
    from quorum_tpu.ops import mer as mer_mod
    from quorum_tpu.serve.engine import representative_read
    tmp, reads_path, db_path = pipeline
    state, meta, _ = db_format.read_db(db_path, to_device=True)
    host_state, _, _ = db_format.read_db(db_path, to_device=False)
    r = representative_read(state, meta, 60)
    assert len(r) == 60 and set(r) <= set("ACGT")
    assert r != "A" * 60
    hits = 0
    for i in range(60 - K + 1):
        fh, fl = mer_mod.pack_kmer(r[i:i + K], K)
        chi, clo = mer_mod.canonical_py(fh, fl, K)
        if db_format.db_lookup_np(host_state, meta, chi, clo):
            hits += 1
    # the walk only leaves the DB when the sampled contigs run out —
    # the overwhelming majority of its k-mers must be present (the
    # all-A read this replaces had essentially none)
    assert hits >= (60 - K + 1) * 3 // 4
    # deterministic per database
    assert representative_read(state, meta, 60) == r
    with pytest.raises(RuntimeError, match="below k"):
        representative_read(state, meta, K - 1)
