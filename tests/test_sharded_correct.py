"""Multi-chip stage 2: the data-parallel shard_map corrector must be
bit-identical to the single-chip corrector (models/corrector, itself
pinned against the oracle), and the sharded->single table relayout must
preserve every entry."""

import conftest
import numpy as np
import jax.numpy as jnp
import pytest

from quorum_tpu.models import corrector
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.ops import table
from quorum_tpu.parallel import sharded, sharded_correct

K = 11


def make_inputs(seed, n_reads, read_len=60, glen=None, err=0.03):
    rng = np.random.default_rng(seed)
    if glen is None:
        glen = max(150, n_reads * 8)  # ~8x coverage so anchors exist
    genome = rng.integers(0, 4, size=glen).astype(np.int8)
    return sharded_correct._synthetic_reads(rng, genome, n_reads, read_len,
                                            err)


def build_single(codes, quals, qual_thresh=53):
    from quorum_tpu.models.create_database import extract_observations

    meta = table.TableMeta(k=K, bits=7, size_log2=13)
    st = table.make_table(meta)
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), K, qual_thresh)
    st, full = table.add_kmer_batch(st, meta, chi, clo, q, valid)
    assert not bool(full)
    return st, meta


def test_to_read_layout_preserves_entries():
    codes, quals, _ = make_inputs(0, 32)
    mesh = sharded.make_mesh(4, devices=conftest.cpu_devices(4))
    smeta = sharded.ShardedMeta(k=K, bits=7, local_size_log2=10, n_shards=4)
    sstate, smeta = sharded.build_database_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, smeta,
        qual_thresh=53)
    st, meta = sharded_correct.to_read_layout(sstate, smeta)

    svals = np.asarray(sstate.vals)
    skh = np.asarray(sstate.keys_hi)
    skl = np.asarray(sstate.keys_lo)
    occ = svals != table.EMPTY_VAL
    assert occ.sum() > 0
    # every sharded entry must be found at its full value in the
    # relayouted table via the plain single-chip lookup
    got = np.asarray(table.lookup(st, meta, jnp.asarray(skh[occ]),
                                  jnp.asarray(skl[occ])))
    assert np.array_equal(got, svals[occ])
    # and the relayouted table holds nothing else
    occ1, _, _ = table.table_stats(st, meta)
    assert int(occ1) == int(occ.sum())


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_dp_corrector_matches_single_chip(n_shards):
    codes, quals, lengths = make_inputs(n_shards, 8 * n_shards)
    st, meta = build_single(codes, quals)
    cfg = ECConfig(k=K, cutoff=2, poisson_dtype="float32")

    single = corrector.correct_batch(st, meta, codes, quals, lengths, cfg)

    mesh = sharded.make_mesh(n_shards, devices=conftest.cpu_devices(n_shards))
    step = sharded_correct.correct_step(mesh, meta, cfg)
    rep = sharded_correct.replicate_table(st, mesh)
    res = step(rep, codes, quals, lengths)

    assert np.array_equal(np.asarray(res.out), np.asarray(single.out))
    assert np.array_equal(np.asarray(res.start), np.asarray(single.start))
    assert np.array_equal(np.asarray(res.end), np.asarray(single.end))
    assert np.array_equal(np.asarray(res.status), np.asarray(single.status))
    for fld in corrector.LogState._fields:
        assert np.array_equal(np.asarray(getattr(res.fwd_log, fld)),
                              np.asarray(getattr(single.fwd_log, fld)))
        assert np.array_equal(np.asarray(getattr(res.bwd_log, fld)),
                              np.asarray(getattr(single.bwd_log, fld)))
    # the batch must actually exercise correction
    assert int(np.sum(np.asarray(res.status) == corrector.OK)) > 0
    assert int(np.asarray(res.fwd_log.n).sum()) > 0


def test_dp_corrector_with_contaminant():
    n_shards = 4
    codes, quals, lengths = make_inputs(99, 8 * n_shards)
    st, meta = build_single(codes, quals)
    cfg = ECConfig(k=K, cutoff=2, poisson_dtype="float32")

    # contaminant set: the k-mers of one read
    cmeta = table.TableMeta(k=K, bits=1, size_log2=8)
    cstate = table.make_table(cmeta)
    from quorum_tpu.models.create_database import extract_observations
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes[:1]), jnp.asarray(quals[:1]), K, 0)
    cstate, full = table.add_kmer_batch(cstate, cmeta, chi, clo, q, valid)
    assert not bool(full)

    single = corrector.correct_batch(st, meta, codes, quals, lengths, cfg,
                                     contam=(cstate, cmeta))

    mesh = sharded.make_mesh(n_shards, devices=conftest.cpu_devices(n_shards))
    step = sharded_correct.correct_step(mesh, meta, cfg, cmeta=cmeta)
    rep = sharded_correct.replicate_table(st, mesh)
    crep = sharded_correct.replicate_table(cstate, mesh)
    res = step(rep, codes, quals, lengths, crep)

    assert np.array_equal(np.asarray(res.status), np.asarray(single.status))
    assert np.array_equal(np.asarray(res.out), np.asarray(single.out))
    # the contaminated read must be flagged
    assert int(np.asarray(res.status)[0]) == corrector.ST_CONTAMINANT


def test_end_to_end_dryrun():
    mesh = sharded.make_mesh(4, devices=conftest.cpu_devices(4))
    sharded_correct.dryrun(mesh, 4)
