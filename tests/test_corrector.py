"""Device corrector vs oracle parity, including adversarial genomes
that force every branch of the extension logic (VERDICT r1 #1/#6).

Each scenario builds an explicit (count, quality) k-mer database (so
branch counts are controlled exactly), corrects a read batch on device,
and requires bit-exact agreement with the oracle on (ok, error, seq,
fwd_log, bwd_log, start, end). Oracle branch counters assert that the
adversarial inputs actually reach the paths they target.
"""

import conftest  # noqa: F401  (pins CPU devices)

import numpy as np
import jax.numpy as jnp
import pytest

from quorum_tpu.ops import ctable, mer
from quorum_tpu.models.oracle import DictDB, OracleCorrector
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.models import corrector

K = 9
BASES = "ACGT"


def table_from_dict(d, k, size_log2=14):
    """Device tile table + DictDB with exact (count, qual) per
    canonical mer."""
    khis, klos, vals = [], [], []
    dd = {}
    for s, (cnt, q) in d.items():
        hi, lo = mer.pack_kmer(s, k)
        chi, clo = mer.canonical_py(hi, lo, k)
        key = (int(chi) << 32) | int(clo)
        dd[key] = (cnt, q)
        khis.append(chi)
        klos.append(clo)
        vals.append((cnt << 1) | q)
    state, meta = ctable.tile_from_entries(
        np.array(khis, np.uint32), np.array(klos, np.uint32),
        np.array(vals, np.uint32), k, 7)
    return state, meta, DictDB(dd, k)


def add_seq(db, s, cnt, q, k=K):
    """Count all canonical k-mers of s into the dict DB."""
    for i in range(len(s) - k + 1):
        hi, lo = mer.pack_kmer(s[i: i + k], k)
        chi, clo = mer.canonical_py(hi, lo, k)
        key_s = mer.unpack_kmer(chi, clo, k)
        cur = db.get(key_s, (0, 0))
        db[key_s] = (min(cur[0] + cnt, 127), max(cur[1], q))


def run_compare(state, meta, db, reads, quals_list, cfg, contam_set=None,
                contam_tab=None, min_len=16):
    """Correct on device and with the oracle; assert exact agreement.
    Returns the oracle (for counter assertions)."""
    b = len(reads)
    l = max(max(len(r) for r in reads), min_len)
    codes = np.full((b, l), -2, np.int8)
    quals = np.zeros((b, l), np.uint8)
    lengths = np.zeros((b,), np.int32)
    for i, (r, q) in enumerate(zip(reads, quals_list)):
        codes[i, : len(r)] = mer.seq_to_codes(r)
        quals[i, : len(r)] = np.frombuffer(q.encode(), np.uint8)
        lengths[i] = len(r)
    oc = OracleCorrector(db, cfg, contaminant=contam_set)
    res = corrector.correct_batch(state, meta, codes, quals, lengths, cfg,
                                  contam=contam_tab)
    dev = corrector.finish_batch(res, b, cfg)
    for i in range(b):
        o = oc.correct(reads[i], quals_list[i])
        d = dev[i]
        assert (o.ok, o.error, o.seq, o.fwd_log, o.bwd_log, o.start, o.end) \
            == (d.ok, d.error, d.seq, d.fwd_log, d.bwd_log, d.start, d.end), \
            f"read {i}: {reads[i]}\noracle={o}\ndevice={d}"
    return oc


def _rng():
    return np.random.default_rng(7)


def rand_seq(rng, n):
    return "".join(BASES[c] for c in rng.integers(0, 4, n))


def rand_quals(rng, n, lo=34, hi=70):
    return "".join(chr(int(c)) for c in rng.integers(lo, hi, n))


# ---------------------------------------------------------------------------
# Randomized scenarios (each asserts its target paths were hit)
# ---------------------------------------------------------------------------

def test_branching_genome_poisson_keep():
    rng = _rng()
    core = rand_seq(rng, 40)
    db = {}
    add_seq(db, core[:20] + "A" + core[20:], 10, 1)
    add_seq(db, core[:20] + "C" + core[20:], 7, 1)
    state, meta, dictdb = table_from_dict(db, K)
    reads, quals = [], []
    for _ in range(64):
        src = core[:20] + ("A" if rng.random() < 0.5 else "C") + core[20:]
        start = int(rng.integers(0, max(len(src) - 30, 1)))
        ln = int(min(len(src) - start, 20 + rng.integers(0, 12)))
        r = list(src[start: start + ln])
        for _ in range(rng.integers(0, 3)):
            r[rng.integers(0, ln)] = BASES[rng.integers(0, 4)]
        reads.append("".join(r))
        quals.append(rand_quals(rng, ln))
    cfg = ECConfig(k=K, cutoff=30, poisson_dtype="float32")
    oc = run_compare(state, meta, dictdb, reads, quals, cfg)
    assert oc.counters["keep_poisson"] > 0
    assert oc.counters["count1_sub"] > 0


def test_low_coverage_poisson_reject_and_tiebreak():
    rng = _rng()
    g = rand_seq(rng, 300)
    db = {}
    add_seq(db, g, 3, 1)
    add_seq(db, rand_seq(rng, 60), 5, 0)
    state, meta, dictdb = table_from_dict(db, K)
    reads, quals = [], []
    for _ in range(64):
        start = int(rng.integers(0, 260))
        ln = int(min(300 - start, 25 + rng.integers(0, 15)))
        r = list(g[start: start + ln])
        for _ in range(rng.integers(0, 3)):
            r[rng.integers(0, ln)] = BASES[rng.integers(0, 4)]
        if rng.random() < 0.3:
            r[rng.integers(0, ln)] = "N"
        reads.append("".join(r))
        quals.append(rand_quals(rng, ln))
    cfg = ECConfig(k=K, cutoff=8, qual_cutoff=60, poisson_dtype="float32")
    oc = run_compare(state, meta, dictdb, reads, quals, cfg)
    assert oc.counters["poisson_rejected"] > 0
    assert oc.counters["ambiguous"] > 0
    assert oc.counters["tiebreak_next_base"] > 0
    assert oc.counters["keep_cutoff_or_qual"] > 0


def test_window_trip_rewind():
    rng = _rng()
    g = rand_seq(rng, 300)
    db = {}
    add_seq(db, g, 3, 1)
    state, meta, dictdb = table_from_dict(db, K)
    reads, quals = [], []
    for _ in range(64):
        start = int(rng.integers(0, 260))
        ln = int(min(300 - start, 40))
        r = list(g[start: start + ln])
        p0 = int(rng.integers(0, max(ln - 8, 1)))
        for j in range(int(rng.integers(2, 5))):
            r[min(p0 + j * 2, ln - 1)] = BASES[rng.integers(0, 4)]
        reads.append("".join(r))
        quals.append(rand_quals(rng, ln))
    cfg = ECConfig(k=K, cutoff=30, window=6, error=2,
                   poisson_dtype="float32")
    oc = run_compare(state, meta, dictdb, reads, quals, cfg)
    assert oc.counters["window_trip"] > 0


def test_homo_trim():
    rng = _rng()
    g = rand_seq(rng, 150) + "A" * 30 + rand_seq(rng, 40)
    db = {}
    add_seq(db, g, 8, 1)
    state, meta, dictdb = table_from_dict(db, K)
    reads, quals = [], []
    for _ in range(48):
        start = int(rng.integers(0, 170))
        ln = int(min(len(g) - start, 45))
        r = list(g[start: start + ln])
        if rng.random() < 0.5:
            r[rng.integers(0, ln)] = BASES[rng.integers(0, 4)]
        reads.append("".join(r))
        quals.append(rand_quals(rng, ln))
    cfg = ECConfig(k=K, cutoff=30, homo_trim=3, poisson_dtype="float32")
    run_compare(state, meta, dictdb, reads, quals, cfg)


@pytest.mark.parametrize("trim", [False, True])
def test_contaminants(trim):
    rng = _rng()
    g = rand_seq(rng, 300)
    db = {}
    add_seq(db, g, 5, 1)
    state, meta, dictdb = table_from_dict(db, K)
    adapter = rand_seq(rng, 20)
    cdb = {}
    add_seq(cdb, adapter, 1, 1)
    cstate, cmeta, cdict = table_from_dict(cdb, K)
    contam_set = set(cdict.d.keys())
    reads, quals = [], []
    for _ in range(48):
        start = int(rng.integers(0, 260))
        ln = int(min(300 - start, 35))
        r = g[start: start + ln]
        if rng.random() < 0.4:
            ins = int(rng.integers(0, ln - 5))
            r = r[:ins] + adapter[:10] + r[ins:]
        reads.append(r)
        quals.append(rand_quals(rng, len(r)))
    cfg = ECConfig(k=K, cutoff=8, trim_contaminant=trim,
                   poisson_dtype="float32")
    run_compare(state, meta, dictdb, reads, quals, cfg,
                contam_set=contam_set, contam_tab=(cstate, cmeta))


def test_edge_reads():
    rng = _rng()
    g = rand_seq(rng, 120)
    db = {}
    add_seq(db, g, 5, 1)
    state, meta, dictdb = table_from_dict(db, K)
    reads = [rand_seq(rng, K - 1), "N" * 20, rand_seq(rng, 30),
             "ACGT", g[:K], g[: K + 1], g[5: 5 + K + 2], g]
    quals = [rand_quals(rng, len(r)) for r in reads]
    cfg = ECConfig(k=K, cutoff=8, poisson_dtype="float32")
    run_compare(state, meta, dictdb, reads, quals, cfg)


def test_mixed_lengths_and_mismatched_k():
    rng = _rng()
    g = rand_seq(rng, 200)
    db = {}
    add_seq(db, g, 6, 1)
    state, meta, dictdb = table_from_dict(db, K)
    # contaminant set with wrong k must be rejected (cc:703-705)
    cdb = {}
    add_seq(cdb, rand_seq(rng, 30), 1, 1, k=K + 2)
    cstate_bad, cmeta_bad = corrector._dummy_contam(K + 2)
    cfg = ECConfig(k=K, cutoff=8, poisson_dtype="float32")
    with pytest.raises(ValueError, match="mer length"):
        corrector.correct_batch(state, meta, np.zeros((4, 16), np.int8),
                                np.zeros((4, 16), np.uint8),
                                np.full((4,), 16, np.int32), cfg,
                                contam=(cstate_bad, cmeta_bad))


# ---------------------------------------------------------------------------
# Targeted single-read branch tests
# ---------------------------------------------------------------------------

def _mk_read(seq, qual_char="F"):
    return seq, qual_char * len(seq)


def test_ambiguous_substitution():
    """Error at a branch point with distinct branch counts: the unique
    closest-to-prev candidate wins -> ambig substitution logged."""
    rng = _rng()
    core = rand_seq(rng, 40)
    db = {}
    branch_a = core[:20] + "A" + core[20:]
    branch_c = core[:20] + "C" + core[20:]
    add_seq(db, branch_a, 10, 1)
    add_seq(db, branch_c, 7, 1)
    state, meta, dictdb = table_from_dict(db, K)
    # read follows branch A but has G at the branch point
    read = branch_a[:20] + "G" + branch_a[21:35]
    r, q = _mk_read(read)
    cfg = ECConfig(k=K, cutoff=30, poisson_dtype="float32")
    oc = run_compare(state, meta, dictdb, [r], [q], cfg)
    assert oc.counters["ambig_sub"] > 0
    # and the correction picked A (count 10+7=17 prefix, |10-17| < |7-17|)
    o = OracleCorrector(dictdb, cfg).correct(r, q)
    assert "20:sub:G-A" in o.fwd_log


def _set_mer(db, window, cnt, q):
    hi, lo = mer.pack_kmer(window, K)
    chi, clo = mer.canonical_py(hi, lo, K)
    db[mer.unpack_kmer(chi, clo, K)] = (cnt, q)


def test_tiebreak_overflow_dead_code():
    """prev_count <= min_count at an ambiguous branch takes the
    reference's int-overflow dead-code path: no substitution happens
    and the original base is kept (error_correct_reads.cc:520)."""
    rng = _rng()
    pre = rand_seq(rng, 20)
    post = rand_seq(rng, 20)
    db = {}
    # low-coverage prefix: every pre window count 1 -> prev_count == 1
    # == min_count when the branch is reached
    for i in range(len(pre) - K + 1):
        _set_mer(db, pre[i: i + K], 1, 1)
    # branch variants with count 2 (> min_count, < cutoff,
    # poisson-rejected) plus their continuations (for `success`)
    for x in "AC":
        _set_mer(db, (pre + x)[-K:], 2, 1)
        _set_mer(db, (pre + x + post[0])[-K:], 2, 1)
    state, meta, dictdb = table_from_dict(db, K)
    read = pre + "A" + post[:10]
    r, q = _mk_read(read)
    cfg = ECConfig(k=K, cutoff=30, anchor_count=1, poisson_dtype="float32")
    oc = run_compare(state, meta, dictdb, [r], [q], cfg)
    assert oc.counters["tiebreak_overflow_deadcode"] > 0
    o = OracleCorrector(dictdb, cfg).correct(r, q)
    assert o.ok and "sub" not in o.fwd_log
    # the branch base itself must have been kept
    assert o.seq[20] == "A"


def test_all_alternatives_low_quality_truncates():
    """count>1 at level 0 with ori count 0 -> truncation
    (trunc_lq_alts); with ori == N -> trunc_n_lq."""
    rng = _rng()
    pre = rand_seq(rng, 20)
    post = rand_seq(rng, 20)
    db = {}
    add_seq(db, pre, 5, 1)  # HQ anchor region
    # two LQ-only branch variants
    add_seq(db, pre + "A" + post, 2, 0)
    add_seq(db, pre + "C" + post, 2, 0)
    # remove quality from overlap: rebuild dict so pre mers stay HQ
    for i in range(len(pre) - K + 1):
        hi, lo = mer.pack_kmer(pre[i: i + K], K)
        chi, clo = mer.canonical_py(hi, lo, K)
        s = mer.unpack_kmer(chi, clo, K)
        cnt, _ = db[s]
        db[s] = (cnt, 1)
    state, meta, dictdb = table_from_dict(db, K)
    cfg = ECConfig(k=K, cutoff=30, poisson_dtype="float32")
    r1, q1 = _mk_read(pre + "G" + post[:8])
    r2, q2 = _mk_read(pre + "N" + post[:8])
    oc = run_compare(state, meta, dictdb, [r1, r2], [q1, q2], cfg)
    assert oc.counters["trunc_lq_alts"] > 0
    assert oc.counters["trunc_n_lq"] > 0


def test_n_with_no_eligible_alternative_truncates():
    """N base, multiple HQ alternatives but all counts <= min_count:
    check_code stays -1 -> truncation (trunc_n_no_sub)."""
    rng = _rng()
    pre = rand_seq(rng, 20)
    post = rand_seq(rng, 20)
    db = {}
    add_seq(db, pre, 5, 1)
    add_seq(db, pre + "A" + post, 1, 1)
    add_seq(db, pre + "C" + post, 1, 1)
    for i in range(len(pre) - K + 1):
        hi, lo = mer.pack_kmer(pre[i: i + K], K)
        chi, clo = mer.canonical_py(hi, lo, K)
        s = mer.unpack_kmer(chi, clo, K)
        cnt, _ = db[s]
        db[s] = (5, 1)
    state, meta, dictdb = table_from_dict(db, K)
    cfg = ECConfig(k=K, cutoff=30, poisson_dtype="float32")
    r, q = _mk_read(pre + "N" + post[:8])
    oc = run_compare(state, meta, dictdb, [r], [q], cfg)
    assert oc.counters["trunc_n_no_sub"] > 0


def test_quality_level_reset_in_gba():
    """A higher-quality variant with a lower count beats a low-quality
    variant (the level-reset loop of get_best_alternatives,
    mer_database.hpp:313-324)."""
    rng = _rng()
    pre = rand_seq(rng, 20)
    post = rand_seq(rng, 20)
    db = {}
    add_seq(db, pre, 9, 1)
    add_seq(db, pre + "A" + post, 5, 0)   # LQ, higher count
    add_seq(db, pre + "C" + post, 3, 1)   # HQ, lower count -> wins
    for i in range(len(pre) - K + 1):
        hi, lo = mer.pack_kmer(pre[i: i + K], K)
        chi, clo = mer.canonical_py(hi, lo, K)
        s = mer.unpack_kmer(chi, clo, K)
        cnt, _ = db[s]
        db[s] = (9, 1)
    state, meta, dictdb = table_from_dict(db, K)
    cfg = ECConfig(k=K, cutoff=30, poisson_dtype="float32")
    r, q = _mk_read(pre + "A" + post[:8])
    oc = run_compare(state, meta, dictdb, [r], [q], cfg)
    assert oc.counters["count1_sub"] > 0
    o = OracleCorrector(dictdb, cfg).correct(r, q)
    assert "20:sub:A-C" in o.fwd_log


def test_force_truncate_binary_parity():
    """Homo-trim force_truncate drops backward entries *inside* the kept
    region (inverted operator>=, err_log.hpp:42-46) — byte parity with
    the compiled binary, asserted on the rendered annotations."""
    rng = _rng()
    g = rand_seq(rng, 60) + "G" * 25
    db = {}
    add_seq(db, g, 8, 1)
    state, meta, dictdb = table_from_dict(db, K)
    # error early in the read -> backward-log substitution entry; the
    # 3' homopolymer triggers the trim above it
    read = list(g[10: 10 + 60])
    err_pos = 3
    orig = read[err_pos]
    alt = next(b for b in BASES if b != orig)
    read[err_pos] = alt
    r = "".join(read)
    q = "F" * len(r)
    cfg = ECConfig(k=K, cutoff=30, homo_trim=3, skip=25,
                   poisson_dtype="float32")
    oc = run_compare(state, meta, dictdb, [r], [q], cfg)
    o = OracleCorrector(dictdb, cfg).correct(r, q)
    if o.ok and "5_trunc" not in o.bwd_log and o.bwd_log:
        # the backward sub annotation must have been dropped only if its
        # raw position <= trim point; construct guarantees it is inside
        raise AssertionError(f"unexpected bwd log: {o.bwd_log}")


# ---------------------------------------------------------------------------
# Tile-backend parity: the same scenarios through the ctable tile table
# ---------------------------------------------------------------------------

from quorum_tpu.ops import ctable  # noqa: E402


def tile_from_dict(d, k):
    """Tile device table + DictDB with exact (count, qual) per mer."""
    khis, klos, vals = [], [], []
    dd = {}
    for s, (cnt, q) in d.items():
        hi, lo = mer.pack_kmer(s, k)
        chi, clo = mer.canonical_py(hi, lo, k)
        dd[(int(chi) << 32) | int(clo)] = (cnt, q)
        khis.append(chi)
        klos.append(clo)
        vals.append((cnt << 1) | q)
    state, meta = ctable.tile_from_entries(
        np.array(khis, np.uint32), np.array(klos, np.uint32),
        np.array(vals, np.uint32), k, bits=7)
    return state, meta, DictDB(dd, k)


def test_tile_backend_matches_wide_and_oracle():
    """A coverage-rich random-genome scenario through BOTH table
    backends: device-on-tile must equal device-on-wide must equal the
    oracle, including substitutions, truncations, and window trips."""
    rng = _rng()
    genome = rand_seq(rng, 300)
    db = {}
    add_seq(db, genome, 30, 1)
    wstate, wmeta, dictdb = table_from_dict(db, K)
    tstate, tmeta, _ = tile_from_dict(db, K)

    reads, quals_list = [], []
    for _ in range(48):
        start = int(rng.integers(0, len(genome) - 60))
        r = list(genome[start:start + 60])
        for _e in range(int(rng.integers(0, 3))):
            p = int(rng.integers(0, len(r)))
            r[p] = BASES[int(rng.integers(0, 4))]
        reads.append("".join(r))
        quals_list.append(rand_quals(rng, 60))
    cfg = ECConfig(k=K, cutoff=4, poisson_dtype="float32")

    b = len(reads)
    l = max(len(r) for r in reads)
    codes = np.full((b, l), -2, np.int8)
    quals = np.zeros((b, l), np.uint8)
    lengths = np.zeros((b,), np.int32)
    for i, (r, q) in enumerate(zip(reads, quals_list)):
        codes[i, :len(r)] = mer.seq_to_codes(r)
        quals[i, :len(r)] = np.frombuffer(q.encode(), np.uint8)
        lengths[i] = len(r)

    wres = corrector.correct_batch(wstate, wmeta, codes, quals, lengths, cfg)
    tres = corrector.correct_batch(tstate, tmeta, codes, quals, lengths, cfg)
    wdev = corrector.finish_batch(wres, b, cfg)
    tdev = corrector.finish_batch(tres, b, cfg)
    oc = OracleCorrector(dictdb, cfg)
    n_sub = 0
    for i in range(b):
        o = oc.correct(reads[i], quals_list[i])
        w, t = wdev[i], tdev[i]
        key = (o.ok, o.error, o.seq, o.fwd_log, o.bwd_log, o.start, o.end)
        assert key == (w.ok, w.error, w.seq, w.fwd_log, w.bwd_log,
                       w.start, w.end), f"wide mismatch read {i}"
        assert key == (t.ok, t.error, t.seq, t.fwd_log, t.bwd_log,
                       t.start, t.end), f"tile mismatch read {i}"
        n_sub += o.fwd_log.count("sub")
    assert n_sub > 0  # corrections actually happened


def test_ambig_cap_stall_parity():
    """The ambiguous-lane compaction cap forces stall-and-retry when
    more lanes are ambiguous than fit: results must be bit-identical to
    an uncapped run and to the oracle (delay, not divergence)."""
    rng = _rng()
    core = rand_seq(rng, 40)
    db = {}
    branch_a = core[:20] + "A" + core[20:]
    branch_c = core[:20] + "C" + core[20:]
    add_seq(db, branch_a, 10, 1)
    add_seq(db, branch_c, 7, 1)
    state, meta, dictdb = table_from_dict(db, K)
    # a batch of identical ambiguous reads: every lane hits the probe
    # at the same iteration, so cap=1 stalls all but one per round
    read = branch_a[:20] + "G" + branch_a[21:35]
    reads, quals = zip(*[_mk_read(read) for _ in range(8)])
    cfg = ECConfig(k=K, cutoff=30, poisson_dtype="float32")

    b = len(reads)
    l = max(len(r) for r in reads)
    codes = np.full((b, l), -2, np.int8)
    qarr = np.zeros((b, l), np.uint8)
    lengths = np.zeros((b,), np.int32)
    for i, (r, q) in enumerate(zip(reads, quals)):
        codes[i, : len(r)] = mer.seq_to_codes(r)
        qarr[i, : len(r)] = np.frombuffer(q.encode(), np.uint8)
        lengths[i] = len(r)

    res_cap = corrector.correct_batch(state, meta, codes, qarr, lengths,
                                      cfg, ambig_cap=1)
    res_unc = corrector.correct_batch(state, meta, codes, qarr, lengths,
                                      cfg)
    fin_cap = corrector.finish_batch(res_cap, b, cfg)
    fin_unc = corrector.finish_batch(res_unc, b, cfg)
    assert fin_cap == fin_unc
    # and against the oracle
    oc = OracleCorrector(dictdb, cfg)
    for i, (r, q) in enumerate(zip(reads, quals)):
        o = oc.correct(r, q)
        assert fin_cap[i] == o
    assert "20:sub:G-A" in fin_cap[0].fwd_log
