"""End-to-end tests of the quorum driver (src/quorum.in) and the
mate-pair tools (src/merge_mate_pairs.cc, src/split_mate_pairs.cc):
quality autodetect, CDB->EC orchestration, and the paired
merge | correct | split chain producing <prefix>_1.fa/_2.fa."""

import conftest  # noqa: F401  (pins CPU devices)

import io
import os

import pytest

from quorum_tpu.cli import merge_mate_pairs as merge_cli
from quorum_tpu.cli import quorum as quorum_cli
from quorum_tpu.cli.split_mate_pairs import split_stream
from quorum_tpu.io import db_format
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.models.error_correct import ECOptions, resolve_cutoff
from quorum_tpu.models.oracle import DictDB, OracleCorrector

from test_error_correct_cli import K, make_dataset, oracle_expected


def split_dataset(tmp_path, reads, quals):
    """Write even-indexed reads to pair1.fastq, odd to pair2.fastq."""
    p1, p2 = tmp_path / "pair1.fastq", tmp_path / "pair2.fastq"
    with open(p1, "w") as f1, open(p2, "w") as f2:
        for i, (r, q) in enumerate(zip(reads, quals)):
            f = f1 if i % 2 == 0 else f2
            f.write(f"@read{i}\n{r}\n+\n{q}\n")
    return str(p1), str(p2)


def test_merge_mate_pairs_interleaves(tmp_path):
    reads_path, reads, quals = make_dataset(tmp_path, n_reads=10)
    p1, p2 = split_dataset(tmp_path, reads, quals)
    out = tmp_path / "merged.fastq"
    rc = merge_cli.main(["-o", str(out), p1, p2])
    assert rc == 0
    lines = out.read_text().splitlines()
    headers = [ln[1:] for ln in lines[0::4]]
    # merged order: read0, read1, read2, ... (even file first each pair)
    assert headers == [f"read{i}" for i in range(10)]
    assert lines[1::4] == reads


def test_merge_mate_pairs_fasta_star_quals(tmp_path):
    fa1, fa2 = tmp_path / "a.fa", tmp_path / "b.fa"
    fa1.write_text(">a0\nACGTACGT\n")
    fa2.write_text(">b0\nTTTTAAAA\n")
    out = tmp_path / "merged.fastq"
    rc = merge_cli.main(["-o", str(out), str(fa1), str(fa2)])
    assert rc == 0
    lines = out.read_text().splitlines()
    assert lines == ["@a0", "ACGTACGT", "+", "*" * 8,
                     "@b0", "TTTTAAAA", "+", "*" * 8]


def test_merge_mate_pairs_unpaired_errors(tmp_path, capsys):
    fa1, fa2 = tmp_path / "a.fa", tmp_path / "b.fa"
    fa1.write_text(">a0\nACGT\n>a1\nACGT\n")
    fa2.write_text(">b0\nTTTT\n")
    rc = merge_cli.main([str(fa1), str(fa2)])
    assert rc == 1
    assert "not paired" in capsys.readouterr().err
    rc = merge_cli.main([str(fa1)])
    assert rc == 1


def test_split_stream_alternates(tmp_path):
    inp = io.StringIO(">r0 a b\nAAAA\n>r1 c d\nCCCC\n>r2\nN\n>r3 e f\nGGGG\n")
    split_stream(inp, str(tmp_path / "out"))
    assert (tmp_path / "out_1.fa").read_text() == ">r0 a b\nAAAA\n>r2\nN\n"
    assert (tmp_path / "out_2.fa").read_text() == ">r1 c d\nCCCC\n>r3 e f\nGGGG\n"


def test_quality_autodetect(tmp_path):
    reads_path, _, _ = make_dataset(tmp_path, n_reads=20)
    # dataset quality chars bottom out at 33 (error positions)
    assert quorum_cli.detect_min_q_char(reads_path) == 33


def test_quality_autodetect_illumina_offset(tmp_path):
    p = tmp_path / "r.fastq"
    # min char 66 ('B') -> special Illumina case, reports 64
    p.write_text("@r0\nACGTACGTACGTAC\n+\nBBCDEFGHIJKLMN\n")
    assert quorum_cli.detect_min_q_char(str(p)) == 64


def test_quality_autodetect_unusual_errors(tmp_path):
    p = tmp_path / "r.fastq"
    p.write_text("@r0\nACGT\n+\nQRST\n")  # min char 'Q' = 81
    with pytest.raises(RuntimeError, match="unusual minimum quality char"):
        quorum_cli.detect_min_q_char(str(p))


def test_quorum_driver_single(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    reads_path, reads, quals = make_dataset(tmp_path)
    prefix = str(tmp_path / "qc")
    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix,
                          "--batch-size", "64", reads_path])
    assert rc == 0
    db_path = prefix + "_mer_database.jf"
    assert os.path.exists(db_path)

    state, meta, _ = db_format.read_db(db_path, to_device=True)
    cutoff = resolve_cutoff(state, meta, ECOptions())
    cfg = ECConfig(k=K, cutoff=cutoff, poisson_dtype="float32")
    want_fa, want_log = oracle_expected(db_path, reads, quals, cfg)
    with open(prefix + ".fa") as f:
        assert f.read() == want_fa
    with open(prefix + ".log") as f:
        assert f.read() == want_log


def test_quorum_driver_paired(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    reads_path, reads, quals = make_dataset(tmp_path, n_reads=120)
    p1, p2 = split_dataset(tmp_path, reads, quals)
    prefix = str(tmp_path / "qc")
    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix, "-P",
                          "--batch-size", "64", p1, p2])
    assert rc == 0
    # intermediate single .fa must be gone, split outputs present
    assert not os.path.exists(prefix + ".fa")

    db_path = prefix + "_mer_database.jf"
    cutoff_state = db_format.read_db(db_path, to_device=True)
    cutoff = resolve_cutoff(cutoff_state[0], cutoff_state[1], ECOptions())
    cfg = ECConfig(k=K, cutoff=cutoff, no_discard=True,
                   poisson_dtype="float32")
    # oracle over the *merged* order, then split alternately
    want_fa, want_log = oracle_expected(db_path, reads, quals, cfg)
    fa_records = want_fa.splitlines(keepends=True)
    pairs = ["".join(fa_records[i:i + 2])
             for i in range(0, len(fa_records), 2)]
    want_1 = "".join(pairs[0::2])
    want_2 = "".join(pairs[1::2])
    with open(prefix + "_1.fa") as f:
        assert f.read() == want_1
    with open(prefix + "_2.fa") as f:
        assert f.read() == want_2
    with open(prefix + ".log") as f:
        assert f.read() == want_log
    # every input read appears exactly once across the two files
    n1 = want_1.count(">")
    n2 = want_2.count(">")
    assert n1 == n2 == 60


def test_quorum_driver_bad_size(capsys):
    rc = quorum_cli.main(["-s", "12Q", "whatever.fastq"])
    assert rc == 1
    assert "Invalid size" in capsys.readouterr().err


def test_quorum_driver_no_files(capsys):
    rc = quorum_cli.main([])
    assert rc == 1
    assert "No sequence files" in capsys.readouterr().err


def test_driver_thread_plumbing_and_single_parse(tmp_path, monkeypatch):
    """-t autodetect/forwarding (quorum.in:110-120) and the parse-once
    replay: the reads hit the disk parser exactly once for both
    stages."""
    monkeypatch.chdir(tmp_path)
    reads_path, reads, quals = make_dataset(tmp_path)
    prefix = str(tmp_path / "qc")

    seen = {"cdb": None, "ec": None, "parses": 0}
    real_cdb, real_ec = quorum_cli.cdb_cli.main, quorum_cli.ec_cli.main
    real_read = quorum_cli.fastq.read_batches

    def spy_cdb(argv, **kw):
        seen["cdb"] = list(argv)
        return real_cdb(argv, **kw)

    def spy_ec(argv, **kw):
        seen["ec"] = list(argv)
        seen["ec_prepacked"] = kw.get("prepacked")
        return real_ec(argv, **kw)

    def spy_read(paths, *a, **kw):
        seen["parses"] += 1
        return real_read(paths, *a, **kw)

    monkeypatch.setattr(quorum_cli.cdb_cli, "main", spy_cdb)
    monkeypatch.setattr(quorum_cli.ec_cli, "main", spy_ec)
    monkeypatch.setattr(quorum_cli.fastq, "read_batches", spy_read)
    monkeypatch.setattr(
        "quorum_tpu.models.error_correct.fastq.read_batches", spy_read)
    monkeypatch.setattr(
        "quorum_tpu.models.create_database.fastq.read_batches", spy_read)

    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix,
                          "-t", "3", "--batch-size", "64", reads_path])
    assert rc == 0
    # -t forwarded to both stages
    assert seen["cdb"][seen["cdb"].index("-t") + 1] == "3"
    assert seen["ec"][seen["ec"].index("-t") + 1] == "3"
    # stage 2 got the replay cache; the disk parser ran exactly once
    assert seen["ec_prepacked"] is not None
    assert len(seen["ec_prepacked"]) > 0
    assert seen["parses"] == 1

    # autodetect path: no -t -> cpu count
    seen["cdb"] = None
    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix,
                          "--batch-size", "64", reads_path])
    assert rc == 0
    want = str(os.cpu_count() or 1)
    assert seen["cdb"][seen["cdb"].index("-t") + 1] == want


def test_stage_path_suffixing():
    assert quorum_cli._stage_path("out.json", "stage1") == "out.stage1.json"
    assert quorum_cli._stage_path("metrics", "stage2") == "metrics.stage2"


def test_quorum_driver_metrics_forwarding(tmp_path, monkeypatch):
    """Satellite (ISSUE 1): the driver forwards --metrics to both
    children with per-stage suffixed paths and writes its own
    run-manifest JSON with per-child timings."""
    import json

    from quorum_tpu.telemetry import validate_metrics

    monkeypatch.chdir(tmp_path)
    reads_path, reads, quals = make_dataset(tmp_path)
    prefix = str(tmp_path / "qc")
    mpath = str(tmp_path / "run.json")
    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix,
                          "--batch-size", "64", "--metrics", mpath,
                          reads_path])
    assert rc == 0

    drv = json.load(open(mpath))
    assert validate_metrics(drv) == []
    assert drv["meta"]["driver"] == "quorum"
    assert drv["meta"]["status"] == "ok"
    assert drv["meta"]["jax_backend"]
    assert drv["meta"]["device_count"] >= 1
    assert drv["gauges"]["stage1_seconds"] > 0
    assert drv["gauges"]["stage2_seconds"] > 0

    s1 = json.load(open(str(tmp_path / "run.stage1.json")))
    s2 = json.load(open(str(tmp_path / "run.stage2.json")))
    assert validate_metrics(s1) == []
    assert validate_metrics(s2) == []
    assert s1["meta"]["stage"] == "create_database"
    assert s2["meta"]["stage"] == "error_correct"
    # both stages saw the same reads
    assert s1["counters"]["reads"] == s2["counters"]["reads_in"] \
        == len(reads)
    assert s2["counters"]["reads_corrected"] \
        + s2["counters"]["reads_skipped"] == len(reads)


def test_quorum_driver_live_observability(tmp_path, monkeypatch):
    """Acceptance (ISSUE 2): a driver run with --metrics-port serves a
    Prometheus-parseable /metrics DURING the run (the server closes
    when the run finishes, so every successful scrape below is by
    construction mid-pipeline), --metrics-textfile lints clean, and
    --trace-spans produces span JSONL whose Chrome twin loads as valid
    trace_event JSON."""
    import json
    import threading
    import time
    import urllib.request

    from quorum_tpu.telemetry import (export, validate_chrome_trace,
                                      validate_span_line)

    monkeypatch.chdir(tmp_path)
    reads_path, reads, quals = make_dataset(tmp_path)
    prefix = str(tmp_path / "qc")
    tf = str(tmp_path / "live.prom")
    sp = str(tmp_path / "spans.jsonl")

    scrapes: list[str] = []
    done = threading.Event()

    def scraper():
        # wait for the ephemeral port, then scrape until the run ends
        while not done.is_set():
            srv = export.current_server()
            if srv is None:
                time.sleep(0.005)
                continue
            url = f"http://127.0.0.1:{srv.port}/metrics"
            try:
                with urllib.request.urlopen(url, timeout=2) as r:
                    scrapes.append(r.read().decode())
            except OSError:
                pass  # server may close between check and request
            time.sleep(0.01)

    t = threading.Thread(target=scraper)
    t.start()
    try:
        rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix,
                              "--batch-size", "64",
                              "--metrics-port", "0",
                              "--metrics-textfile", tf,
                              "--trace-spans", sp,
                              reads_path])
    finally:
        done.set()
        t.join()
    assert rc == 0
    assert os.path.exists(prefix + ".fa")

    # mid-run scrapes happened and are Prometheus-parseable
    assert scrapes, "no successful mid-run scrape"
    for text in scrapes:
        assert export.lint_prometheus_text(text) == []
    # by the end of the run a stage counter must have shown up
    assert any("quorum_tpu_" in s and 'stage="' in s for s in scrapes)
    # the server is down after the run (closed by the driver)
    assert export.current_server() is None

    # textfile: present, linting clean via the rename target
    assert export.lint_prometheus_text(open(tf).read()) == []
    assert not os.path.exists(tf + ".tmp")

    # spans: per-stage JSONL + Chrome twins, all schema-valid
    for tag, names in (("stage1", {"stage1_batch", "stage1_insert"}),
                       ("stage2", {"stage2_batch", "stage2_device"})):
        spath = str(tmp_path / f"spans.{tag}.jsonl")
        assert os.path.exists(spath), spath
        lines = [json.loads(x) for x in open(spath) if x.strip()]
        assert lines
        assert all(validate_span_line(o) == [] for o in lines)
        got = {o["span"] for o in lines}
        assert names <= got, (tag, got)
        chrome = str(tmp_path / f"spans.{tag}.trace.json")
        doc = json.load(open(chrome))
        assert validate_chrome_trace(doc) == []
        assert {e["name"] for e in doc["traceEvents"]} >= names
        # nesting: each device step is a child of its batch span
        by_id = {o["id"]: o for o in lines}
        steps = [o for o in lines if o["span"].endswith(
            ("_insert", "_device"))]
        assert steps
        for s in steps:
            assert by_id[s["parent"]]["span"] == f"{tag}_batch"
            assert "step" in s

    # the driver's own span file covers the shared read/pack producer
    dpath = str(tmp_path / "spans.driver.jsonl")
    assert os.path.exists(dpath)
    dlines = [json.loads(x) for x in open(dpath) if x.strip()]
    assert all(validate_span_line(o) == [] for o in dlines)
    assert any(o["span"] == "reads_producer_produce" for o in dlines)
    assert json.load(open(str(tmp_path / "spans.driver.trace.json")))


def test_quorum_driver_uncaught_error_frees_port_and_stamps_manifest(
        tmp_path, monkeypatch):
    """An exception the stage CLIs don't catch must still close the
    --metrics-port server and write the driver manifest with
    status=error."""
    import gc
    import json

    from quorum_tpu.cli import quorum as qmod
    from quorum_tpu.telemetry import export

    monkeypatch.chdir(tmp_path)
    reads_path, _, _ = make_dataset(tmp_path)
    mpath = str(tmp_path / "run.json")

    # TypeError: outside the failure shapes the retry loop contains
    # (RuntimeError/ValueError/OSError become rc-1 stage failures now,
    # covered by test_faults.py) — a genuinely uncaught exception
    def boom(*a, **kw):
        raise TypeError("stage 1 exploded")

    monkeypatch.setattr(qmod.cdb_cli, "main", boom)
    with pytest.raises(TypeError, match="stage 1 exploded"):
        quorum_cli.main(["-s", "64k", "-k", str(K),
                         "-p", str(tmp_path / "qc"),
                         "--metrics", mpath, "--metrics-port", "0",
                         reads_path])
    gc.collect()
    assert export.current_server() is None  # port freed
    drv = json.load(open(mpath))
    assert drv["meta"]["status"] == "error"
