"""End-to-end tests of the stage-2 program surface: the
quorum_error_correct_reads CLI corrects FASTQ files against a stage-1
database and writes the reference's exact output formats
(error_correct_reads.cc:246-341; README.md "Output format").

The expected output is computed by the pure-Python oracle over the same
database — so these tests pin the whole program path (DB file round
trip, auto Poisson cutoff, batching, device correction, log rendering,
file writing) against the independently tested per-read semantics."""

import conftest  # noqa: F401  (pins CPU devices)

import gzip
import os

import numpy as np
import pytest

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli
from quorum_tpu.io import db_format
from quorum_tpu.models.ec_config import ECConfig
from quorum_tpu.models.error_correct import ECOptions, resolve_cutoff
from quorum_tpu.models.oracle import DictDB, OracleCorrector

K = 13
BASES = "ACGT"
QUAL_THRESH = 38  # CDB -q: base+5 for base 33


def _rng():
    return np.random.default_rng(42)


def make_dataset(tmp_path, n_reads=240, read_len=60, genome_len=1500,
                 err_rate=0.02, seed=42):
    """A synthetic genome + error-bearing reads, written as FASTQ."""
    rng = np.random.default_rng(seed)
    genome = "".join(BASES[c] for c in rng.integers(0, 4, genome_len))
    reads, quals = [], []
    for i in range(n_reads):
        start = int(rng.integers(0, genome_len - read_len))
        r = list(genome[start:start + read_len])
        q = [chr(int(c)) for c in rng.integers(40, 70, read_len)]
        for j in range(read_len):
            if rng.random() < err_rate:
                r[j] = BASES[int(rng.integers(0, 4))]
                q[j] = chr(33 + int(rng.integers(0, 4)))
        reads.append("".join(r))
        quals.append("".join(q))
    path = tmp_path / "reads.fastq"
    with open(path, "w") as f:
        for i, (r, q) in enumerate(zip(reads, quals)):
            f.write(f"@read{i}\n{r}\n+\n{q}\n")
    return str(path), reads, quals


def build_db(tmp_path, reads_path, k=K):
    db_path = str(tmp_path / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", str(k), "-b", "7",
                       "-q", str(QUAL_THRESH), "-o", db_path, reads_path])
    assert rc == 0
    return db_path


def oracle_expected(db_path, reads, quals, cfg):
    """Render the oracle's .fa/.log text for the given reads."""
    state, meta, _ = db_format.read_db(db_path, to_device=False)
    db = DictDB.from_table(state, meta)
    oc = OracleCorrector(db, cfg)
    fa, log = [], []
    for i, (r, q) in enumerate(zip(reads, quals)):
        res = oc.correct(r, q)
        hdr = f"read{i}"
        if res.ok:
            fa.append(f">{hdr} {res.fwd_log} {res.bwd_log}\n{res.seq}\n")
        else:
            log.append(f"Skipped {hdr}: {res.error}\n")
            if cfg.no_discard:
                fa.append(f">{hdr}\nN\n")
    return "".join(fa), "".join(log)


def auto_cutoff(db_path):
    state, meta, _ = db_format.read_db(db_path, to_device=True)
    return resolve_cutoff(state, meta, ECOptions())


def test_ec_cli_end_to_end(tmp_path):
    reads_path, reads, quals = make_dataset(tmp_path)
    db_path = build_db(tmp_path, reads_path)
    prefix = str(tmp_path / "out")
    rc = ec_cli.main(["-o", prefix, "--batch-size", "64", db_path,
                      reads_path])
    assert rc == 0

    cutoff = auto_cutoff(db_path)
    assert cutoff > 0
    cfg = ECConfig(k=K, cutoff=cutoff, poisson_dtype="float32")
    want_fa, want_log = oracle_expected(db_path, reads, quals, cfg)
    with open(prefix + ".fa") as f:
        got_fa = f.read()
    with open(prefix + ".log") as f:
        got_log = f.read()
    assert got_fa == want_fa
    assert got_log == want_log
    # the dataset must exercise both surfaces
    assert got_fa.count(">") > 100
    assert ":sub:" in got_fa


def test_ec_cli_no_discard_and_flags(tmp_path):
    reads_path, reads, quals = make_dataset(tmp_path, n_reads=80)
    db_path = build_db(tmp_path, reads_path)
    prefix = str(tmp_path / "out")
    rc = ec_cli.main(["-o", prefix, "-d", "-p", "4", "-w", "8", "-e", "2",
                      "--homo-trim", "6", "--batch-size", "32",
                      db_path, reads_path])
    assert rc == 0
    cfg = ECConfig(k=K, cutoff=4, window=8, error=2, homo_trim=6,
                   no_discard=True, poisson_dtype="float32")
    want_fa, want_log = oracle_expected(db_path, reads, quals, cfg)
    with open(prefix + ".fa") as f:
        assert f.read() == want_fa
    with open(prefix + ".log") as f:
        assert f.read() == want_log


def test_ec_cli_gzip_output(tmp_path):
    reads_path, reads, quals = make_dataset(tmp_path, n_reads=40)
    db_path = build_db(tmp_path, reads_path)
    prefix = str(tmp_path / "out")
    rc = ec_cli.main(["-o", prefix, "--gzip", "--batch-size", "32",
                      db_path, reads_path])
    assert rc == 0
    assert os.path.exists(prefix + ".fa.gz")
    cutoff = auto_cutoff(db_path)
    cfg = ECConfig(k=K, cutoff=cutoff, poisson_dtype="float32")
    want_fa, _ = oracle_expected(db_path, reads, quals, cfg)
    with gzip.open(prefix + ".fa.gz", "rt") as f:
        assert f.read() == want_fa


def test_ec_cli_stdout_default(tmp_path, capsys):
    reads_path, reads, quals = make_dataset(tmp_path, n_reads=40)
    db_path = build_db(tmp_path, reads_path)
    rc = ec_cli.main(["--batch-size", "32", db_path, reads_path])
    assert rc == 0
    cutoff = auto_cutoff(db_path)
    cfg = ECConfig(k=K, cutoff=cutoff, poisson_dtype="float32")
    want_fa, want_log = oracle_expected(db_path, reads, quals, cfg)
    captured = capsys.readouterr()
    assert captured.out == want_fa
    assert captured.err == want_log


def test_ec_cli_contaminant(tmp_path):
    reads_path, reads, quals = make_dataset(tmp_path, n_reads=60)
    db_path = build_db(tmp_path, reads_path)
    # contaminate: take a window from one real read as the adapter
    adapter = reads[3][10:10 + 2 * K]
    contam_path = tmp_path / "adapter.fa"
    contam_path.write_text(f">adapter\n{adapter}\n")
    prefix = str(tmp_path / "out")
    rc = ec_cli.main(["-o", prefix, "--contaminant", str(contam_path),
                      "--batch-size", "32", db_path, reads_path])
    assert rc == 0
    with open(prefix + ".log") as f:
        log_text = f.read()
    assert "Contaminated read" in log_text

    # oracle comparison with the same contaminant set
    from quorum_tpu.ops import mer as merops
    contam_set = set()
    for i in range(len(adapter) - K + 1):
        hi, lo = merops.pack_kmer(adapter[i:i + K], K)
        chi, clo = merops.canonical_py(hi, lo, K)
        contam_set.add((int(chi) << 32) | int(clo))
    state, meta, _ = db_format.read_db(db_path, to_device=False)
    db = DictDB.from_table(state, meta)
    cutoff = auto_cutoff(db_path)
    cfg = ECConfig(k=K, cutoff=cutoff, poisson_dtype="float32")
    oc = OracleCorrector(db, cfg, contaminant=contam_set)
    fa = []
    for i, (r, q) in enumerate(zip(reads, quals)):
        res = oc.correct(r, q)
        if res.ok:
            fa.append(f">read{i} {res.fwd_log} {res.bwd_log}\n{res.seq}\n")
    with open(prefix + ".fa") as f:
        assert f.read() == "".join(fa)


def test_ec_cli_contaminant_k_mismatch(tmp_path):
    reads_path, _, _ = make_dataset(tmp_path, n_reads=20)
    db_path = build_db(tmp_path, reads_path)
    # a quorum DB at the wrong k as contaminant must be rejected
    other_db = str(tmp_path / "wrong.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", str(K - 2), "-b", "7",
                       "-q", str(QUAL_THRESH), "-o", other_db, reads_path])
    assert rc == 0
    rc = ec_cli.main(["-o", str(tmp_path / "o"), "--contaminant", other_db,
                      db_path, reads_path])
    assert rc == 1
