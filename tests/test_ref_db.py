"""Reference-format DB header parsing (io/ref_db).

The fixture header is synthetic — written here in the multi-line
styled-JSON shape Jellyfish's file_header produces — because the
reference toolchain (which links Jellyfish externally) cannot run in
this environment to produce a real one. These tests pin OUR parser's
contract: brace-matched JSON extraction from a binary file, geometry
reporting, and the diagnostic path through db_format.read_header."""

import json

import numpy as np
import pytest

from quorum_tpu.io import db_format, ref_db

STYLED_HEADER = b"""{
   "alignment" : 8,
   "bits" : 7,
   "cmdline" : [ "quorum_create_database", "-s", "200M", "reads.fastq" ],
   "format" : "binary/quorum_db",
   "key_bytes" : 1073741824,
   "key_len" : 48,
   "matrix" : {
      "c" : 64,
      "identity" : false,
      "r" : 48
   },
   "max_reprobe" : 126,
   "size" : 134217728,
   "value_bytes" : 134217728
}"""


def _fixture(tmp_path, header: bytes = STYLED_HEADER):
    path = tmp_path / "ref.qdb"
    align = 8
    pad = (-len(header)) % align
    payload = np.arange(64, dtype=np.uint64).tobytes()
    path.write_bytes(header + b"\x00" * pad + payload)
    return str(path)


def test_parse_styled_header(tmp_path):
    path = _fixture(tmp_path)
    header, payload_off = ref_db.read_ref_header(path)
    assert header["format"] == "binary/quorum_db"
    assert header["key_len"] == 48
    assert header["bits"] == 7
    assert header["size"] == 134217728
    assert header["max_reprobe"] == 126
    assert payload_off % 8 == 0
    assert payload_off >= len(STYLED_HEADER)


def test_parse_compact_header(tmp_path):
    compact = json.dumps({"format": "binary/quorum_db", "size": 16,
                          "key_len": 30, "bits": 1}).encode()
    path = _fixture(tmp_path, compact)
    header, off = ref_db.read_ref_header(path)
    assert header["size"] == 16
    assert off % 8 == 0


def test_braces_inside_strings_do_not_confuse_parser():
    data = b'{"cmdline": ["weird {path} with } brace"], "format": "x"}BIN'
    header, end = ref_db.parse_jf_header(data)
    assert header["cmdline"] == ["weird {path} with } brace"]
    assert data[end:] == b"BIN"


def test_not_json_raises():
    with pytest.raises(ref_db.RefHeaderError):
        ref_db.parse_jf_header(b"\x89PNG not a header")
    with pytest.raises(ref_db.RefHeaderError):
        ref_db.parse_jf_header(b'{"unterminated": tru')


def test_describe_lists_geometry():
    header, _ = ref_db.parse_jf_header(STYLED_HEADER + b"")
    s = ref_db.describe(header)
    assert "key_len=48" in s
    assert "bits=7" in s


def test_read_header_diagnoses_reference_file(tmp_path):
    path = _fixture(tmp_path)
    with pytest.raises(RuntimeError, match="reference-format quorum"):
        db_format.read_header(path)


def test_read_header_still_rejects_garbage(tmp_path):
    path = tmp_path / "junk.qdb"
    path.write_bytes(b"\x00\x01binary junk")
    with pytest.raises(ValueError, match="not a quorum_tpu database"):
        db_format.read_header(str(path))
