"""Chaos tests for the fault-tolerance layer (ISSUE 4): the
deterministic fault-injection harness itself, kill/resume for both
stages asserting byte-identical output, the driver's retry/backoff
with a mocked clock, malformed-FASTQ degradation, and the
checkpoint/journal artifacts' corruption handling.

The expensive truths (a killed stage resumed from its checkpoint
converges on the same bytes) run the REAL device pipeline over the
small synthetic dataset the other end-to-end suites use, so the jit
shapes are shared; everything about the driver's retry loop is tested
with stubbed stages and a mocked clock — the logic under test lives
in the driver, not the stages.
"""

import conftest  # noqa: F401  (pins CPU devices)

import json
import os
import threading

import pytest

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli
from quorum_tpu.cli import quorum as quorum_cli
from quorum_tpu.io import checkpoint as ckpt_mod
from quorum_tpu.io import db_format, fastq
from quorum_tpu.telemetry import registry_for
from quorum_tpu.utils import faults
from quorum_tpu.utils.pipeline import AsyncWriter

from test_error_correct_cli import K, build_db, make_dataset


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends without an installed fault plan."""
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# the fault plan itself
# ---------------------------------------------------------------------------

def test_fault_plan_parse_forms():
    p = faults.FaultPlan.parse(
        [{"site": "a", "action": "error"},
         {"site": "b@batch=3", "action": "sleep", "seconds": 0.01}])
    assert p.specs[0].site == "a" and p.specs[0].batch is None
    assert p.specs[1].site == "b" and p.specs[1].batch == 3
    # single object and {"faults": [...]} wrappers
    assert len(faults.FaultPlan.parse({"site": "x"}).specs) == 1
    assert len(faults.FaultPlan.parse(
        {"faults": [{"site": "x"}, {"site": "y"}]}).specs) == 2
    with pytest.raises(ValueError, match="site"):
        faults.FaultPlan.parse([{"action": "error"}])
    with pytest.raises(ValueError, match="unknown action"):
        faults.FaultPlan.parse([{"site": "x", "action": "explode"}])
    with pytest.raises(ValueError, match="shorthand"):
        faults.FaultPlan.parse([{"site": "x@reads=3"}])


def test_fault_plan_at_count_batch_matching():
    plan = faults.FaultPlan.parse([
        {"site": "s", "at": 2, "count": 2, "action": "error"},
        {"site": "t", "batch": 5, "action": "error"},
    ])
    faults.install(plan)
    faults.inject("s")                      # hit 1: below `at`
    with pytest.raises(faults.FaultError):
        faults.inject("s")                  # hit 2: fires
    with pytest.raises(faults.FaultError):
        faults.inject("s")                  # hit 3: count=2 still firing
    faults.inject("s")                      # hit 4: spent
    faults.inject("t", batch=4)             # wrong batch: no match
    faults.inject("t")                      # no batch tag: no match
    with pytest.raises(faults.FaultError):
        faults.inject("t", batch=5)
    faults.inject("t", batch=5)             # count=1 spent
    # unknown site never fires; disabled inject is a no-op
    faults.inject("nowhere", batch=123)
    faults.reset()
    faults.inject("s")


def test_fault_actions_and_load_plan(tmp_path, monkeypatch):
    plan = faults.FaultPlan.parse([
        {"site": "io", "action": "io_error", "message": "disk gone"},
        {"site": "zzz", "action": "sleep", "seconds": 0.0},
    ])
    faults.install(plan)
    with pytest.raises(OSError, match="disk gone"):
        faults.inject("io")
    faults.inject("zzz")  # sleeps 0 then continues

    # @file and bare-path loading
    pf = tmp_path / "plan.json"
    pf.write_text('[{"site": "p", "action": "error"}]')
    assert faults.load_plan(f"@{pf}").specs[0].site == "p"
    assert faults.load_plan(str(pf)).specs[0].site == "p"
    with pytest.raises(ValueError, match="bad fault plan"):
        faults.load_plan("not json {")

    # env-var fallback installs; explicit empty clears
    monkeypatch.setenv(faults.ENV_VAR, '[{"site": "e", "action": "error"}]')
    assert faults.setup(None).specs[0].site == "e"
    monkeypatch.setenv(faults.ENV_VAR, "")
    assert faults.setup(None) is None
    assert not faults.active()


def test_fault_env_reinstall_keeps_counters(monkeypatch):
    """An in-process stage entry re-reading the SAME env spec must
    keep the running plan's spent counters — a driver retry would
    otherwise re-fire a count=1 fault forever."""
    monkeypatch.setenv(faults.ENV_VAR, '[{"site": "s", "action": "error"}]')
    faults.setup(None)
    with pytest.raises(faults.FaultError):
        faults.inject("s")
    faults.setup(None)          # same spec: plan (and counters) kept
    faults.inject("s")          # count=1 stays spent — no re-fire
    monkeypatch.setenv(faults.ENV_VAR, '[{"site": "t", "action": "error"}]')
    faults.setup(None)          # different spec: fresh plan
    with pytest.raises(faults.FaultError):
        faults.inject("t")


def test_fault_io_error_errno(tmp_path):
    """io_error with errno= raises a REAL errno-classed OSError so
    error-class-sensitive paths (the resource ladder dispatches on
    ENOSPC) can be driven (ISSUE 19)."""
    import errno
    faults.install(faults.FaultPlan.parse(
        [{"site": "w", "action": "io_error", "errno": 28,
          "message": "device full"}]))
    with pytest.raises(OSError, match="device full") as ei:
        faults.inject("w")
    assert ei.value.errno == errno.ENOSPC
    with pytest.raises(ValueError, match="errno"):
        faults.FaultPlan.parse([{"site": "w", "action": "io_error",
                                 "errno": 0}])


def test_fault_diskfull_budget_and_persistence(tmp_path):
    """diskfull charges each matching write against its byte budget
    and fails ENOSPC once past it — and STAYS failing: full disks do
    not empty themselves (ISSUE 19)."""
    import errno
    f = tmp_path / "a.bin"
    f.write_bytes(b"x" * 100)
    faults.install(faults.FaultPlan.parse(
        [{"site": "w", "action": "diskfull", "bytes": 150,
          "count": -1}]))
    faults.inject("w", path=str(f))   # 100 charged: under budget
    with pytest.raises(OSError) as ei:
        faults.inject("w", path=str(f))  # 200 charged: full
    assert ei.value.errno == errno.ENOSPC
    with pytest.raises(OSError):
        faults.inject("w", path=str(f))  # stays full
    # bytes defaults to 0 for diskfull — "already full": first write
    # fails (a pathless call charges 1 token)
    faults.install(faults.FaultPlan.parse(
        [{"site": "w", "action": "diskfull", "count": -1}]))
    with pytest.raises(OSError) as ei:
        faults.inject("w", path=str(f))
    assert ei.value.errno == errno.ENOSPC


def test_fault_path_prefix_scoping(tmp_path):
    """path_prefix scopes a spec to one artifact family: calls with a
    different path, or no path at all, never match (one full
    filesystem, not a full machine)."""
    target = tmp_path / "ck"
    target.mkdir()
    (target / "s.ckpt").write_bytes(b"x" * 10)
    faults.install(faults.FaultPlan.parse(
        [{"site": "w", "action": "diskfull", "count": -1,
          "path_prefix": str(target)}]))
    faults.inject("w")                               # no path: no match
    faults.inject("w", path=str(tmp_path / "other"))  # other fs: no match
    with pytest.raises(OSError):
        faults.inject("w", path=str(target / "s.ckpt"))
    with pytest.raises(ValueError, match="path_prefix"):
        faults.FaultPlan.parse([{"site": "w", "path_prefix": ""}])


# ---------------------------------------------------------------------------
# malformed-FASTQ degradation (--on-bad-read)
# ---------------------------------------------------------------------------

BAD_FASTQ = (b"@good1\nACGT\n+\nIIII\n"
             b"@bad_qual\nACGT\n+\nIIIIIII\n"     # qual longer than seq
             b"@good2\nACGTA\n+\nIIIII\n"
             b"not_a_record_start\n"              # stray line
             b"@good3\nAC\n+\nII\n")


def _write_bad(tmp_path):
    p = tmp_path / "bad.fastq"
    p.write_bytes(BAD_FASTQ)
    return str(p)


def test_bad_read_abort_is_default(tmp_path):
    p = _write_bad(tmp_path)
    with pytest.raises(ValueError, match="quality length"):
        list(fastq.iter_records([p]))


def test_bad_read_skip_counts_and_continues(tmp_path):
    p = _write_bad(tmp_path)
    reg = registry_for(None, force=True)
    pol = fastq.BadReadPolicy("skip", registry=reg)
    recs = list(fastq.iter_records([p], pol))
    assert [h for h, _s, _q in recs] == ["good1", "good2", "good3"]
    assert pol.bad == 2
    assert reg.counter("bad_reads_total").value == 2


def test_bad_read_quarantine_routes_raw_records(tmp_path):
    p = _write_bad(tmp_path)
    qpath = str(tmp_path / "q.quarantine.fastq")
    pol = fastq.BadReadPolicy("quarantine", quarantine_path=qpath)
    recs = list(fastq.iter_records([p], pol))
    pol.close()
    assert len(recs) == 3
    quarantined = open(qpath, "rb").read()
    assert b"@bad_qual\nACGT\n+\nIIIIIII\n" in quarantined
    assert b"not_a_record_start\n" in quarantined
    assert b"good" not in quarantined  # only the bad records


def test_bad_read_unicode_header(tmp_path):
    """A corrupt (non-UTF-8) header byte is a malformed record like
    any other: abort raises, skip drops and counts."""
    p = tmp_path / "u.fastq"
    p.write_bytes(b"@ok\nACGT\n+\nIIII\n"
                  b"@bad\xff\nACGT\n+\nIIII\n"
                  b"@ok2\nAC\n+\nII\n")
    with pytest.raises(UnicodeDecodeError):
        list(fastq.iter_records([str(p)]))
    reg = registry_for(None, force=True)
    pol = fastq.BadReadPolicy("skip", registry=reg)
    recs = list(fastq.iter_records([str(p)], pol))
    assert [h for h, _s, _q in recs] == ["ok", "ok2"]
    assert pol.bad == 1
    assert reg.counter("bad_reads_total").value == 1


def test_bad_read_policy_validation():
    with pytest.raises(ValueError, match="on-bad-read"):
        fastq.BadReadPolicy("explode")
    with pytest.raises(ValueError, match="quarantine"):
        fastq.BadReadPolicy("quarantine")  # no path


def test_ec_cli_skips_bad_reads(tmp_path):
    """End-to-end --on-bad-read=skip through the stage-2 CLI: the bad
    record is dropped mid-stream, every real read still corrects, and
    the counter lands in the metrics document."""
    reads_path, _reads, _quals = make_dataset(tmp_path, n_reads=40)
    db = build_db(tmp_path, reads_path)
    lines = open(reads_path).read().splitlines(keepends=True)
    bad = tmp_path / "bad.fastq"
    # a broken record (qual longer than seq) spliced mid-file
    bad.write_text("".join(lines[:80]) + "@broken\nACGT\n+\nIIIIIII\n"
                   + "".join(lines[80:]))
    mpath = str(tmp_path / "m.json")
    out = str(tmp_path / "out")
    rc = ec_cli.main(["-d", "--on-bad-read", "skip",
                      "--metrics", mpath, "-o", out, db, str(bad)])
    assert rc == 0
    fa = open(out + ".fa").read()
    assert fa.count(">") == 40          # -d: one record per real read
    assert ">broken" not in fa
    doc = json.load(open(mpath))
    assert doc["counters"]["bad_reads_total"] == 1
    assert doc["meta"]["on_bad_read"] == "skip"


# ---------------------------------------------------------------------------
# AsyncWriter.flush barrier (the journal's commit precondition)
# ---------------------------------------------------------------------------

def test_async_writer_flush_barrier(tmp_path):
    p = tmp_path / "w.txt"
    f = open(p, "w")
    w = AsyncWriter([f])
    for i in range(50):
        w.write(0, f"line{i}\n")
    w.flush()
    # everything queued before the barrier is on disk when it returns
    assert open(p).read().count("\n") == 50
    w.write(0, "tail\n")
    w.close()
    f.close()
    assert open(p).read().endswith("tail\n")


# ---------------------------------------------------------------------------
# checkpoint artifacts: corruption and config mismatch
# ---------------------------------------------------------------------------

def test_stage1_checkpoint_corruption_and_peek(tmp_path):
    ck = ckpt_mod.Stage1Checkpoint(str(tmp_path))
    assert ck.load() is None and ck.cursor() is None
    with open(ck.path, "wb") as f:
        f.write(b"garbage, not a header\n")
    with pytest.raises(ckpt_mod.CheckpointError):
        ck.load()
    assert ck.cursor() is None  # peek is non-raising
    ck.clear()
    assert not os.path.exists(ck.path)
    ck.clear()  # idempotent


def test_stage2_journal_truncates_torn_tail(tmp_path):
    prefix = str(tmp_path / "out")
    j = ckpt_mod.Stage2Journal(prefix)
    assert j.load() is None

    class S:
        reads = corrected = skipped = bases_in = bases_out = 0

    out, log = j.open_outputs(None)
    out.write("committed\n")
    out.flush()
    j.commit(1, S(), out.tell(), log.tell(), 64,
             {"db": "a.jf", "inputs": ["r.fastq"]})
    out.write("torn-tail-after-the-commit")
    out.close()
    log.close()

    st = j.load()
    assert st["batches"] == 1 and st["batch_size"] == 64
    with pytest.raises(ckpt_mod.CheckpointError, match="batch_size"):
        j.check_config(st, 128)
    # a different database or input set must refuse to resume —
    # splicing two runs' corrections into one file is corruption
    with pytest.raises(ckpt_mod.CheckpointError, match="db="):
        j.check_config(st, 64, {"db": "OTHER.jf",
                                "inputs": ["r.fastq"]})
    j.check_config(st, 64, {"db": "a.jf", "inputs": ["r.fastq"]})
    out2, log2 = j.open_outputs(st)
    out2.write("resumed\n")
    out2.close()
    log2.close()
    assert open(j.fa_partial).read() == "committed\nresumed\n"
    j.finalize()
    assert open(prefix + ".fa").read() == "committed\nresumed\n"
    assert not os.path.exists(j.path)
    assert not os.path.exists(j.fa_partial)
    j.finalize()  # idempotent


# ---------------------------------------------------------------------------
# kill/resume, stage 1: the counting table converges
# ---------------------------------------------------------------------------

def _db_entries(path):
    state, meta, _ = db_format.read_db(path, to_device=False)
    khi, klo, vals = db_format.db_iterate(state, meta)
    return sorted(zip(khi.tolist(), klo.tolist(), vals.tolist()))


def test_stage1_kill_resume_matches_uninterrupted(tmp_path):
    reads_path, _reads, _quals = make_dataset(tmp_path)
    ckdir = str(tmp_path / "ck")
    base_args = ["-s", "64k", "-m", str(K), "-b", "7", "-q", "38",
                 "--batch-size", "64"]
    db0 = str(tmp_path / "db0.jf")
    assert cdb_cli.main(base_args + ["-o", db0, reads_path]) == 0

    # killed at batch 2 (batches 0 and 1 inserted and checkpointed)
    db1 = str(tmp_path / "db1.jf")
    plan = json.dumps([{"site": "stage1.insert", "batch": 2,
                        "action": "error"}])
    rc = cdb_cli.main(base_args + [
        "-o", db1, "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--fault-plan", plan, reads_path])
    assert rc == 1
    assert not os.path.exists(db1)
    ck = ckpt_mod.Stage1Checkpoint(ckdir)
    assert ck.cursor() == 2

    # resume (no plan): finishes and clears the checkpoint
    mpath = str(tmp_path / "resume.json")
    rc = cdb_cli.main(base_args + [
        "-o", db1, "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--resume", "--metrics", mpath, "--fault-plan", "", reads_path])
    assert rc == 0
    assert ck.cursor() is None  # cleared on success
    assert _db_entries(db1) == _db_entries(db0)

    doc = json.load(open(mpath))
    assert doc["meta"]["resumed"] is True
    assert doc["meta"]["resumed_from_batch"] == 2
    assert doc["counters"]["resume_skipped_reads"] == 128  # 2 x 64
    assert doc["counters"]["checkpoint_writes_total"] >= 1
    assert doc["counters"]["reads"] == 240  # restored + new


def test_stage1_resume_refuses_config_mismatch(tmp_path):
    reads_path, _r, _q = make_dataset(tmp_path)
    ckdir = str(tmp_path / "ck")
    plan = json.dumps([{"site": "stage1.insert", "batch": 1,
                        "action": "error"}])
    args = ["-s", "64k", "-m", str(K), "-b", "7", "-q", "38",
            "--batch-size", "64", "--checkpoint-dir", ckdir,
            "--checkpoint-every", "1"]
    rc = cdb_cli.main(args + ["-o", str(tmp_path / "x.jf"),
                              "--fault-plan", plan, reads_path])
    assert rc == 1
    # different batch size -> the cursor would skip the wrong reads;
    # rc 3 marks the refusal non-retryable for the driver's loop
    rc = cdb_cli.main(["-s", "64k", "-m", str(K), "-b", "7", "-q", "38",
                       "--batch-size", "32", "--checkpoint-dir", ckdir,
                       "--resume", "--fault-plan", "",
                       "-o", str(tmp_path / "x.jf"), reads_path])
    assert rc == ckpt_mod.NON_RETRYABLE_RC


# ---------------------------------------------------------------------------
# kill/resume, stage 2: byte-identical output
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ec_fixture(tmp_path_factory):
    """Dataset + database + uninterrupted baseline output, shared by
    the stage-2 chaos tests."""
    tmp = tmp_path_factory.mktemp("faults_ec")
    reads_path, reads, quals = make_dataset(tmp)
    db = build_db(tmp, reads_path)
    base = str(tmp / "base")
    assert ec_cli.main(["--batch-size", "64", "-o", base,
                        db, reads_path]) == 0
    return tmp, reads_path, db, base


def test_stage2_kill_resume_byte_identical(ec_fixture, tmp_path):
    tmp, reads_path, db, base = ec_fixture
    out = str(tmp_path / "out")
    plan = json.dumps([{"site": "stage2.correct@batch=2",
                        "action": "error"}])
    rc = ec_cli.main(["--batch-size", "64", "--checkpoint-every", "1",
                      "-o", out, "--fault-plan", plan, db, reads_path])
    assert rc == 1
    j = ckpt_mod.Stage2Journal(out)
    assert j.batches_done() == 2
    assert os.path.exists(out + ".fa.partial")
    assert not os.path.exists(out + ".fa")

    mpath = str(tmp_path / "resume.json")
    rc = ec_cli.main(["--batch-size", "64", "--checkpoint-every", "1",
                      "--resume", "--metrics", mpath,
                      "--fault-plan", "", "-o", out, db, reads_path])
    assert rc == 0
    # THE acceptance property: kill -> resume is byte-identical to the
    # uninterrupted run, and the journal/partials are gone
    assert open(out + ".fa").read() == open(base + ".fa").read()
    assert open(out + ".log").read() == open(base + ".log").read()
    assert not os.path.exists(out + ".fa.partial")
    assert not os.path.exists(j.path)

    doc = json.load(open(mpath))
    assert doc["meta"]["resumed"] is True
    assert doc["counters"]["resume_skipped_reads"] == 128
    assert doc["counters"]["checkpoint_writes_total"] >= 1
    # restored + freshly-corrected totals equal the uninterrupted run
    assert doc["counters"]["reads_in"] == 240


def test_stage2_resume_without_journal_is_fresh(ec_fixture, tmp_path):
    """--resume with nothing to resume is a plain run (and still
    finalizes atomically)."""
    _tmp, reads_path, db, base = ec_fixture
    out = str(tmp_path / "fresh")
    rc = ec_cli.main(["--batch-size", "64", "--checkpoint-every", "2",
                      "--resume", "-o", out, db, reads_path])
    assert rc == 0
    assert open(out + ".fa").read() == open(base + ".fa").read()
    assert not os.path.exists(out + ".fa.partial")


def test_stage2_checkpoint_flag_validation(ec_fixture, tmp_path):
    _tmp, reads_path, db, _base = ec_fixture
    # no -o prefix: nowhere to journal
    assert ec_cli.main(["--checkpoint-every", "1", db,
                        reads_path]) == 1
    # gzip output cannot be truncated to a commit point
    assert ec_cli.main(["--checkpoint-every", "1", "--gzip", "-o",
                        str(tmp_path / "z"), db, reads_path]) == 1


# ---------------------------------------------------------------------------
# driver retry/backoff (mocked clock, stubbed stages)
# ---------------------------------------------------------------------------

def test_retry_helper_backoff_sequence_and_cap(monkeypatch):
    sleeps = []
    monkeypatch.setattr(quorum_cli, "_sleep", sleeps.append)
    reg = registry_for(None, force=True)
    attempts = []

    def fn(attempt):
        attempts.append(attempt)
        return 1  # always fails

    rc = quorum_cli._run_stage_with_retries(
        reg, "s", fn, retries=4, backoff_ms=10_000.0,
        cursor_fn=lambda: 7)
    assert rc == 1
    assert attempts == [0, 1, 2, 3, 4]
    # 10s, 20s, then capped at 30s
    assert sleeps == [10.0, 20.0, 30.0, 30.0]
    assert reg.counter("stage_retries_total").value == 4


def test_retry_helper_catches_stage_exceptions(monkeypatch):
    monkeypatch.setattr(quorum_cli, "_sleep", lambda s: None)
    reg = registry_for(None, force=True)
    calls = []

    def fn(attempt):
        calls.append(attempt)
        if attempt == 0:
            raise OSError("transient disk error")
        return 0

    rc = quorum_cli._run_stage_with_retries(reg, "s", fn, retries=1,
                                            backoff_ms=1.0)
    assert rc == 0
    assert calls == [0, 1]
    assert reg.counter("stage_retries_total").value == 1


def test_retry_helper_checkpoint_error_fails_fast(monkeypatch):
    """A deterministic refusal (CheckpointError, or a stage CLI's
    rc 3) must not be retried with backoff."""
    sleeps = []
    monkeypatch.setattr(quorum_cli, "_sleep", sleeps.append)
    reg = registry_for(None, force=True)
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise ckpt_mod.CheckpointError("config mismatch")

    rc = quorum_cli._run_stage_with_retries(reg, "s", fn, retries=5,
                                            backoff_ms=100.0)
    assert rc == ckpt_mod.NON_RETRYABLE_RC
    assert calls == [0] and sleeps == []
    rc = quorum_cli._run_stage_with_retries(
        reg, "s", lambda a: ckpt_mod.NON_RETRYABLE_RC, retries=5,
        backoff_ms=100.0)
    assert rc == ckpt_mod.NON_RETRYABLE_RC and sleeps == []


def test_driver_retries_stage2_with_mocked_clock(tmp_path, monkeypatch):
    """The driver's retry loop end-to-end with stubbed stages: stage 2
    fails twice, the backoff sequence is exact, retried attempts pass
    --resume, and the manifest records every attempt."""
    monkeypatch.chdir(tmp_path)
    reads_path, _r, _q = make_dataset(tmp_path, n_reads=8)
    sleeps = []
    monkeypatch.setattr(quorum_cli, "_sleep", sleeps.append)
    monkeypatch.setattr(quorum_cli.cdb_cli, "main",
                        lambda argv, handoff=None, batches=None, batches_factory=None: 0)
    ec_argvs = []

    def fake_ec(argv, db=None, prepacked=None):
        ec_argvs.append(list(argv))
        return 1 if len(ec_argvs) <= 2 else 0

    monkeypatch.setattr(quorum_cli.ec_cli, "main", fake_ec)
    mpath = str(tmp_path / "run.json")
    rc = quorum_cli.main(["-s", "64k", "-k", str(K),
                          "-p", str(tmp_path / "qc"),
                          "--stage-retries", "2",
                          "--retry-backoff-ms", "100",
                          "--checkpoint-dir", str(tmp_path / "ck"),
                          "--metrics", mpath,
                          "--metrics-interval", "60",
                          reads_path])
    assert rc == 0
    assert len(ec_argvs) == 3
    assert sleeps == [0.1, 0.2]                  # 100ms, then doubled
    assert "--resume" not in ec_argvs[0]
    assert "--resume" in ec_argvs[1] and "--resume" in ec_argvs[2]
    assert "--checkpoint-every" in ec_argvs[0]

    doc = json.load(open(mpath))
    assert doc["counters"]["stage_retries_total"] == 2
    assert doc["meta"]["error_correct_attempts"] == 3
    assert doc["meta"]["create_database_attempts"] == 1
    events = [json.loads(ln)
              for ln in open(mpath[:-5] + ".events.jsonl")]
    retries = [e for e in events if e["event"] == "stage_retry"]
    assert [e["attempt"] for e in retries] == [1, 2]
    assert retries[0]["backoff_ms"] == 100
    assert retries[1]["backoff_ms"] == 200
    assert all(e["stage"] == "error_correct" for e in retries)


def test_driver_gives_up_after_retries(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    reads_path, _r, _q = make_dataset(tmp_path, n_reads=8)
    monkeypatch.setattr(quorum_cli, "_sleep", lambda s: None)
    monkeypatch.setattr(quorum_cli.cdb_cli, "main",
                        lambda argv, handoff=None, batches=None, batches_factory=None: 1)
    rc = quorum_cli.main(["-s", "64k", "-k", str(K),
                          "-p", str(tmp_path / "qc"),
                          "--stage-retries", "1", reads_path])
    assert rc == 1


def test_driver_resume_skips_finished_stage1(tmp_path, monkeypatch):
    """driver --resume with the stage-1 database already on disk (and
    no pending checkpoint) goes straight to stage 2."""
    monkeypatch.chdir(tmp_path)
    reads_path, _r, _q = make_dataset(tmp_path, n_reads=8)
    prefix = str(tmp_path / "qc")
    db_file = prefix + "_mer_database.jf"
    # a file with a valid database header (reuse validates it; a
    # garbage file must trigger a rebuild instead — see below)
    open(db_file, "w").write(
        json.dumps({"format": "binary/quorum_tpu_db", "version": 2,
                    "key_len": 2 * K, "bits": 7, "rb_log2": 4,
                    "rows": 16}) + "\n")
    cdb_calls = []
    monkeypatch.setattr(
        quorum_cli.cdb_cli, "main",
        lambda argv, handoff=None, batches=None, batches_factory=None: cdb_calls.append(1) or 0)
    ec_argvs = []

    def fake_ec(argv, db=None, prepacked=None):
        ec_argvs.append(list(argv))
        assert db is None and prepacked is None  # re-reads from disk
        return 0

    monkeypatch.setattr(quorum_cli.ec_cli, "main", fake_ec)
    mpath = str(tmp_path / "run.json")
    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix,
                          "--resume", "--metrics", mpath, reads_path])
    assert rc == 0
    assert cdb_calls == []            # stage 1 skipped
    assert len(ec_argvs) == 1
    doc = json.load(open(mpath))
    assert doc["meta"]["stage1_resumed_db"] == db_file

    # a torn/foreign file at the db path must NOT be reused: stage 1
    # reruns instead of feeding stage 2 garbage
    open(db_file, "w").write("torn garbage, not a database")
    rc = quorum_cli.main(["-s", "64k", "-k", str(K), "-p", prefix,
                          "--resume", reads_path])
    assert rc == 0
    assert cdb_calls == [1]           # stage 1 ran this time


# ---------------------------------------------------------------------------
# hard process exit (the real kill) — subprocess, shared compile cache
# ---------------------------------------------------------------------------

def test_hard_exit_fault_kills_process(tmp_path):
    """The `exit` action is a real os._exit: no cleanup, no atexit.
    Exercised on a trivial script so the test stays cheap; the full
    kill-at-batch-N -> resume -> byte-diff acceptance runs in
    ci/tier1.sh (tools/resume_smoke.py)."""
    import subprocess
    import sys as _sys

    code = ("from quorum_tpu.utils import faults\n"
            "faults.setup('[{\"site\": \"x\", \"action\": \"exit\", "
            "\"code\": 43}]')\n"
            "faults.inject('x')\n"
            "print('unreachable')\n")
    res = subprocess.run(
        [_sys.executable, "-c", code],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 43
    assert "unreachable" not in res.stdout
    assert "hard exit (43) at x" in res.stderr


# ---------------------------------------------------------------------------
# metrics_check learns the fault-tolerance names
# ---------------------------------------------------------------------------

def test_metrics_check_fault_names(tmp_path):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "metrics_check", os.path.join(repo, "tools", "metrics_check.py"))
    mc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mc)

    ok = {"meta": {"checkpoint_every": 4, "resumed": True,
                   "on_bad_read": "skip", "driver": "quorum"},
          "counters": {"checkpoint_writes_total": 0,
                       "resume_skipped_reads": 128,
                       "bad_reads_total": 2,
                       "stage_retries_total": 1}}
    assert mc._check_fault_names(ok) == []
    missing = {"meta": ok["meta"], "counters": {}}
    errs = mc._check_fault_names(missing)
    assert len(errs) == 4
    assert any("checkpoint_writes_total" in e for e in errs)
    assert any("resume_skipped_reads" in e for e in errs)
    assert any("bad_reads_total" in e for e in errs)
    assert any("stage_retries_total" in e for e in errs)
    # undeclared features require nothing
    assert mc._check_fault_names({"meta": {}, "counters": {}}) == []


# ---------------------------------------------------------------------------
# the hang action + serve sites (ISSUE 7): interruptible sleep-forever
# ---------------------------------------------------------------------------

def test_hang_action_blocks_until_released():
    faults.install(faults.FaultPlan.parse(
        {"site": "serve.engine.step", "action": "hang"}), "hang-t1")
    entered = threading.Event()
    done = threading.Event()

    def victim():
        entered.set()
        faults.inject("serve.engine.step")
        done.set()

    t = threading.Thread(target=victim, daemon=True)
    t.start()
    assert entered.wait(5)
    assert not done.wait(0.2), "hang action did not block"
    faults.release_hangs()
    assert done.wait(5), "release_hangs did not wake the thread"
    t.join(timeout=5)


def test_hang_released_by_next_plan_install():
    faults.install(faults.FaultPlan.parse(
        {"site": "x", "action": "hang"}), "hang-t2")
    done = threading.Event()

    def victim():
        faults.inject("x")
        done.set()

    t = threading.Thread(target=victim, daemon=True)
    t.start()
    assert not done.wait(0.2)
    # installing the NEXT plan must not leak the old plan's threads
    faults.install(faults.FaultPlan.parse(
        {"site": "y", "action": "error"}), "hang-t3")
    assert done.wait(5), "new install did not release hung threads"
    t.join(timeout=5)


def test_hang_spec_at_count_semantics():
    """hang participates in at/count matching like any other action;
    a released plan's further hangs return immediately (released
    stays released)."""
    plan = faults.FaultPlan.parse({"site": "s", "at": 2, "action": "hang"})
    plan.fire("s")                # hit 1: below at -> no action
    assert plan.specs[0].fired == 0
    plan.release_hangs()
    plan.fire("s")                # hit 2: fires, returns at once
    assert plan.specs[0].fired == 1


def test_serve_admit_and_reload_sites_fire():
    faults.install(faults.FaultPlan.parse([
        {"site": "serve.admit", "action": "error"},
        {"site": "serve.reload", "action": "io_error"},
    ]), "sites-t")
    with pytest.raises(faults.FaultError):
        faults.inject("serve.admit")
    with pytest.raises(OSError):
        faults.inject("serve.reload")
