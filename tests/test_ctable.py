"""Tests for the compact one-word-per-entry table (quorum_tpu.ops.ctable).

Covers: Feistel bijectivity (exhaustive for small k), device/host hash
twins, key recovery (iterator), grow rehash consistency, build/query
parity against both a sequential replay of the reference add() rule and
the bucket-overflow -> grow path
(the reference's FULL contract, forced by undersizing — the same trick
as unit_tests/test_mer_database.cc's small initial sizes)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quorum_tpu.ops import ctable

def brute_force_counts(obs, bits):
    """obs: list of (key_int, qual). Returns {key: (count, qual)} by
    replaying the reference add() rule sequentially
    (mer_database.hpp:94-113; formerly in the retired test_table.py)."""
    max_val = (1 << bits) - 1
    d = {}
    for key, q in obs:
        cnt, cq = d.get(key, (0, 0))
        if cq < q:
            d[key] = (1, 1)
        elif cnt == max_val or cq > q:
            pass
        else:
            d[key] = (cnt + 1, cq)
    return d


def split_keys(keys):
    khi = jnp.asarray((keys >> np.uint64(32)).astype(np.uint32))
    klo = jnp.asarray((keys & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    return khi, klo


@pytest.mark.parametrize("k", [4, 9])
def test_feistel_bijective_exhaustive(k):
    n = 1 << (2 * k)
    keys = np.arange(n, dtype=np.uint64)
    khi, klo = split_keys(keys)
    l, r = jax.jit(ctable.feistel_mix, static_argnums=2)(khi, klo, k)
    full = np.asarray(l).astype(np.uint64) << np.uint64(k)
    full = full | np.asarray(r).astype(np.uint64)
    assert len(np.unique(full)) == n  # injective on the full domain
    il, ir = jax.jit(ctable.feistel_unmix, static_argnums=2)(l, r, k)
    ihi, ilo = ctable._halves_to_key(il, ir, k)
    assert np.array_equal(np.asarray(ihi), np.asarray(khi))
    assert np.array_equal(np.asarray(ilo), np.asarray(klo))


@pytest.mark.parametrize("k", [9, 16, 24, 27])
def test_bucket_rem_device_matches_host(k):
    rng = np.random.default_rng(k)
    meta = ctable.CTableMeta(k=k, bits=7,
                             nb_log2=max(6, ctable.min_nb_log2(k, 7)))
    keys = rng.integers(0, 1 << min(63, 2 * k), size=200, dtype=np.uint64)
    keys &= (1 << np.uint64(2 * k)) - np.uint64(1)
    khi, klo = split_keys(keys)
    db, dr = jax.jit(ctable.bucket_rem, static_argnums=2)(khi, klo, meta)
    for i in range(len(keys)):
        hb, hr = ctable.bucket_rem_np(np.uint32(khi[i]), np.uint32(klo[i]),
                                      meta)
        assert int(db[i]) == hb
        assert int(dr[i]) == int(hr)


@pytest.mark.parametrize("k", [9, 24, 27])
def test_keys_from_table_inverts(k):
    rng = np.random.default_rng(k + 1)
    meta = ctable.CTableMeta(k=k, bits=7,
                             nb_log2=max(8, ctable.min_nb_log2(k, 7)))
    keys = rng.integers(0, 1 << min(63, 2 * k), size=500, dtype=np.uint64)
    keys &= (1 << np.uint64(2 * k)) - np.uint64(1)
    khi, klo = split_keys(keys)
    b, r = jax.jit(ctable.bucket_rem, static_argnums=2)(khi, klo, meta)
    ihi, ilo = ctable.keys_from_table(b, r, meta)
    assert np.array_equal(np.asarray(ihi), np.asarray(khi))
    assert np.array_equal(np.asarray(ilo), np.asarray(klo))


@pytest.mark.parametrize("k", [9, 24])
def test_rehash_grow_matches_rehashing(k):
    rng = np.random.default_rng(k + 2)
    nb = max(8, ctable.min_nb_log2(k, 7))
    meta1 = ctable.CTableMeta(k=k, bits=7, nb_log2=nb)
    meta2 = ctable.CTableMeta(k=k, bits=7, nb_log2=nb + 1)
    keys = rng.integers(0, 1 << min(63, 2 * k), size=300, dtype=np.uint64)
    keys &= (1 << np.uint64(2 * k)) - np.uint64(1)
    khi, klo = split_keys(keys)
    b1, r1 = jax.jit(ctable.bucket_rem, static_argnums=2)(khi, klo, meta1)
    gb, gr = ctable.rehash_grow(b1, r1, meta1.nb_log2)
    b2, r2 = jax.jit(ctable.bucket_rem, static_argnums=2)(khi, klo, meta2)
    assert np.array_equal(np.asarray(gb), np.asarray(b2))
    assert np.array_equal(np.asarray(gr), np.asarray(r2))


def build_from_obs(meta, keys, quals, batch=97, max_grows=12):
    """insert_observations in batches with the grow-retry protocol."""
    bstate = ctable.make_build_table(meta)
    for start in range(0, len(keys), batch):
        kk = keys[start:start + batch]
        qq = quals[start:start + batch]
        khi, klo = split_keys(kk)
        qd = jnp.asarray(qq.astype(np.int32))
        pending = jnp.ones(len(kk), dtype=bool)
        for _ in range(max_grows + 1):
            bstate, full, placed = ctable.insert_observations(
                bstate, meta, khi, klo, qd, pending)
            if not full:
                break
            pending = np.asarray(pending & ~np.asarray(placed))
            pending = jnp.asarray(pending)
            bstate, meta = ctable.grow_build(bstate, meta)
        else:
            raise RuntimeError("Hash is full")
    return bstate, meta


@pytest.mark.parametrize("bits", [3, 7])
@pytest.mark.parametrize("nb_log2", [2, 6, 10])
def test_build_matches_sequential_reference_rule(bits, nb_log2):
    k = 12  # keeps min_nb_log2 = 0 so tiny tables force the grow path
    rng = np.random.default_rng(nb_log2 * 100 + bits)
    pool = rng.integers(0, 1 << (2 * k), size=60, dtype=np.uint64)
    idx = rng.integers(0, len(pool), size=800)
    keys = pool[idx]
    quals = rng.integers(0, 2, size=len(keys))
    meta = ctable.CTableMeta(k=k, bits=bits, nb_log2=nb_log2)
    bstate, meta = build_from_obs(meta, keys, quals)
    state = ctable.finalize_build(bstate, meta)

    expect = brute_force_counts(
        [(int(keys[i]), int(quals[i])) for i in range(len(keys))], bits)
    entries = np.asarray(state.entries)
    khi, klo = split_keys(np.asarray(sorted(expect), dtype=np.uint64))
    vals = ctable.lookup(state, meta, khi, klo)
    for i, key in enumerate(sorted(expect)):
        cnt, q = expect[key]
        got = int(vals[i])
        assert got >> 1 == cnt, (hex(key), cnt, got >> 1)
        assert got & 1 == q
        assert ctable.lookup_np(entries, meta, np.uint32(key >> 32),
                                np.uint32(key & 0xFFFFFFFF)) == got
    # absent keys miss
    absent = rng.integers(0, 1 << (2 * k), size=50, dtype=np.uint64)
    absent = np.asarray([a for a in absent if int(a) not in expect],
                        dtype=np.uint64)
    if len(absent):
        ahi, alo = split_keys(absent)
        avals = ctable.lookup(state, meta, ahi, alo)
        assert not np.any(np.asarray(avals))


def test_count_at_best_quality_brute_force():
    """Same observation stream into ctable vs a host brute force of the
    reference's count-at-best-quality semantics (mer_database.hpp:
    94-113: an HQ observation of a key seen only LQ resets the count;
    LQ observations of an HQ key don't count): identical value words
    for every key. (Replaces the retired wide-table cross-check with
    an implementation-independent oracle.)"""
    k, bits = 15, 7
    rng = np.random.default_rng(7)
    pool = rng.integers(0, 1 << (2 * k), size=500, dtype=np.uint64)
    idx = rng.integers(0, len(pool), size=5000)
    keys = pool[idx]
    quals = rng.integers(0, 2, size=len(keys))

    cmeta = ctable.CTableMeta(k=k, bits=bits, nb_log2=9)
    bstate, cmeta = build_from_obs(cmeta, keys, quals, batch=701)
    cstate = ctable.finalize_build(bstate, cmeta)

    maxc = (1 << bits) - 1
    expect = {}
    for key, q in zip(keys.tolist(), quals.tolist()):
        hq, lq = expect.get(key, (0, 0))
        expect[key] = (hq + q, lq + (1 - q))
    uniq = np.unique(keys)
    want = np.array([
        (min(hq if hq else lq, maxc) << 1) | (1 if hq else 0)
        for hq, lq in (expect[key] for key in uniq.tolist())
    ], np.uint32)

    khi, klo = split_keys(uniq)
    cv = np.asarray(ctable.lookup(cstate, cmeta, khi, klo))
    assert np.array_equal(cv, want)


def test_iterate_entries_recovers_key_set():
    k = 14
    rng = np.random.default_rng(3)
    keys = np.unique(
        rng.integers(0, 1 << (2 * k), size=300, dtype=np.uint64))
    quals = rng.integers(0, 2, size=len(keys))
    meta = ctable.CTableMeta(k=k, bits=7, nb_log2=8)
    bstate, meta = build_from_obs(meta, keys, quals)
    state = ctable.finalize_build(bstate, meta)
    khi, klo, vals = ctable.iterate_entries(state, meta)
    got = set((np.asarray(khi).astype(np.uint64) << np.uint64(32)
               | np.asarray(klo).astype(np.uint64)).tolist())
    assert got == set(keys.tolist())
    assert np.all(vals != 0)


def test_layout_infeasible_raises():
    with pytest.raises(ValueError):
        ctable.CTableMeta(k=24, bits=7, nb_log2=10)
    assert ctable.layout_fits(24, 7, 24)
    assert not ctable.layout_fits(24, 7, 23)
    assert ctable.required_nb_log2(100, 24, 7) == 24


# ---------------------------------------------------------------------------
# Tile-bucket query layout
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [9, 24, 31])
def test_tile_roundtrip_and_lookup(k):
    """Synthetic (key, val) entries -> tile table: lookups hit exactly,
    absent keys miss, iterator recovers the key set, host mirror
    agrees."""
    rng = np.random.default_rng(k)
    keys = np.unique(
        rng.integers(0, 1 << min(63, 2 * k), size=4000, dtype=np.uint64)
        & ((1 << np.uint64(2 * k)) - np.uint64(1)))
    vals = rng.integers(2, 256, size=len(keys), dtype=np.uint32)
    khi, klo = split_keys(keys)
    state, meta = ctable.tile_from_entries(np.asarray(khi), np.asarray(klo),
                                           vals, k, bits=7)
    got = np.asarray(ctable.tile_lookup(state, meta, khi, klo))
    assert np.array_equal(got, vals)

    absent = np.setdiff1d(
        rng.integers(0, 1 << min(63, 2 * k), size=500, dtype=np.uint64)
        & ((1 << np.uint64(2 * k)) - np.uint64(1)), keys)
    ahi, alo = split_keys(absent)
    assert not np.any(np.asarray(ctable.tile_lookup(state, meta, ahi, alo)))

    ikhi, iklo, ivals = ctable.tile_iterate(state, meta)
    got_keys = set((ikhi.astype(np.uint64) << np.uint64(32)
                    | iklo.astype(np.uint64)).tolist())
    assert got_keys == set(keys.tolist())

    rows = np.asarray(state.rows)
    for i in rng.integers(0, len(keys), size=30):
        assert ctable.tile_lookup_np(rows, meta, np.uint32(khi[i]),
                                     np.uint32(klo[i])) == int(vals[i])


def test_tile_from_build_matches_bucket4():
    """Full path: observations -> bucket-4 build -> tile pack; tile
    lookups equal the bucket-4 lookups for every key."""
    k = 13
    rng = np.random.default_rng(5)
    pool = rng.integers(0, 1 << (2 * k), size=400, dtype=np.uint64)
    keys = pool[rng.integers(0, len(pool), size=4000)]
    quals = rng.integers(0, 2, size=len(keys))
    meta = ctable.CTableMeta(k=k, bits=7, nb_log2=8)
    bstate, meta = build_from_obs(meta, keys, quals, batch=997)
    cstate = ctable.finalize_build(bstate, meta)
    tstate, tmeta = ctable.tile_from_build(bstate, meta)

    uniq = np.unique(keys)
    khi, klo = split_keys(uniq)
    cv = np.asarray(ctable.lookup(cstate, meta, khi, klo))
    tv = np.asarray(ctable.tile_lookup(tstate, tmeta, khi, klo))
    assert np.array_equal(cv, tv)

    co, cd, ct = ctable.table_stats(cstate, meta)
    to, td, tt = ctable.tile_stats(tstate, tmeta)
    assert (int(co), int(cd), float(ct)) == (int(to), int(td), float(tt))


def test_tile_overflow_grows_rows():
    """Force >64 entries into one bucket's worth of keys by undersizing
    rows; packing must auto-double until it fits."""
    k = 10
    rng = np.random.default_rng(9)
    keys = np.unique(rng.integers(0, 1 << (2 * k), size=600,
                                  dtype=np.uint64))
    vals = np.full(len(keys), 5, dtype=np.uint32)
    khi, klo = split_keys(keys)
    state, meta = ctable.tile_from_entries(np.asarray(khi), np.asarray(klo),
                                           vals, k, bits=7, rb_log2=0)
    assert meta.rb_log2 > 0  # grew
    got = np.asarray(ctable.tile_lookup(state, meta, khi, klo))
    assert np.array_equal(got, vals)


def tile_build_from_obs(meta, keys, quals, batch=97, max_grows=12):
    """tile_insert_observations with the grow-retry protocol."""
    bstate = ctable.make_tile_build(meta)
    for start in range(0, len(keys), batch):
        kk = keys[start:start + batch]
        qq = quals[start:start + batch]
        khi, klo = split_keys(kk)
        qd = jnp.asarray(qq.astype(np.int32))
        pending = jnp.ones(len(kk), dtype=bool)
        for _ in range(max_grows + 1):
            bstate, full, placed = ctable.tile_insert_observations(
                bstate, meta, khi, klo, qd, pending)
            if not full:
                break
            pending = jnp.asarray(np.asarray(pending) & ~np.asarray(placed))
            bstate, meta = ctable.tile_grow_build(bstate, meta)
        else:
            raise RuntimeError("Hash is full")
    return bstate, meta


@pytest.mark.parametrize("k,rb_log2", [(12, 0), (12, 4), (24, 6), (31, 8)])
def test_tile_direct_build_matches_reference_rule(k, rb_log2):
    bits = 7
    rng = np.random.default_rng(k * 10 + rb_log2)
    pool = rng.integers(0, 1 << min(63, 2 * k), size=300,
                        dtype=np.uint64) & ((1 << np.uint64(2 * k)) -
                                            np.uint64(1))
    keys = pool[rng.integers(0, len(pool), size=3000)]
    quals = rng.integers(0, 2, size=len(keys))
    rb = max(rb_log2, ctable.min_tile_rb_log2(k, bits))
    meta = ctable.TileMeta(k=k, bits=bits, rb_log2=rb)
    bstate, meta = tile_build_from_obs(meta, keys, quals, batch=997)
    state = ctable.tile_finalize(bstate, meta)

    expect = brute_force_counts(
        [(int(keys[i]), int(quals[i])) for i in range(len(keys))], bits)
    uk = np.asarray(sorted(expect), dtype=np.uint64)
    khi, klo = split_keys(uk)
    vals = np.asarray(ctable.tile_lookup(state, meta, khi, klo))
    for i, key in enumerate(uk):
        cnt, q = expect[int(key)]
        assert int(vals[i]) == (cnt << 1) | q, (hex(int(key)), cnt, q,
                                                int(vals[i]))
    # iterator recovers exactly the inserted key set
    ikhi, iklo, _ = ctable.tile_iterate(state, meta)
    got = set((ikhi.astype(np.uint64) << np.uint64(32)
               | iklo.astype(np.uint64)).tolist())
    assert got == set(int(x) for x in uk)


def test_tile_direct_build_duplicate_flood():
    """Thousands of copies of few keys in one batch: same-key lanes
    must converge without slot waste."""
    k, bits = 16, 7
    rng = np.random.default_rng(0)
    pool = rng.integers(0, 1 << (2 * k), size=5, dtype=np.uint64)
    keys = pool[rng.integers(0, 5, size=8000)]
    quals = np.ones(len(keys), dtype=np.int64)
    meta = ctable.TileMeta(k=k, bits=bits, rb_log2=2)
    bstate, meta = tile_build_from_obs(meta, keys, quals, batch=8000)
    state = ctable.tile_finalize(bstate, meta)
    occ, distinct, _ = ctable.tile_stats(state, meta)
    assert int(occ) == len(np.unique(keys))
    khi, klo = split_keys(np.unique(keys))
    vals = np.asarray(ctable.tile_lookup(state, meta, khi, klo))
    assert np.all(vals >> 1 == 127)  # saturated at max_val
    assert np.all(vals & 1 == 1)
