"""Host pipeline error paths and telemetry gauges: producer exceptions
re-raised at the consumer, AsyncWriter fail-fast and single-raise on
close, and the queue-depth/stall instrumentation."""

import io
import time

import pytest

from quorum_tpu.telemetry import MetricsRegistry
from quorum_tpu.utils.pipeline import AsyncWriter, prefetch


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------

def test_prefetch_passes_items_in_order():
    assert list(prefetch(iter(range(100)), depth=3)) == list(range(100))


def test_prefetch_producer_exception_reraises_at_consumer():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("producer blew up")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="producer blew up"):
        next(it)


def test_prefetch_immediate_producer_error():
    def gen():
        raise ValueError("dead on arrival")
        yield  # pragma: no cover

    with pytest.raises(ValueError, match="dead on arrival"):
        list(prefetch(gen()))


def test_prefetch_consumer_abandon_releases_producer():
    state = {"produced": 0}

    def gen():
        for i in range(10_000):
            state["produced"] += 1
            yield i

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()  # generator close -> stop event -> producer unblocks
    time.sleep(0.5)
    assert state["produced"] < 10_000


def test_prefetch_queue_depth_gauge():
    reg = MetricsRegistry()
    # producer instant, consumer slow: the queue should reach depth
    list_out = []
    for item in prefetch(iter(range(20)), depth=4, metrics=reg):
        time.sleep(0.01)
        list_out.append(item)
    assert list_out == list(range(20))
    depth = reg.gauge("prefetch_queue_depth_max").value
    assert 1 <= depth <= 4


def test_prefetch_producer_stall_gauge():
    reg = MetricsRegistry()
    # depth 1 + slow consumer: the producer must block on a full queue
    for _ in prefetch(iter(range(5)), depth=1, metrics=reg):
        time.sleep(0.25)
    assert reg.gauge("prefetch_producer_stall_seconds").value > 0.0


def test_prefetch_custom_name_prefixes_gauges():
    reg = MetricsRegistry()
    list(prefetch(iter(range(3)), metrics=reg, name="reader"))
    assert "reader_queue_depth_max" in reg.as_dict()["gauges"]


# ---------------------------------------------------------------------------
# AsyncWriter
# ---------------------------------------------------------------------------

class BrokenStream:
    def __init__(self, fail_after=0):
        self.n = 0
        self.fail_after = fail_after

    def write(self, text):
        self.n += 1
        if self.n > self.fail_after:
            raise OSError("dead pipe")


def test_async_writer_writes_and_closes():
    a, b = io.StringIO(), io.StringIO()
    w = AsyncWriter([a, b])
    w.write(0, "x1")
    w.write(1, "y1")
    w.write(0, "x2")
    w.close()
    assert a.getvalue() == "x1x2"
    assert b.getvalue() == "y1"


def test_async_writer_fail_fast_on_write():
    w = AsyncWriter([BrokenStream()])
    w.write(0, "first")  # lands in the queue; the writer thread dies on it
    deadline = time.time() + 5.0
    while w.err is None and time.time() < deadline:
        time.sleep(0.01)
    assert w.err is not None
    with pytest.raises(OSError, match="dead pipe"):
        w.write(0, "second")
    # already raised at write: close() must not raise again
    w.close()


def test_async_writer_single_raise_on_close():
    w = AsyncWriter([BrokenStream()])
    w.write(0, "boom")
    deadline = time.time() + 5.0
    while w.err is None and time.time() < deadline:
        time.sleep(0.01)
    with pytest.raises(OSError, match="dead pipe"):
        w.close()


def test_async_writer_empty_text_skipped():
    a = io.StringIO()
    w = AsyncWriter([a])
    w.write(0, "")
    w.write(0, "data")
    w.close()
    assert a.getvalue() == "data"


def test_async_writer_queue_depth_gauge():
    class SlowStream:
        def __init__(self):
            self.buf = []

        def write(self, text):
            time.sleep(0.02)
            self.buf.append(text)

    reg = MetricsRegistry()
    s = SlowStream()
    w = AsyncWriter([s], metrics=reg)
    for i in range(10):
        w.write(0, f"r{i}")
    w.close()
    assert s.buf == [f"r{i}" for i in range(10)]
    assert reg.gauge("writer_queue_depth_max").value >= 1
