"""Telemetry subsystem: registry semantics, schema validation, the
metrics_check tool's dispatch, and the vlog env-var fallback."""

import importlib
import json
import os
import subprocess
import sys
import threading

import pytest

from quorum_tpu.telemetry import (NULL, MetricsRegistry, SCHEMA_VERSION,
                                  check_file, metric_line, registry_for,
                                  validate_bench_line,
                                  validate_events_line, validate_metrics)
from quorum_tpu.utils.profiling import StageTimer

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
METRICS_CHECK = os.path.join(REPO, "tools", "metrics_check.py")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_null_registry_is_free_and_inert(tmp_path):
    reg = registry_for(None)
    assert reg is NULL
    assert not reg.enabled
    # every surface is a no-op, nothing raises, nothing is written
    reg.counter("c").inc(5)
    reg.gauge("g").set(3)
    reg.gauge("g").set_max(9)
    reg.gauge("g").add(1.0)
    reg.histogram("h").observe(2)
    reg.set_meta(a=1)
    reg.set_timer("t", {})
    reg.event("e", x=1)
    reg.heartbeat(bases=10)
    assert reg.write(str(tmp_path / "never.json")) is None
    assert not (tmp_path / "never.json").exists()
    assert validate_metrics(reg.as_dict()) == []


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter("reads").inc()
    reg.counter("reads").inc(4)
    reg.gauge("fill").set(0.25)
    reg.gauge("depth").set_max(2)
    reg.gauge("depth").set_max(1)  # lower: ignored
    reg.gauge("stall").add(0.5)
    reg.gauge("stall").add(0.25)
    reg.histogram("subs").observe(0, 10)
    reg.histogram("subs").observe(3, 2)
    doc = reg.as_dict()
    assert doc["schema"] == SCHEMA_VERSION
    assert doc["counters"]["reads"] == 5
    assert doc["gauges"]["fill"] == 0.25
    assert doc["gauges"]["depth"] == 2
    assert doc["gauges"]["stall"] == 0.75
    h = doc["histograms"]["subs"]
    assert h == {"count": 12, "sum": 6, "counts": {"0": 10, "3": 2}}
    assert validate_metrics(doc) == []


def test_registry_threaded_counts_are_exact():
    reg = MetricsRegistry()
    c = reg.counter("n")

    def work():
        for _ in range(1000):
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 8000


def test_registry_write_and_events(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=0.001)
    assert reg.enabled
    reg.set_meta(stage="test", k=13)
    reg.counter("reads").inc(7)
    reg.event("hash_grow", rows_before=8, rows_after=16)
    reg.heartbeat(reads=7, bases=1000)
    t = StageTimer()
    with t.stage("insert"):
        pass
    t.add_units("insert", 1000)
    reg.set_timer("stage1", t.as_dict(1000))
    assert reg.write() == p
    doc = json.load(open(p))
    assert validate_metrics(doc) == []
    assert doc["meta"]["stage"] == "test"
    assert doc["counters"]["reads"] == 7
    assert doc["timers"]["stage1"]["stages"]["insert"]["units"] == 1000
    # the events stream sits next to the json and validates too
    ev = p[:-5] + ".events.jsonl"
    assert os.path.exists(ev)
    assert check_file(ev) == []
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    kinds = [x["event"] for x in lines]
    assert "hash_grow" in kinds and "heartbeat" in kinds
    hb = next(x for x in lines if x["event"] == "heartbeat")
    assert "gb_per_h" in hb  # derived from the bases field


def test_heartbeat_rate_limited(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=1000.0)
    for i in range(50):
        reg.heartbeat(reads=i)
    reg.write()
    ev = p[:-5] + ".events.jsonl"
    lines = [x for x in open(ev) if x.strip()]
    assert len(lines) == 1  # only the first beat within the period


def test_no_events_without_interval(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p)  # heartbeat_s = 0
    reg.heartbeat(reads=1)
    reg.event("e")
    reg.write()
    assert not os.path.exists(p[:-5] + ".events.jsonl")


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

def test_validate_metrics_rejects_malformed():
    assert validate_metrics([]) != []
    assert validate_metrics({"schema": "nope"}) != []
    base = MetricsRegistry().as_dict()
    bad = dict(base, counters={"c": -1})
    assert any("non-negative" in e for e in validate_metrics(bad))
    bad = dict(base, gauges={"g": "high"})
    assert any("not a number" in e for e in validate_metrics(bad))
    bad = dict(base, histograms={"h": {"count": 3, "sum": 1,
                                       "counts": {"0": 1}}})
    assert any("counts sum" in e for e in validate_metrics(bad))
    bad = dict(base, extra={})
    assert any("unknown top-level" in e for e in validate_metrics(bad))


def test_validate_events_and_bench_lines():
    assert validate_events_line({"event": "x", "t": 0.1, "n": 3}) == []
    assert validate_events_line({"t": 0.1}) != []
    assert validate_events_line({"event": "x", "t": 0.1,
                                 "bad": [1, 2]}) != []
    assert validate_bench_line(json.loads(
        metric_line("accuracy", pct=1.5, unit="Gb/h"))) == []
    assert validate_bench_line({"value": 2}) != []
    with pytest.raises(ValueError):
        metric_line("m", bad=[1, 2, 3])
    with pytest.raises(ValueError):
        metric_line("")


def test_check_file_dispatches_on_content(tmp_path):
    # bench-style metric lines in a .json file (BENCH_*.json shape)
    bench = tmp_path / "bench.json"
    bench.write_text(metric_line("a", value=1) + "\n"
                     + "# comment\n"
                     + metric_line("b", value=2) + "\n")
    assert check_file(str(bench)) == []
    bad = tmp_path / "bad.json"
    bad.write_text('{"value": 1}\n{"metric": "x", "v": [1]}\n')
    errs = check_file(str(bad))
    assert any(e.startswith("line 1:") for e in errs)
    assert any(e.startswith("line 2:") and "not scalar" in e
               for e in errs)
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert check_file(str(empty)) != []
    assert check_file(str(tmp_path / "missing.json")) != []


def test_metrics_check_tool_cli(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p)
    reg.counter("c").inc()
    reg.write()
    res = subprocess.run([sys.executable, METRICS_CHECK, p],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert "OK" in res.stdout
    bad = tmp_path / "bad.json"
    bad.write_text('{"schema": "wrong", "meta": {}, "counters": {}, '
                   '"gauges": {}, "histograms": {}, "timers": {}}')
    res = subprocess.run([sys.executable, METRICS_CHECK, p, str(bad)],
                         capture_output=True, text=True)
    assert res.returncode == 1
    assert "schema" in res.stderr


# ---------------------------------------------------------------------------
# StageTimer.as_dict (the registry feed) and vlog env fallback
# ---------------------------------------------------------------------------

def test_stage_timer_as_dict_matches_report_facts():
    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("a"):
        pass
    t.add_units("a", 2000)
    d = t.as_dict(2000)
    assert d["stages"]["a"]["calls"] == 2
    assert d["stages"]["a"]["units"] == 2000
    assert d["total_seconds"] >= d["stages"]["a"]["seconds"] >= 0
    assert d["total_units"] == 2000
    assert d["units_per_hour"] > 0
    # attaches cleanly to the schema
    reg = MetricsRegistry()
    reg.set_timer("s", d)
    assert validate_metrics(reg.as_dict()) == []


def test_vlog_env_var_fallback(monkeypatch):
    from quorum_tpu.utils import vlog as vlog_mod
    old = vlog_mod.verbose
    try:
        monkeypatch.setenv("QUORUM_TPU_VERBOSE", "1")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is True
        monkeypatch.setenv("QUORUM_TPU_VERBOSE", "0")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is False
        monkeypatch.delenv("QUORUM_TPU_VERBOSE")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is False
    finally:
        importlib.reload(vlog_mod)
        vlog_mod.verbose = old
