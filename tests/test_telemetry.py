"""Telemetry subsystem: registry semantics, schema validation, the
metrics_check tool's dispatch, and the vlog env-var fallback."""

import importlib
import json
import os
import subprocess
import sys
import threading

import pytest

from quorum_tpu.telemetry import (NULL, MetricsRegistry, SCHEMA_VERSION,
                                  check_file, metric_line, registry_for,
                                  validate_bench_line,
                                  validate_events_line, validate_metrics)
from quorum_tpu.utils.profiling import StageTimer

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
METRICS_CHECK = os.path.join(REPO, "tools", "metrics_check.py")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_null_registry_is_free_and_inert(tmp_path):
    reg = registry_for(None)
    assert reg is NULL
    assert not reg.enabled
    # every surface is a no-op, nothing raises, nothing is written
    reg.counter("c").inc(5)
    reg.gauge("g").set(3)
    reg.gauge("g").set_max(9)
    reg.gauge("g").add(1.0)
    reg.histogram("h").observe(2)
    reg.set_meta(a=1)
    reg.set_timer("t", {})
    reg.event("e", x=1)
    reg.heartbeat(bases=10)
    assert reg.write(str(tmp_path / "never.json")) is None
    assert not (tmp_path / "never.json").exists()
    assert validate_metrics(reg.as_dict()) == []


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter("reads").inc()
    reg.counter("reads").inc(4)
    reg.gauge("fill").set(0.25)
    reg.gauge("depth").set_max(2)
    reg.gauge("depth").set_max(1)  # lower: ignored
    reg.gauge("stall").add(0.5)
    reg.gauge("stall").add(0.25)
    reg.histogram("subs").observe(0, 10)
    reg.histogram("subs").observe(3, 2)
    doc = reg.as_dict()
    assert doc["schema"] == SCHEMA_VERSION
    assert doc["counters"]["reads"] == 5
    assert doc["gauges"]["fill"] == 0.25
    assert doc["gauges"]["depth"] == 2
    assert doc["gauges"]["stall"] == 0.75
    h = doc["histograms"]["subs"]
    assert h == {"count": 12, "sum": 6, "counts": {"0": 10, "3": 2}}
    assert validate_metrics(doc) == []


def test_registry_threaded_counts_are_exact():
    reg = MetricsRegistry()
    c = reg.counter("n")

    def work():
        for _ in range(1000):
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 8000


def test_registry_write_and_events(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=0.001)
    assert reg.enabled
    reg.set_meta(stage="test", k=13)
    reg.counter("reads").inc(7)
    reg.event("hash_grow", rows_before=8, rows_after=16)
    reg.heartbeat(reads=7, bases=1000)
    t = StageTimer()
    with t.stage("insert"):
        pass
    t.add_units("insert", 1000)
    reg.set_timer("stage1", t.as_dict(1000))
    assert reg.write() == p
    doc = json.load(open(p))
    assert validate_metrics(doc) == []
    assert doc["meta"]["stage"] == "test"
    assert doc["counters"]["reads"] == 7
    assert doc["timers"]["stage1"]["stages"]["insert"]["units"] == 1000
    # the events stream sits next to the json and validates too
    ev = p[:-5] + ".events.jsonl"
    assert os.path.exists(ev)
    assert check_file(ev) == []
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    kinds = [x["event"] for x in lines]
    assert "hash_grow" in kinds and "heartbeat" in kinds
    hb = next(x for x in lines if x["event"] == "heartbeat")
    assert "gb_per_h" in hb  # derived from the bases field


def test_heartbeat_rate_limited(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=1000.0)
    for i in range(50):
        reg.heartbeat(reads=i)
    reg.write()
    ev = p[:-5] + ".events.jsonl"
    lines = [x for x in open(ev) if x.strip()]
    assert len(lines) == 1  # only the first beat within the period


def test_no_events_without_interval(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p)  # heartbeat_s = 0
    reg.heartbeat(reads=1)
    reg.event("e")
    reg.write()
    assert not os.path.exists(p[:-5] + ".events.jsonl")


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

def test_validate_metrics_rejects_malformed():
    assert validate_metrics([]) != []
    assert validate_metrics({"schema": "nope"}) != []
    base = MetricsRegistry().as_dict()
    bad = dict(base, counters={"c": -1})
    assert any("non-negative" in e for e in validate_metrics(bad))
    bad = dict(base, gauges={"g": "high"})
    assert any("not a number" in e for e in validate_metrics(bad))
    bad = dict(base, histograms={"h": {"count": 3, "sum": 1,
                                       "counts": {"0": 1}}})
    assert any("counts sum" in e for e in validate_metrics(bad))
    bad = dict(base, extra={})
    assert any("unknown top-level" in e for e in validate_metrics(bad))


def test_validate_events_and_bench_lines():
    assert validate_events_line({"event": "x", "t": 0.1, "n": 3}) == []
    assert validate_events_line({"t": 0.1}) != []
    assert validate_events_line({"event": "x", "t": 0.1,
                                 "bad": [1, 2]}) != []
    assert validate_bench_line(json.loads(
        metric_line("accuracy", pct=1.5, unit="Gb/h"))) == []
    assert validate_bench_line({"value": 2}) != []
    with pytest.raises(ValueError):
        metric_line("m", bad=[1, 2, 3])
    with pytest.raises(ValueError):
        metric_line("")


def test_check_file_dispatches_on_content(tmp_path):
    # bench-style metric lines in a .json file (BENCH_*.json shape)
    bench = tmp_path / "bench.json"
    bench.write_text(metric_line("a", value=1) + "\n"
                     + "# comment\n"
                     + metric_line("b", value=2) + "\n")
    assert check_file(str(bench)) == []
    bad = tmp_path / "bad.json"
    bad.write_text('{"value": 1}\n{"metric": "x", "v": [1]}\n')
    errs = check_file(str(bad))
    assert any(e.startswith("line 1:") for e in errs)
    assert any(e.startswith("line 2:") and "not scalar" in e
               for e in errs)
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert check_file(str(empty)) != []
    assert check_file(str(tmp_path / "missing.json")) != []


def test_metrics_check_tool_cli(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p)
    reg.counter("c").inc()
    reg.write()
    res = subprocess.run([sys.executable, METRICS_CHECK, p],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert "OK" in res.stdout
    bad = tmp_path / "bad.json"
    bad.write_text('{"schema": "wrong", "meta": {}, "counters": {}, '
                   '"gauges": {}, "histograms": {}, "timers": {}}')
    res = subprocess.run([sys.executable, METRICS_CHECK, p, str(bad)],
                         capture_output=True, text=True)
    assert res.returncode == 1
    assert "schema" in res.stderr


# ---------------------------------------------------------------------------
# StageTimer.as_dict (the registry feed) and vlog env fallback
# ---------------------------------------------------------------------------

def test_stage_timer_as_dict_matches_report_facts():
    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("a"):
        pass
    t.add_units("a", 2000)
    d = t.as_dict(2000)
    assert d["stages"]["a"]["calls"] == 2
    assert d["stages"]["a"]["units"] == 2000
    assert d["total_seconds"] >= d["stages"]["a"]["seconds"] >= 0
    assert d["total_units"] == 2000
    assert d["units_per_hour"] > 0
    # attaches cleanly to the schema
    reg = MetricsRegistry()
    reg.set_timer("s", d)
    assert validate_metrics(reg.as_dict()) == []


def test_vlog_env_var_fallback(monkeypatch):
    from quorum_tpu.utils import vlog as vlog_mod
    old = vlog_mod.verbose
    try:
        monkeypatch.setenv("QUORUM_TPU_VERBOSE", "1")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is True
        monkeypatch.setenv("QUORUM_TPU_VERBOSE", "0")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is False
        monkeypatch.delenv("QUORUM_TPU_VERBOSE")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is False
    finally:
        importlib.reload(vlog_mod)
        vlog_mod.verbose = old


# ---------------------------------------------------------------------------
# ISSUE 2: heartbeat clock semantics, explicit events_path, live
# exposition (Prometheus text, textfile atomicity, HTTP endpoint),
# span tracer, and the --prom lint mode
# ---------------------------------------------------------------------------

def test_heartbeat_rate_limit_mocked_clock(tmp_path, monkeypatch):
    """Satellite: with the clock mocked, exactly one event lands per
    interval regardless of how many heartbeat() calls arrive."""
    from quorum_tpu.telemetry import registry as reg_mod

    now = [100.0]
    monkeypatch.setattr(reg_mod.time, "perf_counter", lambda: now[0])
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=5.0)
    for i in range(20):  # t = 100.0 .. 101.9: one interval
        now[0] = 100.0 + i * 0.1
        reg.heartbeat(reads=i)
    now[0] = 105.5  # second interval opens
    for i in range(20):
        reg.heartbeat(reads=100 + i)
    reg.write()
    ev = p[:-5] + ".events.jsonl"
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    assert len(lines) == 2  # at most one per interval
    assert [x["reads"] for x in lines] == [0, 100]
    # every heartbeat record carries a monotonic elapsed_s
    assert [x["elapsed_s"] for x in lines] == [0.0, 5.5]


def test_explicit_events_path_without_final_json(tmp_path):
    """Satellite: an explicit events_path streams heartbeats even when
    no final-JSON path is configured (they used to be dropped)."""
    ev = str(tmp_path / "beats.jsonl")
    reg = registry_for(None, events_path=ev)
    assert reg.enabled
    reg.heartbeat(reads=1)
    reg.heartbeat(reads=2)  # heartbeat_s=0 + explicit path: unlimited
    assert reg.write() is None  # no final JSON...
    assert not any(f.suffix == ".json" for f in tmp_path.iterdir())
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    assert [x["reads"] for x in lines] == [1, 2]
    assert all("elapsed_s" in x for x in lines)
    assert check_file(ev) == []


def test_straggler_event_after_write_never_truncates(tmp_path):
    """ISSUE 11 hardening: write() seals the event sink. An event
    landing after it (an alert ticker's last transition, a late
    exporter) used to re-open the path with 'wb' — truncating the
    whole stream it meant to append to."""
    ev = str(tmp_path / "run.events.jsonl")
    reg = registry_for(None, events_path=ev)
    reg.event("progress", n=1)
    reg.event("progress", n=2)
    reg.write()
    reg.event("alert", rule="late", state="firing")  # straggler
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    assert [x["n"] for x in lines] == [1, 2]  # stream intact
    # and an event-less run never grows a post-hoc events file
    ev2 = str(tmp_path / "empty.events.jsonl")
    reg2 = registry_for(None, events_path=ev2)
    reg2.write()
    reg2.event("late", x=1)
    assert not os.path.exists(ev2)


def test_prometheus_render_and_lint():
    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="stage_x")
    reg.counter("reads").inc(7)
    reg.gauge("fill").set(0.25)
    reg.histogram("subs").observe(0, 3)
    reg.histogram("subs").observe(2, 2)
    text = export.prometheus_text({"stage_x": reg.as_dict()},
                                  {"stage_x": 1.5})
    assert export.lint_prometheus_text(text) == []
    assert 'quorum_tpu_reads_total{stage="stage_x"} 7' in text
    assert 'quorum_tpu_fill{stage="stage_x"} 0.25' in text
    # exact counts -> cumulative le buckets
    assert 'quorum_tpu_subs_bucket{stage="stage_x",le="0"} 3' in text
    assert 'quorum_tpu_subs_bucket{stage="stage_x",le="2"} 5' in text
    assert 'quorum_tpu_subs_bucket{stage="stage_x",le="+Inf"} 5' in text
    assert 'quorum_tpu_subs_sum{stage="stage_x"} 4' in text
    assert 'quorum_tpu_elapsed_seconds{stage="stage_x"} 1.5' in text
    # TYPE headers appear exactly once per metric
    assert text.count("# TYPE quorum_tpu_subs histogram") == 1


def test_prometheus_lint_catches_malformations():
    from quorum_tpu.telemetry.export import lint_prometheus_text

    assert lint_prometheus_text("") != []  # no samples
    assert any("not a valid sample" in e for e in
               lint_prometheus_text("this is not prometheus\n"))
    assert any("missing _total" in e for e in lint_prometheus_text(
        "# TYPE foo counter\nfoo 3\n"))
    bad_buckets = ("# TYPE h histogram\n"
                   'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n')
    assert any("not cumulative" in e for e in
               lint_prometheus_text(bad_buckets))


def test_textfile_atomic_under_concurrent_reads(tmp_path):
    """Satellite: a reader at the rename target never observes a
    half-written textfile, no matter how the writes interleave."""
    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="atomic")
    for i in range(200):  # a body big enough to make torn writes real
        reg.counter(f"c{i:03d}").inc(i)
    export.register_live(reg)
    path = str(tmp_path / "metrics.prom")
    export.write_textfile(path)
    stop = threading.Event()
    torn: list[str] = []

    def reader():
        while not stop.is_set():
            text = open(path).read()
            errs = export.lint_prometheus_text(text)
            if errs:
                torn.extend(errs)
                return

    t = threading.Thread(target=reader)
    t.start()
    try:
        for _ in range(300):
            reg.counter("c000").inc()
            export.write_textfile(path)
    finally:
        stop.set()
        t.join()
    assert torn == []
    assert not os.path.exists(path + ".tmp")  # tmp never lingers


def test_attach_textfile_rate_limit_and_final(tmp_path, monkeypatch):
    """attach_textfile refreshes at most once per period on heartbeats
    but always on the final write()."""
    from quorum_tpu.telemetry import export, registry as reg_mod

    now = [50.0]
    monkeypatch.setattr(reg_mod.time, "perf_counter", lambda: now[0])
    monkeypatch.setattr(export.time, "perf_counter", lambda: now[0])
    path = str(tmp_path / "m.prom")
    writes = []
    real_write = export.write_textfile
    monkeypatch.setattr(export, "write_textfile",
                        lambda p, text=None: writes.append(p)
                        or real_write(p, text))
    reg = registry_for(None, force=True)
    reg.set_meta(stage="tf")
    reg.counter("c").inc()
    export.attach_textfile(reg, path, period=10.0)
    for i in range(5):
        now[0] = 50.0 + i  # all within one period
        reg.heartbeat(reads=i)
    assert len(writes) == 1
    reg.write()  # final=True bypasses the period
    assert len(writes) == 2
    assert export.lint_prometheus_text(open(path).read()) == []


def test_live_http_endpoint_serves_metrics_and_healthz():
    import urllib.request

    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="live")
    reg.counter("scraped").inc(3)
    export.register_live(reg)
    srv = export.serve(0)  # ephemeral port
    try:
        assert export.current_server() is srv
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics") as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            text = r.read().decode()
        assert export.lint_prometheus_text(text) == []
        assert 'quorum_tpu_scraped_total{stage="live"} 3' in text
        with urllib.request.urlopen(base + "/healthz") as r:
            hz = json.loads(r.read().decode())
        assert hz["status"] == "ok"
        assert hz["registries"] >= 1
        try:
            urllib.request.urlopen(base + "/nope")
            assert False, "404 expected"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.close()


def test_span_tracer_jsonl_and_chrome_trace(tmp_path):
    from quorum_tpu.telemetry import (NULL_TRACER, tracer_for,
                                      validate_chrome_trace,
                                      validate_span_line)

    assert tracer_for(None) is NULL_TRACER
    assert not NULL_TRACER.enabled
    with NULL_TRACER.span("x"), NULL_TRACER.step("y", 0):
        pass

    p = str(tmp_path / "spans.jsonl")
    tr = tracer_for(p)
    assert tr.enabled
    with tr.span("outer", reads=128):
        with tr.span("inner"):
            pass
        with tr.step("device", 0, reads=128):
            pass

    def other_thread():
        with tr.span("threaded"):
            pass

    t = threading.Thread(target=other_thread)
    t.start()
    t.join()
    tr.close()
    tr.close()  # idempotent

    lines = [json.loads(x) for x in open(p) if x.strip()]
    assert all(validate_span_line(o) == [] for o in lines)
    by_name = {o["span"]: o for o in lines}
    assert by_name["inner"]["parent"] == by_name["outer"]["id"]
    assert by_name["device"]["parent"] == by_name["outer"]["id"]
    assert by_name["device"]["step"] == 0
    assert by_name["outer"]["parent"] is None
    assert by_name["outer"]["reads"] == 128
    # the other thread starts its own lineage on its own tid
    assert by_name["threaded"]["parent"] is None
    assert by_name["threaded"]["tid"] != by_name["outer"]["tid"]
    # children close before the parent: JSONL is close-ordered
    assert [o["span"] for o in lines].index("inner") \
        < [o["span"] for o in lines].index("outer")
    assert check_file(p) == []

    chrome = p[:-6] + ".trace.json"  # .jsonl -> .trace.json
    doc = json.load(open(chrome))
    assert validate_chrome_trace(doc) == []
    assert {e["name"] for e in doc["traceEvents"]} \
        == {"outer", "inner", "device", "threaded"}
    ev = {e["name"]: e for e in doc["traceEvents"]}
    assert ev["outer"]["args"]["reads"] == 128
    assert ev["inner"]["ts"] >= ev["outer"]["ts"]
    assert check_file(chrome) == []


def test_metrics_check_prom_mode(tmp_path):
    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="s")
    reg.counter("c").inc()
    good = tmp_path / "good.prom"
    good.write_text(export.prometheus_text({"s": reg.as_dict()}))
    bad = tmp_path / "bad.prom"
    bad.write_text("definitely not prometheus\n")
    res = subprocess.run([sys.executable, METRICS_CHECK, "--prom",
                          str(good)], capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    res = subprocess.run([sys.executable, METRICS_CHECK, "--prom",
                          str(good), str(bad)],
                         capture_output=True, text=True)
    assert res.returncode == 1
    assert "sample" in res.stderr


def test_lint_reports_non_numeric_le():
    from quorum_tpu.telemetry.export import lint_prometheus_text

    errs = lint_prometheus_text('# TYPE h histogram\n'
                                'h_bucket{le="abc"} 1\n')
    assert any("le=" in e for e in errs)  # reported, not a crash


def test_span_after_close_does_not_truncate_jsonl(tmp_path):
    """A straggler thread's span closing after tracer.close() must
    not reopen (and truncate) the streamed JSONL."""
    from quorum_tpu.telemetry import tracer_for

    p = str(tmp_path / "s.jsonl")
    tr = tracer_for(p)
    with tr.span("kept"):
        pass
    tr.close()
    with tr.span("late"):  # e.g. a render-pool task outliving the run
        pass
    lines = [json.loads(x) for x in open(p) if x.strip()]
    assert [o["span"] for o in lines] == ["kept"]


def test_http_server_close_is_idempotent():
    from quorum_tpu.telemetry import export

    srv = export.serve(0)
    srv.close()
    srv.close()  # second close: no-op, no error


def test_finished_registry_series_survive_in_live_rendering(tmp_path):
    """A stage registry freed after its run must keep its FINAL series
    in the shared exposition (driver endpoint/textfile carries stage1
    after stage1 returns)."""
    import gc

    from quorum_tpu.telemetry import export

    reg = registry_for(str(tmp_path / "s1.json"))
    reg.set_meta(stage="finished_stage")
    reg.counter("reads").inc(42)
    reg.write()
    del reg
    gc.collect()
    text = export.render_live()
    assert 'quorum_tpu_reads_total{stage="finished_stage"} 42' in text
    # a NEW live registry with the same label supersedes the snapshot
    reg2 = registry_for(None, force=True)
    reg2.set_meta(stage="finished_stage")
    reg2.counter("reads").inc(7)
    text = export.render_live()
    assert 'quorum_tpu_reads_total{stage="finished_stage"} 7' in text
    assert '} 42' not in text


def test_stage_cli_error_still_writes_metrics(tmp_path, monkeypatch):
    """A failed stage run (hash-full RuntimeError) must land its
    metrics document with status=error, not just stop reporting."""
    from quorum_tpu.cli import create_database as cdb_cli

    def boom(*a, **kw):
        raise RuntimeError("Hash is full")

    monkeypatch.setattr(cdb_cli, "create_database_main", boom)
    reads = tmp_path / "r.fastq"
    reads.write_text("@r\nACGT\n+\nIIII\n")
    m = str(tmp_path / "m.json")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", str(tmp_path / "db"), "--metrics", m,
                       str(reads)])
    assert rc == 1
    doc = json.load(open(m))
    assert doc["meta"]["status"] == "error"
    assert validate_metrics(doc) == []


def test_serve_resets_retained_finals():
    """A new endpoint must not report a previous job's counters."""
    from quorum_tpu.telemetry import export

    reg = registry_for(None, force=True)
    reg.set_meta(stage="job_a")
    reg.counter("stale").inc(9)
    reg.write()
    del reg
    assert 'stage="job_a"' in export.render_live()
    srv = export.serve(0)
    try:
        assert 'stage="job_a"' not in export.render_live()
    finally:
        srv.close()


def test_metrics_live_flag_forces_stage_registry(tmp_path):
    """--metrics-live (forwarded by the driver with --metrics-port)
    gives a stage a real registry with no output path, so the
    parent-owned endpoint sees its counters."""
    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.telemetry import export

    export.reset_exposition()
    golden = os.path.join(HERE, "golden", "reads.fastq")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", str(tmp_path / "db.jf"), "--metrics-live",
                       golden])
    assert rc == 0
    text = export.render_live()
    assert 'quorum_tpu_reads_total{stage="create_database"}' in text
    # no metrics file was written (no --metrics path)
    assert not (tmp_path / "db.jf.json").exists()
    assert list(tmp_path.glob("*.json")) == []


def test_attach_textfile_new_target_drops_stale_finals(tmp_path):
    """Attaching a textfile path this process never wrote marks a new
    job: a previous job's retained finals must not leak into it.
    Re-attaching a known path (driver stages sharing one file) keeps
    them."""
    from quorum_tpu.telemetry import export

    export.reset_exposition()
    old = registry_for(None, force=True)
    old.set_meta(stage="old_job")
    old.counter("stale").inc(5)
    old.write()
    del old
    assert 'stage="old_job"' in export.render_live()

    new = registry_for(None, force=True)
    new.set_meta(stage="new_job")
    export.attach_textfile(new, str(tmp_path / "b.prom"))
    assert 'stage="old_job"' not in export.render_live()
    # same-path re-attach retains finals written since
    new.counter("c").inc()
    new.write()
    del new
    later = registry_for(None, force=True)
    later.set_meta(stage="later_stage")
    export.attach_textfile(later, str(tmp_path / "b.prom"))
    assert 'stage="new_job"' in export.render_live()


def test_busy_metrics_port_still_lands_error_document(tmp_path):
    """A busy --metrics-port raises before the pipeline starts; the
    run must still write its metrics document with status=error."""
    import socket

    from quorum_tpu.cli import create_database as cdb_cli

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    s.listen(1)
    port = s.getsockname()[1]
    try:
        reads = tmp_path / "r.fastq"
        reads.write_text("@r\nACGT\n+\nIIII\n")
        m = str(tmp_path / "m.json")
        with pytest.raises(OSError):
            cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7",
                          "-q", "38", "-o", str(tmp_path / "db"),
                          "--metrics", m, "--metrics-port", str(port),
                          str(reads)])
        doc = json.load(open(m))
        assert doc["meta"]["status"] == "error"
    finally:
        s.close()


# ---------------------------------------------------------------------------
# ISSUE 10: device-truth telemetry — metrics_check schemas, the
# devtrace parser/join, and the push transport
# ---------------------------------------------------------------------------

def _mc():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import metrics_check
    return metrics_check


def _doc10(meta=None, counters=None, gauges=None, histograms=None,
           **extra):
    doc = {"schema": SCHEMA_VERSION, "meta": meta or {},
           "counters": counters or {}, "gauges": gauges or {},
           "histograms": histograms or {}, "timers": {}}
    doc.update(extra)
    return doc


def test_metrics_check_devtrace_and_push_names():
    """meta.profile demands the devtrace surface, meta.metrics_push_url
    the pusher's — both recorded at value 0 even when nothing fired,
    so a missing NAME is a regression, not an idle run."""
    mc = _mc()
    errs = mc._check_devtrace_names(_doc10(meta={"profile": "/p"}))
    # 4 counters + 1 gauge + 1 histogram + meta.devtrace_source
    assert len(errs) == 7
    full = _doc10(
        meta={"profile": "/p", "devtrace_source": "none"},
        counters={n: 0 for n in mc.DEVTRACE_COUNTERS},
        gauges={"devtrace_steps": 0},
        histograms={"device_kernel_us":
                    {"count": 0, "sum": 0, "counts": {}}})
    assert mc._check_devtrace_names(full) == []
    # an unprofiled document is not held to it
    assert mc._check_devtrace_names(_doc10()) == []

    errs = mc._check_push_names(
        _doc10(meta={"metrics_push_url": "http://x"}))
    assert len(errs) == 3  # 2 counters + meta.metrics_push_host
    ok = _doc10(meta={"metrics_push_url": "http://x",
                      "metrics_push_host": "h:1"},
                counters={n: 0 for n in mc.PUSH_COUNTERS})
    assert mc._check_push_names(ok) == []
    assert mc._check_push_names(_doc10()) == []


def test_metrics_check_fleet_doc(tmp_path):
    """A push_receiver fleet document must carry per-host shards keyed
    exactly by meta.fleet_hosts — a mismatch means a host's final push
    was silently dropped from the aggregate."""
    mc = _mc()
    shard = _doc10()
    good = _doc10(meta={"fleet": True, "fleet_hosts": ["a:1", "b:2"]},
                  hosts={"a:1": shard, "b:2": shard})
    assert mc._check_fleet_doc(good) == []
    # hosts section missing entirely
    assert mc._check_fleet_doc(
        _doc10(meta={"fleet": True, "fleet_hosts": ["a:1"]})) != []
    # key set drifted from the manifest
    bad = _doc10(meta={"fleet": True, "fleet_hosts": ["a:1", "b:2"]},
                 hosts={"a:1": shard})
    assert any("does not match" in e for e in mc._check_fleet_doc(bad))
    # non-fleet documents are not held to it
    assert mc._check_fleet_doc(_doc10()) == []


def test_validate_request_event_contract():
    """`request` lifecycle events are held to the richer contract:
    trace id, HTTP status, lane, every phase duration >= 0."""
    ev = {"event": "request", "t": 0.1, "request_id": "rid-1",
          "status": 200, "lane": "interactive", "admission_us": 10,
          "queue_us": 5, "device_us": 100, "hedge_us": 0,
          "render_us": 2, "total_us": 120}
    assert validate_events_line(ev) == []
    assert any("request_id" in e for e in
               validate_events_line({**ev, "request_id": ""}))
    assert any("status" in e for e in
               validate_events_line({**ev, "status": "200"}))
    assert any("lane" in e for e in
               validate_events_line({k: v for k, v in ev.items()
                                     if k != "lane"}))
    assert any("device_us" in e for e in
               validate_events_line({**ev, "device_us": -1}))
    assert any("total_us" in e for e in
               validate_events_line({k: v for k, v in ev.items()
                                     if k != "total_us"}))
    # non-request events keep the old loose contract
    assert validate_events_line({"event": "hash_grow", "t": 1.0}) == []


def _chrome_trace(path, events):
    import gzip
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with gzip.open(path, "wb") as f:
        f.write(json.dumps({"traceEvents": events}).encode())


def test_devtrace_chrome_join_idle_unattributed(tmp_path):
    """The midpoint join against step windows: overlapping kernels
    union for idle, out-of-window kernels land in unattributed,
    device-plane events count without an hlo_op arg, runtime
    bookkeeping and the host span twin are excluded."""
    from quorum_tpu.telemetry import devtrace

    prof = str(tmp_path / "prof")
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        # two stage2 step windows
        {"ph": "X", "name": "stage2_device", "ts": 1000.0,
         "dur": 1000.0, "args": {"step_num": 0}},
        {"ph": "X", "name": "stage2_device", "ts": 3000.0,
         "dur": 500.0, "args": {"step_num": 1}},
        # window 0: two overlapping hlo kernels + one device-plane
        # event without args -> busy union [1100, 1400] = 300
        {"ph": "X", "name": "fusion.1", "ts": 1100.0, "dur": 200.0,
         "args": {"hlo_op": "fusion.1"}},
        {"ph": "X", "name": "fusion.2", "ts": 1200.0, "dur": 200.0,
         "args": {"hlo_op": "fusion.2"}},
        {"ph": "X", "name": "while.1", "pid": 7, "ts": 1150.0,
         "dur": 100.0},
        # runtime bookkeeping on the device plane: excluded
        {"ph": "X", "name": "ThreadpoolListener region", "pid": 7,
         "ts": 1300.0, "dur": 500.0},
        # window 1: one kernel
        {"ph": "X", "name": "sort.9", "ts": 3100.0, "dur": 100.0,
         "args": {"hlo_op": "sort.9"}},
        # no window covers this midpoint
        {"ph": "X", "name": "stray", "ts": 5000.0, "dur": 50.0,
         "args": {"hlo_op": "stray"}},
    ]
    _chrome_trace(os.path.join(prof, "plugins", "profile", "run1",
                               "host.trace.json.gz"), events)
    # the HOST span twin observability() drops into the same dir
    # must be ignored (it is not even valid JSON here)
    with open(os.path.join(prof, "spans.trace.json"), "w") as f:
        f.write("not json")
    s = devtrace.summarize_profile(prof)
    assert s.source == "trace_json" and len(s.files) == 1
    assert len(s.steps) == 2
    w0, w1 = sorted(s.steps, key=lambda w: w.ts_us)
    assert w0.n_kernels == 3 and w0.kernel_us == 500.0
    assert w0.idle_us == 1000.0 - 300.0
    assert w1.kernel_us == 100.0 and w1.idle_us == 400.0
    assert s.unattributed_kernel_us == 50.0
    assert s.total_kernel_us == 650.0
    assert s.stage_kernel_us() == {"stage2_device": 600.0}
    top = dict(s.top_kernels(2))
    assert top == {"fusion.1": 200.0, "fusion.2": 200.0}


def _pb_varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _pb(fn, wt, payload):
    key = _pb_varint((fn << 3) | wt)
    if wt == 0:
        return key + _pb_varint(payload)
    return key + _pb_varint(len(payload)) + payload


def test_devtrace_xplane_fallback(tmp_path):
    """The no-dependency XPlane wire reader recovers steps and
    kernels from a hand-encoded xplane.pb, and is skipped for
    directories whose Chrome twin already parsed."""
    from quorum_tpu.telemetry import devtrace

    def meta_entry(mid, name):  # {event,stat}_metadata map entry
        return _pb(1, 0, mid) + _pb(
            2, 2, _pb(1, 0, mid) + _pb(2, 2, name.encode()))

    def stat(mid, val):
        return _pb(4, 2, _pb(1, 0, mid) + _pb(3, 0, val))

    def event(mid, off_ps, dur_ps, stats=b""):
        return _pb(4, 2, _pb(1, 0, mid) + _pb(2, 0, off_ps)
                   + _pb(3, 0, dur_ps) + stats)

    # metadata: event 1 = step annotation, 2 = kernel;
    # stat 1 = step_num, 2 = hlo_op
    plane = (_pb(2, 2, b"/host:CPU")
             + _pb(4, 2, meta_entry(1, "stage1_insert"))
             + _pb(4, 2, meta_entry(2, "fusion.7"))
             + _pb(5, 2, meta_entry(1, "step_num"))
             + _pb(5, 2, meta_entry(2, "hlo_op"))
             + _pb(3, 2,               # one line at t=1us
                   _pb(3, 0, 1000)
                   # step window [1, 1001] us, step_num=4
                   + event(1, 0, 1_000_000_000, stat(1, 4))
                   # kernel at +100us, 50us, hlo_op stat
                   + event(2, 100_000_000, 50_000_000, stat(2, 0))))
    xp = str(tmp_path / "prof")
    os.makedirs(xp)
    with open(os.path.join(xp, "host.xplane.pb"), "wb") as f:
        f.write(_pb(1, 2, plane))
    s = devtrace.summarize_profile(xp)
    assert s.source == "xplane"
    assert len(s.steps) == 1
    w = s.steps[0]
    assert w.name == "stage1_insert" and w.step == 4
    assert w.ts_us == 1.0 and w.dur_us == 1000.0
    assert w.kernel_us == 50.0 and w.n_kernels == 1
    assert s.kernels == {"fusion.7": 50.0}
    # a Chrome twin in the same directory wins; the pb is skipped
    _chrome_trace(os.path.join(xp, "host.trace.json.gz"),
                  [{"ph": "X", "name": "other", "ts": 0.0,
                    "dur": 10.0, "args": {"hlo_op": "other"}}])
    s2 = devtrace.summarize_profile(xp)
    assert s2.source == "trace_json" and len(s2.files) == 1
    assert s2.kernels == {"other": 10.0}


def test_record_profile_metrics_zero_surface(tmp_path):
    """An empty --profile directory still lands the full devtrace
    name surface (zeros) — what metrics_check requires — and the NULL
    registry records nothing."""
    from quorum_tpu.telemetry import devtrace

    assert devtrace.record_profile_metrics(NULL, str(tmp_path)) \
        is False
    reg = registry_for(str(tmp_path / "m.json"))
    assert devtrace.record_profile_metrics(reg, str(tmp_path)) is True
    doc = reg.as_dict()
    for n in ("device_kernel_us_total", "device_step_us_total",
              "device_idle_us_total",
              "device_kernel_unattributed_us_total"):
        assert doc["counters"][n] == 0
    assert doc["gauges"]["devtrace_steps"] == 0
    assert doc["histograms"]["device_kernel_us"]["count"] == 0
    assert doc["meta"]["devtrace_source"] == "none"


class _FakeResp:
    def __init__(self, status=200):
        self.status = status

    def read(self):
        return b"ok"

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_metrics_pusher_terminal_flush_retries(tmp_path):
    """close() survives a receiver hiccup: failed attempts count on
    metrics_push_failures_total, the bounded retry lands both the
    exposition text and the final JSON document, metrics_pushed=True."""
    from quorum_tpu.telemetry.push import MetricsPusher

    calls, sleeps = [], []

    def urlopen(req, timeout=None):
        calls.append((req.full_url, req.data,
                      dict(req.header_items())))
        if len(calls) == 1:
            raise OSError("connection refused")
        return _FakeResp()

    reg = MetricsRegistry()
    pusher = MetricsPusher(reg, "http://127.0.0.1:1/push/",
                           period_s=9999, host_id="h:1",
                           _urlopen=urlopen, _sleep=sleeps.append)
    ok = pusher.close(final_doc={"schema": SCHEMA_VERSION, "meta": {},
                                 "counters": {}, "gauges": {},
                                 "histograms": {}, "timers": {}})
    assert ok is True
    assert reg.counter("metrics_push_failures_total").value == 1
    assert reg.counter("metrics_push_total").value == 1
    assert reg.meta["metrics_pushed"] is True
    assert sleeps == [0.25]
    # attempt 2 = text to the base url, then the final doc to /final
    assert calls[1][0] == "http://127.0.0.1:1/push"
    assert calls[2][0] == "http://127.0.0.1:1/push/final"
    assert json.loads(calls[2][1])["schema"] == SCHEMA_VERSION
    assert calls[1][2].get("X-quorum-host") == "h:1"


def test_metrics_pusher_gives_up_but_never_raises():
    """A permanently-dead receiver costs counters and
    metrics_pushed=False — never an exception."""
    from quorum_tpu.telemetry import push as push_mod

    sleeps = []

    def urlopen(req, timeout=None):
        raise OSError("down")

    reg = MetricsRegistry()
    pusher = push_mod.MetricsPusher(
        reg, "http://127.0.0.1:1", period_s=9999,
        _urlopen=urlopen, _sleep=sleeps.append)
    assert pusher.close(final_doc={"x": 1}) is False
    assert reg.meta["metrics_pushed"] is False
    assert reg.counter("metrics_push_failures_total").value \
        == push_mod.FINAL_ATTEMPTS
    assert len(sleeps) == push_mod.FINAL_ATTEMPTS - 1
