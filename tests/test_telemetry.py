"""Telemetry subsystem: registry semantics, schema validation, the
metrics_check tool's dispatch, and the vlog env-var fallback."""

import importlib
import json
import os
import subprocess
import sys
import threading

import pytest

from quorum_tpu.telemetry import (NULL, MetricsRegistry, SCHEMA_VERSION,
                                  check_file, metric_line, registry_for,
                                  validate_bench_line,
                                  validate_events_line, validate_metrics)
from quorum_tpu.utils.profiling import StageTimer

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
METRICS_CHECK = os.path.join(REPO, "tools", "metrics_check.py")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_null_registry_is_free_and_inert(tmp_path):
    reg = registry_for(None)
    assert reg is NULL
    assert not reg.enabled
    # every surface is a no-op, nothing raises, nothing is written
    reg.counter("c").inc(5)
    reg.gauge("g").set(3)
    reg.gauge("g").set_max(9)
    reg.gauge("g").add(1.0)
    reg.histogram("h").observe(2)
    reg.set_meta(a=1)
    reg.set_timer("t", {})
    reg.event("e", x=1)
    reg.heartbeat(bases=10)
    assert reg.write(str(tmp_path / "never.json")) is None
    assert not (tmp_path / "never.json").exists()
    assert validate_metrics(reg.as_dict()) == []


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter("reads").inc()
    reg.counter("reads").inc(4)
    reg.gauge("fill").set(0.25)
    reg.gauge("depth").set_max(2)
    reg.gauge("depth").set_max(1)  # lower: ignored
    reg.gauge("stall").add(0.5)
    reg.gauge("stall").add(0.25)
    reg.histogram("subs").observe(0, 10)
    reg.histogram("subs").observe(3, 2)
    doc = reg.as_dict()
    assert doc["schema"] == SCHEMA_VERSION
    assert doc["counters"]["reads"] == 5
    assert doc["gauges"]["fill"] == 0.25
    assert doc["gauges"]["depth"] == 2
    assert doc["gauges"]["stall"] == 0.75
    h = doc["histograms"]["subs"]
    assert h == {"count": 12, "sum": 6, "counts": {"0": 10, "3": 2}}
    assert validate_metrics(doc) == []


def test_registry_threaded_counts_are_exact():
    reg = MetricsRegistry()
    c = reg.counter("n")

    def work():
        for _ in range(1000):
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 8000


def test_registry_write_and_events(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=0.001)
    assert reg.enabled
    reg.set_meta(stage="test", k=13)
    reg.counter("reads").inc(7)
    reg.event("hash_grow", rows_before=8, rows_after=16)
    reg.heartbeat(reads=7, bases=1000)
    t = StageTimer()
    with t.stage("insert"):
        pass
    t.add_units("insert", 1000)
    reg.set_timer("stage1", t.as_dict(1000))
    assert reg.write() == p
    doc = json.load(open(p))
    assert validate_metrics(doc) == []
    assert doc["meta"]["stage"] == "test"
    assert doc["counters"]["reads"] == 7
    assert doc["timers"]["stage1"]["stages"]["insert"]["units"] == 1000
    # the events stream sits next to the json and validates too
    ev = p[:-5] + ".events.jsonl"
    assert os.path.exists(ev)
    assert check_file(ev) == []
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    kinds = [x["event"] for x in lines]
    assert "hash_grow" in kinds and "heartbeat" in kinds
    hb = next(x for x in lines if x["event"] == "heartbeat")
    assert "gb_per_h" in hb  # derived from the bases field


def test_heartbeat_rate_limited(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=1000.0)
    for i in range(50):
        reg.heartbeat(reads=i)
    reg.write()
    ev = p[:-5] + ".events.jsonl"
    lines = [x for x in open(ev) if x.strip()]
    assert len(lines) == 1  # only the first beat within the period


def test_no_events_without_interval(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p)  # heartbeat_s = 0
    reg.heartbeat(reads=1)
    reg.event("e")
    reg.write()
    assert not os.path.exists(p[:-5] + ".events.jsonl")


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

def test_validate_metrics_rejects_malformed():
    assert validate_metrics([]) != []
    assert validate_metrics({"schema": "nope"}) != []
    base = MetricsRegistry().as_dict()
    bad = dict(base, counters={"c": -1})
    assert any("non-negative" in e for e in validate_metrics(bad))
    bad = dict(base, gauges={"g": "high"})
    assert any("not a number" in e for e in validate_metrics(bad))
    bad = dict(base, histograms={"h": {"count": 3, "sum": 1,
                                       "counts": {"0": 1}}})
    assert any("counts sum" in e for e in validate_metrics(bad))
    bad = dict(base, extra={})
    assert any("unknown top-level" in e for e in validate_metrics(bad))


def test_validate_events_and_bench_lines():
    assert validate_events_line({"event": "x", "t": 0.1, "n": 3}) == []
    assert validate_events_line({"t": 0.1}) != []
    assert validate_events_line({"event": "x", "t": 0.1,
                                 "bad": [1, 2]}) != []
    assert validate_bench_line(json.loads(
        metric_line("accuracy", pct=1.5, unit="Gb/h"))) == []
    assert validate_bench_line({"value": 2}) != []
    with pytest.raises(ValueError):
        metric_line("m", bad=[1, 2, 3])
    with pytest.raises(ValueError):
        metric_line("")


def test_check_file_dispatches_on_content(tmp_path):
    # bench-style metric lines in a .json file (BENCH_*.json shape)
    bench = tmp_path / "bench.json"
    bench.write_text(metric_line("a", value=1) + "\n"
                     + "# comment\n"
                     + metric_line("b", value=2) + "\n")
    assert check_file(str(bench)) == []
    bad = tmp_path / "bad.json"
    bad.write_text('{"value": 1}\n{"metric": "x", "v": [1]}\n')
    errs = check_file(str(bad))
    assert any(e.startswith("line 1:") for e in errs)
    assert any(e.startswith("line 2:") and "not scalar" in e
               for e in errs)
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert check_file(str(empty)) != []
    assert check_file(str(tmp_path / "missing.json")) != []


def test_metrics_check_tool_cli(tmp_path):
    p = str(tmp_path / "m.json")
    reg = registry_for(p)
    reg.counter("c").inc()
    reg.write()
    res = subprocess.run([sys.executable, METRICS_CHECK, p],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert "OK" in res.stdout
    bad = tmp_path / "bad.json"
    bad.write_text('{"schema": "wrong", "meta": {}, "counters": {}, '
                   '"gauges": {}, "histograms": {}, "timers": {}}')
    res = subprocess.run([sys.executable, METRICS_CHECK, p, str(bad)],
                         capture_output=True, text=True)
    assert res.returncode == 1
    assert "schema" in res.stderr


# ---------------------------------------------------------------------------
# StageTimer.as_dict (the registry feed) and vlog env fallback
# ---------------------------------------------------------------------------

def test_stage_timer_as_dict_matches_report_facts():
    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("a"):
        pass
    t.add_units("a", 2000)
    d = t.as_dict(2000)
    assert d["stages"]["a"]["calls"] == 2
    assert d["stages"]["a"]["units"] == 2000
    assert d["total_seconds"] >= d["stages"]["a"]["seconds"] >= 0
    assert d["total_units"] == 2000
    assert d["units_per_hour"] > 0
    # attaches cleanly to the schema
    reg = MetricsRegistry()
    reg.set_timer("s", d)
    assert validate_metrics(reg.as_dict()) == []


def test_vlog_env_var_fallback(monkeypatch):
    from quorum_tpu.utils import vlog as vlog_mod
    old = vlog_mod.verbose
    try:
        monkeypatch.setenv("QUORUM_TPU_VERBOSE", "1")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is True
        monkeypatch.setenv("QUORUM_TPU_VERBOSE", "0")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is False
        monkeypatch.delenv("QUORUM_TPU_VERBOSE")
        importlib.reload(vlog_mod)
        assert vlog_mod.verbose is False
    finally:
        importlib.reload(vlog_mod)
        vlog_mod.verbose = old


# ---------------------------------------------------------------------------
# ISSUE 2: heartbeat clock semantics, explicit events_path, live
# exposition (Prometheus text, textfile atomicity, HTTP endpoint),
# span tracer, and the --prom lint mode
# ---------------------------------------------------------------------------

def test_heartbeat_rate_limit_mocked_clock(tmp_path, monkeypatch):
    """Satellite: with the clock mocked, exactly one event lands per
    interval regardless of how many heartbeat() calls arrive."""
    from quorum_tpu.telemetry import registry as reg_mod

    now = [100.0]
    monkeypatch.setattr(reg_mod.time, "perf_counter", lambda: now[0])
    p = str(tmp_path / "m.json")
    reg = registry_for(p, heartbeat_s=5.0)
    for i in range(20):  # t = 100.0 .. 101.9: one interval
        now[0] = 100.0 + i * 0.1
        reg.heartbeat(reads=i)
    now[0] = 105.5  # second interval opens
    for i in range(20):
        reg.heartbeat(reads=100 + i)
    reg.write()
    ev = p[:-5] + ".events.jsonl"
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    assert len(lines) == 2  # at most one per interval
    assert [x["reads"] for x in lines] == [0, 100]
    # every heartbeat record carries a monotonic elapsed_s
    assert [x["elapsed_s"] for x in lines] == [0.0, 5.5]


def test_explicit_events_path_without_final_json(tmp_path):
    """Satellite: an explicit events_path streams heartbeats even when
    no final-JSON path is configured (they used to be dropped)."""
    ev = str(tmp_path / "beats.jsonl")
    reg = registry_for(None, events_path=ev)
    assert reg.enabled
    reg.heartbeat(reads=1)
    reg.heartbeat(reads=2)  # heartbeat_s=0 + explicit path: unlimited
    assert reg.write() is None  # no final JSON...
    assert not any(f.suffix == ".json" for f in tmp_path.iterdir())
    lines = [json.loads(x) for x in open(ev) if x.strip()]
    assert [x["reads"] for x in lines] == [1, 2]
    assert all("elapsed_s" in x for x in lines)
    assert check_file(ev) == []


def test_prometheus_render_and_lint():
    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="stage_x")
    reg.counter("reads").inc(7)
    reg.gauge("fill").set(0.25)
    reg.histogram("subs").observe(0, 3)
    reg.histogram("subs").observe(2, 2)
    text = export.prometheus_text({"stage_x": reg.as_dict()},
                                  {"stage_x": 1.5})
    assert export.lint_prometheus_text(text) == []
    assert 'quorum_tpu_reads_total{stage="stage_x"} 7' in text
    assert 'quorum_tpu_fill{stage="stage_x"} 0.25' in text
    # exact counts -> cumulative le buckets
    assert 'quorum_tpu_subs_bucket{stage="stage_x",le="0"} 3' in text
    assert 'quorum_tpu_subs_bucket{stage="stage_x",le="2"} 5' in text
    assert 'quorum_tpu_subs_bucket{stage="stage_x",le="+Inf"} 5' in text
    assert 'quorum_tpu_subs_sum{stage="stage_x"} 4' in text
    assert 'quorum_tpu_elapsed_seconds{stage="stage_x"} 1.5' in text
    # TYPE headers appear exactly once per metric
    assert text.count("# TYPE quorum_tpu_subs histogram") == 1


def test_prometheus_lint_catches_malformations():
    from quorum_tpu.telemetry.export import lint_prometheus_text

    assert lint_prometheus_text("") != []  # no samples
    assert any("not a valid sample" in e for e in
               lint_prometheus_text("this is not prometheus\n"))
    assert any("missing _total" in e for e in lint_prometheus_text(
        "# TYPE foo counter\nfoo 3\n"))
    bad_buckets = ("# TYPE h histogram\n"
                   'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n')
    assert any("not cumulative" in e for e in
               lint_prometheus_text(bad_buckets))


def test_textfile_atomic_under_concurrent_reads(tmp_path):
    """Satellite: a reader at the rename target never observes a
    half-written textfile, no matter how the writes interleave."""
    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="atomic")
    for i in range(200):  # a body big enough to make torn writes real
        reg.counter(f"c{i:03d}").inc(i)
    export.register_live(reg)
    path = str(tmp_path / "metrics.prom")
    export.write_textfile(path)
    stop = threading.Event()
    torn: list[str] = []

    def reader():
        while not stop.is_set():
            text = open(path).read()
            errs = export.lint_prometheus_text(text)
            if errs:
                torn.extend(errs)
                return

    t = threading.Thread(target=reader)
    t.start()
    try:
        for _ in range(300):
            reg.counter("c000").inc()
            export.write_textfile(path)
    finally:
        stop.set()
        t.join()
    assert torn == []
    assert not os.path.exists(path + ".tmp")  # tmp never lingers


def test_attach_textfile_rate_limit_and_final(tmp_path, monkeypatch):
    """attach_textfile refreshes at most once per period on heartbeats
    but always on the final write()."""
    from quorum_tpu.telemetry import export, registry as reg_mod

    now = [50.0]
    monkeypatch.setattr(reg_mod.time, "perf_counter", lambda: now[0])
    monkeypatch.setattr(export.time, "perf_counter", lambda: now[0])
    path = str(tmp_path / "m.prom")
    writes = []
    real_write = export.write_textfile
    monkeypatch.setattr(export, "write_textfile",
                        lambda p, text=None: writes.append(p)
                        or real_write(p, text))
    reg = registry_for(None, force=True)
    reg.set_meta(stage="tf")
    reg.counter("c").inc()
    export.attach_textfile(reg, path, period=10.0)
    for i in range(5):
        now[0] = 50.0 + i  # all within one period
        reg.heartbeat(reads=i)
    assert len(writes) == 1
    reg.write()  # final=True bypasses the period
    assert len(writes) == 2
    assert export.lint_prometheus_text(open(path).read()) == []


def test_live_http_endpoint_serves_metrics_and_healthz():
    import urllib.request

    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="live")
    reg.counter("scraped").inc(3)
    export.register_live(reg)
    srv = export.serve(0)  # ephemeral port
    try:
        assert export.current_server() is srv
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics") as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            text = r.read().decode()
        assert export.lint_prometheus_text(text) == []
        assert 'quorum_tpu_scraped_total{stage="live"} 3' in text
        with urllib.request.urlopen(base + "/healthz") as r:
            hz = json.loads(r.read().decode())
        assert hz["status"] == "ok"
        assert hz["registries"] >= 1
        try:
            urllib.request.urlopen(base + "/nope")
            assert False, "404 expected"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.close()


def test_span_tracer_jsonl_and_chrome_trace(tmp_path):
    from quorum_tpu.telemetry import (NULL_TRACER, tracer_for,
                                      validate_chrome_trace,
                                      validate_span_line)

    assert tracer_for(None) is NULL_TRACER
    assert not NULL_TRACER.enabled
    with NULL_TRACER.span("x"), NULL_TRACER.step("y", 0):
        pass

    p = str(tmp_path / "spans.jsonl")
    tr = tracer_for(p)
    assert tr.enabled
    with tr.span("outer", reads=128):
        with tr.span("inner"):
            pass
        with tr.step("device", 0, reads=128):
            pass

    def other_thread():
        with tr.span("threaded"):
            pass

    t = threading.Thread(target=other_thread)
    t.start()
    t.join()
    tr.close()
    tr.close()  # idempotent

    lines = [json.loads(x) for x in open(p) if x.strip()]
    assert all(validate_span_line(o) == [] for o in lines)
    by_name = {o["span"]: o for o in lines}
    assert by_name["inner"]["parent"] == by_name["outer"]["id"]
    assert by_name["device"]["parent"] == by_name["outer"]["id"]
    assert by_name["device"]["step"] == 0
    assert by_name["outer"]["parent"] is None
    assert by_name["outer"]["reads"] == 128
    # the other thread starts its own lineage on its own tid
    assert by_name["threaded"]["parent"] is None
    assert by_name["threaded"]["tid"] != by_name["outer"]["tid"]
    # children close before the parent: JSONL is close-ordered
    assert [o["span"] for o in lines].index("inner") \
        < [o["span"] for o in lines].index("outer")
    assert check_file(p) == []

    chrome = p[:-6] + ".trace.json"  # .jsonl -> .trace.json
    doc = json.load(open(chrome))
    assert validate_chrome_trace(doc) == []
    assert {e["name"] for e in doc["traceEvents"]} \
        == {"outer", "inner", "device", "threaded"}
    ev = {e["name"]: e for e in doc["traceEvents"]}
    assert ev["outer"]["args"]["reads"] == 128
    assert ev["inner"]["ts"] >= ev["outer"]["ts"]
    assert check_file(chrome) == []


def test_metrics_check_prom_mode(tmp_path):
    from quorum_tpu.telemetry import export

    reg = MetricsRegistry()
    reg.set_meta(stage="s")
    reg.counter("c").inc()
    good = tmp_path / "good.prom"
    good.write_text(export.prometheus_text({"s": reg.as_dict()}))
    bad = tmp_path / "bad.prom"
    bad.write_text("definitely not prometheus\n")
    res = subprocess.run([sys.executable, METRICS_CHECK, "--prom",
                          str(good)], capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    res = subprocess.run([sys.executable, METRICS_CHECK, "--prom",
                          str(good), str(bad)],
                         capture_output=True, text=True)
    assert res.returncode == 1
    assert "sample" in res.stderr


def test_lint_reports_non_numeric_le():
    from quorum_tpu.telemetry.export import lint_prometheus_text

    errs = lint_prometheus_text('# TYPE h histogram\n'
                                'h_bucket{le="abc"} 1\n')
    assert any("le=" in e for e in errs)  # reported, not a crash


def test_span_after_close_does_not_truncate_jsonl(tmp_path):
    """A straggler thread's span closing after tracer.close() must
    not reopen (and truncate) the streamed JSONL."""
    from quorum_tpu.telemetry import tracer_for

    p = str(tmp_path / "s.jsonl")
    tr = tracer_for(p)
    with tr.span("kept"):
        pass
    tr.close()
    with tr.span("late"):  # e.g. a render-pool task outliving the run
        pass
    lines = [json.loads(x) for x in open(p) if x.strip()]
    assert [o["span"] for o in lines] == ["kept"]


def test_http_server_close_is_idempotent():
    from quorum_tpu.telemetry import export

    srv = export.serve(0)
    srv.close()
    srv.close()  # second close: no-op, no error


def test_finished_registry_series_survive_in_live_rendering(tmp_path):
    """A stage registry freed after its run must keep its FINAL series
    in the shared exposition (driver endpoint/textfile carries stage1
    after stage1 returns)."""
    import gc

    from quorum_tpu.telemetry import export

    reg = registry_for(str(tmp_path / "s1.json"))
    reg.set_meta(stage="finished_stage")
    reg.counter("reads").inc(42)
    reg.write()
    del reg
    gc.collect()
    text = export.render_live()
    assert 'quorum_tpu_reads_total{stage="finished_stage"} 42' in text
    # a NEW live registry with the same label supersedes the snapshot
    reg2 = registry_for(None, force=True)
    reg2.set_meta(stage="finished_stage")
    reg2.counter("reads").inc(7)
    text = export.render_live()
    assert 'quorum_tpu_reads_total{stage="finished_stage"} 7' in text
    assert '} 42' not in text


def test_stage_cli_error_still_writes_metrics(tmp_path, monkeypatch):
    """A failed stage run (hash-full RuntimeError) must land its
    metrics document with status=error, not just stop reporting."""
    from quorum_tpu.cli import create_database as cdb_cli

    def boom(*a, **kw):
        raise RuntimeError("Hash is full")

    monkeypatch.setattr(cdb_cli, "create_database_main", boom)
    reads = tmp_path / "r.fastq"
    reads.write_text("@r\nACGT\n+\nIIII\n")
    m = str(tmp_path / "m.json")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", str(tmp_path / "db"), "--metrics", m,
                       str(reads)])
    assert rc == 1
    doc = json.load(open(m))
    assert doc["meta"]["status"] == "error"
    assert validate_metrics(doc) == []


def test_serve_resets_retained_finals():
    """A new endpoint must not report a previous job's counters."""
    from quorum_tpu.telemetry import export

    reg = registry_for(None, force=True)
    reg.set_meta(stage="job_a")
    reg.counter("stale").inc(9)
    reg.write()
    del reg
    assert 'stage="job_a"' in export.render_live()
    srv = export.serve(0)
    try:
        assert 'stage="job_a"' not in export.render_live()
    finally:
        srv.close()


def test_metrics_live_flag_forces_stage_registry(tmp_path):
    """--metrics-live (forwarded by the driver with --metrics-port)
    gives a stage a real registry with no output path, so the
    parent-owned endpoint sees its counters."""
    from quorum_tpu.cli import create_database as cdb_cli
    from quorum_tpu.telemetry import export

    export.reset_exposition()
    golden = os.path.join(HERE, "golden", "reads.fastq")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", str(tmp_path / "db.jf"), "--metrics-live",
                       golden])
    assert rc == 0
    text = export.render_live()
    assert 'quorum_tpu_reads_total{stage="create_database"}' in text
    # no metrics file was written (no --metrics path)
    assert not (tmp_path / "db.jf.json").exists()
    assert list(tmp_path.glob("*.json")) == []


def test_attach_textfile_new_target_drops_stale_finals(tmp_path):
    """Attaching a textfile path this process never wrote marks a new
    job: a previous job's retained finals must not leak into it.
    Re-attaching a known path (driver stages sharing one file) keeps
    them."""
    from quorum_tpu.telemetry import export

    export.reset_exposition()
    old = registry_for(None, force=True)
    old.set_meta(stage="old_job")
    old.counter("stale").inc(5)
    old.write()
    del old
    assert 'stage="old_job"' in export.render_live()

    new = registry_for(None, force=True)
    new.set_meta(stage="new_job")
    export.attach_textfile(new, str(tmp_path / "b.prom"))
    assert 'stage="old_job"' not in export.render_live()
    # same-path re-attach retains finals written since
    new.counter("c").inc()
    new.write()
    del new
    later = registry_for(None, force=True)
    later.set_meta(stage="later_stage")
    export.attach_textfile(later, str(tmp_path / "b.prom"))
    assert 'stage="new_job"' in export.render_live()


def test_busy_metrics_port_still_lands_error_document(tmp_path):
    """A busy --metrics-port raises before the pipeline starts; the
    run must still write its metrics document with status=error."""
    import socket

    from quorum_tpu.cli import create_database as cdb_cli

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    s.listen(1)
    port = s.getsockname()[1]
    try:
        reads = tmp_path / "r.fastq"
        reads.write_text("@r\nACGT\n+\nIIII\n")
        m = str(tmp_path / "m.json")
        with pytest.raises(OSError):
            cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7",
                          "-q", "38", "-o", str(tmp_path / "db"),
                          "--metrics", m, "--metrics-port", str(port),
                          str(reads)])
        doc = json.load(open(m))
        assert doc["meta"]["status"] == "error"
    finally:
        s.close()
