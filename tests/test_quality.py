"""Correction-quality scorecard (telemetry/quality.py, ISSUE 17):
the shared bucketing clamp, the edit-log tally, windowed rates and
EWMA drift under a deterministic feed, the default drift alert rules
firing and healing under a mocked clock, the pure `quality` section
(byte-deterministic across two golden pipeline runs), the coverage
model, schema validation, and the quality_diff accuracy gate."""

import conftest  # noqa: F401  (pins CPU devices)

import importlib
import json
import os
import sys

import pytest

from quorum_tpu.cli import create_database as cdb_cli
from quorum_tpu.cli import error_correct_reads as ec_cli
from quorum_tpu.models import error_correct as ec_mod
from quorum_tpu.telemetry import alerts, quality, registry_for
from quorum_tpu.telemetry.alerts import AlertEngine
from quorum_tpu.telemetry.quality import (QualityScorecard, bounded,
                                          coverage_from_histo,
                                          position_bucket,
                                          predicted_anchor_rate,
                                          section_from_doc,
                                          summarize_results)
from quorum_tpu.telemetry.schema import (QUALITY_DIFF_SCHEMA,
                                         validate_histo,
                                         validate_metrics,
                                         validate_perf_diff,
                                         validate_quality)

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
READS = os.path.join(HERE, "golden", "reads.fastq")
sys.path.insert(0, os.path.join(REPO, "tools"))


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# bucketing + tallies
# ---------------------------------------------------------------------------

def test_bounded_clamp_and_position_bucket():
    assert bounded(-3, 10) == 0
    assert bounded(5, 10) == 5
    assert bounded(99, 10) == 10
    assert position_bucket(0) == 0
    assert position_bucket(7) == 0
    assert position_bucket(8) == 1
    # arbitrarily long reads fold into the last spectrum bucket —
    # fixed cardinality no matter the input
    assert position_bucket(10 ** 9) == quality.SPECTRUM_BUCKETS - 1


def test_tally_log_counts_and_buckets():
    o = ec_mod.new_outcome()
    ns = ec_mod._tally_log("3:sub:A-G 17:sub:C-T 93:3_trunc", o)
    ns += ec_mod._tally_log("5:5_trunc", o)
    assert ns == 2  # only substitutions feed the per-read histogram
    assert o["subs"] == 2 and o["t3"] == 1 and o["t5"] == 1
    assert o["sub_pos"] == {0: 1, 2: 1}
    assert o["t3_pos"] == {11: 1}
    assert o["t5_pos"] == {0: 1}
    # garbage entries are ignored, not crashed on
    before = dict(o)
    assert ec_mod._tally_log("x:sub:A-G nonsense 9:mystery", o) == 0
    assert o == before
    # the maxe clamp render_result applies before observing the
    # substitutions_per_read histogram
    assert bounded(ns, 1) == 1


def test_summarize_results_matches_render_contract():
    results = [
        (">r1 3:sub:A-G 9:3_trunc\nACGT\n", ""),
        ("", "2 no anchor mer found\n"),          # skipped: log line
        (">r3 1:5_trunc\nAC\n", ""),
        (">r4\nACGT\n", ""),                      # clean read
    ]
    assert summarize_results(results) == {
        "reads": 4, "corrected": 3, "skipped": 1,
        "subs": 1, "t3": 1, "t5": 1}


# ---------------------------------------------------------------------------
# windowed rates + drift
# ---------------------------------------------------------------------------

def _fed_registry():
    reg = registry_for(None, force=True)
    sc = QualityScorecard(reg, alpha=0.5, window_reads=1)
    ec_mod.precreate_outcome_counters(reg)
    return reg, sc


def test_scorecard_windows_rates_and_drift():
    reg, sc = _fed_registry()
    assert sc.tick() is False          # no reads yet: no window
    reg.counter("reads_in").inc(10)
    reg.counter("reads_corrected").inc(9)
    reg.counter("reads_skipped").inc(1)
    reg.counter("skipped_contaminant").inc(1)
    reg.counter("substitutions").inc(18)
    assert sc.tick() is True
    doc = reg.as_dict()
    g = doc["gauges"]
    assert g["quality_corrections_per_read"] == 2.0
    assert g["quality_skip_rate"] == 0.1
    assert g["quality_contam_rate"] == 0.1
    assert g["quality_anchor_rate"] == 1.0
    # the first window SEEDS the EWMA baseline: drift stays 0, so a
    # short run that only ever closes one window cannot page
    assert g["quality_drift_score"] == 0.0

    # second window: a contaminant burst — every read skipped
    reg.counter("reads_in").inc(10)
    reg.counter("reads_skipped").inc(10)
    reg.counter("skipped_contaminant").inc(10)
    assert sc.tick() is True
    g2 = reg.as_dict()["gauges"]
    assert g2["quality_contam_rate"] == 1.0
    assert g2["quality_drift_score"] > 4.0  # past the default rule


def test_scorecard_coverage_ratio_against_header_prediction():
    reg, sc = _fed_registry()
    reg.set_meta(coverage_mean=8.0)
    reg.counter("reads_in").inc(100)
    reg.counter("reads_corrected").inc(100)
    assert sc.tick() is True
    doc = reg.as_dict()
    # observed anchor rate 1.0 vs predicted 1 - e^-8 ~ 0.99966
    assert doc["gauges"]["quality_coverage_ratio"] == pytest.approx(
        1.0 / predicted_anchor_rate(8.0), abs=1e-3)
    cov = doc["quality"]["coverage"]
    assert cov["predicted_mean"] == 8.0
    assert cov["predicted_anchor_rate"] == predicted_anchor_rate(8.0)


def test_scorecard_window_respects_min_reads():
    reg = registry_for(None, force=True)
    sc = QualityScorecard(reg, window_reads=100)
    ec_mod.precreate_outcome_counters(reg)
    reg.counter("reads_in").inc(5)
    assert sc.tick() is False           # below the window floor
    assert sc.tick(final=True) is True  # the final write flushes it
    with pytest.raises(ValueError):
        QualityScorecard(registry_for(None, force=True), alpha=0.0)


# ---------------------------------------------------------------------------
# default drift rules under a mocked clock
# ---------------------------------------------------------------------------

def test_quality_rules_fire_and_heal_with_mocked_clock(tmp_path):
    ev = str(tmp_path / "ev.jsonl")
    reg = registry_for(None, events_path=ev, force=True)
    QualityScorecard(reg, window_reads=1)
    clk = Clock()
    eng = AlertEngine(reg, alerts.merge_rules(
        alerts.DEFAULT_QUALITY_RULES), now=clk)
    # the scorecard pre-creates every gauge at its QUIET value
    # (rates 0, ratios 1.0), so a data-plane-free run never pages
    assert eng.evaluate() == []
    reg.gauge("quality_contam_rate").set(0.5)
    assert eng.evaluate() == ["contam_spike"]
    clk.advance(5)
    assert eng.evaluate() == ["contam_spike"]  # still firing, 1 event
    reg.gauge("quality_contam_rate").set(0.0)
    assert eng.evaluate() == []                # healed
    reg.gauge("quality_drift_score").set(9.0)
    assert eng.evaluate() == ["quality_drift"]
    reg.gauge("quality_drift_score").set(0.0)
    reg.gauge("quality_coverage_ratio").set(0.3)
    assert eng.evaluate() == ["coverage_drop"]
    assert reg.counter("alerts_fired_total").value == 3
    states = [json.loads(line) for line in open(ev)
              if json.loads(line).get("event") == "alert"]
    contam = [e["state"] for e in states if e["rule"] == "contam_spike"]
    assert contam == ["firing", "healed"]


# ---------------------------------------------------------------------------
# the pure quality section
# ---------------------------------------------------------------------------

def test_section_is_pure_function_of_the_document():
    reg, sc = _fed_registry()
    reg.counter("reads_in").inc(10)
    reg.counter("reads_corrected").inc(9)
    reg.counter("reads_skipped").inc(1)
    reg.counter("substitutions").inc(18)
    reg.histogram("sub_pos_bucket").observe(0)
    reg.histogram("sub_pos_bucket").observe(12)
    reg.histogram("substitutions_per_read").observe(2)
    sc.tick()
    doc = reg.as_dict()
    assert validate_metrics(doc) == []
    # recomputing the section from the serialized document (minus the
    # section itself) reproduces it exactly — no hidden state
    body = {k: v for k, v in doc.items() if k != "quality"}
    assert section_from_doc(body) == doc["quality"]
    # and serialization is stable across snapshots
    assert (json.dumps(doc["quality"], sort_keys=True)
            == json.dumps(reg.as_dict()["quality"], sort_keys=True))
    # pre-created skip-reason slugs land as zeros, not absences
    assert doc["quality"]["skip_reasons"] == {
        "contaminant": 0, "homopolymer": 0, "no_anchor": 0, "other": 0}
    assert doc["quality"]["sub_pos_spectrum"] == {"0": 1, "12": 1}


def test_validate_quality_rejects_tampering():
    reg, sc = _fed_registry()
    sc.tick(final=True)
    q = reg.as_dict()["quality"]
    assert validate_quality(q) == []
    bad = dict(q, substitutions=-1)
    assert any("substitutions" in e for e in validate_quality(bad))
    bad = dict(q, rates={})
    assert validate_quality(bad)
    bad = dict(q, schema="nope/9")
    assert any("schema" in e for e in validate_quality(bad))


# ---------------------------------------------------------------------------
# coverage model
# ---------------------------------------------------------------------------

def test_coverage_from_histo_finds_mode_past_valley():
    bins = [[1, 100, 500], [2, 10, 50], [3, 0, 30],
            [4, 0, 60], [5, 0, 20]]
    assert coverage_from_histo(bins) == 4.0
    # monotone decreasing = error-dominated: no valley, no fit
    assert coverage_from_histo([[1, 0, 9], [2, 0, 5], [3, 0, 1]]) == 0.0
    assert coverage_from_histo([]) == 0.0
    # low-quality-only bins are excluded from the fit
    assert coverage_from_histo([[1, 9, 0], [2, 5, 0]]) == 0.0
    assert predicted_anchor_rate(0) == 0.0
    assert predicted_anchor_rate(8.0) == pytest.approx(0.999665,
                                                       abs=1e-6)


def test_validate_histo_sidecar():
    from quorum_tpu.cli.histo_mer_database import histo_doc
    import numpy as np
    out = np.zeros((6, 2), dtype=np.int64)
    out[1] = (3, 40)
    out[4] = (0, 60)
    doc = histo_doc(out)
    assert validate_histo(doc) == []
    assert doc["bins"] == [[1, 3, 40], [4, 0, 60]]
    assert doc["stats"]["coverage_mode"] == 4.0
    bad = dict(doc, bins=[[4, 0, 60], [1, 3, 40]])  # not ascending
    assert validate_histo(bad)


# ---------------------------------------------------------------------------
# the accuracy gate (tools/quality_diff.py)
# ---------------------------------------------------------------------------

def _mini_doc():
    reg, sc = _fed_registry()
    reg.counter("reads_in").inc(10)
    reg.counter("reads_corrected").inc(9)
    reg.counter("reads_skipped").inc(1)
    reg.counter("skipped_no_anchor").inc(1)
    reg.counter("substitutions").inc(18)
    reg.histogram("sub_pos_bucket").observe(2)
    sc.tick(final=True)
    return reg.as_dict()


def test_quality_diff_pins_accuracy_exactly(tmp_path):
    qd = importlib.import_module("quality_diff")
    doc = _mini_doc()
    m = tmp_path / "m.json"
    m.write_text(json.dumps(doc))
    base = str(tmp_path / "base.json")
    assert qd.write_baseline(base, {"golden": str(m)}) == 0
    verdict_path = str(tmp_path / "v.json")
    assert qd.run_baseline(base, {"golden": str(m)}, verdict_path,
                           quiet=True) == 0
    verdict = json.loads(open(verdict_path).read())
    assert validate_perf_diff(verdict,
                              schema=QUALITY_DIFF_SCHEMA) == []
    # ANY accuracy movement fails: one extra substitution
    doc2 = json.loads(json.dumps(doc))
    doc2["counters"]["substitutions"] += 1
    del doc2["quality"]  # force recomputation from the counters
    m2 = tmp_path / "m2.json"
    m2.write_text(json.dumps(doc2))
    assert qd.run_baseline(base, {"golden": str(m2)},
                           str(tmp_path / "v2.json"), quiet=True) == 1
    # a vanished document fails like a wrong one
    assert qd.run_baseline(base, {}, None, quiet=True) == 1


def test_quality_diff_profile_paths():
    qd = importlib.import_module("quality_diff")
    doc = _mini_doc()
    prof = qd.profile_from_quality(doc["quality"])
    assert prof["counts.reads"] == 10.0
    assert prof["counts.substitutions"] == 18.0
    assert prof["rates.skip_rate"] == 0.1
    assert prof["skip_reasons.no_anchor"] == 1.0
    # one occupied spectrum bucket: all mass past the midpoint of
    # bucket range [2..2] -> tail_frac 0 (2 > 2//2 is False... the
    # single-bucket case: bucket 2 > max//2=1, so the whole mass is
    # "tail")
    assert prof["spectrum.tail_frac"] == 1.0


# ---------------------------------------------------------------------------
# golden pipeline: byte determinism (the CI acceptance, in-process)
# ---------------------------------------------------------------------------

def test_golden_scorecard_byte_determinism(tmp_path):
    db = str(tmp_path / "db.jf")
    rc = cdb_cli.main(["-s", "64k", "-m", "13", "-b", "7", "-q", "38",
                       "-o", db, READS])
    assert rc == 0
    sections = []
    for i in (1, 2):
        out = str(tmp_path / f"o{i}")
        m = str(tmp_path / f"m{i}.json")
        rc = ec_cli.main(["-p", "4", db, READS, "-o", out,
                          "--metrics", m])
        assert rc == 0
        with open(m) as f:
            doc = json.load(f)
        assert validate_metrics(doc) == []
        sections.append(doc["quality"])
    assert (json.dumps(sections[0], sort_keys=True)
            == json.dumps(sections[1], sort_keys=True))
    q = sections[0]
    assert q["reads"] == 242 and q["corrected"] == 241
    assert q["substitutions"] == 227
    assert q["skip_reasons"]["no_anchor"] == 1
    # the spectrum carries real per-cycle mass, bounded cardinality
    assert q["sub_pos_spectrum"]
    assert all(int(k) < quality.SPECTRUM_BUCKETS
               for k in q["sub_pos_spectrum"])
    assert sum(q["substitutions_per_read"].values()) == 241
