"""Multi-chip sharded table: build + query on a virtual CPU mesh must
agree with the single-chip path (which is itself pinned against the
reference semantics in test_table/test_create_database).

The reference's "undersize to force resize" stress trick (SURVEY §4)
translates here to "tiny local tables + several mesh shapes to force
multi-shard routing"."""

import conftest
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quorum_tpu.ops import table
from quorum_tpu.parallel import sharded
from quorum_tpu.models.create_database import extract_observations


def _random_reads(rng, n, length):
    codes = rng.integers(0, 4, size=(n, length)).astype(np.int8)
    quals = rng.integers(33, 74, size=(n, length)).astype(np.uint8)
    return codes, quals


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_sharded_build_matches_single_chip(n_shards):
    k, bits, qt = 9, 7, 53
    rng = np.random.default_rng(n_shards)
    codes, quals = _random_reads(rng, 16, 80)

    # single-chip truth
    meta1 = table.TableMeta(k=k, bits=bits, size_log2=12)
    st1 = table.make_table(meta1)
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), k, qt
    )
    st1, full = table.add_kmer_batch(st1, meta1, chi, clo, q, valid)
    assert not bool(full)
    occ = np.asarray(st1.vals) != 0
    want = {}
    kh, kl, vv = (np.asarray(a) for a in st1)
    for h, l, v in zip(kh[occ], kl[occ], vv[occ]):
        want[(int(h), int(l))] = int(v)

    # sharded build
    mesh = sharded.make_mesh(n_shards, devices=conftest.cpu_devices(n_shards))
    smeta = sharded.ShardedMeta(k=k, bits=bits, local_size_log2=12,
                                n_shards=n_shards)
    sstate = sharded.make_sharded_table(smeta, mesh)
    step = sharded.build_step(mesh, smeta, qual_thresh=qt)
    pending = jnp.ones((codes.size,), dtype=bool)
    sstate, full, placed = step(sstate, jnp.asarray(codes),
                                jnp.asarray(quals), pending)
    assert not bool(full)

    got = {}
    kh, kl, vv = (np.asarray(a) for a in sstate)
    for h, l, v in zip(kh[vv != 0], kl[vv != 0], vv[vv != 0]):
        got[(int(h), int(l))] = int(v)
    assert got == want

    # keys landed on their owning shards
    local = 1 << smeta.local_size_log2
    occ_idx = np.nonzero(vv != 0)[0]
    owners = np.asarray(
        sharded.owner_of(jnp.asarray(kh[occ_idx]), jnp.asarray(kl[occ_idx]),
                         smeta)
    )
    assert np.array_equal(owners, occ_idx // local)

    # sharded query answers every inserted key and misses absent ones
    keys = sorted(want)
    pad = (-len(keys)) % n_shards
    qhi = np.array([h for h, _ in keys] + [0] * pad, dtype=np.uint32)
    qlo = np.array([l for _, l in keys] + [0] * pad, dtype=np.uint32)
    qstep = sharded.query_step(mesh, smeta)
    res = np.asarray(qstep(sstate, jnp.asarray(qhi), jnp.asarray(qlo)))
    for (key, r) in zip(keys, res):
        assert want[key] == int(r)

    absent_hi = jnp.full((n_shards,), 0x3FFFFFFF, jnp.uint32)
    absent_lo = jnp.full((n_shards,), 0xFFFFFFFF, jnp.uint32)
    assert np.all(np.asarray(qstep(sstate, absent_hi, absent_lo)) == 0)


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_grow_and_retry_exact_once(n_shards):
    """Undersized local tables force the full->grow->retry path; final
    contents must still match the single-chip truth exactly (the
    reference's undersize-to-force-resize stress test, SURVEY §4,
    translated to multi-chip)."""
    k, bits, qt = 9, 7, 53
    rng = np.random.default_rng(99)
    codes, quals = _random_reads(rng, 16, 80)

    meta1 = table.TableMeta(k=k, bits=bits, size_log2=12)
    st1 = table.make_table(meta1)
    chi, clo, q, valid = extract_observations(
        jnp.asarray(codes), jnp.asarray(quals), k, qt
    )
    st1, full = table.add_kmer_batch(st1, meta1, chi, clo, q, valid)
    assert not bool(full)
    kh, kl, vv = (np.asarray(a) for a in st1)
    occ = vv != 0
    want = {(int(h), int(l)): int(v)
            for h, l, v in zip(kh[occ], kl[occ], vv[occ])}

    mesh = sharded.make_mesh(n_shards, devices=conftest.cpu_devices(n_shards))
    smeta = sharded.ShardedMeta(k=k, bits=bits, local_size_log2=4,
                                n_shards=n_shards)
    sstate, smeta = sharded.build_database_sharded(
        [(jnp.asarray(codes), jnp.asarray(quals))], mesh, smeta, qt
    )
    assert smeta.local_size_log2 > 4  # growth actually happened
    kh, kl, vv = (np.asarray(a) for a in sstate)
    occ = vv != 0
    got = {(int(h), int(l)): int(v)
           for h, l, v in zip(kh[occ], kl[occ], vv[occ])}
    assert got == want
