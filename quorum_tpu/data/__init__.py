"""Built-in contaminant data: Illumina adapter k-mer set.

The reference ships `data/adapter.fa` and builds `data/adapter.jf`
from it at build time with `jellyfish count -m 24 -s 5k -C`
(reference: Makefile.am:50-56), for use with the corrector's
`--contaminant` flag. Its fasta is the set of standard Illumina
TruSeq/PE adapter+primer sequences PLUS every single-base substitution
variant of each (an error-tolerant membership set — one sequencing
error in an adapter still hits).

Rather than shipping the ~880-record expansion, this module keeps the
canonical public adapter sequences and regenerates the same expansion
on demand; `adapter_fasta()` materializes it (cached) and the
`--contaminant` loaders accept fasta directly (io/contaminant.py), so
`--contaminant $(python -m quorum_tpu.data)` reproduces the
reference's batteries-included workflow without a Jellyfish build.
"""

from __future__ import annotations

import os

# Standard Illumina adapter / sequencing-primer sequences (public
# Illumina documentation; same set the reference's data/adapter.fa is
# built from): TruSeq universal/indexed adapter stems, the PE flow-cell
# P5/P7-extended primers, and the multiplexing read-2 primer region.
ADAPTERS = (
    "GATCGGAAGAGCTCGTATGCCGTCTTCTGCTTG",
    "ACACTCTTTCCCTACACGACGCTCTTCCGATCT",
    "AATGATACGGCGACCACCGAGATCTACACTCTTTCCCTACACGACGCTCTTCCGATCT",
    "CAAGCAGAAGACGGCATACGAGCTCTTCCGATCT",
    "GATCGGAAGAGCGGTTCAGCAGGAATGCCGAG",
    "CAAGCAGAAGACGGCATACGAGATCGGTCTCGGCATTCCTGCTGAACCGCTCTTCCGATCT",
    "CGGTCTCGGCATTCCTGCTGAACCGCTCTTCCGATCT",
)


def adapter_records():
    """Yield (header, sequence) for the full error-tolerant set: each
    canonical adapter followed by all of its 1-substitution variants
    (dedup'd across the whole set, originals kept first). Headers are
    unique (canonical "1".."7", variants "v0".."vN") so tools that
    index fasta by name (faidx etc.) accept the file."""
    seen = set()
    for i, s in enumerate(ADAPTERS):
        if s not in seen:
            seen.add(s)
            yield str(i + 1), s
    n = 0
    for s in ADAPTERS:
        for j, c in enumerate(s):
            for x in "ACGT":
                if x == c:
                    continue
                v = s[:j] + x + s[j + 1:]
                if v in seen:
                    continue
                seen.add(v)
                yield f"v{n}", v
                n += 1


def adapter_fasta(path: str | None = None) -> str:
    """Write (or reuse) the adapter fasta; returns its path. The
    default cache location embeds a content digest of the expansion,
    so a changed adapter set (or expansion rule) regenerates instead
    of silently reusing a stale file."""
    recs = list(adapter_records())
    if path is None:
        import hashlib
        digest = hashlib.sha256(
            "".join(f">{h}\n{s}\n" for h, s in recs).encode()
        ).hexdigest()[:10]
        cache = os.path.expanduser("~/.cache/quorum_tpu")
        os.makedirs(cache, exist_ok=True)
        path = os.path.join(cache, f"adapters-{digest}.fa")
        if os.path.exists(path):
            return path
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for hdr, seq in recs:
            f.write(f">{hdr}\n{seq}\n")
    os.replace(tmp, path)
    return path
