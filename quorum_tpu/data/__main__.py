"""`python -m quorum_tpu.data [out.fa]` — materialize the built-in
Illumina adapter contaminant fasta and print its path (the reference
ships the equivalent as data/adapter.fa / adapter.jf,
Makefile.am:50-56). Use with `--contaminant <path>` in
quorum / quorum_error_correct_reads."""

import sys

from . import adapter_fasta

if __name__ == "__main__":
    path = sys.argv[1] if len(sys.argv) > 1 else None
    print(adapter_fasta(path))
