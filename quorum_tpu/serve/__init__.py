"""Persistent correction service (ISSUE 3 tentpole).

The offline CLIs pay the full cost of every invocation: reload the
mer database, re-JIT the corrector, exit. `quorum_tpu.serve` is the
inference-style alternative — a warm process that loads the database
and compiled programs ONCE and then batches many small requests onto
the device (the same shape as KMC 3's client/server mode and the GPU
k-mer counters in PAPERS.md):

* `engine.py`  — CorrectionEngine: a loaded DB + the stage-2
  corrector, compiled once per read-length bucket and reused across
  requests.
* `batcher.py` — DynamicBatcher: a bounded request queue feeding a
  dispatcher thread that coalesces waiting requests up to
  `--max-batch` reads or `--max-wait-ms`, runs one device step, and
  demuxes per-request results back through futures.
* `server.py`  — the stdlib-HTTP front end: `POST /correct` (FASTQ
  in, corrected FASTA out, byte-identical to the offline CLI),
  `/healthz`, the live `/metrics` exposition on the same registry,
  admission control (full queue -> 429 + Retry-After), per-request
  deadlines, hot `POST /reload` (atomic engine swap with rollback),
  and graceful drain on SIGTERM / `POST /quiesce`.
* `admission.py` — TokenBucketQuota: per-client token buckets keyed
  on the `X-Quorum-Client` header, so overload degrades by policy
  (429 the greedy client) instead of queue order.
* `client.py`  — a minimal stdlib client plus the
  `quorum-serve-bench` closed-loop load generator.
* `live_table.py` / `ingest.py` — the live ingestion tier (ISSUE 18):
  `POST /ingest` streams FASTQ chunks into a mutable LiveTable owned
  by an IngestDispatcher thread; at epoch boundaries the table is
  sealed, floored, cutoff-resolved, and swapped into the correction
  path via the same generation substrate as /reload — in-flight
  corrections finish on the old epoch, any failure rolls back.

The console entry point is `quorum-serve` (cli/serve.py).
"""

from .admission import TokenBucketQuota
from .batcher import (PRIORITIES, DeadlineExceeded, Draining,
                      DynamicBatcher, EngineStepTimeout, QueueFull)
from .engine import CorrectionEngine
from .ingest import IngestDispatcher
from .live_table import LiveTable, LiveTableCheckpoint, epoch_floor
from .server import CorrectionServer

__all__ = [
    "CorrectionEngine", "DynamicBatcher", "CorrectionServer",
    "QueueFull", "Draining", "DeadlineExceeded", "EngineStepTimeout",
    "TokenBucketQuota", "PRIORITIES",
    "IngestDispatcher", "LiveTable", "LiveTableCheckpoint",
    "epoch_floor",
]
