"""The ingest dispatcher: one thread that owns the live table
(ISSUE 18).

`POST /ingest` handlers never touch the build planes — they hand
parsed FASTQ records to :class:`IngestDispatcher.submit_chunk`, which
enqueues under the dispatcher lock and blocks until the dedicated
worker thread has inserted the chunk (natural backpressure: a client
can't outrun the device). The worker is the sole owner of the
LiveTable, so inserts, grows, checkpoints, and epoch seals are all
single-threaded — the concurrency surface is exactly one
lock-protected queue plus the swap_engine generation substrate the
epoch path already shares with /reload and the watchdog.

Epoch protocol (the tentpole): at a boundary (`--epoch-reads` worth of
new reads, or `--epoch-interval-s` with any new reads, or a forced
`POST /epoch`), the worker seals the table WITHOUT closing it,
re-resolves the Poisson cutoff from the accumulated stats, applies the
time-varying presence floor (live_table.epoch_floor — the policy is
declared in the epoch header), writes the snapshot as a normal v5
database file under `--live-dir`, builds a fresh CorrectionEngine from
it (sample-verified — the verify-at-swap fix rides along), and swaps
it in via `Batcher.swap_engine` with the captured generation:
in-flight corrections finish on the old epoch (the batcher dispatcher
captured its engine reference), a superseded or failed swap rolls
back — the old epoch keeps serving, the orphaned snapshot file is
removed, and the failure is counted (`epoch_swap_failures_total`) for
the next boundary to retry.

Durability: every `--live-checkpoint-every` chunks (and once at
drain) the worker commits a LiveTableCheckpoint carrying the chunk
cursor. A client that stamps `X-Quorum-Ingest-Seq` gets exactly-once
inserts across a kill: after resume, re-sent chunks at-or-below the
restored cursor are acknowledged as duplicates without touching the
table.

Lock order: `ingest.IngestDispatcher._lock` ranks between the HTTP
request lock and the batcher lock (analysis/rules_locks.LOCK_ORDER) —
the worker calls swap_engine and registry updates from OUTSIDE its
lock anyway; only queue/cursor/stats state lives under it.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from ..io import db_format
from ..telemetry import NULL
from ..telemetry.spans import NULL_TRACER
from ..utils import faults, levers, resources, sizes
from ..utils.pipeline import batch_nbytes
from ..utils.vlog import vlog
from .batcher import Draining, QueueFull
from .live_table import LiveTable, LiveTableCheckpoint, epoch_floor


class _Chunk:
    """One queued ingest chunk: records + a done event the submitting
    HTTP thread blocks on."""

    __slots__ = ("seq", "records", "nbytes", "done", "error")

    def __init__(self, seq: int, records):
        self.seq = seq
        self.records = records
        self.nbytes = batch_nbytes(records)
        self.done = threading.Event()
        self.error: BaseException | None = None


class _ForceEpoch:
    """A forced-epoch request (POST /epoch) awaiting the worker."""

    __slots__ = ("done", "ok", "detail")

    def __init__(self):
        self.done = threading.Event()
        self.ok = False
        self.detail: dict = {}


class IngestDispatcher:
    """Owns a LiveTable on a dedicated thread; seals and swaps epoch
    snapshots into the correction batcher."""

    def __init__(self, table: LiveTable, ckpt: LiveTableCheckpoint,
                 epoch_builder, *, live_dir: str,
                 epoch_reads: int = 0, epoch_interval_s: float = 0.0,
                 checkpoint_every: int = 0, queue_chunks: int = 16,
                 floor_initial: int = 1, floor_final: int = 1,
                 floor_ramp: float = 0.0, cursor: int = -1,
                 keep_epochs: int = 2, registry=NULL,
                 tracer=NULL_TRACER):
        self.table = table
        self.ckpt = ckpt
        # epoch_builder(db_path, poisson) -> CorrectionEngine: the CLI
        # closure that resolves the cutoff from the accumulated stats
        # and sample-verifies the candidate before it can swap in
        self.epoch_builder = epoch_builder
        self.live_dir = live_dir
        self.epoch_reads = int(epoch_reads)
        self.epoch_interval_s = float(epoch_interval_s)
        self.checkpoint_every = int(checkpoint_every)
        self.queue_chunks = int(queue_chunks)
        self.floor_initial = int(floor_initial)
        self.floor_final = int(floor_final)
        self.floor_ramp = float(floor_ramp)
        self.keep_epochs = int(keep_epochs)
        self.registry = registry
        self.tracer = tracer
        self.batcher = None

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queue: collections.deque[_Chunk] = collections.deque()
        # byte-bounded backpressure (ISSUE 19): alongside the chunk
        # COUNT bound, a queue over this many queued record bytes
        # answers 429 + Retry-After — one burst of long reads cannot
        # balloon RSS past the budget
        try:
            self.queue_bytes = sizes.parse_size(
                levers.raw("QUORUM_INGEST_QUEUE_BYTES") or "512M")
        except ValueError:
            self.queue_bytes = sizes.parse_size("512M")
        self._queued_bytes = 0
        self._force: _ForceEpoch | None = None
        self._cursor = int(cursor)      # last fully-ingested chunk seq
        self._max_seen = int(cursor)    # dedupe horizon (incl. queued)
        self._draining = False
        self._stopped = False
        self._thread: threading.Thread | None = None
        self._chunks_done = 0
        self._epoch_n = 0
        self._epoch_reads_since = 0
        self._epoch_t0 = time.monotonic()
        self._floor = self.floor_initial
        self._coverage = 0.0
        self._last_epoch_error: str | None = None
        self._epoch_paths: list[str] = []

        reg = registry
        reg.counter("ingest_requests_total")
        reg.counter("ingest_reads_total")
        reg.counter("epoch_swaps_total")
        reg.counter("epoch_swap_failures_total")
        reg.gauge("ingest_cursor").set(self._cursor)
        reg.gauge("live_floor").set(self._floor)

    # -- boot -------------------------------------------------------------
    def boot_epoch(self):
        """Seal and build the boot engine BEFORE the worker thread
        exists (single-threaded; called by the CLI to construct the
        server's first engine — possibly from a resumed table)."""
        path, poisson = self._write_epoch_db()
        engine = self.epoch_builder(path, poisson)
        return engine

    def start(self, batcher) -> None:
        """Attach the correction batcher and start the worker."""
        self.batcher = batcher
        self._thread = threading.Thread(target=self._run,
                                        name="quorum-ingest",
                                        daemon=True)
        self._thread.start()

    # -- HTTP-side API ----------------------------------------------------
    def submit_chunk(self, records, seq: int | None = None) -> dict:
        """Enqueue one chunk and block until it is inserted (or
        dropped as a duplicate). Returns the ack document. Raises
        Draining/QueueFull for the HTTP layer to map to 503/429."""
        reg = self.registry
        reg.counter("ingest_requests_total").inc()
        with self._work:
            if self._draining or self._stopped:
                raise Draining()
            if seq is None:
                seq = self._max_seen + 1
            seq = int(seq)
            if seq <= self._max_seen:
                # at-or-below the horizon: already ingested (resume)
                # or already queued — ack without touching the table
                return {"accepted": True, "duplicate": True,
                        "seq": seq, "cursor": self._cursor}
            if len(self._queue) >= self.queue_chunks:
                raise QueueFull(retry_after=1.0)
            chunk = _Chunk(seq, records)
            # admit-into-empty rule: a single chunk bigger than the
            # whole byte budget must still make progress alone
            if (self._queue
                    and self._queued_bytes + chunk.nbytes
                    > self.queue_bytes):
                raise QueueFull(retry_after=1.0)
            self._queue.append(chunk)
            self._queued_bytes += chunk.nbytes
            reg.gauge("ingest_queue_bytes_max").set_max(
                self._queued_bytes)
            self._max_seen = seq
            self._work.notify_all()
        chunk.done.wait()
        if chunk.error is not None:
            raise chunk.error
        with self._lock:
            return {"accepted": True, "duplicate": False, "seq": seq,
                    "reads": len(records), "cursor": self._cursor}

    def force_epoch(self, timeout: float = 120.0) -> dict:
        """Seal + swap now (POST /epoch), regardless of boundaries.
        Blocks until the worker finishes the attempt."""
        req = _ForceEpoch()
        with self._work:
            if self._stopped:
                raise Draining()
            self._force = req
            self._work.notify_all()
        if not req.done.wait(timeout):
            return {"ok": False, "error": "epoch timed out"}
        return dict(req.detail, ok=req.ok)

    def stats(self) -> dict:
        """The healthz `live` section."""
        with self._lock:
            st = self.table.stats
            return {
                "cursor": self._cursor,
                "queued": len(self._queue),
                "epoch": self._epoch_n,
                "floor": self._floor,
                "coverage": round(self._coverage, 4),
                "reads": st.reads, "bases": st.bases,
                "batches": st.batches, "grows": st.grows,
                "draining": self._draining,
                "last_epoch_error": self._last_epoch_error,
            }

    @property
    def cursor(self) -> int:
        with self._lock:
            return self._cursor

    def drain(self, timeout: float = 60.0) -> None:
        """Stop accepting chunks, finish the queue, commit a final
        checkpoint, and join the worker."""
        with self._work:
            self._draining = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    # -- worker -----------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._work:
                while (not self._queue and self._force is None
                        and not self._draining):
                    self._work.wait(0.25)
                if self._draining and not self._queue:
                    self._stopped = True
                    force = self._force
                    self._force = None
                else:
                    force = self._force
                    self._force = None
                    if force is None and self._queue:
                        chunk = self._queue[0]
                    else:
                        chunk = None
            if self._stopped:
                if force is not None:
                    force.ok, force.detail = self._epoch("drain")
                    force.done.set()
                try:
                    self.ckpt.save(self.table, self.cursor)
                except Exception as e:  # a failed final snapshot must
                    # not block shutdown — the previous one resumes
                    self.registry.counter(
                        "live_checkpoint_failures_total").inc()
                    vlog("live: final checkpoint failed: ", e)
                return
            if force is not None:
                force.ok, force.detail = self._epoch("forced")
                force.done.set()
                continue
            if chunk is None:
                continue
            self._ingest_one(chunk)

    def _ingest_one(self, chunk: _Chunk) -> None:
        reg = self.registry
        try:
            faults.inject("serve.ingest", batch=chunk.seq)
            with self.tracer.span("live_ingest_chunk", seq=chunk.seq,
                                  reads=len(chunk.records)):
                n = self.table.ingest_records(chunk.records)
        except BaseException as e:
            chunk.error = e
            with self._work:
                # pull the failed seq back out of the dedupe horizon
                # so the client's retry isn't dropped as a duplicate
                self._queue.popleft()
                self._queued_bytes -= chunk.nbytes
                self._max_seen = max(
                    [self._cursor] + [c.seq for c in self._queue])
            chunk.done.set()
            return
        reg.counter("ingest_reads_total").inc(n)
        with self._work:
            self._queue.popleft()
            self._queued_bytes -= chunk.nbytes
            self._cursor = chunk.seq
            self._chunks_done += 1
            self._epoch_reads_since += n
            chunks_done = self._chunks_done
            reads_since = self._epoch_reads_since
        reg.gauge("ingest_cursor").set(chunk.seq)
        chunk.done.set()
        if (self.checkpoint_every > 0
                and chunks_done % self.checkpoint_every == 0):
            self.ckpt.save(self.table, chunk.seq)
        if self._boundary_due(reads_since):
            self._epoch("boundary")

    def _boundary_due(self, reads_since: int) -> bool:
        if reads_since <= 0:
            return False
        if self.epoch_reads > 0 and reads_since >= self.epoch_reads:
            return True
        return (self.epoch_interval_s > 0
                and time.monotonic() - self._epoch_t0
                >= self.epoch_interval_s)

    # -- epoch ------------------------------------------------------------
    def _write_epoch_db(self) -> tuple[str, dict]:
        """Seal the live table into `<live-dir>/epoch-NNNNNN.qdb` with
        the floor policy and accumulated Poisson stats declared in the
        header. Single-threaded (worker, or CLI boot)."""
        state, occ, distinct, total = self.table.seal()
        cov = self.table.coverage(distinct, total)
        floor = epoch_floor(self.floor_initial, self.floor_final,
                            self.floor_ramp, cov)
        n = self._epoch_n
        path = os.path.join(self.live_dir, f"epoch-{n:06d}.qdb")
        extra = {
            "live_epoch": {
                "epoch": n,
                "cursor": self._cursor,
                "reads": int(self.table.stats.reads),
                "coverage": cov,
                "floor": floor,
                "floor_policy": {"initial": self.floor_initial,
                                 "final": self.floor_final,
                                 "ramp": self.floor_ramp},
            },
            "poisson_stats": {"distinct_hq": distinct,
                              "total_hq": total},
        }
        if floor > 1:
            # the PR 13 floor machinery: the engine applies
            # prefilter.min_obs via ctable.tile_floor on load
            extra["prefilter"] = {"mode": "live-floor",
                                  "min_obs": floor}
        os.makedirs(self.live_dir, exist_ok=True)
        db_format.write_db(path, state, self.table.meta,
                           n_entries=occ, extra_header=extra)
        self._floor = floor
        self._coverage = cov
        return path, {"distinct_hq": distinct, "total_hq": total,
                      "floor": floor, "coverage": cov}

    def _epoch(self, reason: str) -> tuple[bool, dict]:
        """One epoch attempt: seal → export → build+verify → swap.
        Any failure rolls back — the old epoch keeps serving."""
        reg = self.registry
        if resources.degraded("epoch.snapshot"):
            # the ladder disabled epoch snapshots (an earlier ENOSPC
            # under --live-dir): the serving epoch keeps serving, and
            # boundaries stop burning a doomed seal+export each time
            detail = "epoch snapshots disabled (out of space)"
            with self._lock:
                self._last_epoch_error = detail
            return False, {"error": detail}
        self._epoch_n += 1
        path = None
        try:
            with self.tracer.span("live_epoch", epoch=self._epoch_n,
                                  reason=reason):
                path, poisson = self._write_epoch_db()
                # between snapshot build and the swap: an injected
                # failure here must leave the old epoch serving
                faults.inject("serve.epoch")
                expected = self.batcher.generation
                engine = self.epoch_builder(path, poisson)
                gen = self.batcher.swap_engine(
                    engine, expected_generation=expected)
                if gen < 0:
                    raise RuntimeError(
                        "epoch swap superseded by a concurrent "
                        "engine swap")
        except Exception as e:
            self._epoch_n -= 1
            if resources.is_enospc(e):
                # optional writer on the ladder (ISSUE 19): disable
                # epoch snapshots for the rest of the run — the
                # serving epoch is untouched, ingest keeps counting
                resources.degrade("epoch.snapshot", e, path=path)
            reg.counter("epoch_swap_failures_total").inc()
            reg.event("epoch_swap_failed", reason=reason,
                      error=str(e))
            vlog("live: epoch swap failed (old epoch keeps "
                 "serving): ", e)
            if path is not None:
                try:
                    os.remove(path)
                except OSError:
                    pass
            with self._lock:
                self._last_epoch_error = str(e)
            return False, {"error": str(e)}
        with self._lock:
            self._epoch_reads_since = 0
            self._last_epoch_error = None
            self._epoch_paths.append(path)
            stale = (self._epoch_paths[:-self.keep_epochs]
                     if self.keep_epochs > 0 else [])
            self._epoch_paths = self._epoch_paths[len(stale):]
        self._epoch_t0 = time.monotonic()
        reg.counter("epoch_swaps_total").inc()
        reg.gauge("live_floor").set(poisson["floor"])
        reg.event("epoch_swap", epoch=self._epoch_n, reason=reason,
                  generation=gen, floor=poisson["floor"],
                  coverage=round(poisson["coverage"], 4),
                  distinct_hq=poisson["distinct_hq"],
                  total_hq=poisson["total_hq"], path=path)
        # older snapshots are dead once current+previous exist (an
        # in-flight step only ever holds the previous epoch's mmap,
        # which POSIX keeps alive across the unlink anyway)
        for p in stale:
            try:
                os.remove(p)
            except OSError:
                pass
        return True, {"epoch": self._epoch_n, "generation": gen,
                      "floor": poisson["floor"],
                      "coverage": round(poisson["coverage"], 4),
                      "path": path}
