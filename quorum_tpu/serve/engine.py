"""The warm correction engine: one loaded database + the stage-2
corrector, reused across requests.

Where the offline path (models/error_correct.run_error_correct) loads
the DB, resolves the Poisson cutoff, JITs the corrector, streams one
file, and exits, the engine does the load/resolve ONCE at construction
and then exposes `step(records)` — correct one batch of reads and
return each read's exact offline output text. Byte parity with
`quorum_error_correct_reads` is structural: the device path is the
same `correct_batch_packed` -> `fetch_finish` -> `finish_batch_host`
chain and the rendering is the same `render_result` the offline drain
loop uses.

Compilation discipline: every step pads its rows up to the fixed
`rows` capacity (the batcher's `--max-batch`) and its columns to the
read-length buckets the offline pipeline already uses
(io/fastq.LENGTH_BUCKETS), so the engine compiles at most one
executable per distinct length bucket it ever sees — the
`engine_compiles` counter is the acceptance signal that a warm server
answers a second request without recompilation. `warmup()` pays those
compiles before the first request arrives.

Since ISSUE 15 this contract is ENFORCED, not narrated: the device
step's executable is budgeted in
`analysis/compile_budget.COMPILE_BUDGET`
(`models/corrector.py:_correct_device_packed`), and under
``QUORUM_COMPILE_SENTINEL=1`` (CI tier-1) every jit-cache miss is
ledgered — a warm request that compiles fails the observing test
with the dispatching stack attached, and the serve metrics document
carries the per-site compile counts for the perf_diff gate.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..io import contaminant as contaminant_mod
from ..io import db_format, fastq
from ..ops import ctable, mer
from ..models.corrector import (correct_batch_packed, fetch_finish,
                                finish_batch_host)
from ..models.ec_config import ECConfig
from ..models.error_correct import (ECOptions, new_outcome,
                                    pack_for_stage2,
                                    precreate_outcome_counters,
                                    record_outcome, render_result,
                                    resolve_cutoff)
from ..telemetry import NULL, NULL_TRACER, observe_dispatch_wait
from ..utils import faults
from ..utils.vlog import vlog


class CorrectionEngine:
    """A warm, reusable stage-2 corrector.

    `rows` is the fixed device-batch row capacity: every step is
    padded to exactly `rows` reads so row count never forces a
    recompile (padding rows are length-0 and cost only masked lanes).
    Reads longer than the largest length bucket get a one-off shape —
    allowed, but each distinct oversize length compiles its own
    executable (the offline pipeline behaves the same).

    Thread model: `step` serializes device access with a lock (the
    tunnel degrades under concurrent device use, PERF_NOTES.md); the
    host-side render afterwards runs outside it. One dispatcher
    thread calling `step` is the intended shape (serve/batcher.py).
    """

    def __init__(self, db_path: str, *, cutoff: int | None = None,
                 qual_cutoff: int = 127, skip: int = 1, good: int = 2,
                 anchor_count: int = 3, min_count: int = 1,
                 window: int = 10, error: int = 3,
                 homo_trim: int | None = None,
                 trim_contaminant: bool = False,
                 no_discard: bool = False,
                 contaminant: str | None = None,
                 apriori_error_rate: float = 0.01,
                 poisson_threshold: float = 1e-6,
                 no_mmap: bool = False, rows: int = 1024,
                 verify_db: str = "full",
                 registry=NULL, tracer=NULL_TRACER):
        if rows < 1:
            raise ValueError("rows must be >= 1")
        self.rows = int(rows)
        self.db_path = db_path
        self.registry = registry
        self.tracer = tracer
        self.verify_db = verify_db
        opts = ECOptions(cutoff=cutoff,
                         apriori_error_rate=apriori_error_rate,
                         poisson_threshold=poisson_threshold,
                         no_mmap=no_mmap)
        vlog("Loading mer database")
        # verify_db (ISSUE 8): checksum verification of v5 databases
        # before serving from them — "sample" keeps hot /reload and
        # watchdog rebuilds latency-bounded (seeded chunk scrub), a
        # bad digest refuses the build and the reload rolls back
        self.state, self.meta, _header = db_format.read_db(
            db_path, to_device=True, no_mmap=no_mmap, verify=verify_db)
        cutoff = resolve_cutoff(self.state, self.meta, opts,
                                header=_header)
        # a prefiltered database (ISSUE 14) declares its presence
        # floor; applying it here keeps serve byte-identical to the
        # offline CLI over the same database (plain databases declare
        # nothing — floor 1 is the identity)
        floor = int((_header.get("prefilter") or {}).get("min_obs", 1))
        if floor > 1:
            from ..ops import ctable
            self.state = ctable.tile_floor(self.state, self.meta,
                                           floor)
        vlog("Using cutoff of ", cutoff)
        if cutoff == 0 and opts.cutoff is None:
            raise RuntimeError(
                "Cutoff computation failed. Pass it explicitly with "
                "-p switch.")
        self.cfg = ECConfig(
            k=self.meta.k, skip=skip, good=good,
            anchor_count=anchor_count, min_count=min_count,
            cutoff=cutoff, qual_cutoff=qual_cutoff, window=window,
            error=error, homo_trim=homo_trim,
            trim_contaminant=trim_contaminant, no_discard=no_discard,
            collision_prob=apriori_error_rate / 3.0,
            poisson_threshold=poisson_threshold,
        )
        self.contam = None
        if contaminant is not None:
            vlog("Loading contaminant sequences")
            self.contam = contaminant_mod.load_contaminant(
                contaminant, self.cfg.k)
        self._lock = threading.Lock()
        self._shapes: set[tuple[int, int]] = set()
        # monotone device-step index: serve_device regions are
        # StepTraceAnnotation-tagged with it, so a --profile'd serve
        # run joins kernels to steps exactly like the batch loops
        # (telemetry/devtrace.py)
        self._step_i = 0
        # immutable snapshot of the column widths seen, reassigned
        # whole under the lock: `warm_lengths` must be readable
        # WITHOUT the lock — the watchdog's rebuild consults it while
        # a wedged step may still hold the lock forever
        self._warm: tuple[int, ...] = ()
        registry.gauge("cutoff").set(cutoff)
        registry.set_meta(db=db_path, rows=self.rows, cutoff=cutoff)
        # the data-plane quality surface (ISSUE 17): zero-count skip
        # reasons land in the serve document too, and the header's
        # coverage statistic arms the scorecard's coverage model
        precreate_outcome_counters(registry)
        if getattr(registry, "enabled", False):
            ps = (_header or {}).get("poisson_stats")
            if ps and ps.get("distinct_hq"):
                registry.set_meta(coverage_mean=round(
                    float(ps["total_hq"]) / float(ps["distinct_hq"]),
                    4))

    # -- device step ------------------------------------------------------
    def step(self, records, _warmup: bool = False) -> list[tuple[str, str]]:
        """Correct `records` — a list of (header, seq_bytes,
        qual_bytes) tuples, at most `self.rows` long — and return one
        (fa_text, log_text) pair per record, in order, exactly as the
        offline CLI would write them. Updates the engine's telemetry
        (outcome counters, dispatch/wait split, compile count).
        `_warmup` steps count only `engine_compiles` — synthetic
        warmup reads must not pollute the read/skip counters or the
        latency histograms real traffic is judged by."""
        if len(records) > self.rows:
            raise ValueError(
                f"batch of {len(records)} exceeds engine rows "
                f"{self.rows}")
        if not records:
            return []
        # chaos-harness site: a plan can fail the Nth device step to
        # exercise the batcher's fault isolation (utils/faults.py)
        faults.inject("serve.engine.step")
        reg = NULL if _warmup else self.registry
        batch = fastq._make_batch(list(records), self.rows)
        pk = pack_for_stage2(batch, self.cfg)
        shape = (batch.codes.shape[0], batch.codes.shape[1])
        with self._lock:
            if shape not in self._shapes:
                # first time this (rows, bucket) shape reaches the
                # device: the jit cache compiles a fresh executable.
                # A warm server's steady state never grows this.
                # Counted on the REAL registry even during warmup —
                # warmup exists to move compiles before traffic, and
                # the counter must show them.
                self._shapes.add(shape)
                self._warm = tuple(sorted(
                    {cols for _rows, cols in self._shapes}))
                self.registry.counter("engine_compiles").inc()
                vlog("Engine compiling shape ", shape)
            step_i = self._step_i
            self._step_i += 1
            t0 = time.perf_counter()
            with self.tracer.step("serve_device", step_i,
                                  reads=batch.n):
                cap = 4 * batch.codes.shape[0]
                res, packed = correct_batch_packed(
                    self.state, self.meta, pk, self.cfg,
                    contam=self.contam, pack_cap=cap)
                t1 = time.perf_counter()
                jax.block_until_ready(packed)
                t2 = time.perf_counter()
            with self.tracer.span("serve_fetch"):
                buf = fetch_finish(res, packed)
        # the same *_dispatch_us/*_wait_us split the offline device
        # loops record, so one dashboard reads both
        observe_dispatch_wait(reg, "serve", t0, t1, t2)
        b, l = res.out.shape
        maxe = res.fwd_log.pos.shape[1]
        with self.tracer.span("serve_render", reads=batch.n):
            results = finish_batch_host(buf, batch.n, self.cfg,
                                        batch.codes, b, l, maxe)
            outcome = new_outcome() if reg.enabled else None
            out: list[tuple[str, str]] = []
            n_corr = 0
            for hdr, r in zip(batch.headers, results):
                out.append(render_result(hdr, r, self.cfg, outcome,
                                         maxe=maxe))
                if r.ok:
                    n_corr += 1
        if reg.enabled:
            record_outcome(reg, outcome)
            reg.counter("reads_in").inc(batch.n)
            reg.counter("reads_corrected").inc(n_corr)
            reg.counter("reads_skipped").inc(batch.n - n_corr)
            reg.counter("bases_in").inc(int(batch.lengths[:batch.n].sum()))
            reg.counter("batches").inc()
            reg.histogram("batch_reads").observe(batch.n)
            # per-batch heartbeat: heartbeats drive the textfile
            # exporter and (with --metrics-interval) the JSONL event
            # stream — without this a serving process would refresh
            # its textfile only at startup and drain
            reg.heartbeat(stage="serve",
                          reads=reg.counter("reads_in").value,
                          bases=reg.counter("bases_in").value)
        return out

    # -- warmup -----------------------------------------------------------
    def warmup(self, lengths=(None,)) -> int:
        """Pay the compile cost for the length buckets of `lengths`
        (read lengths, not buckets; None entries are skipped) before
        serving. Returns the number of device steps run. With the
        default single-None argument this is a no-op — the serve CLI
        passes `--warmup-lengths`.

        Each warmup read is REPRESENTATIVE, not synthetic: assembled
        by walking k-mers the loaded database actually holds, with
        one mid-read flip to an absent k-mer (see
        `representative_read`). The old all-A read never found an
        anchor, so the correction path — including the deeper
        extension-loop levels — compiled lazily on the FIRST real
        request, ~4 s of compiles inside the watchdog budget (ROADMAP
        known gap). A read that anchors and corrects pays them here."""
        n = 0
        base = None
        want = [int(ln) for ln in lengths if ln is not None]
        if any(ln <= 0 for ln in want):
            raise ValueError("warmup length must be positive")
        if want:
            try:
                base = representative_read(self.state, self.meta,
                                           max(want))
            except Exception as e:  # noqa: BLE001 - warmup must not kill boot
                vlog("Representative warmup read unavailable (", e,
                     "); falling back to all-A")
        for ln in want:
            if base is not None:
                seq = bytearray(base[:ln].encode())
                # one flip to a (near-certainly) absent k-mer so the
                # corrector anchors on the clean flank and actually
                # extends across an error — the code path real
                # traffic takes
                mid = ln // 2
                seq[mid] = ord("ACGT"["ACGT".index(chr(seq[mid])) ^ 1])
                seq = bytes(seq)
            else:
                seq = b"A" * ln
            qual = b"I" * ln
            self.step([("warmup", seq, qual)], _warmup=True)
            n += 1
        return n

    @property
    def compiles(self) -> int:
        """Distinct device shapes compiled so far (mirrors the
        `engine_compiles` counter even when telemetry is off)."""
        return len(self._shapes)

    @property
    def warm_lengths(self) -> tuple[int, ...]:
        """The column widths (length buckets) this engine has stepped
        — feed them to a replacement engine's `warmup()` so a
        watchdog rebuild or hot reload re-pays exactly the compiles
        the old engine had (a read of length == bucket width maps to
        the same bucket, fastq.bucket_for). Deliberately lock-free
        (atomic read of an immutable snapshot): the rebuild path reads
        it off an engine whose wedged step may hold the lock
        forever."""
        return self._warm


# ---------------------------------------------------------------------------
# Representative warmup reads (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def representative_read(state, meta, length: int,
                        sample_rows: int = 2048) -> str:
    """Assemble a read of `length` bases by walking k-mers the loaded
    database actually holds: sample the first occupied table rows
    (one bounded D2H slice, ~1 MiB — never a full-table gather), seed
    with the highest-count mer found, then extend greedily, at each
    step keeping the base whose next canonical k-mer the DB counts
    highest (one 512 B row fetch per candidate via the jitted
    key-parts kernel). Deterministic per database.

    Why: the all-A synthetic warmup read almost never finds an anchor
    (no poly-A mer in a real table), so the correction path past
    anchoring — the sibling sweep, the extension loop and its deeper
    lane-drained levels — compiled lazily on the FIRST real request,
    ~4 s of warm-cache compiles inside the serve watchdog's budget
    (ROADMAP known gap). A read whose k-mers the DB holds anchors and
    extends like real traffic, so `warmup()` pays those compiles
    before the port opens.

    Raises RuntimeError on an empty table (callers fall back to
    all-A)."""
    k = meta.k
    if length < k:
        raise RuntimeError(f"length {length} is below k={k}")
    rows = state.rows
    n_sample = min(int(meta.rows), int(sample_rows))
    # slice from row 0 so the sampled rows keep their global bucket
    # addresses — tile_iterate reconstructs keys from the row index
    chunk = np.asarray(rows[:n_sample])
    khi, klo, vals = ctable.tile_iterate(
        ctable.TileState(chunk), meta)
    if len(vals) == 0:
        raise RuntimeError("no occupied entries in the sampled rows")
    best = int(np.argmax(vals >> 1))
    seq = mer.unpack_kmer(int(khi[best]), int(klo[best]), k)

    def count(chi: int, clo: int) -> int:
        # one jitted key-parts dispatch + one 512 B row fetch; the
        # entry-layout match itself lives in ctable.tile_row_lookup.
        # Reuses the module-level _tile_parts_jit executable (meta
        # static) instead of re-jitting a per-call lambda — watchdog
        # rebuilds and /reload warmups hit the warm cache instead of
        # churning one fresh executable per representative_read
        # (COMPILE_BUDGET, ISSUE 15)
        addr, rlo, rhi, _p0 = jax.device_get(ctable._tile_parts_jit(
            meta,
            jnp.asarray([np.uint32(chi)]), jnp.asarray([np.uint32(clo)])))
        return ctable.tile_row_lookup(
            np.asarray(rows[int(addr[0])]), meta, rlo[0], rhi[0]) >> 1

    while len(seq) < length:
        tail = seq[-(k - 1):]
        best_base, best_count = None, 0
        for b in "ACGT":
            fh, fl = mer.pack_kmer(tail + b, k)
            chi, clo = mer.canonical_py(fh, fl, k)
            c = count(chi, clo)
            if c > best_count:
                best_base, best_count = b, c
        # off the end of the sampled contigs: keep the read length
        # honest with a deterministic filler (its mers are absent,
        # which simply ends the anchored run like a real read end)
        seq += best_base if best_base else "ACGT"[len(seq) & 3]
    return seq[:length]
