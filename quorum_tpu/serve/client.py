"""Minimal stdlib client for the correction service, plus the
`quorum-serve-bench` closed-loop load generator.

`ServeClient` speaks the tiny HTTP surface of serve/server.py with
http.client only — no dependencies — so tests, tooling, and the bench
share one implementation of the protocol (headers, deadline
forwarding, 429/503 Retry-After handling).

The bench is closed-loop: `--concurrency` workers each post
`--reads-per-request` reads and wait for the answer before posting
again, the standard shape for measuring a service's latency/throughput
trade-off under admission control. Results print as the repo's
bench-style metric lines (telemetry.metric_line), so
`tools/metrics_check.py` can gate a bench run's output like any other
artifact.
"""

from __future__ import annotations

import dataclasses
import gzip as gzip_mod
import http.client
import json
import threading
import time


@dataclasses.dataclass
class ServeResult:
    """One /correct exchange. `status` is the HTTP code; `fa`/`log`
    are the corrected-FASTA and skip-log texts (empty unless 200).
    `request_id` echoes the server's `X-Quorum-Request-Id` (every
    response carries one); `phases` is the server-side phase
    breakdown from `X-Quorum-Phases` (admission/queue/device/hedge/
    render/total µs, lane, bisected/hedged — 200 responses only);
    `quality` is the per-request correction-quality summary from
    `X-Quorum-Quality` (reads/corrected/skipped/subs/truncations,
    ISSUE 17 — 200 responses only; sums across requests reconcile
    with the server's final metrics document)."""

    status: int
    fa: str = ""
    log: str = ""
    reads: int = 0
    corrected: int = 0
    skipped: int = 0
    retry_after_s: float = 0.0
    error: str = ""
    request_id: str = ""
    phases: dict | None = None
    quality: dict | None = None


def _parse_json_header(resp, name: str) -> dict | None:
    raw = resp.headers.get(name)
    if not raw:
        return None
    try:
        doc = json.loads(raw)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def _parse_phases(resp) -> dict | None:
    return _parse_json_header(resp, "X-Quorum-Phases")


class ServeClient:
    """One server, many sequential requests (per instance; use one
    instance per thread — http.client connections are not
    thread-safe)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8100,
                 timeout: float = 600.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    def _request(self, method: str, path: str, body: bytes = b"",
                 headers: dict | None = None,
                 gzip_body: bool = False):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        hdrs = dict(headers or {})
        hdrs.setdefault("Accept-Encoding", "gzip")
        if gzip_body and body:
            body = gzip_mod.compress(body, compresslevel=1)
            hdrs["Content-Encoding"] = "gzip"
        try:
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            if (resp.headers.get("Content-Encoding", "")
                    .lower() == "gzip"):
                data = gzip_mod.decompress(data)
            return resp, data
        finally:
            conn.close()

    def correct(self, fastq_text: str | bytes,
                deadline_ms: float | None = None,
                want_log: bool = False,
                priority: str | None = None,
                client_id: str | None = None,
                request_id: str | None = None,
                gzip_body: bool = False) -> ServeResult:
        """POST /correct. Returns a ServeResult whatever the status —
        callers branch on `.status` (200/429/503/504/...).
        `priority` stamps X-Quorum-Priority (interactive|bulk),
        `client_id` stamps X-Quorum-Client (the quota identity), and
        `request_id` stamps X-Quorum-Request-Id (the trace identity;
        the server generates one when absent — either way the
        response's id lands in `ServeResult.request_id`).
        `gzip_body=True` gzips the request body (Content-Encoding:
        gzip); responses are transparently un-gzipped either way."""
        body = (fastq_text.encode()
                if isinstance(fastq_text, str) else fastq_text)
        path = "/correct" + ("?log=1" if want_log else "")
        headers = {"Content-Type": "text/plain"}
        if deadline_ms is not None:
            headers["X-Quorum-Deadline-Ms"] = str(deadline_ms)
        if priority is not None:
            headers["X-Quorum-Priority"] = priority
        if client_id is not None:
            headers["X-Quorum-Client"] = client_id
        if request_id is not None:
            headers["X-Quorum-Request-Id"] = request_id
        resp, data = self._request("POST", path, body, headers,
                                   gzip_body=gzip_body)
        rid = resp.headers.get("X-Quorum-Request-Id", "")
        if resp.status != 200:
            retry = float(resp.headers.get("Retry-After", 0) or 0)
            err = ""
            try:
                err = json.loads(data.decode() or "{}").get("error", "")
            except ValueError:
                pass
            return ServeResult(status=resp.status, retry_after_s=retry,
                               error=err, request_id=rid)
        phases = _parse_phases(resp)
        qual = _parse_json_header(resp, "X-Quorum-Quality")
        if want_log:
            doc = json.loads(data.decode())
            return ServeResult(status=200, fa=doc["fa"], log=doc["log"],
                               reads=doc["reads"],
                               corrected=doc["corrected"],
                               skipped=doc["skipped"],
                               request_id=rid, phases=phases,
                               quality=qual)
        return ServeResult(
            status=200, fa=data.decode(),
            reads=int(resp.headers.get("X-Quorum-Reads", 0)),
            corrected=int(resp.headers.get("X-Quorum-Corrected", 0)),
            skipped=int(resp.headers.get("X-Quorum-Skipped", 0)),
            request_id=rid, phases=phases, quality=qual)

    def correct_with_retry(self, fastq_text: str | bytes,
                           deadline_ms: float | None = None,
                           want_log: bool = False,
                           max_attempts: int = 6,
                           base_backoff_s: float = 0.1,
                           max_backoff_s: float = 5.0,
                           retry_statuses=(429, 503),
                           priority: str | None = None,
                           client_id: str | None = None,
                           gzip_body: bool = False,
                           sleep=time.sleep) -> ServeResult:
        """`correct()` with polite retries on 429/503: the server's
        already-parsed Retry-After is honored when present, combined
        with capped-exponential backoff (the sleep is the larger of
        the two, capped at `max_backoff_s`) so a missing or tiny hint
        still backs off, and a huge one cannot stall the client past
        the cap. Any other status (200, 400, 500, 504, ...) returns
        immediately; after `max_attempts` the last rejection is
        returned as-is. `sleep` is injectable for tests."""
        backoff = base_backoff_s
        res = self.correct(fastq_text, deadline_ms=deadline_ms,
                           want_log=want_log, priority=priority,
                           client_id=client_id, gzip_body=gzip_body)
        for _ in range(max_attempts - 1):
            if res.status not in retry_statuses:
                return res
            sleep(min(max(res.retry_after_s, backoff), max_backoff_s))
            backoff = min(backoff * 2, max_backoff_s)
            res = self.correct(fastq_text, deadline_ms=deadline_ms,
                               want_log=want_log, priority=priority,
                               client_id=client_id,
                               gzip_body=gzip_body)
        return res

    def reload(self, params: dict | None = None) -> tuple[int, dict]:
        """POST /reload — (status_code, body). 200 carries the new
        engine generation; any failure left the old engine serving."""
        body = json.dumps(params or {}).encode()
        resp, data = self._request(
            "POST", "/reload", body,
            {"Content-Type": "application/json"})
        try:
            doc = json.loads(data.decode() or "{}")
        except ValueError:
            doc = {}
        return resp.status, doc

    def ingest(self, fastq_text: str | bytes,
               seq: int | None = None,
               gzip_body: bool = False) -> tuple[int, dict]:
        """POST /ingest — (status_code, ack). `seq` stamps
        X-Quorum-Ingest-Seq (the at-least-once dedupe identity: a
        retransmit of an already-applied seq acks `duplicate: true`
        without re-counting); omit it to let the server assign the
        next one. 200 acks carry seq/reads/cursor/generation."""
        body = (fastq_text.encode()
                if isinstance(fastq_text, str) else fastq_text)
        headers = {"Content-Type": "text/plain"}
        if seq is not None:
            headers["X-Quorum-Ingest-Seq"] = str(seq)
        resp, data = self._request("POST", "/ingest", body, headers,
                                   gzip_body=gzip_body)
        try:
            doc = json.loads(data.decode() or "{}")
        except ValueError:
            doc = {}
        if resp.status != 200 and "retry_after_s" not in doc:
            retry = float(resp.headers.get("Retry-After", 0) or 0)
            if retry:
                doc["retry_after_s"] = retry
        return resp.status, doc

    def epoch(self) -> tuple[int, dict]:
        """POST /epoch — force an epoch boundary now (seal + swap).
        (status_code, body); 200 carries the new epoch/generation."""
        resp, data = self._request("POST", "/epoch")
        try:
            doc = json.loads(data.decode() or "{}")
        except ValueError:
            doc = {}
        return resp.status, doc

    def healthz(self) -> dict:
        resp, data = self._request("GET", "/healthz")
        if resp.status != 200:
            raise RuntimeError(f"/healthz -> {resp.status}")
        return json.loads(data.decode())

    def healthz_full(self) -> tuple[int, dict]:
        """GET /healthz without raising on 503 — (status_code, body).
        The unhealthy flip (max consecutive engine failures) answers
        503 with the same JSON body."""
        resp, data = self._request("GET", "/healthz")
        return resp.status, json.loads(data.decode())

    def metrics_text(self) -> str:
        resp, data = self._request("GET", "/metrics")
        if resp.status != 200:
            raise RuntimeError(f"/metrics -> {resp.status}")
        return data.decode()

    def quiesce(self) -> dict:
        resp, data = self._request("POST", "/quiesce")
        if resp.status != 200:
            raise RuntimeError(f"/quiesce -> {resp.status}")
        return json.loads(data.decode())


# ---------------------------------------------------------------------------
# quorum-serve-bench
# ---------------------------------------------------------------------------

def _percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


_QKEYS = ("reads", "corrected", "skipped", "subs", "t3", "t5")


def _ingest_bench(args, records, bodies, metric_line) -> int:
    """`--ingest` mode: the main thread streams the input file as
    seq-stamped /ingest chunks while `--concurrency` workers
    interleave /correct requests against whichever epoch is serving.
    Each observed engine-generation change (an epoch swap) closes a
    `serve_bench_ingest_epoch` ledger line carrying the q_* quality
    fields accumulated while that epoch served — the
    corrections-per-read ramp is readable straight off the ledger."""
    import sys

    chunk_reads = max(1, args.chunk_reads)
    chunks: list[bytes] = []
    for i in range(0, len(records), chunk_reads):
        parts = []
        for hdr, seq, qual in records[i:i + chunk_reads]:
            if qual:
                parts.append(f"@{hdr}\n{seq.decode()}\n+\n"
                             f"{qual.decode()}\n")
            else:
                parts.append(f">{hdr}\n{seq.decode()}\n")
        chunks.append("".join(parts).encode())

    stop = threading.Event()
    lock = threading.Lock()
    q_epoch = dict.fromkeys(_QKEYS, 0)
    q_total = dict.fromkeys(_QKEYS, 0)
    corr: dict[int, int] = {}
    errors = [0]

    def corrector():
        c = ServeClient(args.host, args.port)
        rr = 0
        while not stop.is_set():
            body = bodies[rr % len(bodies)]
            rr += 1
            try:
                res = c.correct(body, deadline_ms=args.deadline_ms,
                                priority=args.priority,
                                client_id=args.client_id,
                                gzip_body=args.gzip)
            except OSError:
                with lock:
                    errors[0] += 1
                time.sleep(0.05)
                continue
            with lock:
                corr[res.status] = corr.get(res.status, 0) + 1
                if res.status == 200 and res.quality:
                    for k in _QKEYS:
                        v = int(res.quality.get(k, 0))
                        q_epoch[k] += v
                        q_total[k] += v
            if res.status == 429:
                time.sleep(max(0.05, res.retry_after_s))

    def flush_epoch(gen: int, reads_at: int) -> None:
        # close the ledger line for the generation that just stopped
        # serving: its q_* fields are everything corrected on it
        with lock:
            snap = dict(q_epoch)
            for k in _QKEYS:
                q_epoch[k] = 0
        print(metric_line(
            "serve_bench_ingest_epoch", generation=gen,
            reads_ingested=reads_at,
            **{f"q_{k}": snap[k] for k in _QKEYS}))

    client = ServeClient(args.host, args.port)
    workers = [threading.Thread(target=corrector, daemon=True)
               for _ in range(max(1, args.concurrency))]
    for t in workers:
        t.start()
    t_start = time.perf_counter()
    gen_seen: int | None = None
    reads_sent = chunks_ok = dupes = 0
    try:
        for seq_no, chunk in enumerate(chunks):
            while True:
                try:
                    status, ack = client.ingest(chunk, seq=seq_no,
                                                gzip_body=args.gzip)
                except OSError:
                    time.sleep(0.1)
                    continue
                if status == 200:
                    break
                if status == 429:
                    time.sleep(max(0.05, float(
                        ack.get("retry_after_s", 0) or 0)))
                    continue
                print(f"ingest seq {seq_no} -> {status}: "
                      f"{ack.get('error', '')}", file=sys.stderr)
                return 1
            chunks_ok += 1
            if ack.get("duplicate"):
                dupes += 1
            else:
                reads_sent += int(ack.get("reads", 0))
            gen = int(ack.get("generation", 0))
            if gen_seen is None:
                gen_seen = gen
            elif gen != gen_seen:
                flush_epoch(gen_seen, reads_sent)
                gen_seen = gen
        # seal the tail into a final epoch so the run's ledger covers
        # every ingested read
        status, doc = client.epoch()
        if status == 200 and gen_seen is not None:
            flush_epoch(gen_seen, reads_sent)
    finally:
        stop.set()
        for t in workers:
            t.join()
    wall = time.perf_counter() - t_start
    live: dict = {}
    try:
        live = client.healthz().get("live", {}) or {}
    except (OSError, RuntimeError, ValueError):
        pass
    print(metric_line(
        "serve_bench_ingest", chunks=len(chunks), chunks_ok=chunks_ok,
        chunk_reads=chunk_reads, duplicates=dupes,
        reads_ingested=reads_sent, wall_s=round(wall, 4),
        reads_per_s=(round(reads_sent / wall, 2) if wall > 0 else 0),
        epoch=int(live.get("epoch", 0)),
        coverage=round(float(live.get("coverage", 0.0)), 4),
        floor=int(live.get("floor", 1)),
        corrections_ok=corr.get(200, 0),
        corrections_rejected=corr.get(429, 0),
        transport_errors=errors[0],
        **{f"q_{k}": q_total[k] for k in _QKEYS}))
    return 0 if chunks_ok == len(chunks) else 1


def bench_main(argv=None) -> int:
    """Closed-loop load generation against a running quorum-serve."""
    import argparse
    import sys

    from ..io import fastq as fastq_mod
    from ..telemetry import metric_line

    p = argparse.ArgumentParser(
        prog="quorum-serve-bench",
        description="Closed-loop load generator for quorum-serve: N "
                    "workers post FASTQ slices and wait for each "
                    "answer; prints latency/throughput metric lines.")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("-c", "--concurrency", type=int, default=4,
                   help="Closed-loop workers (default 4)")
    p.add_argument("-n", "--requests", type=int, default=64,
                   help="Total requests to send (default 64)")
    p.add_argument("-r", "--reads-per-request", type=int, default=16,
                   help="Reads per request body (default 16)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="Per-request deadline forwarded to the server")
    p.add_argument("--retry-429", action="store_true",
                   help="Honor Retry-After and retry rejected "
                        "requests instead of counting and moving on")
    p.add_argument("--retry", action="store_true",
                   help="Use ServeClient.correct_with_retry: retry "
                        "429 AND 503 with Retry-After honored under "
                        "capped-exponential backoff (supersedes "
                        "--retry-429)")
    p.add_argument("--priority", choices=("interactive", "bulk"),
                   default=None,
                   help="Stamp X-Quorum-Priority on every request")
    p.add_argument("--client-id", default=None,
                   help="Stamp X-Quorum-Client on every request "
                        "(the quota identity)")
    p.add_argument("--ingest", action="store_true",
                   help="Live-ingestion mode: stream the input file "
                        "as seq-stamped /ingest chunks while the "
                        "workers interleave /correct requests; "
                        "ledgers q_* quality fields per epoch swap "
                        "(requires a quorum-serve started with "
                        "--ingest)")
    p.add_argument("--chunk-reads", type=int, default=64,
                   help="Reads per /ingest chunk in --ingest mode "
                        "(default 64)")
    p.add_argument("--gzip", action="store_true",
                   help="gzip request bodies (Content-Encoding: "
                        "gzip); responses are un-gzipped either way")
    p.add_argument("sequence", help="FASTQ/FASTA file to draw reads from")
    args = p.parse_args(argv)

    # pre-render request bodies: round-robin the file's records into
    # --reads-per-request payloads (wrapping if the file is short)
    records = list(fastq_mod.iter_records([args.sequence]))
    if not records:
        print("no reads in input", file=sys.stderr)
        return 1
    bodies: list[bytes] = []
    rr = 0
    for _ in range(args.requests):
        parts = []
        for _ in range(args.reads_per_request):
            hdr, seq, qual = records[rr % len(records)]
            rr += 1
            if qual:
                parts.append(f"@{hdr}\n{seq.decode()}\n+\n"
                             f"{qual.decode()}\n")
            else:
                parts.append(f">{hdr}\n{seq.decode()}\n")
        bodies.append("".join(parts).encode())

    if args.ingest:
        return _ingest_bench(args, records, bodies, metric_line)

    next_i = [0]
    lock = threading.Lock()
    lat: list[float] = []
    phases: list[dict] = []  # server-side breakdown per 200
    outcomes = {200: 0, 429: 0, 503: 0, 504: 0}
    reads_done = [0]
    errors = [0]

    def worker():
        client = ServeClient(args.host, args.port)
        while True:
            with lock:
                i = next_i[0]
                if i >= len(bodies):
                    return
                next_i[0] += 1
            body = bodies[i]
            while True:
                t0 = time.perf_counter()
                try:
                    if args.retry:
                        res = client.correct_with_retry(
                            body, deadline_ms=args.deadline_ms,
                            priority=args.priority,
                            client_id=args.client_id,
                            gzip_body=args.gzip)
                    else:
                        res = client.correct(
                            body, deadline_ms=args.deadline_ms,
                            priority=args.priority,
                            client_id=args.client_id,
                            gzip_body=args.gzip)
                except OSError:
                    with lock:
                        errors[0] += 1
                    break
                dt = time.perf_counter() - t0
                with lock:
                    outcomes[res.status] = outcomes.get(res.status, 0) + 1
                    if res.status == 200:
                        lat.append(dt)
                        reads_done[0] += res.reads
                        if res.phases:
                            phases.append(res.phases)
                if (res.status == 429 and args.retry_429
                        and not args.retry):
                    time.sleep(max(0.05, res.retry_after_s))
                    continue
                break

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, args.concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    lat.sort()
    print(metric_line(
        "serve_bench", requests=args.requests,
        concurrency=args.concurrency,
        reads_per_request=args.reads_per_request,
        wall_s=round(wall, 4),
        ok=outcomes.get(200, 0), rejected=outcomes.get(429, 0),
        draining=outcomes.get(503, 0), deadline=outcomes.get(504, 0),
        transport_errors=errors[0],
        reads=reads_done[0],
        reads_per_s=round(reads_done[0] / wall, 2) if wall > 0 else 0,
        requests_per_s=(round(len(lat) / wall, 2) if wall > 0 else 0),
        latency_p50_ms=round(_percentile(lat, 50) * 1e3, 3),
        latency_p90_ms=round(_percentile(lat, 90) * 1e3, 3),
        latency_p99_ms=round(_percentile(lat, 99) * 1e3, 3)))
    if phases:
        # the server-side attribution (ISSUE 10): where each request's
        # time went INSIDE the server, from the X-Quorum-Phases header
        # alone — queue wait vs device time is visible client-side,
        # no server access needed
        fields = {}
        for key in ("admission_us", "queue_us", "device_us",
                    "hedge_us", "render_us", "total_us"):
            vals = sorted(float(p.get(key, 0)) for p in phases)
            fields[f"{key.removesuffix('_us')}_mean_ms"] = round(
                sum(vals) / len(vals) / 1e3, 3)
            fields[f"{key.removesuffix('_us')}_p90_ms"] = round(
                _percentile(vals, 90) / 1e3, 3)
        tot = sum(float(p.get("total_us", 0)) for p in phases)
        if tot > 0:
            for key in ("queue_us", "device_us"):
                share = sum(float(p.get(key, 0)) for p in phases) / tot
                fields[f"{key.removesuffix('_us')}_share"] = round(
                    share, 4)
        fields["bisected"] = sum(1 for p in phases if p.get("bisected"))
        fields["hedged"] = sum(1 for p in phases if p.get("hedged"))
        print(metric_line("serve_bench_phases", requests=len(phases),
                          **fields))
    return 0 if outcomes.get(200, 0) > 0 else 1


if __name__ == "__main__":
    import sys
    sys.exit(bench_main())
