"""Per-client admission quotas for the correction service.

Overload in the batcher is FIFO-shaped: the bounded queue sheds at
the door (429) but does not care WHO filled it, so one bulk client
saturating `--queue-requests` starves every interactive one. The
quota layer makes overload degrade by policy instead of queue order:
each client (the `X-Quorum-Client` request header) gets a standard
token bucket — `--quota-rps` tokens per second refill, `--quota-burst`
capacity — and a request that finds its bucket empty answers 429 with
a Retry-After derived from the actual refill time, before it ever
touches the shared queue.

Quotas are per *declared identity*: a request without the
`X-Quorum-Client` header is not quota-limited (there is no principal
to charge; the bounded queue still backstops it). A fleet fronted by
a load balancer stamps the header; abusive anonymous traffic is an
edge concern for the LB, not the correction engine.

The clock is injectable so tests drive refill deterministically.
"""

from __future__ import annotations

import threading
import time


class TokenBucketQuota:
    """One token bucket per client id, created on first sight.

    `admit(client)` costs one token. Buckets refill continuously at
    `rate_per_s` up to `burst`. The table is an LRU bounded at
    `max_clients`: every admit moves the client to the tail (dicts
    are insertion-ordered) and evicts from the head in O(1) — an
    evicted mid-drain client re-enters with a fresh bucket, trading a
    sliver of quota grace under an id flood for never scanning the
    table on the hot admission path.
    """

    def __init__(self, rate_per_s: float, burst: float | None = None,
                 max_clients: int = 10000, clock=time.monotonic):
        if rate_per_s <= 0:
            raise ValueError("quota rate must be > 0")
        self.rate = float(rate_per_s)
        self.burst = float(burst) if burst else max(1.0, self.rate)
        if self.burst < 1.0:
            raise ValueError("quota burst must be >= 1")
        self.max_clients = int(max_clients)
        self.clock = clock
        self._lock = threading.Lock()
        # client -> (tokens, last_refill); LRU order = dict order
        self._buckets: dict[str, tuple[float, float]] = {}

    def admit(self, client: str) -> tuple[bool, float]:
        """Charge one token to `client`. Returns (admitted,
        retry_after_s) — retry_after_s is 0 when admitted, else the
        time until the bucket holds a full token again."""
        now = self.clock()
        with self._lock:
            entry = self._buckets.pop(client, None)
            tokens, last = entry if entry else (self.burst, now)
            tokens = min(self.burst, tokens + (now - last) * self.rate)
            admitted = tokens >= 1.0
            if admitted:
                tokens -= 1.0
            self._buckets[client] = (tokens, now)  # LRU tail
            while len(self._buckets) > self.max_clients:
                self._buckets.pop(next(iter(self._buckets)))
            if admitted:
                return True, 0.0
            return False, (1.0 - tokens) / self.rate

    @property
    def clients(self) -> int:
        with self._lock:
            return len(self._buckets)
