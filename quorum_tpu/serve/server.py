"""The stdlib-HTTP front end of the correction service.

Same transport pattern as telemetry/export.py (ThreadingHTTPServer on
daemon threads, no dependencies), with the service semantics on top:

* ``POST /correct`` — body is FASTQ (or FASTA) text; the response is
  the corrected FASTA text, byte-identical to what
  ``quorum_error_correct_reads`` writes for the same reads, with the
  per-read counts in ``X-Quorum-Reads`` / ``X-Quorum-Corrected`` /
  ``X-Quorum-Skipped`` headers. ``?log=1`` switches to a JSON body
  ``{"fa":..., "log":..., "reads":..., "corrected":..., "skipped":...}``
  carrying the ``.log`` channel too. A per-request deadline comes
  from ``?deadline_ms=`` or the ``X-Quorum-Deadline-Ms`` header
  (default: the server's ``deadline_ms``).
* 429 + ``Retry-After`` when the batcher's bounded queue is full
  (admission control), 503 while draining, 504 past the deadline,
  400 on malformed FASTQ.
* ``GET /healthz`` — liveness JSON (status ok/draining, queue depth,
  uptime, totals).
* ``GET /metrics`` — the live Prometheus exposition, mounted on the
  same registry set as every other quorum endpoint
  (telemetry/export.render_live), so the serve counters and any
  in-process stage registries share one scrape.
* ``POST /quiesce`` — graceful drain: stop admitting, flush in-flight
  batches, then release ``serve_until_drained()`` so the CLI writes
  the final metrics document and exits. SIGTERM takes the same path.
* ``POST /ingest`` / ``POST /epoch`` — the live ingestion tier
  (ISSUE 18, serve/ingest.py): FASTQ chunks stream into a mutable
  LiveTable while /correct keeps serving from the last sealed epoch
  snapshot; /epoch forces a seal+swap outside the configured
  boundaries. 501 unless the CLI started with ``--ingest``.
* gzip transport both ways (stdlib): a request body with
  ``Content-Encoding: gzip`` is inflated with the size cap applied to
  the DECOMPRESSED payload; a response to a client advertising
  ``Accept-Encoding: gzip`` is compressed when big enough to win.

Resilience surface (ISSUE 7):

* **Priority lanes** — the ``X-Quorum-Priority`` header routes a
  request into the batcher's ``interactive`` (default) or ``bulk``
  lane; the dispatcher's weighted pop keeps interactive traffic
  flowing under a bulk backlog.
* **Per-client quotas** — with a `TokenBucketQuota` attached, each
  ``X-Quorum-Client`` identity is charged one token per request;
  an empty bucket answers 429 + Retry-After and
  ``quota_rejections_total`` before the request touches the shared
  queue. Requests without the header are not quota-limited (see
  serve/admission.py).
* ``POST /reload`` — hot swap of DB/contaminant/config on a running
  server: the JSON body (``{"db": ..., "contaminant": ...,
  "cutoff": ...}``, all optional) goes to the CLI-provided
  ``engine_builder``, which validates the new DB's header/k/bits
  BEFORE building (the PR-4 reuse check) and returns a warm engine;
  only then is the batcher's engine swapped (``reload_total``, new
  ``engine_generation``). ANY failure — unreadable header, k/bits
  mismatch, build error, injected ``serve.reload`` fault — rolls
  back: the old engine keeps answering, byte-identical
  (``reload_failures_total``). In-flight batches finish on the old
  engine either way.
* The ``serve.admit`` fault site fires at admission (chaos harness);
  an injected fault maps to a retryable 503.

Request tracing (ISSUE 10): every response echoes
``X-Quorum-Request-Id`` (client-stamped or generated), the id is
threaded through admission → lane → batch → engine step →
hedge/bisect telemetry, and each terminal status emits ONE
structured ``request`` lifecycle event with disjoint per-phase
durations (admission, per-lane queue wait, device step, hedge,
render — their sum is <= the end-to-end time). Successful responses
additionally carry the phase breakdown in ``X-Quorum-Phases`` (JSON),
so clients see queue wait vs device time without server access.
"""

from __future__ import annotations

import gzip as gzip_mod
import json
import threading
import time
import uuid
import zlib
from concurrent.futures import TimeoutError as FutureTimeout

from ..io import fastq
from ..telemetry import NULL, flight
from ..telemetry import export as export_mod
from ..telemetry import quality as quality_mod
from ..utils import faults
from ..utils.vlog import vlog
from .batcher import PRIORITIES, DeadlineExceeded, Draining, QueueFull

# a request body bigger than this is refused with 413 before parsing
# (an unbounded read would let one client exhaust host memory)
MAX_BODY_BYTES = 256 * 1024 * 1024

# responses below this size are sent uncompressed even to a client
# that accepts gzip: the header overhead beats the savings
GZIP_MIN_BYTES = 512


def request_id_for(headers) -> str:
    """The request's trace identity (ISSUE 10): an `X-Quorum-Request-
    Id` the client stamped, sanitized to printable ASCII and bounded —
    header echo must never become an injection surface — or a fresh
    16-hex id when absent/unusable. Commas are stripped too: batch
    events comma-join the victims' ids, so an id containing one would
    make that field unparseable. Every response carries it back, and
    the batcher threads it through lane/batch/hedge telemetry."""
    raw = (headers.get("X-Quorum-Request-Id") or "").strip()
    rid = "".join(c for c in raw
                  if 33 <= ord(c) <= 126 and c != ",")[:128]
    return rid or uuid.uuid4().hex[:16]


def parse_fastq_text(body: bytes) -> list[tuple[str, bytes, bytes]]:
    """Parse a request body as FASTQ/FASTA records via the same
    reader the offline pipeline uses (io/fastq._iter_one), so the
    service accepts exactly the inputs the CLI accepts."""
    import io as _io
    return list(fastq._iter_one(_io.BytesIO(body), "<request>"))


class CorrectionServer:
    """HTTP front end over a DynamicBatcher.

    `serve_until_drained()` blocks the calling thread until a drain
    completes (via `/quiesce`, SIGTERM -> `initiate_drain()`, or
    `close()`), which is when the caller should write final artifacts
    and exit — the CLI runs it under the observability() context
    manager so the final metrics document lands on every exit path.
    """

    def __init__(self, batcher, host: str = "127.0.0.1", port: int = 0,
                 deadline_ms: float | None = None, registry=NULL,
                 drain_grace_s: float = 30.0, quota=None,
                 engine_builder=None, alerts=None, ingest=None):
        import http.server

        self.batcher = batcher
        self.registry = registry
        # ingest dispatcher (serve/ingest.IngestDispatcher, ISSUE 18):
        # None = POST /ingest and /epoch answer 501
        self.ingest = ingest
        self.deadline_ms = deadline_ms
        self.drain_grace_s = drain_grace_s
        # admission quota (serve/admission.TokenBucketQuota or None)
        self.quota = quota
        # alert engine (telemetry/alerts.py, ISSUE 11): /healthz
        # DETAIL only — a burning SLO needs attention, not ejection,
        # so it never touches the liveness verdict
        self.alerts = alerts
        # engine_builder(params: dict) -> warm engine; validates the
        # new DB before building. None = /reload answers 501.
        self.engine_builder = engine_builder
        self._reload_lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._drained = threading.Event()
        self._drain_started = threading.Event()
        self._requests = 0
        self._req_lock = threading.Lock()
        # feature counters exist from setup so the final metrics
        # document carries the surface at value 0 (metrics_check
        # requires the names when meta declares the feature)
        if quota is not None:
            registry.counter("quota_rejections_total")
        if engine_builder is not None:
            registry.counter("reload_total")
            registry.counter("reload_failures_total")
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):  # noqa: N802 - http.server API
                self.request_id = request_id_for(self.headers)
                route = self.path.split("?")[0]
                if route == "/metrics":
                    body = export_mod.render_live().encode()
                    self._reply(200, body,
                                "text/plain; version=0.0.4; charset=utf-8")
                elif route == "/healthz":
                    h = outer.health()
                    # 503 once the batcher flips unhealthy (dispatcher
                    # gone, or --max-consecutive-failures device-step
                    # failures in a row): load balancers eject the
                    # replica instead of the process dying silently
                    self._reply_json(200 if h.get("healthy", True)
                                     else 503, h)
                elif route == "/debug/flight":
                    outer._handle_debug_flight(self)
                else:
                    self._reply_json(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802 - http.server API
                self.request_id = request_id_for(self.headers)
                route, _, query = self.path.partition("?")
                if route == "/correct":
                    outer._handle_correct(self, query)
                elif route == "/ingest":
                    outer._handle_ingest(self)
                elif route == "/epoch":
                    outer._handle_epoch(self)
                elif route == "/reload":
                    outer._handle_reload(self)
                elif route == "/quiesce":
                    vlog("Quiesce requested over HTTP")
                    outer.initiate_drain()
                    self._reply_json(200, {"status": "draining"})
                else:
                    self._reply_json(404, {"error": "not found"})

            # -- plumbing --------------------------------------------
            def _reply(self, code: int, body: bytes, ctype: str,
                       extra: dict | None = None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                # response compression (ISSUE 18 satellite): corrected
                # FASTA compresses ~4x, and the client opted in via
                # Accept-Encoding — tiny bodies skip it (header
                # overhead beats the savings)
                accept = (self.headers.get("Accept-Encoding")
                          or "").lower()
                if "gzip" in accept and len(body) >= GZIP_MIN_BYTES:
                    body = gzip_mod.compress(body, compresslevel=1)
                    self.send_header("Content-Encoding", "gzip")
                self.send_header("Content-Length", str(len(body)))
                # EVERY response echoes the request's trace identity
                # (generated when the client sent none), so a fleet's
                # logs and the server's lifecycle events join on it
                self.send_header("X-Quorum-Request-Id",
                                 getattr(self, "request_id", "-"))
                if self.close_connection:
                    # replies sent WITHOUT reading the request body
                    # (413, bad Content-Length) must kill the
                    # keep-alive connection — the unread bytes would
                    # be parsed as the next request line otherwise
                    self.send_header("Connection", "close")
                for k, v in (extra or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away; nothing to salvage

            def _reply_json(self, code: int, obj: dict,
                            extra: dict | None = None):
                self._reply(code, (json.dumps(obj) + "\n").encode(),
                            "application/json", extra)

            def log_message(self, *a):  # requests are per-batch noise
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="quorum-serve-http", daemon=True)
        self._thread.start()
        registry.set_meta(serve_port=self.port)
        vlog("quorum-serve listening on ", host, ":", self.port)

    # -- request handling -------------------------------------------------
    @staticmethod
    def _read_body(handler, limit: int) -> bytes | int:
        """Validate Content-Length and read the request body. A bad
        or negative length (negative means read-to-EOF — it would
        block the handler thread forever on keep-alive) answers 400,
        an oversized one 413; both kill the keep-alive connection
        (body left unread) and return the status already sent, so the
        caller's lifecycle event carries the real code."""
        try:
            length = int(handler.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if length < 0:
            handler.close_connection = True  # body left unread
            handler._reply_json(400, {"error": "bad Content-Length"})
            return 400
        if length > limit:
            handler.close_connection = True  # body left unread
            handler._reply_json(413, {"error": "request body too large"})
            return 413
        return handler.rfile.read(length)

    @staticmethod
    def _decode_body(handler, body: bytes, limit: int) -> bytes | int:
        """Apply the request's Content-Encoding (ISSUE 18 satellite:
        gzip, stdlib only). The size cap applies to the DECOMPRESSED
        payload — a 1 MiB bomb expanding past `limit` answers 413
        without ever materializing the expansion; truncated or garbage
        gzip answers 400, an unknown coding 415. Like _read_body,
        returns the bytes or the status already sent."""
        enc = (handler.headers.get("Content-Encoding")
               or "").strip().lower()
        if enc in ("", "identity"):
            return body
        if enc != "gzip":
            handler._reply_json(
                415, {"error": f"unsupported Content-Encoding "
                               f"{enc!r} (gzip or identity)"})
            return 415
        d = zlib.decompressobj(16 + zlib.MAX_WBITS)
        try:
            data = d.decompress(body, limit + 1)
        except zlib.error as e:
            handler._reply_json(400, {"error": f"bad gzip body: {e}"})
            return 400
        if len(data) > limit or d.unconsumed_tail:
            handler._reply_json(
                413, {"error": "decompressed body too large"})
            return 413
        if not d.eof:
            handler._reply_json(
                400, {"error": "bad gzip body: truncated stream"})
            return 400
        return data

    def _lifecycle(self, rid: str, lane: str, status: int, t_req0: float,
                   reads: int = 0, req=None, admission_us: int | None = None,
                   render_us: int = 0, quality: dict | None = None) -> dict:
        """Emit the request's ONE lifecycle event (ISSUE 10): every
        terminal status, with the phase ledger when the request got
        far enough to have one. Phases are disjoint sub-intervals of
        the request's wall time, so their sum is <= total_us. Returns
        the phase dict (the 200 path reuses it for the
        `X-Quorum-Phases` response header). `quality` (the 200 path's
        per-request tally, quality.summarize_results) rides along as
        q_* fields, so the request ledger attributes corrections per
        request the way it already attributes time (ISSUE 17)."""
        total_us = int((time.perf_counter() - t_req0) * 1e6)
        ph = {"admission_us": (admission_us if admission_us is not None
                               else total_us),
              "queue_us": 0, "device_us": 0, "hedge_us": 0,
              "render_us": render_us, "total_us": total_us,
              "lane": lane, "bisected": False, "hedged": False}
        if req is not None:
            ph.update(queue_us=int(req.lane_wait_us),
                      device_us=int(req.device_us),
                      hedge_us=int(req.hedge_us),
                      lane=req.lane, bisected=bool(req.bisected),
                      hedged=bool(req.hedged))
        qf = {}
        if quality is not None:
            qf = {"q_corrected": quality["corrected"],
                  "q_skipped": quality["skipped"],
                  "q_subs": quality["subs"],
                  "q_t3": quality["t3"], "q_t5": quality["t5"]}
        self.registry.event("request", request_id=rid, status=status,
                            reads=reads, **ph, **qf)
        if status == 200 and self.registry.enabled:
            # the latency-SLO feed (telemetry/alerts.py): end-to-end
            # time of SERVED requests, log-quantized so the exact-
            # count histogram never trips its cardinality guard the
            # way raw request_us does (failures/rejects are the
            # availability rule's business, so only 200s count here)
            from ..telemetry.alerts import latency_bucket_us
            self.registry.histogram("request_e2e_bucket_us").observe(
                latency_bucket_us(total_us))
        return ph

    def _handle_correct(self, handler, query: str) -> None:
        reg = self.registry
        rid = handler.request_id
        t_req0 = time.perf_counter()
        lane = "interactive"
        params = _parse_query(query)
        if handler.headers.get("Transfer-Encoding"):
            # we only read Content-Length bodies; silently treating a
            # chunked body as empty would answer 200-empty and leave
            # the chunk bytes to desync the keep-alive connection
            handler.close_connection = True  # body left unread
            handler._reply_json(411, {"error": "Content-Length required"})
            self._lifecycle(rid, lane, 411, t_req0)
            return
        body = self._read_body(handler, MAX_BODY_BYTES)
        if isinstance(body, int):
            # _read_body already answered (400 or 413)
            self._lifecycle(rid, lane, body, t_req0)
            return
        body = self._decode_body(handler, body, MAX_BODY_BYTES)
        if isinstance(body, int):
            self._lifecycle(rid, lane, body, t_req0)
            return
        priority = (handler.headers.get("X-Quorum-Priority")
                    or "interactive").strip().lower()
        if priority not in PRIORITIES:
            handler._reply_json(
                400, {"error": f"bad X-Quorum-Priority {priority!r} "
                               f"(one of {PRIORITIES})"})
            self._lifecycle(rid, lane, 400, t_req0)
            return
        lane = priority
        try:
            # chaos-harness site: a plan can fail the Nth admission to
            # prove overload/fault handling at the door (utils/faults)
            faults.inject("serve.admit")
        except Exception as e:  # noqa: BLE001 - injected faults only
            reg.counter("requests_rejected_admission").inc()
            handler._reply_json(503, {"error": str(e)},
                                extra={"Retry-After": 1})
            self._lifecycle(rid, lane, 503, t_req0)
            return
        client_id = handler.headers.get("X-Quorum-Client")
        if self.quota is not None and client_id:
            ok, retry_in = self.quota.admit(client_id)
            if not ok:
                reg.counter("quota_rejections_total").inc()
                handler._reply_json(
                    429, {"error": "client quota exceeded",
                          "retry_after_s": round(retry_in, 3)},
                    extra={"Retry-After": max(1, int(retry_in + 0.999))})
                self._lifecycle(rid, lane, 429, t_req0)
                return
        deadline_ms = self.deadline_ms
        hdr_deadline = (params.get("deadline_ms")
                        or handler.headers.get("X-Quorum-Deadline-Ms"))
        if hdr_deadline is not None:
            try:
                deadline_ms = float(hdr_deadline)
            except ValueError:
                handler._reply_json(400, {"error": "bad deadline_ms"})
                self._lifecycle(rid, lane, 400, t_req0)
                return
        try:
            records = parse_fastq_text(body)
        except (ValueError, UnicodeDecodeError) as e:
            reg.counter("requests_bad_input").inc()
            handler._reply_json(400, {"error": str(e)})
            self._lifecycle(rid, lane, 400, t_req0)
            return
        t0 = time.perf_counter()
        try:
            fut = self.batcher.submit(
                records,
                deadline_s=(deadline_ms / 1000.0
                            if deadline_ms is not None else None),
                priority=priority, request_id=rid)
        except QueueFull as e:
            handler._reply_json(
                429, {"error": "queue full",
                      "retry_after_s": e.retry_after},
                extra={"Retry-After": max(1, int(round(e.retry_after)))})
            self._lifecycle(rid, lane, 429, t_req0, reads=len(records))
            return
        except Draining:
            handler._reply_json(503, {"error": "draining"},
                                extra={"Retry-After": 1})
            self._lifecycle(rid, lane, 503, t_req0, reads=len(records))
            return
        # admission phase ends where the queue phase begins: the
        # ledger's own enqueue stamp, so the phases stay disjoint
        req = getattr(fut, "request", None)
        admission_us = int(((req.t_enq if req is not None else t0)
                            - t_req0) * 1e6)
        # the wall timeout backstops the batcher's deadline handling:
        # a request admitted but stuck behind a wedged device step
        # still gets its 504 (and its late result is discarded)
        wall = (deadline_ms / 1000.0 + 1.0
                if deadline_ms is not None else None)
        try:
            results = fut.result(timeout=wall)
        except DeadlineExceeded:
            handler._reply_json(504, {"error": "deadline exceeded"})
            self._lifecycle(rid, lane, 504, t_req0, reads=len(records),
                            req=req, admission_us=admission_us)
            return
        except FutureTimeout:
            fut.cancel()
            reg.counter("requests_late").inc()
            handler._reply_json(504, {"error": "deadline exceeded"})
            # unlike every other terminal path, the future is NOT
            # resolved here — the request may be mid-step, so the
            # ledger read below is best-effort (single int fields,
            # safe under the GIL, but device/hedge time still
            # accruing on the dispatcher thread can lag)
            self._lifecycle(rid, lane, 504, t_req0, reads=len(records),
                            req=req, admission_us=admission_us)
            return
        except BaseException as e:  # noqa: BLE001 - surfaced as 500
            handler._reply_json(500, {"error": str(e)})
            self._lifecycle(rid, lane, 500, t_req0, reads=len(records),
                            req=req, admission_us=admission_us)
            return
        with self._req_lock:
            self._requests += 1
        if reg.enabled:
            reg.histogram("request_us").observe(
                int((time.perf_counter() - t0) * 1e6))
            reg.histogram("request_reads").observe(len(records))
        t_render = time.perf_counter()
        fa = "".join(r[0] for r in results)
        log = "".join(r[1] for r in results)
        corrected = sum(1 for r in results if r[0] and not r[1])
        skipped = sum(1 for r in results if r[1])
        # the per-request quality tally (ISSUE 17): decoded from the
        # same rendered text the client receives, so the header sums
        # reconcile exactly against the serve document's outcome
        # counters (the parity telemetry_smoke asserts)
        q = quality_mod.summarize_results(results)
        render_us = int((time.perf_counter() - t_render) * 1e6)
        ph = self._lifecycle(rid, lane, 200, t_req0, reads=len(records),
                             req=req, admission_us=admission_us,
                             render_us=render_us, quality=q)
        counts = {"X-Quorum-Reads": len(records),
                  "X-Quorum-Corrected": corrected,
                  "X-Quorum-Skipped": skipped,
                  # the server-side phase breakdown, client-readable:
                  # quorum-serve-bench reports queue wait vs device
                  # time per request from this header alone
                  "X-Quorum-Phases": json.dumps(
                      ph, separators=(",", ":")),
                  # the per-request quality summary, client-readable
                  "X-Quorum-Quality": json.dumps(
                      q, separators=(",", ":"), sort_keys=True)}
        if _flag(params, "log"):
            handler._reply_json(200, {
                "fa": fa, "log": log, "reads": len(records),
                "corrected": corrected, "skipped": skipped}, extra=counts)
        else:
            handler._reply(200, fa.encode(), "text/plain; charset=utf-8",
                           extra=counts)

    # -- live ingestion (ISSUE 18) -----------------------------------------
    def _handle_ingest(self, handler) -> None:
        """POST /ingest: FASTQ chunk into the live table. The handler
        thread blocks until the ingest dispatcher's worker inserted
        the chunk (backpressure), then acks with the committed cursor.
        An `X-Quorum-Ingest-Seq` header makes the chunk idempotent:
        after a kill→resume, re-sent chunks at-or-below the restored
        cursor ack as duplicates without touching the table."""
        reg = self.registry
        rid = handler.request_id
        if self.ingest is None:
            handler._reply_json(
                501, {"error": "live ingestion not configured "
                               "(start quorum-serve with --ingest)"})
            return
        if handler.headers.get("Transfer-Encoding"):
            handler.close_connection = True  # body left unread
            handler._reply_json(411, {"error": "Content-Length "
                                               "required"})
            return
        body = self._read_body(handler, MAX_BODY_BYTES)
        if isinstance(body, int):
            return
        body = self._decode_body(handler, body, MAX_BODY_BYTES)
        if isinstance(body, int):
            return
        seq = handler.headers.get("X-Quorum-Ingest-Seq")
        if seq is not None:
            try:
                seq = int(seq)
            except ValueError:
                handler._reply_json(
                    400, {"error": "bad X-Quorum-Ingest-Seq"})
                return
        try:
            records = parse_fastq_text(body)
        except (ValueError, UnicodeDecodeError) as e:
            reg.counter("requests_bad_input").inc()
            handler._reply_json(400, {"error": str(e)})
            return
        try:
            ack = self.ingest.submit_chunk(records, seq=seq)
        except QueueFull as e:
            handler._reply_json(
                429, {"error": "ingest queue full",
                      "retry_after_s": e.retry_after},
                extra={"Retry-After": max(1, int(round(e.retry_after)))})
            return
        except Draining:
            handler._reply_json(503, {"error": "draining"},
                                extra={"Retry-After": 1})
            return
        except Exception as e:  # noqa: BLE001 - surfaced as 500
            reg.event("ingest_failed", request_id=rid, error=str(e))
            handler._reply_json(500, {"error": str(e)})
            return
        ack["generation"] = int(getattr(self.batcher, "generation", 0))
        handler._reply_json(200, ack)

    def _handle_epoch(self, handler) -> None:
        """POST /epoch: force an epoch seal+swap now, outside the
        --epoch-reads / --epoch-interval-s boundaries (the end-of-run
        'flush everything ingested into the serving table' call)."""
        if self.ingest is None:
            handler._reply_json(
                501, {"error": "live ingestion not configured"})
            return
        body = self._read_body(handler, 1 << 20)
        if isinstance(body, int):
            return
        try:
            res = self.ingest.force_epoch()
        except Draining:
            handler._reply_json(503, {"error": "draining"},
                                extra={"Retry-After": 1})
            return
        handler._reply_json(200 if res.get("ok") else 500, res)

    # -- hot reload --------------------------------------------------------
    def _handle_reload(self, handler) -> None:
        """POST /reload: build a replacement engine from the JSON body
        (via the CLI's engine_builder, which validates the new DB's
        header/k/bits first), then atomically swap it in. The swap is
        all-or-nothing: any failure before it leaves the OLD engine
        serving byte-identical answers (rollback is the absence of the
        swap), and in-flight batches finish on the old engine even
        when it succeeds (the dispatcher captures the engine per step
        attempt)."""
        reg = self.registry
        # a reload body is a small JSON object — 1 MiB is generous
        body = self._read_body(handler, 1 << 20)
        if isinstance(body, int):
            return
        try:
            params = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError) as e:
            handler._reply_json(400, {"error": f"bad JSON body: {e}"})
            return
        if not isinstance(params, dict):
            handler._reply_json(400, {"error": "reload body must be "
                                               "a JSON object"})
            return
        if self.engine_builder is None:
            handler._reply_json(501, {"error": "reload not configured"})
            return
        if self._drain_started.is_set():
            handler._reply_json(503, {"error": "draining"},
                                extra={"Retry-After": 1})
            return
        with self._reload_lock:
            old_gen = self.batcher.generation
            try:
                # chaos-harness site: an injected fault between
                # validation and swap must roll back (utils/faults.py)
                faults.inject("serve.reload")
                new_engine = self.engine_builder(params)
                gen = self.batcher.swap_engine(new_engine)
            except Exception as e:  # noqa: BLE001 - rollback umbrella
                reg.counter("reload_failures_total").inc()
                reg.event("reload_failed", error=str(e),
                          generation=old_gen)
                vlog("Reload failed (old engine keeps serving): ", e)
                code = 400 if isinstance(e, ValueError) else 500
                handler._reply_json(code, {"error": str(e),
                                           "rolled_back": True,
                                           "generation": old_gen})
                return
        reg.counter("reload_total").inc()
        reg.set_meta(engine_generation=gen)
        reg.event("reload", old_generation=old_gen, new_generation=gen)
        vlog("Reloaded engine: generation ", old_gen, " -> ", gen)
        handler._reply_json(200, {"status": "reloaded",
                                  "generation": gen})

    # -- forensics ---------------------------------------------------------
    def _handle_debug_flight(self, handler) -> None:
        """GET /debug/flight: a live flight-recorder snapshot (ring
        contents + all-thread stacks + resolved levers) from a still-
        running replica — the wedged-but-not-dead case, where no dump
        trigger has fired yet. Loopback-only: thread stacks and lever
        values are operator forensics, not a public surface."""
        ip = handler.client_address[0]
        if ip not in ("127.0.0.1", "::1") and not ip.startswith("127."):
            handler._reply_json(403, {"error": "loopback only"})
            return
        rec = flight.current()
        if rec is None or not rec.enabled:
            handler._reply_json(404, {"error": "no flight recorder"})
            return
        try:
            handler._reply_json(200, rec.snapshot())
        except Exception as e:  # noqa: BLE001 - forensics, not liveness
            handler._reply_json(500, {"error": repr(e)})

    # -- health / lifecycle -----------------------------------------------
    def health(self) -> dict:
        with self._req_lock:
            served = self._requests
        healthy = bool(getattr(self.batcher, "healthy", True))
        draining = self._drain_started.is_set()
        h = {
            # a draining replica is still healthy (it answers what it
            # admitted); an unhealthy one is NOT draining — it needs
            # ejection, not patience
            "status": ("draining" if draining
                       else "ok" if healthy else "unhealthy"),
            "healthy": healthy or draining,
            "consecutive_failures": int(getattr(
                self.batcher, "consecutive_failures", 0)),
            "uptime_s": round(time.perf_counter() - self._t0, 3),
            "queue_depth": self.batcher.depth,
            "requests_served": served,
            "engine_compiles": self.batcher.engine.compiles,
            "engine_generation": int(getattr(
                self.batcher, "generation", 0)),
            "port": self.port,
        }
        if self.ingest is not None:
            # the live-ingestion detail (cursor, epoch, floor,
            # coverage): clients poll this to watch the ramp, and the
            # ingest bench ledgers its per-epoch q_* fields off the
            # generation transitions it sees here
            h["live"] = self.ingest.stats()
        if self.alerts is not None:
            # SLO burn + firing rules as DETAIL: the status/healthy
            # verdict above is untouched — load balancers keep
            # routing, operators (and the fleet receiver) see the
            # burn (ISSUE 11)
            try:
                h["alerts"] = self.alerts.summary()
                slo = self.alerts.slo_status()
                if slo:
                    h["slo"] = slo
            except Exception:  # noqa: BLE001 - detail never breaks health
                pass
        return h

    def initiate_drain(self) -> None:
        """Begin graceful drain (idempotent, safe from signal
        handlers and HTTP threads): stop admitting, then flush the
        admitted backlog on a helper thread so the caller — possibly
        an HTTP handler replying to /quiesce — never blocks on it."""
        if self._drain_started.is_set():
            return
        self._drain_started.set()

        def _drain():
            # name what the drain caught in flight BEFORE flushing it:
            # the final document's meta.drained_ids tells an operator
            # which requests a SIGTERM interrupted (empty on an idle
            # drain), matched by X-Quorum-Request-Id on the client side
            try:
                self.registry.set_meta(
                    drained_ids=self.batcher.pending_rids())
            except Exception:  # noqa: BLE001 - forensics never block drain  # qlint: disable=thread-swallowed-exception - best-effort forensics meta; the drain outcome itself is reported below either way
                pass
            # the meta stamp records what ACTUALLY happened: False
            # means the grace period expired with work unflushed — a
            # lossy shutdown must not read as a clean one downstream
            ok = self.batcher.drain(timeout=self.drain_grace_s)
            self.registry.set_meta(drained=bool(ok))
            self._drained.set()

        threading.Thread(target=_drain, name="quorum-serve-drain",
                         daemon=True).start()

    def serve_until_drained(self) -> None:
        """Block until a drain completes, then stop the HTTP listener.
        KeyboardInterrupt also initiates a drain (first ^C graceful)."""
        try:
            while not self._drained.wait(timeout=0.2):
                pass
        except KeyboardInterrupt:
            vlog("Interrupt: draining")
            self.initiate_drain()
            self._drained.wait(timeout=self.drain_grace_s + 5)
        self.close()

    def close(self) -> None:
        """Tear the listener down (idempotent). Does NOT write
        metrics — that's the observability() teardown's job."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        self.initiate_drain()
        self._drained.wait(timeout=self.drain_grace_s + 5)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def _parse_query(query: str) -> dict:
    """`a=x&b` -> {"a": "x", "b": ""} — a bare key keeps an EMPTY
    value (falsy), so `?deadline_ms` without a number falls through
    to the header/default instead of becoming a 1 ms deadline.
    parse_qsl also percent-decodes, so `log=%31` means `log=1`."""
    import urllib.parse
    return dict(urllib.parse.parse_qsl(query, keep_blank_values=True))


def _flag(params: dict, key: str) -> bool:
    """Boolean query flag: present and not an explicit off-value
    (`?log=1` and bare `?log` are on; `?log=0` is off)."""
    v = params.get(key)
    if v is None:
        return False
    return v.lower() not in ("0", "false", "no")
